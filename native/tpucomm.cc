/* tpucomm — TCP-mesh communication runtime (see tpucomm.h).
 *
 * Design notes:
 * - Connection setup: rank r listens on base_port + r; for each pair
 *   (i, j) with i < j, j dials i and identifies itself with its rank.
 * - Messages are framed (tag, nbytes) and matched strictly in order — the
 *   Python layer serializes communicating ops per process with JAX ordered
 *   effects, so out-of-order arrival on one socket is a program error
 *   (matching the reference's token-ordering contract, not a message
 *   re-ordering layer).
 * - Collectives are deterministic schedules over the point-to-point layer.
 *   allreduce/allgather carry SELECTABLE algorithms (ring / recursive
 *   doubling / binomial tree, plus the quantized-wire qring/qrd
 *   allreduce twins — the collective algorithm engine, owned by
 *   mpi4jax_tpu/tune): AUTO consults the decision table installed via
 *   tpucomm_set_coll_table, per-call forcing goes through the *_algo
 *   entry points.
 * - Algorithm wire-protocol invariant: every algorithm is built from the
 *   same framed point-to-point messages (tag kCollectiveTag, comm_id in
 *   every header), so the transport's divergence checks fire identically
 *   under every algorithm — ranks that disagree on the schedule (or on
 *   the algorithm itself) hit the tag/size/comm-id mismatch diagnostics
 *   and abort instead of corrupting data.  The same-host shm arena keeps
 *   its own opword cross-check, and always wins over the selector when a
 *   communicator has an arena (the engine governs the TCP path).
 * - Debug tracing mirrors the reference bridge's format
 *   ("r<rank> | <id> | Op ..."): entry + exit line with wall time.
 * - Fail-fast: any socket/protocol error prints to stderr and returns
 *   nonzero; the Python layer aborts the process group.
 */

#include "tpucomm.h"

#include <arpa/inet.h>
#include <emmintrin.h>
#include <fcntl.h>
#include <immintrin.h>
#include <linux/futex.h>
#include <linux/io_uring.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdarg>
#include <cstddef>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <limits>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <memory>
#include <vector>

namespace {

int g_logging = 0;

double now_s() {
  using clock = std::chrono::steady_clock;
  return std::chrono::duration<double>(clock::now().time_since_epoch())
      .count();
}

std::string call_id() {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  char buf[12];
  std::snprintf(buf, sizeof(buf), "%08llx",
                (unsigned long long)(rng() & 0xffffffffull));
  return buf;
}

struct LogScope {
  int rank;
  std::string id;
  const char* op;
  double t0 = 0;
  bool active;
  /* detail is a callable returning std::string so the formatting (and
   * the call-id rng) costs nothing when logging is off — the hot path
   * pays one branch (allreduce at 1 KB np8 is ~16 us end to end; a
   * handful of std::to_string allocations were measurable) */
  template <typename DetailFn>
  LogScope(int rank, const char* op, DetailFn&& detail)
      : rank(rank), op(op), active(g_logging != 0) {
    if (active) {
      id = call_id();
      std::fprintf(stderr, "r%d | %s | %s %s\n", rank, id.c_str(), op,
                   detail().c_str());
      t0 = now_s();
    }
  }
  ~LogScope() {
    if (active) {
      std::fprintf(stderr, "r%d | %s | %s done with code 0 (%.6f s)\n", rank,
                   id.c_str(), op, now_s() - t0);
    }
  }
};

/* Last-error text, readable from Python via tpucomm_last_error() so the
 * abort path can print a human-readable reason next to the error code
 * (the analog of the reference's ierr -> MPI_Error_string conversion,
 * mpi_xla_bridge.pyx:67-91 there). */
char g_last_error[512] = {0};
std::mutex g_last_error_mu;

void set_last_error(int rank, const char* fmt, ...) {
  char body[448];
  va_list ap;
  va_start(ap, fmt);
  std::vsnprintf(body, sizeof(body), fmt, ap);
  va_end(ap);
  std::lock_guard<std::mutex> lock(g_last_error_mu);
  std::snprintf(g_last_error, sizeof(g_last_error), "r%d: %s", rank, body);
}

#define FAIL(comm, ...)                                              \
  do {                                                               \
    std::fprintf(stderr, "tpucomm r%d: ", (comm)->rank);             \
    std::fprintf(stderr, __VA_ARGS__);                               \
    std::fprintf(stderr, "\n");                                      \
    set_last_error((comm)->rank, __VA_ARGS__);                       \
    return 1;                                                        \
  } while (0)

struct MsgHeader {
  int64_t nbytes;
  int32_t tag;
  int32_t comm_id;  // communicator the message belongs to (world = 0)
};

/* ============== self-healing link layer: wire format ==============
 *
 * With MPI4JAX_TPU_RETRY > 0 ("armed"), every wire frame header grows
 * to MsgHeaderX: the plain header as a PREFIX (so MSG_PEEK-based
 * poison/liveness probes that look at the first 16 bytes keep working
 * unchanged), then a per-link sequence number, the link's connection
 * epoch, and a CRC32C over the extended header.  Sequence numbers are
 * per (link, direction) and count DATA frames only; control frames
 * (heartbeat ping/pong, poison) carry seq 0 and are out-of-band — they
 * are never retained, never replayed, and never advance the receiver's
 * delivery cursor.  All ranks read the same environment, so the wire
 * format agrees job-wide; MPI4JAX_TPU_RETRY unset/0 keeps the 16-byte
 * header and the historic byte stream bit-for-bit. */
struct MsgHeaderX {
  MsgHeader h;
  uint32_t seq_lo;  // low/high halves of the 64-bit link sequence
  uint32_t seq_hi;
  uint32_t epoch;   // link connection epoch at stamp time
  uint32_t crc;     // CRC32C of this struct with crc = 0 (when enabled)
};

/* heartbeat control frames (never visible to user receives) */
constexpr int32_t kPingTag = -7711;
constexpr int32_t kPongTag = -7712;

bool retry_armed();          // MPI4JAX_TPU_RETRY > 0
int64_t wire_hdr_bytes();    // sizeof(MsgHeaderX) when armed, else MsgHeader

/* Reconnect handshake, exchanged raw (not framed) on a fresh socket:
 * each side identifies itself and reports the last data seq it fully
 * delivered, so the peer replays exactly the gap.  Always sealed with
 * CRC32C (control scope — independent of MPI4JAX_TPU_WIRE_CRC). */
struct ReconnectHello {
  uint32_t magic;        // kReconnectMagic
  int32_t rank;          // sender's ROOT (socket-owner) rank
  int32_t comm_id;       // root comm id, as a cross-job sanity check
  uint32_t epoch;        // sender's current link epoch
  uint64_t rx_delivered; // last inbound data seq fully delivered
  uint32_t crc;
};
constexpr uint32_t kReconnectMagic = 0x4d344a52u;  // "M4JR"

struct Comm;
int link_recover(Comm* c, int peer, int fd_seen, const char* what);
int link_send_frame(Comm* c, int dest, int tag, const void* p1, int64_t n1,
                    const void* p2, int64_t n2);
int link_fd(Comm* c, int peer);

/* One retained (replayable) outbound frame: the complete stamped wire
 * bytes, header included, so replay is a verbatim rewrite. */
struct ReplayFrame {
  uint64_t seq = 0;
  std::vector<char> bytes;
};

enum LinkPhase { LINK_UP = 0, LINK_SUSPECT, LINK_RECONNECTING, LINK_DEAD };

/* Per-peer link state, owned by the socket-owning root comm and indexed
 * by ROOT rank.  `mu` serializes recovery per link (the first thread to
 * hit a failure reconnects; threads arriving later block on it, then see
 * the fresh fd and simply retry their frame).  `wmu` serializes whole
 * FRAMES onto the socket when armed, so a heartbeat pong injected from
 * the receive path can never interleave with another thread's
 * header/payload write pair. */
struct LinkState {
  std::mutex mu;
  std::mutex wmu;
  /* receive-side frame mutex: held across the armed header read and by
   * a recovery while it rewires the fd, so fd loads on the read side
   * are synchronized (lock order: mu -> rmu -> wmu) */
  std::mutex rmu;
  uint32_t epoch = 1;
  std::atomic<uint64_t> tx_seq{0};  // last stamped outbound data seq
  std::atomic<uint64_t> rx_seq{0};  // last fully delivered inbound data seq
  /* newest outbound data seq with NO retained copy (too large, or
   * evicted from the ring): a reconnect whose replay gap crosses this
   * cannot restore the stream and must escalate */
  std::atomic<uint64_t> hole_seq{0};
  std::deque<ReplayFrame> ring;  // guarded by wmu (and mu during recovery)
  int64_t ring_bytes = 0;
  std::atomic<int> phase{LINK_UP};
  std::atomic<double> last_rx{0};    // stamp of last inbound bytes seen
  std::atomic<double> last_ping{0};  // stamp of last heartbeat ping sent
};

/* One queued outbound message.  The enqueuing op always waits for
 * completion before returning, so `buf` stays valid (zero-copy). */
struct SendJob {
  int fd = -1;
  int rank = -1;  // enqueuer's rank, for error text
  int dest = -1;
  Comm* comm = nullptr;  // enqueuing comm (self-healing frame path)
  MsgHeader hdr{};
  const void* buf = nullptr;
  int rc = 0;
  bool done = false;
};

struct ShmArena;  // same-host shared-memory fast path, defined below
void arena_destroy(ShmArena* a);
struct Engine;    // async progress engine (per socket-owning comm)
void engine_shutdown(Engine* e);

/* A user message staged off the socket: a coalesced wire frame carries
 * several adjacent small sends from one peer; the receiver lands the
 * one a posted receive is waiting for directly in the user buffer and
 * stages the rest here, consumed strictly in arrival order (the same
 * in-order-channel contract as the wire). */
struct PendingMsg {
  MsgHeader hdr;
  std::vector<char> data;
};

struct Comm;

/* Locality map installed by tpucomm_set_topology (mpi4jax_tpu/topo is
 * the discovering owner): which member ranks share an island (a host /
 * shm domain), each island's leader (its lowest member rank), and the
 * intra-island + leaders sub-communicators the hierarchical collective
 * schedules compose over.  The sub-comms are tpucomm_split children of
 * this comm (they borrow its sockets); the Python bridge creates them
 * and tears them down before the world. */
struct TopoInfo {
  std::vector<int32_t> island_of;             // member rank -> island id
  std::vector<int32_t> leaders;               // island id -> leader rank
  std::vector<std::vector<int32_t>> members;  // island id -> sorted ranks
  Comm* intra = nullptr;   // my island's sub-comm (null: singleton island)
  Comm* leader = nullptr;  // leaders' sub-comm (null: not a leader)
  int n_islands = 0;
  int my_island = -1;
  int my_leader = -1;      // member rank of my island's leader
};

/* shm p2p rings (defined in the arena section below) */
bool ring_p2p_on(const Comm* c);
int shm_try_send(Comm* c, int dest, int tag, const void* buf,
                 int64_t nbytes, bool* inlined);
int shm_recv_status(Comm* c, int source, int tag, void* buf,
                    int64_t nbytes, int32_t* out_src, int32_t* out_tag,
                    int64_t* out_count);
int ring_poll_any(Comm* c, int tag, int* out_source);

struct Comm {
  int rank = -1;
  int size = 0;
  std::vector<int> socks;  // per-peer fd, -1 for self
  ShmArena* arena = nullptr;  // non-null when every member shares this host
  std::string shm_prefix;     // job-unique shm name prefix (inherited)
  std::mutex mu;           // one op at a time (ordered effects upstream)
  /* self-delivery queue: send-to-self enqueues here, recv-from-self pops
   * (MPI allows self-messaging; the reference's exit-flush regression is
   * a sendrecv-to-self, test_common.py:91-114 there).  Guarded by mu. */
  std::deque<std::pair<MsgHeader, std::vector<char>>> self_q;
  /* coalesced sub-messages staged off the wire, keyed by source rank.
   * Touched only by the thread executing this comm's ops (the same
   * single-executor discipline as self_q: either the calling thread
   * running inline, or the progress thread — never both at once). */
  std::map<int, std::deque<PendingMsg>> pending;
  int32_t comm_id = 0;     // deterministic across ranks (world = 0)
  /* effective host of every member — the real host table with the
   * MPI4JAX_TPU_FAKE_HOSTS virtual partition applied; arena eligibility
   * (bootstrap AND split subsets) is decided on THIS view, so a
   * virtually partitioned loopback job behaves like the multi-host
   * shape it models.  Inherited (subsetted) by split/dup children. */
  std::vector<std::string> member_hosts;
  /* discovered locality map (tpucomm_set_topology); null = flat */
  TopoInfo* topo = nullptr;
  bool owns_socks = true;  // split/dup comms borrow the parent's sockets
  int32_t next_split_seq = 1;  // collective-call counter, agrees rank-wide
  Comm* lock_root = this;  // sub-comms serialize on the socket owner's mu:
                           // two comms sharing fds must never interleave
                           // header/payload writes on one socket

  /* Persistent writer thread: the send half of sendrecv/collective
   * rounds is queued here instead of spawning a std::thread per message
   * (round 2 paid thread creation — tens of microseconds — on every
   * round of every collective; VERDICT.md weak #6).  Lives on the
   * socket-owning root comm; lazily started on first use. */
  std::thread writer;
  std::mutex wmu;
  std::condition_variable wcv;       // writer wakeup
  std::condition_variable wdone_cv;  // completion notification
  std::deque<SendJob*> wq;
  bool writer_started = false;
  bool wstop = false;

  /* Async progress engine (lives on the socket-owning root comm, like
   * the writer thread): a dedicated progress thread draining a
   * lock-free submission queue of op descriptors.  Created lazily on
   * the first queued post; null while every op has run inline. */
  Engine* engine = nullptr;

  /* ---- self-healing link layer (populated only when armed) ---- */
  /* member rank -> socket-owning root rank, so sub-comms resolve the
   * one LinkState per physical socket (world: identity; children
   * compose through the parent's map at split time) */
  std::vector<int> root_rank;
  /* per-ROOT-rank link state; lives on the socket owner only */
  std::vector<std::unique_ptr<LinkState>> links;
  /* bootstrap listener, kept open for the comm's lifetime when armed so
   * reconnect dials have somewhere to land (closed at finalize) */
  int listen_fd = -1;
  int base_port = 0;
  std::vector<std::string> real_hosts;  // dialing addresses (not FAKE_HOSTS)
  /* reconnect dials accepted while the expected acceptor was busy
   * elsewhere: root rank -> (connected fd, its hello, already read) */
  std::mutex rcmu;
  std::map<int, std::pair<int, ReconnectHello>> pending_rc;
  /* replaced fds parked (shutdown but not closed) until finalize:
   * closing immediately could hand the fd number to an unrelated open
   * while another thread is still blocked on it */
  std::vector<int> dead_fds;
  /* child comms borrowing these sockets (registered at split, removed
   * at finalize) so a reconnect can rewire every view of a link */
  std::mutex kids_mu;
  std::vector<Comm*> kids;

  ~Comm() {
    if (engine) engine_shutdown(engine);  // drains, joins, frees
    if (writer_started) {
      {
        std::lock_guard<std::mutex> lock(wmu);
        wstop = true;
      }
      wcv.notify_all();
      writer.join();
    }
    if (arena) arena_destroy(arena);
    if (listen_fd >= 0) ::close(listen_fd);
    for (int fd : dead_fds)
      if (fd >= 0) ::close(fd);
    delete topo;
  }
};

/* every op entry point locks the socket-owning ancestor */
std::mutex& comm_mu(Comm* c) { return c->lock_root->mu; }

std::mutex g_comms_mu;
std::map<int64_t, Comm*> g_comms;
int64_t g_next_handle = 1;

Comm* get_comm(int64_t h) {
  std::lock_guard<std::mutex> lock(g_comms_mu);
  auto it = g_comms.find(h);
  return it == g_comms.end() ? nullptr : it->second;
}

void count_sys_fwd();  // transport syscall counter (obs section below)

/* EAGAIN here is reachable only when the uring backend put the mesh on
 * non-blocking fds (a ring-creation failure then lands a direct caller
 * on these loops); park in poll() instead of spinning.  On the URING=0
 * path the fds are blocking unless a deadline is armed — and then the
 * _dl variants serve — so this branch is dead there and the historic
 * byte-for-byte behavior is untouched. */
int io_wait_ready(int fd, bool wr) {
  pollfd pf{fd, (short)(wr ? POLLOUT : POLLIN), 0};
  count_sys_fwd();
  return ::poll(&pf, 1, 60000);
}

int write_all(int fd, const void* buf, int64_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    count_sys_fwd();
    ssize_t w = ::write(fd, p, (size_t)n);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (io_wait_ready(fd, true) < 0 && errno != EINTR) return 1;
        continue;
      }
      return 1;
    }
    p += w;
    n -= w;
  }
  return 0;
}

int read_all(int fd, void* buf, int64_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    count_sys_fwd();
    ssize_t r = ::read(fd, p, (size_t)n);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (io_wait_ready(fd, false) < 0 && errno != EINTR) return 1;
        continue;
      }
      if (r == 0) errno = ECONNRESET;  // EOF: don't report stale "Success"
      return 1;
    }
    p += r;
    n -= r;
  }
  return 0;
}

/* ============== observability event ring ==============
 *
 * One fixed-size ring of TpuObsEvent per process (see tpucomm.h).  All
 * entry points append through ObsScope; the blocking sub-phases add
 * their blocked time to a thread-local accumulator through ObsWaitTimer
 * so every event carries a wait/transfer split.  Disabled (default):
 * one relaxed atomic load per op, no clock reads, no ring writes —
 * g_obs_on is the ONLY thing the hot path touches. */

std::atomic<int> g_obs_on{0};
std::mutex g_obs_mu;
std::vector<TpuObsEvent> g_obs_ring;  // fixed capacity once enabled
int64_t g_obs_total = 0;              // appended since enable (kept + dropped)
int64_t g_obs_dropped = 0;            // overwritten by overflow
int64_t g_obs_seq = 0;                // appended since enable, NEVER reset by
                                      // drain — the absolute sequence space
                                      // tpucomm_obs_peek cursors live in
thread_local double g_obs_wait_acc = 0.0;

/* Self-healing link counters (process totals; see tpucomm_link_counters
 * in tpucomm.h).  The thread-local accumulator mirrors g_obs_wait_acc:
 * successful recoveries bump it so ObsScope can stamp the per-op
 * retries delta on the event that absorbed them. */
std::atomic<int64_t> g_lc_retries{0};      // recovery events entered
std::atomic<int64_t> g_lc_reconnects{0};   // successful reconnect handshakes
std::atomic<int64_t> g_lc_dup_dropped{0};  // duplicate data frames discarded
std::atomic<int64_t> g_lc_crc_errors{0};   // header/control CRC mismatches
std::atomic<int64_t> g_lc_replayed{0};     // retained frames retransmitted
std::atomic<int64_t> g_lc_heartbeats{0};   // idle-link pings sent
thread_local int64_t g_heal_acc = 0;

/* Transport syscall counter: every socket-moving syscall (write/read/
 * writev/send/recv/poll and io_uring_enter; futex parks excluded — they
 * are scheduling, not wire) bumps it, so events carry a per-op syscall
 * count and benchmarks read the process total (tpucomm_syscall_count).
 * Process-global (relaxed) rather than thread-local: the writer/
 * progress threads issue syscalls on BEHALF of the op executing on
 * another thread, and a per-op window over the global counter
 * attributes them to that op — exact for the serialized-op case the
 * benchmarks measure, conserved in total always. */
std::atomic<int64_t> g_syscalls{0};

inline void count_sys() { g_syscalls.fetch_add(1, std::memory_order_relaxed); }

void count_sys_fwd() { count_sys(); }  // callable from above-definition code

void obs_append(const TpuObsEvent& ev) {
  std::lock_guard<std::mutex> lock(g_obs_mu);
  if (g_obs_ring.empty()) return;  // disabled raced with the op's scope
  const int64_t cap = (int64_t)g_obs_ring.size();
  g_obs_ring[(size_t)(g_obs_total % cap)] = ev;
  if (g_obs_total >= cap) g_obs_dropped++;
  g_obs_total++;
  g_obs_seq++;
}

/* RAII event record for one transport op.  Constructed where the op
 * starts EXECUTING (inline on the calling thread, or on the progress
 * thread for queued descriptors); the destructor stamps duration and
 * the wait share accumulated by ObsWaitTimer scopes inside the op.
 * `t_post` (>= 0) is the submission time of an engine-queued op: the
 * event's t_start becomes the post time and queue_s the dispatch
 * delay (post -> execution start), so dur = queue + wait + wire. */
struct ObsScope {
  bool on;
  double t0 = 0, wait0 = 0, post = -1;
  int64_t sys0 = 0, heal0 = 0;
  TpuObsEvent ev{};
  ObsScope(int op, int peer, int tag, int64_t nbytes, int algo = -1,
           double t_post = -1) {
    on = g_obs_on.load(std::memory_order_relaxed) != 0;
    if (!on) return;
    ev.op = op;
    ev.peer = peer;
    ev.tag = tag;
    ev.nbytes = nbytes;
    ev.wire_bytes = nbytes;  // exact ops: the wire carries the payload
    ev.algo = algo;
    wait0 = g_obs_wait_acc;
    heal0 = g_heal_acc;
    sys0 = g_syscalls.load(std::memory_order_relaxed);
    post = t_post;
    t0 = now_s();
  }
  void set_algo(int algo) { ev.algo = algo; }
  /* quantized collectives: the payload's on-wire representation is the
   * packed codec size, not the logical bytes */
  void set_wire(int64_t wb) { ev.wire_bytes = wb; }
  /* hierarchical collectives: label a per-leg event with its transport
   * tier (intra-island vs inter-island) so stats split the bytes */
  void set_tier(int tier) { ev.tier = tier; }
  ~ObsScope() {
    if (!on) return;
    double t1 = now_s();
    double start = post >= 0 && post <= t0 ? post : t0;
    ev.t_start = start;
    ev.dur_s = t1 - start;
    ev.queue_s = t0 - start;
    ev.wait_s = g_obs_wait_acc - wait0;
    if (ev.wait_s > ev.dur_s - ev.queue_s) ev.wait_s = ev.dur_s - ev.queue_s;
    int64_t ds = g_syscalls.load(std::memory_order_relaxed) - sys0;
    ev.syscalls = ds > INT32_MAX ? INT32_MAX : (int32_t)(ds < 0 ? 0 : ds);
    int64_t dh = g_heal_acc - heal0;
    ev.retries = dh > INT32_MAX ? INT32_MAX : (int32_t)(dh < 0 ? 0 : dh);
    obs_append(ev);
  }
};

/* Accumulates blocked time (header arrival, barrier rendezvous) into
 * the wait share of the enclosing ObsScope.  Scoped tightly around the
 * blocking call itself. */
struct ObsWaitTimer {
  bool on;
  double t0 = 0;
  ObsWaitTimer() {
    on = g_obs_on.load(std::memory_order_relaxed) != 0;
    if (on) t0 = now_s();
  }
  ~ObsWaitTimer() {
    if (on) g_obs_wait_acc += now_s() - t0;
  }
};

/* ============== failure detection: transport deadlines ==============
 *
 * MPI4JAX_TPU_TIMEOUT_S bounds every blocking wait on the TCP mesh
 * with a PROGRESS-based deadline: the clock resets whenever any byte
 * moves, so a slow-but-live bulk transfer survives while a wedged peer
 * (hung process, dead NIC, lost frame) trips the deadline instead of
 * hanging the whole job forever.  0 (the default) keeps the historic
 * infinite blocking loops bit-for-bit.  The same knob caps the shm
 * arena's barrier/ring waits (see shm_timeout_s) so one deadline
 * bounds the job regardless of which path a message rides. */

/* Strict seconds parser: a typo'd deadline knob must stop the job, not
 * silently arm NO deadline while the operator believes one is set (the
 * same loud-failure contract as the fault spec and COLL_ALGO parsers).
 * Returns the parsed value; callers clamp non-positive to their "off" /
 * default semantics. */
double parse_env_seconds(const char* name, double dflt) {
  const char* e = std::getenv(name);
  if (!e || !e[0]) return dflt;
  char* end = nullptr;
  double v = std::strtod(e, &end);
  const bool converted = end != e;
  while (end && (*end == ' ' || *end == '\t')) end++;
  if (!converted || (end && *end)) {
    std::fprintf(stderr, "tpucomm: cannot parse %s=%s as seconds\n", name,
                 e);
    std::exit(2);
  }
  return v;
}

double transport_timeout_s() {
  static double v = [] {
    double t = parse_env_seconds("MPI4JAX_TPU_TIMEOUT_S", 0.0);
    return t > 0 ? t : 0.0;  // 0 = no deadline (historic behavior)
  }();
  return v;
}

/* 0 means OFF (same convention as TIMEOUT_S): dial retries forever and
 * accept blocks forever.  Unset = the 30 s default the old fixed
 * 600 x 50 ms retry spin gave the dial side. */
double connect_timeout_s() {
  static double v = [] {
    double t = parse_env_seconds("MPI4JAX_TPU_CONNECT_TIMEOUT_S", 30.0);
    return t > 0 ? t : 0.0;
  }();
  return v;
}

/* ============== self-healing link knobs ==============
 *
 * MPI4JAX_TPU_RETRY arms the link layer: wire headers grow to
 * MsgHeaderX, retained small sends double as a retransmit buffer, and a
 * failing socket gets up to RETRY reconnect attempts (exponential
 * backoff from MPI4JAX_TPU_RETRY_BACKOFF_MS, with jitter) before the
 * failure escalates through the historic poison -> abort -> elastic
 * path.  0 (the default) keeps today's fail-fast path bit-for-bit.
 * Strict parsing, same loud contract as every other knob. */

int64_t parse_env_int(const char* name, int64_t dflt) {
  const char* e = std::getenv(name);
  if (!e || !e[0]) return dflt;
  char* end = nullptr;
  long long v = std::strtoll(e, &end, 10);
  while (end && (*end == ' ' || *end == '\t')) end++;
  if (end == e || (end && *end)) {
    std::fprintf(stderr, "tpucomm: cannot parse %s=%s as an integer\n",
                 name, e);
    std::exit(2);
  }
  return (int64_t)v;
}

int64_t retry_budget() {
  static int64_t v = [] {
    int64_t n = parse_env_int("MPI4JAX_TPU_RETRY", 0);
    return n > 0 ? n : 0;
  }();
  return v;
}

bool retry_armed() { return retry_budget() > 0; }

/* Bytes each frame header occupies on the wire under the current arming
 * (diag/tests cross-check the overhead claim against this). */
[[maybe_unused]] int64_t wire_hdr_bytes() {
  return retry_armed() ? (int64_t)sizeof(MsgHeaderX)
                       : (int64_t)sizeof(MsgHeader);
}

double retry_backoff_ms() {
  static double v = [] {
    double t = parse_env_seconds("MPI4JAX_TPU_RETRY_BACKOFF_MS", 100.0);
    return t > 0 ? t : 100.0;
  }();
  return v;
}

double heartbeat_s() {
  static double v = [] {
    double t = parse_env_seconds("MPI4JAX_TPU_HEARTBEAT_S", 0.0);
    return t > 0 ? t : 0.0;
  }();
  return v;
}

/* Test-only protocol exerciser: replay N extra already-delivered frames
 * on every reconnect, so the receiver's dedup layer provably fires
 * (dup_dropped > 0) while digests stay bit-identical. */
int64_t replay_slack() {
  static int64_t v = [] {
    int64_t n = parse_env_int("MPI4JAX_TPU_RETRY_REPLAY_SLACK", 0);
    return n > 0 ? n : 0;
  }();
  return v;
}

/* MPI4JAX_TPU_WIRE_CRC = auto (default: on iff the link layer is
 * armed — the CRC field only exists in the extended header) | 0 | 1.
 * 1 with RETRY=0 is a spec error: there is no header field to carry
 * the checksum, so honoring it silently would protect nothing. */
bool wire_crc_on() {
  static bool v = [] {
    const char* e = std::getenv("MPI4JAX_TPU_WIRE_CRC");
    if (!e || !e[0] || std::strcmp(e, "auto") == 0) return retry_armed();
    if (std::strcmp(e, "0") == 0) return false;
    if (std::strcmp(e, "1") == 0) {
      if (!retry_armed()) {
        std::fprintf(stderr,
                     "tpucomm: MPI4JAX_TPU_WIRE_CRC=1 requires "
                     "MPI4JAX_TPU_RETRY > 0 (the 16-byte legacy header "
                     "has no checksum field)\n");
        std::exit(2);
      }
      return true;
    }
    std::fprintf(stderr,
                 "tpucomm: cannot parse MPI4JAX_TPU_WIRE_CRC=%s "
                 "(expected auto|0|1)\n", e);
    std::exit(2);
  }();
  return v;
}

/* CRC32C (Castagnoli), software table — headers are 32 bytes, so the
 * table lookup is noise next to the syscall that carries them. */
uint32_t crc32c(const void* data, size_t n) {
  static const uint32_t* table = [] {
    static uint32_t t[256];
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
    return t;
  }();
  uint32_t c = 0xffffffffu;
  const uint8_t* p = static_cast<const uint8_t*>(data);
  for (size_t i = 0; i < n; i++) c = table[(c ^ p[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

/* Stamp an extended header's CRC field (zeroed during the computation). */
void hx_seal(MsgHeaderX* hx) {
  hx->crc = 0;
  if (wire_crc_on())
    hx->crc = crc32c(hx, offsetof(MsgHeaderX, crc));
}

/* Verify a received extended header.  Control payloads are covered by
 * their own seals; data payloads are NOT covered (documented scope:
 * large-payload CRC would tax the hot path; header integrity is what
 * protects stream framing). */
bool hx_check(const MsgHeaderX* hx) {
  if (!wire_crc_on()) return true;
  MsgHeaderX tmp = *hx;
  tmp.crc = 0;
  return crc32c(&tmp, offsetof(MsgHeaderX, crc)) == hx->crc;
}

/* MPI4JAX_TPU_FAKE_HOSTS=r0,r1|r2,r3 — virtual host partition for
 * topology testing on one machine: ranks in one '|'-separated group are
 * treated as sharing a (virtual) host for arena eligibility, ranks in
 * different groups as host-separated even over loopback.  Tokens are
 * `rN` or bare `N`, indexing CURRENT world ranks (an elastic rebuild
 * re-applies the spec against the dense new ranks).  Ranks not listed
 * keep their real host.  Malformed specs exit loudly (same contract as
 * MPI4JAX_TPU_FAULT: a typo'd partition must not silently test the
 * wrong shape).  Out-of-range ranks are ignored (a spec written for
 * np=4 stays valid on a shrunk np=2 world). */
void apply_fake_hosts(std::vector<std::string>& hosts, int size) {
  const char* e = std::getenv("MPI4JAX_TPU_FAKE_HOSTS");
  if (!e || !e[0]) return;
  std::vector<int> seen(hosts.size(), 0);
  int group = 0;
  const char* p = e;
  std::string tok;
  auto flush_tok = [&]() {
    /* trim whitespace */
    size_t b = tok.find_first_not_of(" \t");
    if (b == std::string::npos) {
      tok.clear();
      return;
    }
    tok = tok.substr(b, tok.find_last_not_of(" \t") - b + 1);
    const char* t = tok.c_str();
    if (*t == 'r' || *t == 'R') t++;
    char* end = nullptr;
    long r = std::strtol(t, &end, 10);
    /* digits only ('+5' / ' 5' inside a token would diverge from the
     * Python mirror, which accepts bare digits) */
    if (end == t || *end || r < 0 || !(*t >= '0' && *t <= '9')) {
      std::fprintf(stderr,
                   "tpucomm: cannot parse MPI4JAX_TPU_FAKE_HOSTS token "
                   "%s (expected rN or N, groups separated by |)\n",
                   tok.c_str());
      std::exit(2);
    }
    if (r < size) {
      if (seen[(size_t)r]) {
        std::fprintf(stderr,
                     "tpucomm: MPI4JAX_TPU_FAKE_HOSTS lists rank %ld "
                     "twice\n", r);
        std::exit(2);
      }
      seen[(size_t)r] = 1;
      hosts[(size_t)r] = "fake-host-" + std::to_string(group);
    }
    tok.clear();
  };
  for (;; p++) {
    if (*p == ',' || *p == '|' || *p == '\0') {
      flush_tok();
      if (*p == '|') group++;
      if (*p == '\0') break;
    } else {
      tok.push_back(*p);
    }
  }
}

/* progress detail for the caller's diagnostic when a deadline fires */
thread_local int64_t g_io_done = 0;
thread_local int64_t g_io_want = 0;

/* Deadline anchor for engine-queued ops: deadlines are measured from
 * POST time, not execution start — time an op spends behind others in
 * the submission queue is zero-progress time and must count against
 * the job deadline.  The progress-thread executor sets this to the
 * descriptor's post timestamp; the first deadline-bounded transfer of
 * the op consumes it (anchoring its initial window at the post time),
 * after which the usual any-progress-resets-the-clock rule applies. */
thread_local double g_dl_post_anchor = 0;

/* io_uring submission backend (defined after the fault section; probed
 * once per process, one ring per thread).  uring_io_all implements the
 * exact deadline/progress/anchor semantics of the poll loop below over
 * submitted SQEs instead of poll+read/write pairs. */
bool uring_ready();
int uring_io_all(int fd, void* buf, int64_t n, bool wr, double t);

/* Deadline-bounded read/write of exactly n bytes.  Returns 0 on
 * success, 1 on a socket error (errno describes it), 2 when the
 * deadline passed with zero bytes of progress (g_io_done / g_io_want
 * hold the transfer state).  `t` defaults to the job-wide knob; with
 * that unset this IS read_all/write_all.  With MPI4JAX_TPU_URING
 * active the transfer is submitted to the thread's io_uring instead
 * (same deadline semantics, fewer syscalls). */
template <bool kWrite>
int io_all_deadline(int fd, void* buf, int64_t n, double t = -1.0) {
  if (t < 0) t = transport_timeout_s();
  if (uring_ready()) return uring_io_all(fd, buf, n, kWrite, t);
  if (t <= 0)
    return kWrite ? write_all(fd, buf, n) : read_all(fd, buf, n);
  char* p = static_cast<char*>(buf);
  int64_t left = n;
  double deadline = now_s() + t;
  if (g_dl_post_anchor > 0) {
    /* queued op: the first window is anchored at post time (consumed
     * once; progress below re-anchors at now as usual) */
    double anchored = g_dl_post_anchor + t;
    if (anchored < deadline) deadline = anchored;
    g_dl_post_anchor = 0;
  }
  while (left > 0) {
    double remain = deadline - now_s();
    if (remain <= 0) {
      g_io_done = n - left;
      g_io_want = n;
      return 2;
    }
    pollfd pf{fd, (short)(kWrite ? POLLOUT : POLLIN), 0};
    count_sys();
    int pr = ::poll(&pf, 1, (int)std::min(remain * 1000.0 + 1, 60000.0));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return 1;
    }
    if (pr == 0) continue;  // loop re-checks the deadline
    count_sys();
    ssize_t m = kWrite ? ::write(fd, p, (size_t)left)
                       : ::read(fd, p, (size_t)left);
    if (m <= 0) {
      if (m < 0 && (errno == EINTR || errno == EAGAIN ||
                    errno == EWOULDBLOCK))
        continue;
      if (m == 0 && !kWrite) errno = ECONNRESET;  // EOF, not "Success"
      return 1;
    }
    p += m;
    left -= m;
    deadline = now_s() + t;  // any progress resets the clock
  }
  return 0;
}

int read_all_dl(int fd, void* buf, int64_t n) {
  return io_all_deadline<false>(fd, buf, n);
}

int write_all_dl(int fd, const void* buf, int64_t n) {
  return io_all_deadline<true>(fd, const_cast<void*>(buf), n);
}

/* Caller-side diagnostic for a *_dl result: rc 2 = deadline (op, peer,
 * comm, and bytes-progressed detail), rc 1 = the historic errno text.
 * dir_fmt is a printf format with one %d for the peer rank, e.g.
 * "send to %d" — the rc 1 message matches the pre-deadline wording. */
#define FAIL_IO(comm, rc, dir_fmt, peer)                                    \
  do {                                                                      \
    if ((rc) == 2)                                                          \
      FAIL(comm,                                                            \
           dir_fmt " timed out after %.0f s on comm %d with %lld/%lld "     \
                   "bytes moved — the peer is hung or unreachable "         \
                   "(MPI4JAX_TPU_TIMEOUT_S)",                               \
           peer, transport_timeout_s(), (comm)->comm_id,                    \
           (long long)g_io_done, (long long)g_io_want);                     \
    FAIL(comm, dir_fmt " failed: %s", peer, std::strerror(errno));          \
  } while (0)

/* ============== deterministic fault injection ==============
 *
 * MPI4JAX_TPU_FAULT=rank=R,point=send|recv|connect,after=N,action=hang|exit|close
 * arms exactly one fault in the native layer: on rank R, the (N+1)-th
 * operation at the given point (N defaults to 0) either hangs forever,
 * exits the process (code 17, simulating a crash), or shuts down every
 * mesh socket (simulating a network partition).  This is how the
 * timeout / abort-propagation / watchdog paths are exercised by real
 * multi-process tests — a typo'd spec fails the job loudly instead of
 * silently injecting nothing. */

enum FaultPoint { FP_NONE = 0, FP_SEND, FP_RECV, FP_CONNECT };
enum FaultAction {
  FA_NONE = 0, FA_HANG, FA_EXIT, FA_CLOSE,
  /* transient link faults (one-shot; the self-healing layer is
   * expected to absorb them when armed, or the job to abort loudly) */
  FA_RESET,    // SO_LINGER(0) + close: RST both directions
  FA_DROP,     // kill the connection mid-frame after `param` bytes
  FA_DELAY,    // stall the op for `param` milliseconds, then proceed
  FA_CORRUPT,  // flip a byte in the next wire header after CRC stamping
};

struct FaultSpec {
  bool armed = false;
  int rank = -1;
  int point = FP_NONE;
  long long after = 0;
  int action = FA_NONE;
  long long param = 0;  // drop: bytes before the RST; delay: milliseconds
  std::atomic<long long> hits{0};
};
FaultSpec g_fault;

/* A fired drop/corrupt fault arms this thread-local order for the NEXT
 * wire frame this thread writes; link_send_frame consumes it.  (The
 * fire site and the frame writer are the same thread: inline sends,
 * the writer thread, and the engine drain loop all fire the injector
 * immediately before building their frame.) */
struct WireFault {
  int action = FA_NONE;
  long long param = 0;
};
thread_local WireFault g_wire_fault;
std::once_flag g_fault_once;
/* the spec's rank=R is a JOB rank: comm-local ranks diverge on split
 * sub-comms, so injection keys on the rank this process was born with */
int g_job_rank = -1;

void fault_parse() {
  const char* e = std::getenv("MPI4JAX_TPU_FAULT");
  if (!e || !e[0]) return;
  int rank = -1, point = FP_NONE, action = FA_NONE;
  long long after = 0, param = 0;
  int has_param = 0;
  bool ok = true;
  std::string s(e);
  size_t pos = 0;
  while (pos < s.size() && ok) {
    size_t comma = s.find(',', pos);
    std::string kv = s.substr(pos, comma == std::string::npos
                                       ? std::string::npos
                                       : comma - pos);
    pos = comma == std::string::npos ? s.size() : comma + 1;
    size_t eq = kv.find('=');
    if (eq == std::string::npos) {
      ok = false;
      break;
    }
    std::string k = kv.substr(0, eq), v = kv.substr(eq + 1);
    /* numeric fields parse strictly: atoi("x") == 0 would silently arm
     * the fault on rank 0 — the fake-green failure mode this parser's
     * loud-exit contract exists to prevent */
    auto parse_ll = [&ok](const std::string& s, long long* out) {
      char* end = nullptr;
      long long n = std::strtoll(s.c_str(), &end, 10);
      if (end == s.c_str() || *end) ok = false;
      *out = n;
    };
    if (k == "rank") {
      long long r = -1;
      parse_ll(v, &r);
      rank = (int)r;
    } else if (k == "after") {
      parse_ll(v, &after);
    } else if (k == "bytes" || k == "ms") {
      /* drop=N bytes before the RST / delay=N milliseconds; validated
       * against the action below */
      parse_ll(v, &param);
      has_param = k == "bytes" ? 1 : 2;
    } else if (k == "point") {
      point = v == "send" ? FP_SEND
              : v == "recv" ? FP_RECV
              : v == "connect" ? FP_CONNECT
                               : FP_NONE;
      ok = point != FP_NONE;
    } else if (k == "action") {
      action = v == "hang" ? FA_HANG
               : v == "exit" ? FA_EXIT
               : v == "close" ? FA_CLOSE
               : v == "reset" ? FA_RESET
               : v == "drop" ? FA_DROP
               : v == "delay" ? FA_DELAY
               : v == "corrupt" ? FA_CORRUPT
                                : FA_NONE;
      ok = action != FA_NONE;
    } else {
      ok = false;
    }
  }
  /* bytes= only modifies drop, ms= only delay (an ignored parameter
   * would silently test a different fault than the spec says) */
  if (has_param == 1 && action != FA_DROP) ok = false;
  if (has_param == 2 && action != FA_DELAY) ok = false;
  if (has_param && param < 0) ok = false;
  if (!ok || rank < 0 || point == FP_NONE || action == FA_NONE) {
    std::fprintf(stderr,
                 "tpucomm: malformed MPI4JAX_TPU_FAULT spec %s (expected "
                 "rank=R,point=send|recv|connect[,after=N],"
                 "action=hang|exit|close|reset|drop|delay|corrupt"
                 "[,bytes=N][,ms=N])\n",
                 e);
    std::exit(2);  // silently injecting nothing would fake a green test
  }
  if (!has_param) param = action == FA_DROP ? 20 : 100;
  g_fault.rank = rank;
  g_fault.point = point;
  g_fault.after = after;
  g_fault.action = action;
  g_fault.param = param;
  g_fault.armed = true;
}

void fault_init() { std::call_once(g_fault_once, fault_parse); }

/* RST the connection: SO_LINGER{on, 0} + close sends a reset instead
 * of a FIN, so both ends see ECONNRESET — the transient-fault shape
 * the self-healing layer is built to absorb.  Test-only (the closed fd
 * number may be reused; real traffic never calls this). */
void linger_rst(int fd) {
  struct linger lg{1, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
  ::close(fd);
}

/* Fire the armed fault if (rank, point) match and `after` ops have
 * already passed this point.  `c` may be null at the connect point.
 * `fd` is the socket the firing op is about to use (-1 when unknown):
 * reset kills exactly that connection; drop/corrupt arm a thread-local
 * order the frame writer consumes. */
void fault_fire(Comm* c, int rank, int point, const char* what,
                int fd = -1) {
  if (!g_fault.armed || g_fault.rank != rank || g_fault.point != point)
    return;
  if (g_fault.hits.fetch_add(1, std::memory_order_relaxed) < g_fault.after)
    return;
  const char* action = g_fault.action == FA_HANG ? "hang"
                       : g_fault.action == FA_EXIT ? "exit"
                       : g_fault.action == FA_CLOSE ? "close"
                       : g_fault.action == FA_RESET ? "reset"
                       : g_fault.action == FA_DROP ? "drop"
                       : g_fault.action == FA_DELAY ? "delay"
                                                    : "corrupt";
  std::fprintf(stderr,
               "tpucomm r%d: fault injection: %s at point=%s "
               "(MPI4JAX_TPU_FAULT)\n",
               rank, action, what);
  std::fflush(stderr);
  switch (g_fault.action) {
    case FA_HANG:
      for (;;) std::this_thread::sleep_for(std::chrono::seconds(3600));
    case FA_EXIT:
      std::_Exit(17);
    case FA_CLOSE:
      /* shutdown (not close): other threads may hold the fds; all
       * their I/O now fails/EOFs, exactly like a yanked cable */
      if (c)
        for (int fd2 : c->lock_root->socks)
          if (fd2 >= 0) ::shutdown(fd2, SHUT_RDWR);
      g_fault.armed = false;  // a partition happens once
      break;
    case FA_RESET:
      if (fd >= 0)
        linger_rst(fd);
      else if (c)
        /* no specific socket at this point: reset the whole mesh (the
         * self-healing layer reconnects each link it touches next) */
        for (int fd2 : c->lock_root->socks)
          if (fd2 >= 0) linger_rst(fd2);
      g_fault.armed = false;  // a transient happens once
      break;
    case FA_DROP:
    case FA_CORRUPT:
      /* armed for the next frame THIS thread writes; when the link
       * layer is off there is no frame writer to consume the order, so
       * degrade to a reset at the fire point — the fault still lands
       * and the job still fails loudly instead of testing nothing */
      if (retry_armed()) {
        g_wire_fault.action = g_fault.action;
        g_wire_fault.param = g_fault.param;
      } else if (fd >= 0) {
        linger_rst(fd);
      } else if (c) {
        for (int fd2 : c->lock_root->socks)
          if (fd2 >= 0) linger_rst(fd2);
      }
      g_fault.armed = false;
      break;
    case FA_DELAY:
      std::this_thread::sleep_for(
          std::chrono::milliseconds(g_fault.param > 0 ? g_fault.param : 100));
      g_fault.armed = false;
      break;
    default:
      break;
  }
}

/* ============== zero-copy submission backend (UringEngine) ==============
 *
 * The transport floor below the progress engine: when MPI4JAX_TPU_URING
 * resolves to on, every deadline-bounded transfer routes through a
 * per-thread io_uring instead of the poll+read/write pairs — one
 * io_uring_enter both submits and waits, so a small send (header and
 * payload staged into one registered-buffer frame) or a small receive
 * (header + payload speculatively read in one submission) costs ONE
 * syscall where the poll path pays four; the drain loop's descriptor
 * bursts ride single vectored submissions; and oversized sends go out
 * as MSG_ZEROCOPY (IORING_OP_SEND_ZC) with the kernel's buffer-release
 * notification consumed as a CQE before the op returns, so large
 * payloads skip the kernel copy while the caller keeps the historic
 * buffer-ownership contract.
 *
 * Everything layered above the byte movers is untouched: deadlines are
 * progress-based and anchored at post time (the same g_dl_post_anchor
 * handoff), poison frames and fault injection fire at the same logical
 * points, the coalesced-frame wire format is byte-identical, and
 * MPI4JAX_TPU_URING=0 keeps the poll-driven path for sanitizer builds
 * and old kernels.  One ring per thread (rings are not thread-safe;
 * the calling thread, the progress thread, and the writer thread each
 * lazily own one), torn down at thread exit; a ring that loses track
 * of an in-flight completion is marked broken and rebuilt. */

/* ABI constants newer than the build host's kernel headers (the
 * io_uring ABI is append-only; values from include/uapi/linux) */
constexpr uint8_t kOpSendZc = 47;           /* IORING_OP_SEND_ZC (6.0) */
#ifndef IORING_CQE_F_NOTIF
#define IORING_CQE_F_NOTIF (1U << 3)
#endif
/* sqe->ioprio flag (6.2+): the buffer-release NOTIF cqe reports in its
 * res whether the kernel actually avoided the copy */
constexpr uint16_t kSendZcReportUsage = 1U << 3;
constexpr uint32_t kNotifZcCopied = 1U << 31; /* IORING_NOTIF_USAGE_ZC_COPIED */

constexpr int64_t kZcBytes = 64 * 1024;     /* MSG_ZEROCOPY chunk floor
                                             * (op gate: zc_min_bytes) */
constexpr int64_t kUringSmall = 32 * 1024;  /* staged single-frame ceiling
                                             * (mirrors kEagerBytes) */
constexpr size_t kUringStageBytes =
    (size_t)kUringSmall + 4096;             /* frame staging + recv stash */

struct KernelTimespec {  /* __kernel_timespec (s64/s64) */
  int64_t tv_sec;
  int64_t tv_nsec;
};

int g_uring_avail = 0;          /* resolved by uring_probe() */
bool g_uring_zc = false;        /* kernel supports IORING_OP_SEND_ZC */
char g_uring_reason[160] = "not probed";

/* Adaptive MSG_ZEROCOPY: the kernel pins the pages but may still COPY
 * at delivery (loopback and NIC-without-SG paths go through
 * skb_orphan_frags_rx) and then the zero-copy send is all overhead —
 * pinning plus a notification per send for nothing.  The NOTIF cqe
 * reports which happened (kSendZcReportUsage); a streak of copied
 * notifications with no true zero-copy turns SEND_ZC off process-wide
 * and large sends ride plain submitted sends instead.  Kernels older
 * than 6.2 reject the report flag (-EINVAL, retried once without), and
 * then there is no signal — ZC stays on as probed. */
std::atomic<bool> g_zc_report_ok{true};
std::atomic<int> g_zc_copied_streak{0};
std::atomic<bool> g_zc_fallback{false};
constexpr int kZcCopiedStreakOff = 4;

void zc_note_usage(int32_t res) {
  if (!g_zc_report_ok.load(std::memory_order_relaxed)) return;
  if ((uint32_t)res & kNotifZcCopied) {
    int s = g_zc_copied_streak.fetch_add(1, std::memory_order_relaxed) + 1;
    if (s >= kZcCopiedStreakOff)
      /* visible through tpucomm_uring_status() as
       * "on(zerocopy-fallback)" — diag and the bench rows stamp it */
      g_zc_fallback.store(true, std::memory_order_relaxed);
  } else {
    g_zc_copied_streak.store(0, std::memory_order_relaxed);
  }
}

bool zc_enabled() {
  return g_uring_zc && !g_zc_fallback.load(std::memory_order_relaxed);
}

/* Completion-envelope equivalence: a plain send completes once the
 * kernel ACCEPTS the bytes (sndbuf plus whatever the receiver's kernel
 * absorbs in flight), but a SEND_ZC's buffer release waits for the
 * skbs to be FREED — past that window, for the receiving APPLICATION
 * to consume.  Engaging zero-copy for a payload the kernel could have
 * buffered would turn a buffered send into a rendezvous and deadlock
 * cyclic schedules that the poll path (and the analysis match model)
 * accept.  So ZC is gated to sends that exceed the kernel's maximum
 * possible buffering — the TCP autotune ceilings tcp_wmem[2] +
 * tcp_rmem[2] — where the poll path would also have waited on the
 * receiver and the completion envelopes coincide. */
int64_t proc_tcp_ceiling(const char* path, int64_t dflt) {
  FILE* f = std::fopen(path, "re");
  if (!f) return dflt;
  long long lo = 0, mid = 0, hi = 0;
  int n = std::fscanf(f, "%lld %lld %lld", &lo, &mid, &hi);
  std::fclose(f);
  return n == 3 && hi > 0 ? (int64_t)hi : dflt;
}

int64_t zc_min_bytes() {
  static int64_t v = [] {
    int64_t w = proc_tcp_ceiling("/proc/sys/net/ipv4/tcp_wmem", 4 << 20);
    int64_t r = proc_tcp_ceiling("/proc/sys/net/ipv4/tcp_rmem", 6 << 20);
    return std::max(kZcBytes, w + r);
  }();
  return v;
}

/* MPI4JAX_TPU_URING: auto (-1, probe) | 0 (off) | 1 (on, loud when the
 * kernel cannot).  Strict: a typo'd knob must not silently change the
 * submission path under a sanitizer build or a benchmark. */
int uring_mode() {
  static int m = [] {
    const char* e = std::getenv("MPI4JAX_TPU_URING");
    if (!e) return -1;
    /* whitespace-trimmed, like config.uring_mode() (the Python mirror
     * pins byte-for-byte parity) and the sibling knob parsers */
    const char* b = e;
    while (*b && std::isspace((unsigned char)*b)) ++b;
    const char* t = b + std::strlen(b);
    while (t > b && std::isspace((unsigned char)t[-1])) --t;
    std::string v(b, t);
    if (v.empty() || v == "auto") return -1;
    if (v == "0") return 0;
    if (v == "1") return 1;
    std::fprintf(stderr,
                 "tpucomm: cannot parse MPI4JAX_TPU_URING=%s (expected "
                 "auto, 0, or 1)\n", e);
    std::exit(2);
    return 0;
  }();
  return m;
}

struct Uring {
  int fd = -1;
  void* ring_mem = MAP_FAILED;
  size_t ring_bytes = 0;
  void* sqe_mem = MAP_FAILED;
  size_t sqe_bytes = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned sq_mask = 0;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned cq_mask = 0;
  io_uring_sqe* sqes = nullptr;
  io_uring_cqe* cqes = nullptr;
  uint64_t seq = 0;          /* user_data generator */
  bool broken = false;       /* lost an in-flight CQE: rebuild the ring */
  bool fixed_ok = true;      /* READ/WRITE_FIXED accepted on sockets */
  bool registered = false;   /* stage is an IORING_REGISTER_BUFFERS pool */
  std::vector<char> stage;   /* hot payload pool: staged small frames,
                              * speculative receive stash */
  std::vector<uint64_t> notifs; /* SEND_ZC buffer-release notifications
                                 * still in flight (deferred: collected
                                 * opportunistically by every CQE scan,
                                 * forced by u_flush_notifs before a
                                 * zero-copy send returns) */
  ~Uring() {
    if (sqe_mem != MAP_FAILED) ::munmap(sqe_mem, sqe_bytes);
    if (ring_mem != MAP_FAILED) ::munmap(ring_mem, ring_bytes);
    if (fd >= 0) ::close(fd);
  }
};

/* `why` (optional) receives the failure reason.  Only the call_once
 * probe passes the g_uring_reason global — per-thread ring creation in
 * uring_acquire runs concurrently and must not race writers/readers of
 * the process-wide status string. */
Uring* uring_make(unsigned entries, char* why = nullptr,
                  size_t why_len = 0) {
  io_uring_params p{};
  count_sys();
  int fd = (int)::syscall(__NR_io_uring_setup, entries, &p);
  if (fd < 0) {
    if (why)
      std::snprintf(why, why_len, "io_uring_setup: %s",
                    std::strerror(errno));
    return nullptr;
  }
  if (!(p.features & IORING_FEAT_SINGLE_MMAP) ||
      !(p.features & IORING_FEAT_EXT_ARG) ||
      !(p.features & IORING_FEAT_NODROP)) {
    if (why)
      std::snprintf(why, why_len,
                    "kernel io_uring lacks SINGLE_MMAP/EXT_ARG/NODROP "
                    "(features 0x%x; needs >= 5.11)", p.features);
    ::close(fd);
    return nullptr;
  }
  auto u = std::make_unique<Uring>();
  u->fd = fd;
  u->ring_bytes = std::max<size_t>(
      p.sq_off.array + p.sq_entries * sizeof(unsigned),
      p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe));
  u->ring_mem = ::mmap(nullptr, u->ring_bytes, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
  u->sqe_bytes = p.sq_entries * sizeof(io_uring_sqe);
  u->sqe_mem = ::mmap(nullptr, u->sqe_bytes, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES);
  if (u->ring_mem == MAP_FAILED || u->sqe_mem == MAP_FAILED) {
    if (why)
      std::snprintf(why, why_len, "io_uring ring mmap: %s",
                    std::strerror(errno));
    return nullptr;
  }
  char* r = static_cast<char*>(u->ring_mem);
  u->sq_head = reinterpret_cast<unsigned*>(r + p.sq_off.head);
  u->sq_tail = reinterpret_cast<unsigned*>(r + p.sq_off.tail);
  u->sq_mask = *reinterpret_cast<unsigned*>(r + p.sq_off.ring_mask);
  u->sq_array = reinterpret_cast<unsigned*>(r + p.sq_off.array);
  u->cq_head = reinterpret_cast<unsigned*>(r + p.cq_off.head);
  u->cq_tail = reinterpret_cast<unsigned*>(r + p.cq_off.tail);
  u->cq_mask = *reinterpret_cast<unsigned*>(r + p.cq_off.ring_mask);
  u->cqes = reinterpret_cast<io_uring_cqe*>(r + p.cq_off.cqes);
  u->sqes = static_cast<io_uring_sqe*>(u->sqe_mem);
  u->stage.resize(kUringStageBytes);
  struct iovec iov {u->stage.data(), u->stage.size()};
  count_sys();
  if (::syscall(__NR_io_uring_register, fd, IORING_REGISTER_BUFFERS, &iov,
                1) == 0)
    u->registered = true;  /* pinned pool: READ/WRITE_FIXED skip per-op
                            * page pinning; soft — plain ops serve */
  return u.release();
}

/* One-time probe: resolves availability + SEND_ZC support.  mode 1 on
 * an incapable kernel warns loudly and serves the poll path — the CI
 * legs probe tpucomm_uring_status first and SKIP visibly instead. */
void uring_probe() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (uring_mode() == 0) {
      std::snprintf(g_uring_reason, sizeof(g_uring_reason),
                    "disabled (MPI4JAX_TPU_URING=0)");
      return;
    }
    std::unique_ptr<Uring> probe(
        uring_make(8, g_uring_reason, sizeof(g_uring_reason)));
    if (!probe) {
      if (uring_mode() == 1)
        std::fprintf(stderr,
                     "tpucomm: MPI4JAX_TPU_URING=1 but io_uring is "
                     "unavailable (%s); serving the poll path\n",
                     g_uring_reason);
      return;
    }
    struct {
      io_uring_probe p;   /* ops[0] flexible member lands on ops below */
      io_uring_probe_op ops[64];
    } pr{};
    count_sys();
    if (::syscall(__NR_io_uring_register, probe->fd, IORING_REGISTER_PROBE,
                  &pr, 64) == 0 &&
        pr.p.ops_len > kOpSendZc &&
        (pr.ops[kOpSendZc].flags & IO_URING_OP_SUPPORTED))
      g_uring_zc = true;
    g_uring_avail = 1;
  });
}

/* The calling thread's ring, or null (knob off, kernel can't, or this
 * thread's ring creation failed).  A broken ring (lost CQE after a
 * failed cancel) is torn down — the kernel reaps its in-flight state
 * at fd close — and rebuilt once per breakage. */
Uring* uring_acquire() {
  uring_probe();
  if (g_uring_avail != 1) return nullptr;
  static thread_local std::unique_ptr<Uring> tl;
  static thread_local bool tried = false;
  if (tl && tl->broken) {
    tl.reset();
    tried = false;
  }
  if (!tried) {
    tried = true;
    tl.reset(uring_make(64));
  }
  return tl.get();
}

bool uring_ready() { return uring_acquire() != nullptr; }

io_uring_sqe* u_sqe(Uring* u, uint8_t opcode, int fd, const void* addr,
                    uint32_t len) {
  unsigned tail = __atomic_load_n(u->sq_tail, __ATOMIC_RELAXED);
  io_uring_sqe* s = &u->sqes[tail & u->sq_mask];
  std::memset(s, 0, sizeof(*s));
  s->opcode = opcode;
  s->fd = fd;
  s->addr = (uint64_t)(uintptr_t)addr;
  s->len = len;
  s->user_data = ++u->seq;
  u->sq_array[tail & u->sq_mask] = tail & u->sq_mask;
  __atomic_store_n(u->sq_tail, tail + 1, __ATOMIC_RELEASE);
  return s;
}

bool u_cqe(Uring* u, io_uring_cqe* out) {
  unsigned head = __atomic_load_n(u->cq_head, __ATOMIC_RELAXED);
  if (head == __atomic_load_n(u->cq_tail, __ATOMIC_ACQUIRE)) return false;
  *out = u->cqes[head & u->cq_mask];
  __atomic_store_n(u->cq_head, head + 1, __ATOMIC_RELEASE);
  return true;
}

/* submit + wait in one syscall.  wait_s < 0 waits unbounded; >= 0 uses
 * the EXT_ARG timeout.  Returns 0 (caller re-checks CQEs/deadline) or
 * -1 on a hard enter failure. */
int u_enter(Uring* u, unsigned to_submit, unsigned min_complete,
            double wait_s) {
  io_uring_getevents_arg arg{};
  KernelTimespec ts{};
  unsigned flags = IORING_ENTER_GETEVENTS;
  void* argp = nullptr;
  size_t argsz = 0;
  if (min_complete > 0 && wait_s >= 0) {
    double w = std::min(std::max(wait_s, 0.0), 60.0);
    ts.tv_sec = (int64_t)w;
    ts.tv_nsec = (int64_t)((w - (double)ts.tv_sec) * 1e9);
    arg.ts = (uint64_t)(uintptr_t)&ts;
    flags |= IORING_ENTER_EXT_ARG;
    argp = &arg;
    argsz = sizeof(arg);
  }
  count_sys();
  long r = ::syscall(__NR_io_uring_enter, u->fd, to_submit, min_complete,
                     flags, argp, argsz);
  if (r < 0 && errno != ETIME && errno != EINTR) return -1;
  return 0;
}

/* A CQE that is not the op currently waited on: either a stale CQE of
 * a cancelled earlier op (dropped) or a DEFERRED SEND_ZC buffer-release
 * notification (consumed — and its usage report feeds the adaptive
 * zero-copy fallback).  Every CQE scan routes misses through here so a
 * deferred notification can never be mistaken for garbage. */
void u_note_stale(Uring* u, const io_uring_cqe& c) {
  if (!(c.flags & IORING_CQE_F_NOTIF)) return;
  auto it = std::find(u->notifs.begin(), u->notifs.end(), c.user_data);
  if (it == u->notifs.end()) return;
  u->notifs.erase(it);
  zc_note_usage(c.res);
}

/* Collect every deferred SEND_ZC notification — called before a
 * zero-copy send_msg returns so the caller's buffer-ownership contract
 * holds (the kernel has released the pinned pages), WITHOUT having
 * serialized each chunk against the receiver mid-stream.  Bounded: a
 * notification that never arrives marks the ring broken (rebuilt on
 * next acquire; fd close releases the kernel-side state). */
int u_flush_notifs(Uring* u, double budget_s) {
  if (u->notifs.empty()) return 0;
  double limit = now_s() + (budget_s > 0 ? budget_s : 60.0);
  for (;;) {
    io_uring_cqe c;
    bool any = false;
    while (u_cqe(u, &c)) {
      any = true;
      u_note_stale(u, c);
    }
    if (u->notifs.empty()) return 0;
    if (!any && now_s() > limit) break;
    if (u_enter(u, 0, 1, 0.2) < 0) break;
  }
  u->broken = true;
  u->notifs.clear();
  return 1;
}

/* Submit one I/O SQE and wait for its completion — and, for SEND_ZC,
 * for the kernel's buffer-release notification CQE (the MSG_ZEROCOPY
 * errqueue event surfaced through the ring), so the caller's buffer-
 * ownership contract survives the zero-copy send.  With `defer_notif`
 * the notification is NOT waited for here: it is parked on u->notifs
 * (collected opportunistically by later CQE scans, forced by
 * u_flush_notifs before the enclosing send returns) so back-to-back
 * zero-copy chunks pipeline instead of serializing on the receiver.
 * Returns the op's res (> 0 bytes; 0 = EOF on a receive; < 0 =
 * -errno).  When the progress deadline expires first the in-flight SQE
 * is cancelled (and its CQE drained) and *timed_out is set; a drain
 * that fails marks the ring broken, so a recycled user_data can never
 * be mis-matched. */
int64_t u_do(Uring* u, uint8_t opcode, int fd, const void* p, int64_t len,
             uint16_t buf_index, double deadline, bool* timed_out,
             bool defer_notif = false) {
  *timed_out = false;
  io_uring_sqe* s = u_sqe(u, opcode, fd, p,
                          (uint32_t)std::min<int64_t>(len, 1 << 30));
  if (opcode == IORING_OP_READ_FIXED || opcode == IORING_OP_WRITE_FIXED)
    s->buf_index = buf_index;
  if (opcode == kOpSendZc &&
      g_zc_report_ok.load(std::memory_order_relaxed))
    s->ioprio = kSendZcReportUsage;  /* NOTIF res reports copied vs zc */
  const uint64_t ud = s->user_data;
  unsigned to_submit = 1;
  bool got_main = false, need_notif = false;
  int64_t res = 0;
  for (;;) {
    io_uring_cqe c;
    while (u_cqe(u, &c)) {
      if (c.user_data != ud) {
        u_note_stale(u, c);  /* deferred notif or cancelled-op residue */
        continue;
      }
      if (c.flags & IORING_CQE_F_NOTIF) {
        need_notif = false;
        zc_note_usage(c.res);
        continue;
      }
      got_main = true;
      res = c.res;
      if (c.flags & IORING_CQE_F_MORE) need_notif = true;
    }
    if (got_main && need_notif && defer_notif && res > 0) {
      u->notifs.push_back(ud);
      return res;
    }
    if (got_main && !need_notif) return res;
    double wait_s = -1.0;
    if (deadline > 0) {
      double remain = deadline - now_s();
      if (remain <= 0) {
        if (!got_main) {
          /* cancel the in-flight SQE and drain its CQE so the ring
           * stays coherent for the next op */
          io_uring_sqe* cs = u_sqe(u, IORING_OP_ASYNC_CANCEL, -1, nullptr, 0);
          cs->addr = ud;
          const uint64_t cud = cs->user_data;
          bool seen_cancel = false;
          double limit = now_s() + 5.0;
          unsigned sub = 1;
          while (!(got_main && !need_notif) || !seen_cancel) {
            io_uring_cqe d;
            bool any = false;
            while (u_cqe(u, &d)) {
              any = true;
              if (d.user_data == cud) {
                seen_cancel = true;
              } else if (d.user_data == ud) {
                if (d.flags & IORING_CQE_F_NOTIF) need_notif = false;
                else {
                  got_main = true;
                  if (d.flags & IORING_CQE_F_MORE) need_notif = true;
                }
              } else {
                u_note_stale(u, d);
              }
            }
            if ((got_main && !need_notif) && seen_cancel) break;
            if (!any && now_s() > limit) {
              u->broken = true;
              break;
            }
            if (u_enter(u, sub, 1, 0.2) < 0) {
              u->broken = true;
              break;
            }
            sub = 0;
          }
        }
        *timed_out = true;
        return 0;
      }
      wait_s = std::min(remain + 0.001, 60.0);
    }
    if (u_enter(u, to_submit, 1, wait_s) < 0) {
      u->broken = true;
      return -EIO;
    }
    to_submit = 0;
  }
}

/* The poll loop's exact deadline/progress/anchor semantics over
 * submitted SQEs.  `stage_fixed` marks transfers whose buffer lives in
 * the registered staging pool (READ/WRITE_FIXED, no per-op pinning);
 * writes past the buffering ceiling (zc_min_bytes) go out as SEND_ZC
 * when the kernel supports it. */
int u_io_all(Uring* u, int fd, char* p, int64_t n, bool wr, double t,
             bool stage_fixed = false) {
  int64_t left = n;
  double deadline = 0;
  if (t > 0) {
    deadline = now_s() + t;
    if (g_dl_post_anchor > 0) {
      double anchored = g_dl_post_anchor + t;
      if (anchored < deadline) deadline = anchored;
      g_dl_post_anchor = 0;
    }
  }
  /* zero-copy only past the kernel's autotune buffering ceiling (see
   * zc_min_bytes): below it a plain send completes without the
   * receiver, a ZC buffer release cannot, and the mismatch deadlocks
   * cyclic schedules the poll path accepts */
  const bool zc_ok = wr && !stage_fixed && zc_enabled() && n > zc_min_bytes();
  while (left > 0) {
    uint8_t op;
    uint16_t bidx = 0;
    if (wr) {
      if (zc_ok && left >= kZcBytes)
        op = kOpSendZc;
      else if (stage_fixed && u->registered && u->fixed_ok)
        op = IORING_OP_WRITE_FIXED;
      else
        op = IORING_OP_SEND;
    } else {
      op = (stage_fixed && u->registered && u->fixed_ok)
               ? IORING_OP_READ_FIXED
               : IORING_OP_RECV;
    }
    bool timed_out = false;
    /* zero-copy chunks defer their buffer-release notification (the
     * flush below collects them) — waiting per chunk would serialize
     * the whole payload against the receiver's consumption */
    int64_t m = u_do(u, op, fd, p, left, bidx, deadline, &timed_out,
                     op == kOpSendZc);
    if (timed_out) {
      g_io_done = n - left;
      g_io_want = n;
      u_flush_notifs(u, 0.5);  /* best effort: the job is tearing down */
      return 2;
    }
    if (m <= 0) {
      if (m == -EINTR || m == -EAGAIN) continue;
      if ((m == -EINVAL || m == -EOPNOTSUPP) &&
          (op == IORING_OP_WRITE_FIXED || op == IORING_OP_READ_FIXED)) {
        u->fixed_ok = false;  /* kernel rejects fixed ops here: fall back */
        continue;
      }
      if (m == -EINVAL && op == kOpSendZc &&
          g_zc_report_ok.load(std::memory_order_relaxed)) {
        /* kernel < 6.2: no REPORT_USAGE ioprio flag — retry without
         * (and without the adaptive copied signal, see zc_note_usage) */
        g_zc_report_ok.store(false, std::memory_order_relaxed);
        continue;
      }
      if (m == 0 && !wr) {
        errno = ECONNRESET;  /* EOF, not "Success" */
        return 1;
      }
      errno = m < 0 ? (int)-m : EIO;
      u_flush_notifs(u, 0.5);
      return 1;
    }
    p += m;
    left -= m;
    if (t > 0) deadline = now_s() + t;  /* any progress resets the clock */
  }
  /* the zero-copy ownership contract: every deferred notification must
   * land before the caller's buffer is considered released */
  if (wr && !u->notifs.empty() &&
      u_flush_notifs(u, t > 0 ? t : 0) != 0) {
    errno = EIO;
    return 1;
  }
  return 0;
}

int uring_io_all(int fd, void* buf, int64_t n, bool wr, double t) {
  Uring* u = uring_acquire();
  if (!u) return 1;  /* unreachable: callers gate on uring_ready() */
  return u_io_all(u, fd, static_cast<char*>(buf), n, wr, t);
}

/* One speculative receive: up to `len` bytes in a single submission
 * (blocks until at least one byte, exactly like the poll path's header
 * read).  Returns 0 and the byte count, 1 on error, 2 on deadline. */
int u_recv_some(Uring* u, int fd, char* p, int64_t len, int64_t* got,
                double t, bool stage_fixed) {
  double deadline = 0;
  if (t > 0) {
    deadline = now_s() + t;
    if (g_dl_post_anchor > 0) {
      double anchored = g_dl_post_anchor + t;
      if (anchored < deadline) deadline = anchored;
      g_dl_post_anchor = 0;
    }
  }
  for (;;) {
    uint8_t op = (stage_fixed && u->registered && u->fixed_ok)
                     ? IORING_OP_READ_FIXED
                     : IORING_OP_RECV;
    bool timed_out = false;
    int64_t m = u_do(u, op, fd, p, len, 0, deadline, &timed_out);
    if (timed_out) {
      g_io_done = 0;
      g_io_want = len;
      return 2;
    }
    if (m <= 0) {
      if (m == -EINTR || m == -EAGAIN) continue;
      if ((m == -EINVAL || m == -EOPNOTSUPP) && op == IORING_OP_READ_FIXED) {
        u->fixed_ok = false;
        continue;
      }
      if (m == 0) {
        errno = ECONNRESET;
        return 1;
      }
      errno = (int)-m;
      return 1;
    }
    *got = m;
    return 0;
  }
}

/* Vectored deadline-bounded write: the drain loop's descriptor-burst
 * twin of io_all_deadline (iovecs are advanced in place on partial
 * writes; wire bytes are EXACTLY the concatenated frames).  Routes to
 * one OP_WRITEV submission per attempt under uring, poll+writev pairs
 * otherwise. */
void iov_consume(struct iovec** piov, int* pcnt, size_t done) {
  struct iovec* iov = *piov;
  int cnt = *pcnt;
  while (done > 0 && cnt > 0) {
    if (done >= iov->iov_len) {
      done -= iov->iov_len;
      iov++;
      cnt--;
    } else {
      iov->iov_base = static_cast<char*>(iov->iov_base) + done;
      iov->iov_len -= done;
      done = 0;
    }
  }
  *piov = iov;
  *pcnt = cnt;
}

int writev_all_dl(int fd, struct iovec* iov, int iovcnt, int64_t total) {
  const double t = transport_timeout_s();
  int64_t left = total;
  Uring* u = uring_acquire();
  if (u) {
    double deadline = 0;
    if (t > 0) {
      deadline = now_s() + t;
      if (g_dl_post_anchor > 0) {
        double anchored = g_dl_post_anchor + t;
        if (anchored < deadline) deadline = anchored;
        g_dl_post_anchor = 0;
      }
    }
    while (left > 0) {
      bool timed_out = false;
      int64_t m = u_do(u, IORING_OP_WRITEV, fd, iov, iovcnt, 0, deadline,
                       &timed_out);
      if (timed_out) {
        g_io_done = total - left;
        g_io_want = total;
        return 2;
      }
      if (m <= 0) {
        if (m == -EINTR || m == -EAGAIN) continue;
        errno = m < 0 ? (int)-m : EIO;
        return 1;
      }
      left -= m;
      iov_consume(&iov, &iovcnt, (size_t)m);
      if (t > 0) deadline = now_s() + t;
    }
    return 0;
  }
  double deadline = t > 0 ? now_s() + t : 0;
  if (t > 0 && g_dl_post_anchor > 0) {
    double anchored = g_dl_post_anchor + t;
    if (anchored < deadline) deadline = anchored;
    g_dl_post_anchor = 0;
  }
  while (left > 0) {
    if (t > 0) {
      double remain = deadline - now_s();
      if (remain <= 0) {
        g_io_done = total - left;
        g_io_want = total;
        return 2;
      }
      pollfd pf{fd, POLLOUT, 0};
      count_sys();
      int pr = ::poll(&pf, 1, (int)std::min(remain * 1000.0 + 1, 60000.0));
      if (pr < 0) {
        if (errno == EINTR) continue;
        return 1;
      }
      if (pr == 0) continue;
    }
    count_sys();
    ssize_t w = ::writev(fd, iov, iovcnt);
    if (w < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        /* no deadline armed means no poll() above paces this loop; on
         * the uring-made-nonblocking fds an EAGAIN must park like
         * write_all does, not spin */
        if (t <= 0 && io_wait_ready(fd, true) < 0 && errno != EINTR)
          return 1;
        continue;
      }
      return 1;
    }
    left -= w;
    iov_consume(&iov, &iovcnt, (size_t)w);
    if (t > 0) deadline = now_s() + t;
  }
  return 0;
}

/* ============== self-healing link layer ==============
 *
 * Armed by MPI4JAX_TPU_RETRY > 0.  Every wire frame carries a per-link
 * sequence number and connection epoch (MsgHeaderX); small frames are
 * retained in a bounded per-link ring so that when a socket dies
 * (ECONNRESET / EPIPE / deadline / CRC mismatch) the link reconnects —
 * the HIGHER root rank dials the LOWER's bootstrap listener, both sides
 * exchange ReconnectHello{epoch, last_seq_delivered}, the sender
 * replays exactly the gap, and the receiver drops duplicates by seq —
 * exactly-once delivery, bit-identical to a fault-free run.  Frames
 * with no retained copy (rendezvous-large, or evicted) make a replay
 * infeasible: the link goes DEAD and the failure escalates through the
 * historic poison -> abort -> elastic path unchanged. */

/* retention caps: a frame above kRetainMaxFrame is never retained
 * (rendezvous-large: its loss escalates); the per-link ring holds at
 * most kRetainRing bytes, evicting oldest-first */
constexpr int64_t kRetainMaxFrame = 256 * 1024;
constexpr int64_t kRetainRing = 4 * 1024 * 1024;

void link_idle_service(Comm* root);

/* Resolve the LinkState for `peer` of `c` (nullptr when the link layer
 * is off, for self, or before bootstrap populated the maps).  Sub-comms
 * resolve through root_rank to the one LinkState per physical socket. */
LinkState* link_state(Comm* c, int peer, int* out_rp = nullptr) {
  if (!retry_armed()) return nullptr;
  if (peer < 0 || peer >= c->size || c->root_rank.empty()) return nullptr;
  Comm* root = c->lock_root;
  int rp = c->root_rank[(size_t)peer];
  if (rp < 0 || rp >= (int)root->links.size() || !root->links[(size_t)rp])
    return nullptr;
  if (out_rp) *out_rp = rp;
  return root->links[(size_t)rp].get();
}

/* Snapshot the live fd for `peer` (synchronized against a concurrent
 * reconnect's rewiring via rmu).  -1 while a recovery is mid-flight. */
int link_fd(Comm* c, int peer) {
  int rp = -1;
  LinkState* L = link_state(c, peer, &rp);
  if (!L) return c->socks[(size_t)peer];
  std::lock_guard<std::mutex> rl(L->rmu);
  return c->lock_root->socks[(size_t)rp];
}

/* Is this I/O failure the transient-link shape a reconnect can absorb?
 * rc 2 = deadline, 3 = CRC mismatch, 4 = sequence gap (a reconnect
 * replays from the receiver's cursor, healing the gap or proving it
 * unhealable), 1 = errno-described socket death. */
bool io_rc_retryable(int rc) {
  if (!retry_armed()) return false;
  if (rc == 2 || rc == 3 || rc == 4) return true;
  if (rc != 1) return false;
  switch (errno) {
    case ECONNRESET:
    case EPIPE:
    case ECONNABORTED:
    case ETIMEDOUT:
    case EBADF:      // fd parked by a concurrent recovery
    case ENOTCONN:
    case EIO:
      return true;
    default:
      return false;
  }
}

/* Mark an inbound data frame fully delivered: the dedup cursor the next
 * ReconnectHello reports.  MUST be called after the payload is entirely
 * consumed (never before: a replay of a half-read frame would then be
 * dropped as a duplicate and its bytes lost). */
void wire_mark_delivered(Comm* c, int source, uint64_t seq) {
  if (seq == 0) return;
  LinkState* L = link_state(c, source);
  if (L) L->rx_seq.store(seq, std::memory_order_relaxed);
}

/* Write one control frame (ping/pong: seq 0, no payload, no retention).
 * Bounded at 5 s regardless of the job deadline knob — 32 bytes into a
 * socket buffer never legitimately blocks longer. */
int link_send_control(Comm* root, int rp, int tag) {
  LinkState* L = root->links[(size_t)rp].get();
  std::lock_guard<std::mutex> wl(L->wmu);
  int fd = root->socks[(size_t)rp];
  if (fd < 0) return 1;
  MsgHeaderX hx{};
  hx.h = MsgHeader{0, tag, root->comm_id};
  hx.epoch = L->epoch;
  hx_seal(&hx);
  return io_all_deadline<true>(fd, &hx, sizeof(hx), 5.0) == 0 ? 0 : 1;
}

/* Read one DATA frame header from `source`, transparently servicing
 * control frames (ping -> pong reply, pong -> liveness stamp) and
 * dropping replay duplicates (seq <= delivered cursor: payload drained
 * to scratch, counter bumped).  Legacy (unarmed) callers get the plain
 * 16-byte read.  Returns 0 with *h / *seq_out / *fd_out filled (payload
 * reads MUST use *fd_out — the captured fd — not a fresh socks[] load);
 * 1 errno, 2 deadline, 3 CRC mismatch (errno EBADMSG), 4 sequence gap
 * (errno EIO).  Poison frames pass through as data (seq 0). */
int wire_read_hdr(Comm* c, int source, MsgHeader* h, uint64_t* seq_out,
                  int* fd_out) {
  int rp = -1;
  LinkState* L = link_state(c, source, &rp);
  if (!L) {
    if (seq_out) *seq_out = 0;
    if (fd_out) *fd_out = c->socks[(size_t)source];
    return read_all_dl(c->socks[(size_t)source], h, sizeof(*h));
  }
  Comm* root = c->lock_root;
  thread_local std::vector<char> drain;
  for (;;) {
    MsgHeaderX hx{};
    int fd;
    int rc;
    {
      std::unique_lock<std::mutex> rl(L->rmu);
      fd = root->socks[(size_t)rp];
      if (fd < 0) {
        /* a recovery parked the fd mid-rewire; fail retryably so the
         * caller joins (blocks on) that recovery and retries */
        if (fd_out) *fd_out = -1;
        errno = EBADF;
        return 1;
      }
      rc = read_all_dl(fd, &hx, sizeof(hx));
      if (rc == 0 && !hx_check(&hx)) {
        g_lc_crc_errors.fetch_add(1, std::memory_order_relaxed);
        std::fprintf(stderr,
                     "tpucomm r%d: self-heal: header CRC mismatch from r%d "
                     "(wire corruption) — forcing reconnect\n",
                     root->rank, rp);
        errno = EBADMSG;
        rc = 3;
      }
      if (rc == 0) {
        L->last_rx.store(now_s(), std::memory_order_relaxed);
        if (hx.h.tag == kPingTag && hx.h.nbytes == 0) {
          rl.unlock();
          link_send_control(root, rp, kPongTag);  // best-effort
          continue;
        }
        if (hx.h.tag == kPongTag && hx.h.nbytes == 0) continue;
      }
    }
    if (rc != 0) {
      if (fd_out) *fd_out = fd;
      return rc;
    }
    uint64_t seq = (uint64_t)hx.seq_lo | ((uint64_t)hx.seq_hi << 32);
    if (seq != 0) {
      uint64_t rx = L->rx_seq.load(std::memory_order_relaxed);
      if (seq <= rx) {
        /* replay overlap: already delivered — drain and drop */
        g_lc_dup_dropped.fetch_add(1, std::memory_order_relaxed);
        int64_t left = hx.h.nbytes;
        if (left > 0 && (int64_t)drain.size() < std::min<int64_t>(left, 1 << 16))
          drain.resize((size_t)std::min<int64_t>(left, 1 << 16));
        while (left > 0) {
          int64_t take = std::min<int64_t>(left, (int64_t)drain.size());
          int drc = read_all_dl(fd, drain.data(), take);
          if (drc != 0) {
            if (fd_out) *fd_out = fd;
            return drc;
          }
          left -= take;
        }
        continue;
      }
      if (seq != rx + 1) {
        std::fprintf(stderr,
                     "tpucomm r%d: self-heal: sequence gap from r%d "
                     "(expected %llu, got %llu) — forcing reconnect\n",
                     root->rank, rp, (unsigned long long)(rx + 1),
                     (unsigned long long)seq);
        if (fd_out) *fd_out = fd;
        errno = EIO;
        return 4;
      }
    }
    *h = hx.h;
    if (seq_out) *seq_out = seq;
    if (fd_out) *fd_out = fd;
    return 0;
  }
}

/* Rewire every view of root's link to `rp`: the root's own socks slot
 * plus each registered child borrowing it.  Called with the link's rmu
 * AND wmu held (readers/writers load under those), kids_mu taken here. */
void root_update_fd(Comm* root, int rp, int fd) {
  root->socks[(size_t)rp] = fd;
  std::lock_guard<std::mutex> g(root->kids_mu);
  for (Comm* ch : root->kids) {
    if (ch->root_rank.empty()) continue;
    for (int m = 0; m < ch->size; m++)
      if (m != ch->rank && ch->root_rank[(size_t)m] == rp)
        ch->socks[(size_t)m] = fd;
  }
}

void hello_fill(ReconnectHello* h, Comm* root, LinkState* L) {
  std::memset(h, 0, sizeof(*h));
  h->magic = kReconnectMagic;
  h->rank = root->rank;
  h->comm_id = root->comm_id;
  h->epoch = L->epoch;
  h->rx_delivered = L->rx_seq.load(std::memory_order_relaxed);
  h->crc = crc32c(h, offsetof(ReconnectHello, crc));
}

bool hello_ok(const ReconnectHello* h, int expect_rank, int32_t comm_id) {
  ReconnectHello tmp = *h;
  tmp.crc = 0;
  if (crc32c(&tmp, offsetof(ReconnectHello, crc)) != h->crc) return false;
  if (h->magic != kReconnectMagic || h->comm_id != comm_id) return false;
  return expect_rank < 0 || h->rank == expect_rank;
}

/* Nonblocking dial of root rank `rp`'s bootstrap listener with a
 * deadline; returns a connected blocking-mode fd or -1 (errno set). */
int link_dial(Comm* root, int rp, double deadline_s) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)(root->base_port + rp));
  const char* host = root->real_hosts.empty()
                         ? "127.0.0.1"
                         : root->real_hosts[(size_t)rp].c_str();
  ::inet_pton(AF_INET, host, &addr.sin_addr);  // same resolver as bootstrap
  int fl = ::fcntl(fd, F_GETFL, 0);
  ::fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  int cr = ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (cr != 0 && errno == EINPROGRESS) {
    pollfd pf{fd, POLLOUT, 0};
    count_sys();
    int pr = ::poll(&pf, 1, (int)std::max(deadline_s * 1000.0, 1.0));
    if (pr > 0) {
      int soerr = 0;
      socklen_t sl = sizeof(soerr);
      ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &sl);
      if (soerr == 0) {
        cr = 0;
      } else {
        errno = soerr;
        cr = -1;
      }
    } else {
      errno = ETIMEDOUT;
      cr = -1;
    }
  }
  if (cr != 0) {
    int e = errno;
    ::close(fd);
    errno = e;
    return -1;
  }
  ::fcntl(fd, F_SETFL, fl);  // handshake runs blocking-mode reads/writes
  return fd;
}

/* Reconnect the link `c` <-> `peer` after an I/O failure on fd_seen.
 * Returns 0 when the link is healed (the caller retries its frame: a
 * retained send was already replayed, a receive restarts at frame
 * granularity) and 1 when it could not be (link DEAD — the caller
 * escalates through the historic failure path).  Serialized per link on
 * L->mu; latecomers seeing a fresher fd than the one they failed on
 * return healed immediately. */
int link_recover(Comm* c, int peer, int fd_seen, const char* what) {
  int rp = -1;
  LinkState* L = link_state(c, peer, &rp);
  if (!L) return 1;
  Comm* root = c->lock_root;
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->phase.load(std::memory_order_relaxed) == LINK_DEAD) return 1;
  {
    std::lock_guard<std::mutex> rl(L->rmu);
    int cur = root->socks[(size_t)rp];
    if (cur >= 0 && cur != fd_seen) {
      g_heal_acc++;  // healed by the thread that got here first
      return 0;
    }
  }
  g_lc_retries.fetch_add(1, std::memory_order_relaxed);
  g_heal_acc++;
  L->phase.store(LINK_RECONNECTING, std::memory_order_relaxed);
  const int64_t budget = retry_budget();
  std::fprintf(stderr,
               "tpucomm r%d: self-heal: link to r%d failed (%s) — "
               "reconnecting with replay (epoch %u, budget %lld, "
               "MPI4JAX_TPU_RETRY)\n",
               root->rank, rp, what, L->epoch, (long long)budget);
  std::fflush(stderr);
  /* retire the old socket: shutdown wakes any thread still blocked on
   * it; the fd number is parked (closed only at finalize) so a reused
   * number can never alias a blocked thread's view */
  {
    std::lock_guard<std::mutex> rl(L->rmu);
    std::lock_guard<std::mutex> wl(L->wmu);
    int old_fd = root->socks[(size_t)rp];
    if (old_fd >= 0) {
      ::shutdown(old_fd, SHUT_RDWR);
      std::lock_guard<std::mutex> g(root->rcmu);
      root->dead_fds.push_back(old_fd);
    }
    root_update_fd(root, rp, -1);
  }
  /* hold both frame locks for the whole handshake: in-flight readers
   * and writers have failed out of them by now (the shutdown above
   * guarantees progress), and no new frame may touch the wire until
   * the replay is complete */
  std::lock_guard<std::mutex> rl(L->rmu);
  std::lock_guard<std::mutex> wl(L->wmu);
  const bool dialer = root->rank > rp;  // acceptor = lower rank: it owns
                                        // the listener (bootstrap topology)
  /* deterministic per-(rank, link, epoch) jitter: reproducible runs,
   * decorrelated dial storms */
  uint32_t jstate =
      ((uint32_t)root->rank * 2654435761u) ^ ((uint32_t)rp << 16) ^ L->epoch;
  char reason[160];
  std::snprintf(reason, sizeof(reason), "budget exhausted");
  int64_t attempt = 0;
  for (; attempt < budget; attempt++) {
    if (attempt > 0) {
      jstate = jstate * 1664525u + 1013904223u;
      double base = retry_backoff_ms() * (double)(1 << std::min<int64_t>(attempt - 1, 6));
      double jit = base * 0.25 * ((jstate >> 8) & 0xff) / 255.0;
      double ms = std::min(base + jit, 5000.0);
      std::this_thread::sleep_for(
          std::chrono::microseconds((long long)(ms * 1000.0)));
    }
    const double hs_t =
        std::min(5.0, std::max(0.25, retry_backoff_ms() / 1000.0 * 4));
    int nfd = -1;
    ReconnectHello mine{}, theirs{};
    hello_fill(&mine, root, L);
    if (dialer) {
      nfd = link_dial(root, rp, hs_t);
      if (nfd < 0) {
        std::snprintf(reason, sizeof(reason), "dial failed: %s",
                      std::strerror(errno));
        continue;
      }
      if (io_all_deadline<true>(nfd, &mine, sizeof(mine), hs_t) != 0 ||
          io_all_deadline<false>(nfd, &theirs, sizeof(theirs), hs_t) != 0 ||
          !hello_ok(&theirs, rp, root->comm_id)) {
        std::snprintf(reason, sizeof(reason), "handshake failed");
        ::close(nfd);
        nfd = -1;
        continue;
      }
    } else {
      /* acceptor: a dial may already be stashed by the idle service */
      {
        std::lock_guard<std::mutex> g(root->rcmu);
        auto it = root->pending_rc.find(rp);
        if (it != root->pending_rc.end()) {
          nfd = it->second.first;
          theirs = it->second.second;
          root->pending_rc.erase(it);
        }
      }
      if (nfd < 0 && root->listen_fd >= 0) {
        pollfd pf{root->listen_fd, POLLIN, 0};
        count_sys();
        int pr = ::poll(&pf, 1, (int)(hs_t * 1000.0));
        if (pr > 0) {
          int afd = ::accept(root->listen_fd, nullptr, nullptr);
          if (afd >= 0) {
            ReconnectHello hello{};
            if (io_all_deadline<false>(afd, &hello, sizeof(hello), hs_t) ==
                    0 &&
                hello_ok(&hello, -1, root->comm_id) && hello.rank >= 0 &&
                hello.rank < root->size) {
              if (hello.rank == rp) {
                nfd = afd;
                theirs = hello;
              } else {
                /* a DIFFERENT link's dialer: stash for its recovery */
                std::lock_guard<std::mutex> g(root->rcmu);
                auto it = root->pending_rc.find(hello.rank);
                if (it != root->pending_rc.end()) {
                  ::close(it->second.first);
                  it->second = {afd, hello};
                } else {
                  root->pending_rc[hello.rank] = {afd, hello};
                }
              }
            } else {
              ::close(afd);
            }
          }
        }
      }
      if (nfd < 0) {
        std::snprintf(reason, sizeof(reason),
                      "no reconnect dial from peer within the window");
        continue;
      }
      if (io_all_deadline<true>(nfd, &mine, sizeof(mine), hs_t) != 0) {
        std::snprintf(reason, sizeof(reason), "handshake reply failed");
        ::close(nfd);
        nfd = -1;
        continue;
      }
    }
    /* handshake complete: agree on the epoch, check replay feasibility */
    uint32_t new_epoch = std::max(L->epoch, theirs.epoch) + 1;
    uint64_t prx = theirs.rx_delivered;
    if (L->hole_seq.load(std::memory_order_relaxed) > prx) {
      std::snprintf(reason, sizeof(reason),
                    "replay infeasible: peer delivered through seq %llu but "
                    "the oldest retained frame starts after %llu "
                    "(rendezvous-large or evicted sends cannot replay)",
                    (unsigned long long)prx,
                    (unsigned long long)
                        L->hole_seq.load(std::memory_order_relaxed));
      ::close(nfd);
      break;  // a reconnect cannot fix this: escalate now
    }
    /* trim acknowledged frames (keeping replay_slack() extras so the
     * dedup path is exercisable on demand), then replay the gap */
    uint64_t from = prx;
    int64_t slack = replay_slack();
    while (slack > 0 && from > 0) {
      from--;
      slack--;
    }
    while (!L->ring.empty() && L->ring.front().seq <= from) {
      L->ring_bytes -= (int64_t)L->ring.front().bytes.size();
      L->ring.pop_front();
    }
    int64_t replayed = 0;
    int rrc = 0;
    for (const ReplayFrame& rf : L->ring) {
      if (rf.seq <= from) continue;
      rrc = io_all_deadline<true>(nfd, const_cast<char*>(rf.bytes.data()),
                                  (int64_t)rf.bytes.size(),
                                  std::max(hs_t, 5.0));
      if (rrc != 0) break;
      replayed++;
    }
    if (rrc != 0) {
      std::snprintf(reason, sizeof(reason), "replay write failed: %s",
                    std::strerror(errno));
      ::close(nfd);
      continue;
    }
    /* install: TCP options to match bootstrap, rewire every view */
    int one = 1;
    ::setsockopt(nfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (transport_timeout_s() > 0 || uring_ready()) {
      int fl = ::fcntl(nfd, F_GETFL, 0);
      ::fcntl(nfd, F_SETFL, fl | O_NONBLOCK);
    }
    root_update_fd(root, rp, nfd);
    L->epoch = new_epoch;
    L->phase.store(LINK_UP, std::memory_order_relaxed);
    L->last_rx.store(now_s(), std::memory_order_relaxed);
    L->last_ping.store(0, std::memory_order_relaxed);
    g_lc_reconnects.fetch_add(1, std::memory_order_relaxed);
    g_lc_replayed.fetch_add(replayed, std::memory_order_relaxed);
    std::fprintf(stderr,
                 "tpucomm r%d: self-heal: link to r%d recovered (epoch %u, "
                 "replayed %lld frames, %lld dups dropped) [attempt "
                 "%lld/%lld]\n",
                 root->rank, rp, new_epoch, (long long)replayed,
                 (long long)g_lc_dup_dropped.load(std::memory_order_relaxed),
                 (long long)(attempt + 1), (long long)budget);
    std::fflush(stderr);
    return 0;
  }
  L->phase.store(LINK_DEAD, std::memory_order_relaxed);
  std::fprintf(stderr,
               "tpucomm r%d: self-heal: link to r%d DEAD after %lld "
               "attempt(s): %s — escalating (poison -> abort -> elastic)\n",
               root->rank, rp, (long long)std::max<int64_t>(attempt, 1),
               reason);
  std::fflush(stderr);
  return 1;
}

/* Write one DATA frame (header + up to two payload spans) to `dest`,
 * stamping seq/epoch/CRC, retaining small frames for replay, consuming
 * a pending wire-fault order, and healing retryable failures in place.
 * This is THE armed send path: every frame writer routes here so seq
 * assignment and socket writes stay atomic per link (wmu).  Unarmed
 * comms get the historic header+payload writes, byte-identical. */
int link_send_frame(Comm* c, int dest, int tag, const void* p1, int64_t n1,
                    const void* p2, int64_t n2) {
  const int64_t payload = n1 + n2;
  int rp = -1;
  LinkState* L = link_state(c, dest, &rp);
  if (!L) {
    MsgHeader h{payload, tag, c->comm_id};
    int fd = c->socks[(size_t)dest];
    int rc = write_all_dl(fd, &h, sizeof(h));
    if (rc == 0 && n1 > 0) rc = write_all_dl(fd, p1, n1);
    if (rc == 0 && n2 > 0) rc = write_all_dl(fd, p2, n2);
    return rc;
  }
  Comm* root = c->lock_root;
  for (;;) {
    int rc;
    int fd;
    bool retained = false;
    {
      std::unique_lock<std::mutex> wl(L->wmu);
      fd = root->socks[(size_t)rp];
      if (fd < 0) {
        /* a recovery is rewiring the link: join it and retry */
        wl.unlock();
        if (link_recover(c, dest, -1, "send (link down)") == 0) continue;
        errno = ECONNRESET;
        return 1;
      }
      MsgHeaderX hx{};
      hx.h = MsgHeader{payload, tag, c->comm_id};
      hx.epoch = L->epoch;
      uint64_t seq = L->tx_seq.load(std::memory_order_relaxed) + 1;
      L->tx_seq.store(seq, std::memory_order_relaxed);
      hx.seq_lo = (uint32_t)(seq & 0xffffffffu);
      hx.seq_hi = (uint32_t)(seq >> 32);
      hx_seal(&hx);
      WireFault wf = g_wire_fault;
      g_wire_fault = WireFault{};
      const int64_t frame_bytes = (int64_t)sizeof(hx) + payload;
      if (frame_bytes <= kRetainMaxFrame) {
        /* retain the GOOD frame (a corrupt order flips only the wire
         * copy below, so replay restores the true bytes) */
        ReplayFrame rf;
        rf.seq = seq;
        rf.bytes.resize((size_t)frame_bytes);
        std::memcpy(rf.bytes.data(), &hx, sizeof(hx));
        if (n1 > 0) std::memcpy(rf.bytes.data() + sizeof(hx), p1, (size_t)n1);
        if (n2 > 0)
          std::memcpy(rf.bytes.data() + sizeof(hx) + n1, p2, (size_t)n2);
        L->ring.push_back(std::move(rf));
        L->ring_bytes += frame_bytes;
        while (L->ring_bytes > kRetainRing && !L->ring.empty()) {
          uint64_t ev = L->ring.front().seq;
          uint64_t hole = L->hole_seq.load(std::memory_order_relaxed);
          if (ev > hole) L->hole_seq.store(ev, std::memory_order_relaxed);
          L->ring_bytes -= (int64_t)L->ring.front().bytes.size();
          L->ring.pop_front();
        }
        retained = true;
      } else {
        uint64_t hole = L->hole_seq.load(std::memory_order_relaxed);
        if (seq > hole) L->hole_seq.store(seq, std::memory_order_relaxed);
      }
      if (wf.action == FA_CORRUPT) {
        /* flip a header byte AFTER sealing: the receiver's CRC check
         * must catch it (that is the injected failure) */
        MsgHeaderX bad = hx;
        reinterpret_cast<char*>(&bad)[5] ^= 0x40;
        rc = write_all_dl(fd, &bad, sizeof(bad));
        if (rc == 0 && n1 > 0) rc = write_all_dl(fd, p1, n1);
        if (rc == 0 && n2 > 0) rc = write_all_dl(fd, p2, n2);
        /* the bytes landed but the peer will reject them; force our own
         * side into recovery so both ends converge on a fresh epoch */
        if (rc == 0) {
          errno = EBADMSG;
          rc = 3;
        }
      } else if (wf.action == FA_DROP) {
        int64_t keep = std::min<int64_t>(
            wf.param, retained ? (int64_t)L->ring.back().bytes.size()
                               : (int64_t)sizeof(hx));
        const char* src = retained
                              ? L->ring.back().bytes.data()
                              : reinterpret_cast<const char*>(&hx);
        if (keep > 0) (void)write_all_dl(fd, src, keep);
        linger_rst(fd);  // mid-frame kill: the heal below replays it
        errno = ECONNRESET;
        rc = 1;
      } else if (retained) {
        const ReplayFrame& rf = L->ring.back();
        rc = write_all_dl(fd, rf.bytes.data(), (int64_t)rf.bytes.size());
      } else {
        struct iovec iov[3];
        int cnt = 0;
        iov[cnt++] = {&hx, sizeof(hx)};
        if (n1 > 0) iov[cnt++] = {const_cast<void*>(p1), (size_t)n1};
        if (n2 > 0) iov[cnt++] = {const_cast<void*>(p2), (size_t)n2};
        rc = writev_all_dl(fd, iov, cnt, (int64_t)sizeof(hx) + payload);
      }
    }
    if (rc == 0) return 0;
    if (!io_rc_retryable(rc)) return rc;
    int erc = rc;
    int esave = errno;
    if (link_recover(c, dest, fd, "send") == 0) {
      /* healed.  A retained frame was replayed (or confirmed delivered)
       * by the handshake; an unretained frame only reaches here when
       * the peer confirmed full delivery (otherwise the replay gap
       * crossed its hole and recovery escalated). */
      return 0;
    }
    errno = esave;
    return erc;
  }
}

/* Idle-time service, run from the engine's drain loop when the queue is
 * empty (~10 Hz): accepts and stashes reconnect dials so a busy
 * acceptor never strands a dialer, and drives heartbeats over idle
 * links (MPI4JAX_TPU_HEARTBEAT_S > 0).  All lock acquisition is
 * try-only — this must never stall the progress thread. */
void link_idle_service(Comm* root) {
  if (!retry_armed() || root->links.empty()) return;
  /* (a) accept + stash reconnect dials (no comm lock needed: only the
   * rcmu-guarded stash is touched) */
  if (root->listen_fd >= 0) {
    for (;;) {
      pollfd pf{root->listen_fd, POLLIN, 0};
      if (::poll(&pf, 1, 0) <= 0) break;
      int afd = ::accept(root->listen_fd, nullptr, nullptr);
      if (afd < 0) break;
      ReconnectHello hello{};
      if (io_all_deadline<false>(afd, &hello, sizeof(hello), 2.0) != 0 ||
          !hello_ok(&hello, -1, root->comm_id) || hello.rank < 0 ||
          hello.rank >= root->size) {
        ::close(afd);
        continue;
      }
      std::lock_guard<std::mutex> g(root->rcmu);
      auto it = root->pending_rc.find(hello.rank);
      if (it != root->pending_rc.end()) {
        ::close(it->second.first);
        it->second = {afd, hello};
      } else {
        root->pending_rc[hello.rank] = {afd, hello};
      }
    }
  }
  /* (b) heartbeats: ping links idle past the knob, recover links silent
   * past two windows after a ping */
  const double hb = heartbeat_s();
  if (hb <= 0) return;
  std::unique_lock<std::mutex> cl(root->mu, std::try_to_lock);
  if (!cl.owns_lock()) return;  // an op is running: the wire is live
  const double now = now_s();
  for (int rp = 0; rp < (int)root->links.size(); rp++) {
    LinkState* L = root->links[(size_t)rp].get();
    if (!L || L->phase.load(std::memory_order_relaxed) != LINK_UP) continue;
    int fd;
    {
      std::unique_lock<std::mutex> rl(L->rmu, std::try_to_lock);
      if (!rl.owns_lock()) continue;
      fd = root->socks[(size_t)rp];
      if (fd < 0) continue;
      /* consume control replies queued on the idle socket (peek first:
       * data frames must stay for the op path) */
      for (;;) {
        MsgHeaderX hx{};
        ssize_t p = ::recv(fd, &hx, sizeof(hx), MSG_PEEK | MSG_DONTWAIT);
        if (p < (ssize_t)sizeof(hx)) {
          if (p > 0) L->last_rx.store(now, std::memory_order_relaxed);
          break;
        }
        L->last_rx.store(now, std::memory_order_relaxed);
        if (!hx_check(&hx)) break;  // op path owns CRC failures
        if ((hx.h.tag != kPingTag && hx.h.tag != kPongTag) ||
            hx.h.nbytes != 0)
          break;  // data frame: leave it for the op path
        ::recv(fd, &hx, sizeof(hx), MSG_DONTWAIT);  // consume control
        if (hx.h.tag == kPingTag) {
          rl.unlock();
          link_send_control(root, rp, kPongTag);
          rl.lock();
        }
      }
    }
    const double last_rx = L->last_rx.load(std::memory_order_relaxed);
    const double last_ping = L->last_ping.load(std::memory_order_relaxed);
    if (now - last_rx > hb && now - last_ping > hb) {
      if (link_send_control(root, rp, kPingTag) == 0) {
        L->last_ping.store(now, std::memory_order_relaxed);
        g_lc_heartbeats.fetch_add(1, std::memory_order_relaxed);
      } else {
        (void)link_recover(root, rp, fd, "heartbeat send failed");
        continue;
      }
    }
    if (last_ping > last_rx && now - last_ping > 2 * hb)
      (void)link_recover(root, rp, fd, "heartbeat timeout (no pong)");
  }
}

/* ============== job-wide abort propagation (poison frames) ==============
 *
 * When this process aborts (any FAIL surfacing to the Python bridge),
 * tpucomm_abort_all best-effort writes one poison control frame —
 * kPoisonTag header + this process's last-error text — to every peer
 * socket and shuts the sockets down.  A peer blocked in any recv path
 * consumes the poison and fails immediately naming the aborting rank,
 * so the group tears down within one deadline instead of waiting for
 * timeouts to cascade rank by rank. */
constexpr int32_t kPoisonTag = -7707;

/* Consume a poison frame whose header is already read; always fails.
 * `pre`/`pre_len` hand over payload bytes a speculative uring receive
 * already pulled off the socket. */
int poison_fail_pre(Comm* c, int source, const MsgHeader& h,
                    const char* pre, int64_t pre_len) {
  char text[448] = {0};
  int64_t nb = std::min<int64_t>(h.nbytes, (int64_t)sizeof(text) - 1);
  int64_t take = std::min(nb, pre_len);
  if (take > 0) std::memcpy(text, pre, (size_t)take);
  /* best effort: the aborter shuts the socket down right after the
   * frame, so a partial payload ends in EOF, not a hang */
  if (nb > take) {
    int pfd = retry_armed() ? link_fd(c, source) : c->socks[source];
    if (pfd >= 0) read_all_dl(pfd, text + take, nb - take);
  }
  text[sizeof(text) - 1] = 0;
  FAIL(c, "rank %d aborted the job: %s", source,
       text[0] ? text : "(no detail)");
}

int poison_fail(Comm* c, int source, const MsgHeader& h) {
  return poison_fail_pre(c, source, h, nullptr, 0);
}

void self_deliver(Comm* c, int tag, const void* buf, int64_t nbytes) {
  MsgHeader h{nbytes, tag, c->comm_id};
  const char* p = static_cast<const char*>(buf);
  c->self_q.emplace_back(h, std::vector<char>(p, p + nbytes));
}

int send_msg_tcp(Comm* c, int dest, int tag, const void* buf,
                 int64_t nbytes) {
  if (retry_armed()) {
    /* armed path: every frame goes through the link layer (seq/epoch
     * stamp, retention, heal-in-place).  The uring staged-small fast
     * path is bypassed — classic writes still ride uring inside
     * io_all_deadline, but frame assembly must be the link layer's. */
    fault_fire(c, g_job_rank, FP_SEND, "send", link_fd(c, dest));
    int arc = link_send_frame(c, dest, tag, buf, nbytes, nullptr, 0);
    if (arc) FAIL_IO(c, arc, "send to %d", dest);
    return 0;
  }
  fault_fire(c, g_job_rank, FP_SEND, "send");
  MsgHeader h{nbytes, tag, c->comm_id};
  int rc;
  Uring* u;
  if (nbytes <= kUringSmall && (u = uring_acquire()) != nullptr) {
    /* one staged frame, one submission: header + payload go out in a
     * single io_uring_enter from the registered staging pool (the poll
     * path pays two writes, four syscalls with a deadline armed) */
    char* st = u->stage.data();
    std::memcpy(st, &h, sizeof(h));
    if (nbytes > 0) std::memcpy(st + sizeof(h), buf, (size_t)nbytes);
    rc = u_io_all(u, c->socks[dest], st, (int64_t)sizeof(h) + nbytes, true,
                  transport_timeout_s(), /*stage_fixed=*/true);
  } else {
    rc = write_all_dl(c->socks[dest], &h, sizeof(h));
    if (!rc) rc = write_all_dl(c->socks[dest], buf, nbytes);
  }
  if (rc) FAIL_IO(c, rc, "send to %d", dest);
  return 0;
}

int send_msg(Comm* c, int dest, int tag, const void* buf, int64_t nbytes) {
  if (dest < 0 || dest >= c->size) FAIL(c, "send to invalid rank %d", dest);
  if (dest == c->rank) {
    self_deliver(c, tag, buf, nbytes);
    return 0;
  }
  return send_msg_tcp(c, dest, tag, buf, nbytes);
}

/* ---------------- persistent writer (async send half) ---------------- */

void writer_loop(Comm* root) {
  std::unique_lock<std::mutex> lock(root->wmu);
  for (;;) {
    root->wcv.wait(lock, [&] { return root->wstop || !root->wq.empty(); });
    if (root->wstop && root->wq.empty()) return;
    SendJob* j = root->wq.front();
    root->wq.pop_front();
    lock.unlock();
    /* large frames never reach send_msg_tcp's injector hook — a
     * point=send fault must be able to wedge/kill big transfers too
     * (hang here hangs the whole rank: wait_send then never returns,
     * which is exactly the wedged-peer shape the deadlines detect) */
    fault_fire(nullptr, g_job_rank, FP_SEND, "send", j->fd);
    int rc = 0;
    int io;
    if (retry_armed() && j->comm) {
      /* armed: the link layer stamps, (maybe) retains, and heals */
      io = link_send_frame(j->comm, j->dest, j->hdr.tag, j->buf,
                           j->hdr.nbytes, nullptr, 0);
    } else {
      io = write_all_dl(j->fd, &j->hdr, sizeof(j->hdr));
      if (!io) io = write_all_dl(j->fd, j->buf, j->hdr.nbytes);
    }
    if (io) {
      /* wait_send is an unbounded cv wait — this deadline is what keeps
       * it bounded when the peer stops draining the socket */
      char why[160];
      if (io == 2)
        std::snprintf(why, sizeof(why),
                      "timed out after %.0f s with %lld/%lld bytes moved "
                      "(MPI4JAX_TPU_TIMEOUT_S)",
                      transport_timeout_s(), (long long)g_io_done,
                      (long long)g_io_want);
      else
        std::snprintf(why, sizeof(why), "%s", std::strerror(errno));
      std::fprintf(stderr, "tpucomm r%d: async send to %d failed: %s\n",
                   j->rank, j->dest, why);
      set_last_error(j->rank, "async send to %d failed: %s", j->dest, why);
      rc = 1;
    }
    lock.lock();
    j->rc = rc;
    j->done = true;
    root->wdone_cv.notify_all();
  }
}

/* Eager threshold: a frame this small fits far inside the kernel socket
 * buffer (>= 208KB default), so writing it inline cannot block even
 * before the matching receive posts — the writer thread (two context
 * switches on a busy host) is only needed to guarantee progress for
 * payloads that could fill the pipe. */
constexpr int64_t kEagerBytes = 32 * 1024;

/* Queue the send half of a concurrent send+recv round.  Returns 0 and
 * fills `job` on success; nonzero on validation failure (nothing queued).
 * Callers MUST wait_send() before letting `buf` or `job` die. */
int async_send(Comm* c, SendJob* job, int dest, int tag, const void* buf,
               int64_t nbytes) {
  if (dest < 0 || dest >= c->size) FAIL(c, "send to invalid rank %d", dest);
  if (dest == c->rank) {
    /* deliver synchronously so a following recv-from-self (e.g. the
     * sendrecv self case) finds the frame already queued */
    self_deliver(c, tag, buf, nbytes);
    job->rc = 0;
    job->done = true;
    return 0;
  }
  if (ring_p2p_on(c)) {
    bool inlined = false;
    if (shm_try_send(c, dest, tag, buf, nbytes, &inlined)) {
      job->rc = 1;
      job->done = true;
      return 1;
    }
    if (inlined) {
      job->rc = 0;
      job->done = true;
      return 0;
    }
    /* stub in the ring: the payload follows on TCP (eager inline below,
     * or the writer thread for large frames) */
  }
  if (nbytes <= kEagerBytes) {
    job->rc = send_msg(c, dest, tag, buf, nbytes);
    job->done = true;
    return 0;
  }
  job->fd = retry_armed() ? link_fd(c, dest) : c->socks[dest];
  job->rank = c->rank;
  job->dest = dest;
  job->comm = retry_armed() ? c : nullptr;
  job->hdr = MsgHeader{nbytes, tag, c->comm_id};
  job->buf = buf;
  job->rc = 0;
  job->done = false;
  Comm* root = c->lock_root;
  {
    std::lock_guard<std::mutex> lock(root->wmu);
    if (!root->writer_started) {
      root->writer = std::thread(writer_loop, root);
      root->writer_started = true;
    }
    root->wq.push_back(job);
  }
  root->wcv.notify_one();
  return 0;
}

int wait_send(Comm* c, SendJob* job) {
  Comm* root = c->lock_root;
  std::unique_lock<std::mutex> lock(root->wmu);
  root->wdone_cv.wait(lock, [&] { return job->done; });
  return job->rc;
}

/* MPI_ANY_TAG / MPI_ANY_SOURCE analogs (match utils/status.py). */
constexpr int kAnyTag = -1;
constexpr int kAnySource = -2;

/* collective-protocol frames (never visible to user receives) */
constexpr int kCollectiveTag = -7701;

/* Coalesced container frame: several adjacent small sends to one peer
 * packed into one wire frame by the progress engine (sender side).
 * Payload = repeated [MsgHeader | payload] sub-messages, each with its
 * original user tag; the receive side splits them back apart (first
 * matching sub-message lands directly in the posted user buffer, the
 * rest stage in Comm::pending), so tags, sizes, and per-channel order
 * are bit-for-bit what N separate frames would have delivered. */
constexpr int kCoalescedTag = -7703;

/* True when a frame header is eligible for a wildcard receive on comm
 * `c` with tag filter `tag`: right communicator, and either the exact
 * tag or (under ANY_TAG) any *user* tag — collective-protocol frames
 * mean the peer raced ahead into a collective we will run later, and
 * must never be consumed as user data. */
bool header_matches(const Comm* c, const MsgHeader& h, int tag) {
  if (h.tag == kPoisonTag) return false;  // never user data: a peer abort
  if (h.comm_id != c->comm_id) return false;
  if (tag == kAnyTag)
    return h.tag != kCollectiveTag && h.tag != kCoalescedTag;
  return h.tag == tag;
}

/* Read one coalesced container frame (outer header already consumed)
 * from `source` and split it back into user messages.  When `buf` is a
 * posted receive (non-null) whose tag filter matches the FIRST
 * sub-message, that payload lands directly in the user buffer (no
 * staging copy) and *consumed is set; every other sub-message stages
 * in c->pending[source] in arrival order. */
/* `pre`/`pre_len` hand over container bytes a speculative uring receive
 * already pulled off the socket (consumed before any further socket
 * reads — arrival order is preserved exactly). */
/* Armed callers pass the captured frame fd (`frame_fd` >= 0): an I/O
 * failure mid-container then returns the soft sentinel 5 with the real
 * rc stashed in g_stage_soft_rc, so the caller can roll back the staged
 * sub-messages and heal the link — the whole container was retained by
 * the sender and replays verbatim. */
thread_local int g_stage_soft_rc = 0;
int stage_coalesced_pre(Comm* c, int source, const MsgHeader& outer, int tag,
                        void* buf, int64_t nbytes, int32_t* out_tag,
                        int64_t* out_count, bool* consumed,
                        const char* pre, int64_t pre_len,
                        int frame_fd = -1) {
  if (consumed) *consumed = false;
  int64_t pre_off = 0;
  auto rd = [&](void* dst, int64_t n) -> int {
    char* d = static_cast<char*>(dst);
    int64_t take = std::min(n, pre_len - pre_off);
    if (take > 0) {
      std::memcpy(d, pre + pre_off, (size_t)take);
      pre_off += take;
      d += take;
      n -= take;
    }
    if (n <= 0) return 0;
    return read_all_dl(frame_fd >= 0 ? frame_fd : c->socks[source], d, n);
  };
  int64_t remaining = outer.nbytes;
  bool first = true;
  while (remaining > 0) {
    MsgHeader sh{};
    if (remaining < (int64_t)sizeof(sh))
      FAIL(c, "corrupt coalesced frame from rank %d (%lld trailing bytes)",
           source, (long long)remaining);
    int rc = rd(&sh, sizeof(sh));
    if (rc) {
      if (frame_fd >= 0 && io_rc_retryable(rc)) {
        g_stage_soft_rc = rc;
        return 5;
      }
      FAIL_IO(c, rc, "recv coalesced header from %d", source);
    }
    remaining -= sizeof(sh);
    if (sh.comm_id != c->comm_id || sh.nbytes < 0 || sh.nbytes > remaining)
      FAIL(c, "corrupt coalesced sub-message from rank %d (comm %d, %lld "
           "bytes of %lld left)", source, sh.comm_id, (long long)sh.nbytes,
           (long long)remaining);
    if (first && consumed && buf && (tag == kAnyTag || sh.tag == tag) &&
        sh.nbytes <= nbytes) {
      /* pre-posted receive: land the head message straight in the user
       * buffer instead of staging it */
      rc = rd(buf, sh.nbytes);
      if (rc) {
        if (frame_fd >= 0 && io_rc_retryable(rc)) {
          g_stage_soft_rc = rc;
          return 5;
        }
        FAIL_IO(c, rc, "recv coalesced payload from %d", source);
      }
      if (out_tag) *out_tag = sh.tag;
      if (out_count) *out_count = sh.nbytes;
      *consumed = true;
    } else {
      PendingMsg m;
      m.hdr = sh;
      m.data.resize((size_t)sh.nbytes);
      if (sh.nbytes > 0) {
        rc = rd(m.data.data(), sh.nbytes);
        if (rc) {
          if (frame_fd >= 0 && io_rc_retryable(rc)) {
            g_stage_soft_rc = rc;
            return 5;
          }
          FAIL_IO(c, rc, "recv coalesced payload from %d", source);
        }
      }
      c->pending[source].push_back(std::move(m));
    }
    remaining -= sh.nbytes;
    first = false;
  }
  if (pre_off < pre_len)
    /* the speculative read ran past the whole container — only possible
     * when the awaited message is shorter than posted, which the strict
     * caller is about to abort on; fail with its wording here so the
     * over-read can never silently desynchronize the stream */
    FAIL(c, "size mismatch from rank %d: expected %lld bytes, got %lld",
         source, (long long)nbytes,
         (long long)(outer.nbytes - (int64_t)sizeof(MsgHeader)));
  return 0;
}

int stage_coalesced(Comm* c, int source, const MsgHeader& outer, int tag,
                    void* buf, int64_t nbytes, int32_t* out_tag,
                    int64_t* out_count, bool* consumed) {
  return stage_coalesced_pre(c, source, outer, tag, buf, nbytes, out_tag,
                             out_count, consumed, nullptr, 0);
}

/* Consume the head of c->pending[source] into a posted receive, with
 * exactly the checks the wire path applies (order violation on a tag
 * mismatch, truncation on a short buffer). */
int consume_pending(Comm* c, int source, int tag, void* buf, int64_t nbytes,
                    int32_t* out_src, int32_t* out_tag, int64_t* out_count) {
  auto& q = c->pending[source];
  if (q.empty())
    FAIL(c, "internal: empty pending queue for rank %d", source);
  PendingMsg m = std::move(q.front());
  q.pop_front();
  if (q.empty()) c->pending.erase(source);
  if (tag != kAnyTag && m.hdr.tag != tag)
    FAIL(c, "message order violation: expected tag %d from rank %d, got %d",
         tag, source, m.hdr.tag);
  if (m.hdr.nbytes > nbytes)
    FAIL(c, "message truncated: rank %d sent %lld bytes into a %lld-byte "
         "buffer", source, (long long)m.hdr.nbytes, (long long)nbytes);
  std::memcpy(buf, m.data.data(), (size_t)m.hdr.nbytes);
  if (out_src) *out_src = source;
  if (out_tag) *out_tag = m.hdr.tag;
  if (out_count) *out_count = m.hdr.nbytes;
  return 0;
}

/* Head of the pending queue for `source`, or null. */
const MsgHeader* pending_head(Comm* c, int source) {
  auto it = c->pending.find(source);
  if (it == c->pending.end() || it->second.empty()) return nullptr;
  return &it->second.front().hdr;
}

/* ANY_SOURCE resolution: poll every peer socket until one holds a
 * complete frame HEADER that matches (comm_id, tag), return its rank.
 * Per-socket order is still strict, so a wildcard receive composes with
 * the ordered-transport contract (the reference's default — its libmpi
 * matches MPI_ANY_SOURCE natively, reference recv.py:45).  A socket
 * whose next frame does NOT match can never satisfy this wildcard (its
 * head cannot be consumed while we hold the comm lock) and is dropped
 * from the candidate set, as are peers that exited cleanly. */
int poll_any_source_once(Comm* c, int tag, int* out_source) {
  const bool armed = retry_armed() && !c->root_rank.empty();
  std::vector<pollfd> fds;
  std::vector<int> ranks;
  for (int r = 0; r < c->size; r++) {
    int fd = armed ? link_fd(c, r) : c->socks[r];
    if (fd < 0) continue;
    fds.push_back({fd, POLLIN, 0});
    ranks.push_back(r);
  }
  if (fds.empty()) FAIL(c, "ANY_SOURCE recv with no peers");
  const double t = transport_timeout_s();
  double deadline = t > 0 ? now_s() + t : 0;
  /* per-candidate peeked-header byte counts: the deadline must reset on
   * actual byte PROGRESS, not on poll readiness — a peer stalled
   * mid-header keeps POLLIN asserted forever, which would both defeat
   * the timeout and busy-spin the level-triggered poll */
  std::vector<int64_t> peeked(ranks.size(), 0);
  for (;;) {
    count_sys();
    int n = ::poll(fds.data(), fds.size(), t > 0 ? 100 : -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      FAIL(c, "ANY_SOURCE poll failed: %s", std::strerror(errno));
    }
    if (n == 0) {
      if (t > 0 && now_s() > deadline)
        FAIL(c,
             "ANY_SOURCE recv timed out after %.0f s — no peer delivered "
             "a matching message (MPI4JAX_TPU_TIMEOUT_S)",
             t);
      continue;
    }
    bool progress = false;
    std::vector<size_t> dead;
    for (size_t i = 0; i < fds.size(); i++) {
      if (!(fds[i].revents & POLLIN)) {
        if (fds[i].revents & (POLLHUP | POLLERR)) {
          if (armed &&
              link_recover(c, ranks[i], fds[i].fd, "ANY_SOURCE poll") == 0)
            return -2;  // healed: rebuild the candidate set
          dead.push_back(i);
        }
        continue;
      }
      if (armed) {
        /* armed wire format: peek the 32-byte extended header, service
         * control frames and replay duplicates in place, and heal a
         * failing candidate instead of writing it off */
        MsgHeaderX hx{};
        count_sys();
        ssize_t p = ::recv(fds[i].fd, &hx, sizeof(hx),
                           MSG_PEEK | MSG_DONTWAIT);
        if (p == (ssize_t)sizeof(hx)) {
          LinkState* L = link_state(c, ranks[i]);
          if (L) L->last_rx.store(now_s(), std::memory_order_relaxed);
          if (!hx_check(&hx)) {
            g_lc_crc_errors.fetch_add(1, std::memory_order_relaxed);
            errno = EBADMSG;
            if (link_recover(c, ranks[i], fds[i].fd,
                             "ANY_SOURCE header CRC") == 0)
              return -2;
            FAIL(c, "header CRC mismatch from rank %d (wire corruption)",
                 ranks[i]);
          }
          uint64_t seq = (uint64_t)hx.seq_lo | ((uint64_t)hx.seq_hi << 32);
          if ((hx.h.tag == kPingTag || hx.h.tag == kPongTag) &&
              hx.h.nbytes == 0) {
            ::recv(fds[i].fd, &hx, sizeof(hx), MSG_DONTWAIT);
            if (hx.h.tag == kPingTag && L)
              link_send_control(c->lock_root, c->root_rank[(size_t)ranks[i]],
                                kPongTag);
            progress = true;
            continue;
          }
          if (hx.h.tag == kPoisonTag) {
            ::recv(fds[i].fd, &hx, sizeof(hx), MSG_DONTWAIT);  // consume
            return poison_fail(c, ranks[i], hx.h);
          }
          if (seq != 0 && L &&
              seq <= L->rx_seq.load(std::memory_order_relaxed)) {
            /* replay duplicate at the head: consume and drop it */
            ::recv(fds[i].fd, &hx, sizeof(hx), MSG_DONTWAIT);
            g_lc_dup_dropped.fetch_add(1, std::memory_order_relaxed);
            thread_local std::vector<char> drain;
            int64_t left = hx.h.nbytes;
            if (left > 0 && (int64_t)drain.size() <
                                std::min<int64_t>(left, 1 << 16))
              drain.resize((size_t)std::min<int64_t>(left, 1 << 16));
            int drc = 0;
            while (left > 0 && drc == 0) {
              int64_t take = std::min<int64_t>(left, (int64_t)drain.size());
              drc = read_all_dl(fds[i].fd, drain.data(), take);
              left -= take;
            }
            if (drc) {
              if (io_rc_retryable(drc) &&
                  link_recover(c, ranks[i], fds[i].fd,
                               "ANY_SOURCE dup drain") == 0)
                return -2;
              FAIL_IO(c, drc, "recv payload from %d", ranks[i]);
            }
            progress = true;
            continue;
          }
          if (seq != 0 && L &&
              seq != L->rx_seq.load(std::memory_order_relaxed) + 1) {
            errno = EIO;
            if (link_recover(c, ranks[i], fds[i].fd,
                             "ANY_SOURCE sequence gap") == 0)
              return -2;
            FAIL(c, "sequence gap from rank %d", ranks[i]);
          }
          if (hx.h.tag == kCoalescedTag && hx.h.comm_id == c->comm_id) {
            MsgHeaderX outer{};
            int orc = read_all_dl(fds[i].fd, &outer, sizeof(outer));
            if (orc) {
              if (io_rc_retryable(orc) &&
                  link_recover(c, ranks[i], fds[i].fd,
                               "ANY_SOURCE coalesced header") == 0)
                return -2;
              FAIL(c, "recv coalesced header from %d failed: %s", ranks[i],
                   std::strerror(errno));
            }
            size_t staged0 = 0;
            {
              auto it = c->pending.find(ranks[i]);
              if (it != c->pending.end()) staged0 = it->second.size();
            }
            int src = stage_coalesced_pre(c, ranks[i], outer.h, kAnyTag,
                                          nullptr, 0, nullptr, nullptr,
                                          nullptr, nullptr, 0, fds[i].fd);
            if (src == 5) {
              auto it = c->pending.find(ranks[i]);
              if (it != c->pending.end()) {
                while (it->second.size() > staged0) it->second.pop_back();
                if (it->second.empty()) c->pending.erase(it);
              }
              if (link_recover(c, ranks[i], fds[i].fd,
                               "ANY_SOURCE coalesced") == 0)
                return -2;
              FAIL_IO(c, g_stage_soft_rc, "recv coalesced payload from %d",
                      ranks[i]);
            }
            if (src) return 1;
            wire_mark_delivered(c, ranks[i], seq);
            const MsgHeader* ph = pending_head(c, ranks[i]);
            if (ph && (tag == kAnyTag || ph->tag == tag)) {
              *out_source = ranks[i];
              return 0;
            }
            dead.push_back(i);  // staged head can never match
            continue;
          }
          if (header_matches(c, hx.h, tag)) {
            *out_source = ranks[i];
            return 0;
          }
          dead.push_back(i);  // head frame can never match this wildcard
        } else if (p == 0 || (p < 0 && errno != EAGAIN &&
                              errno != EWOULDBLOCK && errno != EINTR)) {
          if (p == 0) errno = ECONNRESET;
          if (io_rc_retryable(1) &&
              link_recover(c, ranks[i], fds[i].fd, "ANY_SOURCE peek") == 0)
            return -2;
          dead.push_back(i);
        } else if (p > 0 && (int64_t)p > peeked[i]) {
          peeked[i] = p;
          progress = true;
        }
        continue;
      }
      {
        /* POLLIN also fires for EOF; peek the header to tell a real
         * matching frame from a mismatch or a peer that exited */
        MsgHeader h{};
        count_sys();
        ssize_t p = ::recv(fds[i].fd, &h, sizeof(h),
                           MSG_PEEK | MSG_DONTWAIT);
        if (p == (ssize_t)sizeof(h)) {
          if (h.tag == kPoisonTag) {
            ::recv(fds[i].fd, &h, sizeof(h), MSG_DONTWAIT);  // consume hdr
            return poison_fail(c, ranks[i], h);
          }
          if (h.tag == kCoalescedTag && h.comm_id == c->comm_id) {
            /* a coalesced container at the head: split it into pending
             * (consuming the frame preserves per-channel order), then
             * judge the wildcard on the FIRST sub-message's tag */
            MsgHeader outer{};
            if (read_all_dl(c->socks[ranks[i]], &outer, sizeof(outer)))
              FAIL(c, "recv coalesced header from %d failed: %s", ranks[i],
                   std::strerror(errno));
            if (stage_coalesced(c, ranks[i], outer, kAnyTag, nullptr, 0,
                                nullptr, nullptr, nullptr))
              return 1;
            const MsgHeader* ph = pending_head(c, ranks[i]);
            if (ph && (tag == kAnyTag || ph->tag == tag)) {
              *out_source = ranks[i];
              return 0;
            }
            dead.push_back(i);  // staged head can never match
            continue;
          }
          if (header_matches(c, h, tag)) {
            *out_source = ranks[i];
            return 0;
          }
          dead.push_back(i);  // head frame can never match this wildcard
        } else if (p == 0 || (p < 0 && errno != EAGAIN &&
                              errno != EWOULDBLOCK && errno != EINTR)) {
          dead.push_back(i);
        } else if (p > 0 && (int64_t)p > peeked[i]) {
          peeked[i] = p;  // header still arriving: real byte progress
          progress = true;
        }
      }
    }
    if (t > 0) {
      if (progress || !dead.empty()) {
        deadline = now_s() + t;
      } else {
        /* only stalled partial headers keep POLLIN raised with nothing
         * to do: the deadline must be checked HERE too (poll keeps
         * returning ready, so the n == 0 check above never runs), and
         * the loop paced so it can fire without burning a core */
        if (now_s() > deadline)
          FAIL(c,
               "ANY_SOURCE recv timed out after %.0f s — a peer stalled "
               "mid-frame (MPI4JAX_TPU_TIMEOUT_S)",
               t);
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
      }
    }
    for (size_t k = dead.size(); k-- > 0;) {
      fds.erase(fds.begin() + dead[k]);
      ranks.erase(ranks.begin() + dead[k]);
      peeked.erase(peeked.begin() + dead[k]);
    }
    if (fds.empty())
      FAIL(c, "ANY_SOURCE recv: no peer can deliver a matching message "
           "(all disconnected, mismatched, or on other communicators)");
  }
}

int poll_any_source(Comm* c, int tag, int* out_source) {
  for (;;) {
    int rc = poll_any_source_once(c, tag, out_source);
    if (rc != -2) return rc;  // -2: a link healed mid-poll — restart with
                              // fresh fds (the candidate set was rewired)
  }
}

/* Strict-receive fast path over the uring backend: header AND payload
 * speculatively pulled in ONE submission into the registered stash
 * (the sender wrote them contiguously, so they almost always arrive
 * together) — one syscall where the poll path pays four.  Safe because
 * the channel is strictly ordered: the frame at the head IS the one
 * this receive awaits, and every divergence is recoverable —
 *   - a coalesced container's over-pulled content is handed to the
 *     splitter as a prefix (arrival order preserved bit-for-bit),
 *   - a poison frame's text rides the prefix into the abort message,
 *   - any mismatched header fails exactly like the classic path (the
 *     job is aborting; stream position no longer matters).
 * Callers: strict exact-size receives only (recv_msg) — wildcard and
 * status receives keep the classic two-stage path. */
int uring_recv_frame(Comm* c, Uring* u, int source, int tag, void* buf,
                     int64_t nbytes, int64_t* out_count) {
  const int fd = c->socks[source];
  const int64_t want = (int64_t)sizeof(MsgHeader) + nbytes;
  char* st = u->stage.data();
  int64_t got = 0;
  {
    /* first bytes = header arrival = the blocked share (the sender has
     * not reached the matching send until they appear) */
    ObsWaitTimer wt;
    int rc = u_recv_some(u, fd, st, want, &got, transport_timeout_s(),
                         /*stage_fixed=*/true);
    if (rc) FAIL_IO(c, rc, "recv header from %d", source);
    while (got < (int64_t)sizeof(MsgHeader)) {
      int64_t more = 0;
      rc = u_recv_some(u, fd, st + got, want - got, &more,
                       transport_timeout_s(), /*stage_fixed=*/true);
      if (rc) FAIL_IO(c, rc, "recv header from %d", source);
      got += more;
    }
  }
  MsgHeader h;
  std::memcpy(&h, st, sizeof(h));
  const char* body = st + sizeof(h);
  const int64_t body_got = got - (int64_t)sizeof(h);
  if (h.tag == kPoisonTag)
    return poison_fail_pre(c, source, h, body, body_got);
  if (h.comm_id != c->comm_id)
    FAIL(c, "communicator mismatch: rank %d's message is for comm %d, this "
         "is comm %d — ops on sibling communicators must run in a "
         "consistent order on both endpoints", source, h.comm_id,
         c->comm_id);
  if (h.tag == kCoalescedTag) {
    bool consumed = false;
    if (stage_coalesced_pre(c, source, h, tag, buf, nbytes, nullptr,
                            out_count, &consumed, body, body_got))
      return 1;
    if (consumed) return 0;
    return consume_pending(c, source, tag, buf, nbytes, nullptr, nullptr,
                           out_count);
  }
  if (h.tag != tag)
    FAIL(c, "message order violation: expected tag %d from rank %d, got %d",
         tag, source, h.tag);
  if (h.nbytes > nbytes)
    FAIL(c, "message truncated: rank %d sent %lld bytes into a %lld-byte "
         "buffer", source, (long long)h.nbytes, (long long)nbytes);
  int64_t take = std::min(body_got, h.nbytes);
  if (take > 0) std::memcpy(buf, body, (size_t)take);
  if (h.nbytes > take) {
    int rc = u_io_all(u, fd, static_cast<char*>(buf) + take,
                      h.nbytes - take, false, transport_timeout_s());
    if (rc) FAIL_IO(c, rc, "recv payload from %d", source);
  } else if (body_got > h.nbytes) {
    /* over-pulled past a SHORT frame: only reachable when the strict
     * caller is about to abort on the size check — abort with its
     * wording here, never leave the stream desynchronized */
    FAIL(c, "size mismatch from rank %d: expected %lld bytes, got %lld",
         source, (long long)nbytes, (long long)h.nbytes);
  }
  if (out_count) *out_count = h.nbytes;
  return 0;
}

/* Full-featured receive: ANY_TAG / ANY_SOURCE wildcards and short
 * messages allowed (buffer larger than the payload — MPI receive
 * semantics), with the actual source/tag/byte-count reported for status
 * introspection.  The strict recv_msg below keeps the exact-match
 * contract collectives rely on. */
int recv_msg_status(Comm* c, int source, int tag, void* buf, int64_t nbytes,
                    int32_t* out_src, int32_t* out_tag, int64_t* out_count,
                    bool strict_exact = false) {
  fault_fire(c, g_job_rank, FP_RECV, "recv");
  if (source == kAnySource) {
    /* a queued self-message is already complete — it wins immediately,
     * but only when its header actually matches the tag filter (a
     * mismatched self head cannot satisfy this wildcard; a peer might).
     * Staged coalesced sub-messages are equally complete and win next. */
    int pending_src = -1;
    for (const auto& kv : c->pending)
      if (!kv.second.empty() &&
          (tag == kAnyTag || kv.second.front().hdr.tag == tag)) {
        pending_src = kv.first;
        break;
      }
    if (!c->self_q.empty() &&
        header_matches(c, c->self_q.front().first, tag)) {
      source = c->rank;
    } else if (pending_src >= 0) {
      source = pending_src;
    } else if (ring_p2p_on(c)) {
      ObsWaitTimer wt;  // wildcard resolution is pure arrival wait
      if (ring_poll_any(c, tag, &source)) return 1;
    } else {
      ObsWaitTimer wt;
      if (poll_any_source(c, tag, &source)) return 1;
    }
  }
  if (source < 0 || source >= c->size)
    FAIL(c, "recv from invalid rank %d", source);
  if (source == c->rank) {
    /* self-delivery: the ordered op stream means the matching send must
     * already have run (a blocking self-recv first would deadlock —
     * program error, same as MPI) */
    if (c->self_q.empty())
      FAIL(c, "recv from self with no pending self-message");
    auto [h, payload] = std::move(c->self_q.front());
    c->self_q.pop_front();
    if (tag != kAnyTag && h.tag != tag)
      FAIL(c, "message order violation: expected tag %d from self, got %d",
           tag, h.tag);
    if (h.nbytes > nbytes)
      FAIL(c, "message truncated: self-message of %lld bytes into a "
           "%lld-byte buffer", (long long)h.nbytes, (long long)nbytes);
    std::memcpy(buf, payload.data(), h.nbytes);
    if (out_src) *out_src = c->rank;
    if (out_tag) *out_tag = h.tag;
    if (out_count) *out_count = h.nbytes;
    return 0;
  }
  if (pending_head(c, source))
    /* a previously split coalesced frame already delivered this
     * channel's next message: consume it in order, same checks as the
     * wire path */
    return consume_pending(c, source, tag, buf, nbytes, out_src, out_tag,
                           out_count);
  if (ring_p2p_on(c))
    return shm_recv_status(c, source, tag, buf, nbytes, out_src, out_tag,
                           out_count);
  Uring* u;
  if (strict_exact && !retry_armed() && tag != kAnyTag && nbytes > 0 &&
      nbytes <= kUringSmall && (u = uring_acquire()) != nullptr)
    /* strict exact-size receive (recv_msg says so EXPLICITLY — a
     * status caller passing null src/tag still keeps legal
     * short-message semantics): one speculative submission pulls the
     * whole frame (see uring_recv_frame).  Gated off when the link
     * layer is armed: speculative over-pulls cannot be rolled back at
     * frame granularity, which replay-after-reconnect requires (classic
     * reads still ride uring inside io_all_deadline). */
    return uring_recv_frame(c, u, source, tag, buf, nbytes, out_count);
  if (out_src) *out_src = source;
  MsgHeader h{};
  uint64_t seq = 0;
  int ffd = -1;
  int rc;
  for (;;) {
    {
      /* header arrival is the wait phase: the sender hasn't reached (or
       * hasn't finished) the matching send until these bytes appear */
      ObsWaitTimer wt;
      rc = wire_read_hdr(c, source, &h, &seq, &ffd);
    }
    if (rc) {
      /* transient link death with the layer armed: reconnect + replay,
       * then restart this receive at frame granularity (nothing of the
       * failed frame was delivered — delivery marks only run below) */
      if (io_rc_retryable(rc) &&
          link_recover(c, source, ffd, "recv header") == 0)
        continue;
      FAIL_IO(c, rc, "recv header from %d", source);
    }
    if (h.tag == kPoisonTag) return poison_fail(c, source, h);
    if (h.comm_id != c->comm_id)
      FAIL(c, "communicator mismatch: rank %d's message is for comm %d, this "
           "is comm %d — ops on sibling communicators must run in a "
           "consistent order on both endpoints", source, h.comm_id,
           c->comm_id);
    if (h.tag == kCoalescedTag) {
      /* split the container: the first sub-message lands directly in this
       * posted receive when it matches; the rest stage for later recvs */
      size_t staged0 = 0;
      {
        auto it = c->pending.find(source);
        if (it != c->pending.end()) staged0 = it->second.size();
      }
      bool consumed = false;
      int src = stage_coalesced_pre(c, source, h, tag, buf, nbytes, out_tag,
                                    out_count, &consumed, nullptr, 0,
                                    retry_armed() ? ffd : -1);
      if (src == 5) {
        /* mid-container link death: roll the partially staged split
         * back (the sender retained the whole container — the replay
         * redelivers it verbatim from its first byte) */
        auto it = c->pending.find(source);
        if (it != c->pending.end()) {
          while (it->second.size() > staged0) it->second.pop_back();
          if (it->second.empty()) c->pending.erase(it);
        }
        if (link_recover(c, source, ffd, "recv coalesced") == 0) continue;
        FAIL_IO(c, g_stage_soft_rc, "recv coalesced payload from %d",
                source);
      }
      if (src) return 1;
      wire_mark_delivered(c, source, seq);
      if (consumed) return 0;
      return consume_pending(c, source, tag, buf, nbytes, out_src, out_tag,
                             out_count);
    }
    if (tag != kAnyTag && h.tag != tag)
      FAIL(c, "message order violation: expected tag %d from rank %d, got %d",
           tag, source, h.tag);
    if (h.nbytes > nbytes)
      FAIL(c, "message truncated: rank %d sent %lld bytes into a %lld-byte "
           "buffer", source, (long long)h.nbytes, (long long)nbytes);
    rc = read_all_dl(ffd, buf, h.nbytes);
    if (rc) {
      if (io_rc_retryable(rc) &&
          link_recover(c, source, ffd, "recv payload") == 0)
        continue;  // the replay redelivers this frame from its header
      FAIL_IO(c, rc, "recv payload from %d", source);
    }
    wire_mark_delivered(c, source, seq);
    break;
  }
  if (out_tag) *out_tag = h.tag;
  if (out_count) *out_count = h.nbytes;
  return 0;
}

int recv_msg(Comm* c, int source, int tag, void* buf, int64_t nbytes) {
  int64_t count = 0;
  if (recv_msg_status(c, source, tag, buf, nbytes, nullptr, nullptr, &count,
                      /*strict_exact=*/true))
    return 1;
  if (count != nbytes)
    FAIL(c, "size mismatch from rank %d: expected %lld bytes, got %lld",
         source, (long long)nbytes, (long long)count);
  return 0;
}

/* ---------------- element-wise reduction kernels ---------------- */

float bf16_to_f32(uint16_t v) {
  uint32_t bits = (uint32_t)v << 16;
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  /* round to nearest even */
  uint32_t rounded = bits + 0x7fff + ((bits >> 16) & 1);
  return (uint16_t)(rounded >> 16);
}

float f16_to_f32(uint16_t v) {
  uint32_t sign = (v & 0x8000u) << 16;
  uint32_t exp = (v >> 10) & 0x1f;
  uint32_t mant = v & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {
      exp = 127 - 15 + 1;
      while (!(mant & 0x400)) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3ff;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7f800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

uint16_t f32_to_f16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = (int32_t)((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (exp >= 31) return (uint16_t)(sign | 0x7c00u); /* inf/overflow */
  if (exp <= 0) return (uint16_t)sign;              /* flush denormals */
  return (uint16_t)(sign | (exp << 10) | (mant >> 13));
}

template <typename T>
void combine_typed(T* acc, const T* in, int64_t n, int op) {
  switch (op) {
    case TPU_SUM:
      for (int64_t i = 0; i < n; i++) acc[i] = acc[i] + in[i];
      break;
    case TPU_PROD:
      for (int64_t i = 0; i < n; i++) acc[i] = acc[i] * in[i];
      break;
    case TPU_MAX:
      for (int64_t i = 0; i < n; i++)
        acc[i] = acc[i] < in[i] ? in[i] : acc[i];
      break;
    case TPU_MIN:
      for (int64_t i = 0; i < n; i++)
        acc[i] = in[i] < acc[i] ? in[i] : acc[i];
      break;
    default:
      break;
  }
}

template <typename T>
void combine_integer(T* acc, const T* in, int64_t n, int op) {
  switch (op) {
    case TPU_LAND:
      for (int64_t i = 0; i < n; i++) acc[i] = (T)((acc[i] != 0) && (in[i] != 0));
      break;
    case TPU_LOR:
      for (int64_t i = 0; i < n; i++) acc[i] = (T)((acc[i] != 0) || (in[i] != 0));
      break;
    case TPU_LXOR:
      for (int64_t i = 0; i < n; i++) acc[i] = (T)((acc[i] != 0) ^ (in[i] != 0));
      break;
    case TPU_BAND:
      for (int64_t i = 0; i < n; i++) acc[i] = acc[i] & in[i];
      break;
    case TPU_BOR:
      for (int64_t i = 0; i < n; i++) acc[i] = acc[i] | in[i];
      break;
    case TPU_BXOR:
      for (int64_t i = 0; i < n; i++) acc[i] = acc[i] ^ in[i];
      break;
    default:
      combine_typed(acc, in, n, op);
      break;
  }
}

template <typename T, typename ToF, typename FromF>
void combine_via_float(T* acc, const T* in, int64_t n, int op, ToF to_f,
                       FromF from_f) {
  for (int64_t i = 0; i < n; i++) {
    float a = to_f(acc[i]), b = to_f(in[i]);
    float r;
    switch (op) {
      case TPU_SUM: r = a + b; break;
      case TPU_PROD: r = a * b; break;
      case TPU_MAX: r = a < b ? b : a; break;
      case TPU_MIN: r = b < a ? b : a; break;
      default: r = a; break;
    }
    acc[i] = from_f(r);
  }
}

void combine_complex(float* acc, const float* in, int64_t n, int op) {
  /* n complex elements, interleaved re/im */
  for (int64_t i = 0; i < n; i++) {
    float ar = acc[2 * i], ai = acc[2 * i + 1];
    float br = in[2 * i], bi = in[2 * i + 1];
    if (op == TPU_SUM) {
      acc[2 * i] = ar + br;
      acc[2 * i + 1] = ai + bi;
    } else { /* PROD */
      acc[2 * i] = ar * br - ai * bi;
      acc[2 * i + 1] = ar * bi + ai * br;
    }
  }
}

void combine_complex_d(double* acc, const double* in, int64_t n, int op) {
  for (int64_t i = 0; i < n; i++) {
    double ar = acc[2 * i], ai = acc[2 * i + 1];
    double br = in[2 * i], bi = in[2 * i + 1];
    if (op == TPU_SUM) {
      acc[2 * i] = ar + br;
      acc[2 * i + 1] = ai + bi;
    } else {
      acc[2 * i] = ar * br - ai * bi;
      acc[2 * i + 1] = ar * bi + ai * br;
    }
  }
}

int combine(void* acc, const void* in, int64_t count, int dtype, int op,
            Comm* c) {
  switch (dtype) {
    case TPU_BOOL:
    case TPU_U8:
      combine_integer((uint8_t*)acc, (const uint8_t*)in, count, op);
      return 0;
    case TPU_I8:
      combine_integer((int8_t*)acc, (const int8_t*)in, count, op);
      return 0;
    case TPU_I16:
      combine_integer((int16_t*)acc, (const int16_t*)in, count, op);
      return 0;
    case TPU_I32:
      combine_integer((int32_t*)acc, (const int32_t*)in, count, op);
      return 0;
    case TPU_I64:
      combine_integer((int64_t*)acc, (const int64_t*)in, count, op);
      return 0;
    case TPU_U16:
      combine_integer((uint16_t*)acc, (const uint16_t*)in, count, op);
      return 0;
    case TPU_U32:
      combine_integer((uint32_t*)acc, (const uint32_t*)in, count, op);
      return 0;
    case TPU_U64:
      combine_integer((uint64_t*)acc, (const uint64_t*)in, count, op);
      return 0;
    case TPU_F16:
      combine_via_float((uint16_t*)acc, (const uint16_t*)in, count, op,
                        f16_to_f32, f32_to_f16);
      return 0;
    case TPU_BF16:
      combine_via_float((uint16_t*)acc, (const uint16_t*)in, count, op,
                        bf16_to_f32, f32_to_bf16);
      return 0;
    case TPU_F32:
      combine_typed((float*)acc, (const float*)in, count, op);
      return 0;
    case TPU_F64:
      combine_typed((double*)acc, (const double*)in, count, op);
      return 0;
    case TPU_C64:
      if (op != TPU_SUM && op != TPU_PROD)
        FAIL(c, "op %d not defined for complex dtype", op);
      combine_complex((float*)acc, (const float*)in, count, op);
      return 0;
    case TPU_C128:
      if (op != TPU_SUM && op != TPU_PROD)
        FAIL(c, "op %d not defined for complex dtype", op);
      combine_complex_d((double*)acc, (const double*)in, count, op);
      return 0;
    default:
      FAIL(c, "unknown dtype code %d", dtype);
  }
}

int64_t dtype_size(int dtype) {
  switch (dtype) {
    case TPU_BOOL: case TPU_I8: case TPU_U8: return 1;
    case TPU_I16: case TPU_U16: case TPU_F16: case TPU_BF16: return 2;
    case TPU_I32: case TPU_U32: case TPU_F32: return 4;
    case TPU_I64: case TPU_U64: case TPU_F64: case TPU_C64: return 8;
    case TPU_C128: return 16;
    default: return 0;
  }
}

/* ================= same-host shared-memory arena =================
 *
 * When every member of a communicator lives on one host (the common
 * case for the np=N loopback jobs this replaces libmpi's sm BTL for),
 * collectives run through a POSIX shared-memory arena instead of the
 * TCP loopback stack: one slot per rank plus a result region, fenced
 * by a sense-reversing futex barrier (~14 us for 8 ranks on this
 * host's single core, measured).  Point-to-point stays on TCP — its
 * ordered-stream matching semantics are the product contract, and the
 * collectives are where the serial-hop latency and double-copy cost
 * lived (VERDICT r3 weak #3: 1 KB allreduce 6.4 ms, 16 MB at
 * 0.137 GB/s/rank over TCP loopback).
 *
 * Protocol per collective (all ops use exactly two barriers):
 *   write phase  -> publish opword + B1 -> verify -> read/reduce
 *   phase -> B2 -> (allreduce/reduce: copy result out, protected from
 *   overwrite by the *next* op's B1, which no rank can pass before
 *   every rank finished its copy-out and re-entered).
 * Region discipline behind that protection: slot reads all happen
 *   between B1 and B2, so a rank may write its OWN slot before B1; but
 *   result() reads extend PAST B2 (the large-allreduce copy-out), so
 *   nothing may write result() before B1 — every op that publishes
 *   data pre-B1 (bcast, scatter) stages it through slot(root), and
 *   result() is written only between B1 and B2 (the cooperative
 *   reduce).  A pre-B1 result() write can silently corrupt a slower
 *   rank's allreduce copy-out (ADVICE r4 high).
 * The opword (opcode | root | dtype | reduce-op | byte-count per
 * rank, one cacheline each) turns cross-rank collective-order — or
 * type/op — divergence into a fail-fast diagnostic instead of silent
 * corruption: the shm analog of the TCP frames' comm-id/tag order
 * checking.  Equal byte counts with different dtypes (f32 vs i32) or
 * different reduce ops (SUM vs MAX) are caught too.
 *
 * Large allreduce is cooperative: after B1 each rank reduces its
 * 64-byte-aligned chunk of the message across all slots (AVX2 8-wide
 * vertical sum for the hot f32/SUM case, generic combine() otherwise)
 * into the result region, so every byte is reduced exactly once and
 * every rank reads back bitwise-identical results.  Small messages
 * (<= 64 KB) skip the result indirection: each rank redundantly
 * reduces all slots straight into its private output (same slot
 * order, so still bitwise-identical across ranks).
 *
 * Stale-segment safety: the creator (comm rank 0) writes a random
 * nonce into the header and broadcasts it over the already-connected
 * TCP mesh; attachers reject any segment whose nonce mismatches, so a
 * crashed job's leftover /dev/shm file with the same name can never
 * be adopted.  The creator unlinks the name once every rank has
 * attached.  Env knobs: MPI4JAX_TPU_DISABLE_SHM=1 forces TCP-only
 * (CI exercises both paths), MPI4JAX_TPU_SHM_MB sizes the slots
 * (default 32; bigger messages are processed in slot-sized pieces),
 * MPI4JAX_TPU_SHM_TIMEOUT_S bounds barrier waits (default 180),
 * MPI4JAX_TPU_JOBID uniquifies segment names (the launcher sets a
 * uuid; bare env-var jobs fall back to the coord port). */

struct ShmHdr {
  uint64_t magic;  // set LAST by the creator
  uint64_t nonce;  // fresh per creation; attachers verify via TCP bcast
  int32_t nranks;
  int64_t slot_bytes;
  std::atomic<int32_t> attached;
  std::atomic<int32_t> bar_count;
  std::atomic<int32_t> bar_sense;  // futex word
};

constexpr uint64_t kShmMagic = 0x6d34416a73686d31ull;
constexpr int64_t kOpwordStride = 64;  // one cacheline per rank
constexpr int64_t kShmSmallBytes = 64 * 1024;

/* Per-directed-pair SPSC ring for same-host point-to-point (r5).  One
 * producer (src rank) and one consumer (dst rank); head/tail are byte
 * cursors that only ever grow.  The futex seq words let either side
 * park when the ring is full/empty without burning the shared core. */
struct RingHdr {
  /* producer-written and consumer-written fields live on separate
   * cachelines: both sides store on every op, and sharing a line would
   * ping-pong it between cores on exactly the latency path the rings
   * exist to shorten */
  alignas(64) std::atomic<uint64_t> head;  // bytes produced (src writes)
  std::atomic<int32_t> hseq;               // bumped per publish (futex)
  alignas(64) std::atomic<uint64_t> tail;  // bytes consumed (dst writes)
  std::atomic<int32_t> tseq;               // bumped per consume (futex)
};
static_assert(sizeof(RingHdr) <= 128, "RingHdr must fit kRingHdrBytes");

/* Frame inside a ring: header then payload, padded to 16 bytes.  A
 * kRingStub frame carries no ring payload — the message body follows on
 * the TCP socket (large sends keep the writer-thread progress
 * guarantee); the ring remains the (comm, src->dst) ordering spine. */
struct RingFrame {
  int32_t tag;
  int32_t flags;    // kRingStub
  int64_t nbytes;   // payload size (actual, even for stubs)
};
constexpr int32_t kRingStub = 1;
constexpr int64_t kRingHdrBytes = 128;  // RingHdr, cacheline-padded

int64_t ring_round(int64_t n) { return (n + 15) & ~int64_t(15); }

/* Peer-death detection for the shm wait loops: the TCP recv path gets
 * EOF for free when a peer dies; a futex wait on a shared ring does
 * not.  The mesh socket to the peer doubles as a liveness probe (clean
 * exit -> EOF, crash -> RST), checked only on the slow (parked) path.
 * A socket holding undelivered data is alive, not dead.  With the link
 * layer armed, a dead SOCKET is not a dead PEER until the link state
 * machine says so (a transient reset heals on the next op): only
 * LINK_DEAD — budget exhausted or replay infeasible — reports death. */
bool peer_socket_dead(Comm* c, int r) {
  const bool armed = retry_armed() && !c->root_rank.empty();
  int fd = r >= 0 && r < (int)c->socks.size()
               ? (armed ? link_fd(c, r) : c->socks[r])
               : -1;
  if (armed) {
    LinkState* L = link_state(c, r);
    if (L && L->phase.load(std::memory_order_relaxed) == LINK_DEAD)
      return true;
  }
  if (fd < 0) return false;  // self or never-connected: no evidence
  char b[sizeof(MsgHeader)];
  ssize_t p = ::recv(fd, b, sizeof(b), MSG_PEEK | MSG_DONTWAIT);
  if (p == 0) return !armed;
  if (p < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
    return !armed;
  if (p == (ssize_t)sizeof(MsgHeader)) {
    /* a poison control frame means the peer is aborting the job: treat
     * it as dead so shm waiters tear down within one probe interval.
     * (The armed 32-byte header embeds MsgHeader as a prefix, so this
     * 16-byte peek parses the same frame either way.) */
    MsgHeader h{};
    std::memcpy(&h, b, sizeof(h));
    if (h.tag == kPoisonTag) return true;
  }
  return false;
}

struct ShmArena {
  char* base = nullptr;
  size_t map_len = 0;
  int64_t slot_bytes = 0;
  int64_t ring_bytes = 0;
  int nranks = 0;

  ShmHdr* hdr() { return reinterpret_cast<ShmHdr*>(base); }
  std::atomic<uint64_t>* opword(int r) {
    return reinterpret_cast<std::atomic<uint64_t>*>(
        base + 4096 + (int64_t)r * kOpwordStride);
  }
  char* result() { return base + 4096 + (int64_t)nranks * kOpwordStride; }
  char* slot(int r) {
    return result() + slot_bytes + (int64_t)r * slot_bytes;
  }
  /* ring region sits after the slots; one block per directed pair
   * (src, dst), diagonal unused (self goes through self_q) */
  char* ring_base() {
    return result() + (int64_t)(nranks + 1) * slot_bytes;
  }
  RingHdr* ring_hdr(int src, int dst) {
    return reinterpret_cast<RingHdr*>(
        ring_base() +
        ((int64_t)src * nranks + dst) * (kRingHdrBytes + ring_bytes));
  }
  char* ring_data(int src, int dst) {
    return reinterpret_cast<char*>(ring_hdr(src, dst)) + kRingHdrBytes;
  }
  static size_t total_bytes(int nranks, int64_t slot_bytes,
                            int64_t ring_bytes) {
    return 4096 + (size_t)nranks * kOpwordStride +
           (size_t)(nranks + 1) * slot_bytes +
           (size_t)nranks * nranks * (kRingHdrBytes + ring_bytes);
  }
};

void arena_destroy(ShmArena* a) {
  if (a->base) ::munmap(a->base, a->map_len);
  delete a;
}

double shm_timeout_s() {
  const char* e = std::getenv("MPI4JAX_TPU_SHM_TIMEOUT_S");
  double v = e && e[0] ? std::atof(e) : 180.0;
  if (v <= 0) v = 180.0;
  /* the job-wide transport deadline caps shm waits too, so one knob
   * bounds every blocking wait regardless of the path a message rides */
  double t = transport_timeout_s();
  if (t > 0 && t < v) v = t;
  return v;
}

/* Non-temporal streaming copy: bypasses the cache and skips the
 * read-for-ownership a normal store pays, ~3x memcpy for the big
 * arena transfers on this host (9.1 vs 3.1 GB/s measured).  SSE2 is
 * baseline on x86_64.  Ends with sfence so the weakly-ordered stores
 * are globally visible before any following barrier arithmetic. */
void nt_memcpy(void* dst, const void* src, int64_t n) {
  char* d = static_cast<char*>(dst);
  const char* s = static_cast<const char*>(src);
  int64_t head = (16 - ((uintptr_t)d & 15)) & 15;
  if (head > n) head = n;
  if (head) {
    std::memcpy(d, s, head);
    d += head;
    s += head;
    n -= head;
  }
  int64_t i = 0;
  for (; i + 64 <= n; i += 64) {
    __m128i a = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i));
    __m128i b = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 16));
    __m128i e = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 32));
    __m128i f = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 48));
    _mm_stream_si128(reinterpret_cast<__m128i*>(d + i), a);
    _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 16), b);
    _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 32), e);
    _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 48), f);
  }
  if (i < n) std::memcpy(d + i, s + i, n - i);
  _mm_sfence();
}

__attribute__((target("avx2"))) void sum_f32_avx2(float* out,
                                                  const float* const* src,
                                                  int ns, int64_t n) {
  bool aligned = ((uintptr_t)out & 31) == 0;
  int64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 acc = _mm256_loadu_ps(src[0] + i);
    for (int s = 1; s < ns; s++)
      acc = _mm256_add_ps(acc, _mm256_loadu_ps(src[s] + i));
    if (aligned)
      _mm256_stream_ps(out + i, acc);
    else
      _mm256_storeu_ps(out + i, acc);
  }
  for (; i < n; i++) {
    float acc = src[0][i];
    for (int s = 1; s < ns; s++) acc += src[s][i];
    out[i] = acc;
  }
  _mm_sfence();
}

bool have_avx2() {
  static bool v = __builtin_cpu_supports("avx2");
  return v;
}

/* Reduce the same [0, count) element range of ns source buffers into
 * out, combining in source order (deterministic; identical on every
 * rank that runs it with the same sources). */
int vertical_reduce(Comm* c, void* out, const char* const* srcs, int ns,
                    int64_t count, int dtype, int op) {
  if (dtype == TPU_F32 && op == TPU_SUM && have_avx2()) {
    sum_f32_avx2(static_cast<float*>(out),
                 reinterpret_cast<const float* const*>(srcs), ns, count);
    return 0;
  }
  int64_t nb = count * dtype_size(dtype);
  std::memcpy(out, srcs[0], nb);
  for (int s = 1; s < ns; s++)
    if (combine(out, srcs[s], count, dtype, op, c)) return 1;
  return 0;
}

int shm_futex_wait(std::atomic<int32_t>* addr, int32_t expected,
                   int timeout_ms) {
  timespec ts{timeout_ms / 1000, (timeout_ms % 1000) * 1000000L};
  return syscall(SYS_futex, reinterpret_cast<int32_t*>(addr), FUTEX_WAIT,
                 expected, &ts, nullptr, 0);
}

void shm_futex_wake_all(std::atomic<int32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<int32_t*>(addr), FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
}

int shm_barrier(Comm* c) {
  ObsWaitTimer wt;  // barrier rendezvous is pure wait (straggler skew)
  ShmHdr* h = c->arena->hdr();
  _mm_sfence();  // drain NT stores before signaling arrival
  int32_t sense = h->bar_sense.load(std::memory_order_acquire);
  if (h->bar_count.fetch_add(1, std::memory_order_acq_rel) ==
      c->arena->nranks - 1) {
    h->bar_count.store(0, std::memory_order_relaxed);
    h->bar_sense.store(1 - sense, std::memory_order_release);
    shm_futex_wake_all(&h->bar_sense);
    return 0;
  }
  double deadline = now_s() + shm_timeout_s();
  int spins = 0;
  while (h->bar_sense.load(std::memory_order_acquire) == sense) {
    /* few yields, then futex: on hosts where ranks share cores (this
     * one exposes a single core for 8 ranks) long yield loops just
     * churn the run queue — 4 was the measured sweet spot */
    if (spins < 4) {
      spins++;
      ::sched_yield();
      continue;
    }
    shm_futex_wait(&h->bar_sense, sense, 100);
    if (h->bar_sense.load(std::memory_order_acquire) != sense) break;
    for (int r = 0; r < c->size; r++)
      if (r != c->rank && peer_socket_dead(c, r)) {
        /* TOCTOU: the last arriver may have flipped the sense and
         * exited between our sense load and the death probe */
        if (h->bar_sense.load(std::memory_order_acquire) != sense) break;
        FAIL(c, "shm barrier: rank %d exited while this rank waits — "
             "the ranks disagree on the collective schedule", r);
      }
    if (now_s() > deadline)
      FAIL(c,
           "shm barrier timed out after %.0f s — a peer died or the ranks "
           "disagree on the collective schedule (set "
           "MPI4JAX_TPU_SHM_TIMEOUT_S to adjust)",
           shm_timeout_s());
  }
  return 0;
}

/* ================= shm point-to-point rings =================
 *
 * Same-host send/recv/sendrecv (and shift2, which rides them) go
 * through per-directed-pair SPSC rings in the arena instead of the TCP
 * loopback stack (VERDICT r4 #3: np2 sendrecv 1 KB was 27.5 us over
 * TCP while the arena showed ~16 us two-barrier round trips).
 *
 * Contract preserved exactly:
 * - ordered-stream matching per (comm, src->dst): the ring IS the
 *   stream; the head frame must match the expected tag or fail fast
 *   (same "message order violation" diagnostic as the TCP frames);
 * - sends never block on a missing receiver: a frame that doesn't fit
 *   the ring's free space degrades to a kRingStub in the ring (the
 *   ordering spine) with the payload riding the existing TCP
 *   eager/writer-thread path — the progress guarantee the writer
 *   thread gives TCP large sends carries over unchanged;
 * - ANY_SOURCE polls every inbound ring head (self-queue first), and a
 *   head that cannot match is dropped from the candidate set, exactly
 *   like the TCP poll;
 * - collective-protocol traffic never enters the rings (arena comms
 *   run collectives through the barrier protocol above).
 *
 * Knobs: MPI4JAX_TPU_SHM_RING_KB sizes each ring (default 1024;
 * inline cutoff is ring/4), MPI4JAX_TPU_DISABLE_SHM_P2P=1 keeps p2p
 * on TCP while collectives stay on the arena (CI axis; must agree
 * across ranks, like the other shm knobs). */

int ring_wait_space(Comm* c, int dest, RingHdr* rh, int64_t ring_bytes,
                    int64_t need) {
  ObsWaitTimer wt;  // blocked on the consumer draining the ring
  double deadline = now_s() + shm_timeout_s();
  int spins = 0;
  for (;;) {
    uint64_t used = rh->head.load(std::memory_order_relaxed) -
                    rh->tail.load(std::memory_order_acquire);
    if ((int64_t)(ring_bytes - used) >= need) return 0;
    if (spins < 4) {
      spins++;
      ::sched_yield();
      continue;
    }
    int32_t seq = rh->tseq.load(std::memory_order_acquire);
    uint64_t used2 = rh->head.load(std::memory_order_relaxed) -
                     rh->tail.load(std::memory_order_acquire);
    if ((int64_t)(ring_bytes - used2) >= need) return 0;
    shm_futex_wait(&rh->tseq, seq, 50);
    if (peer_socket_dead(c, dest))
      FAIL(c, "send to rank %d failed: peer exited with its inbound "
           "ring full", dest);
    if (now_s() > deadline)
      FAIL(c,
           "shm p2p ring full for %.0f s — the peer stopped receiving "
           "(died, or the ranks disagree on the message schedule)",
           shm_timeout_s());
  }
}

void ring_copy_in(char* data, int64_t ring_bytes, uint64_t at,
                  const void* src, int64_t n) {
  int64_t off = (int64_t)(at % (uint64_t)ring_bytes);
  int64_t first = std::min(n, ring_bytes - off);
  std::memcpy(data + off, src, first);
  if (n > first) std::memcpy(data, (const char*)src + first, n - first);
}

void ring_copy_out(const char* data, int64_t ring_bytes, uint64_t at,
                   void* dst, int64_t n) {
  int64_t off = (int64_t)(at % (uint64_t)ring_bytes);
  int64_t first = std::min(n, ring_bytes - off);
  std::memcpy(dst, data + off, first);
  if (n > first) std::memcpy((char*)dst + first, data, n - first);
}

/* Push one frame (inline payload or stub).  Space for the 16-byte
 * header is always waited for (a full ring of stubs means 64Ki
 * outstanding unreceived messages — schedule bug, surfaced by the
 * timeout); inline callers check free space first and degrade to a
 * stub instead of waiting. */
int ring_push(Comm* c, int dst, int32_t tag, int32_t flags,
              const void* buf, int64_t nbytes) {
  ShmArena* a = c->arena;
  RingHdr* rh = a->ring_hdr(c->rank, dst);
  char* data = a->ring_data(c->rank, dst);
  int64_t payload = (flags & kRingStub) ? 0 : ring_round(nbytes);
  int64_t need = (int64_t)sizeof(RingFrame) + payload;
  if (ring_wait_space(c, dst, rh, a->ring_bytes, need)) return 1;
  uint64_t head = rh->head.load(std::memory_order_relaxed);
  RingFrame f{tag, flags, nbytes};
  ring_copy_in(data, a->ring_bytes, head, &f, sizeof(f));
  if (payload)
    ring_copy_in(data, a->ring_bytes, head + sizeof(RingFrame), buf, nbytes);
  rh->head.store(head + need, std::memory_order_release);
  rh->hseq.fetch_add(1, std::memory_order_release);
  shm_futex_wake_all(&rh->hseq);
  return 0;
}

/* Block until the (src -> me) ring holds a frame; peek it into *out. */
int ring_wait_frame(Comm* c, int src, RingFrame* out) {
  ObsWaitTimer wt;  // frame arrival = wait phase (shm twin of the
                    // TCP header read)
  ShmArena* a = c->arena;
  RingHdr* rh = a->ring_hdr(src, c->rank);
  double deadline = now_s() + shm_timeout_s();
  int spins = 0;
  for (;;) {
    uint64_t tail = rh->tail.load(std::memory_order_relaxed);
    if (rh->head.load(std::memory_order_acquire) != tail) {
      ring_copy_out(a->ring_data(src, c->rank), a->ring_bytes, tail, out,
                    sizeof(*out));
      return 0;
    }
    if (spins < 4) {
      spins++;
      ::sched_yield();
      continue;
    }
    int32_t seq = rh->hseq.load(std::memory_order_acquire);
    if (rh->head.load(std::memory_order_acquire) !=
        rh->tail.load(std::memory_order_relaxed))
      continue;
    shm_futex_wait(&rh->hseq, seq, 50);
    if (rh->head.load(std::memory_order_acquire) !=
        rh->tail.load(std::memory_order_relaxed))
      continue;  // drain whatever arrived, even from a now-dead peer
    if (peer_socket_dead(c, src)) {
      /* TOCTOU: the peer's last act may have been push-then-exit
       * between our emptiness load and the death probe — recheck */
      if (rh->head.load(std::memory_order_acquire) !=
          rh->tail.load(std::memory_order_relaxed))
        continue;
      FAIL(c, "recv from rank %d failed: peer exited with no matching "
           "send pending", src);
    }
    if (now_s() > deadline)
      FAIL(c,
           "shm p2p recv from rank %d timed out after %.0f s — no "
           "matching send arrived (peer died or schedule mismatch)",
           src, shm_timeout_s());
  }
}

/* Consume the head frame after its payload (if inline) is copied out. */
void ring_consume(Comm* c, int src, const RingFrame& f) {
  ShmArena* a = c->arena;
  RingHdr* rh = a->ring_hdr(src, c->rank);
  int64_t payload = (f.flags & kRingStub) ? 0 : ring_round(f.nbytes);
  rh->tail.fetch_add((int64_t)sizeof(RingFrame) + payload,
                     std::memory_order_release);
  rh->tseq.fetch_add(1, std::memory_order_release);
  shm_futex_wake_all(&rh->tseq);
}

bool ring_p2p_on(const Comm* c) {
  return c->arena != nullptr && c->arena->ring_bytes > 0;
}

/* ANY_SOURCE over the rings: first peer whose HEAD frame matches the
 * tag filter wins; a non-matching head disqualifies that peer (its
 * stream can never satisfy this wildcard), mirroring poll_any_source. */
int ring_poll_any(Comm* c, int tag, int* out_source) {
  std::vector<int> cands;
  for (int r = 0; r < c->size; r++)
    if (r != c->rank) cands.push_back(r);
  double deadline = now_s() + shm_timeout_s();
  for (;;) {
    for (size_t i = 0; i < cands.size();) {
      int r = cands[i];
      RingHdr* rh = c->arena->ring_hdr(r, c->rank);
      uint64_t tail = rh->tail.load(std::memory_order_relaxed);
      if (rh->head.load(std::memory_order_acquire) != tail) {
        RingFrame f{};
        ring_copy_out(c->arena->ring_data(r, c->rank), c->arena->ring_bytes,
                      tail, &f, sizeof(f));
        if (tag == kAnyTag || f.tag == tag) {
          *out_source = r;
          return 0;
        }
        cands.erase(cands.begin() + i);  // head can never match
        continue;
      }
      i++;
    }
    if (cands.empty())
      FAIL(c, "ANY_SOURCE recv: no peer can deliver a matching message "
           "(all ring heads mismatched or peers exited)");
    ::sched_yield();
    for (size_t i = 0; i < cands.size();) {
      RingHdr* rh = c->arena->ring_hdr(cands[i], c->rank);
      bool empty = rh->head.load(std::memory_order_acquire) ==
                   rh->tail.load(std::memory_order_relaxed);
      if (empty && peer_socket_dead(c, cands[i]) &&
          /* TOCTOU: push-then-exit between the loads — recheck */
          rh->head.load(std::memory_order_acquire) ==
              rh->tail.load(std::memory_order_relaxed))
        cands.erase(cands.begin() + i);
      else
        i++;
    }
    if (now_s() > deadline)
      FAIL(c, "ANY_SOURCE recv timed out after %.0f s on the shm rings",
           shm_timeout_s());
  }
}

int shm_try_send(Comm* c, int dest, int tag, const void* buf,
                 int64_t nbytes, bool* inlined) {
  /* a send that rides the shm rings never reaches send_msg_tcp, so the
   * injector needs its own hook here (point=send counts transmissions:
   * a stub-degraded send also pays the TCP-payload count).  When the
   * link layer is armed, target the peer's TCP link precisely — shm
   * traffic itself cannot be reset, so the fault lands on the idle
   * socket underneath and heartbeats (or the next stub payload) find
   * it */
  fault_fire(c, g_job_rank, FP_SEND, "send",
             retry_armed() ? link_fd(c, dest) : -1);
  ShmArena* a = c->arena;
  RingHdr* rh = a->ring_hdr(c->rank, dest);
  int64_t need = (int64_t)sizeof(RingFrame) + ring_round(nbytes);
  uint64_t used = rh->head.load(std::memory_order_relaxed) -
                  rh->tail.load(std::memory_order_acquire);
  if (nbytes <= a->ring_bytes / 4 &&
      (int64_t)(a->ring_bytes - used) >= need) {
    *inlined = true;
    return ring_push(c, dest, tag, 0, buf, nbytes);
  }
  /* too big, or no room right now: order rides a stub; payload rides
   * the TCP eager/writer path so the send still cannot block on a
   * missing receiver */
  *inlined = false;
  return ring_push(c, dest, tag, kRingStub, nullptr, nbytes);
}

int shm_recv_status(Comm* c, int source, int tag, void* buf,
                    int64_t nbytes, int32_t* out_src, int32_t* out_tag,
                    int64_t* out_count) {
  ShmArena* a = c->arena;
  RingFrame f{};
  if (ring_wait_frame(c, source, &f)) return 1;
  if (tag != kAnyTag && f.tag != tag)
    FAIL(c, "message order violation: expected tag %d from rank %d, got %d",
         tag, source, f.tag);
  if (f.nbytes > nbytes)
    FAIL(c, "message truncated: rank %d sent %lld bytes into a %lld-byte "
         "buffer", source, (long long)f.nbytes, (long long)nbytes);
  if (f.flags & kRingStub) {
    /* payload is the next TCP frame from this peer; the usual header
       checks keep cross-communicator socket order honest */
    for (;;) {
      MsgHeader h{};
      uint64_t seq = 0;
      int ffd = -1;
      int rc = wire_read_hdr(c, source, &h, &seq, &ffd);
      if (rc) {
        if (io_rc_retryable(rc) &&
            link_recover(c, source, ffd, "recv stub payload header") == 0)
          continue;
        FAIL_IO(c, rc, "recv header from %d", source);
      }
      if (h.tag == kPoisonTag) return poison_fail(c, source, h);
      if (h.comm_id != c->comm_id)
        FAIL(c, "communicator mismatch: rank %d's message is for comm %d, "
             "this is comm %d — ops on sibling communicators must run in a "
             "consistent order on both endpoints", source, h.comm_id,
             c->comm_id);
      if (h.tag != f.tag || h.nbytes != f.nbytes)
        FAIL(c, "shm stub/TCP frame mismatch from rank %d (tag %d/%d, "
             "bytes %lld/%lld)", source, f.tag, h.tag, (long long)f.nbytes,
             (long long)h.nbytes);
      rc = read_all_dl(ffd, buf, h.nbytes);
      if (rc) {
        if (io_rc_retryable(rc) &&
            link_recover(c, source, ffd, "recv stub payload") == 0)
          continue;
        FAIL_IO(c, rc, "recv payload from %d", source);
      }
      wire_mark_delivered(c, source, seq);
      break;
    }
  } else {
    RingHdr* rh = a->ring_hdr(source, c->rank);
    uint64_t tail = rh->tail.load(std::memory_order_relaxed);
    ring_copy_out(a->ring_data(source, c->rank), a->ring_bytes,
                  tail + sizeof(RingFrame), buf, f.nbytes);
  }
  ring_consume(c, source, f);
  if (out_src) *out_src = source;
  if (out_tag) *out_tag = f.tag;
  if (out_count) *out_count = f.nbytes;
  return 0;
}

/* opword layout: opcode byte | root byte | dtype byte | reduce-op byte
 * | 32 bits of per-rank piece bytes (pieces are <= slot_bytes, far
 * below 4 GB).  dtype/op are 0 for ops they don't apply to. */
uint64_t shm_opword(int opcode, int root, int dtype, int op,
                    int64_t nbytes) {
  return ((uint64_t)(uint8_t)opcode << 56) | ((uint64_t)(uint8_t)root << 48) |
         ((uint64_t)(uint8_t)dtype << 40) | ((uint64_t)(uint8_t)op << 32) |
         ((uint64_t)nbytes & 0xffffffffull);
}

enum ShmOpcode {
  SHM_ALLREDUCE = 1, SHM_REDUCE, SHM_SCAN, SHM_BCAST, SHM_BARRIER,
  SHM_ALLGATHER, SHM_GATHER, SHM_SCATTER, SHM_ALLTOALL,
};

/* B1 with the cross-rank schedule check (see section comment). */
int shm_publish_and_check(Comm* c, uint64_t word) {
  ShmArena* a = c->arena;
  a->opword(c->rank)->store(word, std::memory_order_release);
  if (shm_barrier(c)) return 1;
  for (int r = 0; r < a->nranks; r++) {
    uint64_t w = a->opword(r)->load(std::memory_order_acquire);
    if (w != word)
      FAIL(c,
           "collective schedule mismatch: rank %d published op 0x%llx, this "
           "rank op 0x%llx — every member must issue collectives on a "
           "communicator in the same order",
           r, (unsigned long long)w, (unsigned long long)word);
  }
  return 0;
}

int shm_allreduce_like(Comm* c, const void* sendbuf, void* recvbuf,
                       int64_t count, int dtype, int op, int root,
                       bool all_ranks_out) {
  ShmArena* a = c->arena;
  const int64_t esize = dtype_size(dtype);
  const int64_t total = count * esize;
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  const int opcode = all_ranks_out ? SHM_ALLREDUCE : SHM_REDUCE;
  std::vector<const char*> srcs(a->nranks);
  int64_t off = 0;
  do {
    int64_t nb = std::min(total - off, a->slot_bytes);
    int64_t cnt = nb / esize;
    nt_memcpy(a->slot(c->rank), in + off, nb);
    if (shm_publish_and_check(c, shm_opword(opcode, root, dtype, op, nb)))
      return 1;
    for (int r = 0; r < a->nranks; r++) srcs[r] = a->slot(r);
    if (nb <= kShmSmallBytes) {
      /* every interested rank reduces all slots straight into its out */
      if (all_ranks_out || c->rank == root) {
        if (vertical_reduce(c, out + off, srcs.data(), a->nranks, cnt, dtype,
                            op))
          return 1;
      }
      if (shm_barrier(c)) return 1;
    } else {
      /* cooperative: this rank owns a 64-byte-aligned chunk */
      int64_t per = (((nb + a->nranks - 1) / a->nranks) + 63) & ~int64_t(63);
      int64_t lo = std::min(per * c->rank, nb);
      int64_t hi = std::min(lo + per, nb);
      if (hi > lo) {
        std::vector<const char*> chunk(a->nranks);
        for (int r = 0; r < a->nranks; r++) chunk[r] = srcs[r] + lo;
        if (vertical_reduce(c, a->result() + lo, chunk.data(), a->nranks,
                            (hi - lo) / esize, dtype, op))
          return 1;
      }
      if (shm_barrier(c)) return 1;
      if (all_ranks_out || c->rank == root)
        nt_memcpy(out + off, a->result(), nb);
    }
    off += nb;
  } while (off < total);
  return 0;
}

int shm_scan(Comm* c, const void* sendbuf, void* recvbuf, int64_t count,
             int dtype, int op) {
  ShmArena* a = c->arena;
  const int64_t esize = dtype_size(dtype);
  const int64_t total = count * esize;
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  std::vector<const char*> srcs(a->nranks);
  int64_t off = 0;
  do {
    int64_t nb = std::min(total - off, a->slot_bytes);
    nt_memcpy(a->slot(c->rank), in + off, nb);
    if (shm_publish_and_check(c, shm_opword(SHM_SCAN, 0, dtype, op, nb)))
      return 1;
    for (int r = 0; r <= c->rank; r++) srcs[r] = a->slot(r);
    if (vertical_reduce(c, out + off, srcs.data(), c->rank + 1, nb / esize,
                        dtype, op))
      return 1;
    if (shm_barrier(c)) return 1;
    off += nb;
  } while (off < total);
  return 0;
}

int shm_bcast(Comm* c, void* buf, int64_t nbytes, int root) {
  ShmArena* a = c->arena;
  char* p = static_cast<char*>(buf);
  int64_t off = 0;
  do {
    int64_t nb = std::min(nbytes - off, a->slot_bytes);
    /* pre-B1 writes must target the writer's own slot, never result()
     * (a slow rank may still be copying a previous large allreduce out
     * of result() after its B2 — ADVICE r4 high) */
    if (c->rank == root) nt_memcpy(a->slot(root), p + off, nb);
    if (shm_publish_and_check(c, shm_opword(SHM_BCAST, root, 0, 0, nb)))
      return 1;
    if (c->rank != root) std::memcpy(p + off, a->slot(root), nb);
    if (shm_barrier(c)) return 1;
    off += nb;
  } while (off < nbytes);
  return 0;
}

int shm_allgather(Comm* c, const void* sendbuf, int64_t nbytes,
                  void* recvbuf, int root, bool all_ranks_out) {
  ShmArena* a = c->arena;
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  const int opcode = all_ranks_out ? SHM_ALLGATHER : SHM_GATHER;
  int64_t off = 0;
  do {
    int64_t nb = std::min(nbytes - off, a->slot_bytes);
    nt_memcpy(a->slot(c->rank), in + off, nb);
    if (shm_publish_and_check(c, shm_opword(opcode, root, 0, 0, nb)))
      return 1;
    if (all_ranks_out || c->rank == root)
      for (int r = 0; r < a->nranks; r++)
        std::memcpy(out + (int64_t)r * nbytes + off, a->slot(r), nb);
    if (shm_barrier(c)) return 1;
    off += nb;
  } while (off < nbytes);
  return 0;
}

int shm_scatter(Comm* c, const void* sendbuf, void* recvbuf, int64_t nbytes,
                int root) {
  ShmArena* a = c->arena;
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  /* per-piece budget: all nranks pieces must fit one slot */
  int64_t piece = std::max<int64_t>(
      64, (a->slot_bytes / a->nranks) & ~int64_t(63));
  int64_t off = 0;
  do {
    int64_t nb = std::min(nbytes - off, piece);
    /* staged through slot(root), not result(): see bcast note */
    if (c->rank == root)
      for (int r = 0; r < a->nranks; r++)
        nt_memcpy(a->slot(root) + (int64_t)r * nb,
                  in + (int64_t)r * nbytes + off, nb);
    if (shm_publish_and_check(c, shm_opword(SHM_SCATTER, root, 0, 0, nb)))
      return 1;
    std::memcpy(out + off, a->slot(root) + (int64_t)c->rank * nb, nb);
    if (shm_barrier(c)) return 1;
    off += nb;
  } while (off < nbytes);
  return 0;
}

int shm_alltoall(Comm* c, const void* sendbuf, void* recvbuf,
                 int64_t chunk) {
  ShmArena* a = c->arena;
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  int64_t piece = std::max<int64_t>(
      64, (a->slot_bytes / a->nranks) & ~int64_t(63));
  int64_t off = 0;
  do {
    int64_t nb = std::min(chunk - off, piece);
    for (int d = 0; d < a->nranks; d++)
      nt_memcpy(a->slot(c->rank) + (int64_t)d * nb,
                in + (int64_t)d * chunk + off, nb);
    if (shm_publish_and_check(c, shm_opword(SHM_ALLTOALL, 0, 0, 0, nb)))
      return 1;
    for (int s = 0; s < a->nranks; s++)
      std::memcpy(out + (int64_t)s * chunk + off,
                  a->slot(s) + (int64_t)c->rank * nb, nb);
    if (shm_barrier(c)) return 1;
    off += nb;
  } while (off < chunk);
  return 0;
}

int shm_barrier_op(Comm* c) {
  if (shm_publish_and_check(c, shm_opword(SHM_BARRIER, 0, 0, 0, 0))) return 1;
  return shm_barrier(c);
}

int bcast_internal(Comm* c, void* buf, int64_t nbytes, int root);

/* Create/attach the arena for comm c (all members must share this
 * host; collective over c's TCP mesh).  Failure is soft: the comm
 * simply stays on the TCP path.  Called before c is published. */
void arena_init(Comm* c) {
  if (c->size < 2) return;
  const char* dis = std::getenv("MPI4JAX_TPU_DISABLE_SHM");
  if (dis && dis[0] && dis[0] != '0') return;
  int64_t slot_mb = 32;
  if (const char* e = std::getenv("MPI4JAX_TPU_SHM_MB"))
    if (std::atoll(e) > 0) slot_mb = std::atoll(e);
  int64_t slot_bytes = ((slot_mb << 20) + 4095) & ~int64_t(4095);
  int64_t ring_kb = 1024;
  if (const char* e = std::getenv("MPI4JAX_TPU_SHM_RING_KB"))
    if (std::atoll(e) > 0) ring_kb = std::atoll(e);
  const char* p2p_dis = std::getenv("MPI4JAX_TPU_DISABLE_SHM_P2P");
  if (p2p_dis && p2p_dis[0] && p2p_dis[0] != '0') ring_kb = 0;
  int64_t ring_bytes = ring_kb << 10;
  size_t total = ShmArena::total_bytes(c->size, slot_bytes, ring_bytes);
  char name[128];
  std::snprintf(name, sizeof(name), "/%s_c%d", c->shm_prefix.c_str(),
                (int)c->comm_id);

  ShmArena* a = new ShmArena;
  a->slot_bytes = slot_bytes;
  a->ring_bytes = ring_bytes;
  a->nranks = c->size;
  uint64_t nonce = 0;
  if (c->rank == 0) {
    static thread_local std::mt19937_64 rng{std::random_device{}()};
    ::shm_unlink(name);
    int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd >= 0 && ::ftruncate(fd, (off_t)total) == 0) {
      void* base = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                          fd, 0);
      if (base != MAP_FAILED) {
#ifdef MADV_HUGEPAGE
        ::madvise(base, total, MADV_HUGEPAGE);  // fewer TLB misses on the
                                                // multi-MB streaming copies
#endif
        a->base = static_cast<char*>(base);
        a->map_len = total;
        ShmHdr* h = a->hdr();
        nonce = rng() | 1;  // nonzero
        h->nonce = nonce;
        h->nranks = c->size;
        h->slot_bytes = slot_bytes;
        h->attached.store(1, std::memory_order_relaxed);
        h->bar_count.store(0, std::memory_order_relaxed);
        h->bar_sense.store(0, std::memory_order_relaxed);
        std::atomic_thread_fence(std::memory_order_release);
        __atomic_store_n(&h->magic, kShmMagic, __ATOMIC_RELEASE);
      }
    }
    int saved_errno = errno;
    if (fd >= 0) ::close(fd);
    if (!a->base) {
      if (fd >= 0) ::shm_unlink(name);  // don't leak a half-created name
      std::fprintf(stderr,
                   "tpucomm r%d: shm arena creation failed (%s); collectives "
                   "stay on TCP\n",
                   c->rank, std::strerror(saved_errno));
      nonce = 0;
    }
  }
  /* creator tells everyone the nonce (0 = no arena, stay on TCP) */
  uint64_t wire = nonce;
  if (bcast_internal(c, &wire, sizeof(wire), 0) != 0) wire = 0;
  if (wire == 0) {
    if (a->base) {
      ::shm_unlink(name);
      ::munmap(a->base, a->map_len);
    }
    delete a;
    return;
  }
  nonce = wire;
  /* attach waits are bounded by 30 s, tightened by the job deadline */
  const double attach_wait_s = std::min(30.0, shm_timeout_s());
  if (c->rank != 0) {
    double deadline = now_s() + attach_wait_s;
    for (;;) {
      int fd = ::shm_open(name, O_RDWR, 0600);
      if (fd >= 0) {
        struct stat st{};
        if (::fstat(fd, &st) == 0 && (size_t)st.st_size == total) {
          void* base = ::mmap(nullptr, total, PROT_READ | PROT_WRITE,
                              MAP_SHARED, fd, 0);
          ::close(fd);
          if (base != MAP_FAILED) {
            ShmHdr* h = reinterpret_cast<ShmHdr*>(base);
            if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) == kShmMagic &&
                h->nonce == nonce) {
              a->base = static_cast<char*>(base);
              a->map_len = total;
              break;
            }
            ::munmap(base, total);
          }
        } else {
          ::close(fd);
        }
      }
      if (now_s() > deadline) {
        std::fprintf(stderr,
                     "tpucomm r%d: shm arena attach timed out; aborting "
                     "(creator succeeded, so this host is misconfigured)\n",
                     c->rank);
        delete a;
        std::exit(1);  // mixed shm/TCP members would deadlock: fail fast
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    a->hdr()->attached.fetch_add(1, std::memory_order_acq_rel);
  }
  /* everyone waits for full attachment, then the name disappears */
  double deadline = now_s() + attach_wait_s;
  while (a->hdr()->attached.load(std::memory_order_acquire) < c->size) {
    if (now_s() > deadline) {
      std::fprintf(stderr, "tpucomm r%d: shm arena attach wait timed out\n",
                   c->rank);
      std::exit(1);
    }
    ::sched_yield();
  }
  if (c->rank == 0) ::shm_unlink(name);
  c->arena = a;
}

int bcast_internal(Comm* c, void* buf, int64_t nbytes, int root) {
  /* binomial tree rooted at `root` (relative ranks) */
  int vrank = (c->rank - root + c->size) % c->size;
  int dist = 1;
  while (dist < c->size) dist *= 2;
  if (vrank != 0) {
    int lowbit = vrank & (-vrank);
    int parent = (vrank - lowbit + root) % c->size;
    if (recv_msg(c, parent, kCollectiveTag, buf, nbytes)) return 1;
  }
  int lowbit = vrank == 0 ? dist : (vrank & (-vrank));
  for (int step = lowbit / 2; step >= 1; step /= 2) {
    int vchild = vrank + step;
    if (vchild < c->size) {
      int child = (vchild + root) % c->size;
      if (send_msg(c, child, kCollectiveTag, buf, nbytes)) return 1;
    }
  }
  return 0;
}

/* ================= collective algorithm engine (TCP path) =================
 *
 * allreduce/allgather carry selectable schedules; selection is owned by
 * the Python tune package (mpi4jax_tpu/tune), which installs a per-op
 * (min_bytes -> algorithm) decision table here at communicator creation.
 * Per-call forcing rides the *_algo entry points.  All algorithms use
 * the same kCollectiveTag frames as the fixed schedules they replace,
 * so the ordered transport's divergence checks (tag/size/comm-id) keep
 * firing identically under every algorithm — a cross-rank disagreement
 * on the algorithm aborts at the first mismatched frame. */

struct CollTable {
  /* (min_bytes ascending, TpuCollAlgo); empty = built-in heuristic */
  std::vector<std::pair<int64_t, int32_t>> entries;
};
CollTable g_coll_table[3];  // indexed by TpuCollOpKind
std::mutex g_coll_table_mu;

/* Live re-tuning staging area (mpi4jax_tpu/live): candidate tables park
 * here without touching dispatch until every rank commits them at an
 * agreed collective boundary.  g_coll_epoch stamps the live table's
 * generation (0 = the offline-installed table). */
CollTable g_coll_staged[3];
bool g_coll_staged_set[3] = {false, false, false};
int64_t g_coll_epoch = 0;

int coll_table_lookup(int op_kind, int64_t nbytes) {
  std::lock_guard<std::mutex> lock(g_coll_table_mu);
  int algo = TPU_COLL_AUTO;
  for (const auto& e : g_coll_table[op_kind].entries) {
    if (nbytes >= e.first) algo = e.second;
  }
  return algo;
}

const char* coll_algo_name(int algo) {
  switch (algo) {
    case TPU_COLL_RING: return "ring";
    case TPU_COLL_RD: return "rd";
    case TPU_COLL_TREE: return "tree";
    case TPU_COLL_SHM: return "shm";
    case TPU_COLL_QRING: return "qring";
    case TPU_COLL_QRD: return "qrd";
    case TPU_COLL_HRING: return "hring";
    case TPU_COLL_HTREE: return "htree";
    case TPU_COLL_QA2A: return "qalltoall";
    case TPU_COLL_HA2A: return "halltoall";
    case TPU_COLL_HQA2A: return "hqalltoall";
    default: return "auto";
  }
}

/* MPI4JAX_TPU_HIER: process-wide gate over the hierarchical schedules.
 * allow (default) = table/env/API selection may pick hring/htree (and
 * large bcast/reduce route hierarchically) on a multi-island comm;
 * deny = every hierarchical pick degrades to its flat twin (a routing
 * kill-switch; frames still match because the degradation keys on the
 * installed topology, which agrees across ranks); force = every
 * eligible allreduce/allgather upgrades to a hierarchical twin and
 * bcast/reduce route hierarchically at any size.  Must agree across
 * ranks (like COLL_ALGO/COLL_QUANT: the schedules exchange different
 * frames). */
enum { HIER_ALLOW = 0, HIER_DENY = 1, HIER_FORCE = 2 };

int hier_mode() {
  static int v = [] {
    const char* e = std::getenv("MPI4JAX_TPU_HIER");
    if (!e) return HIER_ALLOW;
    std::string s(e);
    const size_t b = s.find_first_not_of(" \t\r\n");
    if (b == std::string::npos) return HIER_ALLOW;
    s = s.substr(b, s.find_last_not_of(" \t\r\n") - b + 1);
    if (s == "allow") return HIER_ALLOW;
    if (s == "deny") return HIER_DENY;
    if (s == "force") return HIER_FORCE;
    std::fprintf(stderr,
                 "tpucomm: cannot parse MPI4JAX_TPU_HIER=%s "
                 "(expected allow, deny, or force)\n", e);
    std::exit(2);  // a typo'd gate must not silently change routing
  }();
  return v;
}

/* bcast/reduce route hierarchically above this payload under
 * hier=allow (below it the flat binomial tree's log2(n) hops win on
 * latency); force removes the floor, deny the routing */
constexpr int64_t kHierMinBytes = 64 * 1024;

/* hierarchical schedules need a discovered multi-island topology */
bool hier_eligible(const Comm* c) {
  return c->topo != nullptr && c->topo->n_islands > 1;
}

bool hier_routable(const Comm* c, int64_t nbytes) {
  if (!hier_eligible(c) || hier_mode() == HIER_DENY) return false;
  return hier_mode() == HIER_FORCE || nbytes >= kHierMinBytes;
}

/* quantized wire formats (codec + schedules defined below) */
bool quant_dtype_ok(int dtype);
int64_t quant_packed_bytes(int64_t count);

/* MPI4JAX_TPU_COLL_QUANT: process-wide gate over the quantized wire
 * formats.  allow (default) = table/env/API selection may pick them;
 * deny = quantized picks degrade to their exact counterparts (a safety
 * kill-switch that never changes which frames match, only their
 * contents); force = every quant-eligible allreduce upgrades to the
 * quantized twin of its selected schedule.  Must agree across ranks
 * (like COLL_ALGO: a divergent gate fails fast on frame-size checks). */
enum { QUANT_ALLOW = 0, QUANT_DENY = 1, QUANT_FORCE = 2 };

int quant_mode() {
  static int v = [] {
    const char* e = std::getenv("MPI4JAX_TPU_COLL_QUANT");
    if (!e) return QUANT_ALLOW;
    /* trim surrounding whitespace (shell exports / YAML trailing
     * newlines) so this agrees byte-for-byte with the Python layers'
     * read of the same knob (utils/config.quant_mode) */
    std::string s(e);
    const size_t b = s.find_first_not_of(" \t\r\n");
    if (b == std::string::npos) return QUANT_ALLOW;
    s = s.substr(b, s.find_last_not_of(" \t\r\n") - b + 1);
    if (s == "allow") return QUANT_ALLOW;
    if (s == "deny") return QUANT_DENY;
    if (s == "force") return QUANT_FORCE;
    std::fprintf(stderr,
                 "tpucomm: cannot parse MPI4JAX_TPU_COLL_QUANT=%s "
                 "(expected allow, deny, or force)\n", e);
    std::exit(2);  // a typo'd gate must not silently change numerics
  }();
  return v;
}

/* The algorithm that will serve (op_kind, nbytes, count) on comm `c`.
 * `requested` = per-call force (AUTO -> table -> built-in heuristic).
 * Also applies legality fixups (allgather has no recursive-doubling
 * schedule for non-power-of-two sizes: falls back to ring; quantized
 * codes degrade to their exact counterparts unless the call is a
 * float SUM allreduce and MPI4JAX_TPU_COLL_QUANT permits), so callers
 * log the algorithm that actually runs.  `dtype`/`rop` carry the
 * reduction context for the quantized-eligibility gate; callers
 * without one (allgather, the byte-only probe) pass the defaults. */
int resolve_coll_algo(Comm* c, int op_kind, int64_t nbytes, int64_t count,
                      int requested, int dtype = -1, int rop = -1) {
  if (c->arena && c->size > 1) return TPU_COLL_SHM;
  int algo = requested;
  if (algo == TPU_COLL_AUTO) algo = coll_table_lookup(op_kind, nbytes);
  if (algo == TPU_COLL_AUTO) {
    /* built-in heuristic, identical to the pre-engine behavior */
    if (op_kind == TPU_OPKIND_ALLREDUCE)
      algo = (nbytes >= 64 * 1024 && count >= c->size) ? TPU_COLL_RING
                                                       : TPU_COLL_TREE;
    else
      algo = TPU_COLL_RING;
  }
  /* per-op canonicalization: the alltoall family (qalltoall/halltoall/
   * hqalltoall) exists only for alltoall, and alltoall has only the
   * pairwise exchange outside that family (rd/tree/qring/... have no
   * alltoall schedule).  Map strays to RING — the exact flat exchange —
   * BEFORE the gates, so deny/force act on canonical codes. */
  if (op_kind == TPU_OPKIND_ALLTOALL) {
    if (algo != TPU_COLL_RING && algo != TPU_COLL_QA2A &&
        algo != TPU_COLL_HA2A && algo != TPU_COLL_HQA2A)
      algo = TPU_COLL_RING;
  } else if (algo == TPU_COLL_QA2A || algo == TPU_COLL_HA2A ||
             algo == TPU_COLL_HQA2A) {
    algo = TPU_COLL_RING;
  }
  /* hierarchical eligibility: needs a discovered multi-island topology
   * on this comm.  A hierarchical pick on a flat comm (or under
   * MPI4JAX_TPU_HIER=deny) degrades to its flat twin; =force upgrades
   * every eligible flat pick.  The topology agrees across ranks (every
   * member installed the same map), so the degradation is consistent
   * and the schedules still match.  BEFORE the quant block: the
   * quantized wire format applies to a hierarchical schedule's
   * inter-island LEG (inside hier_allreduce), never to the whole-op
   * code. */
  {
    const bool h_ok = hier_eligible(c);
    if (algo == TPU_COLL_HRING || algo == TPU_COLL_HTREE) {
      if (!h_ok || hier_mode() == HIER_DENY)
        algo = algo == TPU_COLL_HRING ? TPU_COLL_RING : TPU_COLL_TREE;
    } else if (algo == TPU_COLL_HA2A || algo == TPU_COLL_HQA2A) {
      /* the hierarchical alltoall degrades one axis at a time: HQA2A
       * keeps its quantized wire (QA2A) on a flat comm — the quant
       * block below settles the other axis */
      if (!h_ok || hier_mode() == HIER_DENY)
        algo = algo == TPU_COLL_HA2A ? TPU_COLL_RING : TPU_COLL_QA2A;
    } else if (hier_mode() == HIER_FORCE && h_ok &&
               op_kind == TPU_OPKIND_ALLTOALL) {
      /* same non-upgrade rule as qring below: an explicitly quantized
       * flat exchange only gains the hierarchical route when the quant
       * force gate re-quantizes the leader leg anyway */
      if (algo == TPU_COLL_RING) algo = TPU_COLL_HA2A;
    } else if (hier_mode() == HIER_FORCE && h_ok &&
               algo != TPU_COLL_SHM && algo != TPU_COLL_QRING &&
               algo != TPU_COLL_QRD) {
      /* an explicitly selected quantized wire format is NOT upgraded:
       * the hierarchical leader leg only re-quantizes under
       * COLL_QUANT=force, so rewriting qring -> hring here would
       * silently move ~4x the bytes on the slow tier */
      algo = algo == TPU_COLL_RING ? TPU_COLL_HRING : TPU_COLL_HTREE;
    }
  }
  /* quantized eligibility: allreduce, real floating dtype, SUM.  An
   * ineligible (dtype, op) or the deny gate degrades the quantized
   * code to its exact counterpart — dtype agrees across ranks, so the
   * degradation is consistent and the schedules still match.  BEFORE
   * the allgather fixups, so a (nonsensical) quantized table row for
   * allgather degrades and then takes the normal rd/ring legality
   * path. */
  {
    /* alltoall is pure data movement — no reduction op to gate on, the
     * wire format just needs a codec-legal dtype */
    const bool q_ok =
        op_kind == TPU_OPKIND_ALLTOALL
            ? quant_dtype_ok(dtype)
            : op_kind == TPU_OPKIND_ALLREDUCE && quant_dtype_ok(dtype) &&
                  rop == TPU_SUM;
    if (algo == TPU_COLL_QRING || algo == TPU_COLL_QRD) {
      if (!q_ok || quant_mode() == QUANT_DENY)
        algo = algo == TPU_COLL_QRING ? TPU_COLL_RING : TPU_COLL_RD;
    } else if (algo == TPU_COLL_QA2A || algo == TPU_COLL_HQA2A) {
      if (!q_ok || quant_mode() == QUANT_DENY)
        algo = algo == TPU_COLL_QA2A ? TPU_COLL_RING : TPU_COLL_HA2A;
    } else if (quant_mode() == QUANT_FORCE && q_ok &&
               op_kind == TPU_OPKIND_ALLTOALL) {
      if (algo == TPU_COLL_RING) algo = TPU_COLL_QA2A;
      else if (algo == TPU_COLL_HA2A) algo = TPU_COLL_HQA2A;
    } else if (quant_mode() == QUANT_FORCE && q_ok &&
               algo != TPU_COLL_HRING && algo != TPU_COLL_HTREE) {
      algo = algo == TPU_COLL_RING ? TPU_COLL_QRING : TPU_COLL_QRD;
    }
  }
  if (op_kind == TPU_OPKIND_ALLGATHER && algo == TPU_COLL_RD &&
      (c->size & (c->size - 1)) != 0)
    algo = TPU_COLL_RING;
  if (op_kind == TPU_OPKIND_ALLGATHER && algo == TPU_COLL_TREE &&
      c->size > 200)
    /* the gather half addresses ranks serially; keep the root's recv
     * loop bounded on very wide worlds */
    algo = TPU_COLL_RING;
  return algo;
}

/* chunk [i] covers elements [i*per, min((i+1)*per, count)) */
int64_t chunk_lo(int64_t count, int size, int i) {
  int64_t per = (count + size - 1) / size;
  int64_t lo = per * i;
  return lo < count ? lo : count;
}

/* Receive one exact-size collective frame from `source` and fold it
 * into `dst` in cache-sized blocks AS THE BYTES ARRIVE: the payload
 * goes socket -> small hot scratch -> combine, instead of
 * socket -> multi-MB tmp (a full RAM round trip) -> combine.  Wire
 * format identical to recv_msg (one frame, same header checks); only
 * the landing buffer is blocked.  TCP path only — arena comms never
 * reach the ring schedules. */
constexpr int64_t kCombineBlockBytes = 128 * 1024;

int recv_combine_msg(Comm* c, int source, char* dst, std::vector<char>& tmp,
                     int64_t count, int dtype, int op) {
  fault_fire(c, g_job_rank, FP_RECV, "recv");
  if (pending_head(c, source))
    /* staged user messages precede this collective on the channel: the
     * ranks disagree on the schedule (the wire path would read a user
     * frame here and fail the same way) */
    FAIL(c, "message order violation: collective frame expected from rank "
         "%d but user message (tag %d) is pending", source,
         pending_head(c, source)->tag);
  const int64_t esize = dtype_size(dtype);
  const int64_t nbytes = count * esize;
  MsgHeader h{};
  uint64_t seq = 0;
  int ffd = -1;
  int rc;
  for (;;) {
    {
      ObsWaitTimer wt;  // header arrival = wait phase (see recv_msg_status)
      rc = wire_read_hdr(c, source, &h, &seq, &ffd);
    }
    if (rc == 0) break;
    /* heal-at-header only: the header wait is where a transient reset
     * lands in practice.  Once blocks start folding into dst the frame
     * is partially combined and cannot replay — a mid-payload failure
     * below escalates (documented scope: sharp-bits "Self-healing"). */
    if (io_rc_retryable(rc) &&
        link_recover(c, source, ffd, "recv collective header") == 0)
      continue;
    FAIL_IO(c, rc, "recv header from %d", source);
  }
  if (h.tag == kPoisonTag) return poison_fail(c, source, h);
  if (h.comm_id != c->comm_id)
    FAIL(c, "communicator mismatch: rank %d's message is for comm %d, this "
         "is comm %d — ops on sibling communicators must run in a "
         "consistent order on both endpoints", source, h.comm_id,
         c->comm_id);
  if (h.tag != kCollectiveTag)
    FAIL(c, "message order violation: expected tag %d from rank %d, got %d",
         kCollectiveTag, source, h.tag);
  if (h.nbytes != nbytes)
    FAIL(c, "size mismatch from rank %d: expected %lld bytes, got %lld",
         source, (long long)nbytes, (long long)h.nbytes);
  for (int64_t off = 0; off < nbytes; off += kCombineBlockBytes) {
    int64_t nb = std::min(nbytes - off, kCombineBlockBytes);
    rc = read_all_dl(ffd, tmp.data(), nb);
    if (rc) FAIL_IO(c, rc, "recv payload from %d", source);
    if (combine(dst + off, tmp.data(), nb / esize, dtype, op, c)) return 1;
  }
  wire_mark_delivered(c, source, seq);
  return 0;
}

/* Chunked ring: reduce-scatter then allgather, 2*(n-1)/n of the payload
 * on the wire per rank — the bandwidth-optimal schedule for large
 * messages.  Handles count < size via empty chunks (zero-byte frames).
 * The reduce-scatter receive folds blockwise while the frame streams in
 * (recv_combine_msg) — bitwise identical to landing the whole chunk
 * first, since elementwise combine is independent per block. */
int ring_allreduce(Comm* c, void* recvbuf, int64_t count, int dtype,
                   int op) {
  const int size = c->size, rank = c->rank;
  const int64_t esize = dtype_size(dtype);
  char* buf = static_cast<char*>(recvbuf);
  int next = (rank + 1) % size, prev = (rank - 1 + size) % size;
  std::vector<char> tmp(std::min<int64_t>(
      kCombineBlockBytes, ((count + size - 1) / size) * esize));

  /* phase 1: ring reduce-scatter — after size-1 rounds, chunk (rank+1)%size
   * holds the full reduction */
  for (int step = 0; step < size - 1; step++) {
    int sc = (rank - step + size) % size;
    int rc = (rank - step - 1 + size) % size;
    int64_t slo = chunk_lo(count, size, sc), shi = chunk_lo(count, size, sc + 1);
    int64_t rlo = chunk_lo(count, size, rc), rhi = chunk_lo(count, size, rc + 1);
    SendJob job;
    if (async_send(c, &job, next, kCollectiveTag, buf + slo * esize,
                   (shi - slo) * esize))
      return 1;
    int recv_rc = recv_combine_msg(c, prev, buf + rlo * esize, tmp,
                                   rhi - rlo, dtype, op);
    if (wait_send(c, &job) || recv_rc) return 1;
  }
  /* phase 2: ring allgather of the reduced chunks */
  for (int step = 0; step < size - 1; step++) {
    int sc = (rank + 1 - step + size) % size;
    int rc = (rank - step + size) % size;
    int64_t slo = chunk_lo(count, size, sc), shi = chunk_lo(count, size, sc + 1);
    int64_t rlo = chunk_lo(count, size, rc), rhi = chunk_lo(count, size, rc + 1);
    SendJob job;
    if (async_send(c, &job, next, kCollectiveTag, buf + slo * esize,
                   (shi - slo) * esize))
      return 1;
    int recv_rc = recv_msg(c, prev, kCollectiveTag, buf + rlo * esize,
                           (rhi - rlo) * esize);
    if (wait_send(c, &job) || recv_rc) return 1;
  }
  return 0;
}

/* Binomial-tree reduce to rank 0 + tree bcast: 2*log2(n) serial hops —
 * the latency-favoring schedule for small payloads (the pre-engine
 * small-message default). */
int tree_allreduce(Comm* c, void* recvbuf, int64_t count, int dtype,
                   int op) {
  const int64_t nbytes = count * dtype_size(dtype);
  std::vector<char> tmp(nbytes);
  for (int mask = 1; mask < c->size; mask <<= 1) {
    if (c->rank & mask) {
      if (send_msg(c, c->rank - mask, kCollectiveTag, recvbuf, nbytes))
        return 1;
      break;
    }
    if (c->rank + mask < c->size) {
      if (recv_msg(c, c->rank + mask, kCollectiveTag, tmp.data(), nbytes))
        return 1;
      if (combine(recvbuf, tmp.data(), count, dtype, op, c)) return 1;
    }
  }
  return bcast_internal(c, recvbuf, nbytes, 0);
}

/* Recursive doubling: log2(n) rounds of pairwise full-buffer exchange —
 * every rank holds the result with no bcast phase.  Non-power-of-two
 * sizes use the standard fold: the first 2*rem ranks pair up (evens
 * lend their data to odds and sit out the butterfly), the remaining
 * power-of-two group doubles, then the evens get the result back. */
int rd_allreduce(Comm* c, void* recvbuf, int64_t count, int dtype, int op) {
  const int size = c->size, rank = c->rank;
  const int64_t nbytes = count * dtype_size(dtype);
  int pof2 = 1;
  while (pof2 * 2 <= size) pof2 *= 2;
  const int rem = size - pof2;
  std::vector<char> tmp(nbytes);
  int newrank;
  if (rank < 2 * rem) {
    if ((rank & 1) == 0) {
      if (send_msg(c, rank + 1, kCollectiveTag, recvbuf, nbytes)) return 1;
      newrank = -1;  // sits out the butterfly
    } else {
      if (recv_msg(c, rank - 1, kCollectiveTag, tmp.data(), nbytes))
        return 1;
      if (combine(recvbuf, tmp.data(), count, dtype, op, c)) return 1;
      newrank = rank / 2;
    }
  } else {
    newrank = rank - rem;
  }
  if (newrank >= 0) {
    for (int mask = 1; mask < pof2; mask <<= 1) {
      int newpeer = newrank ^ mask;
      int peer = newpeer < rem ? newpeer * 2 + 1 : newpeer + rem;
      SendJob job;
      if (async_send(c, &job, peer, kCollectiveTag, recvbuf, nbytes))
        return 1;
      int rc = recv_msg(c, peer, kCollectiveTag, tmp.data(), nbytes);
      if (wait_send(c, &job) || rc) return 1;
      if (combine(recvbuf, tmp.data(), count, dtype, op, c)) return 1;
    }
  }
  if (rank < 2 * rem) {
    if (rank & 1) {
      if (send_msg(c, rank - 1, kCollectiveTag, recvbuf, nbytes)) return 1;
    } else {
      if (recv_msg(c, rank + 1, kCollectiveTag, recvbuf, nbytes)) return 1;
    }
  }
  return 0;
}

/* Ring allgather: size-1 rounds, each forwarding the block received
 * last round (the pre-engine default). */
int ring_allgather(Comm* c, const void* sendbuf, int64_t nbytes,
                   void* recvbuf) {
  char* out = static_cast<char*>(recvbuf);
  std::memcpy(out + (int64_t)c->rank * nbytes, sendbuf, nbytes);
  int next = (c->rank + 1) % c->size;
  int prev = (c->rank - 1 + c->size) % c->size;
  if (c->size == 1) return 0;
  for (int round = 0; round < c->size - 1; round++) {
    int send_block = (c->rank - round + c->size) % c->size;
    int recv_block = (c->rank - round - 1 + c->size) % c->size;
    SendJob job;
    if (async_send(c, &job, next, kCollectiveTag,
                   out + (int64_t)send_block * nbytes, nbytes))
      return 1;
    int recv_rc = recv_msg(c, prev, kCollectiveTag,
                           out + (int64_t)recv_block * nbytes, nbytes);
    if (wait_send(c, &job) || recv_rc) return 1;
  }
  return 0;
}

/* Gather to rank 0 + binomial bcast of the stacked result: trades the
 * ring's n-1 serial rounds for a serial gather + log2(n) bcast hops —
 * wins at small payloads where per-hop latency dominates. */
int tree_allgather(Comm* c, const void* sendbuf, int64_t nbytes,
                   void* recvbuf) {
  char* out = static_cast<char*>(recvbuf);
  const int root = 0;
  if (c->rank == root) {
    std::memcpy(out + (int64_t)root * nbytes, sendbuf, nbytes);
    for (int r = 0; r < c->size; r++) {
      if (r == root) continue;
      if (recv_msg(c, r, kCollectiveTag, out + (int64_t)r * nbytes, nbytes))
        return 1;
    }
  } else {
    if (send_msg(c, root, kCollectiveTag, sendbuf, nbytes)) return 1;
  }
  return bcast_internal(c, out, (int64_t)c->size * nbytes, root);
}

/* Recursive-doubling allgather (power-of-two sizes only; resolve_coll_algo
 * degrades to ring otherwise): at step k each rank swaps its current
 * 2^k-block group with partner rank^2^k — log2(n) rounds, same total
 * bytes as the ring. */
int rd_allgather(Comm* c, const void* sendbuf, int64_t nbytes,
                 void* recvbuf) {
  char* out = static_cast<char*>(recvbuf);
  std::memcpy(out + (int64_t)c->rank * nbytes, sendbuf, nbytes);
  for (int mask = 1; mask < c->size; mask <<= 1) {
    int peer = c->rank ^ mask;
    int64_t my_off = (int64_t)(c->rank & ~(mask - 1)) * nbytes;
    int64_t peer_off = (int64_t)(peer & ~(mask - 1)) * nbytes;
    int64_t len = (int64_t)mask * nbytes;
    SendJob job;
    if (async_send(c, &job, peer, kCollectiveTag, out + my_off, len))
      return 1;
    int rc = recv_msg(c, peer, kCollectiveTag, out + peer_off, len);
    if (wait_send(c, &job) || rc) return 1;
  }
  return 0;
}

/* ============ quantized wire formats (qring / qrd) ============
 *
 * EQuARX-style in-collective block quantization (arXiv:2506.17615):
 * every collective frame carries int8 codes plus per-block f32 absmax
 * scales instead of full-precision elements — ~4x fewer payload bytes
 * for f32, ~2x for bf16/f16 — and the receive side dequantizes and
 * reduces streaming in f32.  The codec below IS the wire format; it is
 * also exported (tpucomm_quant_pack/unpack) so diag and the Python
 * accuracy harness can round-trip the exact native bits.
 *
 * Determinism contract: quantization is pure per-block f32 arithmetic
 * (absmax, divide, round-to-nearest-even), so identical inputs pack to
 * identical bytes on every rank, and both algorithms are built so that
 * every rank reconstructs bit-identical RESULTS (see each schedule's
 * comment) — a quantized gradient sync cannot make DP replicas drift
 * apart. */

constexpr int64_t kQuantBlock = 256;  // elements per f32 absmax scale

int64_t quant_blocks(int64_t count) {
  return count > 0 ? (count + kQuantBlock - 1) / kQuantBlock : 0;
}

/* packed layout: ceil(count/256) f32 scales, then count int8 codes */
int64_t quant_packed_bytes(int64_t count) {
  return count > 0 ? 4 * quant_blocks(count) + count : 0;
}

bool quant_dtype_ok(int dtype) {
  return dtype == TPU_F16 || dtype == TPU_BF16 || dtype == TPU_F32 ||
         dtype == TPU_F64;
}

/* dtype buffer -> f32 working values (codec and reduction run in f32) */
void quant_load_f32(const void* src, int dtype, int64_t count, float* dst) {
  switch (dtype) {
    case TPU_F32:
      std::memcpy(dst, src, (size_t)count * 4);
      break;
    case TPU_F64: {
      const double* s = static_cast<const double*>(src);
      for (int64_t i = 0; i < count; i++) dst[i] = (float)s[i];
      break;
    }
    case TPU_BF16: {
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; i++) dst[i] = bf16_to_f32(s[i]);
      break;
    }
    default: {  // TPU_F16 (quant_dtype_ok gates everything else out)
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; i++) dst[i] = f16_to_f32(s[i]);
      break;
    }
  }
}

void quant_store_f32(const float* src, int dtype, int64_t count, void* dst) {
  switch (dtype) {
    case TPU_F32:
      std::memcpy(dst, src, (size_t)count * 4);
      break;
    case TPU_F64: {
      double* d = static_cast<double*>(dst);
      for (int64_t i = 0; i < count; i++) d[i] = (double)src[i];
      break;
    }
    case TPU_BF16: {
      uint16_t* d = static_cast<uint16_t*>(dst);
      for (int64_t i = 0; i < count; i++) d[i] = f32_to_bf16(src[i]);
      break;
    }
    default: {  // TPU_F16
      uint16_t* d = static_cast<uint16_t*>(dst);
      for (int64_t i = 0; i < count; i++) d[i] = f32_to_f16(src[i]);
      break;
    }
  }
}

/* Block kernels.  The AVX2 variants follow the vertical_reduce pattern
 * (target attribute + have_avx2() runtime dispatch) and are BIT-
 * IDENTICAL to the scalar fallbacks: both compute value*(1/scale) in
 * f32, clip to ±127, and round to nearest EVEN (cvtps_epi32 under the
 * default MXCSR mode ≡ the scalar add-2^23-magic-number trick), so a
 * mixed-CPU job cannot diverge on quantized bits.  The pack loop at
 * 16 MiB measures ~9 GB/s with AVX2 vs ~1 GB/s scalar on the CI host —
 * without it the codec, not the wire, would be the bottleneck. */

inline float quant_amax_scalar(const float* x, int64_t n) {
  float amax = 0.0f;
  for (int64_t i = 0; i < n; i++) amax = std::max(amax, std::fabs(x[i]));
  return amax;
}

inline void quant_codes_scalar(const float* x, int64_t n, float inv,
                               int8_t* codes) {
  for (int64_t i = 0; i < n; i++) {
    float v = x[i] * inv;
    v = std::min(127.0f, std::max(-127.0f, v));
    v = (v + 12582912.0f) - 12582912.0f;  // round to nearest even
    codes[i] = (int8_t)(int32_t)v;
  }
}

__attribute__((target("avx2"))) float quant_amax_avx2(const float* x,
                                                      int64_t n) {
  const __m256 mask = _mm256_castsi256_ps(_mm256_set1_epi32(0x7fffffff));
  __m256 am = _mm256_setzero_ps();
  int64_t i = 0;
  for (; i + 8 <= n; i += 8)
    am = _mm256_max_ps(am, _mm256_and_ps(mask, _mm256_loadu_ps(x + i)));
  float tmp[8];
  _mm256_storeu_ps(tmp, am);
  float amax = 0.0f;
  for (int k = 0; k < 8; k++) amax = std::max(amax, tmp[k]);
  for (; i < n; i++) amax = std::max(amax, std::fabs(x[i]));
  return amax;
}

__attribute__((target("avx2"))) void quant_codes_avx2(const float* x,
                                                      int64_t n, float inv,
                                                      int8_t* codes) {
  const __m256 vinv = _mm256_set1_ps(inv);
  const __m256 vlo = _mm256_set1_ps(-127.0f);
  const __m256 vhi = _mm256_set1_ps(127.0f);
  int64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 v = _mm256_mul_ps(_mm256_loadu_ps(x + i), vinv);
    v = _mm256_min_ps(vhi, _mm256_max_ps(vlo, v));
    __m256i q = _mm256_cvtps_epi32(v);  // rounds to nearest even
    __m128i p16 = _mm_packs_epi32(_mm256_castsi256_si128(q),
                                  _mm256_extracti128_si256(q, 1));
    __m128i p8 = _mm_packs_epi16(p16, p16);
    _mm_storel_epi64(reinterpret_cast<__m128i*>(codes + i), p8);
  }
  if (i < n) quant_codes_scalar(x + i, n - i, inv, codes + i);
}

__attribute__((target("avx2"))) void quant_dq_avx2(const int8_t* codes,
                                                   int64_t n, float scale,
                                                   float* dst, bool add) {
  const __m256 vs = _mm256_set1_ps(scale);
  int64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256i q = _mm256_cvtepi8_epi32(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(codes + i)));
    __m256 v = _mm256_mul_ps(vs, _mm256_cvtepi32_ps(q));
    if (add) v = _mm256_add_ps(_mm256_loadu_ps(dst + i), v);
    _mm256_storeu_ps(dst + i, v);
  }
  for (; i < n; i++) {
    const float v = scale * (float)codes[i];
    dst[i] = add ? dst[i] + v : v;
  }
}

__attribute__((target("avx512f,avx512bw,avx512dq"))) float
quant_amax_avx512(const float* x, int64_t n) {
  const __m512 mask = _mm512_castsi512_ps(_mm512_set1_epi32(0x7fffffff));
  __m512 am = _mm512_setzero_ps();
  int64_t i = 0;
  for (; i + 16 <= n; i += 16)
    am = _mm512_max_ps(am, _mm512_and_ps(mask, _mm512_loadu_ps(x + i)));
  float amax = _mm512_reduce_max_ps(am);
  for (; i < n; i++) amax = std::max(amax, std::fabs(x[i]));
  return amax;
}

__attribute__((target("avx512f,avx512bw,avx512dq"))) void
quant_codes_avx512(const float* x, int64_t n, float inv, int8_t* codes) {
  const __m512 vinv = _mm512_set1_ps(inv);
  const __m512 vlo = _mm512_set1_ps(-127.0f);
  const __m512 vhi = _mm512_set1_ps(127.0f);
  int64_t i = 0;
  for (; i + 16 <= n; i += 16) {
    __m512 v = _mm512_mul_ps(_mm512_loadu_ps(x + i), vinv);
    v = _mm512_min_ps(vhi, _mm512_max_ps(vlo, v));
    __m512i q = _mm512_cvtps_epi32(v);  // rounds to nearest even
    /* saturating narrow is exact here: q is pre-clipped to ±127 */
    _mm_storeu_si128(reinterpret_cast<__m128i*>(codes + i),
                     _mm512_cvtsepi32_epi8(q));
  }
  if (i < n) quant_codes_scalar(x + i, n - i, inv, codes + i);
}

__attribute__((target("avx512f,avx512bw,avx512dq"))) void
quant_dq_avx512(const int8_t* codes, int64_t n, float scale, float* dst,
                bool add) {
  const __m512 vs = _mm512_set1_ps(scale);
  int64_t i = 0;
  /* NB: non-temporal stores were tried for the write-only (!add) path
   * and measured SLOWER on the virtualized CI hosts (WC flushes under
   * KVM), besides needing fence discipline around the progress
   * thread — plain stores keep the kernel simple and bit-obvious. */
  for (; i + 16 <= n; i += 16) {
    __m512i q = _mm512_cvtepi8_epi32(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(codes + i)));
    __m512 v = _mm512_mul_ps(vs, _mm512_cvtepi32_ps(q));
    if (add) v = _mm512_add_ps(_mm512_loadu_ps(dst + i), v);
    _mm512_storeu_ps(dst + i, v);
  }
  for (; i < n; i++) {
    const float v = scale * (float)codes[i];
    dst[i] = add ? dst[i] + v : v;
  }
}

/* 0 = scalar, 1 = avx2, 2 = avx512 — one probe, pack/unpack dispatch */
int quant_isa() {
  static int v = [] {
    if (__builtin_cpu_supports("avx512f") &&
        __builtin_cpu_supports("avx512bw") &&
        __builtin_cpu_supports("avx512dq"))
      return 2;
    return have_avx2() ? 1 : 0;
  }();
  return v;
}

/* pack: per-block absmax scale (absmax/127; 1.0 for an all-zero
 * block), codes = round-to-nearest-even of value/scale clipped ±127 */
void quant_pack_f32(const float* x, int64_t count, char* out) {
  const int64_t nb = quant_blocks(count);
  char* scales = out;
  int8_t* codes = reinterpret_cast<int8_t*>(out + 4 * nb);
  const int isa = quant_isa();
  for (int64_t b = 0; b < nb; b++) {
    const int64_t lo = b * kQuantBlock;
    const int64_t n = std::min(count - lo, kQuantBlock);
    const float amax = isa == 2   ? quant_amax_avx512(x + lo, n)
                       : isa == 1 ? quant_amax_avx2(x + lo, n)
                                  : quant_amax_scalar(x + lo, n);
    const float scale = amax > 0.0f ? amax / 127.0f : 1.0f;
    const float inv = 1.0f / scale;
    std::memcpy(scales + 4 * b, &scale, 4);
    if (isa == 2)
      quant_codes_avx512(x + lo, n, inv, codes + lo);
    else if (isa == 1)
      quant_codes_avx2(x + lo, n, inv, codes + lo);
    else
      quant_codes_scalar(x + lo, n, inv, codes + lo);
  }
}

/* dst = scale * code (exact in f32: |code| <= 127 is exact, scale is a
 * stored f32 — every rank dequantizing the same bytes gets the same
 * bits).  `scales`/`codes` may point into one packed buffer (the
 * contiguous wire layout) or at separate staging runs (the streaming
 * receive path): `count` elements starting at dst, whole leading
 * blocks. */
void quant_dq_run(const char* scales, const int8_t* codes, int64_t count,
                  float* dst, bool add) {
  const int64_t nb = quant_blocks(count);
  const int isa = quant_isa();
  for (int64_t b = 0; b < nb; b++) {
    const int64_t lo = b * kQuantBlock;
    const int64_t n = std::min(count - lo, kQuantBlock);
    float scale;
    std::memcpy(&scale, scales + 4 * b, 4);
    if (isa == 2) {
      quant_dq_avx512(codes + lo, n, scale, dst + lo, add);
    } else if (isa == 1) {
      quant_dq_avx2(codes + lo, n, scale, dst + lo, add);
    } else {
      for (int64_t i = 0; i < n; i++) {
        const float v = scale * (float)codes[lo + i];
        dst[lo + i] = add ? dst[lo + i] + v : v;
      }
    }
  }
}

void quant_unpack_f32(const char* in, int64_t count, float* dst) {
  quant_dq_run(in,
               reinterpret_cast<const int8_t*>(in + 4 * quant_blocks(count)),
               count, dst, false);
}

/* Reusable per-thread scratch for the quantized schedules: fresh
 * multi-MB allocations per call cost first-touch page faults that are
 * pure CPU on the loopback critical path (the same reasoning as the
 * bridge's reusable output buffers).  One op executes at a time per
 * thread, so fixed slots cannot alias; a slot grows to the largest
 * payload seen and stays.  Slots: 0 = send packs, 1 = own-chunk pack,
 * 2 = frame scales, 3 = codes run, 4 = received contributions. */
std::vector<char>& quant_tls_buf(int slot, int64_t n) {
  static thread_local std::vector<char> bufs[5];
  auto& b = bufs[slot];
  if ((int64_t)b.size() < n) b.resize((size_t)std::max<int64_t>(n, 1));
  return b;
}

/* Fold the peers' packed contributions into the chunk, quantize the
 * reduced chunk, and dequantize the packed bytes back into the working
 * buffer — ONE L1-blocked pass per 256-element block instead of three
 * whole-chunk passes (fold, pack, unpack).  The per-element arithmetic
 * sequence is exactly the sequential version's, so the packed bytes
 * and the final values are bit-identical to quant_dq_multi_add
 * followed by quant_pack_f32 + quant_unpack_f32. */
void quant_fold_pack(const char* const* packs, int nsrc, int64_t count,
                     float* acc, char* out) {
  const int64_t nb = quant_blocks(count);
  char* scales_out = out;
  int8_t* codes_out = reinterpret_cast<int8_t*>(out + 4 * nb);
  const int isa = quant_isa();
  for (int64_t b = 0; b < nb; b++) {
    const int64_t lo = b * kQuantBlock;
    const int64_t n = std::min(count - lo, kQuantBlock);
    for (int k = 0; k < nsrc; k++) {
      const char* in = packs[k];
      const int8_t* codes =
          reinterpret_cast<const int8_t*>(in + 4 * nb) + lo;
      float scale;
      std::memcpy(&scale, in + 4 * b, 4);
      if (isa == 2) {
        quant_dq_avx512(codes, n, scale, acc + lo, true);
      } else if (isa == 1) {
        quant_dq_avx2(codes, n, scale, acc + lo, true);
      } else {
        for (int64_t i = 0; i < n; i++)
          acc[lo + i] += scale * (float)codes[i];
      }
    }
    const float amax = isa == 2   ? quant_amax_avx512(acc + lo, n)
                       : isa == 1 ? quant_amax_avx2(acc + lo, n)
                                  : quant_amax_scalar(acc + lo, n);
    const float scale = amax > 0.0f ? amax / 127.0f : 1.0f;
    const float inv = 1.0f / scale;
    std::memcpy(scales_out + 4 * b, &scale, 4);
    if (isa == 2)
      quant_codes_avx512(acc + lo, n, inv, codes_out + lo);
    else if (isa == 1)
      quant_codes_avx2(acc + lo, n, inv, codes_out + lo);
    else
      quant_codes_scalar(acc + lo, n, inv, codes_out + lo);
    if (isa == 2) {
      quant_dq_avx512(codes_out + lo, n, scale, acc + lo, false);
    } else if (isa == 1) {
      quant_dq_avx2(codes_out + lo, n, scale, acc + lo, false);
    } else {
      for (int64_t i = 0; i < n; i++)
        acc[lo + i] = scale * (float)codes_out[lo + i];
    }
  }
}

/* Receive one packed-codec collective frame from `source` and
 * dequantize it into `dst` (accumulating when `add`) AS THE BYTES
 * ARRIVE: the scales land in one small read, then the codes stream
 * through a cache-sized scratch run — the packed payload never
 * occupies a full-size intermediate buffer (recv_combine_msg's
 * streaming-fold pattern, specialized to the quantized wire).  Frame
 * checks are identical to recv_msg (one frame, same header
 * diagnostics).  TCP path only — arena comms never reach the
 * quantized schedules. */
int recv_quant_msg(Comm* c, int source, int64_t count, float* dst,
                   bool add) {
  fault_fire(c, g_job_rank, FP_RECV, "recv");
  if (pending_head(c, source))
    FAIL(c, "message order violation: collective frame expected from rank "
         "%d but user message (tag %d) is pending", source,
         pending_head(c, source)->tag);
  const int64_t nbytes = quant_packed_bytes(count);
  MsgHeader h{};
  uint64_t seq = 0;
  int ffd = -1;
  int rc;
  /* Heal-at-header only (same scope as recv_combine_msg): once codes
   * start folding into dst the frame is partially dequantized and
   * cannot replay — a mid-payload failure escalates. */
  for (;;) {
    {
      ObsWaitTimer wt;  // header arrival = wait phase (see recv_msg_status)
      rc = wire_read_hdr(c, source, &h, &seq, &ffd);
    }
    if (rc && io_rc_retryable(rc) &&
        link_recover(c, source, ffd, "recv collective header") == 0)
      continue;
    break;
  }
  if (ffd < 0) ffd = c->socks[source];
  if (rc) FAIL_IO(c, rc, "recv header from %d", source);
  if (h.tag == kPoisonTag) return poison_fail(c, source, h);
  if (h.comm_id != c->comm_id)
    FAIL(c, "communicator mismatch: rank %d's message is for comm %d, this "
         "is comm %d — ops on sibling communicators must run in a "
         "consistent order on both endpoints", source, h.comm_id,
         c->comm_id);
  if (h.tag != kCollectiveTag)
    FAIL(c, "message order violation: expected tag %d from rank %d, got %d",
         kCollectiveTag, source, h.tag);
  if (h.nbytes != nbytes)
    FAIL(c, "size mismatch from rank %d: expected %lld bytes, got %lld",
         source, (long long)nbytes, (long long)h.nbytes);
  if (count <= 0) {
    wire_mark_delivered(c, source, seq);
    return 0;
  }
  const int64_t nb = quant_blocks(count);
  std::vector<char>& scales = quant_tls_buf(2, 4 * nb);
  rc = read_all_dl(ffd, scales.data(), 4 * nb);
  if (rc) FAIL_IO(c, rc, "recv payload from %d", source);
  /* codes in runs of whole blocks (kCombineBlockBytes is a multiple of
   * kQuantBlock, so every run starts on a block boundary) */
  static_assert(kCombineBlockBytes % kQuantBlock == 0,
                "codes runs must stay block-aligned");
  std::vector<char>& run =
      quant_tls_buf(3, std::min<int64_t>(count, kCombineBlockBytes));
  for (int64_t e0 = 0; e0 < count; e0 += kCombineBlockBytes) {
    const int64_t e1 = std::min(count, e0 + kCombineBlockBytes);
    rc = read_all_dl(ffd, run.data(), e1 - e0);
    if (rc) FAIL_IO(c, rc, "recv payload from %d", source);
    quant_dq_run(scales.data() + 4 * (e0 / kQuantBlock),
                 reinterpret_cast<const int8_t*>(run.data()), e1 - e0,
                 dst + e0, add);
  }
  wire_mark_delivered(c, source, seq);
  return 0;
}

/* Quantized ring-family allreduce (the EQuARX decomposition): a DIRECT
 * pairwise quantized reduce-scatter — round r exchanges packed chunks
 * with ranks ±r, so each rank's inputs are quantized exactly ONCE —
 * followed by the ring allgather of the once-quantized reduced chunks.
 * Same total wire bytes as the exact ring (2*(n-1)/n of the payload,
 * at ~1/4 the bytes for f32), but only TWO quantization steps touch
 * any element (input + reduced chunk) instead of one per hop: less
 * codec CPU on the critical path AND a tighter error bound.  Each
 * rank's own contribution to its chunk stays full-precision; the
 * allgather forwards packed bytes verbatim and the owner dequantizes
 * its own packed chunk too, so every rank reconstructs bit-identical
 * results.  SUM only (resolve_coll_algo gates). */
int qring_allreduce(Comm* c, void* recvbuf, int64_t count, int dtype,
                    int op) {
  (void)op;  // gated to TPU_SUM before dispatch
  const int size = c->size, rank = c->rank;
  /* f32 payloads run IN PLACE on the caller's buffer — a 16 MiB call
   * must not pay a 16 MiB zero-fill + two 16 MiB copies of staging
   * (measured: the staging traffic alone cost more than the wire
   * saving on a loopback host).  Other dtypes stage through an
   * uninitialized f32 scratch. */
  float* acc;
  std::unique_ptr<float[]> staged;
  if (dtype == TPU_F32) {
    acc = static_cast<float*>(recvbuf);
  } else {
    staged.reset(new float[(size_t)count]);
    quant_load_f32(recvbuf, dtype, count, staged.get());
    acc = staged.get();
  }
  const int64_t per = chunk_lo(count, size, 1) - chunk_lo(count, size, 0);
  const int64_t ppc = quant_packed_bytes(per);  // per-chunk pack ceiling
  const int64_t mlo = chunk_lo(count, size, rank);
  const int64_t mhi = chunk_lo(count, size, rank + 1);
  /* phase 1: direct quantized reduce-scatter.  Pack EVERY destination
   * chunk up front (dest chunks are never accumulated into, so they
   * still hold the original values — each input element is quantized
   * exactly once) and post all sends before the first receive: the
   * writer thread streams them while this thread drains incoming
   * contributions, instead of a per-round pack -> wire -> fold convoy.
   * Send k goes to rank+k and is the k-th frame receiver rank+k reads
   * from this channel, so every posted frame is at most one deep in a
   * socket buffer — deadlock-free for any buffer size. */
  std::vector<char>& spacks = quant_tls_buf(0, ppc * size);
  std::vector<SendJob> jobs((size_t)size);
  for (int round = 1; round < size; round++) {
    const int dest = (rank + round) % size;
    const int64_t dlo = chunk_lo(count, size, dest);
    const int64_t dhi = chunk_lo(count, size, dest + 1);
    char* p = spacks.data() + (int64_t)dest * ppc;
    quant_pack_f32(acc + dlo, dhi - dlo, p);
    if (async_send(c, &jobs[dest], dest, kCollectiveTag, p,
                   quant_packed_bytes(dhi - dlo))) {
      for (int r2 = 1; r2 < round; r2++)
        wait_send(c, &jobs[(rank + r2) % size]);
      return 1;
    }
  }
  int rc = 0;
  /* land every peer's contribution (one frame per channel, reusable
   * scratch), then fold them in ONE L1-blocked pass over my chunk —
   * the fixed arrival order rank-1, rank-2, ... is preserved per
   * element by the fused fold, so the f32 accumulation is
   * deterministic and bit-identical to sequential folding */
  const int64_t mpb = quant_packed_bytes(mhi - mlo);
  std::vector<char>& contrib =
      quant_tls_buf(4, mpb * std::max(size - 1, 1));
  std::vector<const char*> cptrs((size_t)std::max(size - 1, 1));
  for (int round = 1; round < size && !rc; round++) {
    const int src = (rank - round + size) % size;
    char* slot = contrib.data() + (int64_t)(round - 1) * mpb;
    cptrs[(size_t)(round - 1)] = slot;
    rc = recv_msg(c, src, kCollectiveTag, slot, mpb);
  }
  std::vector<char>& own = quant_tls_buf(1, quant_packed_bytes(mhi - mlo));
  if (!rc && size > 1)
    /* fold + quantize + owner-requantize in one cache-blocked pass:
     * `own` then holds the once-quantized reduced chunk phase 2 ships */
    quant_fold_pack(cptrs.data(), size - 1, mhi - mlo, acc + mlo,
                    own.data());
  /* phase-1 sends keep draining on the writer thread while phase 2
   * packs and posts — both sets are waited together at the end */
  if (rc) {
    for (int round = 1; round < size; round++)
      wait_send(c, &jobs[(rank + round) % size]);
    return 1;
  }
  /* phase 2: direct allgather of the once-quantized reduced chunks —
   * pack the own chunk ONCE, dequantize the same bytes back (owner and
   * receivers hold identical bits), stream the identical frame to
   * every peer off the writer thread, then drain the peers' chunks.
   * Wire bytes per rank are the same as the ring pipeline
   * ((n-1)/n of the packed payload each way) without its step-by-step
   * serialization; per-channel depth stays one frame, so this is
   * deadlock-free for any socket buffer size. */
  {
    std::vector<SendJob> jobs2((size_t)size);
    bool posted_fail = false;
    for (int round = 1; round < size && !posted_fail; round++) {
      const int dest = (rank + round) % size;
      posted_fail = async_send(c, &jobs2[dest], dest, kCollectiveTag,
                               own.data(),
                               quant_packed_bytes(mhi - mlo)) != 0;
      if (posted_fail) rc = 1;
    }
    for (int round = 1; round < size && !rc; round++) {
      const int src = (rank - round + size) % size;
      const int64_t slo = chunk_lo(count, size, src);
      const int64_t shi = chunk_lo(count, size, src + 1);
      rc = recv_quant_msg(c, src, shi - slo, acc + slo, false);
    }
    /* both phases' sends reference spacks/own until here */
    for (int round = 1; round < size; round++) {
      rc |= wait_send(c, &jobs[(rank + round) % size]);
      if (jobs2[(rank + round) % size].fd >= 0 ||
          jobs2[(rank + round) % size].done)
        rc |= wait_send(c, &jobs2[(rank + round) % size]);
    }
    if (rc) return 1;
  }
  if (dtype != TPU_F32) quant_store_f32(acc, dtype, count, recvbuf);
  return 0;
}

/* Quantized recursive doubling: log2(n) pairwise exchanges of the
 * whole packed buffer.  Rank consistency: each side combines
 * dequant(own packed) + dequant(peer packed) — the pair exchanges the
 * same two byte strings and f32 addition is commutative, so merged
 * groups hold identical bits after every round and all ranks finish
 * identical.  Non-power-of-two fold matches rd_allreduce (the odd
 * member of each leading pair also requantizes the final result it
 * returns, keeping the sidelined even member bit-identical). */
int qrd_allreduce(Comm* c, void* recvbuf, int64_t count, int dtype, int op) {
  (void)op;  // gated to TPU_SUM before dispatch
  const int size = c->size, rank = c->rank;
  /* in-place for f32, staged otherwise — see qring_allreduce */
  float* acc;
  std::unique_ptr<float[]> staged;
  if (dtype == TPU_F32) {
    acc = static_cast<float*>(recvbuf);
  } else {
    staged.reset(new float[(size_t)count]);
    quant_load_f32(recvbuf, dtype, count, staged.get());
    acc = staged.get();
  }
  const int64_t pb = quant_packed_bytes(count);
  std::vector<char>& self = quant_tls_buf(0, pb);
  int pof2 = 1;
  while (pof2 * 2 <= size) pof2 *= 2;
  const int rem = size - pof2;
  int newrank;
  if (rank < 2 * rem) {
    quant_pack_f32(acc, count, self.data());
    if ((rank & 1) == 0) {
      if (send_msg(c, rank + 1, kCollectiveTag, self.data(), pb)) return 1;
      newrank = -1;  // sits out the butterfly
    } else {
      quant_unpack_f32(self.data(), count, acc);
      if (recv_quant_msg(c, rank - 1, count, acc, true)) return 1;
      newrank = rank / 2;
    }
  } else {
    newrank = rank - rem;
  }
  if (newrank >= 0) {
    for (int mask = 1; mask < pof2; mask <<= 1) {
      int newpeer = newrank ^ mask;
      int peer = newpeer < rem ? newpeer * 2 + 1 : newpeer + rem;
      quant_pack_f32(acc, count, self.data());
      SendJob job;
      if (async_send(c, &job, peer, kCollectiveTag, self.data(), pb))
        return 1;
      /* requantize the local half while the peer's frame is in
       * flight, then dequantize-and-add the arriving bytes streaming */
      quant_unpack_f32(self.data(), count, acc);
      int rc = recv_quant_msg(c, peer, count, acc, true);
      if (wait_send(c, &job) || rc) return 1;
    }
  }
  if (rem > 0) {
    /* non-power-of-two return phase: the sidelined evens receive the
     * result QUANTIZED, so every other rank must hold the same
     * quantize-dequantize image of it — the odd fold members pack the
     * bytes they send anyway, and the out-of-fold ranks requantize
     * locally (the butterfly left all participants bit-identical, so
     * everyone packs the same bytes and lands on the same result). */
    if (rank < 2 * rem && (rank & 1) == 0) {
      if (recv_quant_msg(c, rank + 1, count, acc, false)) return 1;
    } else {
      quant_pack_f32(acc, count, self.data());
      if (rank < 2 * rem &&
          send_msg(c, rank - 1, kCollectiveTag, self.data(), pb))
        return 1;
      quant_unpack_f32(self.data(), count, acc);
    }
  }
  if (dtype != TPU_F32) quant_store_f32(acc, dtype, count, recvbuf);
  return 0;
}

/* ============ alltoall schedules ============ */

/* The exact pairwise exchange (the historic tpucomm_alltoall body):
 * round r trades chunks with ranks ±r, one in-flight send overlapping
 * the matching receive.  Shared by the flat dispatch path and the
 * intra-island leg of the hierarchical alltoall. */
int flat_alltoall(Comm* c, const void* sendbuf, void* recvbuf,
                  int64_t chunk) {
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  std::memcpy(out + (int64_t)c->rank * chunk,
              in + (int64_t)c->rank * chunk, chunk);
  for (int round = 1; round < c->size; round++) {
    int dest = (c->rank + round) % c->size;
    int src = (c->rank - round + c->size) % c->size;
    SendJob job;
    if (async_send(c, &job, dest, kCollectiveTag,
                   in + (int64_t)dest * chunk, chunk))
      return 1;
    int recv_rc = recv_msg(c, src, kCollectiveTag,
                           out + (int64_t)src * chunk, chunk);
    if (wait_send(c, &job) || recv_rc) return 1;
  }
  return 0;
}

/* Quantized pairwise alltoall (TPU_COLL_QA2A): the same round schedule
 * with every off-rank chunk on the int8+scales codec wire —
 * quant_packed_bytes(count) per chunk instead of count*esize (~4x
 * fewer payload bytes for f32, ~2x for bf16/f16).  Every outgoing
 * chunk is packed up-front (one codec pass per chunk; the own-rank
 * chunk never crosses the wire and is copied EXACT), then the rounds
 * move only packed frames.  Rank-consistent by construction: each
 * destination dequantizes the bytes its source packed — there is no
 * cross-rank reduction to disagree on.  resolve_coll_algo gates
 * dtypes (F16/BF16/F32/F64). */
int q_alltoall(Comm* c, const void* sendbuf, void* recvbuf, int64_t count,
               int dtype) {
  const int size = c->size, rank = c->rank;
  const int64_t chunk = count * dtype_size(dtype);
  const int64_t ppc = quant_packed_bytes(count);
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);
  std::vector<char>& spacks = quant_tls_buf(0, ppc * size);
  if (dtype == TPU_F32) {
    for (int d = 0; d < size; d++) {
      if (d == rank) continue;
      quant_pack_f32(reinterpret_cast<const float*>(in + d * chunk), count,
                     spacks.data() + d * ppc);
    }
  } else {
    std::vector<char>& staged = quant_tls_buf(1, 4 * count);
    float* st = reinterpret_cast<float*>(staged.data());
    for (int d = 0; d < size; d++) {
      if (d == rank) continue;
      quant_load_f32(in + d * chunk, dtype, count, st);
      quant_pack_f32(st, count, spacks.data() + d * ppc);
    }
  }
  std::memcpy(out + (int64_t)rank * chunk, in + (int64_t)rank * chunk,
              chunk);
  for (int round = 1; round < size; round++) {
    int dest = (rank + round) % size;
    int src = (rank - round + size) % size;
    SendJob job;
    if (async_send(c, &job, dest, kCollectiveTag, spacks.data() + dest * ppc,
                   ppc))
      return 1;
    int rc;
    if (dtype == TPU_F32) {
      rc = recv_quant_msg(c, src, count,
                          reinterpret_cast<float*>(out + src * chunk),
                          false);
    } else {
      std::vector<char>& staged = quant_tls_buf(1, 4 * count);
      float* st = reinterpret_cast<float*>(staged.data());
      rc = recv_quant_msg(c, src, count, st, false);
      if (!rc) quant_store_f32(st, dtype, count, out + src * chunk);
    }
    if (wait_send(c, &job) || rc) return 1;
  }
  return 0;
}

/* ============ hierarchical (topology-aware) schedules ============
 *
 * hring / htree compose the flat kernels above over the sub-groups a
 * discovered topology provides (tpucomm_set_topology): an intra-island
 * reduce to the island leader (the shm arena when the island shares a
 * host, a serial member-order reduce over TCP otherwise), a
 * leader-tier allreduce across the slow inter-island links (ring for
 * hring, recursive doubling for htree; upgraded to the qring/qrd
 * quantized twin on that leg only under MPI4JAX_TPU_COLL_QUANT=force),
 * and an intra-island bcast of the result.  At np8 split 2x4 the flat
 * ring crosses the inter-host boundary on every hop; here only the
 * leader leg does — 2*(L-1)/L of the payload per LEADER instead of
 * 2*(n-1)/n per RANK on the slow tier.
 *
 * Determinism: both intra reduce paths combine in island member order
 * (the serial TCP reduce mirrors vertical_reduce's source order), so
 * shm-on and shm-off runs produce identical bits and ONE numpy
 * schedule simulator (topo.simulate_hring_sum) models both.  Every
 * rank of an island receives the leader's bytes verbatim in phase 3,
 * so ranks are always bit-consistent.
 *
 * Every leg additionally records one observability event labeled with
 * its transport tier (TPU_TIER_INTRA / TPU_TIER_INTER) inside the
 * whole-op record, so obs.stats() splits intra- from inter-island
 * bytes. */

/* Serial reduce to sub-comm rank 0 in member order: root starts from
 * its own buffer and folds rank 1, 2, ... sequentially — the same
 * association as the shm arena's vertical_reduce, which is what makes
 * the two intra paths bit-identical.  Islands are host-sized (a few
 * ranks), so the serial fan-in is not the bottleneck leg. */
int serial_reduce0(Comm* c, void* buf, int64_t count, int dtype, int op) {
  const int64_t nbytes = count * dtype_size(dtype);
  if (c->rank == 0) {
    std::vector<char> tmp((size_t)std::min<int64_t>(nbytes,
                                                    kCombineBlockBytes));
    for (int r = 1; r < c->size; r++)
      if (recv_combine_msg(c, r, static_cast<char*>(buf), tmp, count,
                           dtype, op))
        return 1;
    return 0;
  }
  return send_msg(c, 0, kCollectiveTag, buf, nbytes);
}

/* The leader-tier leg of a hierarchical allreduce: `leg` is ring or
 * rd, upgraded to its quantized twin when the force gate and the
 * (dtype, op) eligibility allow.  Returns the algorithm that ran via
 * *ran (for tracing). */
int leader_allreduce_leg(Comm* lead, void* buf, int64_t count, int dtype,
                         int op, int leg, int* ran) {
  const bool q_ok = quant_dtype_ok(dtype) && op == TPU_SUM;
  if (quant_mode() == QUANT_FORCE && q_ok)
    leg = leg == TPU_COLL_RING ? TPU_COLL_QRING : TPU_COLL_QRD;
  *ran = leg;
  switch (leg) {
    case TPU_COLL_QRING: return qring_allreduce(lead, buf, count, dtype, op);
    case TPU_COLL_QRD: return qrd_allreduce(lead, buf, count, dtype, op);
    case TPU_COLL_RD: return rd_allreduce(lead, buf, count, dtype, op);
    default: return ring_allreduce(lead, buf, count, dtype, op);
  }
}

int intra_bcast(Comm* intra, void* buf, int64_t nbytes, int root) {
  if (intra->arena) return shm_bcast(intra, buf, nbytes, root);
  return bcast_internal(intra, buf, nbytes, root);
}

/* Ring-aware point-to-point send for the hierarchical hops: a comm
 * with shm p2p rings delivers user messages through them (recv_msg
 * waits on the ring), so a bare send_msg_tcp would never match —
 * mirror the engine's SEND routing. */
int p2p_send(Comm* c, int dest, int tag, const void* buf, int64_t nbytes) {
  if (ring_p2p_on(c) && dest != c->rank && dest >= 0 && dest < c->size) {
    bool inlined = false;
    if (shm_try_send(c, dest, tag, buf, nbytes, &inlined)) return 1;
    if (inlined) return 0;
    return send_msg_tcp(c, dest, tag, buf, nbytes);
  }
  return send_msg(c, dest, tag, buf, nbytes);
}

/* Hierarchical allreduce (TPU_COLL_HRING / HTREE): recvbuf already
 * holds this rank's contribution (the dispatch site memcpy'd sendbuf
 * in, like every flat algorithm). */
int hier_allreduce(Comm* c, void* recvbuf, int64_t count, int dtype,
                   int op, int leg_algo) {
  TopoInfo* t = c->topo;
  Comm* intra = t->intra;
  Comm* lead = t->leader;
  const int64_t nbytes = count * dtype_size(dtype);
  /* phase 1: intra-island reduce to the island leader (intra rank 0 —
   * split keyed on rank, leader = lowest member) */
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_REDUCE, t->my_leader, 0, nbytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    int rc = intra->arena
                 ? shm_allreduce_like(intra, recvbuf, recvbuf, count,
                                      dtype, op, 0, false)
                 : serial_reduce0(intra, recvbuf, count, dtype, op);
    if (rc) return 1;
  }
  /* phase 2: leaders allreduce the island sums over the slow tier */
  if (lead && lead->size > 1) {
    int ran = leg_algo;
    ObsScope obs(TPU_OBS_ALLREDUCE, -1, 0, nbytes, leg_algo);
    obs.set_tier(TPU_TIER_INTER);
    int rc = leader_allreduce_leg(lead, recvbuf, count, dtype, op,
                                  leg_algo, &ran);
    obs.set_algo(ran);
    if (ran == TPU_COLL_QRING || ran == TPU_COLL_QRD)
      obs.set_wire(quant_packed_bytes(count));
    if (rc) return 1;
  }
  /* phase 3: the leader broadcasts the result within its island */
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_BCAST, t->my_leader, 0, nbytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra_bcast(intra, recvbuf, nbytes, 0)) return 1;
  }
  return 0;
}

/* Hierarchical allgather: intra gather to the leader (member order),
 * leader-tier ring allgatherv of the variable-size island blocks
 * (uneven islands are first-class: block sizes come from the member
 * map), intra bcast of the assembled payload, then a local scatter
 * from island-block order into world-rank order. */
int hier_allgather(Comm* c, const void* sendbuf, int64_t nbytes,
                   void* recvbuf) {
  TopoInfo* t = c->topo;
  Comm* intra = t->intra;
  Comm* lead = t->leader;
  const int L = t->n_islands;
  char* out = static_cast<char*>(recvbuf);
  /* island-block staging: island i's members are contiguous at ioff[i] */
  std::vector<int64_t> ioff((size_t)L + 1, 0);
  for (int i = 0; i < L; i++)
    ioff[(size_t)i + 1] =
        ioff[(size_t)i] + (int64_t)t->members[(size_t)i].size() * nbytes;
  std::vector<char> stage((size_t)ioff[(size_t)L]);
  char* myblock = stage.data() + ioff[(size_t)t->my_island];
  /* phase 1: intra gather to the leader, member order */
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_GATHER, t->my_leader, 0, nbytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra->arena) {
      if (shm_allgather(intra, sendbuf, nbytes, myblock, 0, false))
        return 1;
    } else if (intra->rank == 0) {
      std::memcpy(myblock, sendbuf, (size_t)nbytes);
      for (int r = 1; r < intra->size; r++)
        if (recv_msg(intra, r, kCollectiveTag,
                     myblock + (int64_t)r * nbytes, nbytes))
          return 1;
    } else {
      if (send_msg(intra, 0, kCollectiveTag, sendbuf, nbytes)) return 1;
    }
  } else {
    std::memcpy(myblock, sendbuf, (size_t)nbytes);
  }
  /* phase 2: leader ring allgatherv of the island blocks (the ring
   * allgather schedule with per-island block sizes) */
  if (lead && lead->size > 1) {
    ObsScope obs(TPU_OBS_ALLGATHER, -1, 0,
                 ioff[(size_t)L] - (ioff[(size_t)t->my_island + 1] -
                                    ioff[(size_t)t->my_island]),
                 TPU_COLL_RING);
    obs.set_tier(TPU_TIER_INTER);
    const int lr = lead->rank;  // == island id (leaders sorted by rank)
    const int next = (lr + 1) % L, prev = (lr - 1 + L) % L;
    for (int round = 0; round < L - 1; round++) {
      int sb = (lr - round + L) % L;
      int rb = (lr - round - 1 + L) % L;
      SendJob job;
      if (async_send(lead, &job, next, kCollectiveTag,
                     stage.data() + ioff[(size_t)sb],
                     ioff[(size_t)sb + 1] - ioff[(size_t)sb]))
        return 1;
      int rc = recv_msg(lead, prev, kCollectiveTag,
                        stage.data() + ioff[(size_t)rb],
                        ioff[(size_t)rb + 1] - ioff[(size_t)rb]);
      if (wait_send(lead, &job) || rc) return 1;
    }
  }
  /* phase 3: the leader broadcasts the whole assembled payload */
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_BCAST, t->my_leader, 0, ioff[(size_t)L],
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra_bcast(intra, stage.data(), ioff[(size_t)L], 0)) return 1;
  }
  /* island-block order -> world-rank order (islands need not be
   * contiguous rank ranges: FAKE_HOSTS partitions are arbitrary) */
  for (int i = 0; i < L; i++)
    for (size_t m = 0; m < t->members[(size_t)i].size(); m++)
      std::memcpy(out + (int64_t)t->members[(size_t)i][m] * nbytes,
                  stage.data() + ioff[(size_t)i] + (int64_t)m * nbytes,
                  (size_t)nbytes);
  return 0;
}

/* Hierarchical alltoall (TPU_COLL_HA2A / HQA2A) — hier_allgather's
 * uneven-island block machinery generalized to the all-pairs exchange:
 *
 *   A. intra-island alltoall of the local chunks (shm arena when the
 *      island shares a host, the pairwise exchange otherwise);
 *   B. intra gather of every member's CROSS-island chunks to the
 *      leader (member order);
 *   C. leader-tier pairwise exchange of the cross-island blocks —
 *      block li->k carries p_li*p_k chunks laid out src-member-major,
 *      variable-size per island pair (uneven islands are first-class);
 *      under `quant_leg` each block rides the int8+scales codec wire
 *      as ONE packed frame (256-element codec blocks span chunk
 *      boundaries inside the frame — the numpy simulator replays the
 *      exact concatenation);
 *   D. intra scatter of the received blocks to their destination
 *      members, then a local reorder into world-rank positions.
 *
 * Only phase C touches the slow tier: (n-p_i)*p_i chunks per LEADER
 * instead of (n-1) chunks per RANK crossing islands.  The exact
 * variant is a pure permutation — output bit-identical to the flat
 * pairwise exchange; quant_leg quantizes exactly the chunks that
 * cross islands (intra chunks stay exact).  Every leg records one obs
 * event labeled with its transport tier inside the whole-op record,
 * like the allreduce twins. */
int h_alltoall(Comm* c, const void* sendbuf, void* recvbuf, int64_t chunk,
               int64_t count, int dtype, bool quant_leg) {
  TopoInfo* t = c->topo;
  Comm* intra = t->intra;
  Comm* lead = t->leader;
  const int L = t->n_islands;
  const int li = t->my_island;
  const std::vector<int32_t>& mine = t->members[(size_t)li];
  const int pi = (int)mine.size();
  const int n = c->size;
  const char* in = static_cast<const char*>(sendbuf);
  char* out = static_cast<char*>(recvbuf);

  /* phase A: intra-island exchange (compact to member order, exchange,
   * scatter back to world positions) */
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_ALLTOALL, -1, 0, chunk * pi,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_RING);
    obs.set_tier(TPU_TIER_INTRA);
    std::vector<char> sa((size_t)(chunk * pi)), ra((size_t)(chunk * pi));
    for (int m = 0; m < pi; m++)
      std::memcpy(sa.data() + (int64_t)m * chunk,
                  in + (int64_t)mine[(size_t)m] * chunk, (size_t)chunk);
    int rc = intra->arena
                 ? shm_alltoall(intra, sa.data(), ra.data(), chunk)
                 : flat_alltoall(intra, sa.data(), ra.data(), chunk);
    if (rc) return 1;
    for (int m = 0; m < pi; m++)
      std::memcpy(out + (int64_t)mine[(size_t)m] * chunk,
                  ra.data() + (int64_t)m * chunk, (size_t)chunk);
  } else {
    std::memcpy(out + (int64_t)c->rank * chunk,
                in + (int64_t)c->rank * chunk, (size_t)chunk);
  }
  if (n == pi) return 0;  // single island: resolve degrades before here

  const bool is_leader = c->rank == t->leaders[(size_t)li];
  const int rloc = intra ? intra->rank : 0;  // my island member index
  const int64_t cross_bytes = (int64_t)(n - pi) * chunk;
  /* xoff[k]: byte offset of island k's run inside any (island-order,
   * skipping li; member-order within) cross buffer */
  std::vector<int64_t> xoff((size_t)L, 0);
  {
    int64_t o = 0;
    for (int k = 0; k < L; k++) {
      if (k == li) continue;
      xoff[(size_t)k] = o;
      o += (int64_t)t->members[(size_t)k].size() * chunk;
    }
  }
  /* my cross-island chunks, (island k != li, dst member t_) order */
  std::vector<char> cross((size_t)cross_bytes);
  {
    int64_t off = 0;
    for (int k = 0; k < L; k++) {
      if (k == li) continue;
      for (int32_t w : t->members[(size_t)k]) {
        std::memcpy(cross.data() + off, in + (int64_t)w * chunk,
                    (size_t)chunk);
        off += chunk;
      }
    }
  }

  /* phase B: gather the members' cross buffers at the leader, member
   * order (G[m] = member m's cross buffer) */
  std::vector<char> G;
  if (is_leader) G.resize((size_t)(cross_bytes * pi));
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_GATHER, t->my_leader, 0, cross_bytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra->arena) {
      if (shm_allgather(intra, cross.data(), cross_bytes, G.data(), 0,
                        false))
        return 1;
    } else if (rloc == 0) {
      std::memcpy(G.data(), cross.data(), (size_t)cross_bytes);
      for (int r = 1; r < intra->size; r++)
        if (recv_msg(intra, r, kCollectiveTag,
                     G.data() + (int64_t)r * cross_bytes, cross_bytes))
          return 1;
    } else {
      if (send_msg(intra, 0, kCollectiveTag, cross.data(), cross_bytes))
        return 1;
    }
  } else if (is_leader) {
    std::memcpy(G.data(), cross.data(), (size_t)cross_bytes);
  }

  /* phases C on the leaders: reorder G into per-dest-island blocks,
   * trade blocks pairwise, reorder into per-member scatter payloads */
  std::vector<char> D;  // phase D payload: pi members x cross_bytes
  if (is_leader) {
    /* boff[k]: byte offset of the island-k block in the send (and,
     * p_i*p_k being symmetric in the pair, receive) staging buffer */
    std::vector<int64_t> boff((size_t)L + 1, 0);
    for (int k = 0; k < L; k++)
      boff[(size_t)k + 1] =
          boff[(size_t)k] +
          (k == li ? 0
                   : (int64_t)pi * t->members[(size_t)k].size() * chunk);
    std::vector<char> sblk((size_t)boff[(size_t)L]);
    std::vector<char> rblk((size_t)boff[(size_t)L]);
    for (int k = 0; k < L; k++) {
      if (k == li) continue;
      const int pk = (int)t->members[(size_t)k].size();
      for (int m = 0; m < pi; m++)
        std::memcpy(sblk.data() + boff[(size_t)k] +
                        (int64_t)m * pk * chunk,
                    G.data() + (int64_t)m * cross_bytes + xoff[(size_t)k],
                    (size_t)((int64_t)pk * chunk));
    }
    {
      ObsScope obs(TPU_OBS_ALLTOALL, -1, 0, boff[(size_t)L],
                   quant_leg ? TPU_COLL_QA2A : TPU_COLL_RING);
      obs.set_tier(TPU_TIER_INTER);
      if (quant_leg) {
        int64_t wire = 0;
        for (int k = 0; k < L; k++)
          if (k != li)
            wire += quant_packed_bytes((boff[(size_t)k + 1] -
                                        boff[(size_t)k]) /
                                       dtype_size(dtype));
        obs.set_wire(wire);
      }
      for (int round = 1; round < L; round++) {
        const int kd = (li + round) % L;
        const int ks = (li - round + L) % L;
        const int64_t snb = boff[(size_t)kd + 1] - boff[(size_t)kd];
        const int64_t rnb = boff[(size_t)ks + 1] - boff[(size_t)ks];
        SendJob job;
        int rc;
        if (!quant_leg) {
          if (async_send(lead, &job, kd, kCollectiveTag,
                         sblk.data() + boff[(size_t)kd], snb))
            return 1;
          rc = recv_msg(lead, ks, kCollectiveTag,
                        rblk.data() + boff[(size_t)ks], rnb);
        } else {
          /* one codec frame per block: load the whole block to f32,
           * pack (codec 256-blocks span chunk boundaries), ship */
          const int64_t scount = snb / dtype_size(dtype);
          const int64_t rcount = rnb / dtype_size(dtype);
          std::vector<char>& qs =
              quant_tls_buf(0, quant_packed_bytes(scount));
          if (dtype == TPU_F32) {
            quant_pack_f32(reinterpret_cast<const float*>(
                               sblk.data() + boff[(size_t)kd]),
                           scount, qs.data());
          } else {
            std::vector<char>& st = quant_tls_buf(1, 4 * scount);
            quant_load_f32(sblk.data() + boff[(size_t)kd], dtype, scount,
                           reinterpret_cast<float*>(st.data()));
            quant_pack_f32(reinterpret_cast<const float*>(st.data()),
                           scount, qs.data());
          }
          if (async_send(lead, &job, kd, kCollectiveTag, qs.data(),
                         quant_packed_bytes(scount)))
            return 1;
          if (dtype == TPU_F32) {
            rc = recv_quant_msg(lead, ks, rcount,
                                reinterpret_cast<float*>(
                                    rblk.data() + boff[(size_t)ks]),
                                false);
          } else {
            std::vector<char>& st = quant_tls_buf(1, 4 * rcount);
            float* stf = reinterpret_cast<float*>(st.data());
            rc = recv_quant_msg(lead, ks, rcount, stf, false);
            if (!rc)
              quant_store_f32(stf, dtype, rcount,
                              rblk.data() + boff[(size_t)ks]);
          }
        }
        if (wait_send(lead, &job) || rc) return 1;
      }
    }
    /* per-member scatter payloads: member t_ gets (island k != li, src
     * member m) order — the same run layout as `cross`, so xoff
     * addresses both */
    D.resize((size_t)(cross_bytes * pi));
    for (int k = 0; k < L; k++) {
      if (k == li) continue;
      const int pk = (int)t->members[(size_t)k].size();
      for (int m = 0; m < pk; m++)
        for (int t_ = 0; t_ < pi; t_++)
          std::memcpy(D.data() + (int64_t)t_ * cross_bytes +
                          xoff[(size_t)k] + (int64_t)m * chunk,
                      rblk.data() + boff[(size_t)k] +
                          ((int64_t)m * pi + t_) * chunk,
                      (size_t)chunk);
    }
  }

  /* phase D: scatter each member its cross chunks */
  std::vector<char> stage((size_t)cross_bytes);
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_SCATTER, t->my_leader, 0, cross_bytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra->arena) {
      if (shm_scatter(intra, D.data(), stage.data(), cross_bytes, 0))
        return 1;
    } else if (rloc == 0) {
      std::memcpy(stage.data(), D.data(), (size_t)cross_bytes);
      for (int r = 1; r < intra->size; r++)
        if (p2p_send(intra, r, kCollectiveTag,
                     D.data() + (int64_t)r * cross_bytes, cross_bytes))
          return 1;
    } else {
      if (recv_msg(intra, 0, kCollectiveTag, stage.data(), cross_bytes))
        return 1;
    }
  } else {
    std::memcpy(stage.data(), D.data(), (size_t)cross_bytes);
  }
  /* (island, src member) order -> world-rank positions */
  {
    int64_t off = 0;
    for (int k = 0; k < L; k++) {
      if (k == li) continue;
      for (int32_t w : t->members[(size_t)k]) {
        std::memcpy(out + (int64_t)w * chunk, stage.data() + off,
                    (size_t)chunk);
        off += chunk;
      }
    }
  }
  return 0;
}

/* Hierarchical bcast: root's island first (so its leader holds the
 * payload), then the leader tier, then the remaining islands. */
int hier_bcast(Comm* c, void* buf, int64_t nbytes, int root) {
  TopoInfo* t = c->topo;
  Comm* intra = t->intra;
  Comm* lead = t->leader;
  const int ri = t->island_of[(size_t)root];
  if (t->my_island == ri && intra && intra->size > 1) {
    const auto& mem = t->members[(size_t)ri];
    int rloc = 0;
    for (size_t m = 0; m < mem.size(); m++)
      if (mem[m] == root) rloc = (int)m;
    ObsScope obs(TPU_OBS_BCAST, root, 0, nbytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra_bcast(intra, buf, nbytes, rloc)) return 1;
  }
  if (lead && lead->size > 1) {
    ObsScope obs(TPU_OBS_BCAST, ri, 0, nbytes, TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTER);
    if (bcast_internal(lead, buf, nbytes, ri)) return 1;
  }
  if (t->my_island != ri && intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_BCAST, t->my_leader, 0, nbytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    if (intra_bcast(intra, buf, nbytes, 0)) return 1;
  }
  return 0;
}

/* Hierarchical reduce: intra reduce to the leaders, leader-tier serial
 * reduce to the root island's leader, then a final intra hop to the
 * root when it is not its island's leader.  The flat contract is
 * preserved: only the root's recvbuf holds the reduction; every other
 * rank's recvbuf keeps its input copy (leaders fold into a scratch
 * accumulator, never into the caller's buffer). */
int hier_reduce(Comm* c, const void* sendbuf, void* recvbuf, int64_t count,
                int dtype, int op, int root) {
  TopoInfo* t = c->topo;
  Comm* intra = t->intra;
  Comm* lead = t->leader;
  const int64_t nbytes = count * dtype_size(dtype);
  const int ri = t->island_of[(size_t)root];
  const bool am_leader = t->my_leader == c->rank;
  /* leaders accumulate island (then global) sums in scratch */
  std::vector<char> acc;
  if (am_leader) {
    acc.resize((size_t)nbytes);
    std::memcpy(acc.data(), sendbuf, (size_t)nbytes);
  }
  if (recvbuf != sendbuf) std::memcpy(recvbuf, sendbuf, (size_t)nbytes);
  /* phase 1: intra reduce to the leader (member-order association) */
  if (intra && intra->size > 1) {
    ObsScope obs(TPU_OBS_REDUCE, t->my_leader, 0, nbytes,
                 intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    int rc;
    if (intra->arena) {
      rc = shm_allreduce_like(intra, sendbuf, am_leader ? acc.data()
                                                        : recvbuf,
                              count, dtype, op, 0, false);
    } else if (am_leader) {
      rc = serial_reduce0(intra, acc.data(), count, dtype, op);
    } else {
      rc = send_msg(intra, 0, kCollectiveTag, sendbuf, nbytes);
    }
    if (rc) return 1;
  }
  /* phase 2: leaders reduce to the root island's leader (leader-rank
   * order, root island's own sum first) */
  if (lead && lead->size > 1) {
    ObsScope obs(TPU_OBS_REDUCE, ri, 0, nbytes, TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTER);
    if (lead->rank == ri) {
      std::vector<char> tmp((size_t)nbytes);
      for (int r = 0; r < lead->size; r++) {
        if (r == ri) continue;
        if (recv_msg(lead, r, kCollectiveTag, tmp.data(), nbytes))
          return 1;
        if (combine(acc.data(), tmp.data(), count, dtype, op, c)) return 1;
      }
    } else {
      if (send_msg(lead, ri, kCollectiveTag, acc.data(), nbytes)) return 1;
    }
  }
  /* phase 3: land the result in the root's recvbuf */
  const int root_leader = t->leaders[(size_t)ri];
  if (root == root_leader) {
    if (c->rank == root) std::memcpy(recvbuf, acc.data(), (size_t)nbytes);
    return 0;
  }
  if (c->rank == root_leader || c->rank == root) {
    ObsScope obs(TPU_OBS_SEND, root, 0, nbytes,
                 intra && intra->arena ? TPU_COLL_SHM : TPU_COLL_TREE);
    obs.set_tier(TPU_TIER_INTRA);
    const auto& mem = t->members[(size_t)ri];
    int rloc = 0;
    for (size_t m = 0; m < mem.size(); m++)
      if (mem[m] == root) rloc = (int)m;
    if (c->rank == root_leader) {
      if (p2p_send(intra, rloc, kCollectiveTag, acc.data(), nbytes))
        return 1;
    } else {
      if (recv_msg(intra, 0, kCollectiveTag, recvbuf, nbytes)) return 1;
    }
  }
  return 0;
}

/* ================= async progress engine =================
 *
 * One dedicated progress thread per socket-owning communicator drives
 * a bounded lock-free (SPSC: posts are serialized by the comm lock,
 * the progress thread is the only consumer) submission queue of op
 * descriptors and a per-descriptor completion futex:
 *
 * - small sends DETACH: the payload is copied into the descriptor and
 *   the caller returns immediately — the buffered-send semantics the
 *   static verifier's match model (analysis/_match.py) already
 *   assumes.  Ordering is preserved because the queue drains strictly
 *   in posted order, exactly the serialization the comm lock gave the
 *   inline path;
 * - every other op posts and PARKS on its completion futex when the
 *   queue is non-empty (an earlier op is still in flight — running it
 *   inline would reorder the channel), and runs INLINE on the calling
 *   thread when the engine is idle (no context-switch tax on the
 *   latency path; bit-for-bit the historic behavior);
 * - adjacent detached sends to the same peer coalesce into one
 *   kCoalescedTag wire frame (threshold MPI4JAX_TPU_COALESCE_BYTES;
 *   the receive side splits transparently, tags preserved);
 * - deadlines are measured from POST time (g_dl_post_anchor): time
 *   spent queued behind a wedged op counts against the job deadline,
 *   and abort poison is consumed on the progress thread exactly as it
 *   was inline (the bodies are the same code).
 *
 * MPI4JAX_TPU_PROGRESS_THREAD=0 disables the engine entirely: every
 * op executes inline under the comm lock, the pre-engine behavior. */

bool progress_thread_on() {
  static bool v = [] {
    const char* e = std::getenv("MPI4JAX_TPU_PROGRESS_THREAD");
    if (!e || !e[0]) return true;
    if (!std::strcmp(e, "0") || !std::strcmp(e, "false") ||
        !std::strcmp(e, "off") || !std::strcmp(e, "no"))
      return false;
    if (!std::strcmp(e, "1") || !std::strcmp(e, "true") ||
        !std::strcmp(e, "on") || !std::strcmp(e, "yes"))
      return true;
    std::fprintf(stderr,
                 "tpucomm: cannot parse MPI4JAX_TPU_PROGRESS_THREAD=%s\n", e);
    std::exit(2);
  }();
  return v;
}

int64_t parse_env_bytes(const char* name, int64_t dflt, int64_t lo,
                        int64_t hi) {
  const char* e = std::getenv(name);
  if (!e || !e[0]) return dflt;
  char* end = nullptr;
  long long v = std::strtoll(e, &end, 10);
  if (end == e || *end) {
    std::fprintf(stderr, "tpucomm: cannot parse %s=%s\n", name, e);
    std::exit(2);  // a typo'd knob must not silently change behavior
  }
  if (v < lo) v = lo;
  if (v > hi) v = hi;
  return (int64_t)v;
}

/* sends <= this coalesce when adjacent in posted order (0 = off) */
int64_t coalesce_bytes() {
  static int64_t v =
      parse_env_bytes("MPI4JAX_TPU_COALESCE_BYTES", 4096, 0, 64 * 1024);
  return v;
}

/* submission-queue capacity in descriptors */
int64_t queue_depth() {
  static int64_t v = [] {
    int64_t d = parse_env_bytes("MPI4JAX_TPU_QUEUE_DEPTH", 1024, 16,
                                1 << 16);
    int64_t p = 16;
    while (p < d) p <<= 1;
    return p;
  }();
  return v;
}

/* sends up to this size are copied into the descriptor and detached */
int64_t detach_threshold() {
  static int64_t v = std::max<int64_t>(kEagerBytes, coalesce_bytes());
  return v;
}

constexpr int kCoalesceMaxRun = 32;   // sends merged into one frame, max
constexpr uint32_t kOpStatus = 1;     // flags: status-reporting variant

struct EngineOp {
  int32_t kind = 0;            // TpuObsOp code
  uint32_t flags = 0;
  Comm* comm = nullptr;
  const void* sbuf = nullptr;
  void* rbuf = nullptr;
  int64_t snb = 0, rnb = 0;    // payload bytes (send / recv side)
  int64_t count = 0;           // elements (reductions)
  int dtype = 0, rop = 0;
  int peer = -1, peer2 = -1;   // dest/root/lo , source/hi
  int tag = 0, tag2 = 0;
  int algo = TPU_COLL_AUTO;
  int32_t* out_src = nullptr;  // status out-params (parked ops only)
  int32_t* out_tag = nullptr;
  int64_t* out_count = nullptr;
  double t_post = -1;
  bool detached = false;
  std::vector<char> owned;     // copied payload of a detached send
  std::atomic<int32_t> state{0};  // 0 = queued, 1 = done (futex word)
  int rc = 0;
};

struct Engine {
  std::vector<EngineOp*> slots;
  uint64_t cap = 0;
  std::atomic<uint64_t> head{0};   // produced (posting side)
  std::atomic<uint64_t> tail{0};   // consumed (progress thread)
  std::atomic<int32_t> hseq{0};    // futex: progress thread parks here
  std::atomic<int32_t> tseq{0};    // futex: full-queue posters park here
  std::atomic<int64_t> inflight{0};
  std::atomic<int32_t> stop{0};
  std::atomic<int32_t> sticky{0};  // a detached op failed
  std::thread thr;
  std::vector<char> scratch;       // coalesced frame assembly
};

/* Execute one descriptor: the op bodies, verbatim from the pre-engine
 * public entry points, wrapped in the same ObsScope/LogScope (now fed
 * the post timestamp so events carry the dispatch split). */
int engine_run_body(EngineOp* o) {
  Comm* c = o->comm;
  const double tp = o->t_post;
  switch (o->kind) {
    case TPU_OBS_SEND: {
      ObsScope obs(TPU_OBS_SEND, o->peer, o->tag, o->snb, -1, tp);
      LogScope log(c->rank, "Send", [&] {
        return "to " + std::to_string(o->peer) + " (" +
               std::to_string(o->snb) + " bytes, tag " +
               std::to_string(o->tag) + ")";
      });
      if (ring_p2p_on(c) && o->peer != c->rank && o->peer >= 0 &&
          o->peer < c->size) {
        bool inlined = false;
        if (shm_try_send(c, o->peer, o->tag, o->sbuf, o->snb, &inlined))
          return 1;
        if (inlined) return 0;
        return send_msg_tcp(c, o->peer, o->tag, o->sbuf, o->snb);
      }
      return send_msg(c, o->peer, o->tag, o->sbuf, o->snb);
    }
    case TPU_OBS_RECV: {
      ObsScope obs(TPU_OBS_RECV, o->peer2, o->tag, o->rnb, -1, tp);
      LogScope log(c->rank, "Recv", [&] {
        return "from " + std::to_string(o->peer2) + " (" +
               std::to_string(o->rnb) + " bytes, tag " +
               std::to_string(o->tag) +
               ((o->flags & kOpStatus) ? ", status)" : ")");
      });
      if (o->flags & kOpStatus)
        return recv_msg_status(c, o->peer2, o->tag, o->rbuf, o->rnb,
                               o->out_src, o->out_tag, o->out_count);
      return recv_msg(c, o->peer2, o->tag, o->rbuf, o->rnb);
    }
    case TPU_OBS_SENDRECV: {
      ObsScope obs(TPU_OBS_SENDRECV, o->peer, o->tag, o->snb + o->rnb, -1,
                   tp);
      LogScope log(c->rank, "Sendrecv", [&] {
        return "to " + std::to_string(o->peer) + " from " +
               std::to_string(o->peer2) +
               ((o->flags & kOpStatus) ? " (status)" : "");
      });
      SendJob job;
      if (async_send(c, &job, o->peer, o->tag, o->sbuf, o->snb)) return 1;
      int recv_rc =
          (o->flags & kOpStatus)
              ? recv_msg_status(c, o->peer2, o->tag2, o->rbuf, o->rnb,
                                o->out_src, o->out_tag, o->out_count)
              : recv_msg(c, o->peer2, o->tag2, o->rbuf, o->rnb);
      return wait_send(c, &job) || recv_rc;
    }
    case TPU_OBS_SHIFT2: {
      ObsScope obs(TPU_OBS_SHIFT2, o->peer2, o->tag, 2 * o->snb, -1, tp);
      LogScope log(c->rank, "Shift2", [&] {
        return std::to_string(o->snb) + " bytes, lo " +
               std::to_string(o->peer) + " hi " + std::to_string(o->peer2);
      });
      const int lo = o->peer, hi = o->peer2;
      const int64_t strip_nbytes = o->snb;
      const int tag = o->tag;
      const char* in = static_cast<const char*>(o->sbuf);
      char* out = static_cast<char*>(o->rbuf);
      const char* to_lo = in;
      const char* to_hi = in + strip_nbytes;
      char* from_lo = out;
      char* from_hi = out + strip_nbytes;
      if (lo == c->rank && hi == c->rank) {
        std::memcpy(from_lo, to_hi, strip_nbytes);
        std::memcpy(from_hi, to_lo, strip_nbytes);
        return 0;
      }
      SendJob jlo, jhi;
      bool sent_lo = false, sent_hi = false;
      if (lo >= 0) {
        if (async_send(c, &jlo, lo, tag, to_lo, strip_nbytes)) return 1;
        sent_lo = true;
      } else {
        std::memcpy(from_lo, to_hi, strip_nbytes);  // wall: passthrough
      }
      if (hi >= 0) {
        if (async_send(c, &jhi, hi, tag + 1, to_hi, strip_nbytes)) {
          if (sent_lo) wait_send(c, &jlo);
          return 1;
        }
        sent_hi = true;
      } else {
        std::memcpy(from_hi, to_lo, strip_nbytes);
      }
      int rc = 0;
      if (hi >= 0) rc |= recv_msg(c, hi, tag, from_hi, strip_nbytes);
      if (lo >= 0) rc |= recv_msg(c, lo, tag + 1, from_lo, strip_nbytes);
      if (sent_lo) rc |= wait_send(c, &jlo);
      if (sent_hi) rc |= wait_send(c, &jhi);
      return rc;
    }
    case TPU_OBS_BARRIER: {
      ObsScope obs(TPU_OBS_BARRIER, -1, 0, 0, c->arena ? TPU_COLL_SHM : -1,
                   tp);
      LogScope log(c->rank, "Barrier", [&] { return std::string(); });
      if (c->arena) return shm_barrier_op(c);
      uint8_t token = 1;
      for (int dist = 1; dist < c->size; dist *= 2) {
        int dest = (c->rank + dist) % c->size;
        int src = (c->rank - dist + c->size) % c->size;
        uint8_t got = 0;
        SendJob job;
        if (async_send(c, &job, dest, kCollectiveTag, &token, 1)) return 1;
        int recv_rc = recv_msg(c, src, kCollectiveTag, &got, 1);
        if (wait_send(c, &job) || recv_rc) return 1;
      }
      return 0;
    }
    case TPU_OBS_BCAST: {
      ObsScope obs(TPU_OBS_BCAST, o->peer, 0, o->rnb,
                   c->arena ? TPU_COLL_SHM : -1, tp);
      LogScope log(c->rank, "Bcast", [&] {
        return std::to_string(o->rnb) + " bytes, root " +
               std::to_string(o->peer);
      });
      if (c->arena) return shm_bcast(c, o->rbuf, o->rnb, o->peer);
      /* multi-island worlds route large bcasts through the island
       * leaders (MPI4JAX_TPU_HIER; force drops the size floor, deny
       * the routing) — only the inter-island leg rides the slow tier */
      if (hier_routable(c, o->rnb))
        return hier_bcast(c, o->rbuf, o->rnb, o->peer);
      return bcast_internal(c, o->rbuf, o->rnb, o->peer);
    }
    case TPU_OBS_GATHER: {
      ObsScope obs(TPU_OBS_GATHER, o->peer, 0, o->snb,
                   c->arena ? TPU_COLL_SHM : -1, tp);
      LogScope log(c->rank, "Gather", [&] {
        return std::to_string(o->snb) + " bytes, root " +
               std::to_string(o->peer);
      });
      const int root = o->peer;
      if (c->arena)
        return shm_allgather(c, o->sbuf, o->snb, o->rbuf, root, false);
      if (c->rank == root) {
        char* out = static_cast<char*>(o->rbuf);
        std::memcpy(out + (int64_t)root * o->snb, o->sbuf, o->snb);
        for (int r = 0; r < c->size; r++) {
          if (r == root) continue;
          if (recv_msg(c, r, kCollectiveTag, out + (int64_t)r * o->snb,
                       o->snb))
            return 1;
        }
        return 0;
      }
      return send_msg(c, root, kCollectiveTag, o->sbuf, o->snb);
    }
    case TPU_OBS_SCATTER: {
      ObsScope obs(TPU_OBS_SCATTER, o->peer, 0, o->rnb,
                   c->arena ? TPU_COLL_SHM : -1, tp);
      LogScope log(c->rank, "Scatter", [&] {
        return std::to_string(o->rnb) + " bytes, root " +
               std::to_string(o->peer);
      });
      const int root = o->peer;
      if (c->arena) return shm_scatter(c, o->sbuf, o->rbuf, o->rnb, root);
      if (c->rank == root) {
        const char* in = static_cast<const char*>(o->sbuf);
        std::memcpy(o->rbuf, in + (int64_t)root * o->rnb, o->rnb);
        for (int r = 0; r < c->size; r++) {
          if (r == root) continue;
          if (send_msg(c, r, kCollectiveTag, in + (int64_t)r * o->rnb,
                       o->rnb))
            return 1;
        }
        return 0;
      }
      return recv_msg(c, root, kCollectiveTag, o->rbuf, o->rnb);
    }
    case TPU_OBS_ALLGATHER: {
      int chosen =
          resolve_coll_algo(c, TPU_OPKIND_ALLGATHER, o->snb, 0, o->algo);
      ObsScope obs(TPU_OBS_ALLGATHER, -1, 0, o->snb, chosen, tp);
      LogScope log(c->rank, "Allgather", [&] {
        return std::to_string(o->snb) + " bytes algo " +
               coll_algo_name(chosen);
      });
      if (chosen == TPU_COLL_SHM)
        return shm_allgather(c, o->sbuf, o->snb, o->rbuf, 0, true);
      switch (chosen) {
        case TPU_COLL_TREE:
          return tree_allgather(c, o->sbuf, o->snb, o->rbuf);
        case TPU_COLL_RD:
          return rd_allgather(c, o->sbuf, o->snb, o->rbuf);
        case TPU_COLL_HRING:
        case TPU_COLL_HTREE:
          return hier_allgather(c, o->sbuf, o->snb, o->rbuf);
        default:
          return ring_allgather(c, o->sbuf, o->snb, o->rbuf);
      }
    }
    case TPU_OBS_ALLTOALL: {
      /* count > 0 marks the typed entry (tpucomm_alltoall_algo); the
       * legacy byte-chunk tpucomm_alltoall has no dtype context and
       * always resolves to the exact schedules. */
      const bool typed = o->count > 0;
      int64_t chunk = o->snb;
      if (typed) {
        int64_t esize = dtype_size(o->dtype);
        if (esize == 0) FAIL(c, "bad dtype %d", o->dtype);
        chunk = o->count * esize;
      }
      int chosen =
          resolve_coll_algo(c, TPU_OPKIND_ALLTOALL, chunk * c->size,
                            o->count, o->algo, typed ? o->dtype : -1);
      ObsScope obs(TPU_OBS_ALLTOALL, -1, 0, chunk * c->size, chosen, tp);
      if (chosen == TPU_COLL_QA2A)
        obs.set_wire(quant_packed_bytes(o->count) * c->size);
      LogScope log(c->rank, "Alltoall", [&] {
        return std::to_string(chunk) + " bytes/chunk " +
               coll_algo_name(chosen);
      });
      switch (chosen) {
        case TPU_COLL_SHM:
          return shm_alltoall(c, o->sbuf, o->rbuf, chunk);
        case TPU_COLL_QA2A:
          return q_alltoall(c, o->sbuf, o->rbuf, o->count, o->dtype);
        case TPU_COLL_HA2A:
          return h_alltoall(c, o->sbuf, o->rbuf, chunk, o->count,
                            o->dtype, false);
        case TPU_COLL_HQA2A:
          return h_alltoall(c, o->sbuf, o->rbuf, chunk, o->count,
                            o->dtype, true);
        default:
          return flat_alltoall(c, o->sbuf, o->rbuf, chunk);
      }
    }
    case TPU_OBS_ALLREDUCE: {
      int64_t esize = dtype_size(o->dtype);
      if (esize == 0) FAIL(c, "bad dtype %d", o->dtype);
      int64_t nbytes = o->count * esize;
      int chosen = resolve_coll_algo(c, TPU_OPKIND_ALLREDUCE, nbytes,
                                     o->count, o->algo, o->dtype, o->rop);
      ObsScope obs(TPU_OBS_ALLREDUCE, -1, 0, nbytes, chosen, tp);
      if (chosen == TPU_COLL_QRING || chosen == TPU_COLL_QRD)
        obs.set_wire(quant_packed_bytes(o->count));
      LogScope log(c->rank, "Allreduce", [&] {
        return std::to_string(o->count) + " elems dtype " +
               std::to_string(o->dtype) + " op " + std::to_string(o->rop) +
               " algo " + coll_algo_name(chosen);
      });
      if (c->size == 1) {
        if (o->rbuf != o->sbuf) std::memcpy(o->rbuf, o->sbuf, nbytes);
        return 0;
      }
      if (chosen == TPU_COLL_SHM)
        return shm_allreduce_like(c, o->sbuf, o->rbuf, o->count, o->dtype,
                                  o->rop, 0, true);
      if (o->rbuf != o->sbuf) std::memcpy(o->rbuf, o->sbuf, nbytes);
      switch (chosen) {
        case TPU_COLL_RING:
          return ring_allreduce(c, o->rbuf, o->count, o->dtype, o->rop);
        case TPU_COLL_RD:
          return rd_allreduce(c, o->rbuf, o->count, o->dtype, o->rop);
        case TPU_COLL_QRING:
          return qring_allreduce(c, o->rbuf, o->count, o->dtype, o->rop);
        case TPU_COLL_QRD:
          return qrd_allreduce(c, o->rbuf, o->count, o->dtype, o->rop);
        case TPU_COLL_HRING:
          return hier_allreduce(c, o->rbuf, o->count, o->dtype, o->rop,
                                TPU_COLL_RING);
        case TPU_COLL_HTREE:
          return hier_allreduce(c, o->rbuf, o->count, o->dtype, o->rop,
                                TPU_COLL_RD);
        default:
          return tree_allreduce(c, o->rbuf, o->count, o->dtype, o->rop);
      }
    }
    case TPU_OBS_REDUCE: {
      int64_t esize = dtype_size(o->dtype);
      ObsScope obs(TPU_OBS_REDUCE, o->peer, 0, o->count * esize,
                   c->arena && c->size > 1 ? TPU_COLL_SHM : -1, tp);
      LogScope log(c->rank, "Reduce", [&] {
        return std::to_string(o->count) + " elems, root " +
               std::to_string(o->peer);
      });
      if (esize == 0) FAIL(c, "bad dtype %d", o->dtype);
      const int root = o->peer;
      if (c->arena && c->size > 1) {
        if (c->rank != root && o->rbuf != o->sbuf)
          std::memcpy(o->rbuf, o->sbuf, o->count * esize);
        return shm_allreduce_like(c, o->sbuf, o->rbuf, o->count, o->dtype,
                                  o->rop, root, false);
      }
      int64_t nbytes = o->count * esize;
      /* multi-island worlds fold within each island first, then across
       * the leaders (same gate as bcast; float association changes
       * like any algorithm switch — docs/usage.md) */
      if (hier_routable(c, nbytes))
        return hier_reduce(c, o->sbuf, o->rbuf, o->count, o->dtype,
                           o->rop, root);
      if (c->rank == root) {
        if (o->rbuf != o->sbuf) std::memcpy(o->rbuf, o->sbuf, nbytes);
        std::vector<char> tmp(nbytes);
        for (int r = 0; r < c->size; r++) {
          if (r == root) continue;
          if (recv_msg(c, r, kCollectiveTag, tmp.data(), nbytes)) return 1;
          if (combine(o->rbuf, tmp.data(), o->count, o->dtype, o->rop, c))
            return 1;
        }
        return 0;
      }
      if (o->rbuf != o->sbuf) std::memcpy(o->rbuf, o->sbuf, nbytes);
      return send_msg(c, root, kCollectiveTag, o->rbuf, nbytes);
    }
    case TPU_OBS_SCAN: {
      int64_t esize = dtype_size(o->dtype);
      ObsScope obs(TPU_OBS_SCAN, -1, 0, o->count * esize,
                   c->arena && c->size > 1 ? TPU_COLL_SHM : -1, tp);
      LogScope log(c->rank, "Scan",
                   [&] { return std::to_string(o->count) + " elems"; });
      if (esize == 0) FAIL(c, "bad dtype %d", o->dtype);
      if (c->arena && c->size > 1)
        return shm_scan(c, o->sbuf, o->rbuf, o->count, o->dtype, o->rop);
      int64_t nbytes = o->count * esize;
      if (o->rbuf != o->sbuf) std::memcpy(o->rbuf, o->sbuf, nbytes);
      if (c->rank > 0) {
        std::vector<char> tmp(nbytes);
        if (recv_msg(c, c->rank - 1, kCollectiveTag, tmp.data(), nbytes))
          return 1;
        std::vector<char> mine(nbytes);
        std::memcpy(mine.data(), o->rbuf, nbytes);
        std::memcpy(o->rbuf, tmp.data(), nbytes);
        if (combine(o->rbuf, mine.data(), o->count, o->dtype, o->rop, c))
          return 1;
      }
      if (c->rank < c->size - 1) {
        if (send_msg(c, c->rank + 1, kCollectiveTag, o->rbuf, nbytes))
          return 1;
      }
      return 0;
    }
    default:
      FAIL(c, "unknown engine op kind %d", o->kind);
  }
}

/* Socket-liveness check for the drain-loop merge predicates.  Armed
 * links must snapshot through link_fd (the recovery thread rewires
 * socks under the link locks); an fd of -1 mid-recovery just demotes
 * the op to the single-descriptor path, whose link_send_frame joins
 * the recovery instead of racing it. */
static inline int engine_peer_fd(const EngineOp* o) {
  return retry_armed() ? link_fd(o->comm, o->peer)
                       : o->comm->socks[o->peer];
}

/* True when this descriptor may merge into a coalesced frame. */
bool coalescible(const EngineOp* o) {
  return o->kind == TPU_OBS_SEND && o->detached && coalesce_bytes() > 0 &&
         o->snb <= coalesce_bytes() && o->peer != o->comm->rank &&
         o->peer >= 0 && o->peer < o->comm->size &&
         !ring_p2p_on(o->comm) && engine_peer_fd(o) >= 0;
}

/* One obs event per logical send of a batched drain-loop write (the
 * whole burst's syscalls are attributed to the FIRST event so per-op
 * sums stay exact). */
void engine_obs_burst(EngineOp** ops, int n, int dest, double tw0,
                      int64_t sys0) {
  if (!g_obs_on.load(std::memory_order_relaxed)) return;
  double tw1 = now_s();
  int64_t ds = g_syscalls.load(std::memory_order_relaxed) - sys0;
  for (int i = 0; i < n; i++) {
    TpuObsEvent ev{};
    ev.op = TPU_OBS_SEND;
    ev.peer = dest;
    ev.tag = ops[i]->tag;
    ev.nbytes = ops[i]->snb;
    ev.wire_bytes = ops[i]->snb;
    ev.algo = -1;
    ev.t_start = ops[i]->t_post;
    ev.dur_s = tw1 - ops[i]->t_post;
    ev.queue_s = tw0 - ops[i]->t_post;
    if (ev.queue_s < 0) ev.queue_s = 0;
    if (ev.queue_s > ev.dur_s) ev.queue_s = ev.dur_s;
    ev.syscalls =
        i == 0 ? (int32_t)std::min<int64_t>(ds, INT32_MAX) : 0;
    obs_append(ev);
  }
}

/* Write a run of adjacent detached sends (same comm, same peer) as ONE
 * kCoalescedTag frame.  Tags and sizes ride as per-message sub-headers;
 * the receive side splits them back apart.  Returns the shared rc.
 * The outer header is assembled INTO the scratch buffer, so the whole
 * container leaves in one write (one SQE under uring) — byte-identical
 * wire to the historic header-then-body write pair. */
int engine_write_coalesced(Engine* e, EngineOp** ops, int n) {
  Comm* c = ops[0]->comm;
  const int dest = ops[0]->peer;
  const bool armed = retry_armed();
  int64_t total = 0;
  for (int i = 0; i < n; i++) total += (int64_t)sizeof(MsgHeader) + ops[i]->snb;
  /* armed: the outer header is stamped (seq + epoch + CRC) inside
   * link_send_frame, so only the sub-frames are assembled here; the
   * whole container is then one retained, replayable wire frame */
  e->scratch.resize((size_t)(total + (armed ? 0 : (int64_t)sizeof(MsgHeader))));
  char* p = e->scratch.data();
  if (!armed) {
    MsgHeader outer{total, kCoalescedTag, c->comm_id};
    std::memcpy(p, &outer, sizeof(outer));
    p += sizeof(outer);
  }
  for (int i = 0; i < n; i++) {
    /* one injector hit per LOGICAL send: MPI4JAX_TPU_FAULT's after=N
     * counts user sends, not wire frames, so a fault lands at the same
     * op index with coalescing on or off */
    fault_fire(c, g_job_rank, FP_SEND, "send",
               armed ? link_fd(c, dest) : -1);
    MsgHeader sh{ops[i]->snb, ops[i]->tag, c->comm_id};
    std::memcpy(p, &sh, sizeof(sh));
    p += sizeof(sh);
    std::memcpy(p, ops[i]->sbuf, (size_t)ops[i]->snb);
    p += ops[i]->snb;
  }
  LogScope log(c->rank, "SendCoalesced", [&] {
    return "to " + std::to_string(dest) + " (" + std::to_string(n) +
           " msgs, " + std::to_string(total) + " bytes)";
  });
  g_dl_post_anchor = ops[0]->t_post;
  double tw0 = now_s();
  int64_t sys0 = g_syscalls.load(std::memory_order_relaxed);
  int io = armed
               ? link_send_frame(c, dest, kCoalescedTag, e->scratch.data(),
                                 total, nullptr, 0)
               : write_all_dl(c->socks[dest], e->scratch.data(),
                              total + (int64_t)sizeof(MsgHeader));
  g_dl_post_anchor = 0;
  int rc = 0;
  if (io) {
    char why[160];
    if (io == 2)
      std::snprintf(why, sizeof(why),
                    "timed out after %.0f s with %lld/%lld bytes moved "
                    "(MPI4JAX_TPU_TIMEOUT_S)",
                    transport_timeout_s(), (long long)g_io_done,
                    (long long)g_io_want);
    else
      std::snprintf(why, sizeof(why), "%s", std::strerror(errno));
    std::fprintf(stderr,
                 "tpucomm r%d: coalesced send to %d (%d msgs) failed: %s\n",
                 c->rank, dest, n, why);
    set_last_error(c->rank, "coalesced send to %d failed: %s", dest, why);
    rc = 1;
  }
  engine_obs_burst(ops, n, dest, tw0, sys0);
  return rc;
}

/* True for a detached TCP send the drain loop may merge into a
 * vectored write (no container framing — the wire bytes are EXACTLY
 * the N individual frames). */
bool batchable(const EngineOp* o) {
  return o->kind == TPU_OBS_SEND && o->detached &&
         o->peer != o->comm->rank && o->peer >= 0 &&
         o->peer < o->comm->size && !ring_p2p_on(o->comm) &&
         engine_peer_fd(o) >= 0;
}

/* Write a run of adjacent detached sends that are NOT coalescible
 * (above the threshold, or coalescing off) as one vectored write: the
 * historic drain loop issued one header+payload write pair per
 * descriptor even when several completed descriptors targeted the same
 * socket back-to-back — batching them into a single writev keeps the
 * wire bytes bit-identical while the URING=0 escape hatch also sheds
 * the per-descriptor syscalls. */
int engine_write_batch(Engine* e, EngineOp** ops, int n) {
  (void)e;
  Comm* c = ops[0]->comm;
  const int dest = ops[0]->peer;
  const bool armed = retry_armed();
  std::vector<MsgHeader> hdrs((size_t)n);
  std::vector<struct iovec> iov((size_t)n * 2);
  int64_t total = 0;
  for (int i = 0; i < n; i++) {
    if (!armed) fault_fire(c, g_job_rank, FP_SEND, "send");
    hdrs[(size_t)i] = MsgHeader{ops[i]->snb, ops[i]->tag, c->comm_id};
    iov[(size_t)(2 * i)] = {&hdrs[(size_t)i], sizeof(MsgHeader)};
    iov[(size_t)(2 * i + 1)] = {const_cast<void*>(ops[i]->sbuf),
                                (size_t)ops[i]->snb};
    total += (int64_t)sizeof(MsgHeader) + ops[i]->snb;
  }
  LogScope log(c->rank, "SendBatch", [&] {
    return "to " + std::to_string(dest) + " (" + std::to_string(n) +
           " frames, " + std::to_string(total) + " bytes)";
  });
  g_dl_post_anchor = ops[0]->t_post;
  double tw0 = now_s();
  int64_t sys0 = g_syscalls.load(std::memory_order_relaxed);
  int io = 0;
  if (armed) {
    /* armed: each frame needs its own seq stamp + retained copy, so
     * the run leaves as N sequential link_send_frame writes instead of
     * one shared writev (the merge still saves per-descriptor queue
     * round-trips; only the vectored-syscall saving is conceded) */
    for (int i = 0; i < n && !io; i++) {
      fault_fire(c, g_job_rank, FP_SEND, "send", link_fd(c, dest));
      io = link_send_frame(c, dest, ops[i]->tag, ops[i]->sbuf,
                           ops[i]->snb, nullptr, 0);
    }
  } else {
    io = writev_all_dl(c->socks[dest], iov.data(), 2 * n, total);
  }
  g_dl_post_anchor = 0;
  int rc = 0;
  if (io) {
    char why[160];
    if (io == 2)
      std::snprintf(why, sizeof(why),
                    "timed out after %.0f s with %lld/%lld bytes moved "
                    "(MPI4JAX_TPU_TIMEOUT_S)",
                    transport_timeout_s(), (long long)g_io_done,
                    (long long)g_io_want);
    else
      std::snprintf(why, sizeof(why), "%s", std::strerror(errno));
    std::fprintf(stderr,
                 "tpucomm r%d: batched send to %d (%d frames) failed: %s\n",
                 c->rank, dest, n, why);
    set_last_error(c->rank, "batched send to %d failed: %s", dest, why);
    rc = 1;
  }
  engine_obs_burst(ops, n, dest, tw0, sys0);
  return rc;
}

void engine_loop(Comm* root) {
  Engine* e = root->engine;
  for (;;) {
    uint64_t t = e->tail.load(std::memory_order_relaxed);
    uint64_t h = e->head.load(std::memory_order_acquire);
    if (h == t) {
      if (e->stop.load(std::memory_order_acquire)) return;
      /* idle tick: heartbeat quiet links + drain stray reconnect dials
       * (the ISSUE's uring-timeout-slot role — the 100 ms futex park
       * below already bounds the tick period) */
      if (retry_armed()) link_idle_service(root);
      int32_t seq = e->hseq.load(std::memory_order_acquire);
      if (e->head.load(std::memory_order_acquire) != t) continue;
      shm_futex_wait(&e->hseq, seq, 100);
      continue;
    }
    EngineOp* op = e->slots[t % e->cap];
    int run = 1;
    bool as_container = false;
    if (coalescible(op)) {
      /* small adjacent sends merge into ONE container frame (the
       * historic coalescing wire format, unchanged) */
      as_container = true;
      while (t + run < h && run < kCoalesceMaxRun) {
        EngineOp* nxt = e->slots[(t + run) % e->cap];
        if (!coalescible(nxt) || nxt->comm != op->comm ||
            nxt->peer != op->peer)
          break;
        run++;
      }
      if (run == 1) as_container = false;
    } else if (batchable(op)) {
      /* larger detached sends to one socket back-to-back: one vectored
       * write of the individual frames (bit-identical wire bytes) */
      while (t + run < h && run < kCoalesceMaxRun) {
        EngineOp* nxt = e->slots[(t + run) % e->cap];
        if (!batchable(nxt) || coalescible(nxt) || nxt->comm != op->comm ||
            nxt->peer != op->peer)
          break;
        run++;
      }
    }
    if (run > 1) {
      EngineOp* batch[kCoalesceMaxRun];
      for (int i = 0; i < run; i++) batch[i] = e->slots[(t + i) % e->cap];
      int rc = as_container ? engine_write_coalesced(e, batch, run)
                            : engine_write_batch(e, batch, run);
      e->tail.store(t + run, std::memory_order_release);
      e->tseq.fetch_add(1, std::memory_order_release);
      shm_futex_wake_all(&e->tseq);
      e->inflight.fetch_sub(run, std::memory_order_release);
      if (rc) e->sticky.store(1, std::memory_order_release);
      for (int i = 0; i < run; i++) delete batch[i];
      continue;
    }
    g_dl_post_anchor = op->t_post;
    op->rc = engine_run_body(op);
    g_dl_post_anchor = 0;
    e->tail.store(t + 1, std::memory_order_release);
    e->tseq.fetch_add(1, std::memory_order_release);
    shm_futex_wake_all(&e->tseq);
    e->inflight.fetch_sub(1, std::memory_order_release);
    if (op->detached) {
      if (op->rc) e->sticky.store(1, std::memory_order_release);
      delete op;
    } else {
      /* the waiter owns the descriptor and may destroy it the moment
       * it observes state == 1.  The wake AFTER the store is still
       * safe: FUTEX_WAKE keys on the address only (never dereferences
       * it) — the standard condvar-internal idiom — and the waiter's
       * futex wait is timed (100 ms), so even a wake landing on a
       * recycled stack address costs at most one spurious wakeup. */
      op->state.store(1, std::memory_order_release);
      shm_futex_wake_all(&op->state);
    }
  }
}

/* Lazy engine creation, factored out of engine_post so an armed
 * bootstrap can spin the progress thread up eagerly: heartbeats must
 * tick on a link that never posts an op.  Callers serialize (comm lock
 * or single-threaded bootstrap). */
Engine* engine_ensure(Comm* root) {
  Engine* e = root->engine;
  if (e == nullptr) {
    e = new Engine;
    e->cap = (uint64_t)queue_depth();
    e->slots.assign((size_t)e->cap, nullptr);
    root->engine = e;  // published before the thread starts
    e->thr = std::thread(engine_loop, root);
  }
  return e;
}

/* Post under the comm lock; the queue itself is lock-free SPSC. */
void engine_post(Comm* root, EngineOp* op) {
  Engine* e = engine_ensure(root);
  uint64_t h = e->head.load(std::memory_order_relaxed);
  while (h - e->tail.load(std::memory_order_acquire) >= e->cap) {
    /* bounded queue: park for space (backpressure, not allocation) */
    int32_t seq = e->tseq.load(std::memory_order_acquire);
    if (h - e->tail.load(std::memory_order_acquire) < e->cap) break;
    shm_futex_wait(&e->tseq, seq, 100);
  }
  e->slots[h % e->cap] = op;
  e->inflight.fetch_add(1, std::memory_order_release);
  e->head.store(h + 1, std::memory_order_release);
  e->hseq.fetch_add(1, std::memory_order_release);
  shm_futex_wake_all(&e->hseq);
}

/* Wait (under the comm lock) until the progress thread has drained and
 * completed everything posted so far.  Required before any direct
 * socket I/O outside the engine (split's arena bootstrap). */
void engine_quiesce(Comm* root) {
  Engine* e = root->engine;
  if (!e) return;
  while (e->inflight.load(std::memory_order_acquire) > 0) {
    int32_t seq = e->tseq.load(std::memory_order_acquire);
    if (e->inflight.load(std::memory_order_acquire) <= 0) break;
    shm_futex_wait(&e->tseq, seq, 50);
  }
}

/* The single entry point every public op goes through.  Holds the comm
 * lock for the duration of an INLINE op (the historic exclusivity), or
 * only for the post + park of a queued one (the progress thread never
 * takes the lock — queue order is the serialization). */
int engine_submit(Comm* c, EngineOp* op) {
  op->comm = c;
  Comm* root = c->lock_root;
  std::lock_guard<std::mutex> lock(comm_mu(c));
  Engine* e = root->engine;
  if (e && e->sticky.load(std::memory_order_acquire))
    FAIL(c, "an earlier asynchronously posted send failed — see the "
         "diagnostic above (async progress engine)");
  const bool engine_on = progress_thread_on();
  const bool detach = engine_on && op->kind == TPU_OBS_SEND &&
                      op->snb <= detach_threshold() && op->peer >= 0 &&
                      op->peer < c->size;
  const bool busy =
      e && e->inflight.load(std::memory_order_acquire) > 0;
  if (!engine_on || (!detach && !busy)) {
    /* idle engine (or engine off): run inline on this thread — no
     * context switch on the latency path, bit-for-bit the historic
     * behavior */
    op->t_post = g_obs_on.load(std::memory_order_relaxed) ? now_s() : -1;
    return engine_run_body(op);
  }
  op->t_post = now_s();
  if (detach) {
    auto* hop = new EngineOp;
    hop->kind = op->kind;
    hop->flags = op->flags;
    hop->comm = c;
    hop->snb = op->snb;
    hop->peer = op->peer;
    hop->tag = op->tag;
    hop->t_post = op->t_post;
    hop->detached = true;
    const char* src = static_cast<const char*>(op->sbuf);
    hop->owned.assign(src, src + op->snb);
    hop->sbuf = hop->owned.data();
    engine_post(root, hop);
    return 0;  // buffered-send semantics: completion is asynchronous
  }
  engine_post(root, op);
  while (op->state.load(std::memory_order_acquire) == 0)
    shm_futex_wait(&op->state, 0, 100);
  return op->rc;
}

/* Drain the queue (the loop finishes everything posted before stop is
 * observed with an empty queue), join the thread, free the engine.
 * Declared near the top: Comm's destructor and finalize call it. */
void engine_shutdown(Engine* e) {
  e->stop.store(1, std::memory_order_release);
  e->hseq.fetch_add(1, std::memory_order_release);
  shm_futex_wake_all(&e->hseq);
  if (e->thr.joinable()) e->thr.join();
  if (e->sticky.load(std::memory_order_acquire))
    /* a detached send failed and no later op surfaced it (each failure
     * already printed its own diagnostic at the moment it happened):
     * say so once more at teardown so a job whose LAST op was the
     * failing buffered send cannot drain silently */
    std::fprintf(stderr,
                 "tpucomm: asynchronously posted send(s) failed before "
                 "finalize; data may be undelivered (see diagnostics "
                 "above)\n");
  delete e;
}

}  // namespace

extern "C" {

void tpucomm_set_logging(int enabled) { g_logging = enabled; }

/* The TCP-mesh bootstrap shared by tpucomm_init and tpucomm_shrink:
 * listen for higher ranks, dial lower ranks (deadline-bounded with
 * exponential backoff), exchange rank handshakes, arm non-blocking
 * mode when a transport deadline is set, and attach the same-host shm
 * arena.  Returns a registered handle, 0 on failure. */
static int64_t comm_bootstrap(int rank, int size, int base_port,
                              const char* hosts) {
  auto* c = new Comm;
  c->rank = rank;
  c->size = size;
  c->socks.assign(size, -1);

  std::vector<std::string> host_list(size, "127.0.0.1");
  if (hosts && hosts[0]) {
    std::string s(hosts);
    size_t pos = 0;
    for (int i = 0; i < size; i++) {
      size_t comma = s.find(',', pos);
      host_list[i] = s.substr(pos, comma == std::string::npos
                                       ? std::string::npos
                                       : comma - pos);
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
  }

  /* listener for ranks > me */
  int listen_fd = -1;
  if (rank < size - 1) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons((uint16_t)(base_port + rank));
    if (::bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        ::listen(listen_fd, size) != 0) {
      std::fprintf(stderr, "tpucomm r%d: cannot listen on port %d: %s\n",
                   rank, base_port + rank, std::strerror(errno));
      delete c;
      return 0;
    }
  }

  /* dial every lower rank (retrying while they come up): deadline-bounded
   * with exponential backoff instead of the old fixed 600 x 50 ms spin;
   * the failure names the last errno so a refused port reads differently
   * from an unroutable host */
  const double connect_dl = connect_timeout_s();  // 0 = unbounded
  for (int peer = 0; peer < rank; peer++) {
    int fd = -1;
    int last_errno = 0;
    double deadline = connect_dl > 0
                          ? now_s() + connect_dl
                          : std::numeric_limits<double>::infinity();
    double backoff_ms = 1.0;
    for (;;) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons((uint16_t)(base_port + peer));
      ::inet_pton(AF_INET, host_list[peer].c_str(), &addr.sin_addr);
      /* non-blocking connect + poll: a blackholed host must consume at
       * most the remaining deadline, not the kernel's ~2 min SYN
       * retransmit cycle (the deadline is the contract, and the error
       * text reports the elapsed budget) */
      int fl = ::fcntl(fd, F_GETFL, 0);
      ::fcntl(fd, F_SETFL, fl | O_NONBLOCK);
      int cr = ::connect(fd, (sockaddr*)&addr, sizeof(addr));
      if (cr != 0 && errno == EINPROGRESS) {
        double remain = deadline - now_s();
        pollfd pf{fd, POLLOUT, 0};
        int pr = remain > 0
                     ? ::poll(&pf, 1, (int)std::min(remain * 1000.0 + 1,
                                                    60000.0))
                     : 0;
        if (pr > 0) {
          int soerr = 0;
          socklen_t sl = sizeof(soerr);
          ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &sl);
          if (soerr == 0) {
            cr = 0;
          } else {
            errno = soerr;
            cr = -1;
          }
        } else {
          errno = ETIMEDOUT;
          cr = -1;
        }
      }
      if (cr == 0) {
        ::fcntl(fd, F_SETFL, fl);  // back to blocking for the handshake
        break;
      }
      last_errno = errno;
      ::close(fd);
      fd = -1;
      if (now_s() + backoff_ms / 1000.0 > deadline) break;
      std::this_thread::sleep_for(
          std::chrono::microseconds((int64_t)(backoff_ms * 1000)));
      backoff_ms = std::min(backoff_ms * 2.0, 200.0);
    }
    if (fd < 0) {
      std::fprintf(stderr,
                   "tpucomm r%d: cannot reach rank %d (%s:%d) within "
                   "%.0f s: %s (MPI4JAX_TPU_CONNECT_TIMEOUT_S)\n",
                   rank, peer, host_list[peer].c_str(), base_port + peer,
                   connect_dl, std::strerror(last_errno));
      set_last_error(rank,
                     "bootstrap connect to rank %d (%s:%d) timed out after "
                     "%.0f s: %s", peer, host_list[peer].c_str(),
                     base_port + peer, connect_dl,
                     std::strerror(last_errno));
      delete c;
      return 0;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int32_t my_rank = rank;
    if (write_all(fd, &my_rank, sizeof(my_rank))) {
      delete c;
      return 0;
    }
    c->socks[peer] = fd;
  }

  /* accept every higher rank, bounded by the connect deadline BY
   * DEFAULT: the dial side has been deadline-bounded since the knob
   * landed, but accept used to block forever unless the operator set
   * MPI4JAX_TPU_CONNECT_TIMEOUT_S explicitly — an accept-side hang
   * (one higher rank never scheduled) outlived every other deadline in
   * the stack.  A missing higher rank now hangs accept exactly as long
   * as a missing lower rank hangs connect; 0 opts back into unbounded
   * waits on both sides. */
  const bool bounded_accept = connect_dl > 0;
  for (int expected = rank + 1; expected < size; expected++) {
    if (bounded_accept) {
      double deadline = now_s() + connect_dl;
      int pr = 0;
      do {
        pollfd pf{listen_fd, POLLIN, 0};
        pr = ::poll(&pf, 1, 100);
      } while (pr <= 0 && now_s() < deadline);
      if (pr <= 0) {
        std::fprintf(stderr,
                     "tpucomm r%d: no higher rank dialed within %.0f s "
                     "(%d of %d peers still missing; "
                     "MPI4JAX_TPU_CONNECT_TIMEOUT_S)\n",
                     rank, connect_dl, size - expected, size - rank - 1);
        set_last_error(rank,
                       "bootstrap accept timed out after %.0f s with %d "
                       "higher rank(s) missing", connect_dl,
                       size - expected);
        delete c;
        return 0;
      }
    }
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) {
      std::fprintf(stderr, "tpucomm r%d: accept failed: %s\n", rank,
                   std::strerror(errno));
      delete c;
      return 0;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int32_t peer_rank = -1;
    /* bounded handshake (when the accept side is bounded at all): a
     * peer that connects but wedges before identifying itself must not
     * hold bootstrap hostage past the deadline.  The fd is blocking
     * here, which is fine for the read side: io_all_deadline polls
     * before every read, so it only ever reads available bytes. */
    int hs_rc = bounded_accept
                    ? io_all_deadline<false>(fd, &peer_rank,
                                             sizeof(peer_rank), connect_dl)
                    : read_all(fd, &peer_rank, sizeof(peer_rank));
    if (hs_rc || peer_rank <= rank ||
        peer_rank >= size || c->socks[peer_rank] != -1) {
      std::fprintf(stderr, "tpucomm r%d: bad handshake (peer said %d)\n",
                   rank, peer_rank);
      delete c;
      return 0;
    }
    c->socks[peer_rank] = fd;
  }
  if (listen_fd >= 0) {
    if (retry_armed())
      /* self-healing: reconnect dials from higher ranks land on the
       * bootstrap listener, so it stays open for the comm's lifetime */
      c->listen_fd = listen_fd;
    else
      ::close(listen_fd);
  }

  /* With a transport deadline armed, the mesh runs on non-blocking fds:
   * the deadline paths poll() before every transfer and handle EAGAIN,
   * and a blocking socket write of a large payload would otherwise park
   * in the kernel until ALL bytes are queued — unwakeable past any
   * deadline when the peer stops draining.  Without the knob the fds
   * stay blocking and the historic loops serve untouched.  The uring
   * backend ALSO wants non-blocking fds: a blocking submitted send is
   * punted to an io-wq kernel worker (a context switch per op, and a
   * parked worker past any deadline), where a non-blocking one
   * completes through the ring's internal poll — so an active uring
   * resolves the same fd mode the deadline does. */
  if (transport_timeout_s() > 0 || uring_ready()) {
    for (int fd : c->socks)
      if (fd >= 0) {
        int fl = ::fcntl(fd, F_GETFL, 0);
        if (fl >= 0) ::fcntl(fd, F_SETFL, fl | O_NONBLOCK);
      }
  }

  /* self-healing link layer: one LinkState per peer socket, the
   * identity root_rank map (sub-comms compose through it at split),
   * and the REAL dialing addresses — reconnect must dial the wire
   * host even when FAKE_HOSTS virtually partitions locality */
  if (retry_armed()) {
    c->base_port = base_port;
    c->real_hosts = host_list;
    c->root_rank.resize((size_t)size);
    c->links.resize((size_t)size);
    const double t0 = now_s();
    for (int r = 0; r < size; r++) {
      c->root_rank[(size_t)r] = r;
      if (r == rank) continue;
      c->links[(size_t)r].reset(new LinkState);
      c->links[(size_t)r]->last_rx.store(t0, std::memory_order_relaxed);
    }
  }

  /* same-host groups get the shared-memory collective arena */
  const char* jobid = std::getenv("MPI4JAX_TPU_JOBID");
  char prefix[96];
  /* the base port is part of the prefix even with a job id: elastic
   * recovery re-bootstraps a new world GENERATION at a re-derived port
   * under the same job id, and its arena segments must never collide
   * with (or attach to) the previous generation's */
  if (jobid && jobid[0])
    std::snprintf(prefix, sizeof(prefix), "m4jshm_%.48s_p%d", jobid,
                  base_port);
  else
    std::snprintf(prefix, sizeof(prefix), "m4jshm_p%d", base_port);
  c->shm_prefix = prefix;
  /* arena eligibility keys on the EFFECTIVE host view: the real host
   * table with the MPI4JAX_TPU_FAKE_HOSTS virtual partition applied —
   * a partitioned loopback job loses the world arena exactly like the
   * multi-host shape it models (its intra-island sub-comms get their
   * own arenas through the same check in tpucomm_split).  Sockets
   * always dial the REAL hosts; only locality decisions change. */
  std::vector<std::string> eff_hosts = host_list;
  apply_fake_hosts(eff_hosts, size);
  c->member_hosts = eff_hosts;
  bool same_host = true;
  for (int i = 1; i < size; i++)
    if (eff_hosts[i] != eff_hosts[0]) same_host = false;
  if (same_host) arena_init(c);

  /* armed + engine on: start the progress thread eagerly — heartbeats
   * must tick on a link that never posts an op (half-open detection on
   * idle links is the point) */
  if (retry_armed() && progress_thread_on()) engine_ensure(c);

  std::lock_guard<std::mutex> lock(g_comms_mu);
  int64_t h = g_next_handle++;
  g_comms[h] = c;
  return h;
}

int64_t tpucomm_init(int rank, int size, int base_port, const char* hosts) {
  fault_init();
  g_job_rank = rank;
  fault_fire(nullptr, rank, FP_CONNECT, "connect");
  return comm_bootstrap(rank, size, base_port, hosts);
}

int64_t tpucomm_shrink(int64_t old_h, int new_rank, int new_size,
                       int base_port, const char* hosts) {
  fault_init();
  /* tear the dead world down first: drain/stop its progress engine and
   * close its sockets so the rebuilt mesh starts from a clean fd table.
   * The caller already abandoned the old comm (elastic recovery runs
   * after abort_all poisoned and shut every socket down, so the drain
   * fails fast instead of blocking on dead peers).  Sub-communicators
   * of the old world must be gone before this call — they borrow its
   * sockets. */
  if (old_h != 0) tpucomm_finalize(old_h);
  /* connect-point fault injection keys on the rank this process was
   * BORN with (g_job_rank), exactly like the send/recv points: a fault
   * spec must address the same process before and after renumbering */
  fault_fire(nullptr, g_job_rank, FP_CONNECT, "connect");
  return comm_bootstrap(new_rank, new_size, base_port, hosts);
}

void tpucomm_finalize(int64_t h) {
  std::lock_guard<std::mutex> lock(g_comms_mu);
  auto it = g_comms.find(h);
  if (it == g_comms.end()) return;
  Comm* c = it->second;
  /* drain the progress engine BEFORE closing sockets or freeing the
   * comm: detached sends still in the queue must reach the wire (the
   * buffered-send flush MPI_Finalize performs).  A split/dup comm's
   * descriptors live on the socket owner's engine — quiesce it, or a
   * queued send would dereference this comm after the delete below. */
  if (c->engine) {
    engine_shutdown(c->engine);
    c->engine = nullptr;
  } else if (c->lock_root != c) {
    /* the parent may itself have been finalized already (legal call
     * order before the engine existed): only touch lock_root while it
     * is still registered — we hold g_comms_mu, so this is race-free */
    for (const auto& kv : g_comms)
      if (kv.second == c->lock_root) {
        if (kv.second->engine) engine_quiesce(kv.second);
        break;
      }
  }
  /* a finalized comm may be referenced as another comm's topology
   * sub-communicator (intra-island / leaders): drop that topology
   * entirely — every rank of the owning comm tears its sub-comms down
   * at the same point (the Python bridge owns them), so the map
   * disappears consistently and hierarchical picks degrade to their
   * flat twins everywhere instead of on a subset of ranks */
  for (auto& kv : g_comms) {
    Comm* w = kv.second;
    if (w->topo && (w->topo->intra == c || w->topo->leader == c)) {
      delete w->topo;
      w->topo = nullptr;
    }
  }
  if (c->lock_root != c) {
    /* unregister from the socket owner's reconnect-rewire list — but
     * only while the owner is still registered (it may legally have
     * been finalized first; g_comms_mu makes the check race-free) */
    for (const auto& kv : g_comms)
      if (kv.second == c->lock_root) {
        std::lock_guard<std::mutex> kl(c->lock_root->kids_mu);
        auto& ks = c->lock_root->kids;
        ks.erase(std::remove(ks.begin(), ks.end(), c), ks.end());
        break;
      }
  }
  if (c->owns_socks)
    for (int fd : c->socks)
      if (fd >= 0) ::close(fd);
  delete c;
  g_comms.erase(it);
}

/* Sub-communicators (the analog of MPI_Comm_split / MPI_Comm_dup —
 * the reference accepts any mpi4py comm, users Split()/Clone() freely,
 * comm.py:4-11 + docs/sharp-bits.rst:82-143 there).
 *
 * Collective over the parent: every member must call in the same program
 * position.  Ranks sharing a `color` form a new communicator ordered by
 * (key, parent rank); color < 0 opts out (returns the null handle -1).
 * The child borrows the parent's sockets with ranks remapped; message
 * isolation between sibling comms is enforced by the comm_id carried in
 * every frame header (mismatch = fail-fast, consistent with the ordered
 * transport's no-reordering contract). */
int64_t tpucomm_split(int64_t h, int color, int key) {
  Comm* c = get_comm(h);
  if (!c) return 0;
  std::vector<int32_t> mine{(int32_t)color, (int32_t)key};
  std::vector<int32_t> all(2 * (size_t)c->size);
  if (tpucomm_allgather(h, mine.data(), 2 * sizeof(int32_t), all.data()))
    return 0;
  int32_t seq;
  {
    std::lock_guard<std::mutex> lock(comm_mu(c));
    seq = c->next_split_seq++;
  }
  if (color < 0) return -1;  // null comm: this rank opted out

  std::vector<std::pair<int, int>> members;  // (key, parent rank)
  for (int r = 0; r < c->size; r++)
    if (all[2 * r] == color) members.push_back({all[2 * r + 1], r});
  std::stable_sort(members.begin(), members.end());

  auto* nc = new Comm;
  nc->size = (int)members.size();
  nc->socks.assign(nc->size, -1);
  nc->owns_socks = false;
  /* serialize on (and queue async sends through) the socket owner: two
   * comms sharing fds must never interleave writes on one socket */
  nc->lock_root = c->lock_root;
  for (int nr = 0; nr < nc->size; nr++) {
    int old = members[nr].second;
    if (old == c->rank)
      nc->rank = nr;
    else
      nc->socks[nr] = c->socks[old];
  }
  if (retry_armed() && !c->root_rank.empty()) {
    /* compose the root_rank map through the parent so this child
     * resolves the same per-socket LinkState, and register it with the
     * socket owner so a reconnect rewires this comm's socks view too */
    nc->root_rank.resize((size_t)nc->size);
    for (int nr = 0; nr < nc->size; nr++)
      nc->root_rank[(size_t)nr] =
          c->root_rank[(size_t)members[(size_t)nr].second];
    Comm* rt = nc->lock_root;
    std::lock_guard<std::mutex> kl(rt->kids_mu);
    rt->kids.push_back(nc);
  }
  /* FNV mix of (parent id, call seq, color): identical on every member,
   * distinct across sibling groups and successive splits */
  uint32_t id = 2166136261u;
  for (uint32_t v : {(uint32_t)c->comm_id, (uint32_t)seq, (uint32_t)color}) {
    id ^= v;
    id *= 16777619u;
  }
  nc->comm_id = (int32_t)(id & 0x7fffffff);
  if (nc->comm_id == 0) nc->comm_id = 1;  // 0 is reserved for the world

  /* a subset of a same-(effective-)host group is same-host: a child
   * whose members all share one entry of the parent's member_hosts view
   * gets its own arena even when the PARENT spans hosts — this is what
   * gives an intra-island sub-comm of a multi-host (or FAKE_HOSTS-
   * partitioned) world the shm fast path the hierarchical collectives
   * ride.  arena_init's nonce bcast writes the shared sockets, so it
   * must hold the socket owner's lock like every other op on borrowed
   * fds. */
  nc->shm_prefix = c->shm_prefix;
  if (!c->member_hosts.empty()) {
    nc->member_hosts.resize((size_t)nc->size);
    for (int nr = 0; nr < nc->size; nr++)
      nc->member_hosts[(size_t)nr] =
          c->member_hosts[(size_t)members[(size_t)nr].second];
  }
  bool sub_same_host = nc->size > 1 && !nc->member_hosts.empty();
  for (int nr = 1; sub_same_host && nr < nc->size; nr++)
    if (nc->member_hosts[(size_t)nr] != nc->member_hosts[0])
      sub_same_host = false;
  if (c->arena || sub_same_host) {
    std::lock_guard<std::mutex> lock(comm_mu(nc));
    /* arena bootstrap writes the shared sockets directly (nonce bcast):
     * the progress thread must be idle first — two writers on one
     * socket would interleave frames */
    engine_quiesce(nc->lock_root);
    arena_init(nc);
  }

  std::lock_guard<std::mutex> lock(g_comms_mu);
  int64_t nh = g_next_handle++;
  g_comms[nh] = nc;
  return nh;
}

int64_t tpucomm_dup(int64_t h) {
  Comm* c = get_comm(h);
  if (!c) return 0;
  /* split with one shared color, keyed by rank: same membership and
   * ordering, fresh comm_id (isolated message space) */
  return tpucomm_split(h, 0, c->rank);
}

/* ---- topology installation (mpi4jax_tpu/topo is the owner) ---- */

int tpucomm_set_topology(int64_t h, const int32_t* island_of, int n,
                         int64_t intra_h, int64_t leader_h) {
  Comm* c = get_comm(h);
  if (!c || !island_of || n != c->size) return 1;
  std::unique_ptr<TopoInfo> t(new TopoInfo);
  t->island_of.assign(island_of, island_of + n);
  int max_id = -1;
  for (int r = 0; r < n; r++) {
    if (island_of[r] < 0 || island_of[r] >= n) return 1;
    if (island_of[r] > max_id) max_id = island_of[r];
  }
  t->n_islands = max_id + 1;
  t->members.assign((size_t)t->n_islands, {});
  for (int r = 0; r < n; r++)
    t->members[(size_t)island_of[r]].push_back(r);
  t->leaders.resize((size_t)t->n_islands);
  for (int i = 0; i < t->n_islands; i++) {
    if (t->members[(size_t)i].empty()) return 1;  // ids must be dense
    t->leaders[(size_t)i] = t->members[(size_t)i][0];
    /* island ids ordered by leader rank: the leaders' sub-comm (split
     * keyed on rank) then has leader-comm rank == island id, which the
     * hierarchical schedules rely on */
    if (i > 0 && t->leaders[(size_t)i] <= t->leaders[(size_t)i - 1])
      return 1;
  }
  t->my_island = island_of[c->rank];
  t->my_leader = t->leaders[(size_t)t->my_island];
  const auto& mine = t->members[(size_t)t->my_island];
  Comm* intra = intra_h > 0 ? get_comm(intra_h) : nullptr;
  Comm* lead = leader_h > 0 ? get_comm(leader_h) : nullptr;
  /* a single-island (flat) topology installs for the probes only — no
   * sub-comms needed, the hierarchical schedules never become eligible */
  if (mine.size() > 1 && t->n_islands > 1) {
    int idx = -1;
    for (size_t m = 0; m < mine.size(); m++)
      if (mine[m] == c->rank) idx = (int)m;
    if (!intra || intra->size != (int)mine.size() || intra->rank != idx)
      return 1;
    t->intra = intra;
  }
  if (t->my_leader == c->rank && t->n_islands > 1) {
    if (!lead || lead->size != t->n_islands ||
        lead->rank != t->my_island)
      return 1;
    t->leader = lead;
  }
  /* swap in under the op lock with the engine quiesced: dispatch reads
   * c->topo without a lock, so no op may be mid-flight */
  std::lock_guard<std::mutex> lock(comm_mu(c));
  engine_quiesce(c->lock_root);
  delete c->topo;
  c->topo = t.release();
  return 0;
}

int tpucomm_topo_info(int64_t h, int32_t* out_island_of,
                      int32_t* out_n_islands) {
  Comm* c = get_comm(h);
  if (!c) return -1;
  if (!c->topo) return 1;
  if (out_island_of)
    for (int r = 0; r < c->size; r++)
      out_island_of[r] = c->topo->island_of[(size_t)r];
  if (out_n_islands) *out_n_islands = c->topo->n_islands;
  return 0;
}

int tpucomm_rank(int64_t h) {
  Comm* c = get_comm(h);
  return c ? c->rank : -1;
}

int tpucomm_size(int64_t h) {
  Comm* c = get_comm(h);
  return c ? c->size : -1;
}

/* Observability: did the same-host fast paths engage for this comm?
 * Returns 1 with the arena's sizes, 0 when the comm runs on TCP only,
 * -1 for a bad handle.  (diag CLI / docs §5.5.) */
int tpucomm_shm_info(int64_t h, int64_t* slot_bytes, int64_t* ring_bytes) {
  Comm* c = get_comm(h);
  if (!c) return -1;
  if (!c->arena) {
    *slot_bytes = 0;
    *ring_bytes = 0;
    return 0;
  }
  *slot_bytes = c->arena->slot_bytes;
  *ring_bytes = c->arena->ring_bytes;
  return 1;
}

int tpucomm_send(int64_t h, const void* buf, int64_t nbytes, int dest,
                 int tag) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_SEND;
  op.sbuf = buf;
  op.snb = nbytes;
  op.peer = dest;
  op.tag = tag;
  return engine_submit(c, &op);
}

int tpucomm_recv(int64_t h, void* buf, int64_t nbytes, int source, int tag) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_RECV;
  op.rbuf = buf;
  op.rnb = nbytes;
  op.peer2 = source;
  op.tag = tag;
  return engine_submit(c, &op);
}

const char* tpucomm_uring_status(void) {
  uring_probe();
  if (uring_mode() == 0) return "off";
  if (g_uring_avail == 1) {
    if (!g_uring_zc) return "on(no-zerocopy)";
    /* adaptive: the kernel reported it copies zero-copy sends on this
     * path (loopback) — large sends ride plain submitted sends now */
    if (g_zc_fallback.load(std::memory_order_relaxed))
      return "on(zerocopy-fallback)";
    return "on";
  }
  /* g_uring_reason is frozen once the probe resolves; the format
   * buffer is per-thread so concurrent status calls cannot race */
  static thread_local char buf[200];
  std::snprintf(buf, sizeof(buf), "unavailable(%s)", g_uring_reason);
  return buf;
}

int64_t tpucomm_syscall_count(void) {
  return g_syscalls.load(std::memory_order_relaxed);
}

const char* tpucomm_last_error(void) {
  std::lock_guard<std::mutex> lock(g_last_error_mu);
  return g_last_error;
}

void tpucomm_abort_all(void) {
  /* Best-effort job-wide abort propagation, called by the Python layer
   * on its way into os._exit: one poison frame (kPoisonTag header +
   * last-error text) to every peer of every socket-owning comm, then
   * shutdown — peers blocked in a recv consume the poison and fail
   * naming this rank; peers parked in shm waits see the socket die on
   * their next liveness probe.  Everything here is non-blocking: an
   * abort must never hang behind a full socket buffer. */
  char text[sizeof(g_last_error)] = {0};
  {
    std::lock_guard<std::mutex> lock(g_last_error_mu);
    std::memcpy(text, g_last_error, sizeof(text));
  }
  text[sizeof(text) - 1] = 0;
  const int64_t len = (int64_t)std::strlen(text);
  std::lock_guard<std::mutex> lock(g_comms_mu);
  for (auto& kv : g_comms) {
    Comm* c = kv.second;
    if (!c->owns_socks) continue;  // sub-comms borrow these same fds
    for (int r = 0; r < c->size; r++) {
      int fd = c->socks[r];
      if (fd < 0) continue;
      ssize_t w;
      if (retry_armed()) {
        /* armed peers parse MsgHeaderX frames: send a sealed extended
         * header (seq 0 = control, never dedup'd or replayed) so the
         * poison isn't rejected as a CRC mismatch */
        MsgHeaderX hx{};
        hx.h = MsgHeader{len, kPoisonTag, c->comm_id};
        hx_seal(&hx);
        w = ::send(fd, &hx, sizeof(hx), MSG_NOSIGNAL | MSG_DONTWAIT);
        w = (w == (ssize_t)sizeof(hx)) ? (ssize_t)sizeof(MsgHeader) : -1;
      } else {
        MsgHeader h{len, kPoisonTag, c->comm_id};
        w = ::send(fd, &h, sizeof(h), MSG_NOSIGNAL | MSG_DONTWAIT);
      }
      /* payload only behind a COMPLETE header: a partial header send
       * (nearly-full buffer — the typical abort scenario) followed by
       * text bytes would be parsed as a garbage frame header on the
       * peer; partial header + EOF degrades to the historic dead-socket
       * diagnostic instead */
      if (w == (ssize_t)sizeof(MsgHeader) && len > 0)
        ::send(fd, text, (size_t)len, MSG_NOSIGNAL | MSG_DONTWAIT);
      ::shutdown(fd, SHUT_RDWR);
    }
  }
}

int tpucomm_recv_status(int64_t h, void* buf, int64_t nbytes, int source,
                        int tag, int32_t* out_src, int32_t* out_tag,
                        int64_t* out_count) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_RECV;
  op.flags = kOpStatus;
  op.rbuf = buf;
  op.rnb = nbytes;
  op.peer2 = source;
  op.tag = tag;
  op.out_src = out_src;
  op.out_tag = out_tag;
  op.out_count = out_count;
  return engine_submit(c, &op);
}

int tpucomm_sendrecv_status(int64_t h, const void* sendbuf,
                            int64_t send_nbytes, int dest, void* recvbuf,
                            int64_t recv_nbytes, int source, int sendtag,
                            int recvtag, int32_t* out_src, int32_t* out_tag,
                            int64_t* out_count) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_SENDRECV;
  op.flags = kOpStatus;
  op.sbuf = sendbuf;
  op.snb = send_nbytes;
  op.peer = dest;
  op.rbuf = recvbuf;
  op.rnb = recv_nbytes;
  op.peer2 = source;
  op.tag = sendtag;
  op.tag2 = recvtag;
  op.out_src = out_src;
  op.out_tag = out_tag;
  op.out_count = out_count;
  return engine_submit(c, &op);
}

int tpucomm_sendrecv(int64_t h, const void* sendbuf, int64_t send_nbytes,
                     int dest, void* recvbuf, int64_t recv_nbytes, int source,
                     int tag) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_SENDRECV;
  op.sbuf = sendbuf;
  op.snb = send_nbytes;
  op.peer = dest;
  op.rbuf = recvbuf;
  op.rnb = recv_nbytes;
  op.peer2 = source;
  op.tag = tag;
  op.tag2 = tag;
  return engine_submit(c, &op);
}

int tpucomm_shift2(int64_t h, const void* sendbuf, void* recvbuf,
                   int64_t strip_nbytes, int lo, int hi, int tag) {
  /* Bidirectional 1-D neighbor exchange in ONE op (the
   * MPI_Neighbor_alltoall analog on a ring segment): sendbuf holds
   * [to_lo | to_hi] strips, recvbuf receives [from_lo | from_hi].
   * Both sends go out asynchronously before either receive, so any
   * topology (chain, ring of any length, ring of 2, self-wrap) is
   * deadlock-free within the op when every member calls it at the same
   * program position.  A -1 neighbor is a wall (MPI_PROC_NULL): that
   * side's output strip is the corresponding input passthrough.
   * Frames to the LOW side use `tag`, to the HIGH side `tag+1` —
   * unambiguous even when both neighbors are one peer (ring of 2). */
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_SHIFT2;
  op.sbuf = sendbuf;
  op.rbuf = recvbuf;
  op.snb = strip_nbytes;
  op.peer = lo;
  op.peer2 = hi;
  op.tag = tag;
  return engine_submit(c, &op);
}

int tpucomm_barrier(int64_t h) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_BARRIER;
  return engine_submit(c, &op);
}

int tpucomm_bcast(int64_t h, void* buf, int64_t nbytes, int root) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_BCAST;
  op.rbuf = buf;
  op.rnb = nbytes;
  op.peer = root;
  return engine_submit(c, &op);
}

int tpucomm_gather(int64_t h, const void* sendbuf, int64_t nbytes,
                   void* recvbuf, int root) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_GATHER;
  op.sbuf = sendbuf;
  op.snb = nbytes;
  op.rbuf = recvbuf;
  op.peer = root;
  return engine_submit(c, &op);
}

int tpucomm_scatter(int64_t h, const void* sendbuf, void* recvbuf,
                    int64_t nbytes, int root) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_SCATTER;
  op.sbuf = sendbuf;
  op.rbuf = recvbuf;
  op.rnb = nbytes;
  op.peer = root;
  return engine_submit(c, &op);
}

int tpucomm_allgather_algo(int64_t h, const void* sendbuf, int64_t nbytes,
                           void* recvbuf, int algo) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_ALLGATHER;
  op.sbuf = sendbuf;
  op.snb = nbytes;
  op.rbuf = recvbuf;
  op.algo = algo;
  return engine_submit(c, &op);
}

int tpucomm_allgather(int64_t h, const void* sendbuf, int64_t nbytes,
                      void* recvbuf) {
  return tpucomm_allgather_algo(h, sendbuf, nbytes, recvbuf, TPU_COLL_AUTO);
}

int tpucomm_alltoall(int64_t h, const void* sendbuf, void* recvbuf,
                     int64_t chunk) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_ALLTOALL;
  op.sbuf = sendbuf;
  op.rbuf = recvbuf;
  op.snb = chunk;
  return engine_submit(c, &op);
}

int tpucomm_alltoall_algo(int64_t h, const void* sendbuf, void* recvbuf,
                          int64_t count, int dtype, int algo) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp op;
  op.kind = TPU_OBS_ALLTOALL;
  op.sbuf = sendbuf;
  op.rbuf = recvbuf;
  op.count = count;
  op.dtype = dtype;
  op.algo = algo;
  return engine_submit(c, &op);
}

int tpucomm_allreduce_algo(int64_t h, const void* sendbuf, void* recvbuf,
                           int64_t count, int dtype, int op, int algo) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp eop;
  eop.kind = TPU_OBS_ALLREDUCE;
  eop.sbuf = sendbuf;
  eop.rbuf = recvbuf;
  eop.count = count;
  eop.dtype = dtype;
  eop.rop = op;
  eop.algo = algo;
  return engine_submit(c, &eop);
}

int tpucomm_allreduce(int64_t h, const void* sendbuf, void* recvbuf,
                      int64_t count, int dtype, int op) {
  return tpucomm_allreduce_algo(h, sendbuf, recvbuf, count, dtype, op,
                                TPU_COLL_AUTO);
}

void tpucomm_set_coll_table(int op_kind, const int64_t* min_bytes,
                            const int32_t* algos, int n) {
  if (op_kind < 0 || op_kind > 2) return;
  std::vector<std::pair<int64_t, int32_t>> entries;
  for (int i = 0; i < n; i++) {
    int32_t a = algos[i];
    if (a < TPU_COLL_AUTO || a > TPU_COLL_HQA2A || a == TPU_COLL_SHM)
      continue;  // SHM not forcible; unknown codes dropped
    entries.emplace_back(min_bytes[i], a);
  }
  std::sort(entries.begin(), entries.end());
  std::lock_guard<std::mutex> lock(g_coll_table_mu);
  g_coll_table[op_kind].entries = std::move(entries);
}

void tpucomm_stage_coll_table(int op_kind, const int64_t* min_bytes,
                              const int32_t* algos, int n) {
  if (op_kind < 0 || op_kind > 2) return;
  std::vector<std::pair<int64_t, int32_t>> entries;
  for (int i = 0; i < n; i++) {
    int32_t a = algos[i];
    if (a < TPU_COLL_AUTO || a > TPU_COLL_HQA2A || a == TPU_COLL_SHM)
      continue;  // same validation as the direct install
    entries.emplace_back(min_bytes[i], a);
  }
  std::sort(entries.begin(), entries.end());
  std::lock_guard<std::mutex> lock(g_coll_table_mu);
  g_coll_staged[op_kind].entries = std::move(entries);
  g_coll_staged_set[op_kind] = true;
}

int tpucomm_commit_coll_tables(int64_t h, int64_t epoch) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  /* the tpucomm_set_topology swap discipline: comm lock + engine
   * quiesced, so no op resolved against the old table is mid-flight
   * when the table changes under it */
  std::lock_guard<std::mutex> lock(comm_mu(c));
  engine_quiesce(c->lock_root);
  std::lock_guard<std::mutex> tlock(g_coll_table_mu);
  for (int k = 0; k < 3; k++) {
    if (!g_coll_staged_set[k]) continue;  // never-staged kinds keep theirs
    g_coll_table[k].entries = g_coll_staged[k].entries;
    g_coll_staged[k].entries.clear();
    g_coll_staged_set[k] = false;
  }
  g_coll_epoch = epoch;
  return 0;
}

int64_t tpucomm_coll_epoch(void) {
  std::lock_guard<std::mutex> lock(g_coll_table_mu);
  return g_coll_epoch;
}

int tpucomm_coll_algo_for(int64_t h, int op_kind, int64_t nbytes) {
  Comm* c = get_comm(h);
  if (!c || op_kind < 0 || op_kind > 2) return -1;
  /* count only gates the built-in allreduce heuristic's ring cutoff;
   * approximate with 4-byte elements (the table path ignores it).
   * The probe has no dtype/op context: assume the quant-eligible case
   * (f32 SUM) so it reports qring/qrd where the table picks them — an
   * actual ineligible call degrades to the exact twin at dispatch. */
  return resolve_coll_algo(c, op_kind, nbytes, nbytes / 4, TPU_COLL_AUTO,
                           TPU_F32, TPU_SUM);
}

/* ---- quantized wire codec (diag / tests / accuracy-harness probe) ---- */

int64_t tpucomm_quant_packed_bytes(int64_t count) {
  return quant_packed_bytes(count);
}

int tpucomm_quant_pack(const void* in, int64_t count, int dtype, void* out) {
  if (!quant_dtype_ok(dtype)) return 1;
  if (count <= 0) return 0;
  std::vector<float> tmp((size_t)count);
  quant_load_f32(in, dtype, count, tmp.data());
  quant_pack_f32(tmp.data(), count, static_cast<char*>(out));
  return 0;
}

int tpucomm_quant_unpack(const void* in, int64_t count, int dtype,
                         void* out) {
  if (!quant_dtype_ok(dtype)) return 1;
  if (count <= 0) return 0;
  std::vector<float> tmp((size_t)count);
  quant_unpack_f32(static_cast<const char*>(in), count, tmp.data());
  quant_store_f32(tmp.data(), dtype, count, out);
  return 0;
}

void tpucomm_obs_enable(int enabled, int64_t capacity) {
  std::lock_guard<std::mutex> lock(g_obs_mu);
  if (enabled) {
    if (capacity < 16) capacity = 16;
    g_obs_ring.assign((size_t)capacity, TpuObsEvent{});
  } else {
    g_obs_ring.clear();
    g_obs_ring.shrink_to_fit();
  }
  g_obs_total = 0;
  g_obs_dropped = 0;
  g_obs_seq = 0;
  /* flip the hot-path flag LAST on enable (an op racing this call may
   * observe on=1 with the ring already sized, never a stale ring) */
  g_obs_on.store(enabled ? 1 : 0, std::memory_order_release);
}

void tpucomm_obs_counts(int64_t* out_recorded, int64_t* out_dropped) {
  std::lock_guard<std::mutex> lock(g_obs_mu);
  const int64_t cap = (int64_t)g_obs_ring.size();
  if (out_recorded)
    *out_recorded = g_obs_total < cap ? g_obs_total : cap;
  if (out_dropped) *out_dropped = g_obs_dropped;
}

int64_t tpucomm_obs_drain(TpuObsEvent* out, int64_t max_n) {
  std::lock_guard<std::mutex> lock(g_obs_mu);
  const int64_t cap = (int64_t)g_obs_ring.size();
  if (cap == 0 || max_n <= 0) return 0;
  int64_t held = g_obs_total < cap ? g_obs_total : cap;
  int64_t n = held < max_n ? held : max_n;
  /* oldest-first: when the ring wrapped, the oldest held event sits at
   * g_obs_total % cap; copy the NEWEST n of the held events in order */
  int64_t first = g_obs_total - n;  // index of the oldest copied event
  for (int64_t i = 0; i < n; i++)
    out[i] = g_obs_ring[(size_t)((first + i) % cap)];
  /* held events the caller's buffer could not take (e.g. appended
   * between its count probe and this drain) are COUNTED, never lost
   * silently — the exact-drop-accounting contract */
  g_obs_dropped += held - n;
  g_obs_total = 0;  // drain clears held events; dropped survives
  return n;
}

int64_t tpucomm_obs_peek(TpuObsEvent* out, int64_t max_n, int64_t* cursor,
                         int64_t* out_skipped) {
  std::lock_guard<std::mutex> lock(g_obs_mu);
  if (out_skipped) *out_skipped = 0;
  if (!cursor) return 0;
  const int64_t cap = (int64_t)g_obs_ring.size();
  if (cap == 0 || max_n <= 0) return 0;
  int64_t held = g_obs_total < cap ? g_obs_total : cap;
  /* the held events occupy the absolute sequence range
   * [g_obs_seq - held, g_obs_seq); anything older was overwritten by
   * overflow or cleared by a destructive drain */
  int64_t oldest = g_obs_seq - held;
  int64_t cur = *cursor;
  if (cur < 0) cur = 0;
  if (cur > g_obs_seq) cur = oldest;  // cursor from before a re-enable
  if (cur < oldest) {
    if (out_skipped) *out_skipped = oldest - cur;
    cur = oldest;
  }
  int64_t avail = g_obs_seq - cur;
  int64_t n = avail < max_n ? avail : max_n;
  for (int64_t i = 0; i < n; i++) {
    /* slot of sequence number s: the newest held event (s = seq-1)
     * sits at (g_obs_total - 1) % cap and slots run backwards from
     * there — valid for every s >= oldest because drain resets
     * g_obs_total and g_obs_seq never moves backwards */
    int64_t s = cur + i;
    out[i] = g_obs_ring[(size_t)((g_obs_total - (g_obs_seq - s)) % cap)];
  }
  *cursor = cur + n;
  return n;
}

double tpucomm_obs_clock(void) { return now_s(); }

void tpucomm_link_counters(int64_t* retries, int64_t* reconnects,
                           int64_t* dup_dropped, int64_t* crc_errors,
                           int64_t* replayed, int64_t* heartbeats) {
  /* process totals, monotone since load; all zero unless armed (the
   * counters only increment on armed paths).  The symbol itself doubles
   * as the bridge's layout probe for the 80-byte TpuObsEvent. */
  if (retries) *retries = g_lc_retries.load(std::memory_order_relaxed);
  if (reconnects)
    *reconnects = g_lc_reconnects.load(std::memory_order_relaxed);
  if (dup_dropped)
    *dup_dropped = g_lc_dup_dropped.load(std::memory_order_relaxed);
  if (crc_errors)
    *crc_errors = g_lc_crc_errors.load(std::memory_order_relaxed);
  if (replayed) *replayed = g_lc_replayed.load(std::memory_order_relaxed);
  if (heartbeats)
    *heartbeats = g_lc_heartbeats.load(std::memory_order_relaxed);
}

int tpucomm_reduce(int64_t h, const void* sendbuf, void* recvbuf,
                   int64_t count, int dtype, int op, int root) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp eop;
  eop.kind = TPU_OBS_REDUCE;
  eop.sbuf = sendbuf;
  eop.rbuf = recvbuf;
  eop.count = count;
  eop.dtype = dtype;
  eop.rop = op;
  eop.peer = root;
  return engine_submit(c, &eop);
}

int tpucomm_scan(int64_t h, const void* sendbuf, void* recvbuf,
                 int64_t count, int dtype, int op) {
  Comm* c = get_comm(h);
  if (!c) return 1;
  EngineOp eop;
  eop.kind = TPU_OBS_SCAN;
  eop.sbuf = sendbuf;
  eop.rbuf = recvbuf;
  eop.count = count;
  eop.dtype = dtype;
  eop.rop = op;
  return engine_submit(c, &eop);
}

/* ---- batched dispatch entry (the Python bridge's descriptor hop) ---- */

int tpucomm_execute(int64_t h, const struct TpuOpExec* d) {
  Comm* c = get_comm(h);
  if (!c || !d) return 1;
  EngineOp op;
  op.kind = d->kind;
  op.sbuf = d->sbuf;
  op.rbuf = d->rbuf;
  op.snb = d->snbytes;
  op.rnb = d->rnbytes;
  op.count = d->count;
  op.dtype = d->dtype;
  op.rop = d->rop;
  op.peer = d->peer;
  op.peer2 = d->peer2;
  op.tag = d->tag;
  op.tag2 = d->tag2;  // sendrecv: the bridge sets tag2 == tag
  op.algo = d->algo;
  return engine_submit(c, &op);
}

/* ---- ticketed non-blocking posting (schedule-plan execution) ----
 *
 * The descriptor is heap-allocated and FORCE-QUEUED (never run inline,
 * even on an idle engine): the whole point is returning to the caller
 * before the op completes, so the progress thread can read/write the
 * wire while the host computes.  The queue drains FIFO, so post order
 * is wire order — the exact model the schedule compiler's equivalence
 * prover verified before any plan reaches this entry point. */

int64_t tpucomm_post(int64_t h, const struct TpuOpExec* d) {
  Comm* c = get_comm(h);
  if (!c || !d) return 0;
  auto* op = new EngineOp;
  op->kind = d->kind;
  op->comm = c;
  op->sbuf = d->sbuf;
  op->rbuf = d->rbuf;
  op->snb = d->snbytes;
  op->rnb = d->rnbytes;
  op->count = d->count;
  op->dtype = d->dtype;
  op->rop = d->rop;
  op->peer = d->peer;
  op->peer2 = d->peer2;
  op->tag = d->tag;
  op->tag2 = d->tag2;
  op->algo = d->algo;
  Comm* root = c->lock_root;
  std::lock_guard<std::mutex> lock(comm_mu(c));
  Engine* e = root->engine;
  if (e && e->sticky.load(std::memory_order_acquire)) {
    delete op;
    std::fprintf(stderr,
                 "tpucomm r%d: post rejected — an earlier asynchronously "
                 "posted send failed (see the diagnostic above)\n",
                 c->rank);
    return 0;
  }
  op->t_post = now_s();
  if (!progress_thread_on()) {
    /* engine off: execute inline now; the ticket is already complete,
     * so plan execution degrades to the historic serialized order
     * bit-for-bit (MPI4JAX_TPU_PLAN composes with PROGRESS_THREAD=0) */
    op->rc = engine_run_body(op);
    op->state.store(1, std::memory_order_release);
    return reinterpret_cast<int64_t>(op);
  }
  engine_post(root, op);
  return reinterpret_cast<int64_t>(op);
}

int tpucomm_wait_ticket(int64_t h, int64_t ticket) {
  (void)h;  // the ticket IS the descriptor; the handle is for symmetry
  if (!ticket) return 1;
  auto* op = reinterpret_cast<EngineOp*>(ticket);
  while (op->state.load(std::memory_order_acquire) == 0)
    shm_futex_wait(&op->state, 0, 100);
  int rc = op->rc;
  delete op;
  return rc;
}

}  /* extern "C" */
