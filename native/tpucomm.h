/* tpucomm — host-side communication runtime for mpi4jax_tpu's world tier.
 *
 * The native substrate replacing libmpi in the reference stack (see
 * SURVEY.md §2.3: mpi_xla_bridge.pyx wraps libmpi; this library *is* the
 * message layer): a TCP mesh between one process per rank, with the twelve
 * MPI-style operations implemented over framed point-to-point messages.
 *
 * All functions return 0 on success, nonzero on failure after printing a
 * diagnostic to stderr (fail-fast contract; callers abort the process —
 * the analog of MPI_Abort in the reference's abort_on_error).
 *
 * Dtype codes match mpi4jax_tpu/utils/dtypes.py; op codes match
 * mpi4jax_tpu/ops/reduce_ops.py order.
 */
#ifndef TPUCOMM_H
#define TPUCOMM_H

#include <cstddef>
#include <cstdint>

extern "C" {

/* dtype wire codes (keep in sync with utils/dtypes.py) */
enum TpuDtype {
  TPU_BOOL = 0, TPU_I8, TPU_I16, TPU_I32, TPU_I64,
  TPU_U8, TPU_U16, TPU_U32, TPU_U64,
  TPU_F16, TPU_BF16, TPU_F32, TPU_F64, TPU_C64, TPU_C128,
};

/* reduce op codes */
enum TpuOp {
  TPU_SUM = 0, TPU_PROD, TPU_MAX, TPU_MIN,
  TPU_LAND, TPU_LOR, TPU_LXOR, TPU_BAND, TPU_BOR, TPU_BXOR,
};

/* Collective algorithm codes (keep in sync with mpi4jax_tpu/tune).
 * AUTO consults the installed decision table (tpucomm_set_coll_table),
 * falling back to the built-in heuristic when no table entry matches.
 * SHM is report-only: the same-host arena fast path always wins when a
 * communicator has one (the selector governs the TCP/multi-host path). */
enum TpuCollAlgo {
  TPU_COLL_AUTO = 0,
  TPU_COLL_RING = 1,  /* chunked ring (bandwidth-optimal) */
  TPU_COLL_RD = 2,    /* recursive doubling (latency-optimal, log2 rounds) */
  TPU_COLL_TREE = 3,  /* binomial reduce-to-root + tree bcast */
  TPU_COLL_SHM = 4,   /* report-only: same-host shared-memory arena */
  /* Quantized wire formats (EQuARX-style in-collective block
   * quantization): the ring / recursive-doubling allreduce schedules
   * with every wire frame carrying int8 codes + per-block f32 absmax
   * scales instead of full-precision elements (~4x fewer payload bytes
   * for f32, ~2x for bf16/f16).  Results are APPROXIMATE (~1e-2
   * relative error) and rank-consistent (every rank computes identical
   * output bits).  Allreduce only; legal for real floating dtypes with
   * SUM — any other (dtype, op) silently degrades to the exact
   * counterpart (ring / rd), so a table row or forced code never
   * corrupts an integer or MAX reduction.  MPI4JAX_TPU_COLL_QUANT
   * (allow | deny | force) gates them process-wide. */
  TPU_COLL_QRING = 5, /* quantized chunked ring */
  TPU_COLL_QRD = 6,   /* quantized recursive doubling */
  /* Hierarchical (topology-aware) schedules: intra-island reduce to the
   * island leader (shm arena when the island shares a host, serial TCP
   * otherwise) -> leader-tier allreduce over the inter-island links
   * (ring for HRING, recursive doubling for HTREE; upgraded to the
   * qring/qrd quantized twin on THAT LEG ONLY under
   * MPI4JAX_TPU_COLL_QUANT=force) -> intra-island bcast.  Require a
   * multi-island topology installed via tpucomm_set_topology; degrade
   * to their flat counterparts (ring / tree) on a flat comm or under
   * MPI4JAX_TPU_HIER=deny, and MPI4JAX_TPU_HIER=force upgrades every
   * eligible flat pick.  Also valid for allgather (intra gather ->
   * leader ring allgatherv of island blocks -> intra bcast, any island
   * shapes).  Must agree across ranks like every other algorithm. */
  TPU_COLL_HRING = 7, /* hierarchical: intra reduce + leader ring + bcast */
  TPU_COLL_HTREE = 8, /* hierarchical: intra reduce + leader rd + bcast */
  /* Alltoall family (MoE expert dispatch/combine is the workload).  The
   * flat pairwise exchange keeps code TPU_COLL_RING — rd/tree have no
   * alltoall schedule, so any other non-alltoall code canonicalizes to
   * RING at resolution.  QA2A puts the qring/qrd int8 block codec on
   * every off-rank chunk (per-256-element absmax scales packed into the
   * frame; the own-rank chunk never crosses the wire and stays exact;
   * rank-consistent by construction — each destination dequantizes the
   * sender's packed bytes).  HA2A is the hierarchical schedule
   * (generalizing hier_allgather's uneven-island block machinery):
   * intra-island exchange over the shm/ici tier, then ONLY the
   * cross-island chunk blocks travel the leader tier, then an
   * intra-island scatter — a pure permutation, bit-identical to the
   * flat exchange.  HQA2A quantizes the leader leg only (each
   * cross-island block packed as one codec frame).  Alltoall only;
   * gated by MPI4JAX_TPU_COLL_QUANT / MPI4JAX_TPU_HIER with the exact
   * allow/deny/force semantics of the allreduce twins — an ineligible
   * dtype or a flat comm degrades toward the exact flat exchange
   * consistently across ranks. */
  TPU_COLL_QA2A = 9,   /* quantized pairwise alltoall */
  TPU_COLL_HA2A = 10,  /* hierarchical alltoall (exact) */
  TPU_COLL_HQA2A = 11, /* hierarchical alltoall, quantized leader leg */
};

/* op kinds for the per-op decision tables */
enum TpuCollOpKind {
  TPU_OPKIND_ALLREDUCE = 0,
  TPU_OPKIND_ALLGATHER = 1,
  TPU_OPKIND_ALLTOALL = 2,
};

/* Create a communicator: rank/size, base TCP port, comma-separated host
 * list ("" = all localhost). Returns handle > 0, or 0 on failure. */
int64_t tpucomm_init(int rank, int size, int base_port, const char* hosts);
void tpucomm_finalize(int64_t h);

/* Elastic recovery (mpi4jax_tpu/elastic is the owner): rebuild a
 * world-tier communicator over the surviving ranks after a rank
 * failure.  Finalizes `old_h` (drains its engine, closes its sockets;
 * pass 0 when no old comm exists), then runs the SAME bootstrap as
 * tpucomm_init — listen/dial with the MPI4JAX_TPU_CONNECT_TIMEOUT_S
 * deadline, per-rank handshake, shm arena — with the caller-supplied
 * dense renumbering: `new_rank`/`new_size` describe the shrunk (or
 * respawned) world and `base_port` is the new generation's re-derived
 * port block (the launcher's generation announcement carries it).
 * Every surviving rank must call this at the same recovery point with
 * agreeing arguments.  Fault injection keyed on rank R keeps firing on
 * the process BORN as rank R regardless of renumbering.  Returns a new
 * handle > 0, or 0 on failure (bootstrap timeout, port in use). */
int64_t tpucomm_shrink(int64_t old_h, int new_rank, int new_size,
                       int base_port, const char* hosts);

int tpucomm_rank(int64_t h);
int tpucomm_size(int64_t h);
int tpucomm_shm_info(int64_t h, int64_t* slot_bytes, int64_t* ring_bytes);
void tpucomm_set_logging(int enabled);

/* Collective sub-communicator creation (MPI_Comm_split / MPI_Comm_dup
 * analogs). Returns a new handle, -1 when color < 0 (not a member), or
 * 0 on failure. The child shares the parent's sockets (keep the parent
 * alive); frame headers carry the comm id so misrouted messages between
 * sibling comms abort instead of corrupting. */
int64_t tpucomm_split(int64_t h, int color, int key);
int64_t tpucomm_dup(int64_t h);

/* ---- topology (mpi4jax_tpu/topo is the owner) ----
 *
 * Install the discovered locality map on a communicator:
 * `island_of[r]` assigns member rank r to an island (ranks sharing a
 * host/shm domain; ids must be dense 0..n_islands-1, ordered by each
 * island's lowest member rank).  `intra_h` is this rank's intra-island
 * sub-communicator (0/-1 when its island is a singleton), `leader_h`
 * the leaders' sub-communicator (0/-1 on non-leader ranks); both come
 * from tpucomm_split over `h` with (color=island, key=rank) and
 * (color=leader?0:-1, key=rank) respectively — the Python bridge
 * performs the splits and this call wires them up.  With more than one
 * island installed, the hierarchical algorithms (TPU_COLL_HRING/HTREE)
 * become eligible and bcast/reduce route hierarchically for large
 * payloads (>= 64 KiB, always under MPI4JAX_TPU_HIER=force, never
 * under =deny).  Returns 0 on success, nonzero on an inconsistent map.
 * Every rank of the communicator must install an AGREEING topology
 * (divergence fails fast on the transport's frame checks).
 *
 * MPI4JAX_TPU_FAKE_HOSTS=r0,r1|r2,r3 partitions the ranks of a
 * single-machine job into virtual hosts (read natively at bootstrap):
 * the shm arena is granted per virtual host instead of per real host,
 * so every multi-island shape is testable over loopback.  Ranks not
 * listed keep their real host. */
int tpucomm_set_topology(int64_t h, const int32_t* island_of, int n,
                         int64_t intra_h, int64_t leader_h);

/* Probe the installed topology: writes island_of (size ints; caller
 * allocates) and the island count.  Returns 0 when a topology is
 * installed, 1 when the comm is flat (outputs untouched), -1 on a bad
 * handle. */
int tpucomm_topo_info(int64_t h, int32_t* out_island_of,
                      int32_t* out_n_islands);

/* Human-readable text for the most recent failure in this process (the
 * analog of MPI_Error_string); "" if none. */
const char* tpucomm_last_error(void);

/* Resolved state of the io_uring submission backend (MPI4JAX_TPU_URING;
 * probes the kernel on first call): "on", "on(no-zerocopy)" (ring up,
 * kernel predates IORING_OP_SEND_ZC), "off" (knob = 0), or
 * "unavailable(<reason>)".  This symbol doubles as the layout probe for
 * the uring generation: a library without it never writes
 * TpuObsEvent.syscalls and has no uring path at all — the Python side
 * must treat such a build as uring-unavailable, never misparse it. */
const char* tpucomm_uring_status(void);

/* Process-total transport syscalls (write/read/writev/poll/
 * io_uring_enter; futex parks excluded) since load — the benchmarks'
 * syscalls-per-message denominator reads deltas of this. */
int64_t tpucomm_syscall_count(void);

/* Process-total self-healing link counters since load (all zero unless
 * MPI4JAX_TPU_RETRY > 0 armed the link layer):
 *   retries      recovery events entered (a failing I/O that attempted
 *                a reconnect, successful or not)
 *   reconnects   successful reconnect handshakes (link healed in-place)
 *   dup_dropped  duplicate data frames discarded by the receiver's
 *                sequence dedup (replay overlap — proof the
 *                exactly-once layer did work)
 *   crc_errors   header/control CRC32C mismatches detected (each one
 *                is treated as a link failure and healed or escalated)
 *   replayed     retained frames retransmitted during reconnects
 *   heartbeats   progress-thread pings sent on idle links
 * Null out-pointers are skipped.  This symbol doubles as the layout
 * probe for the self-healing generation: a library exporting it writes
 * TpuObsEvent.retries (80-byte slots); one without it never does
 * (72-byte slots) — the Python side keys the struct layout on this. */
void tpucomm_link_counters(int64_t* retries, int64_t* reconnects,
                           int64_t* dup_dropped, int64_t* crc_errors,
                           int64_t* replayed, int64_t* heartbeats);

/* Job-wide abort propagation: best-effort write one poison control
 * frame (carrying tpucomm_last_error's text) to every peer of every
 * socket-owning communicator and shut the sockets down.  Peers blocked
 * in any receive consume the poison and fail fast naming this rank, so
 * the group tears down within one transport deadline instead of
 * waiting for timeouts to cascade.  Entirely non-blocking; call it
 * immediately before exiting the process on an error (the Python
 * bridge's abort path does).
 *
 * Failure-detection knobs read natively (see utils/config.py):
 *   MPI4JAX_TPU_TIMEOUT_S          progress-based deadline on every
 *                                  blocking transport wait (0 = off)
 *   MPI4JAX_TPU_CONNECT_TIMEOUT_S  bootstrap dial/accept deadline
 *   MPI4JAX_TPU_FAULT              deterministic fault injection:
 *                                  rank=R,point=send|recv|connect,
 *                                  after=N,action=hang|exit|close|
 *                                  reset|drop|delay|corrupt
 *                                  (+ bytes=N for drop, ms=N for
 *                                  delay; the four new actions are
 *                                  one-shot transients the self-healing
 *                                  link layer is expected to absorb)
 *   MPI4JAX_TPU_RETRY              reconnect attempts per link failure
 *                                  (0 = self-healing off, the default:
 *                                  today's fail-fast path bit-for-bit)
 *   MPI4JAX_TPU_RETRY_BACKOFF_MS   first reconnect backoff window
 *                                  (exponential + jitter, default 100)
 *   MPI4JAX_TPU_HEARTBEAT_S        progress-thread idle-link ping
 *                                  period (0 = off, the default)
 *   MPI4JAX_TPU_WIRE_CRC           CRC32C on wire headers/control
 *                                  frames: auto (on iff RETRY>0)|0|1 */
void tpucomm_abort_all(void);

/* Point-to-point.  dest/source == own rank is legal (MPI-style
 * self-messaging: send enqueues on an in-process queue, recv pops it;
 * source may also be -2 = ANY_SOURCE, resolved by polling all peers). */
int tpucomm_send(int64_t h, const void* buf, int64_t nbytes, int dest,
                 int tag);
int tpucomm_recv(int64_t h, void* buf, int64_t nbytes, int source, int tag);
int tpucomm_sendrecv(int64_t h, const void* sendbuf, int64_t send_nbytes,
                     int dest, void* recvbuf, int64_t recv_nbytes,
                     int source, int tag);

/* Status-reporting variants: tag may be -1 (ANY_TAG); messages shorter
 * than the buffer are accepted; the actual source/tag/byte-count are
 * written to the out-params (MPI_Status analog). */
int tpucomm_recv_status(int64_t h, void* buf, int64_t nbytes, int source,
                        int tag, int32_t* out_src, int32_t* out_tag,
                        int64_t* out_count);
int tpucomm_sendrecv_status(int64_t h, const void* sendbuf,
                            int64_t send_nbytes, int dest, void* recvbuf,
                            int64_t recv_nbytes, int source, int sendtag,
                            int recvtag, int32_t* out_src, int32_t* out_tag,
                            int64_t* out_count);
/* Bidirectional 1-D neighbor exchange in one op (MPI_Neighbor_alltoall
 * analog on a ring segment): sendbuf = [to_lo|to_hi] strips of
 * strip_nbytes each, recvbuf = [from_lo|from_hi]; -1 neighbor = wall
 * (output strip is the input passthrough).  Deadlock-free for any ring
 * when all members call at the same program position. */
int tpucomm_shift2(int64_t h, const void* sendbuf, void* recvbuf,
                   int64_t strip_nbytes, int lo, int hi, int tag);
int tpucomm_barrier(int64_t h);
int tpucomm_bcast(int64_t h, void* buf, int64_t nbytes, int root);
int tpucomm_gather(int64_t h, const void* sendbuf, int64_t nbytes,
                   void* recvbuf /* size*nbytes, root only */, int root);
int tpucomm_scatter(int64_t h, const void* sendbuf /* size*nbytes, root */,
                    void* recvbuf, int64_t nbytes, int root);
int tpucomm_allgather(int64_t h, const void* sendbuf, int64_t nbytes,
                      void* recvbuf /* size*nbytes */);
int tpucomm_alltoall(int64_t h, const void* sendbuf /* size*chunk */,
                     void* recvbuf /* size*chunk */, int64_t chunk_nbytes);
int tpucomm_allreduce(int64_t h, const void* sendbuf, void* recvbuf,
                      int64_t count, int dtype, int op);
int tpucomm_reduce(int64_t h, const void* sendbuf, void* recvbuf,
                   int64_t count, int dtype, int op, int root);
int tpucomm_scan(int64_t h, const void* sendbuf, void* recvbuf,
                 int64_t count, int dtype, int op);

/* ---- collective algorithm engine (mpi4jax_tpu/tune is the owner) ----
 *
 * Explicit-algorithm variants: `algo` is a TpuCollAlgo code forced for
 * this one call (AUTO = table/heuristic selection as usual).  Every
 * rank of a communicator must pass the SAME algorithm for the same
 * call — the algorithms exchange different message schedules, and a
 * divergent choice fails fast on the ordered transport's frame checks
 * (tag/size mismatch) rather than corrupting data. */
int tpucomm_allreduce_algo(int64_t h, const void* sendbuf, void* recvbuf,
                           int64_t count, int dtype, int op, int algo);
int tpucomm_allgather_algo(int64_t h, const void* sendbuf, int64_t nbytes,
                           void* recvbuf, int algo);
/* Typed alltoall: `count` elements of `dtype` per destination chunk
 * (sendbuf/recvbuf hold size*count elements).  The dtype context is
 * what makes the quantized wire formats (TPU_COLL_QA2A / HQA2A)
 * resolvable — the legacy byte-chunk tpucomm_alltoall has none and
 * always runs the exact exchange. */
int tpucomm_alltoall_algo(int64_t h, const void* sendbuf, void* recvbuf,
                          int64_t count, int dtype, int algo);

/* Install the process-wide decision table for one op kind: `n` entries
 * of (min_bytes ascending, TpuCollAlgo).  A call with payload `nbytes`
 * under AUTO picks the last entry with min_bytes <= nbytes; an empty
 * table (n = 0) restores the built-in heuristic.  The Python tune
 * package pushes this at communicator creation and on override. */
void tpucomm_set_coll_table(int op_kind, const int64_t* min_bytes,
                            const int32_t* algos, int n);

/* ---- live re-tuning (mpi4jax_tpu/live is the owner) ----
 *
 * Stage-then-commit twin of tpucomm_set_coll_table, so every rank can
 * prepare a candidate table asynchronously and install it at an agreed
 * collective boundary.  tpucomm_stage_coll_table validates and parks
 * one op kind's entries in a staging slot WITHOUT touching dispatch;
 * tpucomm_commit_coll_tables atomically promotes every staged kind to
 * the live table under the comm lock with the progress engine quiesced
 * (the tpucomm_set_topology swap discipline — no op may be mid-flight
 * while the decision table it resolved against changes), and stamps
 * the process-wide table epoch.  Ranks that commit the same staged
 * tables at the same collective boundary therefore keep algorithm
 * agreement; the epoch is readable (tpucomm_coll_epoch) so the Python
 * controller and diag can assert which generation is live. */
void tpucomm_stage_coll_table(int op_kind, const int64_t* min_bytes,
                              const int32_t* algos, int n);

/* Promote all staged tables under comm `h`'s lock (engine quiesced) and
 * set the table epoch.  Kinds never staged since the last commit keep
 * their live table.  Returns 0 on success, 1 for a bad handle. */
int tpucomm_commit_coll_tables(int64_t h, int64_t epoch);

/* The live decision-table epoch: 0 at load (the offline-installed
 * table), then whatever the last successful commit stamped. */
int64_t tpucomm_coll_epoch(void);

/* Resolution probe for diag/tracing: the TpuCollAlgo code that WOULD
 * run for (comm, op kind, payload bytes) — including TPU_COLL_SHM when
 * the same-host arena path serves the call.  -1 for a bad handle.
 * The probe has no dtype/op context, so it assumes the quant-eligible
 * case (f32 SUM): it reports TPU_COLL_QRING/QRD where the table picks
 * them; an actual int or MAX call at that size degrades to the exact
 * counterpart at dispatch. */
int tpucomm_coll_algo_for(int64_t h, int op_kind, int64_t nbytes);

/* ---- quantized wire format (qring / qrd payload codec) ----
 *
 * The EQuARX-style block codec the quantized algorithms put on the
 * wire, exported so diag / tests / the Python accuracy harness can
 * round-trip the EXACT native format: `count` elements quantize to
 * ceil(count/256) f32 absmax scales followed by `count` int8 codes in
 * one contiguous buffer of tpucomm_quant_packed_bytes(count) bytes.
 * Codes are round-to-nearest-even of value/scale, clipped to ±127;
 * scale = blockwise absmax/127 (1.0 for an all-zero block).  Legal
 * dtypes: F16 / BF16 / F32 / F64 (the conversion runs through f32).
 * Both functions return 0 on success, nonzero on an ineligible dtype. */
int64_t tpucomm_quant_packed_bytes(int64_t count);
int tpucomm_quant_pack(const void* in, int64_t count, int dtype, void* out);
int tpucomm_quant_unpack(const void* in, int64_t count, int dtype,
                         void* out);

/* ---- observability event ring (mpi4jax_tpu/obs is the owner) ----
 *
 * A fixed-size in-memory ring of per-op records: every transport entry
 * point appends one event (op, peer/root, tag, bytes, algorithm, and a
 * wait-phase/transfer-phase timing split) when recording is enabled.
 * Overflow overwrites the OLDEST events and counts every overwrite, so
 * a drained recording always says exactly how much it is missing.
 * When disabled (the default) the hot path pays one relaxed atomic
 * load per op and performs no ring writes and no clock reads. */

/* op codes for TpuObsEvent.op (order is the wire contract with
 * mpi4jax_tpu/obs/_native.py's OBS_OP_NAMES) */
enum TpuObsOp {
  TPU_OBS_SEND = 0, TPU_OBS_RECV, TPU_OBS_SENDRECV, TPU_OBS_SHIFT2,
  TPU_OBS_BARRIER, TPU_OBS_BCAST, TPU_OBS_GATHER, TPU_OBS_SCATTER,
  TPU_OBS_ALLGATHER, TPU_OBS_ALLTOALL, TPU_OBS_ALLREDUCE,
  TPU_OBS_REDUCE, TPU_OBS_SCAN,
};

/* transport tier an event's bytes moved on (TpuObsEvent.tier).  FLAT is
 * every non-hierarchical op (the whole-op record of a hierarchical
 * collective is also FLAT — its per-leg children carry the split).
 * INTRA/INTER label the legs a hierarchical collective emits in
 * addition to its whole-op record, so obs.stats() splits intra- from
 * inter-island bytes.  ICI is reserved for device-mesh collectives
 * (lax.psum / Pallas RDMA) routed outside this host transport. */
enum TpuObsTier {
  TPU_TIER_FLAT = 0,
  TPU_TIER_INTRA = 1,  /* within one island (shm arena / same host) */
  TPU_TIER_INTER = 2,  /* between island leaders (TCP / DCN) */
  TPU_TIER_ICI = 3,    /* reserved: on-device ICI mesh */
};

struct TpuObsEvent {
  double t_start;  /* seconds on the recorder clock (tpucomm_obs_clock);
                    * for engine-queued ops this is the POST time, so the
                    * event covers dispatch + wait + wire */
  double dur_s;    /* whole-op wall time, post -> completion */
  double wait_s;   /* blocked share: header arrival waits + barrier waits
                    * accumulated inside the op */
  double queue_s;  /* dispatch share: post -> native execution start (the
                    * submission-queue delay; 0 for inline execution).
                    * wire = dur - queue - wait */
  int64_t nbytes;  /* LOGICAL payload bytes of this call (0 for barrier) */
  int64_t wire_bytes; /* the payload's on-wire representation: equal to
                    * nbytes for every exact op; the packed (int8 codes
                    * + f32 scales) size for quantized collectives —
                    * nbytes / wire_bytes is the compression ratio */
  int32_t op;      /* TpuObsOp */
  int32_t peer;    /* peer/root rank; -1 when not applicable */
  int32_t tag;     /* user tag; 0 when not applicable */
  int32_t algo;    /* TpuCollAlgo that served the call; -1 when n/a */
  int32_t tier;    /* TpuObsTier: 0 flat/whole-op, 1 intra-island leg,
                    * 2 inter-island leg (hierarchical collectives emit
                    * one extra event per leg carrying the tier) */
  int32_t syscalls; /* transport syscalls (write/read/writev/poll/
                    * io_uring_enter — futexes excluded) issued while
                    * this op executed, so stats/traces attribute the
                    * submit-batching win.  Occupies the former padding
                    * slot (layout unchanged, still 72-byte slots);
                    * probe tpucomm_uring_status to tell a library that
                    * writes it from one whose slot is always 0. */
  int32_t retries; /* link self-heal events (successful reconnect +
                    * replay cycles) absorbed while this op executed —
                    * nonzero marks an op whose latency includes a
                    * transparent recovery.  Grows the slot to 80
                    * bytes; probe tpucomm_link_counters to tell an
                    * 80-byte library from a 72-byte one. */
  int32_t reserved0; /* keeps the slot 8-byte aligned; always 0 */
};

/* Arm (enabled=1) or disarm (0) recording.  `capacity` is the ring size
 * in events (clamped to >= 16); re-enabling resizes and clears. */
void tpucomm_obs_enable(int enabled, int64_t capacity);

/* Totals since the last enable/drain: events currently held, and the
 * exact number overwritten by overflow. */
void tpucomm_obs_counts(int64_t* out_recorded, int64_t* out_dropped);

/* Copy up to max_n held events into `out` (the newest max_n, in
 * oldest-first order), then clear the ring.  Held events that do not
 * fit `out` are added to the drop counter — never silently lost; the
 * drop counter survives until re-enable.  Returns the number copied. */
int64_t tpucomm_obs_drain(struct TpuObsEvent* out, int64_t max_n);

/* Non-destructive cursor read: copy up to max_n events appended at or
 * after `*cursor` (an absolute per-enable sequence number; pass 0 to
 * start from the oldest held) into `out`, oldest first, WITHOUT
 * clearing the ring or touching the drop counter — a second consumer
 * (the live controller) can follow the stream while the end-of-run
 * tpucomm_obs_drain still sees every held event.  On return `*cursor`
 * points one past the last copied event; `*out_skipped` (may be NULL)
 * counts events between the old cursor and the oldest still readable
 * (lost to ring overflow or a destructive drain).  A cursor from
 * before the last re-enable is clamped.  Returns the number copied. */
int64_t tpucomm_obs_peek(struct TpuObsEvent* out, int64_t max_n,
                         int64_t* cursor, int64_t* out_skipped);

/* The recorder's clock (monotonic seconds, arbitrary per-process
 * epoch — the same clock TpuObsEvent.t_start uses), so the Python side
 * can map event times onto the unix epoch by sampling both. */
double tpucomm_obs_clock(void);

/* ---- async progress engine (batched dispatch entry) ----
 *
 * One descriptor-driven entry point serving every transport op: the
 * Python bridge packs a TpuOpExec once per op (a cached struct, no
 * per-call ctypes marshalling of 6-8 scalar arguments) and calls
 * tpucomm_execute.  Internally every op — this entry AND the classic
 * per-op entries above — routes through the progress engine: a
 * dedicated per-communicator progress thread drives a lock-free
 * submission queue, so the caller either returns immediately (small
 * sends: payload copied, completion asynchronous — the buffered-send
 * semantics the static verifier's match model already assumes) or
 * parks on a single completion futex while the progress thread runs
 * the socket I/O.  Small adjacent sends to one peer coalesce into one
 * wire frame (split transparently on the receive side, tags
 * preserved).
 *
 * Engine knobs (read natively, registered in utils/config.py):
 *   MPI4JAX_TPU_PROGRESS_THREAD  1 (default) = engine on; 0 = every op
 *                                executes inline on the calling thread
 *                                (the historic behavior, bit-for-bit)
 *   MPI4JAX_TPU_COALESCE_BYTES   sends <= this many bytes that are
 *                                adjacent in posted order to the same
 *                                peer merge into one frame (default
 *                                4096; 0 disables coalescing)
 *   MPI4JAX_TPU_QUEUE_DEPTH     submission-queue capacity in ops
 *                                (default 1024; posting parks when
 *                                full)
 *   MPI4JAX_TPU_URING           io_uring submission backend under the
 *                                same descriptor queue (auto | 0 | 1,
 *                                strict parser): batched submits, a
 *                                registered staging pool, and
 *                                MSG_ZEROCOPY (IORING_OP_SEND_ZC) for
 *                                sends past the kernel's buffering
 *                                ceiling (tcp_wmem[2]+tcp_rmem[2] —
 *                                below it a plain send completes
 *                                without the receiver but a ZC buffer
 *                                release cannot, and the envelope
 *                                mismatch would deadlock cyclic
 *                                schedules the poll path accepts).
 *                                auto (default)
 *                                probes the kernel; 0 keeps the poll-
 *                                driven path bit-for-bit (sanitizer
 *                                builds, old kernels); 1 asks for it
 *                                loudly (falls back with a warning
 *                                when the kernel cannot).  Wire bytes,
 *                                deadlines (measured from post time),
 *                                poison, and fault injection are
 *                                identical on both paths. */

/* op kinds reuse the TpuObsOp codes; scalar roles per kind:
 *   SEND       sbuf,snbytes -> peer(dest), tag
 *   RECV       rbuf,rnbytes <- peer2(source), tag   (strict size)
 *   SENDRECV   sbuf,snbytes -> peer(dest); rbuf,rnbytes <- peer2, tag
 *   SHIFT2     sbuf=[to_lo|to_hi], rbuf, snbytes=strip, peer(lo),
 *              peer2(hi), tag
 *   BARRIER    (no buffers)
 *   BCAST      rbuf,rnbytes in place, peer(root)
 *   GATHER     sbuf,snbytes -> rbuf (root only), peer(root)
 *   SCATTER    sbuf -> rbuf,rnbytes per rank, peer(root)
 *   ALLGATHER  sbuf,snbytes -> rbuf (size*snbytes); algo
 *   ALLTOALL   sbuf -> rbuf, snbytes = per-peer chunk bytes; count > 0
 *              makes the call typed (count elems/chunk, dtype; algo
 *              then resolves the quantized/hierarchical schedules)
 *   ALLREDUCE  sbuf -> rbuf, count/dtype/rop; algo
 *   REDUCE     sbuf -> rbuf, count/dtype/rop, peer(root)
 *   SCAN       sbuf -> rbuf, count/dtype/rop */
struct TpuOpExec {
  int32_t kind;      /* TpuObsOp code */
  int32_t algo;      /* forced TpuCollAlgo (collectives; 0 = selection) */
  const void* sbuf;
  void* rbuf;
  int64_t snbytes;
  int64_t rnbytes;
  int64_t count;     /* elements (reductions) */
  int32_t dtype;
  int32_t rop;
  int32_t peer;      /* dest / root / lo */
  int32_t peer2;     /* source / hi */
  int32_t tag;
  int32_t tag2;      /* reserved (distinct recv tag) */
};

int tpucomm_execute(int64_t h, const struct TpuOpExec* d);

/* ---- ticketed non-blocking posting (schedule-plan execution) ----
 *
 * tpucomm_post enqueues a descriptor on the communicator's progress
 * engine WITHOUT waiting for completion and returns a ticket (> 0; 0 on
 * failure).  The engine drains its queue strictly in posted order, so
 * post order IS wire order — exactly the FIFO contract the schedule
 * compiler's equivalence prover (mpi4jax_tpu/analysis/_plan.py) models
 * when it verifies a plan.  The caller owns every buffer named in the
 * descriptor until the matching tpucomm_wait_ticket returns.
 *
 * tpucomm_wait_ticket parks on the descriptor's completion futex and
 * returns the op's result code (0 = success), then frees the ticket.
 * Each ticket must be waited exactly once; waiting tickets in post
 * order costs nothing extra (FIFO: an earlier ticket is always done
 * before a later one).  With MPI4JAX_TPU_PROGRESS_THREAD=0 the post
 * executes inline and the wait returns the stored result — plans
 * degrade to the historic serialized execution, never to different
 * semantics.  Deadlines (MPI4JAX_TPU_TIMEOUT_S) measure from post time
 * and fault injection fires inside the op bodies, both exactly as for
 * parked ops. */
int64_t tpucomm_post(int64_t h, const struct TpuOpExec* d);
int tpucomm_wait_ticket(int64_t h, int64_t ticket);

}  /* extern "C" */

#endif  /* TPUCOMM_H */
