"""World-tier (multi-process MPMD) hello — the launcher quickstart.

The reference's quickstart is ``mpirun -n 4 python script.py`` (its
README); here the bundled launcher plays that role:

    python -m mpi4jax_tpu.runtime.launch -n 4 examples/world_hello.py

Each rank is one process; ``get_default_comm()`` returns the world
communicator wired up from the launcher's environment.  Everything below
also works inside ``jax.jit`` (ordered effects serialize the transport
calls per rank).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# World ranks are host processes: pin the CPU backend in-process (an
# accelerator plugin may ignore the JAX_PLATFORMS env var and try to
# claim the device per rank; set WORLD_HELLO_PLATFORM to opt in).
jax.config.update(
    "jax_platforms", os.environ.get("WORLD_HELLO_PLATFORM", "cpu"))

if os.environ.get("MPI4JAX_TPU_RANK") is None:
    sys.exit("run me under the launcher: "
             "python -m mpi4jax_tpu.runtime.launch -n 4 "
             "examples/world_hello.py")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

comm = m4j.get_default_comm()
rank, size = comm.rank(), comm.size()

# collective: every rank contributes, every rank receives
x = jnp.arange(4, dtype=jnp.float32) + rank
total = m4j.allreduce(x, op=m4j.SUM, comm=comm)

# point-to-point ring under jit (per-rank source/dest — true MPMD)
ring = jax.jit(lambda v: m4j.sendrecv(v, shift=1, comm=comm))(x)

# wildcard receive with status introspection (rank 0 drains everyone)
if rank == 0:
    sources = []
    for _ in range(size - 1):
        status = m4j.Status()
        m4j.recv(x, source=m4j.ANY_SOURCE, status=status, comm=comm)
        sources.append(status.Get_source())
    print(f"rank 0 heard from ranks {sorted(sources)}")
else:
    m4j.send(x, dest=0, tag=rank, comm=comm)

# user-defined reduction (MPI_Op_create analog)
absmax = m4j.custom_op(
    "ABSMAX", lambda a, b: jnp.maximum(jnp.abs(a), jnp.abs(b)))
peak = m4j.allreduce(jnp.float32(rank - 1.5), op=absmax, comm=comm)

print(f"rank {rank}/{size}: sum={np.asarray(total)[:2]} "
      f"ring={np.asarray(ring)[:2]} absmax={float(peak)}")
