"""Train the dp x tp x sp GPT on synthetic data (demo CLI).

    python examples/train_gpt.py --mesh 2 2 2 --steps 20
    python examples/train_gpt.py --pp 8 --steps 20     # pipeline variant
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", type=int, nargs=3, default=None,
                    help="dp tp sp (default: auto over all devices)")
    ap.add_argument("--pp", type=int, default=None,
                    help="use the pipeline-parallel model with this many "
                         "stages instead of dp/tp/sp")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from mpi4jax_tpu.models.transformer import GPT, GPTConfig, init_params

    ndev = len(jax.devices())
    rng = np.random.RandomState(0)

    if args.pp:
        from mpi4jax_tpu.models import pp_transformer as ppm

        pp = args.pp
        cfg = GPTConfig(
            vocab=256, d_model=args.d_model, n_heads=args.heads,
            n_layers=max(args.layers, pp), d_ff=4 * args.d_model,
            max_seq=args.seq,
        )
        mesh = Mesh(np.array(jax.devices()[:pp]).reshape(pp), ("pp",))
        model = ppm.PPGPT(cfg, mesh)
        params = ppm.init_params(cfg, pp=pp)
        step = model.train_step_fn(lr=3e-4)
        toks = jnp.asarray(rng.randint(
            0, cfg.vocab, (4, args.batch, args.seq)).astype(np.int32))

        loss, params = step(params, toks)  # compile
        t0 = time.perf_counter()
        for i in range(args.steps):
            loss, params = step(params, toks)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / args.steps
        print(f"pp={pp}: loss {float(loss):.4f}, {dt*1e3:.1f} ms/step")
        return

    if args.mesh:
        dp, tp, sp = args.mesh
    else:
        from __graft_entry__ import _factor3

        dp, tp, sp = _factor3(ndev)
    n = dp * tp * sp
    cfg = GPTConfig(
        vocab=256, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers, d_ff=4 * args.d_model, max_seq=args.seq,
    )
    mesh = Mesh(
        np.array(jax.devices()[:n]).reshape(dp, tp, sp), ("dp", "tp", "sp")
    )
    model = GPT(cfg, mesh)
    params = init_params(cfg, tp=tp)
    opt_state = model.init_opt_state(params)
    step = model.train_step_fn(opt_state)
    toks = jnp.asarray(
        rng.randint(0, cfg.vocab, (args.batch, args.seq)).astype(np.int32)
    )

    loss, params, opt_state = step(params, opt_state, toks)  # compile
    t0 = time.perf_counter()
    for i in range(args.steps):
        loss, params, opt_state = step(params, opt_state, toks)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    print(
        f"mesh (dp={dp}, tp={tp}, sp={sp}): loss {float(loss):.4f}, "
        f"{dt*1e3:.1f} ms/step"
    )


if __name__ == "__main__":
    main()
