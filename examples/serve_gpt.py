"""Continuous-batching GPT serving over the world tier — elastically.

    python -m mpi4jax_tpu.runtime.launch -n 3 --elastic \
        examples/serve_gpt.py --requests 12 --max-new 8

Rank 0 is the frontend (request queue + sequence state), every rank
decodes its slice of the running batch (the DP pattern over the
world-tier transport), and the whole job keeps answering requests
across a rank death: kill a worker mid-stream —

    MPI4JAX_TPU_FAULT=rank=1,point=recv,after=60,action=exit \
    MPI4JAX_TPU_TIMEOUT_S=8 MPI4JAX_TPU_DISABLE_SHM=1 \
    python -m mpi4jax_tpu.runtime.launch -n 3 --elastic \
        examples/serve_gpt.py

— and the survivors shrink, retry the in-flight requests, and drain
the queue (docs/elasticity.md walks through this).

The model is the tiny GPT-2 from ``benchmarks/quant_accuracy.py`` with
random weights (a serving-mechanics demo, not a language demo); greedy
argmax decoding, so completions are deterministic and independent of
the world size — an elastic run returns exactly what an uninterrupted
run would.
"""

import argparse
import importlib.util
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import mpi4jax_tpu  # noqa: E402,F401
from mpi4jax_tpu.elastic import serving  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "m4j_serve_model", os.path.join(REPO, "benchmarks",
                                    "quant_accuracy.py"))
_qa = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_qa)

VOCAB, D_MODEL, N_LAYER, N_HEAD, SEQ = 64, 32, 2, 4, 48


def make_decode_fn():
    import jax
    import jax.numpy as jnp

    # device arrays: numpy params fancy-indexed by a traced token array
    # would call __array__ on the tracer
    params = jax.tree.map(jnp.asarray, _qa.gpt2_init(
        np.random.RandomState(0), VOCAB, D_MODEL, N_LAYER, N_HEAD, SEQ))

    @jax.jit
    def logits_fn(toks):
        return _qa.gpt2_logits(params, jnp.asarray(toks), N_LAYER, N_HEAD)

    def decode_fn(toks, lengths, start, stop):
        # greedy argmax at each row's last real position: a pure
        # function of the row contents, so retried iterations (and
        # shrunk worlds) produce identical tokens
        logits = np.asarray(logits_fn(toks[start:stop]))
        idx = np.asarray(lengths[start:stop], np.int64) - 1
        rows = logits[np.arange(stop - start), idx]
        return rows.argmax(-1).astype(np.int32)

    return decode_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=4)
    args = ap.parse_args()

    comm = transport.get_world_comm()
    _ = comm.handle
    decode_fn = make_decode_fn()

    if comm.rank() != 0:
        serving.serve_worker(comm, decode_fn)
        return

    server = serving.Server(comm, decode_fn, max_batch=args.max_batch)
    rng = np.random.RandomState(7)
    t0 = time.perf_counter()
    for i in range(args.requests):
        prompt = rng.randint(0, VOCAB, size=rng.randint(2, 6)).tolist()
        server.submit(prompt, max_new=args.max_new)
    done = server.run_until_drained()
    server.stop()
    dt = time.perf_counter() - t0

    for r in sorted(done, key=lambda r: r.id):
        print(f"req {r.id}: prompt {r.prompt} -> {r.generated} "
              f"({r.latency_s * 1e3:.1f} ms"
              + (f", {r.retries} retried iter(s)" if r.retries else "")
              + ")")
    lat = sorted(r.latency_s for r in done)
    print(f"served {len(done)} requests in {dt:.2f} s "
          f"(p50 {lat[len(lat) // 2] * 1e3:.1f} ms, "
          f"max {lat[-1] * 1e3:.1f} ms, "
          f"{server.recoveries} recovery(ies), final world size "
          f"{comm.size()})", flush=True)


if __name__ == "__main__":
    main()
