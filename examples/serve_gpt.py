"""KV-cached GPT serving over the world tier — elastically.

    python -m mpi4jax_tpu.runtime.launch -n 3 --elastic \
        examples/serve_gpt.py --requests 12 --max-new 8

Rank 0 is the frontend (admission queue, commit point), the other
ranks run the serving-plane worker loop: prefill ranks chew prompt
chunks against a paged KV cache and ship the finished KV to the decode
ranks, which then produce one token per iteration with an O(1)
``decode_step`` instead of re-running the full sequence
(docs/serving.md).  On a multi-island world (or with
``MPI4JAX_TPU_SERVE_ROLES=disagg``) the two phases land on different
ranks; the whole job keeps answering requests across a rank death —
kill a worker mid-stream:

    MPI4JAX_TPU_FAULT=rank=1,point=recv,after=60,action=exit \
    MPI4JAX_TPU_TIMEOUT_S=8 MPI4JAX_TPU_DISABLE_SHM=1 \
    python -m mpi4jax_tpu.runtime.launch -n 3 --elastic \
        examples/serve_gpt.py

— the survivors shrink, roles re-derive, in-flight requests re-prefill
from their committed tokens, and the queue drains (docs/elasticity.md
covers the recovery machinery).

The model is the tiny seeded GPT the benchmarks share
(``serving.make_jax_gpt_adapter``: jitted fixed-shape decode kernel;
where jax is unusable the identical numpy model serves instead).
Greedy argmax decoding, so completions are deterministic and
independent of world size and role split — an elastic run returns
exactly what an uninterrupted run would.
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import mpi4jax_tpu  # noqa: E402,F401
from mpi4jax_tpu import serving  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402


def make_adapter():
    try:
        return serving.make_jax_gpt_adapter(), "jax (jitted decode)"
    except Exception as err:  # noqa: BLE001 — any jax breakage
        return serving.make_numpy_gpt_adapter(), f"numpy ({err})"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--roles", default=None,
                    help="auto | colocated | disagg (default: the "
                         "MPI4JAX_TPU_SERVE_ROLES knob, then auto)")
    args = ap.parse_args()

    comm = transport.get_world_comm()
    _ = comm.handle
    adapter, backend = make_adapter()

    if comm.rank() != 0:
        serving.serve_worker(comm, adapter, roles_mode=args.roles)
        return

    server = serving.Server(comm, adapter, max_batch=args.max_batch,
                            chunk_tokens=32, roles_mode=args.roles)
    print(f"adapter backend: {backend}; {server.roles.describe()}",
          flush=True)
    rng = np.random.RandomState(7)
    t0 = time.perf_counter()
    for _ in range(args.requests):
        prompt = rng.randint(0, adapter.vocab,
                             size=rng.randint(2, 6)).tolist()
        verdict = server.submit(prompt, max_new=args.max_new)
        assert verdict.admitted, verdict.reason
    done = server.run_until_drained()
    server.stop()
    dt = time.perf_counter() - t0

    for r in sorted(done, key=lambda r: r.id):
        print(f"req {r.id}: prompt {r.prompt} -> {r.generated} "
              f"({r.latency_s * 1e3:.1f} ms, ttft "
              f"{r.ttft_s * 1e3:.1f} ms"
              + (f", {r.retries} re-prefill(s)" if r.retries else "")
              + ")")
    lat = sorted(r.latency_s for r in done)
    print(f"served {len(done)} requests in {dt:.2f} s "
          f"(p50 {lat[len(lat) // 2] * 1e3:.1f} ms, "
          f"max {lat[-1] * 1e3:.1f} ms, "
          f"{server.recoveries} recovery(ies), final world size "
          f"{comm.size()})", flush=True)


if __name__ == "__main__":
    main()
