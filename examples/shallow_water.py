"""Shallow-water demo / benchmark CLI.

The TPU-first counterpart of the reference demo
(/root/reference/examples/shallow_water.py, run there with ``mpirun -n N``):
here the decomposition is a device-mesh ProcessGrid inside one process —
every device (TPU chip or virtual CPU device) is a rank.

    # demo run, all devices in a 2-column grid
    python examples/shallow_water.py

    # benchmark: 100x-scaled domain, 0.1 model days (the reference's
    # headline benchmark config, docs/shallow-water.rst there)
    python examples/shallow_water.py --benchmark

    # explicit decomposition / domain
    python examples/shallow_water.py --grid 2 4 --size 360 720 --days 1
"""

import argparse
import json
import os
import sys
import time

import numpy as np

# allow running straight from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--grid", type=int, nargs=2, default=None,
                   help="process grid (gy gx); default: auto over devices")
    p.add_argument("--size", type=int, nargs=2, default=None,
                   help="global domain (ny nx); default 180x360 (demo) "
                        "or 1800x3600 (--benchmark)")
    p.add_argument("--days", type=float, default=None,
                   help="model days to simulate (default 10 demo / 0.1 bench)")
    p.add_argument("--benchmark", action="store_true",
                   help="benchmark config: big domain, short run, no output")
    p.add_argument("--multistep", type=int, default=25,
                   help="steps fused into one jit call")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line with the timing result")
    return p.parse_args()


def auto_grid(n_devices):
    gy = 1
    for cand in range(int(np.sqrt(n_devices)), 0, -1):
        if n_devices % cand == 0:
            gy = cand
            break
    return (gy, n_devices // gy)


def main():
    args = parse_args()

    import jax

    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    ndev = len(jax.devices())
    grid_shape = tuple(args.grid) if args.grid else auto_grid(ndev)
    ny, nx = (
        tuple(args.size)
        if args.size
        else ((1800, 3600) if args.benchmark else (180, 360))
    )
    days = args.days if args.days is not None else (0.1 if args.benchmark else 10.0)

    # pad the domain up to divisibility
    gy, gx = grid_shape
    ny += (-ny) % gy
    nx += (-nx) % gx

    params = SWParams(dx=5e3, dy=5e3)
    grid = ProcessGrid(grid_shape)
    model = ShallowWater(grid, (ny, nx), params)

    n_steps = int(days * params.day_seconds / params.dt)
    multistep = max(1, min(args.multistep, n_steps))

    print(
        f"shallow_water: domain ({ny}, {nx}), grid {grid_shape}, "
        f"{ndev} device(s) [{jax.devices()[0].platform}], dt={params.dt:.2f}s, "
        f"{n_steps} steps ({days} model days)"
    )

    state = model.init()
    first = model.step_fn(1, first=True)
    step = model.step_fn(multistep, first=False)

    # warmup / compile
    state = first(state)
    jax.block_until_ready(step(state))

    t0 = time.perf_counter()
    done = 1
    while done < n_steps:
        state = step(state)
        jax.block_until_ready(state.h)
        done += multistep
    elapsed = time.perf_counter() - t0

    h = model.interior(state.h)
    assert np.all(np.isfinite(h)), "solution diverged"
    print(f"solution took {elapsed:.2f} s "
          f"({done / elapsed:.1f} steps/s, h range [{h.min():.2f}, {h.max():.2f}])")

    if args.json:
        print(json.dumps({
            "domain": [ny, nx], "grid": list(grid_shape),
            "steps": done, "seconds": round(elapsed, 3),
            "steps_per_s": round(done / elapsed, 2),
        }))
    return elapsed, done


if __name__ == "__main__":
    main()
