"""Data-parallel CNN training on synthetic data (demo CLI).

    python examples/train_resnet.py --steps 20 --batch 64
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--image", type=int, default=32)
    ap.add_argument("--depth", type=int, nargs="+", default=[2, 2, 2, 2])
    ap.add_argument("--widths", type=int, nargs="+",
                    default=[32, 64, 128, 256])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu.models import resnet

    cfg = resnet.ResNetConfig(
        stages=tuple(args.depth), widths=tuple(args.widths), n_classes=10,
        stem="imagenet" if args.image >= 64 else "small",
    )
    mesh = m4j.make_mesh()
    ndev = len(jax.devices())
    batch = args.batch - args.batch % ndev

    rng = np.random.RandomState(0)
    x = jnp.asarray(
        rng.randn(batch, args.image, args.image, 3).astype(np.float32)
    )
    y = jnp.asarray(rng.randint(0, 10, (batch,)).astype(np.int32))

    params = resnet.init_params(cfg)
    step = resnet.make_dp_train_step(cfg, mesh, lr=0.05)
    loss, params = step(params, x, y)  # compile
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss, params = step(params, x, y)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    print(
        f"dp={ndev}: loss {float(loss):.4f}, {dt*1e3:.1f} ms/step "
        f"(batch {batch})"
    )


if __name__ == "__main__":
    main()
