"""Docs build check: every relative link/path mentioned in docs/*.md and
README.md must exist, and every MPI4JAX_TPU_* knob mentioned anywhere in
the docs must be declared in utils/config.py's registry docstring (the
single-source-of-truth rule the registry exists for)."""

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
errors = []

md_files = sorted(REPO.glob("docs/*.md")) + [REPO / "README.md"]

link_re = re.compile(r"\]\((?!https?://|#)([^)#]+)")
for md in md_files:
    text = md.read_text()
    for target in link_re.findall(text):
        p = (md.parent / target).resolve()
        if not p.exists():
            errors.append(f"{md.relative_to(REPO)}: broken link -> {target}")

registry = (REPO / "mpi4jax_tpu/utils/config.py").read_text()
knob_re = re.compile(r"MPI4JAX_TPU_[A-Z0-9_]+")
for md in md_files:
    for knob in set(knob_re.findall(md.read_text())):
        if knob not in registry:
            errors.append(
                f"{md.relative_to(REPO)}: knob {knob} not in "
                "utils/config.py registry"
            )

if errors:
    print("\n".join(errors))
    sys.exit(1)
print(f"docs check OK ({len(md_files)} files)")
