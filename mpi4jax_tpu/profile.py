"""Recording inspection CLI for the observability subsystem.

    python -m mpi4jax_tpu.profile report  out.json.rank0.json [...]
    python -m mpi4jax_tpu.profile report  out.json            # merged trace
    python -m mpi4jax_tpu.profile merge   --out out.json out.json.rank*.json

``report`` renders the per-op / per-peer / per-algorithm table (count,
bytes, p50/p95/p99 latency, wait fraction, effective GB/s) from one or
more recordings — per-rank part files dumped at finalize
(``MPI4JAX_TPU_TRACE``) or a merged Chrome trace; ``--json`` emits the
``obs.stats`` object instead.  ``merge`` combines part files into one
Perfetto-loadable Chrome trace (what ``mpi4jax_tpu.launch --trace``
does automatically).

The logic is stdlib-only — no jax usage, no native build.  The ``-m``
form shown above still imports the package (whose ``__init__`` gates on
the jax version); where that gate blocks (no jax, jax < 0.6), run this
file directly instead — it loads the obs package by path:

    python path/to/mpi4jax_tpu/profile.py report out.json.rank*.json

See docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import sys

try:
    from . import obs
except ImportError:  # pragma: no cover - standalone tooling load
    import importlib.util
    import os as _os

    _spec = importlib.util.spec_from_file_location(
        "m4j_obs_standalone",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "obs", "__init__.py"),
        submodule_search_locations=[
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          "obs")],
    )
    obs = importlib.util.module_from_spec(_spec)
    sys.modules["m4j_obs_standalone"] = obs
    _spec.loader.exec_module(obs)


def _load_all(paths):
    """(events, dropped, ranks) across recording files of either kind."""
    events = []
    dropped = {}
    ranks = set()
    for path in paths:
        try:
            part = obs.load_part(path)
        except (ValueError, json.JSONDecodeError):
            evs, _ = obs.load_events(path)  # merged chrome trace
            events.extend(evs)
            continue
        rank = int(part.get("rank", 0))
        ranks.add(rank)
        for src, n in (part.get("dropped") or {}).items():
            dropped[f"rank{rank}.{src}"] = int(n)
        events.extend(part["events"])
    return events, dropped, sorted(ranks)


def cmd_report(args) -> int:
    events, dropped, ranks = _load_all(args.recordings)
    stats = obs.summarize(events, dropped=dropped)
    if args.json:
        print(json.dumps(stats, indent=1, sort_keys=True))
        return 0
    if ranks:
        print(f"# {len(events)} events from rank(s) "
              f"{','.join(map(str, ranks))}")
    print(obs.render_table(stats))
    return 0


def cmd_merge(args) -> int:
    merged = obs.merge_files(args.recordings)
    errors = obs.validate_chrome_trace(merged)
    if errors:
        print(f"profile: merged trace failed validation: {errors[:3]}",
              file=sys.stderr, flush=True)
        return 2
    with open(args.out, "w") as f:
        json.dump(merged, f)
    spans = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
    print(f"profile: merged {len(args.recordings)} recording(s), "
          f"{spans} spans -> {args.out} (load in https://ui.perfetto.dev)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m mpi4jax_tpu.profile")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="per-op/per-algo table from "
                                        "recordings")
    rep.add_argument("recordings", nargs="+",
                     help="part files (out.json.rank*.json) and/or merged "
                          "traces")
    rep.add_argument("--json", action="store_true",
                     help="emit the obs.stats object instead of the table")
    rep.set_defaults(fn=cmd_report)
    mrg = sub.add_parser("merge", help="merge part files into one "
                                       "Perfetto trace")
    mrg.add_argument("recordings", nargs="+", help="part files")
    mrg.add_argument("--out", required=True, help="merged trace path")
    mrg.set_defaults(fn=cmd_merge)
    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"profile: {e}", file=sys.stderr, flush=True)
        return 2


if __name__ == "__main__":
    sys.exit(main())
