"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The reference exposes the primitive for this — alltoall with a
``(nproc, ...)`` leading axis (SURVEY.md §2.4 "FFT/spectral slab transpose",
alltoall.py:39-83 there) — but no attention layer.  Here the full pattern:
sequence-sharded activations are re-sharded to head-sharded with one
``all_to_all``, attention runs locally per head group, and a second
``all_to_all`` restores sequence sharding.  On TPU both transposes ride the
bisection bandwidth of the ICI fabric.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def seq_to_heads(x, *, axis):
    """(B, T_local, H, D) seq-sharded → (B, T_global, H_local, D) head-sharded."""
    size = lax.axis_size(axis)
    b, t_loc, h, d = x.shape
    if h % size:
        raise ValueError(f"heads ({h}) must divide the axis size ({size})")
    h_loc = h // size
    # split heads into `size` groups, one per destination rank
    x = x.reshape(b, t_loc, size, h_loc, d).transpose(2, 0, 1, 3, 4)
    # (size, B, T_local, H_local, D): row j -> rank j
    x = lax.all_to_all(x, axis, split_axis=0, concat_axis=0)
    # rows now hold every rank's sequence chunk of our head group
    x = x.reshape(size, b, t_loc, h_loc, d).transpose(1, 0, 2, 3, 4)
    return x.reshape(b, size * t_loc, h_loc, d)


def heads_to_seq(x, *, axis):
    """Inverse of :func:`seq_to_heads`."""
    size = lax.axis_size(axis)
    b, t_glob, h_loc, d = x.shape
    if t_glob % size:
        raise ValueError(
            f"global sequence ({t_glob}) must divide the axis size ({size})"
        )
    t_loc = t_glob // size
    x = x.reshape(b, size, t_loc, h_loc, d).transpose(1, 0, 2, 3, 4)
    x = lax.all_to_all(x, axis, split_axis=0, concat_axis=0)
    x = x.reshape(size, b, t_loc, h_loc, d).transpose(1, 2, 0, 3, 4)
    return x.reshape(b, t_loc, size * h_loc, d)


def ulysses_attention(q, k, v, *, axis, causal: bool = False, scale=None):
    """Attention over the full sequence via head-sharding (DeepSpeed-Ulysses).

    q/k/v: ``(B, T_local, H, D)`` sequence-sharded on ``axis``.  Requires
    the head count to be divisible by the axis size.  Exact attention; the
    sequence is materialized per head group (memory O(T_global·H/size)).
    """
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    qh = seq_to_heads(q, axis=axis)
    kh = seq_to_heads(k, axis=axis)
    vh = seq_to_heads(v, axis=axis)

    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", qh.astype(jnp.float32), kh.astype(jnp.float32)
    ) * scale
    if causal:
        t = qh.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(
            mask[None, None], scores, jnp.finfo(jnp.float32).min
        )
    probs = jnp.exp(
        scores - jnp.max(scores, axis=-1, keepdims=True)
    )
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vh.astype(jnp.float32))
    return heads_to_seq(out.astype(q.dtype), axis=axis)
