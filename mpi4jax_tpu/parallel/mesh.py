"""Communicators and the SPMD entry point.

The reference's communicator is an MPI handle cloned from COMM_WORLD
(/root/reference/mpi4jax/_src/comm.py:4-11).  TPU-native, a communicator is a
*mesh axis*: ranks are positions along one or more named axes of a
``jax.sharding.Mesh``, and ops execute inside ``shard_map`` where those axes
are bound.  ``spmd`` is the front door: it wraps a per-rank function the way
``mpirun`` wraps a per-rank process.

Design notes:
- ``MeshComm`` is hashable/comparable by axis names — like the reference's
  ``HashableMPIType`` wrapper (_src/utils.py:133-152), comms appear in traced
  code and must be stable static params.
- A context stack supplies the default comm (reference: lazily cloned
  COMM_WORLD); ``spmd`` pushes its comm for the duration of the trace so op
  calls inside need no explicit ``comm=``.
- Splitting a 2-D grid into row/column sub-communicators (the shallow-water
  pattern) is just naming two mesh axes — ``ProcessGrid`` below.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


class CommBase:
    """Abstract communicator."""

    def rank(self):
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def __enter__(self):
        _push_comm(self)
        return self

    def __exit__(self, *exc):
        _pop_comm(self)
        return False


class MeshComm(CommBase):
    """Communicator over one mesh axis (or several, flattened in order).

    ``axis`` may be a single axis name or a tuple of names; with a tuple the
    rank is the row-major flattening of the per-axis indices (matching how
    ``Mesh`` flattens devices).
    """

    def __init__(self, axis="mpi", *, mesh: Optional[Mesh] = None):
        if isinstance(axis, str):
            axis = (axis,)
        self.axes: tuple = tuple(axis)
        self.mesh = mesh

    # -- identity ---------------------------------------------------------
    @property
    def axis(self):
        """The axis argument to pass to lax collectives."""
        return self.axes if len(self.axes) > 1 else self.axes[0]

    def __hash__(self):
        return hash(("mpi4jax_tpu.MeshComm", self.axes))

    def __eq__(self, other):
        return isinstance(other, MeshComm) and other.axes == self.axes

    def __repr__(self):
        return f"MeshComm(axis={self.axes!r})"

    # -- topology ---------------------------------------------------------
    def rank(self):
        """Linearized rank along this comm's axes (traced; inside shard_map)."""
        r = lax.axis_index(self.axes[0])
        for name in self.axes[1:]:
            r = r * lax.axis_size(name) + lax.axis_index(name)
        return r

    def size(self) -> int:
        n = 1
        for name in self.axes:
            n *= lax.axis_size(name)
        return n

    def sub(self, axis) -> "MeshComm":
        """Sub-communicator over a subset of this comm's axes."""
        if isinstance(axis, str):
            axis = (axis,)
        for a in axis:
            if a not in self.axes:
                raise ValueError(f"axis {a!r} not part of {self!r}")
        return MeshComm(axis, mesh=self.mesh)


_DEFAULT_AXIS = "mpi"


class _CommStack(threading.local):
    def __init__(self):
        self.stack = []


_comm_stack = _CommStack()


def _push_comm(comm):
    _comm_stack.stack.append(comm)


def _pop_comm(comm):
    top = _comm_stack.stack.pop()
    if top is not comm:  # pragma: no cover - misuse guard
        raise RuntimeError("communicator context stack corrupted")


def current_comm() -> Optional[CommBase]:
    return _comm_stack.stack[-1] if _comm_stack.stack else None


_world_comm = None


def get_default_comm() -> CommBase:
    """Innermost active comm, else the process 'world'.

    Outside any context this returns the world-tier communicator when the
    process was launched by the mpi4jax_tpu launcher (multi-process mode),
    else a ``MeshComm`` over the default axis name — the single-controller
    SPMD world.
    """
    comm = current_comm()
    if comm is not None:
        return comm
    from ..runtime import transport

    if transport.in_world():
        return transport.get_world_comm()
    return MeshComm(_DEFAULT_AXIS)


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    axis: str = _DEFAULT_AXIS,
    devices: Optional[Sequence] = None,
    backend: Optional[str] = None,
) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all available devices)."""
    if devices is None:
        devices = jax.devices(backend) if backend else jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def spmd(
    fn=None,
    *,
    comm: Optional[MeshComm] = None,
    mesh: Optional[Mesh] = None,
    in_specs=None,
    out_specs=None,
    check_vma: bool = False,
):
    """Run ``fn`` once per rank over a device mesh (the `mpirun` of this
    framework).

    Wraps ``jax.shard_map``: every array argument is split along its leading
    axis across the comm's devices (override with ``in_specs``/``out_specs``)
    and ``fn`` sees its local shard, exactly like an MPI rank sees its local
    buffer.  Inside ``fn``, the comm is the ambient default — op calls need
    no ``comm=`` argument.

        mesh = m4j.make_mesh()
        @m4j.spmd(mesh=mesh)
        def step(x):
            return m4j.allreduce(x, op=m4j.SUM)
    """

    def wrap(f):
        def call(*args):
            nonlocal comm, mesh
            if mesh is None:
                mesh = make_mesh() if comm is None or comm.mesh is None else comm.mesh
            if comm is None:
                comm_ = MeshComm(mesh.axis_names, mesh=mesh)
            else:
                comm_ = MeshComm(comm.axes, mesh=mesh)
            spec_in = in_specs if in_specs is not None else P(comm_.axes)
            spec_out = out_specs if out_specs is not None else P(comm_.axes)

            def ranked(*local_args):
                with comm_:
                    return f(*local_args)

            return jax.shard_map(
                ranked,
                mesh=mesh,
                in_specs=spec_in,
                out_specs=spec_out,
                check_vma=check_vma,
            )(*args)

        call.__name__ = getattr(f, "__name__", "spmd_fn")
        return call

    if fn is not None:
        return wrap(fn)
    return wrap
