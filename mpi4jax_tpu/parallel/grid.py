"""Cartesian process grids over device meshes.

The reference builds its 2-D process grid by hand from the rank
(/root/reference/examples/shallow_water.py:57-107: rank → (row, col),
neighbor ranks, periodic wraparound).  TPU-native, the grid *is* the mesh:
two named axes, coordinates are ``lax.axis_index`` per axis, and neighbor
communication is ``lax.ppermute`` along one axis — which on a TPU torus maps
straight onto nearest-neighbor ICI links.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh

from .mesh import MeshComm


class ProcessGrid:
    """An N-D cartesian communicator over mesh axes.

    ``shape`` gives the number of ranks per dimension; ``axes`` names the
    mesh axes (created if a mesh isn't supplied).
    """

    def __init__(
        self,
        shape: Sequence[int],
        *,
        axes: Optional[Sequence[str]] = None,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence] = None,
    ):
        self.shape = tuple(int(s) for s in shape)
        ndim = len(self.shape)
        if axes is None:
            axes = tuple(f"grid{i}" for i in range(ndim))
        self.axes = tuple(axes)
        if len(self.axes) != ndim:
            raise ValueError("axes must match shape length")
        if mesh is None:
            n = int(np.prod(self.shape))
            if devices is None:
                devices = jax.devices()
            if len(devices) < n:
                raise ValueError(
                    f"grid {self.shape} needs {n} devices, have {len(devices)}"
                )
            mesh = Mesh(
                np.asarray(devices[:n]).reshape(self.shape), self.axes
            )
        self.mesh = mesh
        self.comm = MeshComm(self.axes, mesh=mesh)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def coords(self) -> Tuple:
        """This rank's grid coordinates (traced; inside shard_map)."""
        return tuple(lax.axis_index(a) for a in self.axes)

    def axis_comm(self, dim: int) -> MeshComm:
        """Sub-communicator along one grid dimension (row/col comms)."""
        return MeshComm(self.axes[dim], mesh=self.mesh)

    def __repr__(self):
        return f"ProcessGrid(shape={self.shape}, axes={self.axes})"
