"""Mixture-of-Experts expert parallelism: the alltoall dispatch/combine.

Experts are rank-sharded (one expert per rank of ``comm``); every token
is routed top-1 and shipped to its expert's rank with one ``alltoall``,
the expert FFN runs locally, and a second ``alltoall`` brings the
outputs home — the GShard/Switch dispatch pattern, where the exchange
volume is the activation traffic that dominates MoE scaling.  Both
transposes are :func:`mpi4jax_tpu.ops.alltoall`, so they ride whatever
the engine picks — and accept the same per-call controls:
``compression="int8"`` for the quantized wire format (EQuARX's
observation that routed activations tolerate low-precision transport,
arXiv:2506.17615) and ``algo=`` to force a schedule
(``"qalltoall"``/``"halltoall"``/``"hqalltoall"``) on a world comm.

Composes with the other axes exactly like :mod:`.tp`/:mod:`.ulysses`:
``comm`` names the expert axis (a ``MeshComm`` sub-axis or a world
comm), so dp/tp/pp can own the remaining axes.  Capacity-based binning
keeps every shape static for jit: each rank sends exactly ``capacity``
token slots to every expert, overflow tokens are dropped (their output
is the zero vector — the standard Switch capacity discipline).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import ops
from .mesh import get_default_comm


def _resolve(comm):
    return comm if comm is not None else get_default_comm()


def expert_capacity(tokens: int, n_experts: int,
                    capacity_factor: float = 1.25) -> int:
    """Token slots each rank reserves per expert (static for jit)."""
    return max(1, int(math.ceil(tokens / n_experts * capacity_factor)))


def router_top1(x, w_gate):
    """Top-1 routing: ``(expert_idx, gate_prob, full_probs)`` per token.

    The softmax runs in f32 regardless of the activation dtype — the
    gate probabilities weight the combine and must not collapse in
    bf16.
    """
    logits = jnp.asarray(x) @ w_gate
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    prob = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    return idx, prob, probs


def dispatch(x, expert_idx, capacity: int, *, comm=None,
             compression=None, algo=None):
    """Bin tokens per destination expert and exchange: returns
    ``(expert_inputs, route)`` where ``expert_inputs`` is the
    ``(size, capacity, d)`` buffer of tokens routed to THIS rank's
    expert (row ``j`` from rank ``j``) and ``route`` is the opaque
    state :func:`combine` needs to scatter outputs home.

    ``compression``/``algo`` pass straight to the underlying
    :func:`~mpi4jax_tpu.ops.alltoall` — the dispatch direction and the
    combine direction are independent calls, so a caller may quantize
    one and not the other.
    """
    comm = _resolve(comm)
    size = comm.size()
    t, d = x.shape
    oh = jax.nn.one_hot(expert_idx, size, dtype=jnp.int32)
    # position of each token inside its expert's queue (0-based)
    pos = jnp.sum(jnp.cumsum(oh, axis=0) * oh, axis=-1) - 1
    keep = (pos >= 0) & (pos < capacity)
    pos_c = jnp.clip(pos, 0, capacity - 1)
    buf = jnp.zeros((size, capacity, d), jnp.asarray(x).dtype)
    # .add, not .set: dropped tokens contribute zeros to a clipped slot
    # that may also hold a kept token — overwriting would corrupt it
    buf = buf.at[expert_idx, pos_c].add(
        jnp.where(keep[:, None], x, jnp.zeros_like(x)))
    recv = ops.alltoall(buf, comm=comm, compression=compression,
                        algo=algo)
    return recv, (expert_idx, pos_c, keep)


def combine(expert_out, route, *, comm=None, compression=None,
            algo=None):
    """Return trip of :func:`dispatch`: ship each expert's outputs back
    to the ranks that sent the tokens and scatter them into token
    order.  Dropped tokens come back as zeros."""
    comm = _resolve(comm)
    expert_idx, pos_c, keep = route
    back = ops.alltoall(expert_out, comm=comm, compression=compression,
                        algo=algo)
    y = back[expert_idx, pos_c]
    return jnp.where(keep[:, None], y, jnp.zeros_like(y))


def moe_ffn(x, params, *, comm=None, capacity_factor: float = 1.25,
            compression=None, algo=None):
    """One expert-parallel MoE FFN block: route, dispatch, this rank's
    expert (a two-layer relu FFN), combine, gate-weight.

    ``x``: ``(tokens, d_model)`` — this rank's local tokens.
    ``params``: ``w_gate (d_model, size)`` (replicated) plus THIS
    rank's expert ``w_in (d_model, d_ff) / b_in / w_out (d_ff,
    d_model) / b_out`` (see :func:`init_moe_params`).
    """
    comm = _resolve(comm)
    size = comm.size()
    idx, prob, _ = router_top1(x, params["w_gate"])
    cap = expert_capacity(x.shape[0], size, capacity_factor)
    recv, route = dispatch(x, idx, cap, comm=comm,
                           compression=compression, algo=algo)
    flat = recv.reshape(size * cap, -1)
    h = jnp.maximum(flat @ params["w_in"] + params["b_in"], 0)
    out = (h @ params["w_out"] + params["b_out"]).astype(x.dtype)
    y = combine(out.reshape(size, cap, -1), route, comm=comm,
                compression=compression, algo=algo)
    return y * prob[:, None].astype(y.dtype)


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    expert_rank: int, dtype=jnp.float32):
    """Replicated gate + rank ``expert_rank``'s expert weights.

    Every rank derives the expert bank from the same ``key`` and slices
    its own expert, so the sharding is reproducible without a broadcast.
    """
    kg, ki, ko = jax.random.split(key, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    w_in = jax.random.normal(ki, (n_experts, d_model, d_ff), dtype) * scale_in
    w_out = jax.random.normal(ko, (n_experts, d_ff, d_model), dtype) * scale_out
    return {
        "w_gate": jax.random.normal(kg, (d_model, n_experts), dtype)
        * scale_in,
        "w_in": w_in[expert_rank],
        "b_in": jnp.zeros((d_ff,), dtype),
        "w_out": w_out[expert_rank],
        "b_out": jnp.zeros((d_model,), dtype),
    }
