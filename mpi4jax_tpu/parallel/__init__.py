from .mesh import (
    CommBase,
    MeshComm,
    current_comm,
    get_default_comm,
    make_mesh,
    spmd,
)

__all__ = [
    "CommBase",
    "MeshComm",
    "current_comm",
    "get_default_comm",
    "make_mesh",
    "moe",
    "spmd",
]


def __getattr__(name):  # lazy: the layer modules pull in ops/jax.nn
    if name == "moe":
        # import_module, NOT `from . import`: the fromlist path re-reads
        # the attribute off this package and would recurse right back
        # here while the submodule import is still in flight
        import importlib

        mod = importlib.import_module(".moe", __name__)
        globals()["moe"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
