from .mesh import (
    CommBase,
    MeshComm,
    current_comm,
    get_default_comm,
    make_mesh,
    spmd,
)

__all__ = [
    "CommBase",
    "MeshComm",
    "current_comm",
    "get_default_comm",
    "make_mesh",
    "spmd",
]
