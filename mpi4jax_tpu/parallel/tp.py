"""Tensor parallelism: column/row-sharded linear layers.

The reference's TP embodiment is the column-split matvec + allreduce with a
``linear_transpose``-able collective (SURVEY.md §2.4,
test_allreduce_matvec.py:12-66 there).  These helpers package the standard
Megatron pairing: a column-parallel layer (no comm in, sharded out) followed
by a row-parallel layer (sharded in, one psum out) — exactly one collective
per pair, riding ICI.
"""

from __future__ import annotations


from .. import ops


def column_parallel(x, w_shard, b_shard=None):
    """y_shard = x @ w_shard (+ b_shard): output features sharded, no comm.

    ``w_shard``: (d_in, d_out/size) — this rank's column block.
    """
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard, w_shard, b=None, *, comm=None):
    """y = allreduce(x_shard @ w_shard) (+ b): input features sharded, one
    SUM collective produces the replicated output.

    ``w_shard``: (d_in/size, d_out) — this rank's row block.  ``b`` is added
    once (after the reduction), not per shard.
    """
    partial = x_shard @ w_shard
    y = ops.allreduce(partial, op=ops.SUM, comm=comm)
    if b is not None:
        y = y + b
    return y


def shard_columns(w, rank, size):
    """Static helper: slice columns of a full weight for ``rank``."""
    d = w.shape[-1]
    if d % size:
        raise ValueError(f"cannot split {d} columns over {size} ranks")
    step = d // size
    return w[..., rank * step:(rank + 1) * step]


def shard_rows(w, rank, size):
    d = w.shape[0]
    if d % size:
        raise ValueError(f"cannot split {d} rows over {size} ranks")
    step = d // size
    return w[rank * step:(rank + 1) * step]
