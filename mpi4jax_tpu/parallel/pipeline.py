"""Pipeline parallelism over a mesh axis (SPMD GPipe).

The reference's pipeline embodiment is ordered point-to-point send/recv
chains between ranks, deadlock-free by token ordering (SURVEY.md §2.4,
test_send_and_recv.py:96-115 there).  TPU-native, the stage handoff is one
``lax.ppermute`` per pipeline tick inside a ``lax.scan``: every stage
executes the same program (no per-rank code), bubbles are masked compute,
and reverse-mode autodiff replays the schedule backward for free.

The world tier (one process per rank) still supports the reference's
explicit send/recv MPMD style for pipelines that need it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, microbatches, *, axis,
                   prepare_fn=None):
    """Run microbatches through a chain of stages along ``axis``.

    Args:
        stage_fn: ``stage_fn(params, x) -> y`` — one stage's compute; the
            activation shape must be the same for every stage boundary.
        stage_params: this rank's stage parameters (any pytree; inside
            ``shard_map`` each rank passes its own shard).
        microbatches: ``(M, ...)`` microbatch inputs, consumed by stage 0
            (other ranks may pass the same array; only stage 0 reads it).
        axis: mesh axis enumerating pipeline stages.
        prepare_fn: optional map from a raw microbatch to the activation
            fed into stage 0 (e.g. an embedding lookup) — lets microbatch
            dtype/shape differ from the inter-stage activation.

    Returns:
        ``(M, ...)`` outputs, valid on the **last** stage (use
        :func:`mpi4jax_tpu.bcast` from the last rank if every stage needs
        them); other ranks hold zeros.
    """
    size = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    n_ticks = m + size - 1

    if prepare_fn is None:
        prepare_fn = lambda mb: mb

    act = jax.eval_shape(prepare_fn, jax.ShapeDtypeStruct(
        microbatches.shape[1:], microbatches.dtype
    ))

    def tick(carry, t):
        incoming = carry  # activation handed off by the previous stage
        mb = t - idx  # microbatch index this stage processes at tick t
        active = (mb >= 0) & (mb < m)
        # stage 0 reads (and prepares) its microbatch; later stages read
        # the handoff
        x0 = prepare_fn(microbatches[jnp.clip(mb, 0, m - 1)])
        x_in = jnp.where(idx == 0, x0, incoming)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        handoff = lax.ppermute(
            y, axis, [(i, i + 1) for i in range(size - 1)]
        )
        return handoff, y

    init = jnp.zeros(act.shape, act.dtype)
    _, ys = lax.scan(tick, init, jnp.arange(n_ticks))
    # the last stage produced microbatch j at tick j + size - 1
    out = ys[size - 1:]
    is_last = idx == size - 1
    return jnp.where(is_last, out, jnp.zeros_like(out))
