"""Ring attention: exact attention over sequence shards with a k/v ring.

The reference has no long-context support; its building block for rings is
token-ordered sendrecv (SURVEY.md §5.7 points at sendrecv.py:46-125 as the
primitive to build this from).  TPU-native, the ring is ``lax.ppermute``
over ICI inside ``shard_map`` (one hop per step, bandwidth-optimal), and the
accumulation is the online-softmax (flash) recurrence so only one k/v block
is ever resident per device.

Shapes: q/k/v are ``(batch, seq_local, heads, head_dim)`` per rank, the
sequence axis sharded over ``axis``.  Causality is handled block-wise: the
k/v block's global offset is compared against the query block's.

The step loop is ``lax.scan`` so the whole thing is reverse-differentiable;
wrap in ``jax.checkpoint`` upstream to keep backward memory at one block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _ring_shift(x, axis):
    (out,) = _ring_shift_many((x,), axis)
    return out


def _ring_shift_many(xs, axis):
    """Rotate several arrays one ring hop together.  Under
    ``MPI4JAX_TPU_PALLAS_COLLECTIVES=1`` all payloads ride one RDMA kernel
    (every DMA in flight before any wait); otherwise one ppermute each."""
    from ..utils import config as _config

    if _config.pallas_collectives_enabled():
        from ..ops import pallas_collectives as _pc

        if _pc.can_route(axis):
            return _pc.ring_shift_n(xs, axis, 1)
    size = lax.axis_size(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]
    return tuple(lax.ppermute(x, axis, perm) for x in xs)


def ring_attention(q, k, v, *, axis, causal: bool = False, scale=None,
                   impl: str = "auto", block_q: int = None,
                   block_k: int = None):
    """Exact (flash-accumulated) attention across a sequence-sharded ring.

    Args:
        q, k, v: ``(B, T_local, H, D)`` per rank, sequence sharded on
            ``axis``.
        axis: mesh axis name carrying the sequence shards.
        causal: apply a causal mask over *global* positions.
        scale: score scale (default ``1/sqrt(D)``).
        impl: ``"pallas"`` — local blocks via the Pallas flash kernel
            (``ops/flash.py``, MXU + VMEM-resident online softmax);
            ``"xla"`` — fused-einsum flash recurrence below; ``"auto"``
            picks pallas.
        block_q, block_k: Pallas tile sizes (clamped to divisors of
            ``T_local``); default: ``ring_flash_attention``'s tuned
            1024-block configuration.

    Returns:
        ``(B, T_local, H, D)`` attention output, sequence-sharded like q.
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    scale_traced = isinstance(scale, jax.core.Tracer)
    if impl == "auto":
        # Pallas pays off compiled on TPU; off-TPU it would run in the
        # (slow) interpreter, and a traced scale cannot be a static
        # kernel parameter — fall back to the XLA path for both.
        from ..ops.flash import target_platform

        impl = ("pallas" if target_platform() == "tpu"
                and not scale_traced else "xla")
    if impl == "pallas":
        if scale_traced:
            raise ValueError(
                "impl='pallas' needs a static Python scale; got a traced "
                "value (use impl='xla' for a learnable scale)")
        from ..ops.flash import ring_flash_attention

        kw = {}
        if block_q is not None:
            kw["block_q"] = block_q
        if block_k is not None:
            kw["block_k"] = block_k
        return ring_flash_attention(
            q, k, v, axis=axis, causal=causal, scale=scale, **kw)
    size = lax.axis_size(axis)
    my_block = lax.axis_index(axis)
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))

    # work in (B, H, T, D) for clean einsums
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    q_pos = my_block * t_loc + jnp.arange(t_loc)  # global query positions

    neg_inf = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        # after i hops, we hold the block originally owned by rank - i
        src_block = (my_block - i) % size
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", qt.astype(jnp.float32),
            k_cur.astype(jnp.float32),
        ) * scale
        if causal:
            k_pos = src_block * t_loc + jnp.arange(t_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, neg_inf)

        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32)
        )
        # rotate the k/v ring one hop (skip the send on the last step is a
        # micro-optimization XLA handles via dead-code once unrolled; with
        # scan we keep the uniform body)
        k_nxt, v_nxt = _ring_shift_many((k_cur, v_cur), axis)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    from ..ops._mesh_impl import as_varying

    # the accumulators start as constants but become varying inside the
    # scan body — promote up front so checked shard_maps accept the carry
    o0 = as_varying(jnp.zeros((b, h, t_loc, d), jnp.float32), axis)
    m0 = as_varying(jnp.full((b, h, t_loc), neg_inf, jnp.float32), axis)
    l0 = as_varying(jnp.zeros((b, h, t_loc), jnp.float32), axis)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, kt, vt), jnp.arange(size)
    )
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
