"""Halo (ghost-cell) exchange for domain-decomposed stencil codes.

The reference's embodiment is ``enforce_boundaries`` — up to four
token-ordered sendrecv/send/recv calls per call, serialized by the token
chain (/root/reference/examples/shallow_water.py:173-271, SURVEY.md §3.5).

TPU-first redesign: one ``lax.ppermute`` per direction per axis, *batched* —
the strips for all fields are exchanged in one collective each, there is no
token chain to serialize (SPMD order suffices), and XLA overlaps the
ppermutes of independent axes.  This addresses SURVEY.md §7 hard part 2
(per-call host round-trips would kill TPU throughput).

Layout convention: a local field of interior shape ``(m, n)`` is stored as
``(m + 2*halo, n + 2*halo)`` with ghost rings on every side.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
from jax import lax

from .grid import ProcessGrid


def _axis_exchange(f, dim: int, axis_name: str, halo: int, periodic: bool):
    """Fill the two ghost strips of ``f`` along array dimension ``dim``."""
    n = lax.axis_size(axis_name)
    extent = f.shape[dim]

    lo_interior = lax.slice_in_dim(f, halo, 2 * halo, axis=dim)
    hi_interior = lax.slice_in_dim(
        f, extent - 2 * halo, extent - halo, axis=dim
    )

    if n == 1:
        if periodic:
            # self-neighbor: wrap own interior strips into own ghosts
            from_above, from_below = hi_interior, lo_interior
        else:
            return f
    else:
        from ..utils import config as _config

        use_rdma = False
        if _config.pallas_collectives_enabled():
            from ..ops import pallas_collectives as _pc

            use_rdma = _pc.can_route(axis_name)
        if use_rdma:
            # one kernel, both directions' DMAs in flight before either
            # wait — both ICI link directions busy (ring_shift2); at
            # non-periodic boundaries the wrapped values are masked below,
            # same as the zeros ppermute would deliver
            from_above, from_below = _pc.ring_shift2(
                hi_interior, lo_interior, axis_name
            )
        else:
            to_prev = [(i, i - 1) for i in range(1, n)]
            to_next = [(i, i + 1) for i in range(n - 1)]
            if periodic:
                to_prev.append((0, n - 1))
                to_next.append((n - 1, 0))
            # neighbor below (index+1) sends its low-interior strip to us →
            # our high ghost; neighbor above (index-1) sends its
            # high-interior → our low ghost.
            from_above = lax.ppermute(hi_interior, axis_name, to_next)
            from_below = lax.ppermute(lo_interior, axis_name, to_prev)

    idx = lax.axis_index(axis_name)
    lo_ghost = lax.slice_in_dim(f, 0, halo, axis=dim)
    hi_ghost = lax.slice_in_dim(f, extent - halo, extent, axis=dim)
    if not periodic:
        # at the physical boundary keep the existing ghost values (the
        # solver's boundary condition), not the zeros ppermute delivers
        from_above = jnp.where(idx > 0, from_above, lo_ghost)
        from_below = jnp.where(idx < n - 1, from_below, hi_ghost)

    start_lo = [0] * f.ndim
    start_hi = [0] * f.ndim
    start_hi[dim] = extent - halo
    f = lax.dynamic_update_slice(f, from_above.astype(f.dtype), start_lo)
    f = lax.dynamic_update_slice(f, from_below.astype(f.dtype), start_hi)
    return f


def halo_exchange(
    f,
    grid: ProcessGrid,
    *,
    halo: int = 1,
    periodic: Sequence[bool] | bool = True,
    dims: Optional[Sequence[int]] = None,
):
    """Fill ghost rings of ``f`` from grid neighbors along each dimension.

    Args:
        f: local array (or tuple of arrays — exchanged together) whose
            leading ``grid.ndim`` dimensions carry ``halo``-wide ghost rings.
        grid: the :class:`ProcessGrid`.
        halo: ghost width.
        periodic: per-dimension wraparound flag (scalar broadcasts).
        dims: which array dims correspond to grid dims (default: 0..ndim-1).
    """
    single = not isinstance(f, (tuple, list))
    fields = (f,) if single else tuple(f)
    if isinstance(periodic, bool):
        periodic = (periodic,) * grid.ndim
    if dims is None:
        dims = tuple(range(grid.ndim))

    if all(lax.axis_size(grid.axes[g]) == 1 for g in range(grid.ndim)):
        # no direction actually communicates (single-block grid): stacking
        # would only buy batched collectives, and its full-array
        # stack/unstack copies dominate the step on one chip — update each
        # field's ghosts in place instead
        out = []
        for x in fields:
            for gdim, (adim, per) in enumerate(zip(dims, periodic)):
                x = _axis_exchange(x, adim, grid.axes[gdim], halo, per)
            out.append(x)
        return out[0] if single else tuple(out)

    # Batch all fields into one stacked exchange per direction: one
    # collective instead of len(fields) — fewer, larger ICI transfers.
    stacked = jnp.stack([x.astype(fields[0].dtype) for x in fields])
    for gdim, (adim, per) in enumerate(zip(dims, periodic)):
        stacked = _axis_exchange(
            stacked, adim + 1, grid.axes[gdim], halo, per
        )
    out = tuple(stacked[i].astype(fields[i].dtype) for i in range(len(fields)))
    return out[0] if single else out
