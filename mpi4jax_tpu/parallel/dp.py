"""Data parallelism: gradient synchronization via differentiable allreduce.

The reference's DP embodiment is ``allreduce(op=SUM)`` inside the loss so it
sits *inside* ``jax.grad`` (SURVEY.md §2.4, allreduce.py:41-76 +
test_allreduce_matvec.py there).  Same pattern here, plus the conventional
outside-the-loss helpers.
"""

from __future__ import annotations

import jax

from .. import ops


def _resolve(comm):
    if comm is None:
        from .mesh import get_default_comm

        comm = get_default_comm()
    return comm


def pmean(x, *, comm=None):
    """Mean across ranks (differentiable; SUM allreduce / size)."""
    comm = _resolve(comm)
    return ops.allreduce(x, op=ops.SUM, comm=comm) / comm.size()


def sync_gradients(grads, *, comm=None):
    """Allreduce-mean every leaf of a gradient pytree (one call per leaf;
    XLA fuses/overlaps the collectives on ICI)."""
    return jax.tree.map(lambda g: pmean(g, comm=comm), grads)


def value_and_synced_grad(loss_fn, *, comm=None):
    """``value_and_grad`` of a per-shard loss with DP synchronization.

    ``loss_fn(params, *batch) -> scalar`` is computed on the local shard;
    the returned function yields the global mean loss and the allreduce-mean
    gradient.  (Note: with replicated params inside ``shard_map``, a psum
    inside the loss alone does NOT produce synced grads — the transpose of
    psum delivers the cotangent to each local term, so the cross-rank sum of
    per-rank gradients must be taken explicitly. Differentiating *through*
    ``shard_map`` from outside syncs automatically; this helper is for the
    per-rank-grad style.)
    """

    def wrapped(params, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        return pmean(loss, comm=comm), sync_gradients(grads, comm=comm)

    return wrapped
