"""Data parallelism: gradient synchronization via differentiable allreduce.

The reference's DP embodiment is ``allreduce(op=SUM)`` inside the loss so it
sits *inside* ``jax.grad`` (SURVEY.md §2.4, allreduce.py:41-76 +
test_allreduce_matvec.py there).  Same pattern here, plus the conventional
outside-the-loss helpers.
"""

from __future__ import annotations

import jax

from .. import ops


def _resolve(comm):
    if comm is None:
        from .mesh import get_default_comm

        comm = get_default_comm()
    return comm


def pmean(x, *, comm=None):
    """Mean across ranks (differentiable; SUM allreduce / size)."""
    comm = _resolve(comm)
    return ops.allreduce(x, op=ops.SUM, comm=comm) / comm.size()


def _resolve_bucket_bytes(bucket_bytes):
    if bucket_bytes is not None:
        return int(bucket_bytes)
    import os

    from ..utils import config

    # default: bucket only when MPI4JAX_TPU_PLAN_BUCKET_KB is set
    # EXPLICITLY.  Deliberately NOT implied by plan mode: the schedule
    # compiler traces the program in a pre-launch subprocess where
    # MPI4JAX_TPU_PLAN is not yet exported — keying the schedule on the
    # plan flag would make the compiled plan (per-leaf) and the runtime
    # (bucketed) disagree and self-disable.  The bucket knob itself is
    # passed to both (launch exports the environment to the analyzer
    # and to every rank), so trace-time and runtime always agree.
    if os.environ.get("MPI4JAX_TPU_PLAN_BUCKET_KB") is None:
        return 0
    return config.plan_bucket_bytes()


def sync_gradients(grads, *, comm=None, bucket_bytes=None):
    """Allreduce-mean every leaf of a gradient pytree.

    Default: one call per leaf (the historic schedule; XLA fuses/
    overlaps the collectives on ICI).  With ``bucket_bytes`` > 0 — or
    whenever ``MPI4JAX_TPU_PLAN_BUCKET_KB`` is set explicitly in the
    environment — adjacent same-dtype leaves concatenate into buckets
    of up to that many bytes and sync as ONE allreduce per bucket: the
    fusion the schedule compiler's ``bucket`` marks describe
    (docs/analysis.md § "From verifier to compiler").  The knob, not
    plan mode, selects bucketing, so the analyzer (which traces before
    ``MPI4JAX_TPU_PLAN`` is exported) and the runtime always see the
    same schedule.  SUM over a concatenation is
    elementwise, so bucketed and per-leaf results are identical; fewer,
    larger wire messages amortize per-op latency in deep models.
    ``benchmarks/schedule_overlap.py`` measures the effect.
    """
    import jax.numpy as jnp

    bucket_bytes = _resolve_bucket_bytes(bucket_bytes)
    if bucket_bytes <= 0:
        return jax.tree.map(lambda g: pmean(g, comm=comm), grads)
    comm = _resolve(comm)
    leaves, treedef = jax.tree.flatten(grads)

    synced = [None] * len(leaves)
    bucket = []          # (leaf index, raveled leaf)
    bucket_nbytes = 0

    def flush():
        nonlocal bucket, bucket_nbytes
        if not bucket:
            return
        if len(bucket) == 1:
            i, flat = bucket[0]
            synced[i] = pmean(flat, comm=comm)
        else:
            joined = jnp.concatenate([flat for _, flat in bucket])
            red = pmean(joined, comm=comm)
            off = 0
            for i, flat in bucket:
                synced[i] = red[off:off + flat.size]
                off += flat.size
        bucket, bucket_nbytes = [], 0

    prev_dtype = None
    for i, leaf in enumerate(leaves):
        arr = jnp.asarray(leaf)
        nbytes = arr.size * arr.dtype.itemsize
        oversize = nbytes > bucket_bytes
        if (arr.dtype != prev_dtype or oversize
                or bucket_nbytes + nbytes > bucket_bytes):
            flush()
        if oversize:
            synced[i] = pmean(arr, comm=comm)
        else:
            bucket.append((i, arr.ravel()))
            bucket_nbytes += nbytes
        prev_dtype = arr.dtype
    flush()

    # reshape flattened slices back; deliberately NO astype — pmean's
    # dtype promotion (int mean -> float) must match the per-leaf path
    # exactly, or bucketed and unbucketed results would diverge
    synced = [s.reshape(jnp.shape(leaf))
              if s is not None and jnp.shape(s) != jnp.shape(leaf) else s
              for s, leaf in zip(synced, leaves)]
    return jax.tree.unflatten(treedef, synced)


def elastic_step_fn(loss_fn, *, lr, batch_fn, optimizer=None):
    """Build a ``step_fn(params, step, comm)`` for
    :func:`mpi4jax_tpu.elastic.training.run` out of a per-shard loss:
    SGD (or ``optimizer(params, grads, lr) -> params``) over
    DP-synchronized gradients, with the local batch re-derived every
    step from ``batch_fn(step, rank, size)``.

    The rank/size indirection is the elastic wiring: after a recovery
    shrinks the world, the SAME function reshards the global batch over
    the new ranks — keep the global batch size divisible by every world
    size you intend to survive and the synced gradient stays the global
    mean, so the resumed loss trajectory matches an uninterrupted run
    up to float reassociation (docs/elasticity.md documents the bound).
    """
    import jax

    def sgd(params, grads, lr_):
        return jax.tree.map(lambda p, g: p - lr_ * g, params, grads)

    opt = optimizer or sgd

    def step_fn(params, step, comm):
        comm_ = _resolve(comm)
        batch = batch_fn(step, int(comm_.rank()), int(comm_.size()))
        if not isinstance(batch, tuple):
            batch = (batch,)
        _, grads = jax.value_and_grad(loss_fn)(params, *batch)
        grads = sync_gradients(grads, comm=comm_)
        return opt(params, grads, lr)

    return step_fn


def value_and_synced_grad(loss_fn, *, comm=None):
    """``value_and_grad`` of a per-shard loss with DP synchronization.

    ``loss_fn(params, *batch) -> scalar`` is computed on the local shard;
    the returned function yields the global mean loss and the allreduce-mean
    gradient.  (Note: with replicated params inside ``shard_map``, a psum
    inside the loss alone does NOT produce synced grads — the transpose of
    psum delivers the cotangent to each local term, so the cross-rank sum of
    per-rank gradients must be taken explicitly. Differentiating *through*
    ``shard_map`` from outside syncs automatically; this helper is for the
    per-rank-grad style.)
    """

    def wrapped(params, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        return pmean(loss, comm=comm), sync_gradients(grads, comm=comm)

    return wrapped
