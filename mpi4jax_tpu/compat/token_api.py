"""Explicit-token compatibility API.

Signature-level parity with the reference's primary (token) API
(/root/reference/mpi4jax/_src/collective_ops/*.py): every function returns
``(result, token)`` (or just ``token`` for send/barrier), and accepts
``token=None`` to start a chain, exactly like

    res, token = mpi4jax.allreduce(x, op=MPI.SUM, comm=comm, token=token)

The reference threads real XLA tokens through its custom calls
(allreduce.py:63-64,101-104 there).  Here tokens are scalar arrays tied to op
inputs/outputs with ``lax.optimization_barrier`` (ops/_dispatch.py): on the
mesh tier SPMD program order already guarantees a deadlock-free global order,
so the token's job reduces to expressing *extra* ordering edges the dataflow
doesn't carry — which the barrier provides; on the world tier the ordered
effect provides ordering and the token is carried for API fidelity.
"""

from __future__ import annotations

from .. import ops as _ops
from ..ops import _dispatch
from ..ops.reduce_ops import SUM

create_token = _dispatch.create_token


def _start(token, x=None):
    return _dispatch.create_token(x) if token is None else token


def allreduce(x, op=SUM, *, comm=None, token=None):
    return _ops.allreduce(x, op, comm=comm, token=_start(token, x))


def allgather(x, *, comm=None, token=None):
    return _ops.allgather(x, comm=comm, token=_start(token, x))


def alltoall(x, *, comm=None, token=None):
    return _ops.alltoall(x, comm=comm, token=_start(token, x))


def barrier(*, comm=None, token=None):
    return _ops.barrier(comm=comm, token=_start(token))


def bcast(x, root=0, *, comm=None, token=None):
    return _ops.bcast(x, root, comm=comm, token=_start(token, x))


def gather(x, root=0, *, comm=None, token=None):
    return _ops.gather(x, root, comm=comm, token=_start(token, x))


def recv(x, source, tag=None, *, comm=None, token=None, status=None):
    return _ops.recv(
        x, source, tag, comm=comm, token=_start(token, x), status=status
    )


def reduce(x, op=SUM, root=0, *, comm=None, token=None):
    return _ops.reduce(x, op, root, comm=comm, token=_start(token, x))


def scan(x, op=SUM, *, comm=None, token=None):
    return _ops.scan(x, op, comm=comm, token=_start(token, x))


def scatter(x, root=0, *, comm=None, token=None):
    return _ops.scatter(x, root, comm=comm, token=_start(token, x))


def send(x, dest, tag=0, *, comm=None, token=None):
    return _ops.send(x, dest, tag, comm=comm, token=_start(token, x))


def sendrecv(
    x, *, perm=None, shift=None, wrap=True, source=None, dest=None,
    tag=None, sendtag=0, recvtag=None, status=None, comm=None, token=None
):
    return _ops.sendrecv(
        x, perm=perm, shift=shift, wrap=wrap, source=source, dest=dest,
        tag=tag, sendtag=sendtag, recvtag=recvtag, status=status,
        comm=comm, token=_start(token, x),
    )


__all__ = [
    "allgather", "allreduce", "alltoall", "barrier", "bcast", "create_token",
    "gather", "recv", "reduce", "scan", "scatter", "send", "sendrecv",
]
