from . import token_api  # noqa: F401
