"""Joint tuning: one search over algorithm x quantization x topology.

A 16 MiB allreduce's fate used to be decided by four independent
layers — the decision table, ``MPI4JAX_TPU_COLL_QUANT``,
``MPI4JAX_TPU_HIER``, and whether a schedule plan is installed — and
the interactions are real: the hierarchical ring's leader leg is
quant-eligible, and plan bucketing changes the payload sizes that pick
the best algorithm.  Following GC3's one-compiler-over-the-whole-space
argument (arXiv:2201.11840) and EQuARX's put-quantization-inside-the-
selection-loop argument (arXiv:2506.17615), this module owns the ONE
search space:

A **combo** is a string naming one point of the joint space:

- a plain algorithm name (``ring``/``rd``/``tree``) — exact wire,
  whatever gates;
- a quantized wire format (``qring``/``qrd``) — the quantization
  decision IS the algorithm choice (per-call forcible, no env needed);
- a hierarchical schedule (``hring``/``htree``) — the topology
  decision, per-call forcible on a multi-island comm;
- a gated variant (``hring+q``/``htree+q``) — the hierarchical
  schedule with its leader leg quantized, which only exists under
  ``MPI4JAX_TPU_COLL_QUANT=force`` (the native gate is cached
  per-process, so the driver measures these in a dedicated sub-job);
- an ICI-data-plane variant (``hring+ici``/... and the doubly gated
  ``hring+q+ici``/...) — the hierarchical schedule with its
  intra-island leg on the Pallas fused ring (``topo/_ici_leg.py``),
  which needs ``MPI4JAX_TPU_ICI_LEG`` active (``force`` in the
  driver's sub-jobs; ``auto`` only activates on an all-ici-tier
  island).  A shape where the leg cannot run (no TPU island, or
  ``ICI_LEG=off``) EXCLUDES these combos from the candidate set —
  they would silently measure the plain schedule under a wrong label.

:func:`joint_search` runs the model-seeded search: measure every
eligible combo at a few anchor sizes, fit the cost model, then at every
other size measure only the model's top-k predictions (plus anything
the model has never seen) and crown the best *measured* combo — seeded
by prediction, decided by measurement.  The winners collapse into the
version-2 cache's per-size-band combination entries.

Stdlib-only and side-effect free: the CLI (``__main__.py``) supplies
the live ``measure`` callable; unit tests supply synthetic ones.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

try:
    from ._model import CostModel
except ImportError:  # pragma: no cover - standalone tooling load
    import importlib.util as _ilu
    import os as _os

    _spec = _ilu.spec_from_file_location(
        "m4j_tune_model_standalone",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "_model.py"))
    _model_mod = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_model_mod)
    CostModel = _model_mod.CostModel

#: gated-variant suffix: the combo's leader leg rides the quantized
#: wire under MPI4JAX_TPU_COLL_QUANT=force
QUANT_LEG_SUFFIX = "+q"

#: gated-variant suffix: the combo's intra-island leg rides the Pallas
#: ICI data plane (topo/_ici_leg.py) under MPI4JAX_TPU_ICI_LEG=force
ICI_LEG_SUFFIX = "+ici"

#: every point of the joint space per op (allgather has no quantized
#: schedule — it is pure data movement and the wire format is lossy —
#: and no ICI-leg variant — the leg is an f32 SUM allreduce schedule)
JOINT_CANDIDATES: Dict[str, Tuple[str, ...]] = {
    "allreduce": ("ring", "rd", "tree", "qring", "qrd",
                  "hring", "htree", "hring+q", "htree+q",
                  "hring+ici", "htree+ici", "hring+q+ici", "htree+q+ici"),
    "allgather": ("ring", "rd", "tree", "hring", "htree"),
    # the expert-routing exchange: quantized/hierarchical twins are
    # first-class per-call-forcible codes (hqalltoall quantizes ONLY
    # the leader leg, so no "+q" gated sub-job is needed — the code
    # itself names the quantized-leader schedule), and there is no
    # ICI-leg variant (the leg is an allreduce schedule)
    "alltoall": ("ring", "qalltoall", "halltoall", "hqalltoall"),
}


def _combo_parts(combo: str) -> Tuple[str, frozenset]:
    """``"hring+q+ici"`` -> ``("hring", {"q", "ici"})``."""
    parts = str(combo).split("+")
    return parts[0], frozenset(parts[1:])


def combo_algo(combo: str) -> str:
    """The per-call-forcible algorithm under a combo label."""
    return _combo_parts(combo)[0]


def combo_gates(combo: str) -> Dict[str, str]:
    """Env gates (beyond the allow defaults) a combo needs to run as
    measured.  Empty for every per-call-forcible combo; the suffixes
    compose (``hring+q+ici`` needs both force gates)."""
    _, legs = _combo_parts(combo)
    gates: Dict[str, str] = {}
    if "q" in legs:
        gates["MPI4JAX_TPU_COLL_QUANT"] = "force"
    if "ici" in legs:
        gates["MPI4JAX_TPU_ICI_LEG"] = "force"
    return gates


def check_combo(combo: str, op: str) -> str:
    combo = str(combo).strip()
    if combo not in JOINT_CANDIDATES.get(op, ()):
        raise ValueError(
            f"unknown joint combination {combo!r} for {op} "
            f"(expected one of {JOINT_CANDIDATES.get(op)})")
    return combo


def eligible_combos(op: str, *, multi_island: bool, quant_mode: str,
                    hier_mode: str, ici_leg: bool = False) -> List[str]:
    """The combos worth measuring on THIS deployment shape: quantized
    wire formats drop under quant deny (the engine would degrade the
    rows right back), hierarchical schedules need a discovered
    multi-island topology (anywhere else they degrade to their flat
    twins and the sweep would time ring/tree twice under wrong
    labels), the quantized-leader-leg variants need both, and the
    ``+ici`` variants need the ICI intra-island leg to actually
    activate here (``ici_leg`` — no TPU island under ``auto``, or
    ``MPI4JAX_TPU_ICI_LEG=off``, excludes them: a row timing the
    native intra path under an ``+ici`` label would be a lie)."""
    try:
        # shared vocabulary (A2A_*: the alltoall schedule family)
        from . import A2A_HIER, A2A_QUANT, HIER_ALGOS, QUANT_ALGOS
    except ImportError:  # standalone load: the engine's stable names
        HIER_ALGOS = frozenset(("hring", "htree"))
        QUANT_ALGOS = frozenset(("qring", "qrd"))
        A2A_QUANT = frozenset(("qalltoall", "hqalltoall"))
        A2A_HIER = frozenset(("halltoall", "hqalltoall"))

    out = []
    for combo in JOINT_CANDIDATES[op]:
        algo, legs = _combo_parts(combo)
        quantized = algo in QUANT_ALGOS or algo in A2A_QUANT \
            or "q" in legs
        if quantized and quant_mode == "deny":
            continue
        if (algo in HIER_ALGOS or algo in A2A_HIER) \
                and (not multi_island or hier_mode == "deny"):
            continue
        if "ici" in legs and not ici_leg:
            continue
        out.append(combo)
    return out


def synthetic_measure(ranks: int) -> Callable[[str, int, str],
                                              Optional[float]]:
    """A deterministic alpha-beta cost table shaped like a real
    multi-island deployment, for driving :func:`joint_search` without
    live communication (the verify-scale harness and tuner unit tests
    at virtual world sizes): hierarchical schedules amortize the
    inter-island latency term, quantized wire formats cut the
    bandwidth term, the ICI leg shaves intra-island latency.  Same
    (op, nbytes, combo) → same seconds, every call, every host — the
    point is search-machinery sanity at scale, not real timings."""
    def measure(op: str, nbytes: int, combo: str) -> Optional[float]:
        algo, legs = _combo_parts(combo)
        alpha = 40e-6 if algo.startswith("h") else 120e-6
        beta = 2.0e-9
        if algo in ("qring", "qrd", "qalltoall", "hqalltoall") \
                or "q" in legs:
            beta *= 0.55
        if "ici" in legs:
            alpha *= 0.8
        steps = 2.0 if op == "allreduce" else 1.0
        return alpha * steps + beta * float(nbytes) \
            + 1e-9 * max(0, ranks - 1)
    return measure


def _anchor_sizes(sizes: Sequence[int], n_anchors: int = 3) -> List[int]:
    """The sizes every combo is measured at to seed the model: the
    extremes plus the middle of the ladder (log-wise) — enough to pin
    each combo's alpha and beta, cheap enough to afford for every
    candidate."""
    ordered = sorted(set(int(s) for s in sizes))
    if len(ordered) <= n_anchors:
        return ordered
    picks = {ordered[0], ordered[-1], ordered[len(ordered) // 2]}
    return sorted(picks)


def joint_search(
    measure: Callable[[str, int, str], Optional[float]],
    candidates_by_op: Dict[str, Sequence[str]],
    sizes: Sequence[int],
    *,
    model: Optional[CostModel] = None,
    topk: int = 3,
    ranks: int = 0,
    log: Optional[Callable[[dict], None]] = None,
) -> Tuple[Dict[str, Dict[int, str]], List[dict], CostModel]:
    """Model-seeded joint search.

    ``measure(op, nbytes, combo)`` returns the agreed cross-rank median
    seconds of one live measurement, or None when the combo cannot be
    measured in this process (its gates are not active — the driver
    runs those in a sub-job).  ``model`` may arrive pre-seeded from
    ``--from-trace`` recordings; everything measured here is added to
    it, so the returned model reflects the live run.

    Returns ``(best, measurements, model)``: the best *measured* combo
    per (op, size), the measurement rows (cache-payload shaped, each
    stamped with its search phase), and the updated model.
    """
    model = model if model is not None else CostModel(world_size=ranks)
    best: Dict[str, Dict[int, str]] = {}
    measurements: List[dict] = []

    def _measure(op, nbytes, combo, phase):
        dt = measure(op, nbytes, combo)
        if dt is None:
            return None
        model.add_sample(op, combo, nbytes, dt)
        row = {"op": op, "bytes": int(nbytes), "combo": combo,
               "algo": combo_algo(combo), "seconds": round(float(dt), 9),
               "ranks": int(ranks), "phase": phase}
        gates = combo_gates(combo)
        if gates:
            # the cache payload's top-level knobs stamp records the
            # DRIVER's env; a gated combo's rows were measured under
            # their own sub-job gates — say so per row, or the stamp
            # would misstate exactly the measurements it exists for
            row["gates"] = gates
        measurements.append(row)
        if log is not None:
            log(row)
        return dt

    for op, cands in candidates_by_op.items():
        cands = [check_combo(c, op) for c in cands]
        if not cands:
            continue
        anchors = _anchor_sizes(sizes)
        measured: Dict[int, Dict[str, float]] = {}
        for nbytes in anchors:
            for combo in cands:
                dt = _measure(op, nbytes, combo, "anchor")
                if dt is not None:
                    measured.setdefault(nbytes, {})[combo] = dt
        for nbytes in sorted(set(int(s) for s in sizes)):
            here = measured.setdefault(nbytes, {})
            if nbytes not in anchors:
                ranked = model.rank_combos(op, nbytes, cands)
                # measure the model's top-k predictions plus every
                # combo it has no opinion on — prediction seeds, live
                # measurement decides
                chosen = [c for c, _ in ranked[:topk]]
                chosen += [c for c, p in ranked[topk:] if p is None]
                for combo in chosen:
                    dt = _measure(op, nbytes, combo, "refine")
                    if dt is not None:
                        here[combo] = dt
            if here:
                best.setdefault(op, {})[nbytes] = min(here, key=here.get)
    return best, measurements, model


def merge_winners(
    measurement_sets: Sequence[Sequence[dict]],
) -> Tuple[Dict[str, Dict[int, str]], List[dict]]:
    """Fold measurement rows from several sub-jobs (the base sweep and
    the gated ``+q``/``+ici`` sweeps) into one winner table: the best measured
    combo per (op, size) across every set, plus the concatenated rows.
    Re-measurements of one (op, size, combo) keep their best (the
    quietest observation of the same schedule)."""
    pooled: Dict[Tuple[str, int, str], float] = {}
    rows: List[dict] = []
    for mset in measurement_sets:
        for row in mset:
            combo = row.get("combo") or row.get("algo")
            if not combo or float(row.get("seconds", 0)) <= 0:
                continue
            key = (str(row["op"]), int(row["bytes"]), str(combo))
            dt = float(row["seconds"])
            if key not in pooled or dt < pooled[key]:
                pooled[key] = dt
            rows.append(row)
    best: Dict[str, Dict[int, str]] = {}
    per: Dict[Tuple[str, int], Dict[str, float]] = {}
    for (op, nbytes, combo), dt in pooled.items():
        per.setdefault((op, nbytes), {})[combo] = dt
    for (op, nbytes), by_combo in per.items():
        best.setdefault(op, {})[nbytes] = min(by_combo, key=by_combo.get)
    return best, rows
