"""Collective cost model: fit from recorded timings, predict anywhere.

The joint tuner (``_joint.py``, ``python -m mpi4jax_tpu.tune --joint``)
and the schedule compiler (``analysis/_plan.py``) both need ONE answer
to "how long will this collective take?" — per (op, algorithm
combination, payload size) on the topology shape the measurements came
from.  This module is that answer: a :class:`CostModel` holds the
measured medians and fits a classic **alpha-beta** curve per (op,
combo),

    t(b) = alpha + b * beta        (startup latency + inverse bandwidth)

by weighted least squares (weights ``1/t^2`` — relative error, so the
microsecond end of a nine-order-of-magnitude sweep is not drowned by
the 16 MiB end).  Queries at a measured size return the measurement;
between measured sizes they log-log interpolate (the measured curve is
ground truth where it exists); outside the measured range they ride the
fitted line.  That split is what makes the model honest: the fit only
ever *extrapolates*, never overrides data.

Sources of samples, in the order the joint tuner uses them:

- ``obs`` recordings of real runs (``tune.fit_model_from_events`` —
  the dispatch/wait/wire splits ride along as per-sample fractions);
- the tuner's own sweep measurement rows (``from_measurements``).

Jax-free, numpy-free, stdlib-only — importable (and test-loadable)
standalone like the rest of the tune package.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

MODEL_VERSION = 1

#: default candidate ladder for gradient-bucket sizing (bytes)
BUCKET_LADDER = tuple(1 << p for p in range(16, 23))  # 64 KiB .. 4 MiB

#: concurrency-group cap bounds the model may suggest (the compiler's
#: static default, _deps.MAX_GROUP = 4, sits inside this range)
MIN_GROUP_CAP = 2
MAX_GROUP_CAP = 8


def _median(values: Sequence[float]) -> float:
    """Interpolated median, identical to numpy's / the profile report's
    p50 on the same samples (the tune package's house convention)."""
    vals = sorted(float(v) for v in values)
    n = len(vals)
    return (vals[(n - 1) // 2] + vals[n // 2]) / 2.0


def _fit_alpha_beta(points: Dict[int, float]) -> Tuple[float, float]:
    """Weighted least squares of ``t = alpha + beta * bytes`` over
    ``{bytes: seconds}`` with weights ``1/t^2`` (relative error).
    Degenerate inputs fall back conservatively: one point becomes a
    pure-bandwidth line through it, so extrapolation never predicts a
    free collective."""
    items = [(float(b), float(t)) for b, t in points.items() if t > 0]
    if not items:
        return 0.0, 0.0
    if len(items) == 1:
        b, t = items[0]
        return (t, 0.0) if b <= 0 else (0.0, t / b)
    sw = swx = swy = swxx = swxy = 0.0
    for b, t in items:
        w = 1.0 / (t * t)
        sw += w
        swx += w * b
        swy += w * t
        swxx += w * b * b
        swxy += w * b * t
    denom = sw * swxx - swx * swx
    if denom <= 0:
        b, t = items[-1]
        return (0.0, t / b) if b > 0 else (t, 0.0)
    beta = (sw * swxy - swx * swy) / denom
    alpha = (swy - beta * swx) / sw
    # a fitted negative coefficient (noise on a near-flat curve) would
    # predict negative times out of range; clamp to the physical floor
    return max(alpha, 0.0), max(beta, 0.0)


class CostModel:
    """Measured medians + fitted alpha-beta curves per (op, combo).

    A *combo* is the joint tuner's algorithm-combination label: a plain
    algorithm name (``ring``/``qring``/``hring``/...) or a gated
    variant (``hring+q`` — the hierarchical ring with its leader leg
    quantized under ``MPI4JAX_TPU_COLL_QUANT=force``).  The model does
    not interpret combos; ``_joint.py`` owns their semantics.
    """

    def __init__(self, *, world_size: int = 0, topology: Optional[str] = None,
                 dtype: str = "float32", knobs: Optional[dict] = None,
                 source: str = ""):
        self.world_size = int(world_size)
        self.topology = topology
        self.dtype = str(dtype)
        self.knobs = dict(knobs or {})
        self.source = str(source)
        #: (op, combo) -> {nbytes: median seconds}
        self.samples: Dict[Tuple[str, str], Dict[int, float]] = {}
        #: (op, combo) -> {nbytes: mean wire fraction} (may be sparse)
        self.wire_frac: Dict[Tuple[str, str], Dict[int, float]] = {}
        #: (op, combo) -> {nbytes: mean dispatch fraction} (may be sparse)
        self.dispatch_frac: Dict[Tuple[str, str], Dict[int, float]] = {}
        self._fits: Dict[Tuple[str, str], Tuple[float, float]] = {}

    # -- construction ---------------------------------------------------

    def add_sample(self, op: str, combo: str, nbytes: int, seconds: float,
                   *, wire_frac: Optional[float] = None,
                   dispatch_frac: Optional[float] = None) -> None:
        key = (str(op), str(combo))
        self.samples.setdefault(key, {})[int(nbytes)] = float(seconds)
        if wire_frac is not None:
            self.wire_frac.setdefault(key, {})[int(nbytes)] = \
                float(wire_frac)
        if dispatch_frac is not None:
            self.dispatch_frac.setdefault(key, {})[int(nbytes)] = \
                float(dispatch_frac)
        self._fits.pop(key, None)

    @classmethod
    def from_measurements(cls, measurements, **meta) -> "CostModel":
        """Build from tuner/benchmark measurement rows (dicts with
        ``op``/``bytes``/``seconds`` and a combination label under
        ``combo`` or ``algo``).  Multiple rows for one (op, combo,
        bytes) collapse to their median."""
        acc: Dict[Tuple[str, str, int], List[float]] = {}
        fracs: Dict[Tuple[str, str, int], Dict[str, float]] = {}
        for row in measurements:
            combo = row.get("combo") or row.get("algo")
            if not combo or row.get("op") is None:
                continue
            secs = float(row.get("seconds", 0.0))
            if secs <= 0:
                continue
            key = (str(row["op"]), str(combo), int(row["bytes"]))
            acc.setdefault(key, []).append(secs)
            for frac in ("wire_frac", "dispatch_frac"):
                if row.get(frac) is not None:
                    fracs.setdefault(key, {})[frac] = float(row[frac])
        model = cls(**meta)
        for (op, combo, nbytes), vals in sorted(acc.items()):
            fr = fracs.get((op, combo, nbytes), {})
            model.add_sample(op, combo, nbytes, _median(vals),
                             wire_frac=fr.get("wire_frac"),
                             dispatch_frac=fr.get("dispatch_frac"))
        return model

    # -- prediction -----------------------------------------------------

    def combos(self, op: str) -> List[str]:
        """Combination labels the model has samples for, for one op."""
        return sorted(c for (o, c) in self.samples if o == op)

    def _fit(self, key: Tuple[str, str]) -> Tuple[float, float]:
        if key not in self._fits:
            self._fits[key] = _fit_alpha_beta(self.samples.get(key, {}))
        return self._fits[key]

    def predict(self, op: str, nbytes: int,
                combo: str) -> Optional[float]:
        """Predicted seconds for one collective, or None when the model
        has never seen (op, combo) — the joint tuner treats None as
        "must measure live"."""
        key = (str(op), str(combo))
        pts = self.samples.get(key)
        if not pts:
            return None
        nbytes = int(nbytes)
        if nbytes in pts:
            return pts[nbytes]
        sizes = sorted(pts)
        lo = max((s for s in sizes if s < nbytes), default=None)
        hi = min((s for s in sizes if s > nbytes), default=None)
        if lo is not None and hi is not None:
            # log-log interpolation between the bracketing measurements
            import math

            f = ((math.log(nbytes) - math.log(lo))
                 / (math.log(hi) - math.log(lo)))
            return math.exp(math.log(pts[lo]) * (1 - f)
                            + math.log(pts[hi]) * f)
        alpha, beta = self._fit(key)
        pred = alpha + beta * nbytes
        if hi is not None:
            # below the measured range, clamp to what the data implies:
            # at most the smallest measurement (smaller payload, same
            # schedule), and at least its pure-bandwidth scaling —
            # per-byte cost alpha/b + beta is non-increasing in b, so
            # t(b) >= (b/B) * t(B) for b < B holds for ANY alpha-beta
            # curve.  Without the floor, an alpha fit near zero (two
            # wire-bound large samples) would fabricate a near-free
            # 1 KB op and bias bucket pricing / combo seeding.
            floor = pts[hi] * nbytes / hi
            return min(max(pred, floor), pts[hi])
        return max(pred, 0.0)

    def rank_combos(self, op: str, nbytes: int,
                    candidates: Sequence[str]):
        """``[(combo, predicted seconds | None), ...]`` sorted fastest
        first; unpredictable combos (no samples) sort last, so a search
        that measures the top-k always includes the genuinely unknown
        ones in its "must measure" tail."""
        scored = [(c, self.predict(op, nbytes, c)) for c in candidates]
        return sorted(scored,
                      key=lambda cp: (cp[1] is None,
                                      cp[1] if cp[1] is not None else 0.0))

    # -- what the schedule compiler asks --------------------------------

    def best_bucket_bytes(self, total_bytes: int,
                          ladder: Sequence[int] = BUCKET_LADDER,
                          op: str = "allreduce",
                          combo: Optional[str] = None) -> Optional[int]:
        """The gradient-bucket ceiling minimizing the predicted cost of
        syncing ``total_bytes`` of small gradients: ``ceil(total/b)``
        buckets each paying ``predict(op, b)``.  ``combo`` defaults to
        the model's best-predicted combination at each candidate size
        (the decision table will be tuned from the same model, so the
        bucketed allreduces really run that pick).  None when the model
        has no samples for the op (the compiler then keeps its static
        default)."""
        total = max(int(total_bytes), 1)
        cands = self.combos(op)
        if not cands:
            return None

        def _pred(nbytes):
            if combo is None:
                preds = [p for _, p in self.rank_combos(op, nbytes, cands)
                         if p is not None]
                return preds[0] if preds else None
            return self.predict(op, nbytes, combo)

        best_b, best_cost = None, None
        # descending, with a 0.1% improvement bar: near-ties keep the
        # LARGER bucket (fewer dispatches, same predicted wire time)
        for b in sorted((int(b) for b in ladder), reverse=True):
            # full buckets at b, plus one remainder bucket at its own
            # (smaller) predicted cost — pricing the tail at the full
            # bucket size would overcharge every ceiling > total
            full, rem = divmod(total, b)
            cost = 0.0
            if full:
                per = _pred(b)
                if per is None:
                    continue
                cost += full * per
            if rem:
                per = _pred(rem)
                if per is None:
                    continue
                cost += per
            if best_cost is None or cost < best_cost * 0.999:
                best_b, best_cost = b, cost
        return best_b

    def suggested_group_cap(self, nbytes: int, op: str = "send",
                            combo: str = "ring",
                            default: int = 4) -> int:
        """Concurrency-group cap for the schedule compiler: how many
        independent ops' completions are worth keeping outstanding
        together.  Dispatch-dominated sizes (the fitted startup alpha
        is most of the predicted time) benefit from deeper groups —
        each deferred completion hides another alpha — while wire-bound
        sizes gain nothing past the default.  Clamped to
        [MIN_GROUP_CAP, MAX_GROUP_CAP]; ``default`` when the model has
        no samples for (op, combo)."""
        key = (str(op), str(combo))
        if not self.samples.get(key):
            # sends are not recorded per-algorithm; fall back to any
            # same-op samples before giving up
            alts = [c for (o, c) in self.samples if o == op]
            if not alts:
                return int(default)
            key = (str(op), alts[0])
        alpha, beta = self._fit(key)
        t = alpha + beta * max(int(nbytes), 1)
        if t <= 0:
            return int(default)
        alpha_share = alpha / t
        if alpha_share >= 0.5:
            cap = MAX_GROUP_CAP
        elif alpha_share >= 0.25:
            cap = 6
        else:
            cap = int(default)
        return max(MIN_GROUP_CAP, min(MAX_GROUP_CAP, cap))

    # -- persistence ----------------------------------------------------

    def to_json(self) -> dict:
        def _grid(table):
            return {f"{op}/{combo}": {str(b): v
                                      for b, v in sorted(pts.items())}
                    for (op, combo), pts in sorted(table.items())}

        return {
            "version": MODEL_VERSION,
            "world_size": self.world_size,
            "topology": self.topology,
            "dtype": self.dtype,
            "knobs": dict(self.knobs),
            "source": self.source,
            "samples": _grid(self.samples),
            "wire_frac": _grid(self.wire_frac),
            "dispatch_frac": _grid(self.dispatch_frac),
        }

    @classmethod
    def from_json(cls, data: dict) -> "CostModel":
        if int(data.get("version", -1)) != MODEL_VERSION:
            raise ValueError(
                f"cost model has version {data.get('version')!r}, "
                f"expected {MODEL_VERSION}")
        model = cls(world_size=int(data.get("world_size", 0)),
                    topology=data.get("topology"),
                    dtype=data.get("dtype", "float32"),
                    knobs=data.get("knobs"),
                    source=data.get("source", ""))

        def _load(table, dest):
            for key, pts in (table or {}).items():
                op, _, combo = key.partition("/")
                dest[(op, combo)] = {int(b): float(v)
                                     for b, v in pts.items()}

        _load(data.get("samples"), model.samples)
        _load(data.get("wire_frac"), model.wire_frac)
        _load(data.get("dispatch_frac"), model.dispatch_frac)
        return model


def model_path(world_size: int,
               topo_fingerprint: Optional[str] = None) -> str:
    """Default persistent path: ``MPI4JAX_TPU_TUNE_MODEL`` overrides,
    else ``~/.cache/mpi4jax_tpu/model_<size>[_<topohash>].json`` beside
    the tune cache."""
    forced = os.environ.get("MPI4JAX_TPU_TUNE_MODEL")
    if forced and forced.strip():
        return forced
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    suffix = f"_{topo_fingerprint}" if topo_fingerprint else ""
    return os.path.join(base, "mpi4jax_tpu",
                        f"model_{int(world_size)}{suffix}.json")


def save_model(model: CostModel, path: Optional[str] = None) -> str:
    p = path or model_path(model.world_size, model.topology)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    tmp = f"{p}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(model.to_json(), f, indent=1, sort_keys=True)
    os.replace(tmp, p)
    return p


def load_model(path: str) -> CostModel:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "samples" not in data:
        raise ValueError(f"{path} is not a cost-model file")
    return CostModel.from_json(data)
