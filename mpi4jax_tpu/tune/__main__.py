"""Offline collective autotuner CLI.

    python -m mpi4jax_tpu.tune [--np 4] [--sizes 1024,...,16777216]
                               [--repeats N] [--ops allreduce,alltoall]
                               [--cache PATH] [--port P] [--joint]

Sweeps every selectable algorithm (ring / recursive doubling / tree,
plus the quantized qring/qrd allreduce twins) for each (op, payload
size) on a live job and writes the winners to the
persistent cache (``tune.cache_path(world_size)``), which is loaded at
communicator creation on every subsequent run — see ``tune.install``.

``--joint`` replaces the one-axis sweep with the JOINT search
(``tune/_joint.py``, docs/usage.md § Joint tuning): algorithm x
quantization x topology combinations compete in one space, seeded by a
cost model (``tune/_model.py``) fit from anchor measurements (and, with
``--from-trace``, from real-run recordings) and refined by live
measurement of the model's top-k per size.  Combinations whose gates
are per-process — ``+q`` (quantized leader leg, needs
``MPI4JAX_TPU_COLL_QUANT=force``), ``+ici`` (intra-island legs on the
Pallas ICI data plane, needs ``MPI4JAX_TPU_ICI_LEG=force``), and their
composition — are grouped by gate set and measured in one dedicated
sub-job per set, skipping sets whose gate cannot engage (quant deny /
ici off).  The result is ONE v2 cache recording the winning *combination* per
size band, plus the fitted cost-model file
(``tune._model.model_path``) the schedule compiler can consult.

``--from-trace out.json.rank0.json`` (or a glob / the merged trace)
skips the synthetic sweep entirely and derives the cache from a REAL
run's recorded per-op timings (``mpi4jax_tpu.launch --trace`` +
``mpi4jax_tpu/obs`` — docs/observability.md): the winner per (op,
payload size) is the algorithm with the best median observed time.
Recordings from superseded elastic world generations are rejected (an
elastic shrink mid-recording must not pool pre- and post-shrink
timings into one median).  With ``--joint``, recordings SEED the model
instead of replacing the sweep.

Three modes:

- **driver** (the normal invocation, outside a world job): re-executes
  itself under the bundled launcher at ``--np`` ranks with the shm arena
  disabled — the selector governs the TCP/multi-host path, and tuning
  through the arena would measure the wrong transport.
- **rank** (inside a world job): runs the sweep over the native
  transport directly (no jit in the loop: the tuner measures the
  wire/algorithm cost itself), agrees on per-size winners via a MAX
  allreduce of the timings, and rank 0 writes the cache atomically.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

if __package__ in (None, ""):  # executed as a file by the launcher
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    )

try:
    from mpi4jax_tpu import tune
except ImportError:
    # the package __init__ gates on the jax version; the engine itself
    # is stdlib-only, so the no-live-job mode (--from-trace) still works
    # when this file is run directly: python mpi4jax_tpu/tune/__main__.py
    import importlib.util

    _spec = importlib.util.spec_from_file_location(
        "m4j_tune_standalone",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "__init__.py"),
    )
    tune = importlib.util.module_from_spec(_spec)
    sys.modules["m4j_tune_standalone"] = tune
    _spec.loader.exec_module(tune)

# native wire codes (tpucomm.h): dtype f32 = 11, ops SUM = 0 / MAX = 2
_F32, _F64 = 11, 12
_SUM, _MAX = 0, 2

DEFAULT_SIZES = [1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10,
                 1 << 20, 4 << 20, 16 << 20]
#: algorithms swept per op.  The sweep's payload is f32 SUM, so the
#: quantized wire formats are eligible and measured HONESTLY for
#: allreduce (the dominant DP-gradient shape); a cache row naming
#: qring/qrd silently degrades to the exact twin at dispatch for
#: ineligible calls (integer dtypes, MAX/MIN), so the sweep's winners
#: are safe to install table-wide.  Sweeping them can be suppressed
#: with MPI4JAX_TPU_COLL_QUANT=deny (the rows would degrade anyway).
CANDIDATES = {
    "allreduce": ("ring", "rd", "tree", "qring", "qrd"),
    "allgather": ("ring", "rd", "tree"),
    "alltoall": ("ring", "qalltoall"),
}


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(prog="python -m mpi4jax_tpu.tune")
    ap.add_argument("--np", type=int, default=None, dest="np_",
                    help="ranks to tune for (driver mode; default 4). "
                         "With --from-trace: override the recording's "
                         "own world size")
    ap.add_argument("--sizes", default=None,
                    help="comma-separated payload byte sizes "
                         "(default: 1KB..16MB x4 ladder)")
    ap.add_argument("--repeats", type=int, default=0,
                    help="timed iterations per point (0 = auto-scale)")
    ap.add_argument("--ops", default="allreduce,allgather,alltoall")
    ap.add_argument("--cache", default=None,
                    help="cache file path (default: tune.cache_path(np))")
    ap.add_argument("--port", type=int, default=None,
                    help="launcher base port (driver mode)")
    ap.add_argument("--no-quantize", action="store_true",
                    help="with --from-trace: never promote a wire-bound "
                         "exact allreduce winner to its quantized twin "
                         "(qring/qrd); the derived table stays exact-only")
    ap.add_argument("--from-trace", default=None, metavar="REC[,REC...]",
                    help="derive the cache from a recorded real run "
                         "instead of a synthetic sweep: comma-separated "
                         "recording part files (out.json.rank*.json) "
                         "and/or merged traces written by `launch --trace` "
                         "(globs allowed); winners are the best median "
                         "observed per (op, payload size).  With --joint "
                         "the recordings SEED the cost model instead")
    ap.add_argument("--joint", action="store_true",
                    help="search the joint algorithm x quantization x "
                         "topology space (model-seeded, measurement-"
                         "refined) and write a v2 cache recording the "
                         "winning combination per size band, plus the "
                         "fitted cost-model file")
    ap.add_argument("--topk", type=int, default=3,
                    help="--joint: combos measured live per non-anchor "
                         "size (the model's best k predictions; unknown "
                         "combos are always measured)")
    ap.add_argument("--model-out", default=None,
                    help="--joint: cost-model output path (default: "
                         "tune._model.model_path(np), or "
                         "MPI4JAX_TPU_TUNE_MODEL)")
    # internal plumbing between the --joint driver and its sub-jobs
    ap.add_argument("--joint-out", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--joint-combos", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--joint-model", default=None, help=argparse.SUPPRESS)
    return ap.parse_args(argv)


def _from_trace(args) -> int:
    try:
        paths = _trace_paths(args.from_trace)
    except FileNotFoundError as e:
        print(f"tune: {e}", file=sys.stderr, flush=True)
        return 2
    try:
        cache = tune.cache_from_trace(
            paths, world_size=args.np_, cache_path_override=args.cache,
            quantize=not args.no_quantize,
        )
    except (ValueError, OSError) as e:
        print(f"tune: --from-trace: {e}", file=sys.stderr, flush=True)
        return 2
    print(f"tune: cache written to {cache} (from {len(paths)} "
          "recording file(s))")
    return 0


def _driver(args) -> int:
    """Re-exec under the launcher, then report the written cache."""
    np_ = args.np_ or 4
    cmd = [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
           "-n", str(np_)]
    if args.port:
        cmd += ["--port", str(args.port)]
    cmd += [os.path.abspath(__file__)]
    for flag, val in (("--sizes", args.sizes),
                      ("--repeats", args.repeats or None),
                      ("--ops", args.ops)):
        if val:
            cmd += [flag, str(val)]
    # only forward an EXPLICIT cache path: the default path may be
    # topology-keyed (tune_<size>_<topohash>.json), and only the ranks
    # know the discovered fingerprint — rank 0 prints where it wrote
    if args.cache:
        cmd += ["--cache", args.cache]
    cache = args.cache
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # tune the TCP path: the arena would hide every algorithm behind the
    # same-host fast path (the selector governs TCP/multi-host).  Under
    # a MPI4JAX_TPU_FAKE_HOSTS partition the WORLD arena is already
    # withheld by the virtual host split, and the intra-island arenas
    # are part of what the hierarchical rows measure — leave shm alone.
    if not os.environ.get("MPI4JAX_TPU_FAKE_HOSTS", "").strip():
        env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    # a forced algorithm would make every sweep point measure one
    # schedule — the sweep must be free to force its own
    env.pop("MPI4JAX_TPU_COLL_ALGO", None)
    rc = subprocess.run(cmd, env=env).returncode
    if rc == 0 and cache:
        print(f"tune: cache written to {cache}")
    return rc


def _time_point(comm, bridge, np, op, nbytes, algo, repeats):
    """Median per-call wall time of `repeats` forced-algorithm
    collectives, maxed across ranks (a collective is as slow as its
    slowest rank).

    Each sample starts from a barrier — the same methodology as
    ``allreduce_sweep``'s raw loop, for the same reason: back-to-back
    free-running calls accumulate rank drift whose stalls land on
    whichever schedule runs later, an artifact of the loop rather than
    of the algorithm — and near-twin candidates (hring+q vs htree+q)
    differ by less than that drift."""
    code = tune.ALGO_CODES[algo]
    h = comm.handle
    if op == "allreduce":
        x = np.ones(max(nbytes // 4, 1), np.float32)
        out = np.empty_like(x)

        def run():
            bridge.allreduce_raw(h, x, out, _F32, _SUM, algo=code)
    elif op == "alltoall":
        # nbytes is the whole send buffer (size rows of nbytes/size),
        # matching the public op's (size, ...) contract
        x = np.ones((comm.size(),
                     max(nbytes // 4 // comm.size(), 1)), np.float32)
        out = np.empty_like(x)

        def run():
            bridge.alltoall_raw(h, x, out, algo=code)
    else:
        x = np.ones(max(nbytes // 4, 1), np.float32)
        out = np.empty((comm.size(),) + x.shape, np.float32)

        def run():
            bridge.allgather_raw(h, x, out, algo=code)

    run()  # warmup + cross-rank alignment on the same op count
    times = []
    for _ in range(max(repeats, 3)):
        bridge.barrier(h)  # outside the timed window, same for every algo
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    times.sort()
    n = len(times)
    dt = (times[(n - 1) // 2] + times[n // 2]) / 2.0
    agreed = np.empty(1, np.float64)
    bridge.allreduce_raw(h, np.array([dt], np.float64), agreed, _F64, _MAX)
    return float(agreed[0])


def _rank(args) -> int:
    import numpy as np

    from mpi4jax_tpu.runtime import bridge, transport

    comm = transport.get_world_comm()
    n = comm.size()
    if not hasattr(bridge.get_lib(), "tpucomm_allreduce_algo"):
        # a stale prebuilt .so without per-call forcing would make every
        # candidate time the same default schedule — the written cache
        # would be noise dressed up as measurements.  Fail instead.
        print("tune: ERROR — the loaded native library predates the "
              "algorithm engine (no tpucomm_allreduce_algo); rebuild "
              "native/ before tuning", file=sys.stderr, flush=True)
        return 1
    active, _, _ = bridge.shm_info(comm.handle)
    if active and comm.rank() == 0:
        print("tune: WARNING — the shm arena is active; collectives take "
              "the same-host fast path and every algorithm will measure "
              "alike (run via the driver, which disables the arena)",
              file=sys.stderr, flush=True)

    sizes = ([int(s) for s in args.sizes.split(",")] if args.sizes
             else DEFAULT_SIZES)
    ops = [tune._check_op(o.strip()) for o in args.ops.split(",") if o.strip()]
    # hierarchical rows: only a comm with a discovered multi-island
    # topology runs them for real (anywhere else they degrade to their
    # flat twins and the sweep would time ring/tree twice under
    # different labels — noise dressed up as measurements)
    from mpi4jax_tpu import topo as _topo

    topology = _topo.get_topology(comm.handle)
    hier_ok = (topology is not None and topology.multi
               and hasattr(bridge.get_lib(), "tpucomm_set_topology"))
    from mpi4jax_tpu.utils.config import hier_mode, quant_mode

    if hier_mode() == "deny":
        hier_ok = False
    measurements = []
    best = {op: {} for op in ops}
    for op in ops:
        for nbytes in sizes:
            repeats = args.repeats or max(7, min(30, int(3e6 / max(nbytes, 1))))
            per_algo = {}
            cands = CANDIDATES[op]
            if hier_ok:
                extra = (("halltoall", "hqalltoall") if op == "alltoall"
                         else ("hring", "htree"))
                cands = cands + tuple(a for a in extra
                                      if a not in cands)
            if quant_mode() == "deny":
                cands = tuple(a for a in cands
                              if a not in tune.QUANT_ALGOS
                              and a not in tune.A2A_QUANT)
            for algo in cands:
                dt = _time_point(comm, bridge, np, op, nbytes, algo, repeats)
                per_algo[algo] = dt
                measurements.append({
                    "op": op, "bytes": nbytes, "algo": algo,
                    "seconds": round(dt, 9), "ranks": n,
                })
            winner = min(per_algo, key=per_algo.get)
            best[op][nbytes] = winner
            if comm.rank() == 0:
                print(json.dumps({
                    "op": op, "bytes": nbytes, "winner": winner,
                    "seconds": {a: round(t, 9) for a, t in per_algo.items()},
                }), flush=True)

    if comm.rank() == 0:
        table = {op: tune.entries_from_measurements(best[op]) for op in ops}
        # a multi-island sweep's winners are only valid on that shape:
        # stamp + key the cache on the topology fingerprint (flat
        # sweeps keep the legacy un-keyed name)
        topo_fp = (topology.fingerprint()
                   if topology is not None and topology.multi else None)
        path = tune.save_cache(n, table, measurements, path=args.cache,
                               topo_fingerprint=topo_fp)
        print(f"tune: wrote {path}", flush=True)
    bridge.barrier(comm.handle)  # cache is on disk before any rank exits
    return 0


def _trace_paths(spec: str):
    import glob as _glob

    paths = []
    for piece in spec.split(","):
        piece = piece.strip()
        if not piece:
            continue
        hits = sorted(_glob.glob(piece))
        if not hits:
            raise FileNotFoundError(
                f"--from-trace: no file matches {piece!r}")
        paths.extend(hits)
    return paths


def _joint_rank(args) -> int:
    """One rank of the joint search: every rank runs the identical
    model-seeded search (the per-point timings are MAX-agreed across
    ranks, so the search trajectory — and the winners — agree), and
    rank 0 hands the measurement rows back to the driver."""
    import numpy as np

    from mpi4jax_tpu import topo as _topo
    from mpi4jax_tpu.runtime import bridge, transport
    from mpi4jax_tpu.utils.config import hier_mode, quant_mode

    joint = tune._submodule("_joint")
    _model = tune._submodule("_model")

    comm = transport.get_world_comm()
    n = comm.size()
    if not hasattr(bridge.get_lib(), "tpucomm_allreduce_algo"):
        print("tune: ERROR — the loaded native library predates the "
              "algorithm engine; rebuild native/ before tuning",
              file=sys.stderr, flush=True)
        return 1
    topology = _topo.get_topology(comm.handle)
    multi = (topology is not None and topology.multi
             and hasattr(bridge.get_lib(), "tpucomm_set_topology"))
    sizes = ([int(s) for s in args.sizes.split(",")] if args.sizes
             else DEFAULT_SIZES)
    ops = [tune._check_op(o.strip()) for o in args.ops.split(",")
           if o.strip()]
    only = None
    if args.joint_combos:
        only = {c.strip() for c in args.joint_combos.split(",")
                if c.strip()}
    qm, hm = quant_mode(), hier_mode()
    # whether the ICI intra-island leg activates for f32 SUM allreduce
    # in THIS process (topology eligibility x MPI4JAX_TPU_ICI_LEG)
    leg_on = bool(multi and _topo.ici_leg_active(comm.handle))

    def _runs_as_labeled(combo, op):
        """Whether a per-call force of this combo's algorithm would
        actually RUN the labeled schedule under the process gates —
        the native resolver upgrades exact picks under a force gate,
        and a row timing the upgrade under an exact label is noise
        dressed up as a measurement."""
        algo = joint.combo_algo(combo)
        gates = joint.combo_gates(combo)
        wants_ici = "MPI4JAX_TPU_ICI_LEG" in gates
        if wants_ici and not leg_on:
            # +ici only exists where the leg activates (the driver
            # measures these in their own gated sub-jobs)
            return False
        if op == "allreduce" and algo in tune.HIER_ALGOS \
                and leg_on and not wants_ici:
            # the leg hijacks every f32 SUM hring/htree dispatch:
            # a plain (or +q) hierarchical row measured here would
            # time the ICI leg under the wrong label
            return False
        if "MPI4JAX_TPU_COLL_QUANT" in gates:
            # +q only exists under the force gate (the driver measures
            # these in their own sub-job)
            return qm == "force"
        if qm == "force":
            if algo in ("ring", "rd", "tree"):
                return False  # upgraded to the quantized twin
            if algo in tune.HIER_ALGOS:
                return False  # leader leg quantized: that IS +q
            if algo == "halltoall":
                return False  # leader leg quantized: that IS hqalltoall
        if hm == "force" and multi and algo in ("ring", "rd", "tree"):
            return False  # upgraded to the hierarchical twin
        return True

    candidates = {}
    for op in ops:
        cands = joint.eligible_combos(op, multi_island=multi,
                                      quant_mode=qm, hier_mode=hm,
                                      ici_leg=leg_on)
        cands = [c for c in cands if _runs_as_labeled(c, op)]
        if only is not None:
            cands = [c for c in cands if c in only]
        if cands:
            candidates[op] = cands

    seed = None
    if args.joint_model:
        seed = _model.load_model(args.joint_model)

    def measure(op, nbytes, combo):
        algo = joint.combo_algo(combo)
        repeats = args.repeats or max(7, min(30, int(3e6 / max(nbytes, 1))))
        return _time_point(comm, bridge, np, op, nbytes, algo, repeats)

    def log(row):
        if comm.rank() == 0:
            print(json.dumps(row), flush=True)

    best, measurements, model = joint.joint_search(
        measure, candidates, sizes, model=seed, topk=max(args.topk, 1),
        ranks=n, log=log)
    if comm.rank() == 0 and args.joint_out:
        payload = {
            "world_size": n,
            "multi": bool(multi),
            "topology": (topology.fingerprint()
                         if topology is not None and topology.multi
                         else None),
            "measurements": measurements,
        }
        tmp = f"{args.joint_out}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, args.joint_out)
    bridge.barrier(comm.handle)  # results are on disk before exit
    return 0


def _joint_driver(args) -> int:
    """Orchestrate the joint search: the base sub-job covers every
    per-call-forcible combination; the gated variants (quantized
    leader leg under per-process COLL_QUANT=force, ICI intra leg under
    ICI_LEG=force, and their composition) each get their own sub-job
    on a multi-island shape; the merged winners become ONE v2 cache
    plus the fitted cost-model file."""
    import tempfile

    from mpi4jax_tpu.utils.config import ici_leg_mode, quant_mode

    joint = tune._submodule("_joint")
    _model = tune._submodule("_model")

    np_ = args.np_ or 4
    workdir = tempfile.mkdtemp(prefix="m4j_joint_")

    def _sub_job(out_path, extra_env, extra_args, job_index=0):
        cmd = [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
               "-n", str(np_)]
        if args.port:
            # a fresh port block per sub-job: the base job's sockets
            # may still sit in TIME_WAIT when the forced_q job binds
            # (the same offset the --knob-grid driver applies)
            cmd += ["--port", str(args.port + job_index * (np_ + 2))]
        cmd += [os.path.abspath(__file__), "--joint",
                "--joint-out", out_path, "--topk", str(args.topk)]
        for flag, val in (("--sizes", args.sizes),
                          ("--repeats", args.repeats or None),
                          ("--ops", args.ops)):
            if val:
                cmd += [flag, str(val)]
        cmd += extra_args
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        if not os.environ.get("MPI4JAX_TPU_FAKE_HOSTS", "").strip():
            env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
        env.pop("MPI4JAX_TPU_COLL_ALGO", None)
        # an inherited FORCE gate would make the native resolver
        # silently upgrade per-call-forced exact algorithms (ring ->
        # qring/hring, ...) — every plain-labeled row would measure the
        # upgraded schedule and poison the cache/model.  The driver
        # owns the gates: base job runs under allow, the forced_q job
        # sets its own; an operator's deny stays (it restricts the
        # candidate set instead).
        for gate in ("MPI4JAX_TPU_COLL_QUANT", "MPI4JAX_TPU_HIER",
                     "MPI4JAX_TPU_ICI_LEG"):
            if env.get(gate, "").strip() == "force" \
                    and gate not in extra_env:
                print(f"tune: --joint: ignoring inherited {gate}=force "
                      "for the sweep sub-job (forced upgrades would "
                      "mislabel the exact-algorithm rows); gated "
                      "combinations are measured in their own sub-job",
                      file=sys.stderr, flush=True)
                env.pop(gate)
        env.update(extra_env)
        rc = subprocess.run(cmd, env=env).returncode
        if rc != 0:
            return rc, None
        try:
            with open(out_path) as f:
                return 0, json.load(f)
        except (OSError, ValueError) as e:
            print(f"tune: --joint: sub-job wrote no results: {e}",
                  file=sys.stderr, flush=True)
            return 2, None

    seed_args = []
    if args.from_trace:
        # recordings seed the model: the ranks start from the real
        # run's medians instead of measuring every anchor blind.  The
        # same world-generation gate as plain --from-trace applies — a
        # seed pooling pre- and post-shrink timings would steer the
        # top-k refinement from wrong-world medians.
        try:
            paths = _trace_paths(args.from_trace)
            events, _size = tune.collect_trace_events(paths)
            seed = tune.fit_model_from_events(events, world_size=np_,
                                              source="trace-seed")
            seed_path = os.path.join(workdir, "seed_model.json")
            _model.save_model(seed, path=seed_path)
            seed_args = ["--joint-model", seed_path]
        except (OSError, ValueError) as e:
            print(f"tune: --joint: cannot seed from recordings ({e}); "
                  "searching unseeded", file=sys.stderr, flush=True)

    rc, base = _sub_job(os.path.join(workdir, "base.json"), {}, seed_args)
    if rc != 0 or base is None:
        return rc or 2
    n = int(base["world_size"])
    topo_fp = base.get("topology")
    sets = [base["measurements"]]

    if base.get("multi"):
        # the gated variants exist only under their per-process force
        # gates: one sub-job per distinct gate set (quantized leader
        # leg, ICI intra leg, and their composition), each measuring
        # only the combos it gates — labeled as what actually ran.
        # An operator's deny/off excludes the matching gate sets
        # instead of mislabeling them.
        by_gates = {}
        for c in joint.JOINT_CANDIDATES["allreduce"]:
            gates = joint.combo_gates(c)
            if not gates:
                continue
            if "MPI4JAX_TPU_COLL_QUANT" in gates \
                    and quant_mode() == "deny":
                continue
            if "MPI4JAX_TPU_ICI_LEG" in gates \
                    and ici_leg_mode() == "off":
                continue
            by_gates.setdefault(tuple(sorted(gates.items())), []).append(c)
        for j, gk in enumerate(sorted(by_gates), start=1):
            combos = by_gates[gk]
            rc, gated = _sub_job(
                os.path.join(workdir, f"gated_{j}.json"), dict(gk),
                ["--joint-combos", ",".join(combos)], job_index=j)
            if rc == 0 and gated is not None:
                sets.append(gated["measurements"])
            else:
                print(f"tune: --joint: the gated sub-job for "
                      f"{', '.join(combos)} failed; the cache is "
                      "written without those rows",
                      file=sys.stderr, flush=True)

    best, rows = joint.merge_winners(sets)
    if not best:
        print("tune: --joint: no measurements survived; nothing to "
              "write", file=sys.stderr, flush=True)
        return 2
    model = _model.CostModel.from_measurements(
        rows, world_size=n, topology=topo_fp, source="joint",
        knobs=tune._config_mod().knob_env())
    model_file = _model.save_model(model, path=args.model_out)
    cache = tune.cache_from_joint(n, best, rows, path=args.cache,
                                  topo_fingerprint=topo_fp,
                                  model_file=model_file)
    for op in sorted(best):
        for nbytes in sorted(best[op]):
            print(json.dumps({"op": op, "bytes": nbytes,
                              "winner": best[op][nbytes]}), flush=True)
    print(f"tune: joint cache written to {cache}")
    print(f"tune: cost model written to {model_file}")
    return 0


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.from_trace and not args.joint:
        return _from_trace(args)
    try:
        from mpi4jax_tpu.runtime import transport
    except ImportError as e:
        if args.joint:
            print(f"tune: --joint needs the full package "
                  f"(jax >= 0.6): {e}", file=sys.stderr, flush=True)
            return 2
        print(f"tune: the sweep modes need the full package "
              f"(jax >= 0.6): {e}\n"
              "tune: --from-trace works standalone on recordings",
              file=sys.stderr, flush=True)
        return 2
    if args.joint:
        if transport.in_world():
            return _joint_rank(args)
        return _joint_driver(args)
    if transport.in_world():
        return _rank(args)
    return _driver(args)


if __name__ == "__main__":
    sys.exit(main())
