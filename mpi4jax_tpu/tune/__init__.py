"""Collective algorithm engine: selection, overrides, persistent autotuning.

The world tier's TCP collectives carry selectable schedules (ring /
recursive doubling / binomial tree, plus the quantized-wire qring/qrd
allreduce twins — ``native/tpucomm.cc``); this package owns WHICH one
runs.  Selection is a per-(op, payload-size-bucket)
decision table resolved in layers, strongest last:

1. static defaults (``_DEFAULT_TABLE`` — the pre-engine heuristics),
2. the persistent autotune cache (``~/.cache/mpi4jax_tpu/tune_<size>.json``,
   written by ``python -m mpi4jax_tpu.tune`` and loaded at communicator
   creation),
3. API overrides (:func:`set_algorithm`),
4. the ``MPI4JAX_TPU_COLL_ALGO`` env var (operator kill-switch; formats
   ``ring`` or ``allreduce=ring,allgather=tree``).

The merged table is pushed into the native layer
(``tpucomm_set_coll_table``) so every dispatch path — eager, host
callback, and the XLA FFI fast path — resolves the algorithm per call
from the actual payload size, with zero wire-format changes.

Consistency contract: selection must be identical on every rank of a
communicator (same cache file, same env, same override calls).  A
divergent choice cannot corrupt data — the algorithms exchange different
framed message schedules, so the ordered transport's tag/size/comm-id
checks abort the job at the first mismatched frame — but it is a
program error.  The same-host shm arena always wins over the selector
(the engine governs the TCP/multi-host path); forced algorithms are
no-ops on arena communicators.

This module is importable without jax or the native library (pure
stdlib) so the decision table can be inspected anywhere; only
:func:`install` touches the native layer.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple


def _submodule(name: str):
    """Load a sibling submodule, surviving the standalone (file-loaded)
    import mode the tier-1 suite and the CLI fallback use."""
    if __package__:
        try:
            from importlib import import_module

            return import_module(f".{name}", __package__)
        except ImportError:
            pass  # standalone file load: fall through to the file path
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"m4j_tune_{name}_standalone",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _config_mod():
    """utils.config, loaded standalone when the package gate blocks the
    normal import (the knob mirrors are stdlib-only)."""
    try:
        from ..utils import config

        return config
    except ImportError:  # pragma: no cover - standalone tooling load
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "m4j_tune_config_standalone",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         os.pardir, "utils", "config.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

# keep in sync with native/tpucomm.h (TpuCollAlgo / TpuCollOpKind)
ALGO_CODES = {"auto": 0, "ring": 1, "rd": 2, "tree": 3, "shm": 4,
              "qring": 5, "qrd": 6, "hring": 7, "htree": 8,
              "qalltoall": 9, "halltoall": 10, "hqalltoall": 11}
ALGO_NAMES = {v: k for k, v in ALGO_CODES.items()}
OPS = ("allreduce", "allgather", "alltoall")
OP_KIND = {"allreduce": 0, "allgather": 1, "alltoall": 2}

#: hierarchical (topology-aware) schedules: intra-island reduce ->
#: leader-tier allreduce (ring for hring, recursive doubling for
#: htree; the one leg eligible for the quantized wire formats under
#: MPI4JAX_TPU_COLL_QUANT=force) -> intra-island bcast.  Selected by
#: the native engine only on comms with a discovered multi-island
#: topology (mpi4jax_tpu/topo); anywhere else they silently degrade to
#: their flat twins, and MPI4JAX_TPU_HIER (allow | deny | force) gates
#: them process-wide.  Valid for allreduce AND allgather.
HIER_ALGOS = frozenset(("hring", "htree"))
#: the flat degrade twins (hring -> ring, htree -> tree) live in the
#: NATIVE resolver only; ``WorldComm.coll_algo`` reports the resolved
#: pick, so the Python side never re-derives them

#: quantized wire-format algorithms (EQuARX-style int8 codes + f32
#: absmax scales inside every collective frame) — allreduce only,
#: selected by the native engine only for real floating dtypes with
#: SUM (anything else silently degrades to the exact twin), and gated
#: process-wide by MPI4JAX_TPU_COLL_QUANT (allow | deny | force).
QUANT_ALGOS = frozenset(("qring", "qrd"))
#: exact counterpart a quantized algorithm degrades to, and the
#: quantized twin an exact pick promotes to (tree's broadcast shape has
#: no quantized schedule; its latency regime maps to qrd).  A
#: hierarchical pick maps to the flat quantized twin of its leader leg:
#: the compression="int8" route forces ONE native algorithm per call,
#: and there is no whole-schedule quantized hierarchical code — the
#: hierarchy's quantized inter-host leg rides COLL_QUANT=force instead
#: (docs/usage.md § Transport tiers and topology).
EXACT_TWIN = {"qring": "ring", "qrd": "rd",
              "qalltoall": "ring", "hqalltoall": "halltoall"}
QUANT_TWIN = {"ring": "qring", "rd": "qrd", "tree": "qrd",
              "qring": "qring", "qrd": "qrd",
              "hring": "qring", "htree": "qrd"}

#: the alltoall schedule family (PR 8 + PR 10 treatment for the
#: expert-routing exchange): qalltoall quantizes every off-rank chunk
#: with the int8+scales wire codec; halltoall is the hierarchical
#: exchange (intra-island over shm/TCP, only cross-island BLOCKS over
#: the leader tier — a pure permutation, bit-identical to the flat
#: exchange); hqalltoall quantizes the leader leg only.  Gated by the
#: same MPI4JAX_TPU_COLL_QUANT / MPI4JAX_TPU_HIER knobs as the
#: allreduce twins; HIER_ALGOS/QUANT_ALGOS keep their historic
#: allreduce/allgather meaning (test-pinned), so the alltoall family
#: gets its own sets.
A2A_ALGOS = frozenset(("qalltoall", "halltoall", "hqalltoall"))
A2A_QUANT = frozenset(("qalltoall", "hqalltoall"))
A2A_HIER = frozenset(("halltoall", "hqalltoall"))
#: flat twin a hierarchical pick degrades to under MPI4JAX_TPU_HIER=deny
HIER_FLAT_TWIN = {"hring": "ring", "htree": "tree",
                  "halltoall": "ring", "hqalltoall": "qalltoall"}

#: --from-trace promotion thresholds: an exact allreduce winner at or
#: above this payload whose recorded wire share (dur - wait - dispatch)
#: is at least this fraction is wire-bound — compressing its frames is
#: the lever that helps, so the derived cache rows name the quantized
#: twin (see cache_from_trace)
QUANT_PROMOTE_MIN_BYTES = 64 * 1024
QUANT_PROMOTE_WIRE_FRAC = 0.6

#: algorithm labels whose recorded events carry tuning signal (every
#: selectable TCP algorithm; "auto" never labels an event and "shm"
#: measures the arena, not the engine) — THE one copy consumers share
TRACE_ALGOS = frozenset(ALGO_CODES) - {"auto", "shm"}


def _usable_trace_event(ev):
    """(op, nbytes, dur_s) for a native TCP-path collective event with
    an algorithm label, or None — the shared filter under
    measurements_from_events and wire_fractions_from_events."""
    op = str(ev.get("name", "")).lower()
    if (op not in OPS or ev.get("src") != "native"
            or ev.get("algo") not in TRACE_ALGOS):
        return None
    if ev.get("tier"):
        # a hierarchical collective's per-LEG event (intra reduce /
        # leader allreduce): it times one leg, not the algorithm named
        # in its label — only the whole-op record carries tuning signal
        return None
    nbytes = int(ev.get("bytes", 0))
    dur_s = float(ev.get("dur_us", 0.0)) / 1e6
    if nbytes <= 0 or dur_s <= 0:
        return None
    return op, nbytes, dur_s

#: persistent-cache wire format: v2 adds the JOINT layer — per-size-band
#: algorithm *combinations* (``combos``: algo x quant x topology, see
#: ``_joint.py``), the knob-environment stamp (``knobs``), and an
#: optional cost-model pointer (``model``).  v1 files (algo-only) still
#: load here; the ``table`` key keeps its v1 meaning (the per-call-
#: forcible algorithm per band), but a pre-v2 RELEASE's loader rejects
#: a v2 file by its version gate — its install() then warns "ignoring
#: unusable tune cache" and runs on defaults, never on a misread table.
CACHE_VERSION = 2
_READABLE_CACHE_VERSIONS = (1, 2)

# bucket table entries: (min_bytes ascending, algo name).  The defaults
# mirror the pre-engine built-in heuristics in native/tpucomm.cc.
Entry = Tuple[int, str]
Table = Dict[str, List[Entry]]

_DEFAULT_TABLE: Table = {
    "allreduce": [(0, "tree"), (64 * 1024, "ring")],
    "allgather": [(0, "ring")],
    "alltoall": [(0, "ring")],
}

#: defaults on a comm with a discovered MULTI-ISLAND topology
#: (install() flips to these): bandwidth-bound payloads take the
#: hierarchical ring — only the leader leg crosses the slow inter-host
#: tier — while small payloads keep the flat tree's log2(n) hops.  The
#: allgather default stays flat ring (hring/htree are selectable rows;
#: the sweep decides per deployment).  Cache/API/env still override.
_HIER_DEFAULT_TABLE: Table = {
    "allreduce": [(0, "tree"), (64 * 1024, "hring")],
    "allgather": [(0, "ring")],
    "alltoall": [(0, "ring")],
}

_overrides: Dict[str, Dict[int, str]] = {op: {} for op in OPS}
_cache_table: Optional[Table] = None
_cache_origin: Optional[str] = None  # path the cache table came from
_cache_combos: Optional[Table] = None  # v2 joint combos (label entries)
_topo_multi: bool = False            # install() saw a multi-island topology
_cache_loaded_for = None             # (world_size, topo_fp) of _cache_table
_noticed: set = set()                # shadow notices already printed


def _check_op(op: str) -> str:
    if op not in OPS:
        raise ValueError(f"unknown collective op {op!r} (expected one of {OPS})")
    return op


def _check_algo(algo: str, op: Optional[str] = None) -> str:
    name = str(algo).strip().lower()
    if name in ("recursive_doubling", "recursive-doubling"):
        name = "rd"
    if name not in ALGO_CODES or name == "shm":
        raise ValueError(
            f"unknown collective algorithm {algo!r} "
            "(expected auto, ring, rd, tree, qring, qrd, hring, htree, "
            "qalltoall, halltoall, or hqalltoall)"
        )
    if op == "allgather" and name in QUANT_ALGOS:
        raise ValueError(
            f"{name} is an allreduce-only algorithm: quantized wire "
            "formats are lossy and allgather is pure data movement"
        )
    if op == "alltoall" and name not in ("auto", "ring") \
            and name not in A2A_ALGOS:
        raise ValueError(
            f"{name} is not an alltoall schedule (expected auto, ring, "
            "qalltoall, halltoall, or hqalltoall)"
        )
    if op in ("allreduce", "allgather") and name in A2A_ALGOS:
        raise ValueError(
            f"{name} is an alltoall-only algorithm (the allreduce twins "
            "are qring/qrd and hring/htree)"
        )
    return name


def cache_path(world_size: int,
               topo_fingerprint: Optional[str] = None) -> str:
    """Path of the persistent autotune cache for a world size.

    ``MPI4JAX_TPU_TUNE_CACHE`` overrides the full path (tests, shared
    clusters); otherwise ``$XDG_CACHE_HOME``-aware
    ``~/.cache/mpi4jax_tpu/tune_<size>[_<topohash>].json``.  The
    topology fingerprint (``Topology.fingerprint()``: a hash of world
    size, island sizes, and per-island tiers) keys the cache on the
    SHAPE the sweep was measured on — a table tuned on one host layout
    must not silently govern another (2x4 and 8x1 have different
    winners).  ``install`` still falls back to the legacy un-keyed
    ``tune_<size>.json`` when no topology-keyed file exists.  The file
    records the world size it was measured at; loading it for a
    different size is rejected (install() then warns and runs on
    defaults).
    """
    forced = os.environ.get("MPI4JAX_TPU_TUNE_CACHE")
    if forced:
        return forced
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    suffix = f"_{topo_fingerprint}" if topo_fingerprint else ""
    return os.path.join(base, "mpi4jax_tpu",
                        f"tune_{world_size}{suffix}.json")


def _validate_table(raw) -> Table:
    if not isinstance(raw, dict):
        raise ValueError("tune table must be a dict of op -> entries")
    table: Table = {}
    for op, entries in raw.items():
        _check_op(op)
        out: List[Entry] = []
        for e in entries:
            if not isinstance(e, (list, tuple)) or len(e) != 2:
                raise ValueError(f"malformed tune entry for {op}: {e!r}")
            min_bytes = int(e[0])
            if min_bytes < 0:
                raise ValueError(f"negative min_bytes in tune entry: {e!r}")
            out.append((min_bytes, _check_algo(e[1], op)))
        table[op] = sorted(out)
    return table


def load_cache(world_size: int, path: Optional[str] = None,
               topo_fingerprint: Optional[str] = None) -> Table:
    """Parse + validate a persistent cache file; raises ``ValueError`` on
    malformed content (a missing file raises ``FileNotFoundError``).
    On success the table becomes the process's cache layer.

    ``topo_fingerprint`` keys the default path AND cross-checks a
    topology-stamped file: a cache measured on one topology shape must
    not govern another.  Legacy files without a topology stamp load for
    any shape (the documented fallback)."""
    global _cache_table, _cache_origin, _cache_combos
    p = path or cache_path(world_size, topo_fingerprint)
    with open(p) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "table" not in data:
        raise ValueError(f"tune cache {p} has no 'table' key")
    if int(data.get("version", -1)) not in _READABLE_CACHE_VERSIONS:
        raise ValueError(
            f"tune cache {p} has version {data.get('version')!r}, "
            f"expected one of {_READABLE_CACHE_VERSIONS}"
        )
    if int(data.get("world_size", -1)) != int(world_size):
        # a table measured at one world size must not govern another
        # (install() downgrades this to a warning and runs on defaults)
        raise ValueError(
            f"tune cache {p} was measured at world size "
            f"{data.get('world_size')!r}, this job has {world_size}"
        )
    stamped = data.get("topology")
    if (stamped and topo_fingerprint and
            str(stamped) != str(topo_fingerprint)):
        raise ValueError(
            f"tune cache {p} was measured on topology {stamped!r}, "
            f"this job discovered {topo_fingerprint!r}"
        )
    table = _validate_table(data["table"])
    combos = None
    if data.get("combos"):
        combos = _validate_combos(data["combos"])
    _cache_table = table
    _cache_combos = combos
    _cache_origin = p
    return table


def _validate_combos(raw) -> Table:
    """Validate a v2 cache's joint-combination entries: same bucket
    shape as the algorithm table, but the labels are the joint space's
    combos (``hring+q`` legal, validated by ``_joint.check_combo``)."""
    joint = _submodule("_joint")
    if not isinstance(raw, dict):
        raise ValueError("tune cache combos must be a dict of op -> entries")
    combos: Table = {}
    for op, entries in raw.items():
        _check_op(op)
        out: List[Entry] = []
        for e in entries:
            if not isinstance(e, (list, tuple)) or len(e) != 2:
                raise ValueError(f"malformed combo entry for {op}: {e!r}")
            min_bytes = int(e[0])
            if min_bytes < 0:
                raise ValueError(f"negative min_bytes in combo entry: {e!r}")
            out.append((min_bytes, joint.check_combo(e[1], op)))
        combos[op] = sorted(out)
    return combos


def save_cache(world_size: int, table: Table, measurements=(),
               path: Optional[str] = None, transport: str = "tcp",
               topo_fingerprint: Optional[str] = None,
               combos: Optional[Table] = None,
               model_path: Optional[str] = None) -> str:
    """Atomically write the cache file; returns its path.

    Every payload is stamped with the active knob environment
    (``knobs``) so the winners are reproducible without reading the
    shell history; measurement rows of gate-dependent combinations
    (``hring+q``/...) additionally carry their own ``gates`` — they
    were measured in a sub-job whose gates differ from the driver's
    stamp.  ``combos`` (the joint tuner's per-band algorithm
    *combinations*) and ``model_path`` (the cost-model file the search
    was seeded by) make the payload a v2 joint cache; without them the
    file is still written as v2 but carries only the v1 semantics."""
    p = path or cache_path(world_size, topo_fingerprint)
    table = _validate_table(table)
    if combos is not None:
        combos = _validate_combos(combos)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    payload = {
        "version": CACHE_VERSION,
        "world_size": int(world_size),
        "transport": transport,
        "table": {op: [list(e) for e in entries]
                  for op, entries in table.items()},
        "measurements": list(measurements),
        "knobs": _config_mod().knob_env(),
    }
    if combos is not None:
        payload["combos"] = {op: [list(e) for e in entries]
                             for op, entries in combos.items()}
    if model_path:
        payload["model"] = str(model_path)
    if topo_fingerprint:
        payload["topology"] = str(topo_fingerprint)
    tmp = f"{p}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(tmp, p)
    return p


def _env_table() -> Table:
    """Parse ``MPI4JAX_TPU_COLL_ALGO``: a bare algorithm name forces every
    op; ``op=algo[,op=algo...]`` forces per op.  Raises ``ValueError`` on
    anything else (fail-fast, like the boolean knob parser)."""
    raw = os.environ.get("MPI4JAX_TPU_COLL_ALGO", "").strip()
    if not raw:
        return {}
    table: Table = {}
    if "=" not in raw:
        algo = _check_algo(raw)
        # a bare quantized name governs allreduce only (it has no
        # allgather schedule); other ops keep their normal selection
        if algo in QUANT_ALGOS:
            return {"allreduce": [(0, algo)]}
        # the alltoall family only has alltoall schedules
        if algo in A2A_ALGOS:
            return {"alltoall": [(0, algo)]}
        # rd/tree/hring/htree have no alltoall schedule; only
        # auto/ring are valid for every op
        if algo not in ("auto", "ring"):
            return {op: [(0, algo)]
                    for op in ("allreduce", "allgather")}
        return {op: [(0, algo)] for op in OPS}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        op, _, algo = part.partition("=")
        op = _check_op(op.strip())
        table[op] = [(0, _check_algo(algo, op))]
    return table


def set_algorithm(op: str, algo: str, min_bytes: int = 0) -> None:
    """Force ``algo`` for ``op`` payloads >= ``min_bytes`` (the API twin
    of ``MPI4JAX_TPU_COLL_ALGO``, which still wins when set).  Takes
    effect immediately on live communicators — the native layer re-reads
    the table per call."""
    op = _check_op(op)
    _overrides[op][int(min_bytes)] = _check_algo(algo, op)
    _reinstall()


def clear_overrides() -> None:
    """Drop every :func:`set_algorithm` override (cache/env/defaults
    remain in force)."""
    for op in OPS:
        _overrides[op].clear()
    _reinstall()


def decision_table() -> Table:
    """The merged (defaults <- cache <- API overrides <- env) table.
    The default layer is topology-aware: once ``install`` has seen a
    multi-island topology, bandwidth-bound allreduces default to the
    hierarchical ring (``_HIER_DEFAULT_TABLE``)."""
    base = _HIER_DEFAULT_TABLE if _topo_multi else _DEFAULT_TABLE
    table: Table = {op: list(base[op]) for op in OPS}
    if _cache_table:
        for op, entries in _cache_table.items():
            table[op] = list(entries)
    for op in OPS:
        if _overrides[op]:
            merged = dict(table[op])
            # an override at min_bytes B governs [B, inf): drop inherited
            # entries above it so e.g. set_algorithm("allreduce", "rd")
            # at 0 really forces rd everywhere
            lo = min(_overrides[op])
            merged = {mb: a for mb, a in merged.items() if mb < lo}
            merged.update(_overrides[op])
            table[op] = sorted(merged.items())
    for op, entries in _env_table().items():
        table[op] = list(entries)
    return table


def get_algorithm(op: str, nbytes: int) -> str:
    """The algorithm name selected for ``op`` at ``nbytes`` (TCP path;
    the shm arena, when active, overrides this — see
    ``WorldComm.coll_algo`` for the arena-aware probe)."""
    op = _check_op(op)
    entries = decision_table()[op]
    algo = "auto"
    for min_bytes, name in entries:
        if int(nbytes) >= min_bytes:
            algo = name
    if algo == "auto":
        # mirror the native built-in heuristic
        if op == "allreduce":
            algo = "ring" if int(nbytes) >= 64 * 1024 else "tree"
        else:
            algo = "ring"
    return algo


def quantized_algorithm(nbytes: int) -> str:
    """The quantized wire-format algorithm that should carry an
    allreduce of ``nbytes`` (the ``compression="int8"`` route): the
    quantized twin of whatever the engine would pick exactly —
    bandwidth-bound sizes compress as qring, latency-bound ones as
    qrd — so a tuned deployment keeps its shape under compression."""
    return QUANT_TWIN[get_algorithm("allreduce", nbytes)]


def default_algorithm(op: str, nbytes: int) -> str:
    """The static default (pre-engine built-in heuristic) pick, ignoring
    cache/API/env — what a pre-engine native library actually runs (it
    has no table to install into)."""
    op = _check_op(op)
    algo = _DEFAULT_TABLE[op][0][1]
    for min_bytes, name in _DEFAULT_TABLE[op]:
        if int(nbytes) >= min_bytes:
            algo = name
    return algo


def sources() -> List[str]:
    """Which layers contribute to the current decision table."""
    out = ["defaults:topology" if _topo_multi else "defaults"]
    if _cache_table is not None:
        out.append(f"cache:{_cache_origin}")
    if any(_overrides[op] for op in OPS):
        out.append("api")
    if os.environ.get("MPI4JAX_TPU_COLL_ALGO", "").strip():
        out.append("env:MPI4JAX_TPU_COLL_ALGO")
    return out


def describe() -> dict:
    """Diag-friendly summary: table, sources, representative picks."""
    table = decision_table()
    out = {
        "sources": sources(),
        "table": {op: [list(e) for e in entries]
                  for op, entries in table.items()},
        "picks": {
            op: {"1KB": get_algorithm(op, 1024),
                 "16MB": get_algorithm(op, 16 << 20)}
            for op in OPS
        },
    }
    if _cache_combos:
        out["combos"] = {op: [list(e) for e in entries]
                         for op, entries in _cache_combos.items()}
    return out


def cache_combos() -> Optional[Table]:
    """The loaded joint cache's per-band algorithm combinations, or
    None (no cache, or a v1 algo-only cache)."""
    return _cache_combos


def _notice_shadowed() -> None:
    """Satellite of the joint tuner: when a process-wide env knob
    overrides (or degrades) an installed cache pick, say so LOUDLY once
    per distinct conflict instead of letting the precedence chain
    shadow the cache silently — naming both picks, so the operator
    knows which measurement they are discarding.

    Covered shadows: ``MPI4JAX_TPU_COLL_ALGO`` replacing a cached
    algorithm outright; ``MPI4JAX_TPU_COLL_QUANT=deny`` degrading a
    cached quantized pick to its exact twin; a joint-cache ``+q``
    combo whose quantized leader leg needs ``COLL_QUANT=force``; an
    ``+ici`` combo whose intra leg is switched off by
    ``MPI4JAX_TPU_ICI_LEG=off``; and ``MPI4JAX_TPU_HIER=deny``
    flattening a cached hierarchical pick.
    """
    if _cache_table is None:
        return
    msgs: List[str] = []
    env_raw = os.environ.get("MPI4JAX_TPU_COLL_ALGO", "").strip()
    if env_raw:
        env_t = _env_table()
        for op, entries in sorted(_cache_table.items()):
            if op not in env_t:
                continue
            forced = env_t[op][-1][1]
            shadowed = sorted({a for _, a in entries if a != forced})
            if shadowed:
                msgs.append(
                    f"MPI4JAX_TPU_COLL_ALGO={env_raw} overrides the "
                    f"installed tune-cache pick(s) {', '.join(shadowed)} "
                    f"for {op} with '{forced}' (cache: {_cache_origin})")
    cfg = _config_mod()
    try:
        qm, hm = cfg.quant_mode(), cfg.hier_mode()
        im = cfg.ici_leg_mode()
    except ValueError:
        # a malformed gate is about to abort the job loudly anyway
        qm = hm = im = "allow"
    joint = _submodule("_joint")
    picks = _cache_combos or _cache_table
    for op, entries in sorted(picks.items()):
        for mb, combo in entries:
            algo = joint.combo_algo(combo)
            gates = joint.combo_gates(combo)
            where = f"{op} >= {mb} B (cache: {_cache_origin})"
            if (algo in QUANT_ALGOS or algo in A2A_QUANT) \
                    and qm == "deny":
                msgs.append(
                    f"MPI4JAX_TPU_COLL_QUANT=deny degrades the installed "
                    f"cache pick '{combo}' to its exact twin "
                    f"'{EXACT_TWIN[algo]}' for {where}")
            elif "MPI4JAX_TPU_COLL_QUANT" in gates and qm != "force":
                msgs.append(
                    f"the installed joint-cache pick '{combo}' needs "
                    f"MPI4JAX_TPU_COLL_QUANT=force for its quantized "
                    f"leader leg; the active gate '{qm}' leaves that leg "
                    f"exact ('{algo}' runs) for {where}")
            if "MPI4JAX_TPU_ICI_LEG" in gates and im == "off":
                msgs.append(
                    f"the installed joint-cache pick '{combo}' rides the "
                    f"Pallas ICI intra-island leg; MPI4JAX_TPU_ICI_LEG=off "
                    f"keeps the native intra paths ('{algo}' runs) for "
                    f"{where}")
            if (algo in HIER_ALGOS or algo in A2A_HIER) and hm == "deny":
                flat = HIER_FLAT_TWIN[algo]
                msgs.append(
                    f"MPI4JAX_TPU_HIER=deny degrades the installed cache "
                    f"pick '{combo}' to its flat twin '{flat}' for {where}")
    for msg in msgs:
        if msg not in _noticed:
            _noticed.add(msg)
            print(f"[tune] NOTICE: {msg}", file=sys.stderr, flush=True)


def entries_from_measurements(best: Dict[int, str]) -> List[Entry]:
    """Collapse per-size winners ``{bytes: algo}`` into bucket entries:
    the winner at size s governs [s, next measured size); the smallest
    size's winner extends down to 0."""
    if not best:
        return []
    sizes = sorted(best)
    entries: List[Entry] = [(0, best[sizes[0]])]
    for s in sizes[1:]:
        if best[s] != entries[-1][1]:
            entries.append((s, best[s]))
    return entries


def measurements_from_events(events) -> Dict[str, Dict[int, Dict[str, float]]]:
    """Median observed seconds per (op, payload bytes, algorithm) from a
    recorded run's canonical events (``mpi4jax_tpu.obs`` dumps).

    Only native TCP-path collective events count: the same-host shm
    arena and the ops-layer spans measure a different thing than the
    algorithm engine selects for, and events without an algorithm or
    byte count carry no tuning signal.
    """
    samples: Dict[str, Dict[int, Dict[str, List[float]]]] = {}
    for ev in events:
        usable = _usable_trace_event(ev)
        if usable is None:
            continue
        op, nbytes, dur_s = usable
        samples.setdefault(op, {}).setdefault(nbytes, {}) \
            .setdefault(ev["algo"], []).append(dur_s)
    out: Dict[str, Dict[int, Dict[str, float]]] = {}
    for op, by_size in samples.items():
        for nbytes, by_algo in by_size.items():
            for algo, durs in by_algo.items():
                durs.sort()
                # interpolated median, identical to numpy / the p50 the
                # profile report prints for the same recording — the
                # tuner's "best median" and the operator's table must
                # name the same winner
                n = len(durs)
                med = (durs[(n - 1) // 2] + durs[n // 2]) / 2.0
                out.setdefault(op, {}).setdefault(nbytes, {})[algo] = med
    return out


def wire_fractions_from_events(events) -> Dict[str, Dict[int, Dict[str, float]]]:
    """Mean recorded wire share — ``(dur - wait - dispatch) / dur`` —
    per (op, payload bytes, algorithm), same event filter as
    :func:`measurements_from_events`.  A high wire fraction means the
    op spends its time MOVING bytes (not blocked on peers, not queued):
    exactly the regime where compressing the frames pays, so
    :func:`cache_from_trace` uses this to decide when an exact winner
    should be promoted to its quantized twin."""
    fracs: Dict[str, Dict[int, Dict[str, List[float]]]] = {}
    for ev in events:
        usable = _usable_trace_event(ev)
        if usable is None:
            continue
        op, nbytes, dur_s = usable
        wire_s = max(dur_s - float(ev.get("wait_us", 0.0)) / 1e6
                     - float(ev.get("dispatch_us", 0.0)) / 1e6, 0.0)
        fracs.setdefault(op, {}).setdefault(nbytes, {}) \
            .setdefault(ev["algo"], []).append(wire_s / dur_s)
    return {
        op: {nbytes: {algo: sum(fr) / len(fr)
                      for algo, fr in by_algo.items()}
             for nbytes, by_algo in by_size.items()}
        for op, by_size in fracs.items()
    }


def dispatch_fractions_from_events(events) -> Dict[str, Dict[int, Dict[str, float]]]:
    """Mean recorded dispatch share — ``dispatch / dur`` — per (op,
    payload bytes, algorithm), same event filter as
    :func:`measurements_from_events`.  A high dispatch fraction means
    the op spends its time queued behind the engine, which is what the
    cost model's concurrency-group-cap suggestion keys on."""
    fracs: Dict[str, Dict[int, Dict[str, List[float]]]] = {}
    for ev in events:
        usable = _usable_trace_event(ev)
        if usable is None:
            continue
        op, nbytes, dur_s = usable
        disp_s = float(ev.get("dispatch_us", 0.0)) / 1e6
        fracs.setdefault(op, {}).setdefault(nbytes, {}) \
            .setdefault(ev["algo"], []).append(min(disp_s / dur_s, 1.0))
    return {
        op: {nbytes: {algo: sum(fr) / len(fr)
                      for algo, fr in by_algo.items()}
             for nbytes, by_algo in by_size.items()}
        for op, by_size in fracs.items()
    }


def fit_model_from_events(events, *, world_size: int = 0,
                          topo_fingerprint: Optional[str] = None,
                          source: str = "trace"):
    """Fit a :class:`tune._model.CostModel` from a recorded run's
    canonical events: the per-(op, size, algorithm) medians become the
    model's samples, with the recorded wire and dispatch fractions
    riding along (the same event filter as ``--from-trace``).  The
    model is stamped with the active knob environment — a recording is
    only comparable to runs under the same gates."""
    _model = _submodule("_model")
    samples = measurements_from_events(events)
    wire = wire_fractions_from_events(events)
    disp = dispatch_fractions_from_events(events)
    model = _model.CostModel(
        world_size=world_size, topology=topo_fingerprint,
        knobs=_config_mod().knob_env(), source=source)
    for op, by_size in samples.items():
        for nbytes, by_algo in by_size.items():
            for algo, med in by_algo.items():
                model.add_sample(
                    op, algo, nbytes, med,
                    wire_frac=wire.get(op, {}).get(nbytes, {}).get(algo),
                    dispatch_frac=disp.get(op, {}).get(nbytes, {})
                    .get(algo))
    return model


def collect_trace_events(paths: Sequence[str], obs_dump=None):
    """Load recorded events from part files / merged traces with the
    elastic world-generation gate applied: a file spanning generations
    is refused outright, files from superseded generations are skipped
    with a loud notice (pre- and post-shrink timings must never pool
    into one median).  Returns ``(events, seen_world_size)`` — the one
    loader BOTH ``--from-trace`` consumers (cache derivation and the
    ``--joint`` model seed) go through."""
    if obs_dump is None:
        try:
            from ..obs import _dump as obs_dump
        except ImportError:  # pragma: no cover - standalone tooling load
            import importlib.util

            _spec = importlib.util.spec_from_file_location(
                "m4j_obs_dump_standalone",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir, "obs", "_dump.py"))
            obs_dump = importlib.util.module_from_spec(_spec)
            _spec.loader.exec_module(obs_dump)
    per_file = []
    for path in paths:
        evs, size, gens = obs_dump.load_events_meta(path)
        if len(gens) > 1:
            # a merged trace spanning an elastic recovery: its spans
            # cannot be attributed to one world membership, so pre- and
            # post-shrink timings would pool into one median
            raise ValueError(
                f"{path} merges recordings from world generations "
                f"{sorted(gens)} (an elastic recovery happened "
                "mid-job); pass the per-rank part files instead — "
                "only the latest generation's timings are usable")
        per_file.append((path, evs, size, max(gens)))
    latest_gen = max((g for _, _, _, g in per_file), default=0)
    stale = [(path, g) for path, _, _, g in per_file if g != latest_gen]
    if stale:
        # an elastic shrink mid-recording: pre-shrink worlds have a
        # different membership (and size), so their timings must not
        # pool with the survivors' — reject them loudly, keep the rest
        names = ", ".join(f"{os.path.basename(p)} (generation {g})"
                          for p, g in stale)
        print(f"tune: --from-trace: ignoring {len(stale)} recording(s) "
              f"from superseded world generation(s): {names} — only "
              f"generation {latest_gen}, the latest, carries timings "
              "for the surviving world", file=sys.stderr, flush=True)
        per_file = [(p, e, s, g) for p, e, s, g in per_file
                    if g == latest_gen]
    events: List[dict] = []
    seen_size = 0
    for _, evs, size, _ in per_file:
        events.extend(evs)
        seen_size = max(seen_size, size)
    return events, seen_size


def cache_from_trace(paths: Sequence[str], world_size: Optional[int] = None,
                     cache_path_override: Optional[str] = None,
                     quantize: bool = True) -> str:
    """Derive the persistent algorithm cache from a recorded real run
    (the ``python -m mpi4jax_tpu.tune --from-trace`` backend): the
    winner per (op, size) is the algorithm with the best median observed
    time, collapsed into bucket entries exactly like the synthetic
    sweep.  ``paths`` are recording part files and/or merged Chrome
    traces; ``world_size`` defaults to the recordings' own metadata.
    Raises ``ValueError`` when the recording carries no usable TCP-path
    collective timings (e.g. the run rode the shm arena throughout).

    With ``quantize`` (the default), an exact allreduce winner at
    >= QUANT_PROMOTE_MIN_BYTES whose recorded wire share is at least
    QUANT_PROMOTE_WIRE_FRAC is promoted to its quantized twin
    (qring/qrd): the recording says those calls spend their time moving
    bytes, so shrinking the frames is the available lever.  Promotion
    is recorded per measurement (``promoted_from``); it is skipped
    entirely under ``MPI4JAX_TPU_COLL_QUANT=deny`` (the native engine
    would degrade the rows right back) and ineligible calls (integer
    dtypes, non-SUM) degrade natively at dispatch, so a promoted row is
    always safe.  Pass ``quantize=False`` (CLI: ``--no-quantize``) for
    an exact-only table.
    """
    events, seen_size = collect_trace_events(paths)
    n = int(world_size or seen_size)
    if n < 2:
        raise ValueError(
            "cannot tell the recording's world size — pass world_size "
            "(tune --from-trace --np N)")
    samples = measurements_from_events(events)
    if quantize:
        try:
            from ..utils.config import quant_mode
        except ImportError:  # pragma: no cover - standalone tooling load
            quant_mode = lambda: os.environ.get(  # noqa: E731
                "MPI4JAX_TPU_COLL_QUANT", "allow").strip() or "allow"
        quantize = quant_mode() != "deny"
    wire_fracs = wire_fractions_from_events(events) if quantize else {}
    best: Dict[str, Dict[int, str]] = {}
    measurements = []
    for op, by_size in samples.items():
        for nbytes, by_algo in sorted(by_size.items()):
            winner = min(by_algo, key=by_algo.get)
            promoted_from = None
            if (quantize and op == "allreduce"
                    and winner in ("ring", "rd", "tree")
                    and nbytes >= QUANT_PROMOTE_MIN_BYTES):
                frac = wire_fracs.get(op, {}).get(nbytes, {}) \
                    .get(winner, 0.0)
                if frac >= QUANT_PROMOTE_WIRE_FRAC:
                    promoted_from, winner = winner, QUANT_TWIN[winner]
            best.setdefault(op, {})[nbytes] = winner
            for algo, dt in sorted(by_algo.items()):
                measurements.append({
                    "op": op, "bytes": nbytes, "algo": algo,
                    "seconds": round(dt, 9), "ranks": n,
                    "source": "trace",
                })
            if promoted_from is not None:
                measurements.append({
                    "op": op, "bytes": nbytes, "algo": winner,
                    "promoted_from": promoted_from,
                    "wire_frac": round(wire_fracs[op][nbytes]
                                       [promoted_from], 4),
                    "ranks": n, "source": "trace:quant-promotion",
                })
    if not best:
        raise ValueError(
            "the recording holds no TCP-path collective timings with "
            "algorithm labels (shm-arena runs measure the same-host "
            "fast path, which the engine does not select for)")
    table = {op: entries_from_measurements(b) for op, b in best.items()}
    return save_cache(n, table, measurements, path=cache_path_override,
                      transport="tcp:from-trace")


def cache_from_joint(world_size: int, best: Dict[str, Dict[int, str]],
                     measurements=(), *, path: Optional[str] = None,
                     topo_fingerprint: Optional[str] = None,
                     model_file: Optional[str] = None) -> str:
    """Write the v2 joint cache from per-(op, size) winning combos (the
    ``--joint`` search's output): the ``combos`` layer records the full
    winning combination per size band, and the derived ``table`` keeps
    the v1 meaning — the per-call-forcible algorithm under each combo —
    so the native install path and v1 readers are untouched."""
    joint = _submodule("_joint")
    combos = {op: entries_from_measurements(b) for op, b in best.items()}

    def _algo_entries(op, entries):
        out: List[Entry] = []
        for mb, combo in entries:
            algo = _check_algo(joint.combo_algo(combo), op)
            if not out or out[-1][1] != algo:
                out.append((mb, algo))
        return out

    table = {op: _algo_entries(op, entries)
             for op, entries in combos.items()}
    return save_cache(world_size, table, measurements, path=path,
                      transport="tcp:joint",
                      topo_fingerprint=topo_fingerprint, combos=combos,
                      model_path=model_file)


def install(world_size: Optional[int] = None, topology=None) -> bool:
    """Load the persistent cache (if present) and push the merged
    decision table into the native layer.  Called by
    ``runtime.bridge.comm_init`` at communicator creation; safe to call
    again after overrides.  Returns True when the native table was
    pushed (False: native lib unavailable or too old).

    ``topology`` (a ``topo.Topology``, when discovery ran) does two
    things: a multi-island map flips the default layer to the
    hierarchical table, and its fingerprint keys the cache lookup —
    ``tune_<size>_<topohash>.json`` first, the legacy un-keyed
    ``tune_<size>.json`` as a fallback."""
    global _topo_multi, _cache_table, _cache_origin, _cache_combos, \
        _cache_loaded_for
    topo_fp = None
    if topology is not None:
        _topo_multi = bool(getattr(topology, "multi", False))
        if _topo_multi:
            topo_fp = topology.fingerprint()
    if world_size is not None:
        want = (int(world_size), topo_fp)
        if _cache_loaded_for is not None and _cache_loaded_for != want:
            # an elastic rebuild changed the world shape: the in-memory
            # cache belongs to the old one — drop it and reload below
            _cache_table = None
            _cache_origin = None
            _cache_combos = None
            _cache_loaded_for = None
        if _cache_table is None:
            candidates = []
            if topo_fp:
                candidates.append((cache_path(world_size, topo_fp),
                                   topo_fp))
            legacy = cache_path(world_size)
            if not candidates or candidates[0][0] != legacy:
                candidates.append((legacy, None))
            for path, fp in candidates:
                try:
                    load_cache(world_size, path=path, topo_fingerprint=fp)
                    _cache_loaded_for = want
                    break
                except FileNotFoundError:
                    continue
                except ValueError as e:
                    import warnings

                    warnings.warn(f"ignoring unusable tune cache: {e}")
                    break
            else:
                _cache_loaded_for = want  # nothing on disk for this shape
    # a conflicting env knob silently shadowing a measured cache pick is
    # the one precedence interaction operators cannot see — say so once
    _notice_shadowed()
    return _push_native()


def _reinstall() -> None:
    """Re-push after an override change, but only into an already-loaded
    native lib (never force a build from a pure-Python code path)."""
    try:
        from ..runtime import bridge
    except ImportError:  # standalone import (no runtime stack around)
        return
    if bridge._lib is not None:
        _push_native()


def _push_native() -> bool:
    from ..runtime import bridge

    table = decision_table()
    coded = {
        OP_KIND[op]: [(mb, ALGO_CODES[name]) for mb, name in entries]
        for op, entries in table.items()
    }
    return bridge.set_coll_table(coded)
