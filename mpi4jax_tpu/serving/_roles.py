"""Prefill/decode role assignment over the discovered topology.

Disaggregation (the DistServe/Splitwise serving pattern) puts the two
phases on different ranks: prefill is compute-bound bursts, decode is
latency-bound steady state, and colocating them makes every prompt
burst a decode-latency spike.  Here the split maps onto the PR 10
island map: prefill ranks live in the *frontend's* island (the prompt
feed is frontend -> prefill, cheap intra-island), decode ranks live in
the *other* islands, and the finished-KV transfer rides the leader
tier between them (eligible for the ICI leg / int8 wire like any other
inter-island traffic).

The assignment is a pure function of (world size, island map, mode) —
every rank derives the SAME plan from the same broadcast-free inputs,
and an elastic shrink just re-derives it from the recovered topology
(falling back to colocated when the survivors cannot hold both roles).

Mode comes from ``MPI4JAX_TPU_SERVE_ROLES`` (``config.serve_roles()``):
``auto`` disaggregates when the topology is multi-island with >= 3
ranks, ``colocated``/``disagg`` force either way (``disagg`` on a
world too small to hold a frontend plus both roles raises — silently
colocating under a forced split would invalidate what a test thinks
it measured).
"""

from __future__ import annotations

from typing import List, Optional

from ..utils import config


class RolePlan:
    """The derived placement: who prefills, who decodes, who fronts.

    ``mode`` is the *resolved* mode ("colocated" | "disagg"), never
    "auto".  In colocated mode every rank carries both role lists and
    each request's prefill rank IS its decode rank."""

    def __init__(self, size: int, mode: str, prefill_ranks: List[int],
                 decode_ranks: List[int]):
        self.size = int(size)
        self.frontend = 0
        self.mode = mode
        self.prefill_ranks = list(prefill_ranks)
        self.decode_ranks = list(decode_ranks)

    def placement(self, seq: int):
        """(prefill_rank, decode_rank) for the ``seq``-th admitted
        request — a deterministic round-robin, so the frontend's plan
        and any replay of it agree."""
        d = self.decode_ranks[seq % len(self.decode_ranks)]
        if self.mode == "colocated":
            return d, d
        p = self.prefill_ranks[seq % len(self.prefill_ranks)]
        return p, d

    def role_of(self, rank: int) -> str:
        parts = []
        if rank == self.frontend:
            parts.append("frontend")
        if rank in self.prefill_ranks:
            parts.append("prefill")
        if rank in self.decode_ranks:
            parts.append("decode")
        return "+".join(parts) or "idle"

    def describe(self) -> str:
        return (f"serve roles mode={self.mode} frontend={self.frontend} "
                f"prefill={self.prefill_ranks} decode={self.decode_ranks}")


def _disagg_split(size: int, topology) -> Optional[RolePlan]:
    """The disaggregated split, or None when this world cannot hold
    one (needs the frontend plus >= 1 prefill and >= 1 decode rank)."""
    workers = list(range(1, size))
    if len(workers) < 2:
        return None
    if topology is not None and getattr(topology, "multi", False):
        home = topology.island_of[0]
        prefill = [r for r in workers if topology.island_of[r] == home]
        decode = [r for r in workers if topology.island_of[r] != home]
        if prefill and decode:
            return RolePlan(size, "disagg", prefill, decode)
        # frontend's island holds everyone (or no one): positional split
    half = max(1, len(workers) // 2)
    return RolePlan(size, "disagg", workers[:half], workers[half:])


def assign_roles(size: int, topology=None, *,
                 mode: Optional[str] = None) -> RolePlan:
    """Derive the role plan for a ``size``-rank world with an optional
    discovered :class:`~mpi4jax_tpu.topo.Topology` (see module
    docstring for the mode semantics)."""
    mode = mode or config.serve_roles()
    if mode == "colocated" or (mode == "auto" and (
            size < 3 or topology is None
            or not getattr(topology, "multi", False))):
        ranks = list(range(size))
        return RolePlan(size, "colocated", ranks, ranks)
    plan = _disagg_split(size, topology)
    if plan is None:
        if mode == "disagg":
            raise ValueError(
                f"MPI4JAX_TPU_SERVE_ROLES=disagg needs >= 3 ranks "
                f"(frontend + prefill + decode), got {size}")
        ranks = list(range(size))
        return RolePlan(size, "colocated", ranks, ranks)
    return plan
