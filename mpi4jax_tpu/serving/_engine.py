"""The disaggregated serving engine: frontend, workers, and the wire.

One iteration of the plane (every rank, lock-step):

1. the frontend broadcasts a 4-word header ``[op, n_pre, n_act,
   chunk_w]`` (root 0);
2. for ``op=STEP`` it broadcasts the *prefill table* — per row
   ``[req_id, prefill_rank, decode_rank, start, count, total]`` plus
   the right-padded chunk-token matrix — and the *active table*
   (``[req_id, decode_rank, last_tok]``);
3. every rank walks both tables in global order doing only the rows it
   owns: prefill ranks chew their chunk (``adapter.prefill`` against
   the partial cache), and on the *final* chunk compute the first
   generated token and ship the finished KV to the row's decode rank
   (the KV wire); decode ranks run ``adapter.decode_step`` for their
   active rows;
4. an allgather returns the fixed-width result vector (one slot per
   table row, ``-1`` = no token this iteration) and the frontend
   COMMITS: tokens are appended only after the full exchange
   succeeded.

Everything before the commit is replayable, which is the whole elastic
story: on a :class:`RankFailure` every rank recovers, drops its ENTIRE
KV cache (cache state is a pure function of each request's token
prefix — see the adapter contract), the frontend re-derives roles from
the recovered topology and re-prefills every in-flight request from
its committed tokens.  Nothing is lost, and with an exactly
prefix-consistent adapter the transcripts are byte-identical to an
uninterrupted run.

The KV wire is exact (raw entry dtype) by default;
``MPI4JAX_TPU_COLL_QUANT=force`` upgrades eligible float32 KV to the
PR 8 int8+scales codec (same gate as the quantized collectives — and
like them, a numerics change, which is why it is opt-in: the
disagg-vs-colocated bit-consistency guarantee holds on the exact
wire).  Transfers and compute are recorded as obs spans labeled
``phase=prefill|decode|kv_xfer`` (KV spans also carry ``tier="kv"`` so
``obs.stats()`` surfaces the moved bytes in ``tier_bytes``).

Failure model (unchanged from the toy plane, now release-safe): the
request queue lives in rank 0's process.  A worker promoted to rank 0
by a recovery cannot reconstruct it — it broadcasts STOP to release
the other survivors *first*, then raises.
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional

import numpy as np

from ..elastic._errors import is_rank_failure
from ..elastic._world import recover
from ..obs import _recorder as _obs
from ..utils import config
from ._adapter import ModelAdapter
from ._kv import KVCache
from ._roles import RolePlan, assign_roles
from ._scheduler import Admission, SLOController, Verdict

#: header opcodes (int64 header [op, n_pre, n_act, chunk_w])
_OP_STOP = 0
_OP_STEP = 1

_KV_TAG_BASE = 1 << 20


def _bcast(comm, arr):
    if comm.size() == 1:
        return arr
    from ..runtime import bridge

    return bridge.bcast(comm.handle, arr, 0)


def _allgather(comm, arr):
    if comm.size() == 1:
        return arr.reshape(1, -1)
    from ..runtime import bridge

    return bridge.allgather(comm.handle, arr,
                            comm.size()).reshape(comm.size(), -1)


def _span(name, t0_unix, dur_s, *, phase, peer=-1, nbytes=0, tier=None):
    if _obs.enabled():
        _obs.record_span(name, t0_unix, dur_s, peer=peer, nbytes=nbytes,
                         tier=tier, phase=phase)


def _kv_wire_quant(dtype) -> bool:
    """Whether finished-KV transfers ride the int8 codec: only under
    the explicit ``force`` gate (a numerics change must be asked for),
    and only for the codec's dtype."""
    if np.dtype(dtype) != np.float32:
        return False
    if config.quant_mode() != "force":
        return False
    from ..runtime import bridge

    return bridge.quant_available()


def _kv_send(comm, entries: np.ndarray, dest: int, req_id: int) -> None:
    from ..runtime import bridge

    t0 = time.perf_counter()
    t_unix = time.time()
    flat = np.ascontiguousarray(entries).reshape(-1)
    if _kv_wire_quant(flat.dtype):
        bridge.send(comm.handle, bridge.quant_pack(flat), dest,
                    _KV_TAG_BASE + req_id)
    else:
        bridge.send(comm.handle, flat, dest, _KV_TAG_BASE + req_id)
    _span("serve.kv_xfer", t_unix, time.perf_counter() - t0,
          phase="kv_xfer", peer=dest, nbytes=entries.nbytes, tier="kv")


def _kv_recv(comm, ntok: int, entry_shape, dtype, source: int,
             req_id: int) -> np.ndarray:
    from ..runtime import bridge

    t0 = time.perf_counter()
    t_unix = time.time()
    count = int(ntok * int(np.prod(entry_shape, dtype=np.int64)))
    if _kv_wire_quant(dtype):
        packed = bridge.recv(comm.handle,
                             (bridge.quant_packed_bytes(count),), np.uint8,
                             source, _KV_TAG_BASE + req_id)
        flat = bridge.quant_unpack(packed, count, np.float32)
    else:
        flat = bridge.recv(comm.handle, (count,), dtype, source,
                           _KV_TAG_BASE + req_id)
    entries = flat.reshape((ntok,) + tuple(entry_shape))
    _span("serve.kv_xfer", t_unix, time.perf_counter() - t0,
          phase="kv_xfer", peer=source, nbytes=entries.nbytes, tier="kv")
    return entries


class _RankState:
    """Per-rank compute state: the paged caches.  ``prefill`` holds
    partial per-request KV while a prompt is being chewed; ``decode``
    holds the cache of every request this rank owns for decoding."""

    def __init__(self, adapter: ModelAdapter):
        self.adapter = adapter
        self.prefill = KVCache(adapter.kv_entry_shape, adapter.kv_dtype)
        self.decode = KVCache(adapter.kv_entry_shape, adapter.kv_dtype)

    def drop_all(self):
        self.prefill.drop_all()
        self.decode.drop_all()


def _run_tables(comm, state: _RankState, pre_meta, pre_toks, act_meta):
    """The compute half of one iteration (every rank): walk both
    tables in global order, do the rows this rank owns, return the
    fixed-width result vector (-1 = not mine / no token)."""
    me = comm.rank()
    adapter = state.adapter
    n_pre = len(pre_meta)
    result = np.full(n_pre + len(act_meta), -1, np.int64)
    for i, row in enumerate(pre_meta):
        req_id, p_rank, d_rank, start, count, total = (int(v) for v in row)
        finished = start + count == total
        if me == p_rank:
            t0 = time.perf_counter()
            t_unix = time.time()
            chunk = np.asarray(pre_toks[i, :count], np.int32)
            past = (state.prefill.view(req_id)
                    if state.prefill.length(req_id) else None)
            if (past is None and start != 0) or (
                    past is not None and len(past) != start):
                raise RuntimeError(
                    f"prefill cache for request {req_id} holds "
                    f"{state.prefill.length(req_id)} tokens but the plan "
                    f"says chunk starts at {start}")
            entries, logits = adapter.prefill(chunk, past)
            state.prefill.append(req_id, entries)
            _span("serve.prefill", t_unix, time.perf_counter() - t0,
                  phase="prefill", nbytes=entries.nbytes)
            if finished:
                result[i] = int(np.argmax(logits))
                kv = state.prefill.view(req_id)
                state.prefill.free(req_id)
                if d_rank == me:
                    state.decode.load(req_id, kv)
                else:
                    _kv_send(comm, kv, d_rank, req_id)
        elif me == d_rank and finished:
            entries = _kv_recv(comm, total, adapter.kv_entry_shape,
                               adapter.kv_dtype, p_rank, req_id)
            state.decode.load(req_id, entries)
    for j, row in enumerate(act_meta):
        req_id, d_rank, last_tok = (int(v) for v in row)
        if me != d_rank:
            continue
        t0 = time.perf_counter()
        t_unix = time.time()
        past = state.decode.view(req_id)
        entry, logits = adapter.decode_step(past, last_tok)
        state.decode.append(req_id, entry)
        _span("serve.decode", t_unix, time.perf_counter() - t0,
              phase="decode", nbytes=entry.nbytes)
        result[n_pre + j] = int(np.argmax(logits))
    return result


def _derive_roles(comm, mode: Optional[str]) -> RolePlan:
    topo = comm.topology() if hasattr(comm, "topology") else None
    return assign_roles(comm.size(), topo, mode=mode)


def _derive_roles_after_recovery(comm, mode: Optional[str]) -> RolePlan:
    """Roles for a recovered world.  A forced ``disagg`` that no longer
    fits the shrunk world (< 3 survivors) degrades to colocated — loudly
    — instead of killing the survivors mid-recovery; the verdict is a
    pure function of (size, mode), so every rank reaches the same plan
    with no extra protocol.  (At startup the raise stands: the user
    asked for a split the world cannot host.)"""
    try:
        return _derive_roles(comm, mode)
    except ValueError as e:
        sys.stderr.write(f"[serving] NOTICE: {e}; the recovered world "
                         "keeps serving with colocated roles\n")
        return _derive_roles(comm, "colocated")


def _release_peers(comm) -> None:
    """Broadcast STOP so survivors waiting in the worker loop return
    instead of hanging on a frontend that is about to raise."""
    try:
        _bcast(comm, np.array([_OP_STOP, 0, 0, 0], np.int64))
    except BaseException as e:  # noqa: BLE001 - release is best-effort
        if not is_rank_failure(e):
            raise


def serve_worker(comm, adapter: ModelAdapter, *,
                 roles_mode: Optional[str] = None) -> RolePlan:
    """The non-frontend loop: follow the frontend's plan until STOP.
    Survives rank death: recovers in place, drops all cached KV (the
    frontend re-prefills), re-derives roles from the recovered
    topology.  Returns the final role plan (for diag/reporting).  If a
    recovery promotes this worker to rank 0 it first releases the
    other survivors (STOP broadcast), then raises — the frontend's
    request state died with the old rank 0."""
    state = _RankState(adapter)
    roles = _derive_roles(comm, roles_mode)
    while True:
        try:
            hdr = _bcast(comm, np.zeros(4, np.int64))
            if int(hdr[0]) == _OP_STOP:
                return roles
            n_pre, n_act, chunk_w = (int(v) for v in hdr[1:])
            pre_meta = np.zeros((n_pre, 6), np.int64)
            pre_toks = np.zeros((n_pre, chunk_w), np.int32)
            act_meta = np.zeros((n_act, 3), np.int64)
            if n_pre:
                pre_meta = _bcast(comm, pre_meta).reshape(n_pre, 6)
                pre_toks = _bcast(comm, pre_toks).reshape(n_pre, chunk_w)
            if n_act:
                act_meta = _bcast(comm, act_meta).reshape(n_act, 3)
            result = _run_tables(comm, state, pre_meta, pre_toks, act_meta)
            _allgather(comm, result)
        except BaseException as e:
            if not is_rank_failure(e):
                raise
            recover(comm)
            state.drop_all()
            roles = _derive_roles_after_recovery(comm, roles_mode)
            if comm.rank() == 0:
                _release_peers(comm)
                raise RuntimeError(
                    "this worker became the frontend after recovery — "
                    "frontend state (the request queue) lived on the "
                    "dead rank 0 and cannot be reconstructed")


class Request:
    """One generation request and its lifecycle timestamps."""

    QUEUED, PREFILL, ACTIVE, DONE = "queued", "prefill", "active", "done"

    def __init__(self, req_id, prompt, max_new: int):
        self.id = int(req_id)
        self.prompt = [int(t) for t in prompt]
        self.tokens = list(self.prompt)
        self.max_new = int(max_new)
        self.state = self.QUEUED
        #: the token list prefill consumes — the prompt initially; after
        #: an elastic recovery, everything committed so far
        self.feed = list(self.prompt)
        self.fed = 0  # tokens of ``feed`` consumed by prefill chunks
        self.placement = None  # (prefill_rank, decode_rank)
        self.retries = 0
        self.submitted_at = time.perf_counter()
        self.first_token_at = None
        self.completed_at = None

    @property
    def done(self):
        return self.state == self.DONE

    @property
    def generated(self):
        return self.tokens[len(self.prompt):]

    @property
    def latency_s(self):
        if self.completed_at is None:
            return None
        return self.completed_at - self.submitted_at

    @property
    def ttft_s(self):
        """Time to first token (prefill-phase latency)."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


class Server:
    """The frontend (rank 0; every other rank runs :func:`serve_worker`
    with the SAME adapter).  See the module docstring for the
    iteration protocol and failure model."""

    def __init__(self, comm, adapter: ModelAdapter, *,
                 max_batch: Optional[int] = None,
                 chunk_tokens: int = 512,
                 queue_cap: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 roles_mode: Optional[str] = None,
                 eos: Optional[int] = None):
        if comm.rank() != 0:
            raise ValueError("Server runs on rank 0; other ranks run "
                             "serve_worker()")
        self.comm = comm
        self.adapter = adapter
        self.eos = eos
        self._roles_mode = roles_mode
        self.roles = _derive_roles(comm, roles_mode)
        self.scheduler = SLOController(max_batch=max_batch,
                                       chunk_tokens=chunk_tokens,
                                       slo_ms=slo_ms)
        self.admission = Admission(queue_cap)
        self._state = _RankState(adapter)
        self.requests: List[Request] = []
        self.completed: List[Request] = []
        self.verdicts: List[Verdict] = []
        self.recoveries = 0
        self._next_id = 0
        self._seq = 0  # admission order, drives round-robin placement

    # ---------------- admission ----------------

    def submit(self, prompt, max_new: int, req_id=None) -> Verdict:
        """Admission-controlled submit: ALWAYS returns a
        :class:`Verdict`; the request object rides on
        ``verdict.request`` when admitted.  Shed verdicts are loud
        (stderr) — overload is an event, not a silent drop."""
        if req_id is None:
            req_id = self._next_id
            self._next_id += 1
        prompt = [int(t) for t in prompt]
        total = len(prompt) + int(max_new)
        if total > self.adapter.max_seq:
            verdict = Verdict(req_id, False,
                              f"prompt+max_new {total} exceeds model "
                              f"context {self.adapter.max_seq}")
            self.admission.shed += 1
        else:
            verdict = self.admission.offer(req_id, len(prompt))
        self.verdicts.append(verdict)
        if not verdict.admitted:
            print(f"[serving] {verdict!r}", file=sys.stderr, flush=True)
            verdict.request = None
            return verdict
        req = Request(req_id, prompt, max_new)
        req.placement = self.roles.placement(self._seq)
        self._next_id = max(self._next_id, req.id + 1)
        self._seq += 1
        self.requests.append(req)
        verdict.request = req
        return verdict

    @property
    def active(self):
        return [r for r in self.requests if not r.done]

    # ---------------- the iteration ----------------

    def _build_tables(self):
        pre_rows, act_rows = [], []
        budget = self.scheduler.chunk_tokens * max(
            1, len(self.roles.prefill_ranks))
        for r in self.requests:
            if r.state not in (Request.QUEUED, Request.PREFILL):
                continue
            if budget <= 0:
                break
            chunk = min(len(r.feed) - r.fed, self.scheduler.chunk_tokens)
            pre_rows.append((r, r.fed, chunk))
            budget -= chunk
        for r in self.requests:
            if r.state == Request.ACTIVE:
                act_rows.append(r)
            if len(act_rows) >= self.scheduler.max_batch:
                break
        return pre_rows, act_rows

    def step(self) -> List[Request]:
        """One lock-step iteration; returns the requests that COMPLETED
        this iteration.  On rank failure nothing is committed — the
        world recovers, every in-flight request re-prefills on the new
        world, and the next call retries."""
        pre_rows, act_rows = self._build_tables()
        if not pre_rows and not act_rows:
            return []
        t_step0 = time.perf_counter()
        try:
            chunk_w = max((c for _, _, c in pre_rows), default=1)
            pre_meta = np.zeros((len(pre_rows), 6), np.int64)
            pre_toks = np.zeros((len(pre_rows), chunk_w), np.int32)
            for i, (r, start, count) in enumerate(pre_rows):
                pre_meta[i] = (r.id, r.placement[0], r.placement[1],
                               start, count, len(r.feed))
                pre_toks[i, :count] = r.feed[start:start + count]
            act_meta = np.zeros((len(act_rows), 3), np.int64)
            for j, r in enumerate(act_rows):
                act_meta[j] = (r.id, r.placement[1], r.tokens[-1])
            _bcast(self.comm, np.array(
                [_OP_STEP, len(pre_rows), len(act_rows), chunk_w],
                np.int64))
            if len(pre_rows):
                _bcast(self.comm, pre_meta)
                _bcast(self.comm, pre_toks)
            if len(act_rows):
                _bcast(self.comm, act_meta)
            result = _run_tables(self.comm, self._state, pre_meta,
                                 pre_toks, act_meta)
            gathered = _allgather(self.comm, result)
        except BaseException as e:
            if not is_rank_failure(e):
                raise
            self._recover_and_reset(len(pre_rows) + len(act_rows))
            return []
        # ---- the commit point: everything above is replayable ----
        done_now = []
        now = time.perf_counter()
        for i, (r, start, count) in enumerate(pre_rows):
            r.fed = start + count
            if r.fed < len(r.feed):
                r.state = Request.PREFILL
                continue
            tok = int(gathered[self._owner_row(r.placement[0]), i])
            assert tok >= 0, (r.id, "finished prefill returned no token")
            if r.first_token_at is None:
                r.first_token_at = now
            self._commit_token(r, tok, done_now)
            if not r.done:
                r.state = Request.ACTIVE
        n_pre = len(pre_rows)
        for j, r in enumerate(act_rows):
            tok = int(gathered[self._owner_row(r.placement[1]), n_pre + j])
            assert tok >= 0, (r.id, "active decode returned no token")
            self._commit_token(r, tok, done_now)
        if act_rows:
            verdict = self.scheduler.observe(
                (time.perf_counter() - t_step0) * 1e3)
            if verdict:
                print(f"[serving] SLO: {verdict}", file=sys.stderr,
                      flush=True)
            # max-batch floor hit: translate the scheduler's re-tune
            # request into a live-controller poke (an immediate drift
            # evaluation).  The flag is consumed every step — it never
            # sticks, and with the controller disarmed the request is
            # still counted for the operator (live.status()).
            if self.scheduler.retune_requested:
                from .. import live

                live.consume_retune(self.scheduler)
        self.requests = [r for r in self.requests if not r.done]
        return done_now

    def _owner_row(self, rank: int) -> int:
        # allgather rows are rank-ordered; size-1 fast path has one row
        return rank if self.comm.size() > 1 else 0

    def _commit_token(self, r: Request, tok: int, done_now: list) -> None:
        r.tokens.append(tok)
        if (len(r.generated) >= r.max_new
                or (self.eos is not None and tok == self.eos)):
            r.state = Request.DONE
            r.completed_at = time.perf_counter()
            done_now.append(r)
            self.completed.append(r)
            self.admission.retire()

    def _recover_and_reset(self, in_flight: int) -> None:
        self.recoveries += 1
        recover(self.comm)
        self._state.drop_all()
        self.roles = _derive_roles_after_recovery(self.comm,
                                                  self._roles_mode)
        for seq, r in enumerate(self.requests):
            # every request re-prefills from its committed tokens: the
            # KV it had lived on ranks that may be gone, and cache
            # state is a pure function of the prefix anyway
            if r.state != Request.QUEUED or r.fed:
                r.retries += 1
            r.state = Request.QUEUED
            r.feed = list(r.tokens)
            r.fed = 0
            r.placement = self.roles.placement(seq)
        self._seq = len(self.requests)
        print(f"[serving] recovered (world size now {self.comm.size()}, "
              f"{self.roles.describe()}); re-prefilling "
              f"{len(self.requests)} in-flight request(s) "
              f"({in_flight} were mid-iteration)",
              file=sys.stderr, flush=True)

    def run_until_drained(self, *, max_iters: int = 100000):
        it = 0
        while self.active:
            it += 1
            if it > max_iters:
                raise RuntimeError(
                    f"serving did not drain within {max_iters} iterations")
            self.step()
        return self.completed

    def stop(self) -> None:
        """Release the workers (broadcast the stop opcode)."""
        _release_peers(self.comm)
