"""Serving v2: the disaggregated prefill/decode inference plane.

The toy plane (``mpi4jax_tpu.elastic.serving``) proved the elastic
story — requests survive rank death — but re-decodes every full
sequence every iteration, runs both phases undifferentiated on every
rank, and admits unboundedly.  This package is the real subsystem
(docs/serving.md):

- **KV-cache-backed decode** — the ``decode_fn`` contract is replaced
  by a model adapter (:class:`ModelAdapter`: ``prefill`` /
  ``decode_step``) over paged, rank-local KV blocks
  (:class:`KVCache`), turning per-token work from O(sequence) model
  passes into one cached step;
- **prefill/decode disaggregation** (:func:`assign_roles`) mapped onto
  the discovered topology: prefill ranks chew prompt chunks in the
  frontend's island and ship finished KV to decode ranks across the
  leader tier (exact wire by default, int8 codec under
  ``MPI4JAX_TPU_COLL_QUANT=force``), with roles re-derived from the
  recovered topology after an elastic shrink;
- **admission control + SLO feedback** (:class:`Admission`,
  :class:`SLOController`): a bounded queue with loud per-request shed
  verdicts, token-budgeted chunked prefill, and a rolling-window p99
  loop over the ``phase=decode`` spans that adapts max-batch/chunk
  size against ``MPI4JAX_TPU_SERVE_SLO_MS``.

Numpy-only at import time (the world tier's portability contract);
the jitted GPT adapter imports jax lazily.
"""

from ._adapter import (  # noqa: F401
    JaxGPTAdapter,
    ModelAdapter,
    NumpyGPTAdapter,
    ToyAdapter,
    make_jax_gpt_adapter,
    make_numpy_gpt_adapter,
)
from ._engine import Request, Server, serve_worker  # noqa: F401
from ._kv import KVCache  # noqa: F401
from ._roles import RolePlan, assign_roles  # noqa: F401
from ._scheduler import Admission, SLOController, Verdict  # noqa: F401

__all__ = [
    "Admission",
    "JaxGPTAdapter",
    "KVCache",
    "ModelAdapter",
    "NumpyGPTAdapter",
    "Request",
    "RolePlan",
    "SLOController",
    "Server",
    "ToyAdapter",
    "Verdict",
    "assign_roles",
    "make_jax_gpt_adapter",
    "make_numpy_gpt_adapter",
    "serve_worker",
]
