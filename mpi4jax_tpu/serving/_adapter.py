"""The model adapter contract: what the serving engine asks of a model.

The toy plane (``elastic/serving.py``) takes a ``decode_fn`` over the
*whole* padded token matrix and recomputes every position every
iteration — O(S) model work per generated token.  The v2 contract
splits the two phases the KV cache separates:

- ``prefill(toks, past=None) -> (entries, logits)`` — consume a chunk
  of tokens given an existing cache, returning one cache *entry* per
  consumed token plus the logits predicting the next token;
- ``decode_step(past, last_tok) -> (entry, logits)`` — consume exactly
  one token against the cache: semantically ``prefill([last_tok],
  past)``, but O(cache-lookup) instead of O(sequence) in model work.

The cache *entry* layout is adapter-declared (``kv_entry_shape`` /
``kv_dtype``); the engine never looks inside one — it pages them
(``_kv.KVCache``), ships them between ranks (the KV wire), and hands
the contiguous ``(ntok, *entry_shape)`` view back to the adapter.

Determinism contract: an adapter must be a pure function of the token
prefix — same tokens, same entries, same logits, on every rank.  The
engine greedy-decodes (``argmax``), so disaggregated and colocated
placements produce identical transcripts.  :class:`ToyAdapter` is
additionally *exactly* prefix-consistent (integer state): re-prefilling
a prefix reproduces the incremental cache bit-for-bit, which is what
the elastic retry path relies on in tests.  The GPT adapters are
prefix-consistent up to float associativity (chunk boundaries change
gemm shapes), so fault-retry tests pin the toy adapter.

Numpy-only at import time; :func:`make_jax_gpt_adapter` imports jax
lazily so CPU containers without a usable jax still serve end-to-end
through :class:`NumpyGPTAdapter`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class ModelAdapter:
    """Base contract (see module docstring).  Subclasses set ``vocab``,
    ``kv_entry_shape``, ``kv_dtype``, ``max_seq`` and implement
    :meth:`prefill`; :meth:`decode_step` has a correct (if slow)
    default."""

    vocab: int = 0
    kv_entry_shape: Tuple[int, ...] = ()
    kv_dtype = np.float32
    max_seq: int = 1 << 30

    def prefill(self, toks: np.ndarray,
                past: Optional[np.ndarray] = None):
        raise NotImplementedError

    def decode_step(self, past: np.ndarray, last_tok: int):
        entries, logits = self.prefill(
            np.asarray([int(last_tok)], np.int32), past)
        return entries[0], logits


class ToyAdapter(ModelAdapter):
    """The toy plane's hash model, restated with a KV cache: the next
    token is ``(sum(tokens)*31 + len*7 + last) % 997``, and the cache
    entry for position i is the running sum over ``tokens[:i+1]`` —
    integer state, so incremental decode, chunked prefill, and a full
    re-prefill all agree bit-for-bit.  ``decode_step`` is O(1) where
    the toy ``decode_fn`` re-sums the whole row."""

    vocab = 997
    kv_entry_shape = (1,)
    kv_dtype = np.int64

    def prefill(self, toks, past=None):
        toks = np.asarray(toks, np.int64).reshape(-1)
        prev_sum = int(past[-1, 0]) if past is not None and len(past) else 0
        prev_len = len(past) if past is not None else 0
        cums = prev_sum + np.cumsum(toks)
        entries = cums[:, None].astype(np.int64)
        n = prev_len + len(toks)
        nxt = int((int(cums[-1]) * 31 + n * 7 + int(toks[-1])) % self.vocab)
        logits = np.zeros(self.vocab, np.float32)
        logits[nxt] = 1.0
        return entries, logits

    def decode_step(self, past, last_tok):
        s = (int(past[-1, 0]) if len(past) else 0) + int(last_tok)
        n = len(past) + 1
        nxt = int((s * 31 + n * 7 + int(last_tok)) % self.vocab)
        logits = np.zeros(self.vocab, np.float32)
        logits[nxt] = 1.0
        return np.array([s], np.int64), logits


def _ln(x, g):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + 1e-5) * g


class NumpyGPTAdapter(ModelAdapter):
    """KV-cached pure-numpy twin of the tiny pre-LN GPT in
    ``benchmarks/quant_accuracy.py`` (``gpt2_init`` params verbatim).
    One cache entry per token: ``(n_layer, 2, n_head, d_head)`` float32
    — the per-layer K and V rows — which is the quant-eligible KV wire
    format (f32, int8-packable by the PR 8 codec)."""

    def __init__(self, params, *, n_layer: int, n_head: int):
        self.params = params
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.vocab, self.d_model = params["wte"].shape
        self.max_seq = params["wpe"].shape[0]
        self.d_head = self.d_model // self.n_head
        self.kv_entry_shape = (self.n_layer, 2, self.n_head, self.d_head)
        self.kv_dtype = np.float32

    def _heads(self, t):
        # (T, d_model) -> (n_head, T, d_head)
        T = t.shape[0]
        return t.reshape(T, self.n_head, self.d_head).transpose(1, 0, 2)

    def prefill(self, toks, past=None):
        p = self.params
        toks = np.asarray(toks, np.int64).reshape(-1)
        P = len(past) if past is not None else 0
        T = len(toks)
        if P + T > self.max_seq:
            raise ValueError(
                f"sequence {P + T} exceeds the model's max_seq "
                f"{self.max_seq}")
        x = p["wte"][toks] + p["wpe"][P:P + T]
        entries = np.zeros((T,) + self.kv_entry_shape, np.float32)
        for i in range(self.n_layer):
            h = p[f"h{i}"]
            a_in = _ln(x, h["ln1"])
            qkv = a_in @ h["attn_qkv"]
            q, k, v = np.split(qkv, 3, axis=-1)
            entries[:, i, 0] = k.reshape(T, self.n_head, self.d_head)
            entries[:, i, 1] = v.reshape(T, self.n_head, self.d_head)
            if P:
                k_all = np.concatenate(
                    [past[:, i, 0].reshape(P, -1), k])  # (P+T, d_model)
                v_all = np.concatenate(
                    [past[:, i, 1].reshape(P, -1), v])
            else:
                k_all, v_all = k, v
            qh = self._heads(q)                      # (nh, T, dh)
            kh = self._heads(k_all)                  # (nh, P+T, dh)
            vh = self._heads(v_all)
            att = (qh @ kh.transpose(0, 2, 1)) / np.sqrt(self.d_head)
            # causal: query at absolute position P+r sees keys <= P+r
            key_pos = np.arange(P + T)
            q_pos = P + np.arange(T)
            mask = key_pos[None, :] <= q_pos[:, None]  # (T, P+T)
            att = np.where(mask[None], att, -1e9)
            att = np.exp(att - att.max(-1, keepdims=True))
            att = att / att.sum(-1, keepdims=True)
            out = (att @ vh).transpose(1, 0, 2).reshape(T, -1)
            x = x + out @ h["attn_out"]
            m_in = _ln(x, h["ln2"])
            m = np.maximum(m_in @ h["mlp_in"], 0.0)
            x = x + m @ h["mlp_out"]
        x_last = _ln(x[-1], p["ln_f"])
        logits = x_last @ p["wte"].T
        return entries, logits.astype(np.float32)


def make_numpy_gpt_adapter(*, seed: int = 0, vocab: int = 64,
                           d_model: int = 32, n_layer: int = 2,
                           n_head: int = 4,
                           max_seq: int = 576) -> NumpyGPTAdapter:
    """A :class:`NumpyGPTAdapter` over a deterministically-seeded small
    model — the same ``gpt2_init`` parameter recipe the training and
    quant-accuracy benchmarks use, so tooling everywhere speaks one
    model family."""
    rng = np.random.RandomState(seed)

    def norm(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    params = {
        "wte": norm(vocab, d_model),
        "wpe": norm(max_seq, d_model),
        "ln_f": np.ones(d_model, np.float32),
    }
    for i in range(n_layer):
        params[f"h{i}"] = {
            "ln1": np.ones(d_model, np.float32),
            "attn_qkv": norm(d_model, 3 * d_model),
            "attn_out": norm(d_model, d_model),
            "ln2": np.ones(d_model, np.float32),
            "mlp_in": norm(d_model, 4 * d_model),
            "mlp_out": norm(4 * d_model, d_model),
        }
    return NumpyGPTAdapter(params, n_layer=n_layer, n_head=n_head)


class JaxGPTAdapter(NumpyGPTAdapter):
    """The jitted tier of the same model: prefill stays numpy (one
    pass per prompt), the per-token ``decode_step`` runs a jitted
    fixed-shape kernel over the padded cache — the shape never changes
    across tokens, so jax traces exactly once.  Import of jax is
    deferred to construction; on containers without jax the numpy
    adapter serves the identical model."""

    def __init__(self, params, *, n_layer: int, n_head: int):
        super().__init__(params, n_layer=n_layer, n_head=n_head)
        import jax
        import jax.numpy as jnp

        S = self.max_seq

        def step(wte, wpe, layer_stack, ln_f, past, length, tok):
            # past: (S, n_layer, 2, n_head, d_head) zero-padded;
            # length: live entries; tok: the one token to consume
            def ln(x, g):
                mu = jnp.mean(x, -1, keepdims=True)
                var = jnp.var(x, -1, keepdims=True)
                return (x - mu) / jnp.sqrt(var + 1e-5) * g

            x = wte[tok] + wpe[length]
            new_entry = jnp.zeros(self.kv_entry_shape, jnp.float32)
            for i in range(self.n_layer):
                h = {k: layer_stack[k][i] for k in layer_stack}
                a_in = ln(x, h["ln1"])
                qkv = a_in @ h["attn_qkv"]
                q, k, v = jnp.split(qkv, 3)
                kh = k.reshape(self.n_head, self.d_head)
                vh = v.reshape(self.n_head, self.d_head)
                new_entry = new_entry.at[i, 0].set(kh)
                new_entry = new_entry.at[i, 1].set(vh)
                k_all = past[:, i, 0].at[length].set(kh)  # (S, nh, dh)
                v_all = past[:, i, 1].at[length].set(vh)
                qh = q.reshape(self.n_head, 1, self.d_head)
                att = (qh @ k_all.transpose(1, 2, 0)) / np.sqrt(self.d_head)
                live = jnp.arange(S) <= length
                att = jnp.where(live[None, None, :], att, -1e9)
                att = jnp.exp(att - jnp.max(att, -1, keepdims=True))
                att = att / jnp.sum(att, -1, keepdims=True)
                out = (att @ v_all.transpose(1, 0, 2)).reshape(-1)
                x = x + out @ h["attn_out"]
                m_in = ln(x, h["ln2"])
                m = jnp.maximum(m_in @ h["mlp_in"], 0.0)
                x = x + m @ h["mlp_out"]
            logits = ln(x, ln_f) @ wte.T
            return new_entry, logits

        self._layer_stack = {
            k: np.stack([params[f"h{i}"][k] for i in range(self.n_layer)])
            for k in params["h0"]}
        self._step = jax.jit(step)
        self._np = np

    def decode_step(self, past, last_tok):
        S = self.max_seq
        padded = self._np.zeros((S,) + self.kv_entry_shape,
                                self._np.float32)
        if len(past):
            padded[:len(past)] = past
        entry, logits = self._step(
            self.params["wte"], self.params["wpe"], self._layer_stack,
            self.params["ln_f"], padded, len(past), int(last_tok))
        return (self._np.asarray(entry, self._np.float32),
                self._np.asarray(logits, self._np.float32))


def make_jax_gpt_adapter(**kw) -> "JaxGPTAdapter":
    """Jitted variant of :func:`make_numpy_gpt_adapter` (same seeded
    params, same transcripts up to float associativity).  Raises
    ImportError where jax is unavailable — callers fall back to the
    numpy adapter."""
    ref = make_numpy_gpt_adapter(**kw)
    return JaxGPTAdapter(ref.params, n_layer=ref.n_layer,
                         n_head=ref.n_head)
