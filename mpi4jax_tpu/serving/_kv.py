"""Paged per-request KV blocks, rank-local.

The decode engine's cache: per request, a list of fixed-size *pages*,
each holding ``page`` per-token cache entries of the model adapter's
declared entry shape/dtype.  Pages make append O(1) without repeated
whole-cache reallocation, keep memory proportional to live tokens
(rounded up to one page), and free in O(pages) when a request retires.

The cache is deliberately a dumb store: it knows nothing about
transformers.  An *entry* is whatever the adapter says one token's
cache state is — ``(n_layer, 2, n_head, d_head)`` float32 for the GPT
adapters, ``(1,)`` int64 running state for the toy adapter — so the
same pager backs both, and the KV wire format (``_engine.py``) is just
``view()``'s contiguous ``(ntok, *entry_shape)`` array.

Everything here is numpy-only: the cache lives on whatever rank runs
the adapter, never inside jax tracing.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


class KVCache:
    """Rank-local paged cache: request id -> growing token-entry log."""

    def __init__(self, entry_shape: Tuple[int, ...], dtype,
                 page: int = 64):
        self.entry_shape = tuple(int(d) for d in entry_shape)
        self.dtype = np.dtype(dtype)
        self.page = max(int(page), 1)
        self._pages: Dict[int, List[np.ndarray]] = {}
        self._len: Dict[int, int] = {}
        self.pages_allocated = 0  # lifetime counter (stats/tests)

    def __contains__(self, req_id) -> bool:
        return int(req_id) in self._pages

    def length(self, req_id) -> int:
        """Tokens cached for ``req_id`` (0 when unknown)."""
        return self._len.get(int(req_id), 0)

    def entry_nbytes(self) -> int:
        n = self.dtype.itemsize
        for d in self.entry_shape:
            n *= d
        return n

    def nbytes(self, req_id) -> int:
        """Logical cache bytes held for ``req_id`` (live entries, not
        page padding — the number the KV wire actually moves)."""
        return self.length(req_id) * self.entry_nbytes()

    def append(self, req_id, entries: np.ndarray) -> None:
        """Append one or more per-token entries.  ``entries`` is either
        a single entry (``entry_shape``) or a batch
        (``(n, *entry_shape)``)."""
        req_id = int(req_id)
        entries = np.asarray(entries, self.dtype)
        if entries.shape == self.entry_shape:
            entries = entries[None]
        if entries.shape[1:] != self.entry_shape:
            raise ValueError(
                f"entry shape {entries.shape[1:]} != declared "
                f"{self.entry_shape}")
        pages = self._pages.setdefault(req_id, [])
        n = self._len.get(req_id, 0)
        for entry in entries:
            slot = n % self.page
            if slot == 0:
                pages.append(np.zeros((self.page,) + self.entry_shape,
                                      self.dtype))
                self.pages_allocated += 1
            pages[-1][slot] = entry
            n += 1
        self._len[req_id] = n

    def view(self, req_id) -> np.ndarray:
        """Contiguous ``(ntok, *entry_shape)`` copy of the live entries
        (the adapter-facing and wire-facing form)."""
        req_id = int(req_id)
        n = self._len.get(req_id, 0)
        out = np.zeros((n,) + self.entry_shape, self.dtype)
        for i, pg in enumerate(self._pages.get(req_id, ())):
            lo = i * self.page
            take = min(self.page, n - lo)
            if take <= 0:
                break
            out[lo:lo + take] = pg[:take]
        return out

    def load(self, req_id, entries: np.ndarray) -> None:
        """Replace ``req_id``'s cache with ``entries`` (the receive side
        of a KV transfer)."""
        self.free(req_id)
        if len(entries):
            self.append(req_id, np.asarray(entries, self.dtype))
        else:
            self._pages[int(req_id)] = []
            self._len[int(req_id)] = 0

    def free(self, req_id) -> None:
        self._pages.pop(int(req_id), None)
        self._len.pop(int(req_id), None)

    def drop_all(self) -> None:
        """Forget everything — the elastic-recovery reset: cached state
        is a pure function of each request's token prefix, so dropping
        it is always safe (the engine re-prefills)."""
        self._pages.clear()
        self._len.clear()

    @property
    def live_requests(self) -> int:
        return len(self._pages)

    @property
    def live_pages(self) -> int:
        return sum(len(p) for p in self._pages.values())
