"""Admission control and the SLO feedback loop.

Three pure-Python pieces, each unit-testable without ranks:

- :class:`Admission` — a bounded queue.  ``offer()`` returns a loud
  per-request :class:`Verdict`: ``admitted`` or ``shed`` with the
  reason (queue at ``MPI4JAX_TPU_SERVE_QUEUE_CAP``, prompt longer than
  the model's context).  Shedding at submit time is the overload
  contract: a client learns *immediately* instead of its request aging
  out inside an unbounded queue.

- token-budgeted batch building — each iteration admits prefill work
  up to a token budget (``chunk_tokens``) so one giant prompt cannot
  starve decode latency: prompts are chewed in chunks across
  iterations (chunked prefill), while every active request always
  decodes its one token per iteration.

- :class:`SLOController` — the feedback loop.  A rolling window of
  per-iteration decode-phase durations (the same numbers the obs
  ``phase=decode`` spans record) is compared against
  ``MPI4JAX_TPU_SERVE_SLO_MS`` (p99 over the window): overshooting
  halves the live max-batch (floor 1) and can request an algorithm
  re-tune; comfortably-under (< half the SLO) regrows toward — never
  beyond — the configured starting point.  A quiescent run therefore
  makes ZERO adaptations (test-pinned): the live value starts at the
  knob and nothing pushes it away.
"""

from __future__ import annotations

import collections
from typing import Optional

from ..obs import _stats
from ..utils import config


class Verdict:
    """Per-request admission outcome — always returned, never thrown,
    so callers log/count shed load instead of unwinding."""

    def __init__(self, req_id, admitted: bool, reason: str):
        self.req_id = req_id
        self.admitted = admitted
        self.reason = reason

    def __repr__(self):
        state = "admitted" if self.admitted else "SHED"
        return f"<submit {self.req_id}: {state} ({self.reason})>"


class Admission:
    """Bounded admission: ``pending`` counts requests admitted but not
    yet retired (queued + in flight) against the cap."""

    def __init__(self, cap: Optional[int] = None,
                 max_prompt: Optional[int] = None):
        self.cap = int(cap) if cap is not None else config.serve_queue_cap()
        self.max_prompt = max_prompt
        self.pending = 0
        self.shed = 0
        self.admitted = 0

    def offer(self, req_id, prompt_len: int) -> Verdict:
        if self.max_prompt is not None and prompt_len > self.max_prompt:
            self.shed += 1
            return Verdict(req_id, False,
                           f"prompt {prompt_len} exceeds model context "
                           f"{self.max_prompt}")
        if self.pending >= self.cap:
            self.shed += 1
            return Verdict(req_id, False,
                           f"queue at capacity ({self.cap}); retry later")
        self.pending += 1
        self.admitted += 1
        return Verdict(req_id, True, f"queued ({self.pending}/{self.cap})")

    def retire(self, n: int = 1) -> None:
        self.pending = max(0, self.pending - int(n))


class SLOController:
    """The decode-latency feedback loop (see module docstring).

    ``observe(decode_ms)`` feeds one iteration's decode-phase duration;
    the controller owns the live ``max_batch`` and ``chunk_tokens``
    values the batch builder reads.  ``slo_ms <= 0`` disables the loop
    (the knob default): observe() still counts, but never adapts.
    """

    #: window of iterations the p99 is computed over; also the
    #: cool-down after an adaptation (the window refills before the
    #: next verdict) — tests pin adaptation latency to <= 2*WINDOW
    #: iterations
    WINDOW = 16

    def __init__(self, *, max_batch: Optional[int] = None,
                 chunk_tokens: int = 512, slo_ms: Optional[float] = None):
        self.initial_max_batch = (int(max_batch) if max_batch is not None
                                  else config.serve_max_batch())
        self.max_batch = self.initial_max_batch
        self.chunk_tokens = int(chunk_tokens)
        self.initial_chunk_tokens = self.chunk_tokens
        self.slo_ms = (float(slo_ms) if slo_ms is not None
                       else config.serve_slo_ms())
        self.adaptations = 0
        self.retune_requested = False
        self.iterations = 0
        self._window = collections.deque(maxlen=self.WINDOW)

    def observe(self, decode_ms: float) -> Optional[str]:
        """Feed one iteration; returns a human-readable adaptation
        verdict when one fired, else None."""
        self.iterations += 1
        if self.slo_ms <= 0:
            return None
        self._window.append(float(decode_ms))
        if len(self._window) < self.WINDOW:
            return None
        p99 = _stats.percentile(self._window, 99)
        if p99 > self.slo_ms:
            self._window.clear()
            if self.max_batch > 1:
                self.max_batch = max(1, self.max_batch // 2)
                self.chunk_tokens = max(
                    32, min(self.chunk_tokens,
                            self.initial_chunk_tokens) // 2)
                self.adaptations += 1
                return (f"decode p99 {p99:.2f}ms > SLO {self.slo_ms}ms: "
                        f"max_batch -> {self.max_batch}, chunk_tokens -> "
                        f"{self.chunk_tokens}")
            # already at the floor: batch size cannot help — ask the
            # tuner layer for an algorithm re-tune instead
            if not self.retune_requested:
                self.retune_requested = True
                self.adaptations += 1
                return (f"decode p99 {p99:.2f}ms > SLO {self.slo_ms}ms at "
                        "max_batch=1: requesting algorithm re-tune")
            return None
        if (p99 < self.slo_ms / 2
                and self.max_batch < self.initial_max_batch):
            self._window.clear()
            self.max_batch = min(self.initial_max_batch, self.max_batch * 2)
            self.chunk_tokens = min(self.initial_chunk_tokens,
                                    self.chunk_tokens * 2)
            self.adaptations += 1
            return (f"decode p99 {p99:.2f}ms well under SLO "
                    f"{self.slo_ms}ms: max_batch -> {self.max_batch}")
        return None
