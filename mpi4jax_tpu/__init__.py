"""mpi4jax_tpu — TPU-native MPI-style primitives for JAX.

The capability contract of mpi4jax (see SURVEY.md), rebuilt TPU-first: the
twelve point-to-point and collective operations callable inside ``jax.jit``,
with SPMD/ordered-effect execution ordering, autodiff and batching for the
differentiable collectives, debug tracing, and fail-fast error handling.

Two tiers behind one API (DESIGN.md):
- **mesh tier**: ops compile to XLA collectives over ICI inside
  ``shard_map`` — the TPU fast path (``spmd``, ``make_mesh``, ``MeshComm``);
- **world tier**: one process per rank over the native C++ transport
  (``mpi4jax_tpu.runtime``), for MPMD programs and DCN-scale jobs.

Public API parity with /root/reference/mpi4jax/__init__.py:9-39 (12 ops +
capability probe), with ReduceOps as framework objects instead of mpi4py
handles.
"""

from .utils import jax_compat as _jax_compat

_jax_compat.check_jax_version()

from .ops import (  # noqa: E402
    ALL_OPS,
    BAND,
    BOR,
    BXOR,
    LAND,
    LOR,
    LXOR,
    MAX,
    MIN,
    PROD,
    SUM,
    ReduceOp,
    allgather,
    allreduce,
    alltoall,
    as_reduce_op,
    barrier,
    bcast,
    create_token,
    custom_op,
    gather,
    neighbor_exchange,
    permute,
    recv,
    reduce,
    scan,
    scatter,
    send,
    sendrecv,
)
from .ops._world_impl import explicit_token_ordering  # noqa: E402
from .parallel import (  # noqa: E402
    MeshComm,
    current_comm,
    get_default_comm,
    make_mesh,
    spmd,
)
from . import elastic  # noqa: E402
from .elastic import RankFailure  # noqa: E402
from .runtime.transport import WorldComm  # noqa: E402
from .utils.status import ANY_SOURCE, ANY_TAG, Status  # noqa: E402
from .utils.tracing import set_logging  # noqa: E402

__version__ = "0.1.0"


def has_ici_support() -> bool:
    """True when a TPU/accelerator backend with >1 addressable device (an ICI
    domain a mesh can span) is present.  The spiritual analog of the
    reference's ``has_cuda_support`` (_src/utils.py:158-164)."""
    import jax

    try:
        return len(jax.devices()) > 1 or jax.devices()[0].platform != "cpu"
    except RuntimeError:
        return False


def _flush(timeout=None):
    """Block until all pending communication effects have executed.

    Parity with the reference's ``flush`` / atexit barrier
    (_src/flush.py:4-6): pending async dispatch at interpreter teardown can
    deadlock multi-process jobs.
    """
    import jax

    jax.effects_barrier()


import atexit as _atexit  # noqa: E402

_atexit.register(_flush)

__all__ = [
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "bcast",
    "create_token",
    "gather",
    "permute",
    "neighbor_exchange",
    "recv",
    "reduce",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "ReduceOp",
    "as_reduce_op",
    "custom_op",
    "ALL_OPS",
    "SUM",
    "PROD",
    "MAX",
    "MIN",
    "LAND",
    "LOR",
    "LXOR",
    "BAND",
    "BOR",
    "BXOR",
    "MeshComm",
    "current_comm",
    "get_default_comm",
    "WorldComm",
    "elastic",
    "RankFailure",
    "make_mesh",
    "spmd",
    "set_logging",
    "has_ici_support",
    "explicit_token_ordering",
    "Status",
    "ANY_TAG",
    "ANY_SOURCE",
    "__version__",
]
