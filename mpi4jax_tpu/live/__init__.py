"""Live drift detection and collective re-tuning (the online brain).

The tune package fits its cost model offline and installs a static
decision table at communicator creation; this package keeps that table
honest at runtime.  With ``MPI4JAX_TPU_LIVE=auto`` a lightweight
controller thread follows the native obs ring through the
NON-DESTRUCTIVE cursor (``tpucomm_obs_peek`` — the end-of-run trace
dump still sees every event), keeps a rolling window of the freshest
collective timings, and compares per-(op, size band, algorithm)
medians against the cost model's predictions.  When an observed median
drifts past ``MPI4JAX_TPU_LIVE_DRIFT_PCT``, rank 0 refits the model on
the window (``tune.fit_model_from_events`` semantics: fresh medians
overlay the baseline samples), re-runs ``rank_combos`` per observed
size, and — when the winners actually change — stages a candidate v2
table.

The swap is the hard part: every rank must install the new table at
the same collective boundary or the algorithm-agreement contract
breaks (a cross-rank disagreement aborts at the first mismatched
frame).  The protocol is a deterministic epoch rendezvous riding the
SPMD invariant — all ranks of a communicator execute the same
collective sequence, so a per-comm boundary counter is synchronized by
construction:

1. every collective wrapper in ``runtime.bridge`` calls the boundary
   hook before dispatch; at every ``cooldown/4``-th world boundary all
   ranks run a 16-byte bcast from rank 0 carrying (epoch, payload
   length);
2. a header naming an epoch above the local one is followed by a
   second bcast with the JSON-coded candidate table;
3. every rank stages the table (``tpucomm_stage_coll_table``) and
   commits at that same boundary (``tpucomm_commit_coll_tables`` —
   comm lock held, progress engine quiesced, exactly the
   ``tpucomm_set_topology`` swap discipline).

``off`` (the default) installs no hook and starts no thread —
pre-live behavior bit-for-bit.  The whole package is jax-free like
``tune/``; only ``runtime.bridge`` (injected) touches the native
layer.  Collectives dispatched through the XLA FFI fast path bypass
the Python wrappers, so their calls feed drift detection (the native
ring records them) but only bridge-level collectives advance the
rendezvous boundary — see docs/sharp-bits.md.
"""

from __future__ import annotations

import sys
import threading

from ..utils import config
from . import _controller, _drift, _swap  # noqa: F401 (re-export)

_lock = threading.Lock()
_ctrl = None     # the armed Controller (None = disarmed)
_swap_state = None
_retune_requests = 0


def arm(lib, handle, rank: int, size: int) -> bool:
    """Start the controller + boundary hook for one world comm (the
    bridge calls this from ``_post_init_setup`` under
    ``MPI4JAX_TPU_LIVE=auto``).  Returns False — disarmed, loudly —
    when the loaded .so predates the cursor read or the epoch
    plumbing (recording and dispatch keep working, just untuned)."""
    global _ctrl, _swap_state
    from ..obs import _native as obs_native
    from ..runtime import bridge

    disarm()
    if bridge.coll_epoch() is None or not obs_native.peek_available(lib):
        print("[live] native library predates live re-tuning "
              "(tpucomm_obs_peek/tpucomm_coll_epoch missing) — "
              "controller disarmed", file=sys.stderr, flush=True)
        return False
    window = config.live_window()
    cooldown = config.live_cooldown_ops()
    drift_pct = config.live_drift_pct()
    # the controller follows the native ring; when no recording armed
    # it (MPI4JAX_TPU_TRACE / obs.start() ran _install_obs first), arm
    # the ring itself — sized past the window so the cursor outruns
    # overflow.  Never re-enable over an armed recording: that would
    # clear events the end-of-run dump owns.
    from .. import obs

    if not obs.enabled():
        obs_native.enable(lib, max(4 * window, 4096))
    period = max(1, cooldown // 4)
    with _lock:
        _swap_state = _swap.SwapProtocol(bridge, handle, rank, size,
                                         period)
        _ctrl = _controller.Controller(
            lib, handle, rank, size, _swap_state, window=window,
            drift_pct=drift_pct, cooldown_ops=cooldown)
        _swap_state.on_commit = _ctrl.note_commit
    bridge.set_live_boundary(_on_boundary)
    _ctrl.start()
    return True


def disarm(handle=None) -> None:
    """Stop the controller and clear the boundary hook.  ``handle``
    restricts the disarm to that comm's controller (closing an
    unrelated sub-comm must not kill the world's loop)."""
    global _ctrl, _swap_state
    with _lock:
        ctrl, sw = _ctrl, _swap_state
        if ctrl is None:
            return
        if handle is not None and int(handle) != int(sw.handle):
            return
        _ctrl = None
        _swap_state = None
    from ..runtime import bridge

    bridge.set_live_boundary(None)
    ctrl.stop()


def armed() -> bool:
    return _ctrl is not None


def _on_boundary(handle) -> None:
    """The bridge's collective-boundary hook while armed."""
    sw = _swap_state
    if sw is not None:
        sw.on_boundary(handle)


def status() -> dict:
    """One snapshot of the live plane: epoch, boundary count, swap
    history, drift/proposal counters, and the cursor's health — what
    diag and the world programs print."""
    ctrl, sw = _ctrl, _swap_state
    out = {
        "armed": ctrl is not None,
        "retune_requests": _retune_requests,
    }
    if ctrl is None:
        return out
    out.update(ctrl.status())
    out.update({
        "epoch": sw.epoch,
        "boundaries": sw.boundaries,
        "swaps": list(sw.swaps),
    })
    return out


def propose(named_tables, note: str = "manual") -> int:
    """Stage a candidate decision table for the next rendezvous —
    ``{op: [(min_bytes, algo_name), ...], ...}`` — from rank 0 (other
    ranks: a loud no-op returning the current epoch).  The test/tooling
    entry that exercises the full stage -> rendezvous -> quiesced
    commit path without waiting for organic drift.  Returns the epoch
    the proposal will carry."""
    ctrl, sw = _ctrl, _swap_state
    if ctrl is None:
        raise RuntimeError("live.propose() needs an armed controller "
                           "(MPI4JAX_TPU_LIVE=auto)")
    if sw.rank != 0:
        print("[live] propose() ignored off rank 0 (rank 0 is the sole "
              "proposer)", file=sys.stderr, flush=True)
        return sw.epoch
    from .. import tune

    coded = {}
    for op, entries in named_tables.items():
        kind = tune.OP_KIND[op]
        coded[str(kind)] = [[int(mb), int(tune.ALGO_CODES[name])]
                            for mb, name in entries]
    payload = {
        "tables": coded,
        "named": {op: [[int(mb), str(name)] for mb, name in entries]
                  for op, entries in named_tables.items()},
        "report": {"note": str(note), "changes": []},
    }
    return sw.propose(payload)


def request_retune(reason: str = "api") -> None:
    """Poke the controller for an immediate drift evaluation (the SLO
    floor-hit consumer).  Counted even when disarmed, so callers can
    always fire-and-forget."""
    global _retune_requests
    _retune_requests += 1
    ctrl = _ctrl
    if ctrl is not None:
        ctrl.poke(reason)


def consume_retune(scheduler) -> bool:
    """Consume (and RESET) a serving ``SLOController.retune_requested``
    flag, translating it into an immediate drift evaluation.  Returns
    whether a request was consumed — the serving engine calls this
    every step; the flag never sticks."""
    if not getattr(scheduler, "retune_requested", False):
        return False
    scheduler.retune_requested = False
    request_retune("slo-floor")
    return True
