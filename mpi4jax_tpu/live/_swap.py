"""Epoch rendezvous: agreeing on WHEN every rank installs a new table.

The algorithm-agreement contract says all ranks of a communicator must
dispatch every collective with the same algorithm — frames carry the
algorithm and a receiver aborts on mismatch.  So a decision table can
only change when every rank changes it at the same point in the
collective sequence.  This module is that point.

The protocol leans entirely on the SPMD invariant (every rank of a
comm executes the same collective sequence — the schedule verifier's
tier-0 property), which makes a plain per-comm boundary counter a
synchronized clock:

- ``on_boundary`` runs at the top of every bridge-level collective;
  every ``period``-th boundary is a rendezvous;
- at a rendezvous all ranks execute a bcast of a 2-slot int64 header
  from rank 0: ``(epoch, payload_len)``.  No proposal pending ->
  ``(current_epoch, 0)`` and everyone moves on (the steady-state cost:
  one 16-byte bcast every ``period`` collectives);
- a header carrying ``epoch > local`` is followed by a second bcast of
  the JSON payload; every rank stages the coded tables and commits
  under the comm lock with the progress engine quiesced
  (``tpucomm_commit_coll_tables`` — the ``tpucomm_set_topology`` swap
  discipline), stamping the shared epoch.

Rank 0 is the sole proposer, so two ranks can never race different
tables for the same epoch; every other rank is a pure follower.  The
rendezvous' own bcasts re-enter the boundary hook — the ``_in_rv``
guard makes them invisible to the counter, or the counter would
desynchronize from the *application's* collective sequence.

The corpus program ``tests/world_programs/epoch_rendezvous.py`` proves
the agreement property in the match simulator; the divergent variant
(one rank skipping a rendezvous) is the mismatch the verifier must
flag."""

from __future__ import annotations

import json
import sys
import threading

import numpy as np


class SwapProtocol:
    """Per-comm boundary counter + the rendezvous/commit state machine.

    ``bridge`` is injected (the module object) so unit tests can drive
    the protocol against a fake bridge without a native library."""

    def __init__(self, bridge, handle, rank: int, size: int,
                 period: int):
        self.bridge = bridge
        self.handle = int(handle)
        self.rank = int(rank)
        self.size = int(size)
        self.period = max(int(period), 1)
        cur = bridge.coll_epoch()
        self.epoch = int(cur) if cur is not None else 0
        self.boundaries = 0
        self.last_swap_boundary: int | None = None
        self.swaps: list = []     # [{epoch, boundary, report}, ...]
        self.on_commit = None     # callback(spec) after a commit
        self._pending = None      # rank 0: payload dict awaiting rendezvous
        self._next_epoch = self.epoch  # rank 0: highest epoch proposed
        self._lock = threading.Lock()
        self._in_rv = False

    # -- proposer side (rank 0) -----------------------------------------

    def propose(self, payload: dict) -> int:
        """Park a payload (``{"tables": {kind: [[mb, code]...]},
        "named": ..., "report": ...}``) for the next rendezvous; a
        newer proposal before that simply replaces it (latest wins —
        the superseded table was never installed anywhere).  Returns
        the epoch the proposal will commit as."""
        with self._lock:
            self._next_epoch = max(self._next_epoch, self.epoch) + 1
            self._pending = (self._next_epoch, dict(payload))
            return self._next_epoch

    def pending(self) -> bool:
        with self._lock:
            return self._pending is not None

    def boundaries_since_swap(self) -> int:
        if self.last_swap_boundary is None:
            return self.boundaries
        return self.boundaries - self.last_swap_boundary

    # -- every rank ------------------------------------------------------

    def on_boundary(self, handle) -> None:
        """The bridge hook: count this comm's collectives, rendezvous on
        every ``period``-th.  Other comms' collectives (topology
        sub-comms, serving side channels) don't advance the clock —
        their sequences are not synchronized with the world's."""
        if self._in_rv or int(handle) != self.handle:
            return
        self.boundaries += 1
        if self.boundaries % self.period:
            return
        self._rendezvous()

    def _rendezvous(self) -> None:
        # every rank reaches this at the same world-collective boundary
        # (SPMD invariant); the bcasts below are therefore matched
        self._in_rv = True
        try:
            pend = None
            if self.rank == 0:
                with self._lock:
                    pend = self._pending
            hdr = np.zeros(2, dtype=np.int64)
            payload_bytes = b""
            if pend is not None:
                payload_bytes = json.dumps(
                    pend[1], sort_keys=True).encode("utf-8")
                hdr[0] = pend[0]
                hdr[1] = len(payload_bytes)
            else:
                hdr[0] = self.epoch
            hdr = self.bridge.bcast(self.handle, hdr, 0)
            epoch, nbytes = int(hdr[0]), int(hdr[1])
            if epoch <= self.epoch or nbytes <= 0:
                return
            buf = np.zeros(nbytes, dtype=np.uint8)
            if self.rank == 0:
                buf[:] = np.frombuffer(payload_bytes, dtype=np.uint8)
            buf = self.bridge.bcast(self.handle, buf, 0)
            spec = json.loads(bytes(buf.tobytes()).decode("utf-8"))
            self._commit(epoch, spec)
            if self.rank == 0:
                with self._lock:
                    # clear only the proposal just installed; a newer
                    # one that raced in waits for the next rendezvous
                    if self._pending is not None \
                            and self._pending[0] == epoch:
                        self._pending = None
        finally:
            self._in_rv = False

    def _commit(self, epoch: int, spec: dict) -> None:
        coded = {int(k): [(int(mb), int(code)) for mb, code in entries]
                 for k, entries in spec.get("tables", {}).items()}
        if not self.bridge.stage_coll_table(coded):
            # arm() verified the native capability, so this is a bug,
            # not a version skew — but never desynchronize silently
            raise RuntimeError("live swap: tpucomm_stage_coll_table "
                               "unavailable mid-run")
        self.bridge.commit_coll_tables(self.handle, epoch)
        self.epoch = epoch
        self.last_swap_boundary = self.boundaries
        record = {"epoch": epoch, "boundary": self.boundaries,
                  "named": spec.get("named", {}),
                  "report": spec.get("report", {})}
        self.swaps.append(record)
        if self.rank == 0:
            changes = (spec.get("report") or {}).get("changes") or []
            detail = "; ".join(changes) if changes \
                else (spec.get("report") or {}).get("note", "")
            print(f"[live] epoch {epoch} committed at boundary "
                  f"{self.boundaries}: {detail}",
                  file=sys.stderr, flush=True)
        cb = self.on_commit
        if cb is not None:
            cb(record)
