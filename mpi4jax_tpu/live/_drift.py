"""Drift detection: rolling observed medians vs cost-model predictions.

Pure and jax-free — a :class:`DriftDetector` is fed canonical events
(the obs dump schema) and compares, per (op, power-of-two size band,
algorithm), the rolling median of observed durations against what the
tune cost model predicts for that algorithm at the band's observed
sizes.  A finding means "the model's picture of THIS algorithm at THIS
size is wrong by more than the threshold" — slower (interference, a
degraded link, a topology the sweep never saw) or faster (the
contention the sweep measured under is gone).  Either direction can
flip a decision-table winner, so both count as drift.

Findings are confirmed in two phases.  A rolling window straddles the
moment contention starts, so the first median that crosses the
threshold is a REGIME MIX — half quiescent, half contended — and a
table built from it under-records the incumbent's true drifted cost
(the adopted baseline then invites an immediate swap back: ping-pong).
So the first crossing only marks the key SUSPECT and clears its
window; the finding is reported when a window of entirely post-onset
samples crosses again.  A suspect whose fresh window comes back inside
the threshold was a transient — suspicion is dropped.

The detector carries no policy: it never proposes tables and never
touches the native layer.  The controller owns what to do with a
finding."""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import tune
from ..tune import _model


def band_of(nbytes: int) -> int:
    """The power-of-two size band a payload falls in (floor)."""
    n = max(int(nbytes), 1)
    return 1 << (n.bit_length() - 1)


class Drift:
    """One finding: (op, band, algo) whose observed median left the
    model's prediction by more than the threshold."""

    __slots__ = ("op", "band", "algo", "nbytes", "observed_s",
                 "predicted_s", "deviation_pct", "samples")

    def __init__(self, op, band, algo, nbytes, observed_s, predicted_s,
                 deviation_pct, samples):
        self.op = op
        self.band = band
        self.algo = algo
        self.nbytes = nbytes            # median payload size in the band
        self.observed_s = observed_s
        self.predicted_s = predicted_s
        self.deviation_pct = deviation_pct
        self.samples = samples

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Drift({self.op}@{self.band} {self.algo}: "
                f"{self.observed_s * 1e6:.0f}us observed vs "
                f"{self.predicted_s * 1e6:.0f}us predicted, "
                f"{self.deviation_pct:+.0f}%)")


class DriftDetector:
    """Rolling per-(op, band, algo) duration windows + the comparison
    against ``model.predict``.

    ``model`` may be ``None`` (no baseline yet): events still
    accumulate, :meth:`drifts` reports nothing, and :meth:`set_model`
    arms the comparison once the controller has a baseline.  Only
    events ``tune._usable_trace_event`` accepts are counted — the same
    filter the offline ``--from-trace`` fit applies, so the detector
    never flags an event class the model could not have learned from
    (shm, per-leg tiers, ops spans)."""

    def __init__(self, model: Optional[_model.CostModel], *,
                 drift_pct: float = 30.0, per_key: int = 64,
                 min_samples: int = 6):
        self.model = model
        self.drift_pct = float(drift_pct)
        self.per_key = max(int(per_key), min_samples)
        self.min_samples = max(int(min_samples), 2)
        #: (op, band, algo) -> deque[(nbytes, dur_s)]
        self._windows: Dict[Tuple[str, int, str], deque] = {}
        #: keys whose first threshold crossing cleared their window —
        #: confirmed (reported) only if a fully fresh window re-crosses
        self._suspect: set = set()
        self.events_seen = 0
        self.events_used = 0

    def set_model(self, model: Optional[_model.CostModel]) -> None:
        self.model = model

    def reset(self) -> None:
        """Forget all samples (a table swap makes the incumbent's
        pre-swap timings stale evidence)."""
        self._windows.clear()
        self._suspect.clear()

    def observe(self, events) -> None:
        """Feed canonical events (obs dump schema)."""
        for ev in events:
            self.events_seen += 1
            usable = tune._usable_trace_event(ev)
            if usable is None:
                continue
            op, nbytes, dur_s = usable
            algo = ev.get("algo")
            key = (op, band_of(nbytes), algo)
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = deque(maxlen=self.per_key)
            win.append((int(nbytes), float(dur_s)))
            self.events_used += 1

    def drifts(self) -> List[Drift]:
        """CONFIRMED findings, largest deviation first (empty without a
        model or before any key holds ``min_samples``).

        Stateful: a key's first threshold crossing marks it suspect and
        clears its window instead of reporting (see the module
        docstring) — callers poll this as new events arrive, so a real
        regime change confirms one fresh window later with regime-pure
        medians, while a transient spike clears itself."""
        if self.model is None:
            return []
        out: List[Drift] = []
        crossed = set()
        for key, win in self._windows.items():
            op, band, algo = key
            if len(win) < self.min_samples:
                continue
            med_bytes = int(_model._median([b for b, _ in win]))
            med_dur = _model._median([d for _, d in win])
            pred = self.model.predict(op, med_bytes, algo)
            if pred is None or pred <= 0:
                # the model has never seen this algorithm: there is no
                # prediction to drift from (the candidate build will
                # still learn the fresh samples)
                continue
            dev = (med_dur - pred) / pred * 100.0
            if abs(dev) <= self.drift_pct:
                # a full fresh window back inside the threshold: the
                # suspected onset was a transient, not a regime change
                self._suspect.discard(key)
                continue
            crossed.add(key)
            if key in self._suspect:
                out.append(Drift(op, band, algo, med_bytes, med_dur,
                                 pred, dev, len(win)))
        for key in crossed - self._suspect:
            # phase 1: the window straddles the onset — its median mixes
            # regimes, so it may only arm suspicion, never a finding
            self._suspect.add(key)
            self._windows[key].clear()
        out.sort(key=lambda d: -abs(d.deviation_pct))
        return out

    def window_events(self) -> List[dict]:
        """The held samples re-shaped as minimal canonical events —
        what the controller overlays on the baseline to build a
        candidate model."""
        out = []
        for (op, _band, algo), win in self._windows.items():
            for nbytes, dur_s in win:
                out.append({"name": op, "src": "native", "ts_us": 0.0,
                            "dur_us": dur_s * 1e6, "wait_us": 0.0,
                            "dispatch_us": 0.0, "bytes": nbytes,
                            "peer": -1, "tag": 0, "algo": algo})
        return out
