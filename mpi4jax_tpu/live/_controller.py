"""The live controller: follow the obs ring, detect drift, propose.

One daemon thread per armed communicator, on EVERY rank — following
the native ring through the non-destructive peek cursor keeps each
rank's drift picture current for :func:`live.status` — but only rank
0's controller ever proposes a table (the swap protocol's sole-proposer
rule).  The loop:

1. ``peek`` new events off the native ring (cursor follow — the
   end-of-run drain still sees everything), canonicalize, feed the
   rolling window and the :class:`.._drift.DriftDetector`;
2. baseline: the persisted tune cost model for this world size when
   one exists (``MPI4JAX_TPU_TUNE_MODEL`` honored), else a one-shot
   self-fit from the first full window — the "normal" the detector
   measures drift against;
3. on drift past the threshold, outside the cooldown, with no proposal
   in flight: build a CANDIDATE model — the baseline's samples with
   the window's fresh medians overlaid — re-rank every measured
   algorithm at the union of observed sizes and current table
   boundaries, and collapse the winners into a v2 table;
4. winners actually changed -> hand the payload to the swap protocol;
   rendezvous and commit happen on the application's collective
   boundary, never on this thread.

The overlay (not a window-only refit) is what keeps re-ranking sound:
the window only ever times the INCUMBENT algorithm, so alternatives
keep their baseline predictions while the incumbent's drifted timing
replaces its own — exactly the comparison "is someone else faster than
what I am now observing".  On commit the candidate model BECOMES the
baseline: the outgoing incumbent's learned (drifted) cost persists, so
when the new pick inevitably also runs slower than its quiescent
prediction under the same contention, the re-ranking compares it
against reality instead of proposing a swap straight back — without
adoption the controller ping-pongs between the top two algorithms
every cooldown window.

A controller tick must never take the job down: per-tick exceptions
are counted and swallowed (visible in :func:`live.status`)."""

from __future__ import annotations

import os
import sys
import threading
import traceback
from collections import deque

from .. import tune
from ..tune import _model
from ..utils import config
from . import _drift


def _lookup(entries, nbytes: int):
    """The algorithm a (min_bytes, algo) ladder selects at ``nbytes``."""
    algo = None
    for mb, name in entries or []:
        if int(nbytes) >= int(mb):
            algo = name
    return algo


class Controller:
    def __init__(self, lib, handle, rank: int, size: int, swap, *,
                 window: int, drift_pct: float, cooldown_ops: int,
                 poll_s: float = 0.05):
        self._lib = lib
        self._handle = int(handle)
        self._rank = int(rank)
        self._size = int(size)
        self._swap = swap
        self._window = max(int(window), 16)
        self._cooldown = max(int(cooldown_ops), 1)
        # hysteresis: a re-pick must beat the incumbent's OBSERVED cost
        # by half the drift threshold — when two algorithms degrade to
        # within noise of each other under the same contention, the
        # honest answer is "not worth a swap", not a ping-pong
        self._hyst = max(0.5, min(0.9, 1.0 - float(drift_pct) / 200.0))
        self._poll_s = float(poll_s)
        self._cursor = 0
        self._skipped = 0
        self._events = deque(maxlen=self._window)
        self._detector = _drift.DriftDetector(
            None, drift_pct=drift_pct,
            per_key=max(8, self._window // 4))
        # current installed ladder, by op name — what a candidate must
        # beat; starts from the tuner's merged view and tracks commits
        self._current = {op: [(int(mb), str(name)) for mb, name in ent]
                         for op, ent in tune.decision_table().items()}
        self._baseline = None
        self._baseline_source = None
        self._cand_model = None   # candidate awaiting adoption on commit
        # self-fit once the window is half full (a full window could
        # take arbitrarily long on a quiet job)
        self._selffit_at = max(self._window // 2, 16)
        self._drift_flags = 0
        self._proposals = 0
        self._pokes = 0
        self._errors = 0
        self._last_drifts: list = []
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="mpi4jax-tpu-live", daemon=True)
        self._load_baseline()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)

    def poke(self, reason: str = "api") -> None:
        """Request an immediate evaluation (the SLO retune path)."""
        self._pokes += 1
        self._wake.set()

    def status(self) -> dict:
        with self._mu:
            return {
                "cursor": self._cursor,
                "cursor_skipped": self._skipped,
                "window_events": len(self._events),
                "baseline": self._baseline_source,
                "drift_flags": self._drift_flags,
                "proposals": self._proposals,
                "pokes": self._pokes,
                "errors": self._errors,
                "last_drifts": list(self._last_drifts),
            }

    def note_commit(self, record: dict) -> None:
        """Swap-commit callback (application thread): track the newly
        installed ladders and drop the detector's windows — the
        incumbent's pre-swap timings are stale evidence now."""
        with self._mu:
            for op, entries in (record.get("named") or {}).items():
                self._current[op] = [(int(mb), str(name))
                                     for mb, name in entries]
            if self._cand_model is not None:
                # adopt: the candidate carries the window's learned
                # costs for the drifted bands, so post-swap re-ranking
                # measures the new incumbent against what the old one
                # ACTUALLY cost — not its stale quiescent prediction
                # (which would flag drift and swap straight back)
                self._baseline = self._cand_model
                self._cand_model = None
                if self._baseline_source and not \
                        self._baseline_source.endswith("+live-overlay"):
                    self._baseline_source += "+live-overlay"
                self._detector.set_model(self._baseline)
            self._detector.reset()

    # -- the loop --------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._poll_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - the job outlives us
                self._errors += 1
                if self._errors <= 3:
                    traceback.print_exc(file=sys.stderr)

    def _tick(self) -> None:
        from ..obs import _native as obs_native
        from ..obs import _recorder

        raw, self._cursor, sk = obs_native.peek(self._lib, self._cursor)
        self._skipped += sk
        with self._mu:
            if raw:
                canon = _recorder.canonicalize_native(raw)
                self._events.extend(canon)
                self._detector.observe(canon)
            if self._baseline is None:
                if len(self._events) < self._selffit_at:
                    return
                # self-fit: the first window becomes "normal" — drift
                # is then measured as departure from the job's own
                # early behavior
                self._baseline = tune.fit_model_from_events(
                    list(self._events), world_size=self._size,
                    source="live-selffit")
                self._baseline_source = "self-fit"
                self._detector.set_model(self._baseline)
                return
            if self._rank != 0:
                return
            if self._swap.pending():
                return
            if self._swap.boundaries_since_swap() < self._cooldown:
                return
            drifts = self._detector.drifts()
            if not drifts:
                return
            self._drift_flags += len(drifts)
            self._last_drifts = [d.as_dict() for d in drifts]
            tables, changes = self._candidate(drifts)
        if not tables:
            return
        payload = self._payload(tables, changes)
        self._swap.propose(payload)
        with self._mu:
            self._proposals += 1

    # -- baseline / candidate -------------------------------------------

    def _load_baseline(self) -> None:
        path = _model.model_path(self._size)
        if not os.path.exists(path):
            return
        try:
            self._baseline = _model.load_model(path)
            self._baseline_source = f"model-file:{path}"
            self._detector.set_model(self._baseline)
        except Exception as e:
            print(f"[live] ignoring unreadable cost model {path}: {e}",
                  file=sys.stderr, flush=True)

    def _eligible(self, combo: str) -> bool:
        """Combos the controller may install: plain algorithm names the
        native table accepts (gated variants like ``hring+q`` need knob
        forcing the controller does not own), quantized families only
        when the active mode permits lossy wires."""
        if combo not in tune.ALGO_CODES:
            return False
        if combo in ("auto", "shm"):
            return False
        if combo in (tune.QUANT_ALGOS | tune.A2A_QUANT) \
                and config.quant_mode() == "deny":
            return False
        return True

    def _candidate(self, drifts):
        """(tables, changes): per-op ladders whose winners moved, plus
        human-readable old -> new lines for the drifted bands."""
        cand = _model.CostModel.from_json(self._baseline.to_json())
        # overlay the DETECTOR's per-key windows, not the raw event
        # window: the detector medians are current-regime (its short
        # deques evict pre-drift samples), while the raw window can
        # still be half quiescent — an overlay that averages regimes
        # under-records the incumbent's drifted cost, and the adopted
        # baseline then invites an immediate swap back
        meas = tune.measurements_from_events(
            self._detector.window_events())
        for op, by_size in meas.items():
            for nbytes, by_algo in by_size.items():
                for algo, med in by_algo.items():
                    cand.add_sample(op, algo, nbytes, med)
        tables, changes = {}, []
        for op in tune.OPS:
            sizes = {s for (o, _c), pts in cand.samples.items()
                     if o == op for s in pts}
            cur = self._current.get(op) or []
            # keep the existing ladder's breakpoints in play so a
            # candidate refines the installed structure instead of
            # collapsing it to only the observed sizes
            sizes |= {max(int(mb), 1) for mb, _ in cur}
            if not sizes:
                continue
            combos = [c for c in cand.combos(op) if self._eligible(c)]
            if not combos:
                continue
            best = {}
            for s in sorted(sizes):
                ranked = cand.rank_combos(op, s, combos)
                pick = next((c for c, p in ranked if p is not None),
                            None)
                if pick is not None:
                    best[s] = pick
            if not best:
                continue
            entries = [(int(mb), str(name)) for mb, name in
                       tune.entries_from_measurements(best)]
            if entries == cur:
                continue
            tables[op] = entries
            for d in drifts:
                if d.op != op:
                    continue
                old = _lookup(cur, d.nbytes)
                new = _lookup(entries, d.nbytes)
                if old == new:
                    continue
                pred_new = cand.predict(op, d.nbytes, new) \
                    if new is not None else None
                if pred_new is not None and \
                        pred_new >= d.observed_s * self._hyst:
                    # within the hysteresis band of what the incumbent
                    # actually costs — not worth paying for a swap
                    continue
                changes.append(f"{op}@{d.band}: {old} -> {new}")
        if tables and not changes:
            # ladders moved only at non-drifted sizes — too weak a
            # signal to pay a swap for
            return {}, []
        if tables:
            # staged for adoption when (if) this proposal commits
            self._cand_model = cand
        return tables, changes

    def _payload(self, tables, changes) -> dict:
        return {
            "tables": {str(tune.OP_KIND[op]):
                       [[mb, tune.ALGO_CODES[name]]
                        for mb, name in entries]
                       for op, entries in tables.items()},
            "named": {op: [[mb, name] for mb, name in entries]
                      for op, entries in tables.items()},
            "report": {"changes": changes, "note": "drift"},
        }
