"""CLI for the static communication verifier and schedule compiler.

    python -m mpi4jax_tpu.analyze program.py --np 4 [--json]
                                             [--timeout S] [--schedules]
                                             [--optimize]
                                             [--emit-plan OUT.json]
                                             [--diff-plan GOLDEN.json]

Runs ``program.py`` once per simulated rank inside one process (virtual
world: threads, in-memory matching, real values — no processes spawned,
no live communication), and prints the findings table with the finding
kind, the rank pair, and the source line/equation of every involved op.
``--optimize`` additionally compiles the verified schedule into an
execution plan (docs/analysis.md § "From verifier to compiler") gated
by the equivalence prover; ``--json`` always reports the schedule/plan
``cache_key`` and ``analyzer_version`` so plan caches invalidate and CI
diffs stay stable.

Exit codes: 0 clean, 3 findings reported (or plan drift under
``--diff-plan``), 2 usage or analyzer error — the same contract
``mpi4jax_tpu.launch --verify`` relies on.
"""

from __future__ import annotations

import argparse
import json
import sys

EXIT_CLEAN = 0
EXIT_ERROR = 2
EXIT_FINDINGS = 3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.analyze",
        description="statically verify a world-tier program's "
                    "communication schedule (no processes, no live comm)",
    )
    ap.add_argument("prog", help="per-rank python program to verify")
    ap.add_argument("-n", "--np", type=int, required=True, dest="np_",
                    metavar="N", help="world size to verify at")
    ap.add_argument("--timeout", type=float, default=None,
                    help="virtual-world wall deadline in seconds "
                         "(default MPI4JAX_TPU_ANALYZE_TIMEOUT_S or 120)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--schedules", action="store_true",
                    help="also print each rank's extracted schedule")
    ap.add_argument("--show-output", action="store_true",
                    help="echo the analyzed program's captured "
                         "stdout/stderr")
    ap.add_argument("--symbolic", choices=("auto", "off"), default=None,
                    help="rank-symbolic analysis: auto (default; the "
                         "symbolic path engages on canonicalizable "
                         "schedules at large world sizes, with sound "
                         "concrete fallback) or off (pin the concrete "
                         "path bit-for-bit).  Overrides "
                         "MPI4JAX_TPU_ANALYZE_SYMBOLIC for this run")
    ap.add_argument("--errors-only", action="store_true",
                    help="exit 3 only on error-severity findings; "
                         "warnings are still printed (the launch "
                         "--verify gate uses this: a warning documents "
                         "an assumption, it does not block a job)")
    ap.add_argument("--optimize", action="store_true",
                    help="also run the schedule compiler: dependence "
                         "analysis + verified rewrite (concurrency "
                         "groups, hoisted recv posts, coalesce/bucket "
                         "marks); prints the plan and the equivalence-"
                         "prover verdict (docs/analysis.md § From "
                         "verifier to compiler)")
    ap.add_argument("--emit-plan", metavar="OUT.json", default=None,
                    help="write the verified execution plan as JSON "
                         "(implies --optimize); consumable via "
                         "MPI4JAX_TPU_PLAN=OUT.json or launch --plan")
    ap.add_argument("--diff-plan", metavar="GOLDEN.json", default=None,
                    help="diff the compiled plan against a golden plan "
                         "file (implies --optimize); exits 3 on drift — "
                         "the verify-corpus CI contract")
    # anything the analyzer doesn't recognize is the PROGRAM's argv
    # (its sys.argv, exactly as under the launcher); a leading "--"
    # separates explicitly when a program flag collides with ours
    args, prog_args = ap.parse_known_args(argv)
    if prog_args[:1] == ["--"]:
        prog_args = prog_args[1:]

    if args.np_ < 1:
        print("--np must be >= 1", file=sys.stderr)
        return EXIT_ERROR
    if args.symbolic is not None:
        import os

        os.environ["MPI4JAX_TPU_ANALYZE_SYMBOLIC"] = args.symbolic

    from . import check_program

    try:
        report = check_program(args.prog, args.np_,
                               timeout_s=args.timeout,
                               argv=prog_args)
    except (OSError, SyntaxError, ValueError) as err:
        # unreadable file / not-python / bad arguments: usage error
        print(f"cannot analyze {args.prog}: {err}", file=sys.stderr)
        return EXIT_ERROR
    except Exception as err:  # analyzer bug: still honor the contract
        import traceback

        traceback.print_exc()
        print(f"analyzer error on {args.prog}: {err}", file=sys.stderr)
        return EXIT_ERROR

    optimize = args.optimize or args.emit_plan or args.diff_plan
    plan_drift = None
    if optimize:
        from . import diff_plans, load_plan, plan_report, save_plan

        try:
            plan = plan_report(report)
        except ValueError as err:
            # e.g. a typo'd MPI4JAX_TPU_PLAN_BUCKET_KB: keep the CLI's
            # documented exit contract (2 = analyzer/usage error), not
            # a raw traceback the launch gate cannot classify
            print(f"schedule compiler error: {err}", file=sys.stderr)
            return EXIT_ERROR
        if args.emit_plan:
            save_plan(plan, args.emit_plan)
        if args.diff_plan:
            try:
                golden = load_plan(args.diff_plan)
            except (OSError, ValueError, KeyError) as err:
                print(f"cannot load golden plan {args.diff_plan}: {err}",
                      file=sys.stderr)
                return EXIT_ERROR
            plan_drift = diff_plans(golden, plan)
            if plan_drift and not args.json:
                print("PLAN DRIFT against "
                      f"{args.diff_plan}:\n{plan_drift}", file=sys.stderr)

    if args.json:
        blob = report.to_json()
        if plan_drift is not None:
            # CI consumers must be able to tell drift from findings —
            # and see WHAT drifted — from the JSON alone
            blob["plan_drift"] = plan_drift
        print(json.dumps(blob))
    else:
        print(report.format_table(show_schedules=args.schedules))
        if optimize:
            print(report.plan.format())
        if args.show_output and report.output:
            print("-- program output (captured) --")
            print(report.output, end="")
    flagged = report.errors if args.errors_only else report.findings
    if flagged:
        return EXIT_FINDINGS
    return EXIT_FINDINGS if plan_drift else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
