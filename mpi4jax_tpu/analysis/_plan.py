"""Schedule compiler: verified execution plans over extracted schedules.

Closes the ROADMAP's "schedule compilation à la GC3" loop: PR 3 extracts
the exact per-rank communication schedule from a jaxpr, PR 5 built the
execution substrate (detached buffered sends, per-peer coalescing,
pre-postable descriptors on the async progress engine) — this module
compiles the schedule into an :class:`ExecutionPlan` the runtime
(``runtime/planrt.py``) can execute with overlap:

- **concurrency groups** — consecutive, mutually-independent ops (per
  the ``_deps`` dependence DAG) whose completions may be outstanding
  together; the runner waits at the group boundary, not per op;
- **hoisted receives** — each eligible recv carries its earliest safe
  *post* point, so the progress engine reads the wire while the host is
  still computing (``post_at < idx`` in the plan);
- **coalescing marks** — adjacent small sends to one peer that the PR 5
  engine will merge into one wire frame;
- **gradient buckets** — runs of small same-op/dtype allreduces marked
  for fusion into bucketed allreduces (consumed by ``parallel.dp``).

Every plan is gated by an **equivalence prover** before anything may
execute it: the original and rewritten schedules both replay through the
PR 3 match simulator (``_match.match_schedules``), with every
interleaving inside each concurrency group explored, and the plan is
rejected unless (a) no finding kind appears that the original schedule
did not produce, (b) the per-channel delivery order — and therefore the
delivered values, since payload content rides sends unchanged — is
identical, and (c) no interleaving can deadlock.  Programs whose
schedules carry true cross-rank ordering dependence (the recalibrated
``order_critical_exchange``) or statically-unresolvable control flow are
left unrewritten, with the reason recorded.

Import-light and jax-free like ``_match``/``_deps``: the tier-1 suite
loads this standalone on any host.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import _deps, _match
from ._events import (
    ANALYZER_VERSION,
    COLLECTIVE_KINDS,
    CommEvent,
    Finding,
    event_nbytes,
    schedule_cache_key,
)

#: plan wire-format version (bumped with ANALYZER_VERSION on semantic
#: changes; loaders reject mismatches instead of misreading)
PLAN_FORMAT = 1

#: default gradient-bucket ceiling; MPI4JAX_TPU_PLAN_BUCKET_KB overrides
DEFAULT_BUCKET_BYTES = 1 << 20

#: equivalence-prover budget: total simulations across the base run, the
#: per-group interleavings, and the reversed config
MAX_INTERLEAVINGS = 256

#: finding kinds that make a schedule unplannable — the static schedule
#: is not the (only) runtime schedule, so no rewrite can be proven
UNPLANNABLE_KINDS = frozenset({
    "control_divergence", "comm_in_while", "token_violation",
    "analysis_timeout", "rank_error",
})


#: one analysis-side reading of the coalesce knob (native-clamp mirror)
default_coalesce_bytes = _match.default_coalesce_bytes


def default_bucket_bytes() -> int:
    raw = os.environ.get("MPI4JAX_TPU_PLAN_BUCKET_KB", "").strip()
    if raw:
        try:
            return max(0, int(raw)) * 1024
        except ValueError:
            # same strictness as utils.config.plan_bucket_bytes: a
            # typo'd knob must not silently change the plan's buckets
            raise ValueError(
                f"cannot parse MPI4JAX_TPU_PLAN_BUCKET_KB={raw!r} as KB")
    return DEFAULT_BUCKET_BYTES


def env_cost_model():
    """The cost model named by ``MPI4JAX_TPU_TUNE_MODEL`` (written by
    ``python -m mpi4jax_tpu.tune --joint``), or None.  The compiler
    only probes the disk when the knob is set explicitly, so plans —
    and the golden-plan corpus — compiled without it are byte-stable
    regardless of what a previous tuner run left in ``~/.cache``."""
    path = os.environ.get("MPI4JAX_TPU_TUNE_MODEL", "").strip()
    if not path:
        return None
    try:
        try:
            from ..tune import _model
        except ImportError:  # standalone analysis load (no package)
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "m4j_plan_cost_model",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir, "tune", "_model.py"))
            _model = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(_model)
        return _model.load_model(path)
    except Exception as e:
        # warn-and-continue is the contract: an unusable model file —
        # unreadable, wrong version, OR structurally corrupt (a shape
        # from_json never anticipated) — must never take down plan
        # compilation; the static defaults serve
        import warnings

        warnings.warn(f"ignoring unusable cost model "
                      f"MPI4JAX_TPU_TUNE_MODEL={path}: {e!r}")
        return None


def _model_bucket_bytes(events_by_rank, model) -> Optional[int]:
    """The cost model's gradient-bucket ceiling for THIS schedule: the
    ceiling minimizing the predicted cost of syncing the schedule's
    bucketable allreduce bytes (the dominant rank's total).  None when
    the schedule has nothing to bucket or the model no allreduce data —
    the static default then stands."""
    ladder_max = max(_TUNE_BUCKET_LADDER)
    total = 0
    for events in events_by_rank.values():
        rank_total = sum(
            ev.nbytes or 0 for ev in events
            if ev.kind == "allreduce" and ev.nbytes
            and ev.nbytes <= ladder_max)
        total = max(total, rank_total)
    if total <= 0:
        return None
    return model.best_bucket_bytes(total, ladder=_TUNE_BUCKET_LADDER)


def _model_group_cap(events_by_rank, model) -> Optional[int]:
    """The cost model's concurrency-group cap: keyed on the schedule's
    median deferrable-send payload, using the measured allreduce curve
    as the transport proxy (sends are not swept per-algorithm — the
    collective alpha/beta is the same wire).  None without send events
    or model data."""
    sends = sorted(ev.nbytes for events in events_by_rank.values()
                   for ev in events
                   if ev.kind == "send" and ev.nbytes)
    if not sends:
        return None
    median = sends[len(sends) // 2]
    combos = model.combos("allreduce")
    if not combos:
        return None
    combo = "ring" if "ring" in combos else combos[0]
    cap = model.suggested_group_cap(median, op="allreduce", combo=combo,
                                    default=_deps.MAX_GROUP)
    return cap if cap != _deps.MAX_GROUP else None


#: bucket-size candidates the model evaluates (mirrors
#: tune._model.BUCKET_LADDER without importing it on the hot path)
_TUNE_BUCKET_LADDER = tuple(1 << p for p in range(16, 23))


@dataclass
class PlanOp:
    """One scheduled op in one rank's execution plan.

    ``idx`` is the op's position in the original (token-order) schedule;
    ``group`` its concurrency group; ``post_at`` the position the op is
    *posted* at (< idx only for hoisted receives); ``deferred`` marks
    ops whose completion wait moves to the group boundary (sends);
    ``coalesce`` marks members of a small-send run the engine merges;
    ``bucket`` is the gradient-bucket id, or None.
    """

    idx: int
    kind: str
    comm: Tuple = (0,)
    dest: Optional[int] = None
    source: Optional[int] = None
    lo: Optional[int] = None
    hi: Optional[int] = None
    root: Optional[int] = None
    tag: Optional[int] = None
    sendtag: Optional[int] = None
    recvtag: Optional[int] = None
    reduce_op: Optional[str] = None
    dtype: Optional[str] = None
    shape: Optional[Tuple[int, ...]] = None
    status: bool = False
    nbytes: Optional[int] = None
    group: int = 0
    post_at: int = 0
    deferred: bool = False
    coalesce: bool = False
    bucket: Optional[int] = None

    @classmethod
    def from_event(cls, ev: CommEvent) -> "PlanOp":
        return cls(
            idx=ev.idx, kind=ev.kind, comm=tuple(ev.comm), dest=ev.dest,
            source=ev.source, lo=ev.lo, hi=ev.hi, root=ev.root, tag=ev.tag,
            sendtag=ev.sendtag, recvtag=ev.recvtag, reduce_op=ev.reduce_op,
            dtype=ev.dtype,
            shape=None if ev.shape is None else tuple(ev.shape),
            status=bool(ev.status),
            nbytes=event_nbytes(ev.dtype, ev.shape),
            post_at=ev.idx,
        )

    @property
    def hoisted(self) -> bool:
        return self.post_at < self.idx

    def describe(self) -> str:
        bits = [self.kind]
        if self.kind == "send":
            bits.append(f"to {self.dest} tag {self.tag}")
        elif self.kind == "recv":
            bits.append(f"from {self.source} tag {self.tag}")
        elif self.kind == "sendrecv":
            bits.append(f"to {self.dest} from {self.source}")
        elif self.kind == "shift2":
            bits.append(f"lo {self.lo} hi {self.hi}")
        elif self.root is not None:
            bits.append(f"root {self.root}")
        if self.reduce_op:
            bits.append(f"op {self.reduce_op}")
        if self.dtype:
            shape = "x".join(map(str, self.shape or ()))
            bits.append(f"{self.dtype}[{shape}]")
        marks = []
        if self.hoisted:
            marks.append(f"post@{self.post_at}")
        if self.deferred:
            marks.append("deferred")
        if self.coalesce:
            marks.append("coalesce")
        if self.bucket is not None:
            marks.append(f"bucket {self.bucket}")
        if marks:
            bits.append("(" + ", ".join(marks) + ")")
        return " ".join(bits)

    def to_json(self) -> dict:
        out = {"idx": self.idx, "kind": self.kind, "comm": list(self.comm),
               "group": self.group, "post_at": self.post_at}
        for name in ("dest", "source", "lo", "hi", "root", "tag",
                     "sendtag", "recvtag", "reduce_op", "dtype", "nbytes",
                     "bucket"):
            v = getattr(self, name)
            if v is not None:
                out[name] = v
        if self.shape is not None:
            out["shape"] = list(self.shape)
        for flag in ("status", "deferred", "coalesce"):
            if getattr(self, flag):
                out[flag] = True
        return out

    @classmethod
    def from_json(cls, data: dict) -> "PlanOp":
        kw = dict(data)
        kw["comm"] = tuple(kw.get("comm", (0,)))
        if kw.get("shape") is not None:
            kw["shape"] = tuple(kw["shape"])
        return cls(**kw)


@dataclass
class RankPlan:
    rank: int
    ops: List[PlanOp] = field(default_factory=list)
    groups: List[List[int]] = field(default_factory=list)

    @property
    def n_hoisted(self) -> int:
        return sum(1 for op in self.ops if op.hoisted)

    @property
    def n_deferred(self) -> int:
        return sum(1 for op in self.ops if op.deferred)

    @property
    def n_grouped(self) -> int:
        return sum(len(g) for g in self.groups if len(g) > 1)

    def to_json(self) -> dict:
        return {"rank": self.rank,
                "ops": [op.to_json() for op in self.ops],
                "groups": [list(g) for g in self.groups]}

    @classmethod
    def from_json(cls, data: dict) -> "RankPlan":
        return cls(rank=int(data["rank"]),
                   ops=[PlanOp.from_json(o) for o in data["ops"]],
                   groups=[list(g) for g in data.get("groups", [])])


@dataclass
class ExecutionPlan:
    """A verified (or verifiably rejected) whole-program execution plan."""

    world_size: int
    cache_key: str = ""
    analyzer_version: str = ANALYZER_VERSION
    detach_threshold: int = 0
    coalesce_bytes: int = 0
    bucket_bytes: int = 0
    #: provenance of model-informed choices ("" = static defaults; the
    #: golden corpus compiles without a model, so the field stays absent
    #: there)
    model: str = ""
    ranks: Dict[int, RankPlan] = field(default_factory=dict)
    proved: bool = False
    proof: dict = field(default_factory=dict)
    reasons: List[str] = field(default_factory=list)

    @property
    def rewritten(self) -> bool:
        """True when the plan changes anything relative to token order."""
        return any(
            rp.n_hoisted or rp.n_grouped or rp.n_deferred
            or any(op.bucket is not None or op.coalesce for op in rp.ops)
            for rp in self.ranks.values()
        )

    def summary(self) -> str:
        hoisted = sum(rp.n_hoisted for rp in self.ranks.values())
        deferred = sum(rp.n_deferred for rp in self.ranks.values())
        grouped = sum(rp.n_grouped for rp in self.ranks.values())
        buckets = len({(r, op.bucket) for r, rp in self.ranks.items()
                       for op in rp.ops if op.bucket is not None})
        coalesce = sum(1 for rp in self.ranks.values()
                       for op in rp.ops if op.coalesce)
        verdict = "proved" if self.proved else "NOT PROVED"
        state = "rewritten" if self.rewritten else "unrewritten"
        return (f"plan {self.cache_key or '?'} np={self.world_size}: "
                f"{state}, {verdict} "
                f"({self.proof.get('interleavings', 0)} interleavings); "
                f"{hoisted} hoisted recv(s), {grouped} grouped op(s), "
                f"{deferred} deferred send(s), {coalesce} coalesce "
                f"mark(s), {buckets} bucket(s)")

    def format(self) -> str:
        lines = [self.summary()]
        for reason in self.reasons:
            lines.append(f"  note: {reason}")
        for rank in sorted(self.ranks):
            rp = self.ranks[rank]
            lines.append(f"-- rank {rank}: {len(rp.ops)} op(s), "
                         f"{len(rp.groups)} group(s) --")
            for op in rp.ops:
                lines.append(f"   g{op.group:<3d}[{op.idx}] {op.describe()}")
        return "\n".join(lines)

    def to_json(self) -> dict:
        out = {
            "format": PLAN_FORMAT,
            "analyzer_version": self.analyzer_version,
            "cache_key": self.cache_key,
            "world_size": self.world_size,
            "detach_threshold": self.detach_threshold,
            "coalesce_bytes": self.coalesce_bytes,
            "bucket_bytes": self.bucket_bytes,
            "proved": self.proved,
            "rewritten": self.rewritten,  # derived; for JSON consumers
            "proof": self.proof,
            "reasons": list(self.reasons),
            "ranks": {str(r): rp.to_json()
                      for r, rp in sorted(self.ranks.items())},
        }
        if self.model:
            out["model"] = self.model
        return out

    @classmethod
    def from_json(cls, data: dict) -> "ExecutionPlan":
        if data.get("format") != PLAN_FORMAT:
            raise ValueError(
                f"plan format {data.get('format')!r} is not {PLAN_FORMAT}"
            )
        plan = cls(
            world_size=int(data["world_size"]),
            cache_key=data.get("cache_key", ""),
            analyzer_version=data.get("analyzer_version", ""),
            detach_threshold=int(data.get("detach_threshold", 0)),
            coalesce_bytes=int(data.get("coalesce_bytes", 0)),
            bucket_bytes=int(data.get("bucket_bytes", 0)),
            model=str(data.get("model", "")),
            proved=bool(data.get("proved", False)),
            proof=dict(data.get("proof", {})),
            reasons=list(data.get("reasons", [])),
        )
        for r, rp in data.get("ranks", {}).items():
            plan.ranks[int(r)] = RankPlan.from_json(rp)
        return plan


def diff_plans(a: ExecutionPlan, b: ExecutionPlan,
               a_name: str = "expected", b_name: str = "actual") -> str:
    """Unified diff of two plans' canonical JSON (empty = identical).

    Proof statistics are excluded: the *schedule rewrite* is the golden
    contract, prover timing/budget details are not.
    """
    import difflib

    def canon(p: ExecutionPlan) -> List[str]:
        data = p.to_json()
        data.pop("proof", None)
        return json.dumps(data, indent=1, sort_keys=True).splitlines()

    return "\n".join(difflib.unified_diff(
        canon(a), canon(b), fromfile=a_name, tofile=b_name, lineterm=""))


# ---------------------------------------------------------------------------
# plan construction


def _mark_coalesce(ops: List[PlanOp], coalesce_bytes: int) -> None:
    run: List[int] = []

    def flush():
        if len(run) >= 2:
            for i in run:
                ops[i].coalesce = True
        run.clear()

    prev_key = None
    for i, op in enumerate(ops):
        key = None
        if (op.kind == "send" and op.nbytes is not None
                and coalesce_bytes > 0 and op.nbytes <= coalesce_bytes):
            key = (op.comm, op.dest)
        if key is None or key != prev_key:
            flush()
        if key is not None:
            run.append(i)
        prev_key = key
    flush()


def _mark_buckets(ops: List[PlanOp], bucket_bytes: int) -> None:
    if bucket_bytes <= 0:
        return
    next_bucket = 0
    run: List[int] = []

    def flush():
        nonlocal next_bucket
        if len(run) >= 2:
            for i in run:
                ops[i].bucket = next_bucket
            next_bucket += 1
        run.clear()

    prev_key = None
    filled = 0
    for i, op in enumerate(ops):
        key = None
        if (op.kind == "allreduce" and op.nbytes is not None
                and op.nbytes <= bucket_bytes):
            key = (op.comm, op.reduce_op, op.dtype)
        if key is None or key != prev_key or filled + (op.nbytes or 0) > \
                bucket_bytes:
            flush()
            filled = 0
        if key is not None:
            run.append(i)
            filled += op.nbytes or 0
        prev_key = key
    flush()


def build_plan(
    events_by_rank: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    *,
    world_size: Optional[int] = None,
    findings: Sequence[Finding] = (),
    value_deps_by_rank: Optional[Dict[int, set]] = None,
    detach_threshold: Optional[int] = None,
    coalesce_bytes: Optional[int] = None,
    bucket_bytes: Optional[int] = None,
    max_group: int = _deps.MAX_GROUP,
    aggressive: bool = True,
    force_trivial: bool = False,
    cost_model=None,
) -> ExecutionPlan:
    """Compile per-rank schedules into an (unproven) execution plan.

    ``findings`` is the verification report's finding list: error-level
    findings and statically-unresolvable schedules (control divergence,
    comm-in-while, token violations) make the program unplannable, and a
    recalibrated ``order_critical_exchange`` — true cross-rank ordering
    dependence — leaves the schedule unrewritten (trivial plan).

    ``aggressive=False`` builds the fallback plan: groups and marks but
    no recv hoisting (used when the prover rejects the hoisted plan).

    ``cost_model`` (a ``tune._model.CostModel``; default: the file
    ``MPI4JAX_TPU_TUNE_MODEL`` names, if any) informs the two sizing
    choices the compiler otherwise makes statically: the
    gradient-bucket ceiling (the predicted-cheapest point of the bucket
    ladder for this schedule's bucketable bytes — an EXPLICIT
    ``MPI4JAX_TPU_PLAN_BUCKET_KB`` still wins) and the concurrency-
    group cap (deeper groups where the measured curve says dispatch
    dominates).  The plan records the provenance (``model`` field).
    """
    if world_size is None:
        world_size = len(events_by_rank)
    if detach_threshold is None:
        detach_threshold = _match.default_detach_threshold()
    if coalesce_bytes is None:
        coalesce_bytes = default_coalesce_bytes()
    if cost_model is None:
        cost_model = env_cost_model()
    model_notes = []
    if bucket_bytes is None:
        bucket_bytes = default_bucket_bytes()
        if (cost_model is not None
                and not os.environ.get("MPI4JAX_TPU_PLAN_BUCKET_KB",
                                       "").strip()):
            picked = _model_bucket_bytes(events_by_rank, cost_model)
            if picked is not None and picked != bucket_bytes:
                model_notes.append(
                    f"bucket_bytes {picked} (model; static default "
                    f"{bucket_bytes})")
                bucket_bytes = picked
    if cost_model is not None and max_group == _deps.MAX_GROUP:
        cap = _model_group_cap(events_by_rank, cost_model)
        if cap is not None:
            model_notes.append(
                f"group cap {cap} (model; static default {max_group})")
            max_group = cap
    plan = ExecutionPlan(
        world_size=world_size,
        cache_key=schedule_cache_key(events_by_rank, world_size),
        detach_threshold=detach_threshold,
        coalesce_bytes=coalesce_bytes,
        bucket_bytes=bucket_bytes,
    )
    if model_notes:
        plan.model = "; ".join(model_notes)
        plan.reasons.append("cost model consulted: " + plan.model)

    blockers = sorted(
        {f.kind for f in findings
         if f.severity == "error" or f.kind in UNPLANNABLE_KINDS}
    )
    pinned = any(f.kind == "order_critical_exchange" for f in findings)
    # the runtime runner serves the WORLD communicator only: a schedule
    # that communicates on sub-comms would desync its cursor (sub-comm
    # ops bypass the world runner), so such programs stay unrewritten
    world_key = (0,)
    subcomms = any(
        tuple(ev.comm) != world_key
        for events in events_by_rank.values() for ev in events
    )
    trivial = bool(blockers) or pinned or subcomms or force_trivial
    if blockers:
        plan.reasons.append(
            "unplannable schedule: " + ", ".join(blockers)
        )
    if pinned:
        plan.reasons.append(
            "order-critical exchange: true cross-rank ordering "
            "dependence — schedule left unrewritten"
        )
    if subcomms and not (blockers or pinned or force_trivial):
        plan.reasons.append(
            "sub-communicator schedule: plan execution serves the "
            "world communicator only — schedule left unrewritten"
        )

    for rank, events in sorted(events_by_rank.items()):
        ops = [PlanOp.from_event(ev) for ev in events]
        for pos, op in enumerate(ops):
            # positions are the plan's coordinate system; re-number so a
            # truncated/merged extraction cannot desync the groups
            op.idx = pos
            op.post_at = pos
        if trivial:
            groups = [[i] for i in range(len(ops))]
        else:
            vdeps = (value_deps_by_rank or {}).get(rank)
            deps = _deps.build_rank_deps(events, value_deps=vdeps)
            groups = _deps.concurrency_groups(events, deps,
                                              max_group=max_group)
            # never hoist on a channel that ANYWHERE in the schedule
            # also carries a Status or wildcard receive: a pre-posted
            # strict descriptor owns the next wire message on its
            # channel, and mixing it with flexible receives is exactly
            # the reconciliation the runtime fallback cannot do safely
            wild_comms = set()
            status_channels = set()
            for ev in events:
                if ev.source == _deps.ANY_SOURCE:
                    wild_comms.add(ev.comm)
                elif ev.status and ev.kind in ("recv", "sendrecv"):
                    status_channels.add((ev.comm, ev.source))
            for pos, op in enumerate(ops):
                if op.kind == "send":
                    op.deferred = True
                if (aggressive and op.kind == "recv"
                        and op.comm not in wild_comms
                        and (op.comm, op.source) not in status_channels):
                    op.post_at = _deps.recv_post_point(events, deps, pos)
            _mark_coalesce(ops, min(coalesce_bytes, detach_threshold))
            _mark_buckets(ops, bucket_bytes)
        for gid, members in enumerate(groups):
            for pos in members:
                ops[pos].group = gid
        plan.ranks[rank] = RankPlan(rank=rank, ops=ops, groups=groups)
    return plan


# ---------------------------------------------------------------------------
# equivalence prover


def _planned_order(events: List[CommEvent], rp: RankPlan) -> List[int]:
    """Positions of ``events`` in planned wire order.

    A hoisted recv (``post_at = p < idx``) is posted immediately after
    op ``p``'s own post, so its wire slot sits between ``p`` and
    ``p + 1``; the FIFO progress engine makes post order the wire order.
    For the common temporal hoist (``p == idx - 1``) the order is
    unchanged — only the *time* of the post moves earlier, into the
    host-compute gap.  Multiple hoists to one point keep their original
    relative order."""
    keys = []
    for pos in range(len(events)):
        op = rp.ops[pos]
        if op.hoisted:
            keys.append((op.post_at + 0.5, pos))
        else:
            keys.append((float(pos), pos))
    return [pos for _, pos in sorted(keys)]


def _apply_perm(order: List[int], members: List[int],
                perm: Tuple[int, ...]) -> List[int]:
    """Reorder ``members`` (original positions) within ``order`` slots."""
    slots = [order.index(m) for m in members]
    out = list(order)
    for slot, m in zip(sorted(slots), perm):
        out[slot] = m
    return out


def _simulate(events_by_rank, comms, orders,
              service_order=None) -> Tuple[set, dict]:
    schedules = {
        r: [events_by_rank[r][pos] for pos in orders[r]]
        for r in events_by_rank
    }
    deliv: dict = {}
    findings = _match.match_schedules(schedules, comms, deliveries=deliv,
                                      service_order=service_order)
    return {f.kind for f in findings}, deliv


def _group_interleavings(events, members: List[int]) -> List[Tuple[int, ...]]:
    """Every completion order a concurrency group can exhibit at run
    time.  The FIFO progress engine pins the relative wire order of
    same-engine members to post order, so the realizable orders are the
    riffles of the per-engine-root subsequences (identity excluded).

    NOTE: today ``build_plan`` leaves sub-communicator schedules
    unrewritten, so every compilable plan's events share one engine
    root and this returns [] — the realizable set is the singleton post
    order, and the proof reduces to planned order + rank-service
    rotations.  The riffle machinery is the contract a future
    multi-engine (or out-of-order-engine) planner must re-enter, and
    the unit tests pin it with hand-built foreign-engine events."""
    by_root: Dict[Tuple, List[int]] = {}
    for m in members:
        by_root.setdefault(_deps._engine_root(events[m].comm), []).append(m)
    seqs = list(by_root.values())
    if len(seqs) == 1:
        return []  # one engine: post order IS the only realizable order

    def riffle(parts: List[List[int]]):
        if all(not p for p in parts):
            yield ()
            return
        for i, p in enumerate(parts):
            if not p:
                continue
            rest = [list(q) for q in parts]
            head = rest[i].pop(0)
            for tail in riffle(rest):
                yield (head,) + tail

    return [perm for perm in riffle([list(s) for s in seqs])
            if list(perm) != members]


def prove_plan(
    events_by_rank: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    plan: ExecutionPlan,
    max_interleavings: int = MAX_INTERLEAVINGS,
    symmetry=None,
) -> bool:
    """Replay original and planned schedules through the match simulator.

    Configurations explored:

    - the planned wire order itself (hoists applied);
    - for every concurrency group, every completion order the execution
      substrate can realize (the FIFO progress engine pins same-engine
      members to post order; members on different engine roots riffle
      freely), with all other groups at planned order;
    - every rotation of the simulator's rank-service order, which
      exposes matches that depend on which rank happens to progress
      first (ANY_SOURCE races).

    The plan is accepted only if every replay (a) produces no finding
    kind the original schedule did not, and (b) delivers the same
    messages in the same per-channel order — which pins delivered
    values, since payload content rides sends unchanged.  A replay that
    stalls shows up as (a): deadlock/unmatched kinds.  Sets
    ``plan.proved`` and ``plan.proof``.

    ``symmetry`` (a ``_symbolic.SymmetryPartition``) quotients the
    proof: one replay per class-level configuration, with rank-service
    rotations collapsed to class-service rotations — the step that
    keeps the budget independent of np (512 concrete rotations exceed
    ``MAX_INTERLEAVINGS``; the quotient needs one per class).  A plan
    outside the symbolic model silently falls back to the concrete
    proof below, which stays sound at any size (at worst: budget
    exceeded, plan rejected unproven).
    """
    if symmetry is not None:
        from . import _symbolic

        try:
            verdict = _symbolic.prove_plan_symbolic(
                events_by_rank, comms, plan, symmetry,
                max_interleavings=max_interleavings)
        except (_symbolic.Uncanonicalizable, _symbolic.FallbackNeeded):
            verdict = None
        if verdict is not None:
            return verdict
    ranks = sorted(events_by_rank)
    base_orders = {r: list(range(len(v)))
                   for r, v in events_by_rank.items()}
    base_kinds, base_deliv = _simulate(events_by_rank, comms, base_orders)
    planned = {r: _planned_order(events_by_rank[r], plan.ranks[r])
               for r in events_by_rank}

    # (orders, service_order) configurations
    configs: List[Tuple[Dict[int, List[int]], Optional[List[int]]]] = [
        (planned, None)
    ]
    for rank in ranks:
        rp = plan.ranks[rank]
        for members in rp.groups:
            if len(members) < 2:
                continue
            for perm in _group_interleavings(events_by_rank[rank],
                                             members):
                orders = dict(planned)
                orders[rank] = _apply_perm(planned[rank], members, perm)
                configs.append((orders, None))
    for shift in range(1, len(ranks)):
        rotated = ranks[shift:] + ranks[:shift]
        configs.append((planned, rotated))

    exhaustive = len(configs) <= max_interleavings
    if not exhaustive:
        configs = configs[:max_interleavings]

    failures: List[str] = []
    for i, (orders, service) in enumerate(configs):
        kinds, deliv = _simulate(events_by_rank, comms, orders,
                                 service_order=service)
        new_kinds = kinds - base_kinds
        if new_kinds:
            failures.append(
                f"interleaving {i}: new finding kind(s) "
                f"{sorted(new_kinds)}"
            )
        elif deliv != base_deliv:
            failures.append(
                f"interleaving {i}: per-channel delivery order changed"
            )
        if failures:
            break

    plan.proof = {
        "interleavings": len(configs),
        "exhaustive": exhaustive,
        "base_finding_kinds": sorted(base_kinds),
        "failures": failures,
    }
    plan.proved = not failures and exhaustive
    if failures:
        plan.reasons.extend(failures)
    elif not exhaustive:
        plan.reasons.append(
            f"interleaving budget exceeded ({max_interleavings}); "
            "plan rejected unproven"
        )
    return plan.proved


def compile_schedules(
    events_by_rank: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    *,
    findings: Sequence[Finding] = (),
    world_size: Optional[int] = None,
    value_deps_by_rank: Optional[Dict[int, set]] = None,
    detach_threshold: Optional[int] = None,
    coalesce_bytes: Optional[int] = None,
    bucket_bytes: Optional[int] = None,
    max_interleavings: int = MAX_INTERLEAVINGS,
    cost_model=None,
    symmetry=None,
) -> ExecutionPlan:
    """Build the most aggressive provable plan: try hoisting + grouping,
    fall back to no-hoist, then to the trivial (unrewritten) plan.  The
    returned plan always carries ``proved`` and the downgrade reasons —
    an unsafe rewrite is *demonstrably* rejected, never silently run.

    ``symmetry`` (a ``_symbolic.SymmetryPartition``) is forwarded to
    the equivalence prover; see :func:`prove_plan`."""
    if cost_model is None:
        # resolve the env-named model once for all three attempts
        cost_model = env_cost_model()
    kw = dict(
        world_size=world_size, findings=findings,
        value_deps_by_rank=value_deps_by_rank,
        detach_threshold=detach_threshold, coalesce_bytes=coalesce_bytes,
        bucket_bytes=bucket_bytes, cost_model=cost_model,
    )
    plan = build_plan(events_by_rank, comms, aggressive=True, **kw)
    if prove_plan(events_by_rank, comms, plan, max_interleavings,
                  symmetry=symmetry):
        return plan
    rejected_reasons = list(plan.reasons)

    fallback = build_plan(events_by_rank, comms, aggressive=False, **kw)
    fallback.reasons = rejected_reasons + [
        "hoisted plan rejected by the equivalence prover; "
        "retrying without recv hoisting"
    ]
    if prove_plan(events_by_rank, comms, fallback, max_interleavings,
                  symmetry=symmetry):
        fallback.reasons = [r for r in fallback.reasons
                            if not r.startswith("interleaving ")]
        return fallback

    trivial = build_plan(events_by_rank, comms, aggressive=False,
                         force_trivial=True, **kw)
    trivial.reasons = [
        "grouped plan rejected by the equivalence prover; "
        "schedule left unrewritten"
    ]
    prove_plan(events_by_rank, comms, trivial, max_interleavings,
               symmetry=symmetry)
    return trivial


# ---------------------------------------------------------------------------
# plan cache (per jaxpr/schedule hash)


def plan_cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "mpi4jax_tpu", "plans")


def plan_cache_path(cache_key: str) -> str:
    return os.path.join(plan_cache_dir(), f"{cache_key}.json")


def save_plan(plan: ExecutionPlan, path: Optional[str] = None) -> str:
    path = path or plan_cache_path(plan.cache_key)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(plan.to_json(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _plan_from_data(data: dict, path: str) -> ExecutionPlan:
    plan = ExecutionPlan.from_json(data)
    if plan.analyzer_version != ANALYZER_VERSION:
        raise ValueError(
            f"plan at {path} was compiled by analyzer "
            f"{plan.analyzer_version!r}, this is {ANALYZER_VERSION!r} — "
            "recompile (the cache key embeds the version exactly so "
            "stale plans invalidate instead of misexecuting)"
        )
    return plan


def load_plan(path: str) -> ExecutionPlan:
    with open(path) as f:
        data = json.load(f)
    return _plan_from_data(data, path)


def cached_plan(cache_key: str) -> Optional[ExecutionPlan]:
    """The cached verified plan for a schedule hash, or None (missing,
    unreadable, version-mismatched, or never proved)."""
    path = plan_cache_path(cache_key)
    try:
        plan = load_plan(path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return None
    if plan.cache_key != cache_key or not plan.proved:
        return None
    return plan


# ---------------------------------------------------------------------------
# elastic-safe plans: bundles (one plan per survivable world size) and
# in-recovery re-derivation


def events_from_plan(plan: ExecutionPlan):
    """Reconstruct the per-rank schedules a plan was compiled from:
    ``(events_by_rank, comms)`` ready for :func:`compile_schedules`.

    A :class:`PlanOp` carries every field of the event's *semantic
    identity* (``_events.canonical_event`` — exactly what the schedule
    cache key hashes), so the reconstruction round-trips the cache key
    bit-for-bit; only presentation (source-site strings) is lost.  This
    is what lets elastic recovery re-derive and re-PROVE a stored plan
    from the plan file alone, with no program re-trace."""
    events_by_rank: Dict[int, List[CommEvent]] = {}
    for rank, rp in sorted(plan.ranks.items()):
        events_by_rank[rank] = [
            CommEvent(
                rank=rank, idx=i, kind=op.kind, comm=tuple(op.comm),
                dest=op.dest, source=op.source, lo=op.lo, hi=op.hi,
                root=op.root, tag=op.tag, sendtag=op.sendtag,
                recvtag=op.recvtag, reduce_op=op.reduce_op,
                dtype=op.dtype,
                shape=None if op.shape is None else tuple(op.shape),
                status=bool(op.status),
            )
            for i, op in enumerate(rp.ops)
        ]
    # compilable plans serve the world communicator only (build_plan
    # leaves sub-comm schedules unrewritten; planrt.install refuses
    # them), so the comm map is exactly the world membership
    comms = {(0,): tuple(sorted(plan.ranks))}
    return events_by_rank, comms


def recompile_plan(stored: ExecutionPlan, *,
                   max_interleavings: int = MAX_INTERLEAVINGS,
                   cost_model=None) -> ExecutionPlan:
    """Re-derive and re-prove a stored plan from its own schedule: the
    full compile pipeline (dependence DAG, hoist points, equivalence
    prover) runs fresh on the reconstructed events.  The result's
    ``cache_key`` must equal the stored one — the signature check the
    elastic reinstall path enforces (a mismatch means the file does not
    contain the schedule it claims to)."""
    events_by_rank, comms = events_from_plan(stored)
    return compile_schedules(
        events_by_rank, comms, world_size=stored.world_size,
        detach_threshold=stored.detach_threshold,
        coalesce_bytes=stored.coalesce_bytes,
        bucket_bytes=stored.bucket_bytes,
        max_interleavings=max_interleavings, cost_model=cost_model)


#: bundle wire format: ``{"format": "plan-bundle", "version": 1,
#: "plans": {"<np>": <plan json>}}`` — one verified plan per world size
#: a shrinking elastic job may pass through.  ``launch --plan
#: --elastic`` emits these (one analyzer run per size), and
#: ``planrt``/``bridge.rebuild`` pick the surviving size's plan at
#: recovery.
BUNDLE_FORMAT = "plan-bundle"
BUNDLE_VERSION = 1


def save_bundle(plans, path: str) -> str:
    """Atomically write a plan bundle from ``{world_size: plan}`` (or
    an iterable of plans)."""
    if not isinstance(plans, dict):
        plans = {p.world_size: p for p in plans}
    payload = {
        "format": BUNDLE_FORMAT,
        "version": BUNDLE_VERSION,
        "analyzer_version": ANALYZER_VERSION,
        "plans": {str(int(n)): p.to_json()
                  for n, p in sorted(plans.items())},
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def is_bundle(data) -> bool:
    return isinstance(data, dict) and data.get("format") == BUNDLE_FORMAT


def _bundle_from_data(data: dict, path: str) -> Dict[int, ExecutionPlan]:
    if int(data.get("version", -1)) != BUNDLE_VERSION:
        raise ValueError(
            f"plan bundle {path} has version {data.get('version')!r}, "
            f"expected {BUNDLE_VERSION}")
    out: Dict[int, ExecutionPlan] = {}
    for n, pdata in data.get("plans", {}).items():
        plan = ExecutionPlan.from_json(pdata)
        if plan.analyzer_version != ANALYZER_VERSION:
            raise ValueError(
                f"plan bundle {path} was compiled by analyzer "
                f"{plan.analyzer_version!r}, this is "
                f"{ANALYZER_VERSION!r} — recompile")
        out[int(n)] = plan
    return out


def load_bundle(path: str) -> Dict[int, ExecutionPlan]:
    """``{world_size: plan}`` from a bundle file; raises ``ValueError``
    on anything else (including version/analyzer drift — stale bundles
    must invalidate, not misexecute)."""
    with open(path) as f:
        data = json.load(f)
    if not is_bundle(data):
        raise ValueError(f"{path} is not a plan bundle")
    return _bundle_from_data(data, path)


def load_plan_for_size(path: str, world_size: int) -> Optional[ExecutionPlan]:
    """The plan serving ``world_size`` from ``path`` — a single-plan
    file (must match the size exactly) or a bundle (picks the size's
    entry).  None when the file holds no plan for that size; raises on
    unreadable/stale files.  One read + one parse — this sits on the
    elastic-recovery reinstall path."""
    with open(path) as f:
        data = json.load(f)
    if is_bundle(data):
        return _bundle_from_data(data, path).get(int(world_size))
    plan = _plan_from_data(data, path)
    return plan if plan.world_size == int(world_size) else None
