"""Schedule compiler: verified execution plans over extracted schedules.

Closes the ROADMAP's "schedule compilation à la GC3" loop: PR 3 extracts
the exact per-rank communication schedule from a jaxpr, PR 5 built the
execution substrate (detached buffered sends, per-peer coalescing,
pre-postable descriptors on the async progress engine) — this module
compiles the schedule into an :class:`ExecutionPlan` the runtime
(``runtime/planrt.py``) can execute with overlap:

- **concurrency groups** — consecutive, mutually-independent ops (per
  the ``_deps`` dependence DAG) whose completions may be outstanding
  together; the runner waits at the group boundary, not per op;
- **hoisted receives** — each eligible recv carries its earliest safe
  *post* point, so the progress engine reads the wire while the host is
  still computing (``post_at < idx`` in the plan);
- **coalescing marks** — adjacent small sends to one peer that the PR 5
  engine will merge into one wire frame;
- **gradient buckets** — runs of small same-op/dtype allreduces marked
  for fusion into bucketed allreduces (consumed by ``parallel.dp``).

Every plan is gated by an **equivalence prover** before anything may
execute it: the original and rewritten schedules both replay through the
PR 3 match simulator (``_match.match_schedules``), with every
interleaving inside each concurrency group explored, and the plan is
rejected unless (a) no finding kind appears that the original schedule
did not produce, (b) the per-channel delivery order — and therefore the
delivered values, since payload content rides sends unchanged — is
identical, and (c) no interleaving can deadlock.  Programs whose
schedules carry true cross-rank ordering dependence (the recalibrated
``order_critical_exchange``) or statically-unresolvable control flow are
left unrewritten, with the reason recorded.

Import-light and jax-free like ``_match``/``_deps``: the tier-1 suite
loads this standalone on any host.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import _deps, _match
from ._events import (
    ANALYZER_VERSION,
    COLLECTIVE_KINDS,
    CommEvent,
    Finding,
    event_nbytes,
    schedule_cache_key,
)

#: plan wire-format version (bumped with ANALYZER_VERSION on semantic
#: changes; loaders reject mismatches instead of misreading)
PLAN_FORMAT = 1

#: default gradient-bucket ceiling; MPI4JAX_TPU_PLAN_BUCKET_KB overrides
DEFAULT_BUCKET_BYTES = 1 << 20

#: equivalence-prover budget: total simulations across the base run, the
#: per-group interleavings, and the reversed config
MAX_INTERLEAVINGS = 256

#: finding kinds that make a schedule unplannable — the static schedule
#: is not the (only) runtime schedule, so no rewrite can be proven
UNPLANNABLE_KINDS = frozenset({
    "control_divergence", "comm_in_while", "token_violation",
    "analysis_timeout", "rank_error",
})


#: one analysis-side reading of the coalesce knob (native-clamp mirror)
default_coalesce_bytes = _match.default_coalesce_bytes


def default_bucket_bytes() -> int:
    raw = os.environ.get("MPI4JAX_TPU_PLAN_BUCKET_KB", "").strip()
    if raw:
        try:
            return max(0, int(raw)) * 1024
        except ValueError:
            # same strictness as utils.config.plan_bucket_bytes: a
            # typo'd knob must not silently change the plan's buckets
            raise ValueError(
                f"cannot parse MPI4JAX_TPU_PLAN_BUCKET_KB={raw!r} as KB")
    return DEFAULT_BUCKET_BYTES


@dataclass
class PlanOp:
    """One scheduled op in one rank's execution plan.

    ``idx`` is the op's position in the original (token-order) schedule;
    ``group`` its concurrency group; ``post_at`` the position the op is
    *posted* at (< idx only for hoisted receives); ``deferred`` marks
    ops whose completion wait moves to the group boundary (sends);
    ``coalesce`` marks members of a small-send run the engine merges;
    ``bucket`` is the gradient-bucket id, or None.
    """

    idx: int
    kind: str
    comm: Tuple = (0,)
    dest: Optional[int] = None
    source: Optional[int] = None
    lo: Optional[int] = None
    hi: Optional[int] = None
    root: Optional[int] = None
    tag: Optional[int] = None
    sendtag: Optional[int] = None
    recvtag: Optional[int] = None
    reduce_op: Optional[str] = None
    dtype: Optional[str] = None
    shape: Optional[Tuple[int, ...]] = None
    status: bool = False
    nbytes: Optional[int] = None
    group: int = 0
    post_at: int = 0
    deferred: bool = False
    coalesce: bool = False
    bucket: Optional[int] = None

    @classmethod
    def from_event(cls, ev: CommEvent) -> "PlanOp":
        return cls(
            idx=ev.idx, kind=ev.kind, comm=tuple(ev.comm), dest=ev.dest,
            source=ev.source, lo=ev.lo, hi=ev.hi, root=ev.root, tag=ev.tag,
            sendtag=ev.sendtag, recvtag=ev.recvtag, reduce_op=ev.reduce_op,
            dtype=ev.dtype,
            shape=None if ev.shape is None else tuple(ev.shape),
            status=bool(ev.status),
            nbytes=event_nbytes(ev.dtype, ev.shape),
            post_at=ev.idx,
        )

    @property
    def hoisted(self) -> bool:
        return self.post_at < self.idx

    def describe(self) -> str:
        bits = [self.kind]
        if self.kind == "send":
            bits.append(f"to {self.dest} tag {self.tag}")
        elif self.kind == "recv":
            bits.append(f"from {self.source} tag {self.tag}")
        elif self.kind == "sendrecv":
            bits.append(f"to {self.dest} from {self.source}")
        elif self.kind == "shift2":
            bits.append(f"lo {self.lo} hi {self.hi}")
        elif self.root is not None:
            bits.append(f"root {self.root}")
        if self.reduce_op:
            bits.append(f"op {self.reduce_op}")
        if self.dtype:
            shape = "x".join(map(str, self.shape or ()))
            bits.append(f"{self.dtype}[{shape}]")
        marks = []
        if self.hoisted:
            marks.append(f"post@{self.post_at}")
        if self.deferred:
            marks.append("deferred")
        if self.coalesce:
            marks.append("coalesce")
        if self.bucket is not None:
            marks.append(f"bucket {self.bucket}")
        if marks:
            bits.append("(" + ", ".join(marks) + ")")
        return " ".join(bits)

    def to_json(self) -> dict:
        out = {"idx": self.idx, "kind": self.kind, "comm": list(self.comm),
               "group": self.group, "post_at": self.post_at}
        for name in ("dest", "source", "lo", "hi", "root", "tag",
                     "sendtag", "recvtag", "reduce_op", "dtype", "nbytes",
                     "bucket"):
            v = getattr(self, name)
            if v is not None:
                out[name] = v
        if self.shape is not None:
            out["shape"] = list(self.shape)
        for flag in ("status", "deferred", "coalesce"):
            if getattr(self, flag):
                out[flag] = True
        return out

    @classmethod
    def from_json(cls, data: dict) -> "PlanOp":
        kw = dict(data)
        kw["comm"] = tuple(kw.get("comm", (0,)))
        if kw.get("shape") is not None:
            kw["shape"] = tuple(kw["shape"])
        return cls(**kw)


@dataclass
class RankPlan:
    rank: int
    ops: List[PlanOp] = field(default_factory=list)
    groups: List[List[int]] = field(default_factory=list)

    @property
    def n_hoisted(self) -> int:
        return sum(1 for op in self.ops if op.hoisted)

    @property
    def n_deferred(self) -> int:
        return sum(1 for op in self.ops if op.deferred)

    @property
    def n_grouped(self) -> int:
        return sum(len(g) for g in self.groups if len(g) > 1)

    def to_json(self) -> dict:
        return {"rank": self.rank,
                "ops": [op.to_json() for op in self.ops],
                "groups": [list(g) for g in self.groups]}

    @classmethod
    def from_json(cls, data: dict) -> "RankPlan":
        return cls(rank=int(data["rank"]),
                   ops=[PlanOp.from_json(o) for o in data["ops"]],
                   groups=[list(g) for g in data.get("groups", [])])


@dataclass
class ExecutionPlan:
    """A verified (or verifiably rejected) whole-program execution plan."""

    world_size: int
    cache_key: str = ""
    analyzer_version: str = ANALYZER_VERSION
    detach_threshold: int = 0
    coalesce_bytes: int = 0
    bucket_bytes: int = 0
    ranks: Dict[int, RankPlan] = field(default_factory=dict)
    proved: bool = False
    proof: dict = field(default_factory=dict)
    reasons: List[str] = field(default_factory=list)

    @property
    def rewritten(self) -> bool:
        """True when the plan changes anything relative to token order."""
        return any(
            rp.n_hoisted or rp.n_grouped or rp.n_deferred
            or any(op.bucket is not None or op.coalesce for op in rp.ops)
            for rp in self.ranks.values()
        )

    def summary(self) -> str:
        hoisted = sum(rp.n_hoisted for rp in self.ranks.values())
        deferred = sum(rp.n_deferred for rp in self.ranks.values())
        grouped = sum(rp.n_grouped for rp in self.ranks.values())
        buckets = len({(r, op.bucket) for r, rp in self.ranks.items()
                       for op in rp.ops if op.bucket is not None})
        coalesce = sum(1 for rp in self.ranks.values()
                       for op in rp.ops if op.coalesce)
        verdict = "proved" if self.proved else "NOT PROVED"
        state = "rewritten" if self.rewritten else "unrewritten"
        return (f"plan {self.cache_key or '?'} np={self.world_size}: "
                f"{state}, {verdict} "
                f"({self.proof.get('interleavings', 0)} interleavings); "
                f"{hoisted} hoisted recv(s), {grouped} grouped op(s), "
                f"{deferred} deferred send(s), {coalesce} coalesce "
                f"mark(s), {buckets} bucket(s)")

    def format(self) -> str:
        lines = [self.summary()]
        for reason in self.reasons:
            lines.append(f"  note: {reason}")
        for rank in sorted(self.ranks):
            rp = self.ranks[rank]
            lines.append(f"-- rank {rank}: {len(rp.ops)} op(s), "
                         f"{len(rp.groups)} group(s) --")
            for op in rp.ops:
                lines.append(f"   g{op.group:<3d}[{op.idx}] {op.describe()}")
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "format": PLAN_FORMAT,
            "analyzer_version": self.analyzer_version,
            "cache_key": self.cache_key,
            "world_size": self.world_size,
            "detach_threshold": self.detach_threshold,
            "coalesce_bytes": self.coalesce_bytes,
            "bucket_bytes": self.bucket_bytes,
            "proved": self.proved,
            "rewritten": self.rewritten,  # derived; for JSON consumers
            "proof": self.proof,
            "reasons": list(self.reasons),
            "ranks": {str(r): rp.to_json()
                      for r, rp in sorted(self.ranks.items())},
        }

    @classmethod
    def from_json(cls, data: dict) -> "ExecutionPlan":
        if data.get("format") != PLAN_FORMAT:
            raise ValueError(
                f"plan format {data.get('format')!r} is not {PLAN_FORMAT}"
            )
        plan = cls(
            world_size=int(data["world_size"]),
            cache_key=data.get("cache_key", ""),
            analyzer_version=data.get("analyzer_version", ""),
            detach_threshold=int(data.get("detach_threshold", 0)),
            coalesce_bytes=int(data.get("coalesce_bytes", 0)),
            bucket_bytes=int(data.get("bucket_bytes", 0)),
            proved=bool(data.get("proved", False)),
            proof=dict(data.get("proof", {})),
            reasons=list(data.get("reasons", [])),
        )
        for r, rp in data.get("ranks", {}).items():
            plan.ranks[int(r)] = RankPlan.from_json(rp)
        return plan


def diff_plans(a: ExecutionPlan, b: ExecutionPlan,
               a_name: str = "expected", b_name: str = "actual") -> str:
    """Unified diff of two plans' canonical JSON (empty = identical).

    Proof statistics are excluded: the *schedule rewrite* is the golden
    contract, prover timing/budget details are not.
    """
    import difflib

    def canon(p: ExecutionPlan) -> List[str]:
        data = p.to_json()
        data.pop("proof", None)
        return json.dumps(data, indent=1, sort_keys=True).splitlines()

    return "\n".join(difflib.unified_diff(
        canon(a), canon(b), fromfile=a_name, tofile=b_name, lineterm=""))


# ---------------------------------------------------------------------------
# plan construction


def _mark_coalesce(ops: List[PlanOp], coalesce_bytes: int) -> None:
    run: List[int] = []

    def flush():
        if len(run) >= 2:
            for i in run:
                ops[i].coalesce = True
        run.clear()

    prev_key = None
    for i, op in enumerate(ops):
        key = None
        if (op.kind == "send" and op.nbytes is not None
                and coalesce_bytes > 0 and op.nbytes <= coalesce_bytes):
            key = (op.comm, op.dest)
        if key is None or key != prev_key:
            flush()
        if key is not None:
            run.append(i)
        prev_key = key
    flush()


def _mark_buckets(ops: List[PlanOp], bucket_bytes: int) -> None:
    if bucket_bytes <= 0:
        return
    next_bucket = 0
    run: List[int] = []

    def flush():
        nonlocal next_bucket
        if len(run) >= 2:
            for i in run:
                ops[i].bucket = next_bucket
            next_bucket += 1
        run.clear()

    prev_key = None
    filled = 0
    for i, op in enumerate(ops):
        key = None
        if (op.kind == "allreduce" and op.nbytes is not None
                and op.nbytes <= bucket_bytes):
            key = (op.comm, op.reduce_op, op.dtype)
        if key is None or key != prev_key or filled + (op.nbytes or 0) > \
                bucket_bytes:
            flush()
            filled = 0
        if key is not None:
            run.append(i)
            filled += op.nbytes or 0
        prev_key = key
    flush()


def build_plan(
    events_by_rank: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    *,
    world_size: Optional[int] = None,
    findings: Sequence[Finding] = (),
    value_deps_by_rank: Optional[Dict[int, set]] = None,
    detach_threshold: Optional[int] = None,
    coalesce_bytes: Optional[int] = None,
    bucket_bytes: Optional[int] = None,
    max_group: int = _deps.MAX_GROUP,
    aggressive: bool = True,
    force_trivial: bool = False,
) -> ExecutionPlan:
    """Compile per-rank schedules into an (unproven) execution plan.

    ``findings`` is the verification report's finding list: error-level
    findings and statically-unresolvable schedules (control divergence,
    comm-in-while, token violations) make the program unplannable, and a
    recalibrated ``order_critical_exchange`` — true cross-rank ordering
    dependence — leaves the schedule unrewritten (trivial plan).

    ``aggressive=False`` builds the fallback plan: groups and marks but
    no recv hoisting (used when the prover rejects the hoisted plan).
    """
    if world_size is None:
        world_size = len(events_by_rank)
    if detach_threshold is None:
        detach_threshold = _match.default_detach_threshold()
    if coalesce_bytes is None:
        coalesce_bytes = default_coalesce_bytes()
    if bucket_bytes is None:
        bucket_bytes = default_bucket_bytes()
    plan = ExecutionPlan(
        world_size=world_size,
        cache_key=schedule_cache_key(events_by_rank, world_size),
        detach_threshold=detach_threshold,
        coalesce_bytes=coalesce_bytes,
        bucket_bytes=bucket_bytes,
    )

    blockers = sorted(
        {f.kind for f in findings
         if f.severity == "error" or f.kind in UNPLANNABLE_KINDS}
    )
    pinned = any(f.kind == "order_critical_exchange" for f in findings)
    # the runtime runner serves the WORLD communicator only: a schedule
    # that communicates on sub-comms would desync its cursor (sub-comm
    # ops bypass the world runner), so such programs stay unrewritten
    world_key = (0,)
    subcomms = any(
        tuple(ev.comm) != world_key
        for events in events_by_rank.values() for ev in events
    )
    trivial = bool(blockers) or pinned or subcomms or force_trivial
    if blockers:
        plan.reasons.append(
            "unplannable schedule: " + ", ".join(blockers)
        )
    if pinned:
        plan.reasons.append(
            "order-critical exchange: true cross-rank ordering "
            "dependence — schedule left unrewritten"
        )
    if subcomms and not (blockers or pinned or force_trivial):
        plan.reasons.append(
            "sub-communicator schedule: plan execution serves the "
            "world communicator only — schedule left unrewritten"
        )

    for rank, events in sorted(events_by_rank.items()):
        ops = [PlanOp.from_event(ev) for ev in events]
        for pos, op in enumerate(ops):
            # positions are the plan's coordinate system; re-number so a
            # truncated/merged extraction cannot desync the groups
            op.idx = pos
            op.post_at = pos
        if trivial:
            groups = [[i] for i in range(len(ops))]
        else:
            vdeps = (value_deps_by_rank or {}).get(rank)
            deps = _deps.build_rank_deps(events, value_deps=vdeps)
            groups = _deps.concurrency_groups(events, deps,
                                              max_group=max_group)
            # never hoist on a channel that ANYWHERE in the schedule
            # also carries a Status or wildcard receive: a pre-posted
            # strict descriptor owns the next wire message on its
            # channel, and mixing it with flexible receives is exactly
            # the reconciliation the runtime fallback cannot do safely
            wild_comms = set()
            status_channels = set()
            for ev in events:
                if ev.source == _deps.ANY_SOURCE:
                    wild_comms.add(ev.comm)
                elif ev.status and ev.kind in ("recv", "sendrecv"):
                    status_channels.add((ev.comm, ev.source))
            for pos, op in enumerate(ops):
                if op.kind == "send":
                    op.deferred = True
                if (aggressive and op.kind == "recv"
                        and op.comm not in wild_comms
                        and (op.comm, op.source) not in status_channels):
                    op.post_at = _deps.recv_post_point(events, deps, pos)
            _mark_coalesce(ops, min(coalesce_bytes, detach_threshold))
            _mark_buckets(ops, bucket_bytes)
        for gid, members in enumerate(groups):
            for pos in members:
                ops[pos].group = gid
        plan.ranks[rank] = RankPlan(rank=rank, ops=ops, groups=groups)
    return plan


# ---------------------------------------------------------------------------
# equivalence prover


def _planned_order(events: List[CommEvent], rp: RankPlan) -> List[int]:
    """Positions of ``events`` in planned wire order.

    A hoisted recv (``post_at = p < idx``) is posted immediately after
    op ``p``'s own post, so its wire slot sits between ``p`` and
    ``p + 1``; the FIFO progress engine makes post order the wire order.
    For the common temporal hoist (``p == idx - 1``) the order is
    unchanged — only the *time* of the post moves earlier, into the
    host-compute gap.  Multiple hoists to one point keep their original
    relative order."""
    keys = []
    for pos in range(len(events)):
        op = rp.ops[pos]
        if op.hoisted:
            keys.append((op.post_at + 0.5, pos))
        else:
            keys.append((float(pos), pos))
    return [pos for _, pos in sorted(keys)]


def _apply_perm(order: List[int], members: List[int],
                perm: Tuple[int, ...]) -> List[int]:
    """Reorder ``members`` (original positions) within ``order`` slots."""
    slots = [order.index(m) for m in members]
    out = list(order)
    for slot, m in zip(sorted(slots), perm):
        out[slot] = m
    return out


def _simulate(events_by_rank, comms, orders,
              service_order=None) -> Tuple[set, dict]:
    schedules = {
        r: [events_by_rank[r][pos] for pos in orders[r]]
        for r in events_by_rank
    }
    deliv: dict = {}
    findings = _match.match_schedules(schedules, comms, deliveries=deliv,
                                      service_order=service_order)
    return {f.kind for f in findings}, deliv


def _group_interleavings(events, members: List[int]) -> List[Tuple[int, ...]]:
    """Every completion order a concurrency group can exhibit at run
    time.  The FIFO progress engine pins the relative wire order of
    same-engine members to post order, so the realizable orders are the
    riffles of the per-engine-root subsequences (identity excluded).

    NOTE: today ``build_plan`` leaves sub-communicator schedules
    unrewritten, so every compilable plan's events share one engine
    root and this returns [] — the realizable set is the singleton post
    order, and the proof reduces to planned order + rank-service
    rotations.  The riffle machinery is the contract a future
    multi-engine (or out-of-order-engine) planner must re-enter, and
    the unit tests pin it with hand-built foreign-engine events."""
    by_root: Dict[Tuple, List[int]] = {}
    for m in members:
        by_root.setdefault(_deps._engine_root(events[m].comm), []).append(m)
    seqs = list(by_root.values())
    if len(seqs) == 1:
        return []  # one engine: post order IS the only realizable order

    def riffle(parts: List[List[int]]):
        if all(not p for p in parts):
            yield ()
            return
        for i, p in enumerate(parts):
            if not p:
                continue
            rest = [list(q) for q in parts]
            head = rest[i].pop(0)
            for tail in riffle(rest):
                yield (head,) + tail

    return [perm for perm in riffle([list(s) for s in seqs])
            if list(perm) != members]


def prove_plan(
    events_by_rank: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    plan: ExecutionPlan,
    max_interleavings: int = MAX_INTERLEAVINGS,
) -> bool:
    """Replay original and planned schedules through the match simulator.

    Configurations explored:

    - the planned wire order itself (hoists applied);
    - for every concurrency group, every completion order the execution
      substrate can realize (the FIFO progress engine pins same-engine
      members to post order; members on different engine roots riffle
      freely), with all other groups at planned order;
    - every rotation of the simulator's rank-service order, which
      exposes matches that depend on which rank happens to progress
      first (ANY_SOURCE races).

    The plan is accepted only if every replay (a) produces no finding
    kind the original schedule did not, and (b) delivers the same
    messages in the same per-channel order — which pins delivered
    values, since payload content rides sends unchanged.  A replay that
    stalls shows up as (a): deadlock/unmatched kinds.  Sets
    ``plan.proved`` and ``plan.proof``.
    """
    ranks = sorted(events_by_rank)
    base_orders = {r: list(range(len(v)))
                   for r, v in events_by_rank.items()}
    base_kinds, base_deliv = _simulate(events_by_rank, comms, base_orders)
    planned = {r: _planned_order(events_by_rank[r], plan.ranks[r])
               for r in events_by_rank}

    # (orders, service_order) configurations
    configs: List[Tuple[Dict[int, List[int]], Optional[List[int]]]] = [
        (planned, None)
    ]
    for rank in ranks:
        rp = plan.ranks[rank]
        for members in rp.groups:
            if len(members) < 2:
                continue
            for perm in _group_interleavings(events_by_rank[rank],
                                             members):
                orders = dict(planned)
                orders[rank] = _apply_perm(planned[rank], members, perm)
                configs.append((orders, None))
    for shift in range(1, len(ranks)):
        rotated = ranks[shift:] + ranks[:shift]
        configs.append((planned, rotated))

    exhaustive = len(configs) <= max_interleavings
    if not exhaustive:
        configs = configs[:max_interleavings]

    failures: List[str] = []
    for i, (orders, service) in enumerate(configs):
        kinds, deliv = _simulate(events_by_rank, comms, orders,
                                 service_order=service)
        new_kinds = kinds - base_kinds
        if new_kinds:
            failures.append(
                f"interleaving {i}: new finding kind(s) "
                f"{sorted(new_kinds)}"
            )
        elif deliv != base_deliv:
            failures.append(
                f"interleaving {i}: per-channel delivery order changed"
            )
        if failures:
            break

    plan.proof = {
        "interleavings": len(configs),
        "exhaustive": exhaustive,
        "base_finding_kinds": sorted(base_kinds),
        "failures": failures,
    }
    plan.proved = not failures and exhaustive
    if failures:
        plan.reasons.extend(failures)
    elif not exhaustive:
        plan.reasons.append(
            f"interleaving budget exceeded ({max_interleavings}); "
            "plan rejected unproven"
        )
    return plan.proved


def compile_schedules(
    events_by_rank: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    *,
    findings: Sequence[Finding] = (),
    world_size: Optional[int] = None,
    value_deps_by_rank: Optional[Dict[int, set]] = None,
    detach_threshold: Optional[int] = None,
    coalesce_bytes: Optional[int] = None,
    bucket_bytes: Optional[int] = None,
    max_interleavings: int = MAX_INTERLEAVINGS,
) -> ExecutionPlan:
    """Build the most aggressive provable plan: try hoisting + grouping,
    fall back to no-hoist, then to the trivial (unrewritten) plan.  The
    returned plan always carries ``proved`` and the downgrade reasons —
    an unsafe rewrite is *demonstrably* rejected, never silently run."""
    kw = dict(
        world_size=world_size, findings=findings,
        value_deps_by_rank=value_deps_by_rank,
        detach_threshold=detach_threshold, coalesce_bytes=coalesce_bytes,
        bucket_bytes=bucket_bytes,
    )
    plan = build_plan(events_by_rank, comms, aggressive=True, **kw)
    if prove_plan(events_by_rank, comms, plan, max_interleavings):
        return plan
    rejected_reasons = list(plan.reasons)

    fallback = build_plan(events_by_rank, comms, aggressive=False, **kw)
    fallback.reasons = rejected_reasons + [
        "hoisted plan rejected by the equivalence prover; "
        "retrying without recv hoisting"
    ]
    if prove_plan(events_by_rank, comms, fallback, max_interleavings):
        fallback.reasons = [r for r in fallback.reasons
                            if not r.startswith("interleaving ")]
        return fallback

    trivial = build_plan(events_by_rank, comms, aggressive=False,
                         force_trivial=True, **kw)
    trivial.reasons = [
        "grouped plan rejected by the equivalence prover; "
        "schedule left unrewritten"
    ]
    prove_plan(events_by_rank, comms, trivial, max_interleavings)
    return trivial


# ---------------------------------------------------------------------------
# plan cache (per jaxpr/schedule hash)


def plan_cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "mpi4jax_tpu", "plans")


def plan_cache_path(cache_key: str) -> str:
    return os.path.join(plan_cache_dir(), f"{cache_key}.json")


def save_plan(plan: ExecutionPlan, path: Optional[str] = None) -> str:
    path = path or plan_cache_path(plan.cache_key)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(plan.to_json(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_plan(path: str) -> ExecutionPlan:
    with open(path) as f:
        data = json.load(f)
    plan = ExecutionPlan.from_json(data)
    if plan.analyzer_version != ANALYZER_VERSION:
        raise ValueError(
            f"plan at {path} was compiled by analyzer "
            f"{plan.analyzer_version!r}, this is {ANALYZER_VERSION!r} — "
            "recompile (the cache key embeds the version exactly so "
            "stale plans invalidate instead of misexecuting)"
        )
    return plan


def cached_plan(cache_key: str) -> Optional[ExecutionPlan]:
    """The cached verified plan for a schedule hash, or None (missing,
    unreadable, version-mismatched, or never proved)."""
    path = plan_cache_path(cache_key)
    try:
        plan = load_plan(path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return None
    if plan.cache_key != cache_key or not plan.proved:
        return None
    return plan
