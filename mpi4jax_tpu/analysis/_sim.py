"""Virtual-world executor: run a per-rank program under analysis.

Executes the *whole program* once per rank — each rank a thread inside one
process — with every world-tier op intercepted at the primitive-impl layer
and served by an in-memory matcher instead of the native transport:

- no processes are spawned and no live communication is created (sockets,
  shm, the native library are never touched);
- values are real: collectives/point-to-point compute their actual numpy
  semantics, so known-good programs' assertions pass and the verdict
  "clean" means the full program ran;
- everything runs under ``jax.disable_jit`` so each op executes eagerly on
  its rank's thread in exact program order — the analyzer sees the true
  per-rank schedule (including data-dependent trip counts) and can name
  the source line of every event;
- matching failures (tag/dtype/shape mismatch, divergent collectives) are
  findings mirroring the native transport's fail-fast aborts; a global
  stall is classified by the wait graph (deadlock cycles, unmatched ops,
  wildcard starvation) in milliseconds instead of a runtime deadline.

The conservative schedule-level passes (``order_critical_findings``) run
on the recorded schedules afterwards, so hazards that do not bite under
correct ordering are still reported.
"""

from __future__ import annotations

import contextlib
import io
import os
import sys
import threading
import time
import traceback
from collections import deque

import numpy as np

from . import _match
from ._events import ANY_SOURCE, ANY_TAG, CommEvent, Finding, Report
from ._fake import AbstractComm, AnalysisError


class SimAbort(RuntimeError):
    """Raised inside a rank thread when the virtual world aborts the job
    (mirrors the native transport's fail-fast poison cascade)."""


_NP_COMBINE = {
    "SUM": np.add, "PROD": np.multiply,
    "MAX": np.maximum, "MIN": np.minimum,
    "LAND": np.logical_and, "LOR": np.logical_or, "LXOR": np.logical_xor,
    "BAND": np.bitwise_and, "BOR": np.bitwise_or, "BXOR": np.bitwise_xor,
}


def _fold(op_name, arrays):
    uf = _NP_COMBINE[op_name]
    out = arrays[0]
    for a in arrays[1:]:
        out = uf(out, a)
    return np.asarray(out, dtype=arrays[0].dtype)


class _TokenCtx:
    """Per-rank-thread pseudo-trace for the chain guard, keeping analyzed
    tokens alive so id()-keyed tracking cannot alias."""

    __slots__ = ("refs", "__weakref__")

    def __init__(self):
        self.refs = []


class VirtualWorld:
    """One analysis run of ``program`` at world size ``size``."""

    def __init__(self, size: int, program: str, timeout_s=None, argv=None):
        from ..utils import config

        self.size = int(size)
        self.program = os.path.abspath(program)
        self.argv = list(argv or ())
        if timeout_s is None:
            timeout_s = config.analyze_timeout_s()
        # 0 = no deadline, matching MPI4JAX_TPU_TIMEOUT_S's convention
        self.timeout_s = float(timeout_s)
        self.cv = threading.Condition()
        self.channels = {}      # (comm_key, src_w, dst_w) -> deque
        self.schedules = {r: [] for r in range(self.size)}
        self.comms = {(0,): tuple(range(self.size))}
        self.findings = []
        self._finding_keys = set()
        self.state = {r: "init" for r in range(self.size)}
        self.blocked = {}       # rank -> (CommEvent, waits_on_fn)
        self.coll = {}          # (comm_key, seq) -> {rank: (event, payload)}
        self.coll_results = {}  # (comm_key, seq) -> {rank: value}
        self.coll_counter = {}  # (rank, comm_key) -> int
        self.aborted = False
        self._token_ctx = {}    # thread ident -> _TokenCtx

    # -- executor protocol (ops/_world_impl hooks) ----------------------

    def owns(self, comm) -> bool:
        # require OUR session: a rank thread leaked by a timed-out earlier
        # run must not inject events into a later run's world
        return isinstance(comm, AbstractComm) and comm._session is self

    def run_primitive(self, prim_name, args, params):
        from ..ops import _world_impl

        sig = _world_impl.schedule_signature(prim_name)
        if sig is None:
            raise AnalysisError(
                f"no schedule signature for primitive {prim_name!r}")
        base, spec, _ = sig
        comm = params["comm"]
        data = np.asarray(args[0]) if args else None
        event = self._make_event(base, spec, comm, data, params)
        with self.cv:
            self.schedules[event.rank].append(event)
        value = self._dispatch(event, comm, data, params)
        # hand jax back a jax array: downstream code (and jax internals)
        # expect op results to be Arrays, not bare numpy
        import jax.numpy as jnp

        return jnp.asarray(value)

    def _make_event(self, base, spec, comm, data, params):
        world_rank = comm.members[comm.rank()]
        fields = {}
        for field, pname in spec.items():
            if field == "kind":
                continue
            value = params.get(pname)
            if field == "reduce_op" and value is not None:
                value = value.name
            fields[field] = value
        return CommEvent(
            rank=world_rank,
            idx=len(self.schedules[world_rank]),
            kind=spec["kind"],
            comm=comm.key,
            dtype=None if data is None else str(data.dtype),
            shape=None if data is None else tuple(data.shape),
            site=self._site(),
            status=params.get("status") is not None,
            **fields,
        )

    def _site(self) -> str:
        # walk raw frames (cheap) instead of materializing the whole
        # stack per event: the DEEPEST frame in the analyzed file wins
        import linecache

        frame = sys._getframe(1)
        best = None
        while frame is not None:
            if frame.f_code.co_filename == self.program:
                best = (frame.f_lineno,)
                break  # walking outward: first hit IS the deepest
            frame = frame.f_back
        if best is None:
            return "<analysis>"
        lineno = best[0]
        text = linecache.getline(self.program, lineno).strip()
        loc = f"{os.path.basename(self.program)}:{lineno}"
        return f"{loc} `{text[:70]}`" if text else loc

    # -- op dispatch ----------------------------------------------------

    def _dispatch(self, event, comm, data, params):
        kind = event.kind
        if kind == "send":
            self._push_send(event, comm, event.dest, data)
            return np.zeros((), np.int32)
        if kind == "recv":
            payload, src_local, tag, nbytes = self._complete_recv(
                event, comm, event.source, event.tag)
            self._fill_status(params, src_local, tag, nbytes)
            return payload
        if kind == "sendrecv":
            send_part = CommEvent(
                rank=event.rank, idx=event.idx, kind="send",
                comm=event.comm, dest=event.dest, tag=event.sendtag,
                dtype=event.dtype, shape=event.shape, site=event.site)
            self._push_send(send_part, comm, event.dest, data)
            payload, src_local, tag, nbytes = self._complete_recv(
                event, comm, event.source, event.recvtag)
            self._fill_status(params, src_local, tag, nbytes)
            return payload
        if kind == "shift2":
            return self._do_shift2(event, comm, data)
        if kind == "barrier":
            self._do_collective(event, comm, None)
            return np.zeros((), np.int32)
        return self._do_collective(event, comm, data)

    @staticmethod
    def _fill_status(params, src_local, tag, nbytes):
        status = params.get("status")
        if status is not None:
            status.obj._fill(src_local, tag, nbytes)

    def _push_send(self, event, comm, dest_local, payload):
        with self.cv:
            self._raise_if_aborted()
            dst_w = comm.members[dest_local]
            key = (comm.key, event.rank, dst_w)
            self.channels.setdefault(key, deque()).append((payload, event))
            self.cv.notify_all()

    def _complete_recv(self, event, comm, source_local, tag):
        me = event.rank
        with self.cv:
            while True:
                self._raise_if_aborted()
                got = self._match_recv_locked(event, comm, source_local,
                                              tag)
                if got is not None:
                    self._set_running(me)
                    payload, send_ev, src_w = got
                    return (payload, comm.members.index(src_w),
                            send_ev.tag,
                            0 if payload is None else payload.nbytes)
                self._block(me, event,
                            ("recv", comm, source_local, tag))
                self._stall_check_locked()
                self.cv.wait(0.05)

    def _match_recv_locked(self, event, comm, source_local, tag):
        me = event.rank
        if source_local == ANY_SOURCE:
            for src_w in comm.members:  # self-sends are legal; scan all
                q = self.channels.get((comm.key, src_w, me))
                if not q:
                    continue
                head_payload, head_ev = q[0]
                if tag not in (None, ANY_TAG) and head_ev.tag != tag:
                    continue  # wildcard scan skips incompatible heads
                q.popleft()
                self._settle_match(head_ev, event)
                return head_payload, head_ev, src_w
            return None
        src_w = comm.members[source_local]
        q = self.channels.get((comm.key, src_w, me))
        if not q:
            return None
        # strict in-order channel: the head IS the match; any field
        # disagreement is a fail-fast program error (native abort)
        head_payload, head_ev = q.popleft()
        self._settle_match(head_ev, event)
        return head_payload, head_ev, src_w

    def _settle_match(self, send_ev, recv_ev):
        found = _match.compare_p2p(send_ev, recv_ev)
        if found:
            self._record_locked(found)
            self._abort_locked()
            raise SimAbort(found[0].message)

    def _do_shift2(self, event, comm, data):
        me = event.rank
        out = [None, None]
        for i, peer in enumerate((event.lo, event.hi)):
            if peer is None or peer < 0:
                continue
            send_part = CommEvent(
                rank=me, idx=event.idx, kind="send", comm=event.comm,
                dest=peer, tag=event.tag,
                dtype=event.dtype, shape=event.shape, site=event.site)
            self._push_send(send_part, comm, peer, data[i])
        with self.cv:
            for i, peer in enumerate((event.lo, event.hi)):
                if peer is None or peer < 0:
                    # wall: passthrough of the opposite input strip
                    out[i] = data[1 - i]
                    continue
                src_w = comm.members[peer]
                while True:
                    self._raise_if_aborted()
                    q = self.channels.get((comm.key, src_w, me))
                    if q:
                        payload, send_ev = q.popleft()
                        self._settle_match(send_ev, event)
                        out[i] = payload
                        break
                    self._block(me, event, ("recv", comm, peer, event.tag))
                    self._stall_check_locked()
                    self.cv.wait(0.05)
            self._set_running(me)
        return np.stack(out)

    def _do_collective(self, event, comm, payload):
        me = event.rank
        with self.cv:
            self._raise_if_aborted()
            seq = self.coll_counter.get((me, comm.key), 0)
            self.coll_counter[(me, comm.key)] = seq + 1
            gkey = (comm.key, seq)
            group = self.coll.setdefault(gkey, {})
            group[me] = (event, payload)
            members = comm.members
            if set(group) == set(members):
                events = [group[m][0] for m in members]
                found = _match.compare_collective(events)
                if found:
                    self._record_locked(found)
                    self._abort_locked()
                    raise SimAbort(found[0].message)
                self.coll_results[gkey] = self._compute_collective(
                    gkey, members)
                self.cv.notify_all()
            else:
                while gkey not in self.coll_results:
                    self._block(me, event, ("coll", gkey, members))
                    self._stall_check_locked()
                    self.cv.wait(0.05)
                    self._raise_if_aborted()
            self._set_running(me)
            results = self.coll_results[gkey]
            value = results.pop(me)
            if not results:
                del self.coll_results[gkey]
                del self.coll[gkey]
            return value

    def _compute_collective(self, gkey, members):
        group = self.coll[gkey]
        kind = group[members[0]][0].kind
        stack = [np.asarray(group[m][1]) for m in members
                 if group[m][1] is not None]
        out = {}
        if kind == "barrier":
            for m in members:
                out[m] = np.zeros((), np.int32)
        elif kind == "allreduce":
            red = _fold(group[members[0]][0].reduce_op, stack)
            for m in members:
                out[m] = red
        elif kind == "reduce":
            root_ev = group[members[0]][0]
            red = _fold(root_ev.reduce_op, stack)
            for i, m in enumerate(members):
                out[m] = red if i == root_ev.root else np.asarray(
                    group[m][1])
        elif kind == "scan":
            op = group[members[0]][0].reduce_op
            for i, m in enumerate(members):
                out[m] = _fold(op, stack[:i + 1])
        elif kind == "bcast":
            root = group[members[0]][0].root
            val = np.asarray(group[members[root]][1])
            for m in members:
                out[m] = val
        elif kind == "allgather":
            val = np.stack(stack)
            for m in members:
                out[m] = val
        elif kind == "gather":
            root = group[members[0]][0].root
            val = np.stack(stack)
            for i, m in enumerate(members):
                out[m] = val if i == root else np.asarray(group[m][1])
        elif kind == "scatter":
            root = group[members[0]][0].root
            rows = np.asarray(group[members[root]][1])
            for i, m in enumerate(members):
                out[m] = rows[i]
        elif kind == "alltoall":
            for i, m in enumerate(members):
                out[m] = np.stack(
                    [np.asarray(group[mj][1])[i] for mj in members])
        else:  # split/dup rendezvous values are computed by the caller
            for m in members:
                out[m] = None
        return out

    # -- comm management (FakeComm.split/dup route here) ----------------

    def split_collective(self, comm, color, key, _dup=False):
        comm._split_seq += 1
        seq = comm._split_seq
        me_local = comm.rank()
        me_world = comm.members[me_local]
        sort_key = me_local if key is None else int(key)
        event = CommEvent(
            rank=me_world, idx=len(self.schedules[me_world]),
            kind="split", comm=comm.key, site=self._site())
        with self.cv:
            self.schedules[me_world].append(event)
            gkey = (comm.key, "split", seq)
            group = self.coll.setdefault(gkey, {})
            group[me_world] = (event, (color, sort_key, me_local))
            members = comm.members
            if set(group) == set(members):
                results = {}
                by_color = {}
                for m in members:
                    c, k, loc = group[m][1]
                    if c < 0:
                        results[m] = None
                        continue
                    by_color.setdefault(c, []).append((k, loc, m))
                for c, entries in by_color.items():
                    entries.sort()
                    sub_members = tuple(m for _, _, m in entries)
                    new_key = comm.key + (seq, c)
                    self.comms[new_key] = sub_members
                    for sub_rank, (_, _, m) in enumerate(entries):
                        results[m] = (new_key, sub_members, sub_rank)
                self.coll_results[gkey] = results
                self.cv.notify_all()
            else:
                while gkey not in self.coll_results:
                    self._block(me_world, event, ("coll", gkey, members))
                    self._stall_check_locked()
                    self.cv.wait(0.05)
                    self._raise_if_aborted()
            self._set_running(me_world)
            results = self.coll_results[gkey]
            mine = results.pop(me_world)
            if not results:
                del self.coll_results[gkey]
                del self.coll[gkey]
        if mine is None:
            return None
        new_key, sub_members, sub_rank = mine
        return AbstractComm(sub_rank, len(sub_members), key=new_key,
                            members=sub_members, session=self)

    def dup_collective(self, comm):
        return self.split_collective(comm, 0, None, _dup=True)

    # -- chain-guard hooks ----------------------------------------------

    def _token_trace(self, tok=None):
        ident = threading.get_ident()
        ctx = self._token_ctx.get(ident)
        if ctx is None:
            ctx = self._token_ctx[ident] = _TokenCtx()
        if tok is not None:
            ctx.refs.append(tok)
        return ctx

    def _token_warn(self, comm, n_heads, how):
        rank = None
        if isinstance(comm, AbstractComm):
            rank = comm.members[comm.rank()]
        finding = Finding(
            "token_violation",
            f"a world op on {comm!r} is {how} while {n_heads} other token "
            "chain(s) on the same comm are live — relative order is "
            "UNDEFINED in explicit-token mode and can deadlock",
            ranks=() if rank is None else (rank,),
            comm=comm.key if isinstance(comm, AbstractComm) else (),
            sites=(self._site(),),
        )
        with self.cv:
            self._record_locked([finding])

    # -- bookkeeping ----------------------------------------------------

    def _record_locked(self, findings):
        for f in findings:
            key = (f.kind, f.ranks, f.comm, f.message)
            if key in self._finding_keys:
                continue
            self._finding_keys.add(key)
            self.findings.append(f)

    def _raise_if_aborted(self):
        if self.aborted:
            raise SimAbort("virtual world aborted")

    def _abort_locked(self):
        self.aborted = True
        self.cv.notify_all()

    def _block(self, rank, event, info):
        self.state[rank] = "blocked"
        self.blocked[rank] = (event, info)

    def _set_running(self, rank):
        self.state[rank] = "running"
        self.blocked.pop(rank, None)

    def _satisfiable_locked(self, event, info) -> bool:
        """Fresh check: can this blocked op still make progress?"""
        kind = info[0]
        if kind == "recv":
            comm, source_local, tag = info[1], info[2], info[3]
            me = event.rank
            if source_local == ANY_SOURCE:
                for src_w in comm.members:
                    q = self.channels.get((comm.key, src_w, me))
                    if q and (tag in (None, ANY_TAG)
                              or q[0][1].tag == tag):
                        return True
                return False
            return bool(self.channels.get(
                (comm.key, comm.members[source_local], me)))
        if kind == "coll":
            gkey, members = info[1], info[2]
            if gkey in self.coll_results:
                return True  # result computed, pickup pending
            group = self.coll.get(gkey, {})
            return set(group) == set(members)
        return True  # unknown: never declare a stall on it

    def _waits_on_locked(self, event, info):
        kind = info[0]
        if kind == "recv":
            comm, source_local = info[1], info[2]
            if source_local == ANY_SOURCE:
                return tuple(m for m in comm.members if m != event.rank)
            return (comm.members[source_local],)
        if kind == "coll":
            gkey, members = info[1], info[2]
            group = self.coll.get(gkey, {})
            return tuple(m for m in members if m not in group)
        return ()

    def _stall_check_locked(self):
        """Declare a stall only when it is PROVEN: nobody is running and
        no blocked op can make progress.  Every predicate is re-evaluated
        fresh under the lock — state captured at block time can be stale
        (a result may be computed but not yet picked up)."""
        if self.aborted:
            return
        if any(s in ("init", "running") for s in self.state.values()):
            return
        blocked = {r: be for r, be in self.blocked.items()
                   if self.state[r] == "blocked"}
        if not blocked:
            return
        if any(self._satisfiable_locked(ev, info)
               for ev, info in blocked.values()):
            return
        blocked_evs = {r: ev for r, (ev, _) in blocked.items()}
        waits_on = {r: self._waits_on_locked(ev, info)
                    for r, (ev, info) in blocked.items()}
        done = frozenset(r for r, s in self.state.items()
                         if s in ("done", "failed"))
        found = _match.wait_graph_findings(blocked_evs, waits_on, done)
        if found:
            self._record_locked(found)
        self._abort_locked()

    def _record_rank_error(self, rank, err):
        site = ""
        if isinstance(err, BaseException):
            for frame in traceback.extract_tb(err.__traceback__):
                if os.path.abspath(frame.filename) == self.program:
                    site = (f"{os.path.basename(frame.filename)}:"
                            f"{frame.lineno} `{(frame.line or '').strip()[:70]}`")
            message = (f"rank {rank} raised "
                       f"{type(err).__name__}: {err}")
        else:
            message = f"rank {rank} {err}"
        with self.cv:
            self._record_locked([Finding(
                "rank_error", message, ranks=(rank,),
                sites=(site,) if site else (),
            )])

    # -- the run --------------------------------------------------------

    def _rank_main(self, rank, code):
        from ..parallel import mesh

        comm = AbstractComm(rank, self.size, key=(0,),
                            members=tuple(range(self.size)), session=self)
        mesh._push_comm(comm)
        with self.cv:
            self.state[rank] = "running"
        ok = False
        g = {"__name__": "__main__", "__file__": self.program,
             "__builtins__": __builtins__}
        try:
            exec(code, g)
            ok = True
        except SystemExit as e:
            ok = e.code in (None, 0)
            if not ok:
                self._record_rank_error(rank, f"exited with code {e.code}")
        except SimAbort:
            pass  # the abort's cause is already a finding
        except BaseException as e:  # noqa: BLE001 - report, then classify
            self._record_rank_error(rank, e)
        finally:
            with self.cv:
                self.state[rank] = "done" if ok else "failed"
                self.blocked.pop(rank, None)
                self.cv.notify_all()
                self._stall_check_locked()

    def run(self) -> Report:
        import jax

        from ..ops import _world_impl

        with open(self.program) as f:
            src = f.read()
        code = compile(src, self.program, "exec")
        old_disable = bool(jax.config.jax_disable_jit)
        # programs mutate process-global jax config at import (x64 is the
        # common one); snapshot so one analyzed program cannot leak into
        # the next run in this process
        old_x64 = bool(jax.config.jax_enable_x64)
        # the program sees its own argv, exactly as under the launcher
        old_argv = sys.argv
        sys.argv = [self.program] + self.argv
        jax.config.update("jax_disable_jit", True)
        _world_impl._set_analysis_executor(self)
        _world_impl._set_analysis_token_hooks(self._token_trace,
                                              self._token_warn)
        out_buf = io.StringIO()
        threads = [
            threading.Thread(target=self._rank_main, args=(r, code),
                             daemon=True, name=f"analysis-rank-{r}")
            for r in range(self.size)
        ]
        t0 = time.monotonic()
        try:
            with contextlib.redirect_stdout(out_buf), \
                    contextlib.redirect_stderr(out_buf):
                for t in threads:
                    t.start()
                if self.timeout_s > 0:
                    deadline = t0 + self.timeout_s
                    for t in threads:
                        t.join(max(0.1, deadline - time.monotonic()))
                else:  # 0 = no deadline (the stall detector still runs)
                    for t in threads:
                        t.join()
                if any(t.is_alive() for t in threads):
                    with self.cv:
                        self._record_locked([Finding(
                            "analysis_timeout",
                            f"virtual world did not finish within "
                            f"{self.timeout_s:g}s; rank states: "
                            f"{dict(sorted(self.state.items()))}",
                        )])
                        self._abort_locked()
                    for t in threads:
                        t.join(2.0)
        finally:
            _world_impl._set_analysis_executor(None)
            _world_impl._set_analysis_token_hooks(None, None)
            sys.argv = old_argv
            jax.config.update("jax_disable_jit", old_disable)
            jax.config.update("jax_enable_x64", old_x64)
        with self.cv:
            if not self.aborted:
                seen_chan = set()
                for (ckey, s, d), q in self.channels.items():
                    if not q or (ckey, s, d) in seen_chan:
                        continue
                    seen_chan.add((ckey, s, d))
                    _, ev = q[0]
                    self._record_locked([Finding(
                        "unmatched_send",
                        f"rank {s} sends to rank {d} (tag {ev.tag}) but "
                        "no matching receive ever runs "
                        f"({len(q)} message(s) queued)",
                        ranks=(s, d), comm=ckey,
                        sites=(f"rank {s}: {ev.describe()}",),
                    )])
            self._record_locked(
                _match.order_critical_findings(self.schedules, self.comms))
        from ._events import schedule_cache_key

        return Report(
            world_size=self.size,
            target=self.program,
            findings=list(self.findings),
            schedules={r: [e.describe() for e in evs]
                       for r, evs in self.schedules.items()},
            output=out_buf.getvalue(),
            events=dict(self.schedules),
            comms=dict(self.comms),
            cache_key=schedule_cache_key(self.schedules, self.size),
        )
