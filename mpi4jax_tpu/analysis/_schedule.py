"""Static schedule extraction: closed jaxpr -> per-rank CommEvent list.

``trace_rank_schedule`` traces a user function once for one simulated rank
(abstract eval only — world primitives never execute, no comm exists) and
walks the closed jaxpr, including every sub-jaxpr a higher-order primitive
carries:

- ``pjit``/``closed_call``/``custom_jvp/vjp``/``remat``: inlined — each
  call site contributes its body's events in place, so the same inner
  function called twice is two schedule segments (exactly what executes);
- ``scan``: the body is unrolled ``length`` times (the trip count is
  static in the jaxpr);
- ``while``: the trip count is data-dependent — the body is walked once
  and a ``comm_in_while`` warning is attached when it communicates;
- ``cond``: branch schedules are compared; diverging communication is a
  ``control_divergence`` warning (branch 0 is assumed), since the taken
  branch cannot be known statically.

On top of extraction, a static token-discipline pass checks the
explicit-token wire format (the ``*_t`` primitives): every token-variant
equation on a comm must be reachable — through the value graph — from the
previous token-variant equation on that comm, else their relative order is
undefined (the reordered/forked-chain footgun); a tokenless world op bound
with the unordered effect amid live chains is flagged the same way.
"""

from __future__ import annotations

import os
from typing import List, Tuple

from ._events import CommEvent, Finding
from ._fake import AbstractComm

#: scan bodies are unrolled; cap the total extracted events per rank so a
#: million-step scan cannot stall analysis (a finding reports the cut).
MAX_EVENTS_PER_RANK = 20000


def _site_of(eqn, pos) -> str:
    label = f"eqn {pos} {eqn.primitive.name}"
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return (f"{os.path.basename(frame.file_name)}:"
                    f"{frame.start_line} ({label})")
    except Exception:
        pass
    return label


def _comm_key(comm):
    if isinstance(comm, AbstractComm):
        return comm.key
    lineage = getattr(comm, "_lineage", None)
    return tuple(lineage) if lineage is not None else ("comm", id(comm))


def _sub_jaxprs(params):
    """Generic recursion targets: every (Closed)Jaxpr in eqn params."""
    from jax._src import core as jcore

    out = []
    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if isinstance(item, jcore.ClosedJaxpr):
                out.append(item.jaxpr)
            elif isinstance(item, jcore.Jaxpr):
                out.append(item)
    return out


class _Extractor:
    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.events: List[CommEvent] = []
        self.findings: List[Finding] = []
        self.truncated = False
        #: jaxpr buffer use/def chains, reduced to event coordinates:
        #: (producer_pos, consumer_pos) pairs where the consumer's
        #: payload is computed from the producer's output.  Token
        #: operands/results are deliberately EXCLUDED from propagation —
        #: the token edge is the artificial serialization the schedule
        #: compiler (analysis._plan) is licensed to overlap across;
        #: these pairs are the true data dependencies it must keep.
        self.value_deps: set = set()
        self._var_deps = {}   # top-level Var -> frozenset of event pos

    # -- value-dependence bookkeeping (jaxpr buffer use/def chains) -----

    def _deps_of(self, invars, drop_token=False):
        from jax._src import core as jcore

        vs = [v for v in invars if isinstance(v, jcore.Var)]
        if drop_token and vs:
            vs = vs[:-1]  # trailing operand is the explicit token
        out = frozenset()
        for v in vs:
            out |= self._var_deps.get(v, frozenset())
        return out

    def _set_deps(self, outvars, deps, drop_token=False):
        from jax._src import core as jcore

        vs = list(outvars)
        if drop_token and len(vs) > 1:
            # the token result carries NO data dependence: the token edge
            # is the artificial serialization the plan may overlap across
            self._var_deps[vs[-1]] = frozenset()
            vs = vs[:-1]
        for v in vs:
            if isinstance(v, jcore.Var):
                self._var_deps[v] = deps

    # -- events ---------------------------------------------------------

    def _emit(self, eqn, pos, top=False):
        from ..ops import _world_impl

        sig = _world_impl.schedule_signature(eqn.primitive.name)
        if sig is None:
            return False
        base, spec, token_variant = sig
        params = eqn.params
        ins = self._deps_of(eqn.invars, drop_token=token_variant) \
            if top else frozenset()
        if params.get("transpose"):
            if top:  # identity pass: data flows through, no comm
                self._set_deps(eqn.outvars, ins, drop_token=token_variant)
            return True
        if len(self.events) >= MAX_EVENTS_PER_RANK:
            if not self.truncated:
                self.truncated = True
                self.findings.append(Finding(
                    "analysis_timeout",
                    f"rank {self.rank}: schedule longer than "
                    f"{MAX_EVENTS_PER_RANK} events; truncated",
                    ranks=(self.rank,),
                ))
            if top:
                self._set_deps(eqn.outvars, ins, drop_token=token_variant)
            return True
        comm = params.get("comm")
        fields = {}
        for field, pname in spec.items():
            if field == "kind":
                continue
            value = params.get(pname)
            if field == "reduce_op" and value is not None:
                value = value.name
            fields[field] = value
        dtype = shape = None
        data_vars = [v for v in eqn.invars
                     if hasattr(v, "aval") and hasattr(v.aval, "shape")]
        if token_variant and len(data_vars) > 1:
            data_vars = data_vars[:-1]  # trailing operand is the token
        if spec["kind"] not in ("barrier",) and data_vars:
            aval = data_vars[0].aval
            dtype = str(aval.dtype)
            shape = tuple(aval.shape)
        epos = len(self.events)
        self.events.append(CommEvent(
            rank=self.rank,
            idx=epos,
            kind=spec["kind"],
            comm=_comm_key(comm),
            dtype=dtype,
            shape=shape,
            site=_site_of(eqn, pos),
            status=params.get("status") is not None,
            **fields,
        ))
        if top:
            for d in ins:
                self.value_deps.add((d, epos))
            self._set_deps(eqn.outvars, ins | {epos},
                           drop_token=token_variant)
        return True

    # -- recursion ------------------------------------------------------

    def _absorb_region(self, eqn, before: int, top: bool):
        """Conservative value-dependence treatment of a higher-order
        region (scan/while/cond/opaque call) whose internal dataflow is
        not tracked var-by-var: the region's events are chained in order
        (no reordering inside), every event depends on the eqn's inputs,
        and the eqn's outputs depend on everything inside."""
        if not top:
            return
        after = len(self.events)
        ins = self._deps_of(eqn.invars)
        inside = list(range(before, after))
        for a, b in zip(inside, inside[1:]):
            self.value_deps.add((a, b))
        for e in inside:
            for d in ins:
                self.value_deps.add((d, e))
        self._set_deps(eqn.outvars, ins | set(inside))

    def _inline_call(self, eqn, sub, top: bool) -> bool:
        """Precise inlining for single-body call primitives (pjit,
        remat, custom_jvp/vjp bodies): outer operands map 1:1 onto the
        body's invars, so the use/def chains stay var-accurate through
        the call boundary instead of degrading to an opaque region."""
        from jax._src import core as jcore

        if len(sub.invars) != len(eqn.invars):
            return False
        if top:
            for outer, inner in zip(eqn.invars, sub.invars):
                if isinstance(outer, jcore.Var) and \
                        isinstance(inner, jcore.Var):
                    self._var_deps[inner] = self._var_deps.get(
                        outer, frozenset())
        self.walk(sub, top=top)
        if top:
            deps = self._deps_of(sub.outvars)
            outs = len(eqn.outvars)
            if len(sub.outvars) == outs:
                for outer, inner in zip(eqn.outvars, sub.outvars):
                    if isinstance(outer, jcore.Var):
                        self._var_deps[outer] = (
                            self._var_deps.get(inner, frozenset())
                            if isinstance(inner, jcore.Var)
                            else frozenset())
            else:
                self._set_deps(eqn.outvars, deps)
        return True

    def walk(self, jaxpr, top=True):
        self._token_pass(jaxpr)
        for pos, eqn in enumerate(jaxpr.eqns):
            if self.truncated:
                return
            if self._emit(eqn, pos, top=top):
                continue
            name = eqn.primitive.name
            params = eqn.params
            if name == "scan":
                body = params["jaxpr"].jaxpr
                length = int(params.get("length", 1))
                before = len(self.events)
                if length > 0:
                    self.walk(body, top=False)
                    per_iter = len(self.events) - before
                    if per_iter:
                        for _ in range(length - 1):
                            if self.truncated:
                                return
                            self.walk(body, top=False)
                self._absorb_region(eqn, before, top)
            elif name == "while":
                # runtime order is cond, body, cond, ... — one iteration
                # assumed: cond events first, then the body's
                before = len(self.events)
                cond = params.get("cond_jaxpr")
                if cond is not None:
                    self.walk(cond.jaxpr, top=False)
                self.walk(params["body_jaxpr"].jaxpr, top=False)
                if len(self.events) > before:
                    self.findings.append(Finding(
                        "comm_in_while",
                        f"rank {self.rank}: communication inside a while "
                        "loop — the trip count is data-dependent, one "
                        "iteration assumed; divergent per-rank trip "
                        "counts would deadlock at run time",
                        ranks=(self.rank,),
                        sites=(_site_of(eqn, pos),),
                    ))
                self._absorb_region(eqn, before, top)
            elif name == "cond":
                branches = params.get("branches", ())
                sub_schedules = []
                for br in branches:
                    sub = _Extractor(self.rank, self.world_size)
                    sub.walk(br.jaxpr)
                    sub_schedules.append(sub)
                sigs = [
                    tuple(
                        (e.kind, e.comm, e.dest, e.source, e.root,
                         e.tag, e.sendtag, e.recvtag, e.reduce_op,
                         e.dtype, e.shape)
                        for e in sub.events
                    )
                    for sub in sub_schedules
                ]
                if len(set(sigs)) > 1:
                    self.findings.append(Finding(
                        "control_divergence",
                        f"rank {self.rank}: cond branches carry different "
                        "communication schedules — the taken branch is "
                        "data-dependent, so ranks can diverge at run "
                        "time; branch 0 assumed for matching",
                        ranks=(self.rank,),
                        sites=(_site_of(eqn, pos),),
                    ))
                base = len(self.events)
                if sub_schedules:
                    chosen = sub_schedules[0]
                    for e in chosen.events:
                        e.idx = base + e.idx
                        self.events.append(e)
                    self.findings.extend(chosen.findings)
                self._absorb_region(eqn, base, top)
            else:
                subs = _sub_jaxprs(params)
                if not subs:
                    if top:  # pure compute: dataflow passes through
                        self._set_deps(eqn.outvars,
                                       self._deps_of(eqn.invars))
                    continue
                if len(subs) == 1 and self._inline_call(eqn, subs[0], top):
                    continue
                before = len(self.events)
                for sub in subs:
                    self.walk(sub, top=False)
                self._absorb_region(eqn, before, top)

    # -- static token discipline ---------------------------------------

    def _token_pass(self, jaxpr):
        """Flag reordered/unthreaded explicit-token chains in one jaxpr."""
        from ..ops import _world_impl

        producer = {}
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                producer[v] = eqn
        comm_eqns = []          # (pos, eqn, comm_key, is_token_variant)
        for pos, eqn in enumerate(jaxpr.eqns):
            sig = _world_impl.schedule_signature(eqn.primitive.name)
            if sig is None or eqn.params.get("transpose"):
                continue
            _, _, token_variant = sig
            if token_variant or eqn.params.get("ordered") is False:
                comm_eqns.append(
                    (pos, eqn, _comm_key(eqn.params.get("comm")),
                     token_variant))
        if len(comm_eqns) < 2:
            return

        from jax._src import core as jcore

        def _vars(eqn):
            return [v for v in eqn.invars
                    if isinstance(v, jcore.Var) and v in producer]

        ancestor_cache = {}

        def comm_ancestors(eqn):
            key = id(eqn)
            if key in ancestor_cache:
                return ancestor_cache[key]
            ancestor_cache[key] = acc = set()
            stack = _vars(eqn)
            seen = set()
            while stack:
                v = stack.pop()
                if v in seen:
                    continue
                seen.add(v)
                parent = producer.get(v)
                if parent is None:
                    continue
                from ..ops import _world_impl as wi

                if wi.schedule_signature(parent.primitive.name):
                    acc.add(id(parent))
                acc |= comm_ancestors(parent)
                stack.extend(_vars(parent))
            return acc

        prev_by_comm = {}
        for pos, eqn, ckey, token_variant in comm_eqns:
            prev = prev_by_comm.get(ckey)
            if prev is not None:
                prev_pos, prev_eqn = prev
                if not token_variant:
                    self.findings.append(Finding(
                        "token_violation",
                        f"rank {self.rank}: a tokenless world op runs "
                        "with the unordered effect while explicit token "
                        "chains are live on the same comm — its order "
                        "against them is undefined",
                        ranks=(self.rank,), comm=ckey,
                        sites=(_site_of(eqn, pos),
                               _site_of(prev_eqn, prev_pos)),
                    ))
                elif id(prev_eqn) not in comm_ancestors(eqn):
                    self.findings.append(Finding(
                        "token_violation",
                        f"rank {self.rank}: two world ops on the same "
                        "comm sit on unconnected token chains — their "
                        "relative order is undefined and can deadlock "
                        "(thread the previous op's token, or root a new "
                        "chain with create_token(x))",
                        ranks=(self.rank,), comm=ckey,
                        sites=(_site_of(eqn, pos),
                               _site_of(prev_eqn, prev_pos)),
                    ))
            prev_by_comm[ckey] = (pos, eqn)


def trace_rank_schedule(fn, args, kwargs, rank: int, world_size: int,
                        comm=None
                        ) -> Tuple[List[CommEvent], List[Finding], set]:
    """Trace ``fn`` for one simulated rank; abstract eval only.

    Returns ``(events, findings, value_deps)`` — ``value_deps`` is the
    jaxpr's buffer use/def chains reduced to event coordinates: the set
    of ``(producer_pos, consumer_pos)`` pairs where the consumer's
    payload is computed from the producer's output.  Token edges are
    excluded by construction, so the pair set is exactly the *true data
    dependence* the schedule compiler must preserve (everything else is
    token serialization it may overlap across).

    The trace-time token chain guard's warnings are captured as
    ``token_violation`` findings: the guard sees the *user-level* chain
    (a forked chain the AD side-chain later repairs on the wire is still
    a program bug worth reporting).
    """
    import jax

    from ..ops import _world_impl

    if comm is None:
        comm = AbstractComm(rank, world_size)
    guard_findings: List[Finding] = []

    def _warn_hook(warn_comm, n_heads, how):
        guard_findings.append(Finding(
            "token_violation",
            f"rank {rank}: a world op on {warn_comm!r} is {how} while "
            f"{n_heads} other token chain(s) on the same comm are live — "
            "relative order is UNDEFINED in explicit-token mode and can "
            "deadlock",
            ranks=(rank,), comm=_comm_key(warn_comm),
        ))

    old_trace = _world_impl._analysis_token_trace
    old_warn = _world_impl._analysis_warn_hook
    _world_impl._set_analysis_token_hooks(old_trace, _warn_hook)
    try:
        with comm:  # ambient default comm for tokenless call sites
            closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    except Exception as err:  # surface trace failures as findings
        guard_findings.append(Finding(
            "rank_error",
            f"rank {rank}: tracing failed with "
            f"{type(err).__name__}: {err}",
            ranks=(rank,),
        ))
        return [], guard_findings, set()
    finally:
        _world_impl._set_analysis_token_hooks(old_trace, old_warn)
    ex = _Extractor(rank, world_size)
    ex.walk(closed.jaxpr)
    return ex.events, ex.findings + guard_findings, ex.value_deps
