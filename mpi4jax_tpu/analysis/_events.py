"""Event and finding vocabulary for the static communication verifier.

Deliberately jax-free: the match simulation (``_match.py``) and these data
types run anywhere — the tier-1 suite exercises them even on hosts whose
jax predates the package minimum, and the launcher's ``--verify`` parses
their JSON form without importing jax in-process.

A :class:`CommEvent` is one communication operation as it appears in one
rank's ordered schedule — extracted either statically from a closed jaxpr
(``_schedule.py``) or dynamically by the virtual-world executor
(``_sim.py``).  Field semantics follow the primitives' params
(``ops/_world_impl.SCHEDULE_SIGNATURES`` is the authoritative export).

Wildcard sentinels match ``utils/status.py`` (ANY_TAG = -1,
ANY_SOURCE = -2) but are re-declared here to keep this module
import-light.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

ANY_TAG = -1
ANY_SOURCE = -2

#: Version of the analyzer's extraction + matching + planning semantics.
#: Bumped whenever schedules, finding kinds, or the plan format change
#: meaning — cached plans and CI golden files key on it, so a semantic
#: change invalidates them instead of silently drifting.
ANALYZER_VERSION = "7.0"

#: Event kinds that move data point-to-point.
P2P_KINDS = frozenset({"send", "recv", "sendrecv", "shift2"})

#: Event kinds that are collective over every member of the comm.
COLLECTIVE_KINDS = frozenset({
    "allreduce", "reduce", "scan", "bcast", "allgather", "gather",
    "scatter", "alltoall", "barrier", "split",
})

#: Collective kinds whose semantics depend on a reduce operator.
REDUCING_KINDS = frozenset({"allreduce", "reduce", "scan"})

#: Collective kinds with a root parameter.
ROOTED_KINDS = frozenset({"reduce", "bcast", "gather", "scatter"})

_DTYPE_BYTES = {
    "bool": 1, "bfloat16": 2, "float16": 2, "float32": 4, "float64": 8,
    "int8": 1, "int16": 2, "int32": 4, "int64": 8,
    "uint8": 1, "uint16": 2, "uint32": 4, "uint64": 8,
    "complex64": 8, "complex128": 16,
}


def event_nbytes(dtype, shape) -> Optional[int]:
    """Payload bytes from a (dtype string, shape tuple) pair, or None
    when either is unknown.  Kept numpy-free so the matcher and planner
    stay importable anywhere (the tier-1 standalone-loading contract)."""
    if dtype is None or shape is None:
        return None
    itemsize = _DTYPE_BYTES.get(str(dtype))
    if itemsize is None:  # "float32" styles covered above; parse "f4"/"<f8"
        digits = "".join(ch for ch in str(dtype) if ch.isdigit())
        if not digits:
            return None
        bits = int(digits)
        itemsize = bits // 8 if bits >= 8 else 1
    n = itemsize
    for d in shape:
        n *= int(d)
    return n


@dataclass
class CommEvent:
    """One communication op in one rank's schedule."""

    rank: int
    idx: int                       # position in this rank's schedule
    kind: str                      # see P2P_KINDS / COLLECTIVE_KINDS
    comm: Tuple = (0,)             # comm key (lineage tuple; same across ranks)
    # point-to-point routing (None where not applicable)
    dest: Optional[int] = None
    source: Optional[int] = None
    lo: Optional[int] = None       # shift2 neighbors (-1 = wall)
    hi: Optional[int] = None
    root: Optional[int] = None
    tag: Optional[int] = None      # send tag / directed recv tag
    sendtag: Optional[int] = None  # sendrecv split tags
    recvtag: Optional[int] = None
    reduce_op: Optional[str] = None
    dtype: Optional[str] = None
    shape: Optional[Tuple[int, ...]] = None
    site: str = ""                 # "file.py:123 (eqn 4 mpi4jax_tpu_send)"
    status: bool = False           # recv/sendrecv fills an MPI-style Status
    # internal matcher state (not part of identity)
    _sent: bool = field(default=False, repr=False, compare=False)

    @property
    def nbytes(self) -> Optional[int]:
        """Payload bytes of this event, or None when unknown."""
        return event_nbytes(self.dtype, self.shape)

    def describe(self) -> str:
        bits = [self.kind]
        if self.kind == "send":
            bits.append(f"to {self.dest} tag {self.tag}")
        elif self.kind == "recv":
            src = "ANY_SOURCE" if self.source == ANY_SOURCE else self.source
            tag = "ANY_TAG" if self.tag == ANY_TAG else self.tag
            bits.append(f"from {src} tag {tag}")
        elif self.kind == "sendrecv":
            bits.append(f"to {self.dest} from {self.source}")
        elif self.kind == "shift2":
            bits.append(f"lo {self.lo} hi {self.hi}")
        elif self.root is not None:
            bits.append(f"root {self.root}")
        if self.reduce_op:
            bits.append(f"op {self.reduce_op}")
        if self.dtype:
            shape = "x".join(map(str, self.shape or ()))
            bits.append(f"{self.dtype}[{shape}]")
        where = f" @ {self.site}" if self.site else ""
        return " ".join(bits) + where

    def collective_signature(self):
        """The fields every rank must agree on for a matched collective.

        ``split`` deliberately excludes color/key (divergent colors are the
        point); reducing kinds include the operator; rooted kinds the root.
        """
        sig = [self.kind]
        if self.kind in REDUCING_KINDS:
            sig.append(("op", self.reduce_op))
        if self.kind in ROOTED_KINDS:
            sig.append(("root", self.root))
        if self.kind not in ("barrier", "split"):
            sig.append(("dtype", self.dtype))
            sig.append(("shape", self.shape))
        return tuple(sig)


def canonical_event(ev: "CommEvent") -> tuple:
    """The semantic identity of one event: every field that affects
    matching or planning, none of the presentation (site strings).  The
    schedule cache key and the golden-plan corpus hash these, so a
    comment shifting line numbers does not invalidate a cached plan."""
    return (ev.kind, tuple(ev.comm), ev.dest, ev.source, ev.lo, ev.hi,
            ev.root, ev.tag, ev.sendtag, ev.recvtag, ev.reduce_op,
            ev.dtype, None if ev.shape is None else tuple(ev.shape),
            bool(ev.status))


def schedule_cache_key(events_by_rank: dict, world_size: int) -> str:
    """sha256 over the canonical schedules + world size + analyzer
    version — the plan/schedule cache key ``analyze --json`` reports."""
    import hashlib

    h = hashlib.sha256()
    h.update(f"analyzer={ANALYZER_VERSION};np={world_size}".encode())
    for rank in sorted(events_by_rank):
        h.update(f";rank={rank}".encode())
        for ev in events_by_rank[rank]:
            h.update(repr(canonical_event(ev)).encode())
    return h.hexdigest()[:32]


# ---------------------------------------------------------------------------
# findings

#: kind -> (severity, one-line description) — the finding catalogue
#: (docs/analysis.md carries a worked example per kind).
FINDING_KINDS = {
    "deadlock": ("error", "cyclic send/recv or collective wait"),
    "unmatched_send": ("error", "a sent message is never received"),
    "unmatched_recv": ("error", "a receive no rank ever sends to"),
    "tag_mismatch": ("error", "matched endpoints disagree on the tag"),
    "dtype_mismatch": ("error", "matched endpoints disagree on the dtype"),
    "shape_mismatch": ("error",
                       "matched endpoints disagree on the shape/byte count"),
    "collective_mismatch": ("error",
                            "ranks run different collectives at the same "
                            "program position"),
    "reduce_op_mismatch": ("error",
                           "ranks run the same collective with different "
                           "reduce operators"),
    "root_mismatch": ("error",
                      "ranks run the same collective with different roots"),
    "wildcard_starvation": ("error",
                            "an ANY_SOURCE receive has no send left to "
                            "match"),
    "token_violation": ("warning",
                        "a world op's effect token is unthreaded or "
                        "reordered (undefined order in explicit-token "
                        "mode)"),
    "order_critical_exchange": ("warning",
                                "cyclic send<->recv traffic between two "
                                "ranks: correct only under strict "
                                "program-order execution; any reordering "
                                "or missing effect edge deadlocks"),
    "control_divergence": ("warning",
                           "communication differs between cond branches; "
                           "data-dependent schedules cannot be verified "
                           "statically"),
    "comm_in_while": ("warning",
                      "communication inside a while loop: trip count is "
                      "data-dependent, one iteration assumed"),
    "rank_error": ("error", "a rank's program raised during analysis"),
    "analysis_timeout": ("error",
                         "the match simulation did not finish in time"),
}


@dataclass
class Finding:
    kind: str
    message: str
    ranks: Tuple[int, ...] = ()
    comm: Tuple = ()
    sites: Tuple[str, ...] = ()

    @property
    def severity(self) -> str:
        return FINDING_KINDS.get(self.kind, ("error", ""))[0]

    def format(self) -> str:
        ranks = ",".join(map(str, self.ranks)) if self.ranks else "-"
        head = f"{self.severity.upper():7s} {self.kind:24s} ranks {ranks:7s}"
        lines = [f"{head} {self.message}"]
        for s in self.sites:
            lines.append(f"{'':8s}  at {s}")
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "severity": self.severity,
            "message": self.message,
            "ranks": list(self.ranks),
            "comm": list(self.comm),
            "sites": list(self.sites),
        }


def collapse_findings(findings, class_of) -> list:
    """Symmetry-collapsed view of a finding list: findings whose rank
    tuples land in the same equivalence classes (same kind, same comm)
    merge into one entry — the lowest-rank representative finding plus
    the instance count and the affected-rank total.  Big-np reports
    stay readable and byte-stable: 510 identical ring findings become
    one representative + ``count: 510``.

    ``class_of`` maps rank -> class index (``SymmetryPartition.
    class_of``); ranks outside it (defensive) collapse as themselves.
    """
    groups: dict = {}
    order = []
    n = len(class_of)
    for f in findings:
        key = (f.kind,
               tuple(class_of[r] if 0 <= r < n else ("r", r)
                     for r in f.ranks),
               tuple(f.comm))
        g = groups.get(key)
        if g is None:
            g = groups[key] = {"rep": f, "count": 0, "ranks": set()}
            order.append(key)
        g["count"] += 1
        g["ranks"].update(f.ranks)
    return [
        {
            "kind": key[0],
            "representative": groups[key]["rep"].to_json(),
            "count": groups[key]["count"],
            "affected_ranks": len(groups[key]["ranks"]),
        }
        for key in order
    ]


@dataclass
class Report:
    """Verdict of one verification run."""

    world_size: int
    target: str                    # program path or function name
    findings: list
    schedules: dict = field(default_factory=dict)  # rank -> [event str]
    output: str = ""               # captured program stdout/stderr (sim)
    #: raw CommEvent lists (rank -> [CommEvent]) — the schedule compiler's
    #: input; not serialized (the string form above is the JSON view)
    events: dict = field(default_factory=dict, repr=False)
    #: comm key -> ordered world-rank member tuple, as matched
    comms: dict = field(default_factory=dict, repr=False)
    #: schedule/plan cache key: a hash of the canonical per-rank schedules
    #: + world size + ANALYZER_VERSION.  Plan caches and CI diffs key on
    #: it — same program, same analyzer ⇒ same key.
    cache_key: str = ""
    analyzer_version: str = ANALYZER_VERSION
    #: attached by the schedule compiler (analysis._plan) when --optimize
    #: runs: a PlanResult, or None
    plan: object = field(default=None, repr=False)
    #: rank-symmetry partition (analysis._symbolic.SymmetryPartition)
    #: when the world canonicalized, else None — drives the symmetry-
    #: collapsed findings view in to_json and the quotient prover
    symmetry: object = field(default=None, repr=False)

    @property
    def ok(self) -> bool:
        return not self.findings

    @property
    def errors(self):
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self):
        return [f for f in self.findings if f.severity == "warning"]

    def kinds(self):
        return {f.kind for f in self.findings}

    def format_table(self, *, show_schedules: bool = False) -> str:
        lines = [
            f"static verify: {self.target} at world size {self.world_size}"
        ]
        if not self.findings:
            lines.append("CLEAN   no findings")
        for f in self.findings:
            lines.append(f.format())
        if show_schedules:
            for rank in sorted(self.schedules):
                lines.append(f"-- rank {rank} schedule --")
                for s in self.schedules[rank]:
                    lines.append(f"   {s}")
        return "\n".join(lines)

    def to_json(self) -> dict:
        out = {
            "target": self.target,
            "world_size": self.world_size,
            "ok": self.ok,
            "analyzer_version": self.analyzer_version,
            "cache_key": self.cache_key,
            "findings": [f.to_json() for f in self.findings],
            "schedules": {
                str(r): list(v) for r, v in self.schedules.items()
            },
        }
        if self.plan is not None:
            out["plan"] = self.plan.to_json()
        if self.symmetry is not None:
            sym = self.symmetry.to_json()
            sym["findings_collapsed"] = collapse_findings(
                self.findings, self.symmetry.class_of)
            out["symmetry"] = sym
        return out
