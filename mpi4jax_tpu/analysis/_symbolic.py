"""Rank-symbolic schedule analysis: verify once per equivalence class.

SPMD programs are rank-symmetric by construction: every rank runs the
same code with peers/roots written as affine-mod expressions of its own
rank (``(rank±k) mod np``, island-relative forms under hierarchical
partitions).  This module exploits that symmetry so the match simulation
(``_match.match_schedules``) and the plan equivalence prover
(``_plan.prove_plan``) run once per *class representative* instead of
once per rank — the step that turns the np≤8 linter into the np=512
scale-proof layer (``tools/scale_harness.py``, ``make verify-scale``).

The model, in three layers:

1. **Canonicalization / partition** (:func:`partition_schedules`) —
   each rank's schedule is rewritten into a rank-free *descriptor*:
   every field that matching compares stays concrete (kind, comm,
   reduce op, root, tags, dtype, shape, status, site), while peer
   values (dest/source/lo/hi) are abstracted into first-appearance
   alias ids — capturing *which* peers are equal within the rank
   without naming them.  Ranks with equal descriptors seed a partition
   that is then refined to a fixpoint on peer-class constancy: two
   ranks stay equivalent only if their k-th peers are themselves
   equivalent, for every k.  Island-structured programs (hierarchical
   ``FAKE_HOSTS`` partitions, non-contiguous islands, uneven
   partitions) fall out of the refinement with no special casing: the
   boundary roles become their own classes.

2. **Quotient simulation** (:func:`match_schedules_symbolic`) — all
   members of a class advance in lockstep with their representative.
   Point-to-point channels are grouped into *slots*: one slot per
   (class, concrete-peer-vector) send direction, valid only when the
   peer map is a bijection onto the target class and every consuming
   receive pops the whole slot at once — exactly the condition under
   which every concrete channel in the slot provably carries the same
   FIFO content.  Anything outside the model (wildcard receives,
   sub-communicators, fan-in/fan-out p2p, overlapping channel
   families) raises and the caller falls back to the concrete path —
   the fallback is *sound*, never silent.

3. **Finding lift** — a clean representative comparison proves every
   member clean (field constancy within the class); a dirty one is
   re-run per member through the concrete comparators
   (``compare_p2p``/``compare_collective``/``wait_graph_findings``),
   so symbolic findings are byte-identical to the concrete
   simulation's (the differential gate in ``tests/test_symbolic.py``
   pins this across the verify-corpus at np ∈ {2..8}).

On top sits the np-rescaling layer the scale harness uses
(:func:`fit_peer_form` / :func:`instantiate_peer`): peers observed at
two small calibration sizes are fitted to affine-mod forms
(const, np-1-k, ``(rank+s) mod np``, non-wrapping shift-with-wall,
island-block) and re-instantiated at any target np.  A peer that fits
no form keeps the program honestly concrete-only.

Knob: ``MPI4JAX_TPU_ANALYZE_SYMBOLIC=auto|off`` (strict parse; read
directly from the environment so this module stays standalone-loadable,
the same contract as ``_match.default_coalesce_bytes``; declared in
``utils.config.KNOBS``).  ``off`` pins the concrete path bit-for-bit;
``auto`` engages the symbolic path from ``SYMBOLIC_MIN_NP`` ranks up.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import _match
from ._events import (
    ANY_SOURCE,
    COLLECTIVE_KINDS,
    CommEvent,
    Finding,
)

#: world sizes below this stay on the concrete path under ``auto`` —
#: small worlds are already fast, and keeping them concrete pins the
#: historic behavior of every existing test and golden bit-for-bit
SYMBOLIC_MIN_NP = 9

#: the world communicator key (the only comm the symbolic model serves;
#: sub-communicator schedules fall back to the concrete path)
WORLD_KEY = (0,)

#: peer-carrying event fields, in the fixed order descriptors use
PEER_FIELDS = ("dest", "source", "lo", "hi")


class Uncanonicalizable(Exception):
    """The schedules cannot be canonicalized under rank symmetry
    (wildcard receives, sub-communicators, non-contiguous rank sets);
    the caller must use the concrete path."""


class FallbackNeeded(Exception):
    """A lockstep invariant failed *during* symbolic analysis (p2p
    fan-in/fan-out, overlapping channel families, finding overflow);
    the caller must rerun the concrete path.  Sound: nothing has been
    reported yet when this raises."""


def symbolic_mode() -> str:
    """``MPI4JAX_TPU_ANALYZE_SYMBOLIC`` as "auto" | "off" — strict like
    ``utils.config.quant_mode``: a typo'd mode aborts loudly instead of
    silently changing which verification path ran.  Read from the
    environment directly so the analysis package stays standalone-
    loadable; the knob is declared in ``config.KNOBS``."""
    raw = os.environ.get("MPI4JAX_TPU_ANALYZE_SYMBOLIC")
    if raw is None or not raw.strip():
        return "auto"
    v = raw.strip()
    if v in ("auto", "off"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_ANALYZE_SYMBOLIC={raw!r} "
        "(expected auto or off)")


# ---------------------------------------------------------------------------
# canonicalization: rank descriptors and the symmetry partition


@dataclass
class SymmetryPartition:
    """Equivalence classes of ranks under schedule symmetry.

    ``classes`` holds each class's members ascending; classes are
    ordered by their smallest member, so ``classes[0]`` always contains
    rank 0 and the representative list starts with it."""

    world_size: int
    class_of: List[int]                  # rank -> class index
    classes: List[Tuple[int, ...]]       # class index -> ascending members

    @property
    def reps(self) -> List[int]:
        return [members[0] for members in self.classes]

    @property
    def n_classes(self) -> int:
        return len(self.classes)

    def to_json(self) -> dict:
        return {
            "world_size": self.world_size,
            "n_classes": self.n_classes,
            "classes": [
                {"representative": members[0], "size": len(members)}
                for members in self.classes
            ],
        }


def _rank_descriptor(events: Sequence[CommEvent]) -> tuple:
    """The rank-free canonical form of one rank's schedule: concrete
    everywhere matching compares fields, peer values abstracted into
    first-appearance alias ids (so intra-rank channel aliasing — two
    sends to *the same* peer — survives canonicalization)."""
    alias: Dict[int, int] = {}
    desc = []
    for ev in events:
        peers = []
        for f in PEER_FIELDS:
            v = getattr(ev, f)
            if v is None:
                peers.append((f, None))
            elif f in ("lo", "hi") and v < 0:
                peers.append((f, "wall"))
            elif f == "source" and v == ANY_SOURCE:
                raise Uncanonicalizable(
                    "ANY_SOURCE receive: wildcard matching is "
                    "service-order dependent and has no class-uniform "
                    "channel state")
            else:
                peers.append((f, ("peer", alias.setdefault(v, len(alias)))))
        desc.append((
            ev.kind, tuple(ev.comm), ev.reduce_op, ev.dtype,
            None if ev.shape is None else tuple(ev.shape),
            bool(ev.status), ev.site, ev.tag, ev.sendtag, ev.recvtag,
            ev.root, tuple(peers),
        ))
    return tuple(desc)


def partition_schedules(
    schedules: Dict[int, List[CommEvent]],
    comms: Optional[Dict[Tuple, Tuple[int, ...]]] = None,
) -> SymmetryPartition:
    """Partition ranks into symmetry classes, or raise
    :class:`Uncanonicalizable`.

    Two ranks land in one class iff (a) their canonical descriptors are
    equal and (b) — refined to a fixpoint — every peer reference of one
    points into the same class as the corresponding reference of the
    other.  The refinement is what separates island-boundary roles
    (first/last island, uneven tail islands) without any topology
    input."""
    ranks = sorted(schedules)
    n = len(ranks)
    if n == 0 or ranks != list(range(n)):
        raise Uncanonicalizable("non-contiguous rank set")
    for key, members in (comms or {}).items():
        if tuple(key) != WORLD_KEY or tuple(members) != tuple(range(n)):
            raise Uncanonicalizable(
                "sub-communicator schedule: the symbolic model serves "
                "the world communicator only")

    by_desc: Dict[tuple, List[int]] = {}
    for r in ranks:
        for ev in schedules[r]:
            if tuple(ev.comm) != WORLD_KEY:
                raise Uncanonicalizable("event on a sub-communicator")
            for f in ("dest", "source"):
                v = getattr(ev, f)
                if v is not None and v != ANY_SOURCE \
                        and not (0 <= v < n):
                    raise Uncanonicalizable(
                        f"{f}={v} outside the world")
        by_desc.setdefault(_rank_descriptor(schedules[r]), []).append(r)

    classes = sorted(by_desc.values(), key=lambda ms: ms[0])
    class_of = [0] * n
    for ci, ms in enumerate(classes):
        for r in ms:
            class_of[r] = ci

    def peer_class_signature(r: int) -> tuple:
        sig = []
        for ev in schedules[r]:
            for f in PEER_FIELDS:
                v = getattr(ev, f)
                if v is None or (f in ("lo", "hi") and v < 0):
                    sig.append(None)
                else:
                    sig.append(class_of[v])
        return tuple(sig)

    while True:
        split_any = False
        new_classes: List[List[int]] = []
        for ms in classes:
            by_sig: Dict[tuple, List[int]] = {}
            for r in ms:
                by_sig.setdefault(peer_class_signature(r), []).append(r)
            parts = sorted(by_sig.values(), key=lambda g: g[0])
            if len(parts) > 1:
                split_any = True
            new_classes.extend(parts)
        if not split_any:
            break
        classes = sorted(new_classes, key=lambda g: g[0])
        for ci, ms in enumerate(classes):
            for r in ms:
                class_of[r] = ci

    return SymmetryPartition(
        world_size=n,
        class_of=class_of,
        classes=[tuple(ms) for ms in classes],
    )


# ---------------------------------------------------------------------------
# quotient simulation


class _QuotientSim:
    """Lockstep class-level replay of :func:`_match.match_schedules`.

    Channel *slots* — one per (sending class, concrete peer vector) —
    stand in for the O(np²) concrete channels: a slot is only admitted
    when its peer map is a bijection onto the target class and every
    receive that consumes it pops the whole slot at once, which is
    exactly the condition under which all its concrete channels carry
    identical FIFO state.  Violations raise :class:`FallbackNeeded`.
    """

    def __init__(self, schedules, part: SymmetryPartition,
                 deliveries=None, service_order=None):
        self.schedules = schedules
        self.part = part
        self.classes = part.classes
        self.reps = part.reps
        self.findings: List[Finding] = []
        self.deliveries = deliveries
        if deliveries is not None:
            deliveries.setdefault("p2p", {})
            deliveries.setdefault("coll", {})
        self.service = (list(service_order) if service_order is not None
                        else list(range(len(self.classes))))
        self.pc = [0] * len(self.classes)
        self.steps = 0
        self._sent: set = set()          # (class, pos) combined-op pushes
        self._build_slots()

    # -- static slot derivation --------------------------------------

    def _peer_vector(self, ci: int, pos: int, field: str):
        members = self.classes[ci]
        rep_v = getattr(self.schedules[members[0]][pos], field)
        if rep_v is None or (field in ("lo", "hi") and rep_v < 0):
            for m in members[1:]:
                v = getattr(self.schedules[m][pos], field)
                if not (v is None or (field in ("lo", "hi") and v < 0)):
                    raise FallbackNeeded("wall/peer mix within a class")
            return None
        vec = tuple(getattr(self.schedules[m][pos], field)
                    for m in members)
        if any(not isinstance(v, int) or v < 0 for v in vec):
            raise FallbackNeeded("wall/peer mix within a class")
        return vec

    def _build_slots(self):
        sched_len = [len(self.schedules[rep]) for rep in self.reps]
        # send directions first: every channel family a send ever feeds
        self.slot_info: List[Tuple[int, tuple]] = []   # slot -> (ci, vec)
        slot_ids: Dict[Tuple[int, tuple], int] = {}
        edge_slot: Dict[Tuple[int, int], int] = {}     # (src,dst) -> slot
        self.send_slot: Dict[Tuple[int, int, str], Optional[int]] = {}
        for ci, members in enumerate(self.classes):
            rep = members[0]
            for pos in range(sched_len[ci]):
                ev = self.schedules[rep][pos]
                if ev.kind == "send":
                    fields = ("dest",)
                elif ev.kind == "sendrecv":
                    fields = ("dest",)
                elif ev.kind == "shift2":
                    fields = ("lo", "hi")
                else:
                    continue
                for f in fields:
                    vec = self._peer_vector(ci, pos, f)
                    if vec is None:
                        self.send_slot[(ci, pos, f)] = None
                        continue
                    key = (ci, vec)
                    slot = slot_ids.get(key)
                    if slot is None:
                        if len(set(vec)) != len(vec):
                            raise FallbackNeeded(
                                "p2p fan-in: send peers not distinct "
                                "within the class")
                        tgt = self.part.class_of[vec[0]]
                        if len(vec) != len(self.classes[tgt]):
                            raise FallbackNeeded(
                                "p2p send image does not cover the "
                                "target class")
                        slot = len(self.slot_info)
                        self.slot_info.append((ci, vec))
                        slot_ids[key] = slot
                        for k, src in enumerate(members):
                            edge = (src, vec[k])
                            if edge_slot.setdefault(edge, slot) != slot:
                                raise FallbackNeeded(
                                    "overlapping channel families: one "
                                    "concrete channel fed by two slots")
                    self.send_slot[(ci, pos, f)] = slot
        # receive directions: bind each to the one slot that feeds it
        self.recv_bind: Dict[Tuple[int, int, str], Optional[int]] = {}
        self.recv_src: Dict[Tuple[int, int, str], tuple] = {}
        for ci, members in enumerate(self.classes):
            rep = members[0]
            for pos in range(sched_len[ci]):
                ev = self.schedules[rep][pos]
                if ev.kind in ("recv", "sendrecv"):
                    fields = ("source",)
                elif ev.kind == "shift2":
                    fields = ("lo", "hi")
                else:
                    continue
                for f in fields:
                    vec = self._peer_vector(ci, pos, f)
                    if vec is None:
                        continue
                    owners = {edge_slot.get((vec[k], d))
                              for k, d in enumerate(members)}
                    if len(owners) != 1:
                        raise FallbackNeeded(
                            "receive channels straddle channel "
                            "families")
                    owner = owners.pop()
                    if owner is not None:
                        oci, ovec = self.slot_info[owner]
                        if len(self.classes[oci]) != len(members):
                            raise FallbackNeeded(
                                "receive does not drain its whole "
                                "channel family")
                    self.recv_bind[(ci, pos, f)] = owner
                    self.recv_src[(ci, pos, f)] = vec
        self.fifo: Dict[int, deque] = {
            s: deque() for s in range(len(self.slot_info))}

    # -- lockstep advance --------------------------------------------

    def _current(self, ci: int):
        sched = self.schedules[self.reps[ci]]
        pos = self.pc[ci]
        return sched[pos] if pos < len(sched) else None

    def _push(self, ci: int, pos: int, field: str):
        slot = self.send_slot[(ci, pos, field)]
        if slot is not None:
            self.fifo[slot].append((ci, pos, field))

    def _extend(self, found: List[Finding]):
        self.findings.extend(found)
        if len(self.findings) > _match.MAX_FINDINGS:
            raise FallbackNeeded(
                "finding overflow: the concrete path owns the "
                "truncation point")

    def _match_pair(self, sc, sp, sfield, ci, pos, rfield):
        """One slot pop: the sending (class, pos, part) meets the
        receiving (class, pos, part).  Clean at the representative ⇒
        clean for every member (field constancy within the class);
        dirty ⇒ re-run the concrete comparator per member, so the
        lifted findings (messages embed concrete ranks) are
        byte-identical to the concrete simulation's."""
        members = self.classes[ci]
        svec = self.recv_src[(ci, pos, rfield)]
        rep_src = svec[0]
        send_rep = _match.send_part_event(
            self.schedules[rep_src][sp], dest=members[0])
        recv_rep = self.schedules[members[0]][pos]
        probe = _match.compare_p2p(send_rep, recv_rep)
        if probe:
            found = []
            for k, d in enumerate(members):
                s_ev = _match.send_part_event(
                    self.schedules[svec[k]][sp], dest=d)
                found.extend(_match.compare_p2p(
                    s_ev, self.schedules[d][pos]))
            self._extend(found)
        if self.deliveries is not None:
            # key on the slot's stable identity and the events'
            # original idx (not positions): the prover compares these
            # records across reordered configurations, exactly like the
            # concrete recorder's (send_rank, send_idx, ...) tuples
            slot_key = self.slot_info[self.recv_bind[(ci, pos, rfield)]]
            self.deliveries["p2p"].setdefault(slot_key, []).append(
                (sc, send_rep.idx, send_rep.tag, ci, recv_rep.idx))

    def _complete_recv(self, ci, pos, rfield) -> bool:
        slot = self.recv_bind.get((ci, pos, rfield))
        if slot is None:
            return False
        q = self.fifo[slot]
        if not q:
            return False
        sc, sp, sfield = q.popleft()
        self._match_pair(sc, sp, sfield, ci, pos, rfield)
        return True

    def _advance(self, ci: int) -> bool:
        ev = self._current(ci)
        if ev is None:
            return False
        pos = self.pc[ci]
        if ev.kind == "send":
            self._push(ci, pos, "dest")
            self.pc[ci] += 1
            return True
        if ev.kind == "sendrecv":
            if (ci, pos) not in self._sent:
                self._push(ci, pos, "dest")
                self._sent.add((ci, pos))
            if self._complete_recv(ci, pos, "source"):
                self.pc[ci] += 1
                return True
            return False
        if ev.kind == "shift2":
            if (ci, pos) not in self._sent:
                for f in ("lo", "hi"):
                    self._push(ci, pos, f)
                self._sent.add((ci, pos))
            needed = [f for f in ("lo", "hi")
                      if (ci, pos, f) in self.recv_src]
            for f in needed:
                slot = self.recv_bind[(ci, pos, f)]
                if slot is None or not self.fifo[slot]:
                    return False
            for f in needed:
                q = self.fifo[self.recv_bind[(ci, pos, f)]]
                sc, sp, sfield = q.popleft()
                self._match_pair(sc, sp, sfield, ci, pos, f)
            self.pc[ci] += 1
            return True
        if ev.kind == "recv":
            if self._complete_recv(ci, pos, "source"):
                self.pc[ci] += 1
                return True
            return False
        if ev.kind in COLLECTIVE_KINDS:
            return self._advance_collective(ci, ev)
        return False

    def _advance_collective(self, ci, ev) -> bool:
        arrived_reps = []
        for cj in range(len(self.classes)):
            cur = self._current(cj)
            if cur is None or cur.kind not in COLLECTIVE_KINDS \
                    or tuple(cur.comm) != WORLD_KEY:
                return False
            arrived_reps.append(cur)
        ref_sig = arrived_reps[0].collective_signature()
        if any(e.collective_signature() != ref_sig
               for e in arrived_reps[1:]):
            # dirty rendezvous: lift per member, world-rank order, the
            # exact list the concrete simulation hands compare_collective
            full = [self.schedules[m][self.pc[self.part.class_of[m]]]
                    for m in range(self.part.world_size)]
            self._extend(_match.compare_collective(full))
        if self.deliveries is not None:
            self.deliveries["coll"].setdefault(WORLD_KEY, []).append(
                (arrived_reps[0].kind,
                 tuple(sorted((cj, arrived_reps[cj].idx)
                              for cj in range(len(self.classes))))))
        for cj in range(len(self.classes)):
            self.pc[cj] += 1
        return True

    # -- stall classification, leftovers -----------------------------

    def _stall_findings(self):
        done_ranks = set()
        blocked: Dict[int, CommEvent] = {}
        for ci, members in enumerate(self.classes):
            if self._current(ci) is None:
                done_ranks.update(members)
            else:
                pos = self.pc[ci]
                for m in members:
                    blocked[m] = self.schedules[m][pos]
        done = frozenset(done_ranks)
        stragglers_cache: Optional[Tuple[int, ...]] = None
        waits_on: Dict[int, Tuple[int, ...]] = {}
        for r in sorted(blocked):
            ev = blocked[r]
            if ev.kind in COLLECTIVE_KINDS:
                if stragglers_cache is None:
                    out = []
                    for m in range(self.part.world_size):
                        cur = blocked.get(m)
                        if m in done or (
                            cur is not None
                            and (cur.kind not in COLLECTIVE_KINDS
                                 or tuple(cur.comm) != WORLD_KEY)
                        ):
                            out.append(m)
                    stragglers_cache = tuple(out)
                waits_on[r] = stragglers_cache
            elif ev.kind in ("recv", "sendrecv"):
                waits_on[r] = (ev.source,)
            elif ev.kind == "shift2":
                ci = self.part.class_of[r]
                pos = self.pc[ci]
                missing = []
                for f in ("lo", "hi"):
                    if (ci, pos, f) not in self.recv_src:
                        continue
                    slot = self.recv_bind[(ci, pos, f)]
                    if slot is None or not self.fifo[slot]:
                        missing.append(getattr(ev, f))
                waits_on[r] = tuple(missing)
            else:
                waits_on[r] = ()
        self._extend(
            _match.wait_graph_findings(blocked, waits_on, done))

    def _leftover_findings(self):
        found = []
        for slot, q in self.fifo.items():
            if not q:
                continue
            sc, sp, sfield = q[0]
            oci, ovec = self.slot_info[slot]
            for k, src in enumerate(self.classes[oci]):
                dst = ovec[k]
                ev = _match.send_part_event(
                    self.schedules[src][sp], dest=dst)
                found.append(Finding(
                    "unmatched_send",
                    f"rank {ev.rank} sends to rank {dst} (tag {ev.tag}) "
                    "but no matching receive ever runs",
                    ranks=(ev.rank, dst), comm=WORLD_KEY,
                    sites=(f"rank {ev.rank}: {ev.describe()}",),
                ))
        self._extend(found)

    def run(self) -> List[Finding]:
        total = sum(len(self.schedules[rep]) for rep in self.reps)
        for _ in range(2 * total + 2):
            progressed = False
            for ci in self.service:
                while self._advance(ci):
                    progressed = True
                    self.steps += 1
            if not progressed:
                break
        self._stall_findings()
        self._leftover_findings()
        self._extend(_match.order_critical_findings(
            self.schedules, {WORLD_KEY:
                             tuple(range(self.part.world_size))}))
        return self.findings


def match_schedules_symbolic(
    schedules: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    partition: SymmetryPartition,
    deliveries: Optional[dict] = None,
    service_order: Optional[Sequence[int]] = None,
    stats: Optional[dict] = None,
) -> List[Finding]:
    """Class-level replay of :func:`_match.match_schedules` under a
    symmetry ``partition`` (see :func:`partition_schedules`).

    ``service_order`` is over *class indices* (the prover rotates it).
    ``deliveries`` receives the quotient-level match record — per-slot
    p2p orders and class-level collective rendezvous — comparable
    across configurations that share the partition.  Raises
    :class:`FallbackNeeded` when a lockstep invariant fails; callers
    rerun the concrete path."""
    sim = _QuotientSim(schedules, partition, deliveries=deliveries,
                       service_order=service_order)
    findings = sim.run()
    if stats is not None:
        stats["steps"] = sim.steps
        stats["classes"] = partition.n_classes
    return findings


def verify_schedules(
    schedules: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    deliveries: Optional[dict] = None,
    stats: Optional[dict] = None,
) -> Tuple[List[Finding], Optional[SymmetryPartition]]:
    """Match ``schedules`` by the cheapest sound path: symbolic when
    the knob allows, the world is at least ``SYMBOLIC_MIN_NP`` ranks,
    and the program canonicalizes; concrete otherwise.  Returns
    ``(findings, partition_or_None)`` — the partition is returned even
    when the quotient simulation fell back, so callers can still
    symmetry-collapse the report."""
    part = None
    if symbolic_mode() == "auto" and len(schedules) >= SYMBOLIC_MIN_NP:
        try:
            part = partition_schedules(schedules, comms)
        except Uncanonicalizable:
            part = None
        if part is not None:
            try:
                findings = match_schedules_symbolic(
                    schedules, comms, part, deliveries=deliveries,
                    stats=stats)
                if stats is not None:
                    stats["mode"] = "symbolic"
                return findings, part
            except FallbackNeeded:
                pass
    findings = _match.match_schedules(schedules, comms,
                                      deliveries=deliveries, stats=stats)
    if stats is not None:
        stats["mode"] = "concrete"
    return findings, part


# ---------------------------------------------------------------------------
# quotient equivalence prover


def prove_plan_symbolic(events_by_rank, comms, plan, partition,
                        max_interleavings: Optional[int] = None):
    """Symbolic twin of :func:`_plan.prove_plan`: one replay per
    configuration at class granularity, with rank-service rotations
    quotiented to class-service rotations — what makes the proof
    budget independent of np (concretely, np=512 needs 512 rotations
    and blows the MAX_INTERLEAVINGS budget; symbolically it needs one
    per class).

    Returns ``plan.proved`` on success, or ``None`` when the plan is
    outside the symbolic model (per-class planned orders diverge, or a
    concurrency group has realizable non-post orders) — the caller
    then runs the concrete prover."""
    from . import _plan as P

    if max_interleavings is None:
        max_interleavings = P.MAX_INTERLEAVINGS
    ranks = sorted(events_by_rank)
    planned = {r: P._planned_order(events_by_rank[r], plan.ranks[r])
               for r in ranks}
    for members in partition.classes:
        first = planned[members[0]]
        if any(planned[m] != first for m in members[1:]):
            return None
    for r in ranks:
        for g in plan.ranks[r].groups:
            if len(g) >= 2 and P._group_interleavings(
                    events_by_rank[r], g):
                # multi-engine riffles are per-rank-asymmetric
                # configurations the lockstep model cannot express
                return None

    def sim(order_by_class, service):
        schedules = {
            r: [events_by_rank[r][p]
                for p in order_by_class[partition.class_of[r]]]
            for r in ranks
        }
        deliv: dict = {}
        findings = match_schedules_symbolic(
            schedules, comms, partition, deliveries=deliv,
            service_order=service)
        return {f.kind for f in findings}, deliv

    identity = {ci: list(range(len(events_by_rank[rep])))
                for ci, rep in enumerate(partition.reps)}
    planned_by_class = {ci: planned[rep]
                        for ci, rep in enumerate(partition.reps)}
    try:
        base_kinds, base_deliv = sim(identity, None)
        nclasses = partition.n_classes
        configs = [(planned_by_class, None)]
        for shift in range(1, nclasses):
            svc = list(range(nclasses))
            configs.append((planned_by_class, svc[shift:] + svc[:shift]))
        exhaustive = len(configs) <= max_interleavings
        if not exhaustive:
            configs = configs[:max_interleavings]
        failures: List[str] = []
        for i, (orders, service) in enumerate(configs):
            kinds, deliv = sim(orders, service)
            new_kinds = kinds - base_kinds
            if new_kinds:
                failures.append(
                    f"interleaving {i}: new finding kind(s) "
                    f"{sorted(new_kinds)}")
            elif deliv != base_deliv:
                failures.append(
                    f"interleaving {i}: per-channel delivery order "
                    "changed")
            if failures:
                break
    except FallbackNeeded:
        return None

    plan.proof = {
        "interleavings": len(configs),
        "exhaustive": exhaustive,
        "base_finding_kinds": sorted(base_kinds),
        "failures": failures,
        "symmetry_classes": partition.n_classes,
    }
    plan.proved = not failures and exhaustive
    if failures:
        plan.reasons.extend(failures)
    elif not exhaustive:
        plan.reasons.append(
            f"interleaving budget exceeded ({max_interleavings}); "
            "plan rejected unproven")
    return plan.proved


# ---------------------------------------------------------------------------
# np-rescaling forms (the scale harness's cross-size layer)

#: form kinds, in fitting priority order:
#: ("const", c)        peer = c at every (rank, np)
#: ("hiconst", k)      peer = np - 1 - k           (e.g. "last rank")
#: ("shift", s)        peer = (rank + s) mod np    (wrapped ring)
#: ("shiftwall", s)    peer = rank + s, wall (-1) outside [0, np)
#: ("block", a, d)     peer = (rank // a) * a + d  (island-of-a leader
#:                     offset d; island-relative const)
#: ("wall",)           peer = wall (-1 / None) everywhere
PEER_FORM_KINDS = ("const", "hiconst", "shift", "shiftwall", "block",
                   "wall")


def instantiate_peer(form: tuple, rank: int, np_: int,
                     wall: int = -1) -> Optional[int]:
    """Evaluate a fitted peer form at (rank, np)."""
    kind = form[0]
    if kind == "wall":
        return wall
    if kind == "const":
        return form[1]
    if kind == "hiconst":
        return np_ - 1 - form[1]
    if kind == "shift":
        return (rank + form[1]) % np_
    if kind == "shiftwall":
        p = rank + form[1]
        return p if 0 <= p < np_ else wall
    if kind == "block":
        return (rank // form[1]) * form[1] + form[2]
    raise ValueError(f"unknown peer form {form!r}")


def fit_peer_form(observations, *, block: Optional[int] = None,
                  wall: int = -1) -> Optional[tuple]:
    """Fit one affine-mod peer form to ``[(rank, np, peer), ...]``
    observations gathered at (at least two) calibration world sizes.

    ``peer`` may be the wall sentinel (negative) or None.  ``block``
    optionally offers an island size to try for island-relative forms
    (the caller scales it with np).  Returns the first form (in
    ``PEER_FORM_KINDS`` order) that reproduces *every* observation, or
    None — the caller then keeps the program concrete-only, which is
    the honest answer for peers that are not affine in rank."""
    obs = [(r, n, (wall if p is None or (isinstance(p, int) and p < 0)
                   else p))
           for r, n, p in observations]
    if not obs:
        return None

    def ok(form):
        return all(instantiate_peer(form, r, n, wall=wall) == p
                   for r, n, p in obs)

    if all(p == wall for _, _, p in obs):
        return ("wall",)
    if any(p == wall for _, _, p in obs):
        # mixed wall/peer: only the non-wrapping shift can produce it
        r0, n0, p0 = next(o for o in obs if o[2] != wall)
        form = ("shiftwall", p0 - r0)
        return form if ok(form) else None
    r0, n0, p0 = obs[0]
    candidates = [("const", p0), ("hiconst", n0 - 1 - p0)]
    for s_raw in (p0 - r0, p0 - r0 - n0, p0 - r0 + n0):
        candidates.append(("shift", s_raw))
        candidates.append(("shiftwall", s_raw))
    if block and block > 0:
        candidates.append(("block", block, p0 - (r0 // block) * block))
    for form in candidates:
        if ok(form):
            return form
    return None
