"""Static communication verifier for world-tier programs.

Every communication schedule this framework runs is statically visible —
ops are JAX primitives with explicit params (peer, root, tag, dtype,
shape, comm) and explicit dataflow/effect ordering — so mismatched
collectives, unpaired send/recv, and token-ordering bugs can be caught
*before a single rank is launched*, instead of surfacing as runtime hangs
that the transport deadline converts into late, expensive timeouts.

Three entry points:

- :func:`check` — verify a *function*: traced once per simulated rank
  (abstract eval only; no live comm, no processes), the closed jaxpr
  walked (including scan/cond/while/pjit sub-jaxprs) into per-rank
  schedules, then an N-rank match simulation reports deadlocks,
  unmatched or mismatched endpoints, divergent collectives, and
  token-discipline violations.
- :func:`check_program` — verify a whole per-rank *program file* in a
  virtual world: one thread per rank, world ops served by an in-memory
  matcher with real values (assertions in the program run for real),
  still with no processes and no live communication.
- the CLI — ``python -m mpi4jax_tpu.analyze prog.py --np 4`` — plus the
  launcher's pre-flight (``mpi4jax_tpu.launch --verify``) and the
  ``static_verify`` diag check.

See docs/analysis.md for the finding catalogue with worked examples.
"""

from __future__ import annotations

import inspect

from ._events import (  # noqa: F401
    CommEvent,
    FINDING_KINDS,
    Finding,
    Report,
)
from ._fake import AbstractComm, AnalysisError  # noqa: F401
from ._match import match_schedules  # noqa: F401
from ._schedule import trace_rank_schedule  # noqa: F401
from ._sim import SimAbort, VirtualWorld  # noqa: F401


def _dedupe(findings):
    out, seen = [], set()
    for f in findings:
        key = (f.kind, f.ranks, f.comm, f.message, f.sites)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    out.sort(key=lambda f: (0 if f.severity == "error" else 1, f.kind))
    return out


def check(fn, *args, world_size: int = 2, **kwargs) -> Report:
    """Statically verify the communication schedule of ``fn``.

    ``fn`` is traced once per simulated rank with abstract values only —
    no communication happens and no processes are spawned.  Inside
    ``fn``, :func:`mpi4jax_tpu.get_default_comm` returns the simulated
    rank's communicator; alternatively declare a ``comm`` parameter and
    the analyzer passes it explicitly.

    Returns a :class:`Report`; ``report.ok`` is True when no finding
    survived, and ``report.findings`` lists deadlocks, mismatches,
    divergent collectives, and token-discipline hazards otherwise.
    """
    takes_comm = False
    try:
        takes_comm = "comm" in inspect.signature(fn).parameters \
            and "comm" not in kwargs
    except (TypeError, ValueError):
        pass
    schedules, findings = {}, []
    for rank in range(world_size):
        comm = AbstractComm(rank, world_size)
        kw = dict(kwargs)
        if takes_comm:
            kw["comm"] = comm
        events, fnds = trace_rank_schedule(
            fn, args, kw, rank, world_size, comm=comm)
        schedules[rank] = events
        findings.extend(fnds)
    comms = {(0,): tuple(range(world_size))}
    findings.extend(match_schedules(schedules, comms))
    return Report(
        world_size=world_size,
        target=getattr(fn, "__name__", repr(fn)),
        findings=_dedupe(findings),
        schedules={r: [e.describe() for e in evs]
                   for r, evs in schedules.items()},
    )


def check_program(path: str, world_size: int, timeout_s=None,
                  argv=None) -> Report:
    """Verify a per-rank program file in the virtual world (see
    :class:`VirtualWorld`): real values, recorded schedules, no processes,
    no live communication.  ``argv`` becomes the program's
    ``sys.argv[1:]``, exactly as under the launcher."""
    world = VirtualWorld(world_size, path, timeout_s=timeout_s, argv=argv)
    report = world.run()
    report.findings = _dedupe(report.findings)
    return report
