"""Static communication verifier for world-tier programs.

Every communication schedule this framework runs is statically visible —
ops are JAX primitives with explicit params (peer, root, tag, dtype,
shape, comm) and explicit dataflow/effect ordering — so mismatched
collectives, unpaired send/recv, and token-ordering bugs can be caught
*before a single rank is launched*, instead of surfacing as runtime hangs
that the transport deadline converts into late, expensive timeouts.

Three entry points:

- :func:`check` — verify a *function*: traced once per simulated rank
  (abstract eval only; no live comm, no processes), the closed jaxpr
  walked (including scan/cond/while/pjit sub-jaxprs) into per-rank
  schedules, then an N-rank match simulation reports deadlocks,
  unmatched or mismatched endpoints, divergent collectives, and
  token-discipline violations.
- :func:`check_program` — verify a whole per-rank *program file* in a
  virtual world: one thread per rank, world ops served by an in-memory
  matcher with real values (assertions in the program run for real),
  still with no processes and no live communication.
- the CLI — ``python -m mpi4jax_tpu.analyze prog.py --np 4`` — plus the
  launcher's pre-flight (``mpi4jax_tpu.launch --verify``) and the
  ``static_verify`` diag check.

See docs/analysis.md for the finding catalogue with worked examples.
"""

from __future__ import annotations

import inspect

from ._events import (  # noqa: F401
    ANALYZER_VERSION,
    CommEvent,
    FINDING_KINDS,
    Finding,
    Report,
    schedule_cache_key,
)
from ._fake import AbstractComm, AnalysisError  # noqa: F401
from ._match import match_schedules  # noqa: F401
from ._plan import (  # noqa: F401
    ExecutionPlan,
    cached_plan,
    compile_schedules,
    diff_plans,
    load_plan,
    save_plan,
)
from ._schedule import trace_rank_schedule  # noqa: F401
from ._sim import SimAbort, VirtualWorld  # noqa: F401
from ._symbolic import (  # noqa: F401
    SYMBOLIC_MIN_NP,
    FallbackNeeded,
    SymmetryPartition,
    Uncanonicalizable,
    match_schedules_symbolic,
    partition_schedules,
    symbolic_mode,
    verify_schedules,
)


def _canonical_finding_key(f):
    """Total content order over findings: severity, kind, ranks, comm,
    message, sites.  Fully content-determined, so the final report
    order is independent of the *discovery* order — the property that
    lets the symbolic (rank-symmetry) path reproduce concrete reports
    byte-for-byte, and keeps big-np ``analyze --json`` output stable
    across analyzer-internal reorderings."""
    return (0 if f.severity == "error" else 1, f.kind,
            tuple(f.ranks), str(f.comm), f.message, tuple(f.sites))


def _dedupe(findings):
    out, seen = [], set()
    for f in findings:
        key = (f.kind, f.ranks, f.comm, f.message, f.sites)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    out.sort(key=_canonical_finding_key)
    return out


def check(fn, *args, world_size: int = 2, **kwargs) -> Report:
    """Statically verify the communication schedule of ``fn``.

    ``fn`` is traced once per simulated rank with abstract values only —
    no communication happens and no processes are spawned.  Inside
    ``fn``, :func:`mpi4jax_tpu.get_default_comm` returns the simulated
    rank's communicator; alternatively declare a ``comm`` parameter and
    the analyzer passes it explicitly.

    Returns a :class:`Report`; ``report.ok`` is True when no finding
    survived, and ``report.findings`` lists deadlocks, mismatches,
    divergent collectives, and token-discipline hazards otherwise.
    """
    takes_comm = False
    try:
        takes_comm = "comm" in inspect.signature(fn).parameters \
            and "comm" not in kwargs
    except (TypeError, ValueError):
        pass
    schedules, findings, value_deps = {}, [], {}
    for rank in range(world_size):
        comm = AbstractComm(rank, world_size)
        kw = dict(kwargs)
        if takes_comm:
            kw["comm"] = comm
        events, fnds, vdeps = trace_rank_schedule(
            fn, args, kw, rank, world_size, comm=comm)
        schedules[rank] = events
        value_deps[rank] = vdeps
        findings.extend(fnds)
    comms = {(0,): tuple(range(world_size))}
    match_findings, symmetry = verify_schedules(schedules, comms)
    findings.extend(match_findings)
    report = Report(
        world_size=world_size,
        target=getattr(fn, "__name__", repr(fn)),
        findings=_dedupe(findings),
        schedules={r: [e.describe() for e in evs]
                   for r, evs in schedules.items()},
        events=schedules,
        comms=comms,
        cache_key=schedule_cache_key(schedules, world_size),
    )
    report.value_deps = value_deps
    report.symmetry = symmetry
    return report


def check_program(path: str, world_size: int, timeout_s=None,
                  argv=None) -> Report:
    """Verify a per-rank program file in the virtual world (see
    :class:`VirtualWorld`): real values, recorded schedules, no processes,
    no live communication.  ``argv`` becomes the program's
    ``sys.argv[1:]``, exactly as under the launcher."""
    world = VirtualWorld(world_size, path, timeout_s=timeout_s, argv=argv)
    report = world.run()
    report.findings = _dedupe(report.findings)
    report.symmetry = _maybe_partition(report.events, report.comms)
    return report


def _maybe_partition(events_by_rank, comms):
    """The rank-symmetry partition of an extracted schedule set, when
    the knob allows, the world is big enough for the symbolic path to
    matter, and the program canonicalizes — else None.  Gated at
    ``SYMBOLIC_MIN_NP`` so small-world reports (and every golden) stay
    bit-for-bit what they always were."""
    from . import _symbolic

    if _symbolic.symbolic_mode() != "auto" \
            or len(events_by_rank) < SYMBOLIC_MIN_NP:
        return None
    try:
        return partition_schedules(events_by_rank, comms)
    except Uncanonicalizable:
        return None


def plan_report(report: Report, **kwargs) -> ExecutionPlan:
    """Compile the report's extracted schedules into a verified
    execution plan (see :mod:`._plan`): dependence analysis splits true
    data dependence from token serialization, the rewrite emits
    concurrency groups / hoisted recv posts / coalescing and bucket
    marks, and the equivalence prover replays both schedules through the
    match simulator before the plan may execute.  Attaches the plan to
    ``report.plan`` and returns it."""
    kwargs.setdefault("symmetry", getattr(report, "symmetry", None))
    plan = compile_schedules(
        report.events,
        report.comms or {(0,): tuple(range(report.world_size))},
        findings=report.findings,
        world_size=report.world_size,
        value_deps_by_rank=getattr(report, "value_deps", None),
        **kwargs,
    )
    report.plan = plan
    return plan


def plan_for(fn, *args, world_size: int = 2, **kwargs) -> ExecutionPlan:
    """:func:`check` + :func:`plan_report` in one step: statically
    verify ``fn`` and compile its verified execution plan.  The plan of
    an unverifiable schedule is the trivial (unrewritten) one, with the
    blocking findings recorded in ``plan.reasons``."""
    report = check(fn, *args, world_size=world_size, **kwargs)
    return plan_report(report)
