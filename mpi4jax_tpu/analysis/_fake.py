"""Abstract communicators for analysis: the shape of a WorldComm with no
transport behind it.

An :class:`AbstractComm` carries rank/size/lineage so every op-layer code
path (validation, primitive params, rank-dependent avals like ``gather``)
behaves exactly as in production, but touching the native handle is an
error — analysis must never open a socket or shared-memory segment.

Static checking (``analysis.check``) uses one AbstractComm per simulated
rank and traces with abstract values only.  The virtual-world executor
(``analysis._sim``) attaches a live session so collective comm management
(``split``/``dup``) rendezvouses across rank threads.
"""

from __future__ import annotations

from ..runtime.transport import WorldComm


class AnalysisError(RuntimeError):
    """The analyzed program attempted something analysis cannot allow
    (e.g. touching the native transport)."""


class AbstractComm(WorldComm):
    """A WorldComm stand-in for one simulated rank.

    ``key`` plays the lineage role (identical across the comm's members,
    so primitive-param hashes agree rank-to-rank exactly like production
    comms); ``members`` is the world-rank tuple ordered by sub-rank.
    """

    def __init__(self, rank, size, *, key=(0,), members=None, session=None):
        super().__init__(rank, size, coord="analysis:virtual",
                         lineage=tuple(key))
        self._members = tuple(members) if members is not None \
            else tuple(range(size))
        self._session = session

    @property
    def key(self):
        return self._lineage

    @property
    def members(self):
        return self._members

    @property
    def handle(self):
        raise AnalysisError(
            "an op reached the native transport during static analysis — "
            "this is a bug in mpi4jax_tpu.analysis (no live communication "
            "may happen here)"
        )

    def split(self, color, key=None):
        if self._session is None:
            raise NotImplementedError(
                "comm.split() inside analysis.check() is not supported: a "
                "split's membership depends on every rank's color, which a "
                "per-rank static trace cannot see.  Analyze the full "
                "program instead: python -m mpi4jax_tpu.analyze prog.py "
                "--np N"
            )
        return self._session.split_collective(self, int(color), key)

    def dup(self):
        if self._session is None:
            raise NotImplementedError(
                "comm.dup() inside analysis.check() is not supported; "
                "analyze the full program via python -m "
                "mpi4jax_tpu.analyze instead"
            )
        return self._session.dup_collective(self)

    clone = dup
    Clone = dup
    Split = split

    def coll_algo(self, op: str, nbytes: int) -> str:
        return "analysis"

    def __repr__(self):
        return (f"AbstractComm(rank={self._rank}, size={self._size}, "
                f"key={self._lineage})")
