"""Dependence analysis over one rank's extracted communication schedule.

The ordered-effect token chain serializes EVERY pair of world-tier ops —
that is the deadlock-freedom contract, but it is also the performance
ceiling: two transfers that share no channel and no data serialize
anyway.  This pass walks a rank's :class:`CommEvent` list (plus, on the
``analysis.check`` path, the jaxpr's buffer use/def chains) and keeps
only the dependence edges that are *semantically real*:

- ``channel``  — per-channel FIFO order: two send-parts to the same
  ``(comm, dest)``, or two recv-parts from the same ``(comm, source)``,
  must keep their relative order (the transport matches strictly
  in-order per channel);
- ``collective`` — collectives on one comm rendezvous at per-comm
  positions, so their sequence per comm is order-critical;
- ``wildcard`` — an ``ANY_SOURCE`` (or Status-filling) receive observes
  global arrival state: it conservatively serializes against every
  point-to-point event on its comm, in both directions;
- ``data``     — the payload of a later op is computed from an earlier
  op's output (jaxpr use/def chains; absent on the virtual-world path,
  where posts still happen in program order so payload provenance
  cannot reorder — see ``_plan``).

Everything else — the pure token edge between ops on disjoint channels —
is *artificial serialization*, and the schedule compiler (``_plan``) is
licensed to overlap across it.

Deliberately jax-free and import-light like ``_match``: the tier-1 suite
loads this standalone even on hosts whose jax predates the package
minimum.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ._events import (
    ANY_SOURCE,
    COLLECTIVE_KINDS,
    CommEvent,
)

#: cap on a concurrency group's size: bounds the prover's per-group
#: interleaving enumeration (4! = 24 orders) and the runner's
#: outstanding-ticket window
MAX_GROUP = 4


def send_channels(ev: CommEvent) -> List[Tuple]:
    """(comm, dest_local) keys of every send-part this event carries."""
    if ev.kind == "send":
        return [(ev.comm, ev.dest)]
    if ev.kind == "sendrecv":
        return [(ev.comm, ev.dest)]
    if ev.kind == "shift2":
        return [(ev.comm, p) for p in (ev.lo, ev.hi)
                if p is not None and p >= 0]
    return []


def recv_channels(ev: CommEvent) -> List[Tuple]:
    """(comm, source_local) keys of every recv-part; ANY_SOURCE recvs
    return the wildcard key ``(comm, ANY_SOURCE)``."""
    if ev.kind == "recv":
        return [(ev.comm, ev.source)]
    if ev.kind == "sendrecv":
        return [(ev.comm, ev.source)]
    if ev.kind == "shift2":
        return [(ev.comm, p) for p in (ev.lo, ev.hi)
                if p is not None and p >= 0]
    return []


def is_wildcard(ev: CommEvent) -> bool:
    """True for events whose matching depends on global arrival state:
    ANY_SOURCE receives and Status-filling receives (the Status records
    which message arrived, so even a directed one is order-observable)."""
    if ev.status:
        return True
    return ev.source == ANY_SOURCE


class DepGraph:
    """True-dependence DAG over one rank's schedule.

    ``preds[j]`` holds every i < j that j depends on; ``kind[(i, j)]``
    names the strongest reason (data > wildcard > channel > collective).
    """

    _STRENGTH = {"data": 3, "wildcard": 2, "channel": 1, "collective": 0}

    def __init__(self, n: int):
        self.n = n
        self.preds: List[set] = [set() for _ in range(n)]
        self.kind: Dict[Tuple[int, int], str] = {}

    def add(self, i: int, j: int, kind: str):
        if i < 0 or i == j:
            return
        if i > j:
            i, j = j, i
        old = self.kind.get((i, j))
        if old is None or self._STRENGTH[kind] > self._STRENGTH[old]:
            self.kind[(i, j)] = kind
        self.preds[j].add(i)

    def depends(self, i: int, j: int) -> bool:
        """Direct edge i -> j (i < j)."""
        return i in self.preds[j]

    def edge_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for k in self.kind.values():
            out[k] = out.get(k, 0) + 1
        return out

    def artificial_pairs(self) -> int:
        """Adjacent event pairs whose only ordering was the token chain —
        the serialization the plan is licensed to drop."""
        return sum(
            1 for j in range(1, self.n) if (j - 1) not in self.preds[j]
        )


def build_rank_deps(
    events: Sequence[CommEvent],
    value_deps: Optional[Iterable[Tuple[int, int]]] = None,
) -> DepGraph:
    """The dependence DAG for one rank's ordered schedule.

    ``value_deps`` is the jaxpr-derived set of (producer_pos,
    consumer_pos) pairs (positions into ``events``); None on the
    virtual-world path, where payload provenance cannot constrain the
    plan (posts stay in program order — see module docstring).
    """
    n = len(events)
    g = DepGraph(n)

    last_send: Dict[Tuple, int] = {}   # channel key -> last position
    last_recv: Dict[Tuple, int] = {}
    last_coll: Dict[Tuple, int] = {}   # comm -> last collective position
    last_wild: Dict[Tuple, int] = {}   # comm -> last wildcard position
    last_p2p: Dict[Tuple, int] = {}    # comm -> last p2p-part position

    for j, ev in enumerate(events):
        comm = ev.comm
        sends = send_channels(ev)
        recvs = recv_channels(ev)
        wild = is_wildcard(ev) and bool(recvs)

        if ev.kind in COLLECTIVE_KINDS:
            g.add(last_coll.get(comm, -1), j, "collective")
            last_coll[comm] = j
            continue

        for key in sends:
            g.add(last_send.get(key, -1), j, "channel")
            last_send[key] = j
        if wild:
            # serializes against every p2p event on the comm, both ways
            g.add(last_p2p.get(comm, -1), j, "wildcard")
            g.add(last_wild.get(comm, -1), j, "wildcard")
            last_wild[comm] = j
            # and every recv channel on the comm: a directed recv after a
            # wildcard could otherwise steal the head it would have taken
            for key in list(last_recv):
                if key[0] == comm:
                    last_recv[key] = j
        else:
            for key in recvs:
                g.add(last_recv.get(key, -1), j, "channel")
                last_recv[key] = j
            # recvs after a wildcard on the comm are pinned behind it
            if recvs:
                g.add(last_wild.get(comm, -1), j, "wildcard")
        if sends or recvs:
            prev_wild = last_wild.get(comm, -1)
            if prev_wild >= 0 and prev_wild != j:
                g.add(prev_wild, j, "wildcard")
            last_p2p[comm] = j

    if value_deps:
        for i, j in value_deps:
            if 0 <= i < n and 0 <= j < n and i != j:
                g.add(min(i, j), max(i, j), "data")
    return g


def concurrency_groups(
    events: Sequence[CommEvent],
    deps: DepGraph,
    max_group: int = MAX_GROUP,
) -> List[List[int]]:
    """Partition the schedule into consecutive groups of mutually
    independent events.

    A group's members may complete in any order at run time (the runner
    defers their completion waits); correctness requires that no member
    depends on another.  Collectives, wildcard and Status receives stay
    solo — their blocking structure is the program's synchronization.
    """
    groups: List[List[int]] = []
    cur: List[int] = []
    for j, ev in enumerate(events):
        solo = ev.kind in COLLECTIVE_KINDS or is_wildcard(ev)
        fits = (
            cur
            and not solo
            and len(cur) < max_group
            and all(not deps.depends(i, j) for i in cur)
            # a solo event never shares a group, in either role
            and not (events[cur[0]].kind in COLLECTIVE_KINDS
                     or is_wildcard(events[cur[0]]))
        )
        if fits:
            cur.append(j)
        else:
            if cur:
                groups.append(cur)
            cur = [j]
    if cur:
        groups.append(cur)
    return groups


def _engine_root(comm: Tuple) -> Tuple:
    """Events on one socket-owning communicator tree share ONE progress
    engine (sub-comms borrow the parent's sockets); the lineage's first
    element identifies the tree."""
    return comm[:1] if comm else comm


def recv_post_point(
    events: Sequence[CommEvent],
    deps: DepGraph,
    j: int,
) -> int:
    """The earliest safe POST point for the recv at position ``j``.

    Encoding: ``post_at == j`` posts at the op's own position (no
    hoist); ``post_at == p < j`` posts the recv's descriptor immediately
    after op ``p``'s own post — i.e. inside op ``p``'s host callback,
    before any host compute that separates the two callbacks.  The
    progress engine then reads the wire while the host is still
    computing, which is where the overlap win lives.

    Safety: the engine executes its queue FIFO, so the recv's *wire*
    position is pinned right after op ``p`` — hoisting it past a
    same-engine op would delay that op's wire activity behind a blocking
    read (the classic symmetric-exchange deadlock: both ranks' sends
    stuck in the queue behind both ranks' reads).  The planner therefore
    hoists only across

    - the host-compute gap to the immediately preceding op
      (``p = j - 1``: wire order provably unchanged), and
    - ops on a *different* engine root (independent socket set and
      progress thread: no FIFO coupling), provided they are not
      dependence predecessors of the recv.

    The equivalence prover replays the exact reordered wire schedule, so
    even a planner bug here is caught before anything executes it.
    """
    ev = events[j]
    if ev.kind != "recv" or is_wildcard(ev) or j == 0:
        return j
    root = _engine_root(ev.comm)
    p = j - 1  # post inside the previous op's callback: wire order kept
    while p > 0:
        passed = events[p]
        if _engine_root(passed.comm) == root or deps.depends(p, j):
            break
        p -= 1
    return p
