"""N-rank match simulation over extracted communication schedules.

Pure Python, jax-free: given each rank's ordered :class:`CommEvent` list,
simulate the transport's matching rules and report everything that cannot
match.  The model mirrors ``native/tpucomm.cc``:

- point-to-point channels are per ``(comm, src, dst)`` FIFOs with strict
  in-order matching — a directed receive takes the channel *head* and a
  mismatched tag/dtype/size is a fail-fast program error, exactly like the
  native abort (a finding here, so analysis can continue past it);
- sends are buffered (the sender never blocks on the receiver in the
  native framing), receives block;
- ``ANY_SOURCE`` receives take the first *compatible* channel head, and
  may skip channels whose head doesn't match a concrete tag (the
  transport's wildcard scan does the same);
- collectives rendezvous: every member of the comm must arrive at a
  collective on that comm at the same per-comm position, and all arrived
  signatures must agree (kind, reduce op, root, dtype, shape).

On top of the faithful model sits one conservative pass the runtime cannot
perform: :func:`order_critical_findings` flags rank pairs whose raw
send/recv traffic forms a cycle — schedules that are only correct while
strict program order holds (ordering.py's deadlock-by-construction shape).
Reordering (a lost token edge, a future relaxed transport) deadlocks them,
so the verifier reports the hazard as a warning with both call sites.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ._events import (
    ANY_SOURCE,
    ANY_TAG,
    COLLECTIVE_KINDS,
    CommEvent,
    Finding,
    event_nbytes,
)

MAX_FINDINGS = 200

#: mirror of native/tpucomm.cc kEagerBytes: the floor of the progress
#: engine's detached-send threshold (detach_threshold() there is
#: max(32 KB, MPI4JAX_TPU_COALESCE_BYTES))
ENGINE_DETACH_FLOOR = 32 * 1024


def default_coalesce_bytes() -> int:
    """Resolved MPI4JAX_TPU_COALESCE_BYTES with the native parser's
    clamps (default 4096; 0 = off).  The ONE analysis-side reading of
    the knob — the detach threshold below and the plan compiler's
    coalesce marks both derive from it, so they cannot drift apart.

    Read from the environment directly (not utils.config) so the match
    model stays standalone-loadable, the same contract as the wildcard
    sentinels above; the knob is declared in ``config.KNOBS``."""
    raw = os.environ.get("MPI4JAX_TPU_COALESCE_BYTES", "").strip()
    if raw:
        try:
            return max(0, min(int(raw), 64 * 1024))
        except ValueError:
            pass  # the native parser rejects it loudly; keep the default
    return 4096


def default_detach_threshold() -> int:
    """Bytes up to which a send is truly buffered (detached) at run time.

    Mirrors the native engine's rules: with the async progress engine on
    (MPI4JAX_TPU_PROGRESS_THREAD, default on) sends up to
    max(32 KB, MPI4JAX_TPU_COALESCE_BYTES) copy their payload and return
    immediately, so they can never rendezvous-block.  With the engine
    off every send writes inline and the historic conservative model
    (any send may block) applies — threshold 0.
    """
    raw = os.environ.get("MPI4JAX_TPU_PROGRESS_THREAD", "").strip().lower()
    if raw in ("0", "false", "off", "no"):
        return 0
    return max(ENGINE_DETACH_FLOOR, default_coalesce_bytes())


def send_part_event(ev: CommEvent, dest: int) -> CommEvent:
    """The buffered-send half of ``ev`` toward ``dest``, exactly as the
    matcher pushes it onto the channel: a plain ``send`` is itself; the
    combined ops (``sendrecv``, ``shift2``) synthesize a send event
    carrying the op's send tag and payload signature.  The symbolic
    (rank-symmetry) layer re-synthesizes concrete findings through this
    same constructor, so its lifted findings are byte-identical to the
    concrete simulation's."""
    if ev.kind == "send":
        return ev
    tag = ev.sendtag if ev.kind == "sendrecv" else ev.tag
    return CommEvent(
        rank=ev.rank, idx=ev.idx, kind="send", comm=ev.comm,
        dest=dest, tag=tag, dtype=ev.dtype, shape=ev.shape, site=ev.site,
    )


def _site_pair(a: CommEvent, b: CommEvent) -> Tuple[str, ...]:
    return tuple(
        f"rank {e.rank}: {e.describe()}" for e in (a, b) if e is not None
    )


def compare_p2p(send: CommEvent, recv: CommEvent) -> List[Finding]:
    """Findings for a send/recv pair the channel model has matched."""
    found = []
    want_tag = recv.tag if recv.tag is not None else recv.recvtag
    have_tag = send.tag if send.tag is not None else send.sendtag
    if want_tag not in (None, ANY_TAG) and have_tag != want_tag:
        found.append(Finding(
            "tag_mismatch",
            f"rank {send.rank} sends tag {have_tag} but rank {recv.rank} "
            f"expects tag {want_tag}",
            ranks=(send.rank, recv.rank), comm=send.comm,
            sites=_site_pair(send, recv),
        ))
    if send.dtype and recv.dtype and send.dtype != recv.dtype:
        found.append(Finding(
            "dtype_mismatch",
            f"rank {send.rank} sends {send.dtype} but rank {recv.rank} "
            f"receives into {recv.dtype}",
            ranks=(send.rank, recv.rank), comm=send.comm,
            sites=_site_pair(send, recv),
        ))
    elif send.shape is not None and recv.shape is not None \
            and send.shape != recv.shape:
        if recv.status:
            # a Status-filling receive accepts SHORT messages (the
            # native recv_status contract: the actual byte count lands
            # in the Status); only truncation is a program error
            send_nb = event_nbytes(send.dtype, send.shape)
            recv_nb = event_nbytes(recv.dtype, recv.shape)
            if send_nb is not None and recv_nb is not None \
                    and send_nb <= recv_nb:
                return found
        found.append(Finding(
            "shape_mismatch",
            f"rank {send.rank} sends shape {send.shape} but rank "
            f"{recv.rank} receives into shape {recv.shape}",
            ranks=(send.rank, recv.rank), comm=send.comm,
            sites=_site_pair(send, recv),
        ))
    return found


def compare_collective(events: Sequence[CommEvent]) -> List[Finding]:
    """Findings for one collective rendezvous (one event per member)."""
    found = []
    ref = events[0]
    ref_sig = ref.collective_signature()
    for ev in events[1:]:
        sig = ev.collective_signature()
        if sig == ref_sig:
            continue
        if ev.kind != ref.kind:
            kind, msg = "collective_mismatch", (
                f"rank {ref.rank} runs {ref.kind} while rank {ev.rank} "
                f"runs {ev.kind} at the same program position"
            )
        elif ev.kind in ("allreduce", "reduce", "scan") \
                and ev.reduce_op != ref.reduce_op:
            kind, msg = "reduce_op_mismatch", (
                f"{ev.kind}: rank {ref.rank} uses {ref.reduce_op} while "
                f"rank {ev.rank} uses {ev.reduce_op}"
            )
        elif ev.root != ref.root:
            kind, msg = "root_mismatch", (
                f"{ev.kind}: rank {ref.rank} uses root {ref.root} while "
                f"rank {ev.rank} uses root {ev.root}"
            )
        elif ev.dtype != ref.dtype:
            kind, msg = "dtype_mismatch", (
                f"{ev.kind}: rank {ref.rank} contributes {ref.dtype} "
                f"while rank {ev.rank} contributes {ev.dtype}"
            )
        else:
            kind, msg = "shape_mismatch", (
                f"{ev.kind}: rank {ref.rank} contributes shape "
                f"{ref.shape} while rank {ev.rank} contributes shape "
                f"{ev.shape}"
            )
        found.append(Finding(kind, msg, ranks=(ref.rank, ev.rank),
                             comm=ref.comm, sites=_site_pair(ref, ev)))
    return found


def order_critical_findings(
    schedules: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]] = None,
    detach_threshold: Optional[int] = None,
) -> List[Finding]:
    """Warn on cyclic raw send<->recv traffic between rank pairs.

    Fires when rank a both sends-to and receives-from rank b via separate
    ``send``/``recv`` calls (and b reciprocates): the match relies on every
    op executing exactly in program order.  Combined ``sendrecv``/
    ``shift2`` ops are exempt — they are the reorder-safe way to express
    the same exchange.

    Calibrated against the async progress engine's buffered sends: a
    send at or below ``detach_threshold`` bytes (default: the engine's
    detach threshold, see :func:`default_detach_threshold`) copies its
    payload and returns immediately, so an exchange whose sends on
    EITHER side all fit the threshold cannot rendezvous-block — the
    small side's send always completes, its recv then drains the peer,
    and the cycle is broken.  Only exchanges where both directions can
    actually block are flagged; unknown payload sizes stay conservative.
    """
    comms = comms or {}
    if detach_threshold is None:
        detach_threshold = default_detach_threshold()

    def can_block(send_ev: CommEvent) -> bool:
        nbytes = event_nbytes(send_ev.dtype, send_ev.shape)
        if nbytes is None:
            return True  # unknown payload: stay conservative
        return nbytes > detach_threshold

    def to_world(comm, local_rank):
        members = comms.get(comm)
        return local_rank if members is None else members[local_rank]

    sends: Dict[Tuple, CommEvent] = {}
    recvs: Dict[Tuple, CommEvent] = {}
    # whether ANY send on a direction can block: a small first send must
    # not mask a later above-threshold one on the same direction
    dir_blocks: Dict[Tuple, bool] = {}
    for rank, events in schedules.items():
        for ev in events:
            if ev.kind == "send":
                key = (ev.comm, rank, to_world(ev.comm, ev.dest))
                sends.setdefault(key, ev)
                dir_blocks[key] = dir_blocks.get(key) or can_block(ev)
            elif ev.kind == "recv" and ev.source != ANY_SOURCE:
                recvs.setdefault(
                    (ev.comm, rank, to_world(ev.comm, ev.source)), ev)
    found = []
    seen = set()
    ordered = sorted(
        sends.items(),
        key=lambda kv: (str(kv[0][0]), kv[1].rank, kv[1].idx),
    )
    for (comm, a, b), send_ab in ordered:
        key = (comm, frozenset((a, b)))
        if a == b or key in seen:
            continue
        recv_ab = recvs.get((comm, a, b))
        send_ba = sends.get((comm, b, a))
        recv_ba = recvs.get((comm, b, a))
        if recv_ab is None or send_ba is None or recv_ba is None:
            continue
        seen.add(key)
        if not (dir_blocks.get((comm, a, b))
                and dir_blocks.get((comm, b, a))):
            # EVERY send of at least one direction is a detached
            # buffered send at run time: that rank can never stall
            # before its recvs, so the exchange cannot deadlock under
            # any reordering
            continue
        found.append(Finding(
            "order_critical_exchange",
            f"ranks {a} and {b} exchange messages in both directions "
            "through separate send/recv calls, and both directions exceed "
            f"the buffered-send threshold ({detach_threshold} bytes): the "
            "schedule matches only under strict program-order execution "
            "(tokens/ordered effects intact); any reordering can "
            "rendezvous-block and deadlock. Prefer sendrecv() for "
            "bidirectional exchanges.",
            ranks=(a, b), comm=comm,
            sites=(
                f"rank {a}: {send_ab.describe()}",
                f"rank {a}: {recv_ab.describe()}",
                f"rank {b}: {send_ba.describe()}",
                f"rank {b}: {recv_ba.describe()}",
            ),
        ))
    return found


def wait_graph_findings(
    blocked: Dict[int, CommEvent],
    waits_on: Dict[int, Tuple[int, ...]],
    done: frozenset,
) -> List[Finding]:
    """Classify a stalled simulation: cycles among blocked ranks are
    deadlocks; waits on finished ranks are unmatched operations."""
    found = []
    # cycle detection over blocked ranks
    visiting, order = set(), []

    def _reach(r, path):
        if r in path:
            cycle = path[path.index(r):]
            return tuple(cycle)
        if r in visiting or r not in blocked:
            return None
        visiting.add(r)
        for peer in waits_on.get(r, ()):
            hit = _reach(peer, path + [r])
            if hit:
                return hit
        return None

    reported_cycles = set()
    for r in sorted(blocked):
        cyc = _reach(r, [])
        if cyc and frozenset(cyc) not in reported_cycles:
            reported_cycles.add(frozenset(cyc))
            arrow = " -> ".join(map(str, cyc + (cyc[0],)))
            found.append(Finding(
                "deadlock",
                f"cyclic wait: rank {arrow}; every rank in the cycle is "
                "blocked on a peer in the cycle",
                ranks=tuple(cyc),
                comm=blocked[cyc[0]].comm,
                sites=tuple(
                    f"rank {x}: {blocked[x].describe()}" for x in cyc
                ),
            ))
    in_cycle = set()
    for c in reported_cycles:
        in_cycle |= c
    for r in sorted(blocked):
        if r in in_cycle:
            continue
        ev = blocked[r]
        peers = waits_on.get(r, ())
        if ev.kind == "recv" and ev.source == ANY_SOURCE:
            found.append(Finding(
                "wildcard_starvation",
                f"rank {r} blocks on an ANY_SOURCE receive with no "
                "compatible send left on any channel",
                ranks=(r,), comm=ev.comm,
                sites=(f"rank {r}: {ev.describe()}",),
            ))
        elif ev.kind in COLLECTIVE_KINDS:
            missing = [p for p in peers]
            found.append(Finding(
                "collective_mismatch",
                f"rank {r} waits at {ev.kind} but rank(s) "
                f"{','.join(map(str, missing)) or '?'} never reach a "
                "collective on that communicator",
                ranks=(r,) + tuple(missing), comm=ev.comm,
                sites=(f"rank {r}: {ev.describe()}",),
            ))
        else:
            peer = peers[0] if peers else None
            state = "finished" if peer in done else "blocked elsewhere"
            found.append(Finding(
                "unmatched_recv" if ev.kind != "send" else "unmatched_send",
                f"rank {r} blocks on {ev.kind} from rank {peer}, which "
                f"{state} without a matching operation",
                ranks=(r,) + (() if peer is None else (peer,)),
                comm=ev.comm,
                sites=(f"rank {r}: {ev.describe()}",),
            ))
    return found


class _Channels:
    """Per (comm, src_local, dst_local) FIFO of buffered sends."""

    def __init__(self):
        self._q: Dict[Tuple, deque] = {}

    def push(self, comm, src, dst, event):
        self._q.setdefault((comm, src, dst), deque()).append(event)

    def head(self, comm, src, dst):
        q = self._q.get((comm, src, dst))
        return q[0] if q else None

    def pop(self, comm, src, dst):
        return self._q[(comm, src, dst)].popleft()

    def heads_for(self, comm, dst):
        """[(src, head_event)] over nonempty channels into ``dst``."""
        out = []
        for (c, s, d), q in sorted(self._q.items(),
                                   key=lambda kv: str(kv[0])):
            if c == comm and d == dst and q:
                out.append((s, q[0]))
        return out

    def leftovers(self):
        for (c, s, d), q in self._q.items():
            for ev in q:
                yield c, s, d, ev


def match_schedules(
    schedules: Dict[int, List[CommEvent]],
    comms: Dict[Tuple, Tuple[int, ...]],
    deliveries: Optional[dict] = None,
    service_order: Optional[Sequence[int]] = None,
    stats: Optional[dict] = None,
) -> List[Finding]:
    """Simulate matching of all rank schedules; return the findings.

    ``comms`` maps each comm key to its ordered world-rank member tuple
    (sub-rank i of the comm is world rank members[i]).

    ``deliveries``, when a dict is passed, is filled with the exact
    matching outcome — ``deliveries["p2p"][(comm, src, dst)]`` is the
    in-order list of ``(send_rank, send_idx, tag, recv_rank, recv_idx)``
    matches on that channel and ``deliveries["coll"][comm]`` the ordered
    collective rendezvous — so the schedule compiler's equivalence
    prover can assert a rewritten schedule delivers the same messages in
    the same per-channel order (payload content rides sends unchanged,
    so per-channel send identity ⇒ value identity).

    ``service_order`` overrides the deterministic rank-advance order
    (default: ascending) — the prover varies it to expose matches that
    depend on which rank the simulator happens to serve first
    (ANY_SOURCE races).

    ``stats``, when a dict is passed, receives ``{"steps": N}`` — the
    number of successful event completions the simulation performed
    (the scale harness charts this against the symbolic path's class-
    level step count).
    """
    findings: List[Finding] = []
    pcs = {r: 0 for r in schedules}
    chans = _Channels()
    if deliveries is not None:
        deliveries.setdefault("p2p", {})
        deliveries.setdefault("coll", {})

    def _rec_p2p(comm, src, dst, send_ev, recv_ev):
        if deliveries is None:
            return
        deliveries["p2p"].setdefault((comm, src, dst), []).append(
            (send_ev.rank, send_ev.idx, send_ev.tag,
             recv_ev.rank, recv_ev.idx)
        )

    def _rec_coll(comm, arrived):
        if deliveries is None:
            return
        deliveries["coll"].setdefault(comm, []).append(
            (arrived[0].kind,
             tuple(sorted((e.rank, e.idx) for e in arrived)))
        )
    total = sum(len(v) for v in schedules.values())
    for events in schedules.values():  # make reruns idempotent
        for ev in events:
            ev._sent = False

    def local(comm, world_rank):
        members = comms.get(comm)
        if members is None:
            return world_rank
        return members.index(world_rank)

    def world(comm, local_rank):
        members = comms.get(comm)
        if members is None:
            return local_rank
        return members[local_rank]

    def current(r):
        sched = schedules[r]
        return sched[pcs[r]] if pcs[r] < len(sched) else None

    def try_advance(r) -> bool:
        """Attempt to complete rank r's current event.  Returns True on
        progress (event completed or a send buffered)."""
        ev = current(r)
        if ev is None:
            return False
        me = local(ev.comm, r)
        if ev.kind == "send":
            chans.push(ev.comm, me, ev.dest, ev)
            pcs[r] += 1
            return True
        if ev.kind == "sendrecv":
            if not ev._sent:
                chans.push(ev.comm, me, ev.dest,
                           send_part_event(ev, ev.dest))
                ev._sent = True
            return _complete_recv(r, ev, me, ev.source, ev.recvtag)
        if ev.kind == "shift2":
            if not ev._sent:
                for peer in (ev.lo, ev.hi):
                    if peer is not None and peer >= 0:
                        chans.push(ev.comm, me, peer,
                                   send_part_event(ev, peer))
                ev._sent = True
            needed = [p for p in (ev.lo, ev.hi) if p is not None and p >= 0]
            if any(chans.head(ev.comm, p, me) is None for p in needed):
                return False
            for p in needed:
                sent = chans.pop(ev.comm, p, me)
                _rec_p2p(ev.comm, p, me, sent, ev)
                findings.extend(compare_p2p(sent, ev))
            pcs[r] += 1
            return True
        if ev.kind == "recv":
            return _complete_recv(r, ev, me, ev.source, ev.tag)
        if ev.kind in COLLECTIVE_KINDS:
            members = comms.get(ev.comm, tuple(sorted(schedules)))
            arrived = []
            for m in members:
                cur = current(m)
                if cur is None or cur.kind not in COLLECTIVE_KINDS \
                        or cur.comm != ev.comm:
                    return False
                arrived.append(cur)
            findings.extend(compare_collective(arrived))
            _rec_coll(ev.comm, arrived)
            for m in members:
                pcs[m] += 1
            return True
        return False  # unknown kind: skip defensively

    def _complete_recv(r, ev, me, source, tag) -> bool:
        if source == ANY_SOURCE:
            for src, head in chans.heads_for(ev.comm, me):
                head_tag = head.tag
                if tag in (None, ANY_TAG) or head_tag == tag:
                    sent = chans.pop(ev.comm, src, me)
                    _rec_p2p(ev.comm, src, me, sent, ev)
                    findings.extend(compare_p2p(sent, ev))
                    pcs[r] += 1
                    return True
            return False
        head = chans.head(ev.comm, source, me)
        if head is None:
            return False
        # strict in-order channel: the head is THE match; field
        # disagreements are findings (the native transport aborts here)
        sent = chans.pop(ev.comm, source, me)
        _rec_p2p(ev.comm, source, me, sent, ev)
        findings.extend(compare_p2p(sent, ev))
        pcs[r] += 1
        return True

    service = (list(service_order) if service_order is not None
               else sorted(schedules))
    steps = 0
    for _ in range(2 * total + 2):
        progressed = False
        for r in service:
            while try_advance(r):
                progressed = True
                steps += 1
                if stats is not None:
                    stats["steps"] = steps
                if len(findings) > MAX_FINDINGS:
                    findings.append(Finding(
                        "analysis_timeout",
                        f"more than {MAX_FINDINGS} findings; stopping",
                    ))
                    return findings
        if not progressed:
            break

    # ---- classify whatever could not complete -------------------------
    done = frozenset(r for r in schedules if current(r) is None)
    blocked = {r: current(r) for r in schedules if current(r) is not None}
    waits_on: Dict[int, Tuple[int, ...]] = {}
    for r, ev in blocked.items():
        if ev.kind in COLLECTIVE_KINDS:
            members = comms.get(ev.comm, tuple(sorted(schedules)))
            stragglers = []
            for m in members:
                cur = blocked.get(m)
                if m in done or (
                    cur is not None
                    and (cur.kind not in COLLECTIVE_KINDS
                         or cur.comm != ev.comm)
                ):
                    stragglers.append(m)
            waits_on[r] = tuple(stragglers)
        elif ev.kind in ("recv", "sendrecv"):
            if ev.source == ANY_SOURCE:
                members = comms.get(ev.comm, tuple(sorted(schedules)))
                waits_on[r] = tuple(m for m in members if m != r)
            else:
                waits_on[r] = (world(ev.comm, ev.source),)
        elif ev.kind == "shift2":
            needed = [p for p in (ev.lo, ev.hi) if p is not None and p >= 0]
            me = local(ev.comm, r)
            waits_on[r] = tuple(
                world(ev.comm, p) for p in needed
                if chans.head(ev.comm, p, me) is None
            )
        else:
            waits_on[r] = ()
    findings.extend(wait_graph_findings(blocked, waits_on, done))

    # ---- leftover buffered sends --------------------------------------
    consumed_pairs = set()
    for c, s, d, ev in chans.leftovers():
        dst_world = world(c, d)
        key = (c, s, d)
        if key in consumed_pairs:
            continue
        consumed_pairs.add(key)
        findings.append(Finding(
            "unmatched_send",
            f"rank {ev.rank} sends to rank {dst_world} (tag {ev.tag}) "
            "but no matching receive ever runs",
            ranks=(ev.rank, dst_world), comm=c,
            sites=(f"rank {ev.rank}: {ev.describe()}",),
        ))

    findings.extend(order_critical_findings(schedules, comms))
    return findings
