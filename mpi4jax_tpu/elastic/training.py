"""Checkpoint-resumed training across rank failures.

The loop the acceptance scenario runs (docs/elasticity.md): a DP
training job whose every step is a pure function of (state, step,
comm), checkpointed through ``utils/checkpoint.py``'s committed sharded
writer.  On :class:`RankFailure` the loop recovers the world
(``elastic.recover``), restores the last COMMITTED checkpoint, and
replays from there — steps after the last commit are recomputed on the
new world, so the trajectory continues exactly as if the job had been
restarted from that checkpoint by hand.

Works at the raw bridge level (numpy state, ``bridge.allreduce``
gradient sync — no jax) and at the ops level (jax pytrees,
``parallel.dp.sync_gradients``) alike: the loop never looks inside the
state.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Optional

from ..utils import checkpoint
from ._errors import is_rank_failure
from ._world import current_generation, my_slot, recover


def run(step_fn: Callable[[Any, int, Any], Any], init_state: Any, *,
        steps: int, directory: Optional[str] = None, save_every: int = 1,
        comm=None, replicated: bool = True, keep: Optional[int] = 3,
        max_recoveries: Optional[int] = None):
    """Run ``steps`` training steps elastically; returns the final
    state.

    ``step_fn(state, step, comm) -> state`` must be collective over
    ``comm`` and (for the resumed trajectory to be meaningful)
    deterministic given ``(state, step, world size)``.  The state is
    checkpointed every ``save_every`` steps — ``step_<k>`` holds the
    state AFTER ``k`` steps, and step 0 (the initial state) is
    committed up front so a failure before the first save still has a
    restore point.  ``replicated=True`` (the DP pattern: every rank
    holds identical state) is what allows a restore onto a SHRUNK
    world; pass False for truly sharded state, which then survives
    ``respawn`` recoveries only.

    On a failure the loop recovers, restores the newest committed
    checkpoint, and continues; ``max_recoveries`` bounds how many times
    (None = unbounded — the launcher's generation cap is the global
    backstop).
    """
    if comm is None:
        from ..runtime import transport

        comm = transport.get_world_comm()
    directory = checkpoint._resolve_dir(directory)

    recoveries = 0

    def bootstrap():
        """Restore the newest committed checkpoint, or commit step 0 so
        a failure before the first periodic save still has a restore
        point."""
        try:
            state, start, _ = checkpoint.restore_sharded(
                init_state, directory=directory, comm=comm)
            _log(f"resuming from step {start} "
                 f"(generation {current_generation()})")
            return state, start
        except FileNotFoundError:
            checkpoint.save_sharded(init_state, step=0,
                                    directory=directory, comm=comm,
                                    replicated=replicated, keep=keep)
            return init_state, 0

    # the bootstrap is collective (the step-0 commit barriers), so a
    # rank dying THERE must recover like a mid-step death would
    while True:
        try:
            state, start = bootstrap()
            break
        except BaseException as e:
            if not is_rank_failure(e):
                raise
            recoveries += 1
            if max_recoveries is not None and recoveries > max_recoveries:
                raise
            _log(f"bootstrap failed ({type(e).__name__}); recovering")
            recover(comm)

    step = start
    while step < steps:
        try:
            state = step_fn(state, step, comm)
            step += 1
            if step % max(int(save_every), 1) == 0 or step == steps:
                checkpoint.save_sharded(state, step=step,
                                        directory=directory, comm=comm,
                                        replicated=replicated, keep=keep)
        except BaseException as e:
            if not is_rank_failure(e):
                raise
            recoveries += 1
            if max_recoveries is not None and recoveries > max_recoveries:
                raise
            _log(f"step {step} failed ({type(e).__name__}); recovering")
            recover(comm)
            state, step, _ = checkpoint.restore_sharded(
                init_state, directory=directory, comm=comm)
            # the launcher's recovery post-mortem greps this line
            _log(f"resuming from step {step} "
                 f"(generation {current_generation()})")
    return state


def _log(msg: str) -> None:
    try:
        slot = my_slot()
    except RuntimeError:
        slot = -1
    print(f"[elastic] slot {slot}: {msg}", file=sys.stderr, flush=True)
