"""Continuous-batching inference serving that survives rank death.

This is the *toy* plane: full-sequence re-decode every iteration, no
KV cache, no admission control — kept small as the elastic regression
surface.  The real serving subsystem (KV-cache-backed decode,
prefill/decode disaggregation, admission control, SLO adaptation) is
:mod:`mpi4jax_tpu.serving` — see docs/serving.md.

A minimal serving harness over the world tier (docs/elasticity.md):
rank 0 is the *frontend* — it owns the request queue and the generation
state of every in-flight sequence — and every rank (frontend included)
is a *worker* computing next tokens for its slice of the running batch.

Continuous batching: each iteration decodes ONE token for every active
request; finished requests retire immediately and queued requests join
the next iteration's batch — no waiting for a full batch to drain.

Per iteration the frontend broadcasts the padded token matrix, every
rank decodes rows ``[rank*chunk, (rank+1)*chunk)`` with the
user-supplied ``decode_fn``, and an allgather returns all next tokens
to everyone.  Results are committed ONLY on the frontend after the full
exchange succeeded — so when a rank dies mid-iteration, nothing was
committed, the survivors recover (``elastic.recover``), and the same
active set is simply re-batched on the shrunk world: requests that were
in flight on the dead rank are retried, not lost.

Failure model: the frontend's request state lives in rank 0's process,
so rank 0 itself dying loses the in-flight sequences (clients must
retry; under the ``respawn`` policy the restarted frontend serves new
requests).  Any OTHER rank is expendable at any moment.

No jax required: ``decode_fn`` may be a numpy toy or a jitted model
(``examples/serve_gpt.py`` serves a GPT this way).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, List, Optional

import numpy as np

from ._errors import is_rank_failure
from ._world import recover

#: header opcodes (int64 header [op, nreq, seqlen] broadcast each turn)
_OP_STOP = 0
_OP_STEP = 1


class Request:
    """One generation request: ``tokens`` grows by one per decode
    iteration until ``max_new`` tokens were added (or ``eos`` showed
    up)."""

    def __init__(self, req_id, prompt, max_new: int):
        self.id = req_id
        self.prompt = [int(t) for t in prompt]
        self.tokens = list(self.prompt)
        self.max_new = int(max_new)
        self.done = False
        self.submitted_at = time.perf_counter()
        self.completed_at = None
        self.retries = 0  # decode iterations re-run due to recoveries

    @property
    def generated(self):
        return self.tokens[len(self.prompt):]

    @property
    def latency_s(self):
        if self.completed_at is None:
            return None
        return self.completed_at - self.submitted_at


def _bcast(comm, arr):
    from ..runtime import bridge

    return bridge.bcast(comm.handle, arr, 0)


def _allgather(comm, arr):
    from ..runtime import bridge

    return bridge.allgather(comm.handle, arr, comm.size())


def _decode_round(comm, decode_fn, toks, lengths):
    """One collective decode iteration (all ranks): returns the next
    token for every row.  ``toks`` is the right-padded int32 token
    matrix, ``lengths`` the true sequence lengths."""
    nreq = toks.shape[0]
    chunk = -(-nreq // comm.size())
    start = comm.rank() * chunk
    stop = min(nreq, start + chunk)
    out = np.zeros(chunk, np.int32)
    if start < stop:
        nxt = np.asarray(decode_fn(toks, lengths, start, stop),
                         np.int32).reshape(-1)
        if nxt.shape[0] != stop - start:
            raise ValueError(
                f"decode_fn returned {nxt.shape[0]} tokens for rows "
                f"[{start},{stop})")
        out[:stop - start] = nxt
    return _allgather(comm, out).reshape(-1)[:nreq]


def serve_worker(comm, decode_fn) -> None:
    """The non-frontend loop: follow the frontend's broadcasts until it
    says stop.  Recovers in place on rank failure (the frontend
    re-batches; this worker re-enters the loop on the shrunk world)."""
    while True:
        try:
            hdr = _bcast(comm, np.zeros(3, np.int64))
            if int(hdr[0]) == _OP_STOP:
                return
            nreq, seqlen = int(hdr[1]), int(hdr[2])
            lengths = _bcast(comm, np.zeros(nreq, np.int64))
            toks = _bcast(comm, np.zeros((nreq, seqlen), np.int32))
            _decode_round(comm, decode_fn, toks, lengths)
        except BaseException as e:
            if not is_rank_failure(e):
                raise
            recover(comm)
            if comm.rank() == 0:
                # Release the other survivors FIRST: they re-enter this
                # loop blocked in a bcast rooted at the new rank 0 — if
                # this promoted worker raised immediately, they would
                # hang there until the transport deadline with no idea
                # the frontend is gone.  Only after the survivors'
                # collective state is consistent (they received STOP
                # and returned) is the unrecoverable condition raised
                # here.
                try:
                    _bcast(comm, np.array([_OP_STOP, 0, 0], np.int64))
                except BaseException as stop_err:  # noqa: BLE001
                    if not is_rank_failure(stop_err):
                        raise
                raise RuntimeError(
                    "this worker became the frontend after recovery — "
                    "frontend state (the request queue) lived on the "
                    "dead rank 0 and cannot be reconstructed")


class Server:
    """The frontend (run on rank 0; every other rank runs
    :func:`serve_worker` with the same ``decode_fn``).

    ``decode_fn(toks, lengths, start, stop) -> int32[stop-start]``
    computes the next token for rows ``start..stop`` of the padded
    batch.  It must depend only on the row contents — not on rank or
    world size — so a retried iteration on a shrunk world produces the
    same tokens.
    """

    def __init__(self, comm, decode_fn, *, max_batch: int = 8,
                 eos: Optional[int] = None):
        if comm.rank() != 0:
            raise ValueError("Server runs on rank 0; other ranks run "
                             "serve_worker()")
        self.comm = comm
        self.decode_fn = decode_fn
        self.max_batch = int(max_batch)
        self.eos = eos
        self.queue: List[Request] = []
        self.completed: List[Request] = []
        self.recoveries = 0
        self._next_id = 0

    def submit(self, prompt, max_new: int, req_id=None) -> Request:
        if req_id is None:
            req_id = self._next_id
            self._next_id += 1
        req = Request(req_id, prompt, max_new)
        self.queue.append(req)
        return req

    @property
    def active(self):
        return [r for r in self.queue if not r.done]

    def step(self) -> List[Request]:
        """One continuous-batching iteration: decode one token for up
        to ``max_batch`` active requests; returns the requests that
        COMPLETED this iteration.  On a rank failure nothing is
        committed — the world recovers and the same requests are
        retried on the next call."""
        batch = self.active[:self.max_batch]
        if not batch:
            return []
        try:
            seqlen = max(len(r.tokens) for r in batch)
            toks = np.zeros((len(batch), seqlen), np.int32)
            lengths = np.zeros(len(batch), np.int64)
            for i, r in enumerate(batch):
                toks[i, :len(r.tokens)] = r.tokens
                lengths[i] = len(r.tokens)
            _bcast(self.comm,
                   np.array([_OP_STEP, len(batch), seqlen], np.int64))
            _bcast(self.comm, lengths)
            _bcast(self.comm, toks)
            nxt = _decode_round(self.comm, self.decode_fn, toks, lengths)
        except BaseException as e:
            if not is_rank_failure(e):
                raise
            self.recoveries += 1
            for r in batch:
                r.retries += 1
            recover(self.comm)
            print(f"[elastic] serving: recovered (world size now "
                  f"{self.comm.size()}); retrying {len(batch)} in-flight "
                  "request(s)", file=sys.stderr, flush=True)
            return []
        # the commit point: everything above is replayable
        done_now = []
        for i, r in enumerate(batch):
            r.tokens.append(int(nxt[i]))
            if (len(r.generated) >= r.max_new
                    or (self.eos is not None and int(nxt[i]) == self.eos)):
                r.done = True
                r.completed_at = time.perf_counter()
                done_now.append(r)
                self.completed.append(r)
        self.queue = [r for r in self.queue if not r.done]
        return done_now

    def run_until_drained(self, *, max_iters: int = 100000):
        """Decode until no request is active; returns all completed
        requests."""
        it = 0
        while self.active:
            it += 1
            if it > max_iters:
                raise RuntimeError(
                    f"serving did not drain within {max_iters} "
                    "iterations")
            self.step()
        return self.completed

    def stop(self) -> None:
        """Release the workers (broadcast the stop opcode)."""
        try:
            _bcast(self.comm, np.array([_OP_STOP, 0, 0], np.int64))
        except BaseException as e:
            if not is_rank_failure(e):
                raise
