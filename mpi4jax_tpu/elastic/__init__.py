"""Elastic worlds: surviving a dead rank instead of dying with it.

PR 2 made failure *detection* bounded (deadlines + poison-frame abort
propagation tear a wedged group down in one deadline); this package
converts that machinery into *recovery* (docs/elasticity.md):

- with ``MPI4JAX_TPU_ELASTIC`` set (``launch --elastic`` sets it), a
  transport failure raises :class:`RankFailure` in Python — after
  poisoning every peer so the whole group unblocks and reaches its own
  recovery point — instead of hard-exiting the process;
- :func:`recover` waits for the elastic launcher's next *generation*
  announcement (which names the survivors, their dense renumbering,
  and a re-derived port block) and rebuilds the world communicator over
  the survivors through the native ``tpucomm_shrink`` bootstrap,
  rebinding the existing :class:`~mpi4jax_tpu.WorldComm` in place so
  every reference keeps working;
- :mod:`~mpi4jax_tpu.elastic.training` runs a checkpoint-resumed
  training loop across recoveries (sharded atomic checkpoints from
  ``utils/checkpoint.py``); :mod:`~mpi4jax_tpu.elastic.serving` is a
  continuous-batching inference harness that keeps answering requests
  across an injected rank death.

The package is stdlib+numpy importable (no jax) so the recovery path
works at the raw bridge level too.  Everything is deterministic under
``MPI4JAX_TPU_FAULT``, which is how the test suite drives it.
"""

from ._errors import RankFailure, is_rank_failure  # noqa: F401
from ._world import (  # noqa: F401
    Recovery,
    current_generation,
    my_slot,
    read_generation,
    recover,
    wait_for_generation,
)
from . import serving, training  # noqa: F401

__all__ = [
    "RankFailure",
    "Recovery",
    "current_generation",
    "is_rank_failure",
    "my_slot",
    "read_generation",
    "recover",
    "serving",
    "training",
    "wait_for_generation",
]
