"""The failure type elastic recovery is built around (stdlib-only)."""

from __future__ import annotations


class RankFailure(RuntimeError):
    """A world-tier transport operation failed because a peer died,
    hung past its deadline, or aborted.

    Raised by the bridge's abort path when ``MPI4JAX_TPU_ELASTIC`` is
    set (the non-elastic contract is unchanged: print + ``os._exit``).
    By the time this surfaces, every peer socket has been poisoned and
    shut down — the old communicator is unusable and every surviving
    rank is unblocking toward its own :func:`mpi4jax_tpu.elastic
    .recover` call.  ``op`` names the transport entry that failed.
    """

    def __init__(self, message: str, *, op: str = "?"):
        super().__init__(message)
        self.op = op


def is_rank_failure(exc: BaseException) -> bool:
    """True when ``exc`` is, wraps, or was caused by a
    :class:`RankFailure`.

    A failure inside a jit-compiled program surfaces through jax's
    callback machinery (``XlaRuntimeError`` with the original traceback
    embedded as text), so the cause chain walk is backed by a string
    probe — coarse, but a transport failure string inside an
    XlaRuntimeError in elastic mode has exactly one meaning.
    """
    seen = set()
    stack = [exc]
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        if isinstance(e, RankFailure):
            return True
        stack.extend((e.__cause__, e.__context__))
    text = f"{type(exc).__name__}: {exc}"
    return "RankFailure" in text or "tpucomm_" in text
