"""World reconstruction after a rank failure.

Protocol (docs/elasticity.md): the elastic launcher supervises the rank
group.  When a rank dies it announces a new world *generation* — an
atomically written ``gen_<n>.json`` in ``MPI4JAX_TPU_ELASTIC_DIR``
carrying the member map (original launcher *slot* → dense new rank, or
-1 for lost slots), the new world size, and a re-derived base port.
Surviving ranks catch :class:`RankFailure`, call :func:`recover`, and:

1. wait (bounded by ``MPI4JAX_TPU_ELASTIC_GRACE_S``) for the next
   generation announcement;
2. look up their own new rank by their launcher slot (the
   ``MPI4JAX_TPU_RANK`` this process was BORN with — slots never
   renumber, so maps from consecutive generations compose trivially);
3. rebuild the native communicator over the survivors through
   ``tpucomm_shrink`` — the same bootstrap dialer as ``tpucomm_init``,
   bounded by ``MPI4JAX_TPU_CONNECT_TIMEOUT_S`` — and rebind the
   process's :class:`~mpi4jax_tpu.WorldComm` *in place*, so every held
   reference (jitted closures, the default-comm stack) keeps working.

Renumbering is dense (0..new_size-1), so every rank/size invariant the
static verifier proved about a program's schedule shape holds on the
shrunk world too — a schedule valid for *any* np stays valid.
np-specific *plans* are elastic-safe: ``bridge.rebuild`` re-derives and
re-PROVES the plan for the new world size inside the recovery (from
the ``MPI4JAX_TPU_PLAN`` bundle or a ``planrt.set_plan_source``
callback) and installs it only when the fresh proof passes — a
recovered job keeps its overlap (docs/elasticity.md § Plans survive
recovery).

Under the ``respawn`` policy the announcement keeps the original size
and an identity map; the launcher restarts the dead slot's program in a
fresh process that joins the new bootstrap via plain ``comm_init``
(its environment carries the new generation and coordinates).
"""

from __future__ import annotations

import json
import os
import sys
import time

from ..utils import config
from ._errors import RankFailure

#: the live world generation of this process: starts at the generation
#: the process was born into (MPI4JAX_TPU_GENERATION, 0 for the
#: original world) and advances on every successful recover()
_generation = None

#: newest generation this process ATTEMPTED to join (a failed bootstrap
#: must not re-target the same announcement in a tight loop)
_last_attempted = None


def current_generation() -> int:
    """The world generation this process currently belongs to."""
    global _generation
    if _generation is None:
        _generation = config.generation()
    return _generation


def my_slot() -> int:
    """This process's original launcher slot.  Slots never renumber
    across generations; the generation maps key on them.  For
    generation-0 ranks the slot IS the spawn rank
    (``MPI4JAX_TPU_RANK``); a respawned child may bootstrap with a
    different dense rank, so the launcher gives it its slot identity
    separately (``MPI4JAX_TPU_SLOT``)."""
    raw = os.environ.get("MPI4JAX_TPU_SLOT",
                         os.environ.get("MPI4JAX_TPU_RANK"))
    if raw is None:
        raise RuntimeError(
            "not a world-tier rank (MPI4JAX_TPU_RANK unset); elastic "
            "recovery needs the launcher")
    return int(raw)


def _gen_path(gen_dir: str, n: int) -> str:
    return os.path.join(gen_dir, f"gen_{int(n)}.json")


def read_generation(gen_dir: str, n: int):
    """The generation-``n`` announcement dict, or None when it has not
    been (fully) written yet."""
    try:
        with open(_gen_path(gen_dir, n)) as f:
            spec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if int(spec.get("generation", -1)) != int(n):
        return None
    return spec


def wait_for_generation(n: int, *, grace_s=None, gen_dir=None):
    """Poll the coordination directory until generation >= ``n`` is
    announced; returns the NEWEST announcement (deaths can outpace
    recoveries — a survivor always joins the latest membership).
    Raises :class:`RankFailure` when the grace period expires."""
    gen_dir = gen_dir or config.elastic_dir()
    if gen_dir is None:
        raise RuntimeError(
            "MPI4JAX_TPU_ELASTIC_DIR unset: elastic recovery needs the "
            "launcher's --elastic mode (or an explicit gen_dir)")
    grace_s = config.elastic_grace_s() if grace_s is None else grace_s
    deadline = time.monotonic() + grace_s
    while True:
        newest = None
        k = int(n)
        while True:
            spec = read_generation(gen_dir, k)
            if spec is None:
                break
            newest = spec
            k += 1
        if newest is not None:
            return newest
        if time.monotonic() >= deadline:
            raise RankFailure(
                f"no generation >= {n} announced within {grace_s:g} s "
                f"(MPI4JAX_TPU_ELASTIC_GRACE_S) in {gen_dir}; giving up",
                op="recover")
        time.sleep(0.05)


class Recovery:
    """What one successful :func:`recover` produced."""

    def __init__(self, *, generation, world, rank, size, old_to_new,
                 lost, policy, base_port):
        self.generation = int(generation)
        self.world = world            # the rebound WorldComm
        self.rank = int(rank)         # this process's NEW dense rank
        self.size = int(size)
        self.old_to_new = dict(old_to_new)  # slot -> new rank (-1 = lost)
        self.lost = list(lost)              # slots lost so far (cumulative)
        self.policy = policy
        self.base_port = int(base_port)

    def __repr__(self):
        return (f"Recovery(gen={self.generation}, rank={self.rank}/"
                f"{self.size}, lost={self.lost}, policy={self.policy})")


def recover(world=None, *, grace_s=None):
    """Rebuild the world communicator over the surviving ranks.

    Call after catching :class:`RankFailure` (or anything
    :func:`is_rank_failure` recognizes).  Blocks until the launcher
    announces the next generation, then runs the native shrink
    bootstrap and rebinds ``world`` (default: the process world comm)
    in place.  Raises :class:`RankFailure` again when this process was
    declared lost, the announcement never arrives, or the rebuilt
    bootstrap itself fails — the caller's recovery loop may retry (a
    newer generation supersedes a failed one) or let it propagate (the
    launcher then counts this rank lost and announces yet another
    generation to the remaining survivors).
    """
    global _generation, _last_attempted
    from ..runtime import bridge, transport

    if world is None:
        world = transport.get_world_comm()
    slot = my_slot()
    cur = current_generation()
    if _last_attempted is not None:
        cur = max(cur, _last_attempted)
    # a missing dial deadline would let a recovery wait on a peer that
    # is never coming; the knobs below only tighten unset defaults —
    # explicit operator settings win (os.environ.setdefault)
    os.environ.setdefault("MPI4JAX_TPU_CONNECT_TIMEOUT_S", "30")
    spec = wait_for_generation(cur + 1, grace_s=grace_s)
    gen = int(spec["generation"])
    _last_attempted = gen
    mapping = {int(k): int(v) for k, v in spec.get("map", {}).items()}
    new_rank = mapping.get(slot, -1)
    if new_rank < 0:
        raise RankFailure(
            f"slot {slot} was declared lost in generation {gen} "
            "(the launcher presumed this rank dead)", op="recover")
    new_size = int(spec["size"])
    base_port = int(spec["base_port"])
    hosts = spec.get("hosts", "") or ""
    # children forked/spawned after this point (and the obs re-arm
    # inside the rebuild) must see the new generation
    os.environ["MPI4JAX_TPU_GENERATION"] = str(gen)
    handle = bridge.rebuild(world._handle, new_rank, new_size, base_port,
                            hosts)
    host = (hosts.split(",")[0] if hosts else "127.0.0.1")
    world._rebind(new_rank, new_size, f"{host}:{base_port}", handle)
    _generation = gen
    # stderr: the launcher pumps rank stderr and greps these for its
    # recovery post-mortem
    print(f"[elastic] slot {slot}: recovered into generation {gen} as "
          f"rank {new_rank}/{new_size} (lost slots: {spec.get('lost')})",
          file=sys.stderr, flush=True)
    return Recovery(
        generation=gen, world=world, rank=new_rank, size=new_size,
        old_to_new=mapping, lost=spec.get("lost", []),
        policy=spec.get("policy", "shrink"), base_port=base_port)
