"""World-tier op implementations (multi-process, native transport).

Each op here is a JAX primitive carrying an ordered effect
(utils/effects.py), lowered to a custom call / host callback into the native
C++ transport — the structural twin of the reference's Cython bridge stack
(/root/reference/mpi4jax/_src/xla_bridge/).

Status: primitives land with the native transport (native/); until then every
entry raises with guidance so the mesh tier (the TPU fast path) is never
blocked on it.
"""

from __future__ import annotations

_MSG = (
    "the world tier (one process per rank over the native transport) for "
    "'{op}' is not built in this checkout stage; use the mesh tier "
    "(mpi4jax_tpu.spmd over a device Mesh) instead"
)


def _todo(op):
    raise NotImplementedError(_MSG.format(op=op))


def allreduce(x, op, comm):
    _todo("allreduce")


def allgather(x, comm):
    _todo("allgather")


def alltoall(x, comm):
    _todo("alltoall")


def barrier(comm, token):
    _todo("barrier")


def bcast(x, root, comm):
    _todo("bcast")


def reduce(x, op, root, comm):
    _todo("reduce")


def gather(x, root, comm):
    _todo("gather")


def scatter(x, root, comm):
    _todo("scatter")


def scan(x, op, comm):
    _todo("scan")


def send(x, dest, tag, comm, token):
    _todo("send")


def recv(x, source, tag, comm, token):
    _todo("recv")


def sendrecv_dispatch(x, *, perm, shift, wrap, comm, token):
    _todo("sendrecv")
