"""World-tier op implementations: JAX primitives over the native transport.

This is the ordered-effects core design the reference's experimental notoken
layer pioneered (SURVEY.md §2.2, notoken/collective_ops/allreduce.py:94-187
there) promoted to first-class, on jax 0.9 APIs: every op is a JAX primitive
that

- declares the framework's ordered ``CommEffect`` (utils/effects.py) in its
  abstract eval — the compiler threads a runtime token through all world ops
  in program order, which *is* the deadlock-freedom contract
  (docs/sharp-bits.rst of the reference);
- lowers to a host callback via ``emit_python_callback`` with explicit
  ``ctx.tokens_in``/``set_tokens_out`` plumbing — on TPU this callback is
  the HBM→TPU-VM-host staging path over DCN, the structural twin of the
  reference GPU bridge's sync → copy-to-host → MPI → copy-back
  (mpi_xla_bridge_gpu.pyx:233-251);
- executes the native C++ transport (runtime/bridge.py → native/tpucomm.cc)
  on the host buffers;
- carries reference-parity AD rules registered directly on the primitive:
  allreduce(SUM) JVP + identity transpose (allreduce.py:188-218 there),
  sendrecv JVP + source/dest-swapping transpose (sendrecv.py:390-409), and
  elementwise batching where semantics allow.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax._src import core
from jax._src import callback as _jax_callback
from jax._src import dispatch as _jax_dispatch
from jax._src.interpreters import mlir
from jax._src.lib.mlir import ir
from jax.interpreters import ad, batching

from ..utils import tracing
from ..utils.effects import comm_effect, unordered_comm_effect
from .reduce_ops import ALL_OPS, ReduceOp

_OP_CODE = {op.name: i for i, op in enumerate(ALL_OPS)}

_STAGED_EAGER = None

# ---------------- static-analysis hooks (mpi4jax_tpu.analysis) ----------
#
# Each primitive exports its *schedule signature* — how to read one
# communication event off its params — and every eager impl offers itself
# to an installed analysis executor before touching the real transport.
# The executor (analysis._sim.VirtualWorld) owns ops whose comm is an
# AbstractComm; with none installed the hooks are two predicate checks.

#: primitive base name -> param roles for the communication verifier.
#: "peer"-valued entries name primitive params holding comm-local ranks;
#: token variants ("mpi4jax_tpu_<name>_t") share the base signature.
SCHEDULE_SIGNATURES = {
    "allreduce": {"kind": "allreduce", "reduce_op": "op"},
    "reduce": {"kind": "reduce", "reduce_op": "op", "root": "root"},
    "scan": {"kind": "scan", "reduce_op": "op"},
    "bcast": {"kind": "bcast", "root": "root"},
    "allgather": {"kind": "allgather"},
    "gather": {"kind": "gather", "root": "root"},
    "scatter": {"kind": "scatter", "root": "root"},
    "alltoall": {"kind": "alltoall"},
    "barrier": {"kind": "barrier"},
    "send": {"kind": "send", "dest": "dest", "tag": "tag"},
    "recv": {"kind": "recv", "source": "source", "tag": "tag"},
    "sendrecv": {"kind": "sendrecv", "source": "source", "dest": "dest",
                 "sendtag": "sendtag", "recvtag": "recvtag"},
    "shift2": {"kind": "shift2", "lo": "lo", "hi": "hi", "tag": "tag"},
}


def schedule_signature(prim_name: str):
    """(base_name, signature, is_token_variant) for a world primitive
    name, or None for foreign primitives."""
    if not prim_name.startswith("mpi4jax_tpu_"):
        return None
    base = prim_name[len("mpi4jax_tpu_"):]
    token_variant = base.endswith("_t")
    if token_variant:
        base = base[:-2]
    sig = SCHEDULE_SIGNATURES.get(base)
    if sig is None:
        return None
    return base, sig, token_variant


_analysis_executor = None


def _set_analysis_executor(executor):
    """Install (or with None remove) the virtual-world executor that
    serves world-tier impls during program analysis."""
    global _analysis_executor
    _analysis_executor = executor


def _analysis_intercept(prim_name, args, params):
    """Route an eager bind to the analysis executor when one is installed
    and owns the op's comm.  Returns None when the op should execute
    normally."""
    ex = _analysis_executor
    if ex is not None and ex.owns(params.get("comm")):
        return ex.run_primitive(prim_name, args, params)
    return None


# During virtual-world analysis everything executes eagerly, so the token
# chain guard below — which normally watches tracers — is handed a
# per-rank-thread pseudo-trace to key its state on, plus a hook that turns
# its warnings into structured findings.  Both are None outside analysis.
_analysis_token_trace = None   # fn(tok=None) -> pseudo-trace object
_analysis_warn_hook = None     # fn(comm, n_heads, how) -> None


def _set_analysis_token_hooks(token_trace, warn_hook):
    global _analysis_token_trace, _analysis_warn_hook
    _analysis_token_trace = token_trace
    _analysis_warn_hook = warn_hook

# ---------------- ordering mode ----------------
#
# JAX refuses ORDERED effects in computations spanning more than one
# device ("ordered effects are not supported for more than 1 device"),
# so a jit that mixes mesh-tier shard_map collectives with world-tier
# ops — the TPU-pod composition shape, SURVEY §7 hard part 4 — cannot
# carry the ordered CommEffect.  Inside ``explicit_token_ordering()``
# world primitives bind with the UNORDERED effect instead, and ordering
# becomes the caller's explicit token chain (the reference's primary L1
# design: tokens as data dependencies, docs/sharp-bits.rst there).  Use
# the ``compat.token_api`` signatures, threading every token.

# A jax config state (not a bare global) so the mode participates in the
# jit cache key and trace context: a function traced inside the context
# must never be silently reused outside it (and vice versa).
from ..utils import jax_compat as _jax_compat  # noqa: E402

_explicit_tokens_cfg = _jax_compat.bool_state(
    name="mpi4jax_tpu_explicit_tokens",
    default=False,
    help=(
        "world-tier ops trace with the unordered effect; ordering is the "
        "caller's explicit token chain (multi-device composition mode)"
    ),
    include_in_jit_key=True,
    include_in_trace_context=True,
)


def _ordered_now() -> bool:
    return not _explicit_tokens_cfg.value


import contextlib  # noqa: E402


@contextlib.contextmanager
def explicit_token_ordering():
    """Context manager: world ops trace with the unordered effect.

    Required for jitted programs that span multiple local devices (e.g.
    mesh-tier ``shard_map`` collectives composed with world-tier ops in
    one step).  Ordering of world ops is then carried ONLY by explicit
    token chains (``mpi4jax_tpu.compat.token_api``) or value dataflow —
    exactly the reference's token contract: unthreaded tokens mean
    undefined order.

    Backed by a jax config state, so the mode is part of the jit cache
    key — a function jitted under the context retraces (with ordered
    effects) when later called outside it.

    While the context is active, a trace-time chain guard watches for
    the reference's sharpest bit: a world op binding a FRESH token while
    other ops on the same comm chain theirs in the same trace (undefined
    order → deadlock at run time).  Default: warn; set
    ``MPI4JAX_TPU_STRICT_TOKENS=1`` to raise at trace time instead.
    """
    with _explicit_tokens_cfg(True):
        _chain_guard.enter()
        try:
            yield
        finally:
            _chain_guard.exit()


class _TokenChainGuard:
    """Trace-time detector for unthreaded/forked token chains.

    Live chain heads (tokens returned by world ops, not yet consumed)
    are tracked per ``(comm, trace)`` while ``explicit_token_ordering``
    is active.  Only *tracers* are tracked: a concrete (eager) token
    executes in Python order, where no reordering hazard exists; and
    keying by the tracer's trace object keeps separate jit traces (and
    scan bodies, which trace inner) from cross-polluting.

    ``create_token(x)`` with a data tie registers a *rooted* token —
    starting a new chain from one is legitimate (ordering rides the
    dataflow, e.g. a scan carry); a bare ``create_token()`` registers a
    *fresh* token.  Binding a KNOWN-fresh token while the same comm has
    a live head in the same trace is the footgun the reference can only
    document (docs/sharp-bits.rst:6-34 there).  Tokens the guard has
    never seen (e.g. a chained token that passed through ``lax.cond`` or
    a remat boundary and re-emerged as a new tracer) are NOT flagged —
    zero false positives on correct programs beats flagging every
    transform boundary — and the bind-side side chain orders them
    safely regardless.
    """

    def __init__(self):
        self._depth = 0
        # (id(comm), id(trace)) -> [weakref(trace), set of id(token)].
        # Only token IDS are stored (the jaxpr under construction keeps
        # the tracers — and therefore their ids — alive for the trace's
        # lifetime); the trace weakref prunes a bucket once its trace is
        # collected, so a long-lived explicit_token_ordering() context
        # does not accumulate state across retraces.
        self._heads = {}
        self._rooted = {}   # id(trace) -> [weakref(trace), set of id(tok)]
        self._fresh = {}    # id(trace) -> [weakref(trace), set of id(tok)]

    def enter(self):
        self._depth += 1
        self._prune()

    def exit(self):
        self._depth -= 1
        if self._depth <= 0:
            self._depth = 0
            self._heads.clear()
            self._rooted.clear()
            self._fresh.clear()

    @property
    def active(self):
        return self._depth > 0

    def _prune(self):
        for store in (self._heads, self._rooted, self._fresh):
            dead = [k for k, v in store.items() if v[0]() is None]
            for k in dead:
                del store[k]

    @staticmethod
    def _wref(trace):
        import weakref

        try:
            return weakref.ref(trace)
        except TypeError:
            return lambda: trace  # unweakrefable: keep (bounded by prune)

    @staticmethod
    def _trace_of(tok):
        import jax

        if isinstance(tok, jax.core.Tracer):
            return getattr(tok, "_trace", None)
        if _analysis_token_trace is not None:
            # virtual-world analysis: concrete tokens, Python-ordered per
            # rank thread — key chain state on the thread's pseudo-trace
            return _analysis_token_trace(tok)
        return None

    def note_rooted(self, tok):
        trace = self._trace_of(tok) if self.active else None
        if trace is None:
            return
        ent = self._rooted.setdefault(id(trace), [self._wref(trace), set()])
        ent[1].add(id(tok))

    def note_fresh(self, tok):
        trace = self._trace_of(tok) if self.active else None
        if trace is None:
            return
        ent = self._fresh.setdefault(id(trace), [self._wref(trace), set()])
        ent[1].add(id(tok))

    def _is_rooted(self, trace, tok):
        ent = self._rooted.get(id(trace))
        return ent is not None and id(tok) in ent[1]

    def _is_fresh(self, trace, tok):
        ent = self._fresh.get(id(trace))
        return ent is not None and id(tok) in ent[1]

    def note_op(self, comm, tok_in, tok_out):
        if not self.active:
            return
        trace = self._trace_of(tok_in)
        if trace is None:
            return
        if len(self._heads) > 32:
            self._prune()
        key = (id(comm), id(trace))
        ent = self._heads.setdefault(key, [self._wref(trace), set()])
        heads = ent[1]
        if id(tok_in) in heads:
            heads.discard(id(tok_in))       # chain continues
        elif heads and self._is_fresh(trace, tok_in):
            self._warn(comm, len(heads), "binding a fresh (unrooted) token")
        heads.add(id(tok_out))

    def note_unthreaded(self, comm):
        """A world op traced with NO token at all (primary tokenless
        signature) inside explicit mode: undefined order against any
        live chain on the same comm in the current trace."""
        if not self.active:
            return
        trace = getattr(core.trace_ctx, "trace", None)
        if trace is None or type(trace).__name__ == "EvalTrace":
            if _analysis_token_trace is None:
                return
            trace = _analysis_token_trace()
        ent = self._heads.get((id(comm), id(trace)))
        if ent and ent[1]:
            self._warn(comm, len(ent[1]), "traced with no token")

    def _warn(self, comm, n_heads, how):
        import warnings

        from ..utils import config as _config

        if _analysis_warn_hook is not None:
            _analysis_warn_hook(comm, n_heads, how)

        msg = (
            f"explicit_token_ordering: a world op on comm {comm!r} is "
            f"{how} while {n_heads} other "
            "token chain(s) on the same comm are live in this trace — "
            "the ops' relative order is UNDEFINED and can deadlock at "
            "run time.  Thread the previous op's token (or root a new "
            "chain with create_token(x) tied to a value that depends on "
            "it).  Set MPI4JAX_TPU_STRICT_TOKENS=1 to make this an "
            "error, or =0 to silence it."
        )
        strict = _config.flag("MPI4JAX_TPU_STRICT_TOKENS", None)
        if strict:
            raise RuntimeError(msg)
        if strict is None:
            warnings.warn(msg, stacklevel=4)


_chain_guard = _TokenChainGuard()


def _use_staged_eager() -> bool:
    """True when the local backend cannot run host callbacks inside
    compiled programs, so *eager* world ops must stage HBM↔host
    explicitly in Python instead.

    Known case: the axon TPU tunnel's PJRT plugin reports
    ``UNIMPLEMENTED: axon_pjrt does not support host send/recv
    callbacks`` (and the *ordered* callback path hangs rather than
    erroring).  Real TPU VMs (libtpu) support send/recv callbacks and
    keep the in-program ordered-callback path.  Staged-eager dispatch
    preserves the ordering contract trivially: Python program order is
    execution order.  Override with ``MPI4JAX_TPU_STAGED_EAGER=0/1``.

    Detection: the tunnel registers as platform "tpu", so the plugin is
    identified by the PJRT ``platform_version`` string ("axon x.y.z"),
    which costs no compile.
    """
    global _STAGED_EAGER
    if _STAGED_EAGER is None:
        import os

        env = os.environ.get("MPI4JAX_TPU_STAGED_EAGER", "").strip().lower()
        if env in ("1", "true", "on", "yes"):
            _STAGED_EAGER = True
        elif env in ("0", "false", "off", "no"):
            _STAGED_EAGER = False
        elif jax.default_backend() == "cpu":
            _STAGED_EAGER = False
        else:
            ver = getattr(
                jax.devices()[0].client, "platform_version", ""
            )
            _STAGED_EAGER = "axon" in str(ver).lower()
    return _STAGED_EAGER


def _contig(x) -> np.ndarray:
    # NB: np.ascontiguousarray promotes 0-d to 1-d; np.asarray + explicit
    # copy preserves shape
    a = np.asarray(x)
    return a if a.flags.c_contiguous else a.copy(order="C")


def _np(x, aval):
    return _contig(np.asarray(x, dtype=aval.dtype))


def _emit_unordered_callback(ctx, callback, args):
    """Side-effecting host callback with no compiler token (explicit-token
    mode): the SPMD partitioner requires a sharding on side-effecting
    custom calls; MAXIMAL-on-device-0 runs the transport once per process
    (jax's own pure_callback convention).  Ordering is the caller's
    token/dataflow chain."""
    op_sharding = _jax_callback._callback_op_sharding(
        ctx.module_context.axis_context, None, ctx.avals_out
    )
    results, _, _ = _jax_callback.emit_python_callback(
        ctx, callback, None, list(args), ctx.avals_in, ctx.avals_out,
        has_side_effect=True, returns_token=False, sharding=op_sharding,
    )
    return results


def _staged_result_device(args):
    """Device for a staged-eager result: the first argument's device,
    else the default.  NB: `.device` raises ValueError (not
    AttributeError) on a multi-device sharded Array — probe via
    .devices()."""
    for a in args:
        devs = getattr(a, "devices", None)
        if callable(devs):
            try:
                return next(iter(devs()))
            except Exception:
                continue
    return jax.devices()[0]


def _check_callback_support(ctx):
    """Fail at compile time where the ordered-callback path would HANG
    at run time (axon_pjrt implements no host send/recv callbacks).

    Keyed on the *lowering target*: a world op jitted for the cpu
    platform works in any process (cpu host callbacks always exist),
    even when the process's default backend is the callback-less
    tunnel — e.g. the Status-carrying recv/sendrecv cpu route.
    """
    platforms = tuple(getattr(ctx.module_context, "platforms", ()) or ())
    if platforms and all(p == "cpu" for p in platforms):
        return
    if _use_staged_eager():
        raise NotImplementedError(
            "world-tier ops inside jit need host send/recv callbacks, "
            "which the axon TPU tunnel does not implement; call the op "
            "eagerly (staged-eager dispatch handles D2H/H2D), or run "
            "this rank on JAX_PLATFORMS=cpu, or use a real TPU VM"
        )


def _staged_eager_impl(p, out_aval_fn, host_fn):
    """Eager impl with an explicit staging tier for callback-less
    backends: pull the device buffers to the host (D2H), run the native
    transport on them, push the result back (H2D) — the reference GPU
    bridge's staging sequence performed at the dispatch layer
    (mpi_xla_bridge_gpu.pyx:233-251 there).  Callback-capable backends
    take the normal apply_primitive route (compiled ordered callback).
    """

    def eager_impl(*args, **params):
        analyzed = _analysis_intercept(p.name, args, params)
        if analyzed is not None:
            return analyzed
        if _use_staged_eager():
            host_params = {k: v for k, v in params.items() if k != "ordered"}
            avals = [core.get_aval(a) for a in args]
            out_aval = out_aval_fn(*avals, **host_params)
            host_args = [
                _np(jax.device_get(a), av) for a, av in zip(args, avals)
            ]
            result = host_fn(*host_args, **host_params)
            out = _contig(np.asarray(result, dtype=out_aval.dtype))
            return jax.device_put(out, _staged_result_device(args))
        return _jax_dispatch.apply_primitive(p, *args, **params)

    return eager_impl


def _make_primitive(name, out_aval_fn, host_fn):
    """A world-tier primitive: ordered effect + host-callback lowering.

    ``host_fn(*np_args, **params) -> np.ndarray`` runs on the host;
    ``out_aval_fn(*avals, **params) -> ShapedArray`` declares the result.
    """
    p = core.Primitive(f"mpi4jax_tpu_{name}")
    p.def_impl(_staged_eager_impl(p, out_aval_fn, host_fn))

    def abstract_eval(*avals, **params):
        ordered = params.pop("ordered", True)
        eff = comm_effect if ordered else unordered_comm_effect
        return out_aval_fn(*avals, **params), {eff}

    p.def_effectful_abstract_eval(abstract_eval)

    def lowering(ctx, *args, **params):
        _check_callback_support(ctx)
        ordered = params.pop("ordered", True)
        out_aval = ctx.avals_out[0]

        def _callback(*flat):
            result = host_fn(
                *[_np(a, av) for a, av in zip(flat, ctx.avals_in)], **params
            )
            return (_contig(np.asarray(result, dtype=out_aval.dtype)),)

        if not ordered:
            return _emit_unordered_callback(ctx, _callback, args)
        token = ctx.tokens_in.get(comm_effect)
        results, token, _ = _jax_callback.emit_python_callback(
            ctx,
            _callback,
            token,
            list(args),
            ctx.avals_in,
            ctx.avals_out,
            has_side_effect=True,
            returns_token=True,
        )
        ctx.set_tokens_out(mlir.TokenSet({comm_effect: token}))
        return results

    mlir.register_lowering(p, lowering)
    p._callback_lowering = lowering
    return p


# ---------------- native FFI fast path (cpu platform) ----------------
#
# On cpu the primitives lower to typed XLA FFI custom calls handled
# natively (native/tpucomm_ffi.cc) — the modern analog of the reference's
# Cython custom-call decoders (mpi_xla_bridge_cpu.pyx:20-209 there), with
# scalar params as custom-call attributes instead of operand buffers.  The
# ordered-effect token rides the call as a real operand/result, so ordering
# is identical to the callback path.  On tpu the host-callback lowering
# (HBM→host staging) remains in force.


def _i64_attr(v):
    return ir.IntegerAttr.get(ir.IntegerType.get_signless(64), int(v))


def _i32_attr(v):
    return ir.IntegerAttr.get(ir.IntegerType.get_signless(32), int(v))


def _ffi_attrs(comm=None, op=None, **scalars):
    attrs = {"comm": _i64_attr(comm.handle)}
    if op is not None:
        attrs["op"] = _i32_attr(_OP_CODE[op.name])
    for name, value in scalars.items():
        attrs[name] = _i32_attr(value)
    return attrs


def _emit_ffi_call(ctx, target, args, attrs, alias_in_out=False):
    token = ctx.tokens_in.get(comm_effect)
    result_types = [mlir.token_type()] + [
        mlir.aval_to_ir_type(a) for a in ctx.avals_out
    ]
    call = mlir.custom_call(
        target,
        result_types=result_types,
        operands=[token, *args],
        backend_config=attrs,
        has_side_effect=True,
        api_version=4,
        # in-place ops (same-shape, handler tolerates in == out) alias the
        # data operand onto the result so XLA reuses the buffer instead of
        # materializing a copy — per-op payload-sized savings inside jit
        # (measured ~9 ms/op at 16 MB before aliasing)
        operand_output_aliases={1: 1} if alias_in_out else None,
    )
    token_out, *results = call.results
    ctx.set_tokens_out(mlir.TokenSet({comm_effect: token_out}))
    return results


def _ici_leg_blocks_ffi() -> bool:
    """True when the ICI data-plane leg (topo/_ici_leg.py) could claim
    allreduce calls at runtime: those must keep the host-callback route
    — the leg hooks ``bridge.allreduce_raw``, which the native FFI
    custom call bypasses.  Conservative by design (``force``, or
    ``auto`` with TPU chips present): the per-call dtype/op/topology
    gates live in the bridge hook, and a callback-routed allreduce the
    leg then declines still runs the identical native schedule."""
    from ..utils import config

    mode = config.ici_leg_mode()
    if mode == "off":
        return False
    if mode == "force":
        return True
    from .. import topo

    return topo._tpu_chip_count() > 0


def _register_ffi_lowering(p, target, identity_param=None,
                           alias_in_out=False):
    """cpu lowering: native FFI custom call, falling back to the host
    callback when the fast path is unavailable or disabled.

    ``identity_param`` names a boolean primitive param that short-circuits
    the lowering to the identity (allreduce's transposed adjoint pass,
    reference allreduce.py:87-89); it is never sent as an FFI attribute.

    ``alias_in_out`` marks ops whose native handler accepts
    ``sendbuf == recvbuf`` (allreduce/reduce/scan/bcast, and recv whose
    operand is a dead shape carrier) — NOT sendrecv/alltoall, whose
    send side still reads the operand while the receive side writes.
    """

    def lowering(ctx, *args, **params):
        if identity_param is not None and params.pop(identity_param, False):
            return [args[0]]  # identity pass, no communication
        from ..runtime import bridge

        if (params.get("algo") or not params.get("ordered", True)
                or not bridge.ffi_available()
                or (target == "tpucomm_allreduce"
                    and _ici_leg_blocks_ffi())):
            # unordered (explicit-token) mode keeps the callback route
            # (the FFI call's wire format carries the compiler token),
            # and so does a forced per-call algorithm (the quantized
            # allreduce path) and an allreduce the ICI data-plane leg
            # could claim (the leg hooks the bridge funnel the FFI
            # call would bypass)
            return p._callback_lowering(ctx, *args, **params)
        params.pop("ordered", None)
        params.pop("algo", None)
        return _emit_ffi_call(ctx, target, args, _ffi_attrs(**params),
                              alias_in_out=alias_in_out)

    mlir.register_lowering(p, lowering, platform="cpu")


# ---------------- token-operand variants (explicit-token mode) ----------
#
# In unordered mode, ordering must be a REAL data edge through the op:
# XLA folds ``optimization_barrier`` value-token chains around opaque
# custom calls (observed: a scanned send/recv pair compiled with the
# recv's operand reduced to its zeros initializer — the send dropped out
# of the dependency cone and the scheduler ran recv first).  These
# variants are the reference's L1 wire format (tokens as real
# custom-call operands/results, allreduce.py:101-104 there): each takes
# ``(*data, token)`` and returns ``(out, token')``, with the token
# passed through the host callback itself, so no XLA pass can separate
# the chain from the call.  allreduce carries JVP + transpose (SUM only,
# flag-flip identity) and sendrecv JVP + source/dest-swap transpose —
# the reference's L1 AD contract (allreduce.py:188-218, sendrecv.py:
# 355-409 there) — so the composition shape (mesh collectives + world
# ops in one jitted step) can train, not just infer (VERDICT r4 #2).

_TOKEN_AVAL = core.ShapedArray((), np.dtype(np.uint32))
_token_variants = {}


def _token_ffi_attrs(name, params):
    """FFI attrs for a token-variant bind, or None when this bind cannot
    take the native wire (Status fill is a Python-side effect; split
    send/recv tags and custom reduction ops have no native encoding)."""
    params = dict(params)
    if params.pop("status", None) is not None:
        return None
    if params.pop("algo", None) is not None:
        return None  # forced (quantized) algorithm: callback route only
    if name == "allreduce" and _ici_leg_blocks_ffi():
        return None  # the ICI leg hooks the bridge funnel, not the wire
    op = params.get("op")
    if op is not None and op.name not in _OP_CODE:
        return None  # custom ReduceOp: the fold runs in Python
    if name == "sendrecv":
        if params["sendtag"] != params["recvtag"]:
            return None
        params["tag"] = params.pop("sendtag")
        params.pop("recvtag")
    return _ffi_attrs(**params)


def _single_partition(ctx) -> bool:
    """True when this lowering targets ONE device, where the SPMD
    partitioner (which strips sharding annotations from custom-call
    targets it doesn't special-case — ours included, measured) never
    runs.  Multi-device (composition) lowerings keep the host-callback
    wire, whose targets the Shardy bridge does preserve shardings for.
    """
    platforms = tuple(getattr(ctx.module_context, "platforms", ()) or ())
    if not platforms or any(p != "cpu" for p in platforms):
        return False  # FFI targets are registered for cpu only
    ac = ctx.module_context.axis_context
    n = getattr(ac, "num_devices", None)
    if n is not None:
        return n == 1
    mesh = getattr(ac, "mesh", None)
    if mesh is not None:
        return getattr(mesh, "size", 2) == 1
    # unknown axis context (e.g. pmap replicas): the callback route's
    # MAXIMAL pinning is the only safe once-per-process guarantee
    return False


def _emit_token_ffi(ctx, target, args, attrs, n_data, alias_data=False):
    """Native custom call carrying the u32 ordering token as a REAL
    operand/result (the reference L1 wire format) — the explicit-token
    mode analog of _emit_ffi_call, replacing the per-op Python callback
    (~150 us) with the ~1 us native path.  The token operand aliases the
    token result: the chain costs no copies."""
    result_types = [mlir.aval_to_ir_type(a) for a in ctx.avals_out]
    aliases = {n_data: 1}  # token operand -> token result: chain is free
    if alias_data:
        # in-place-safe handlers (sendbuf == recvbuf tolerated): alias
        # the payload too — the value path measured ~9 ms/op at 16 MB
        # without it (_emit_ffi_call)
        aliases[0] = 0
    call = mlir.custom_call(
        target,
        result_types=result_types,
        operands=list(args),
        backend_config=attrs,
        has_side_effect=True,
        api_version=4,
        operand_output_aliases=aliases,
    )
    return list(call.results)


def _make_token_variant(name, out_aval_fn, host_fn, n_data=1,
                        identity_param=None):
    """``identity_param`` names a bool param that short-circuits the op
    to a pure ``(x, token)`` passthrough — no effect, no callback (the
    allreduce transposed-adjoint pass, reference allreduce.py:87-89)."""
    p = core.Primitive(f"mpi4jax_tpu_{name}_t")
    p.multiple_results = True

    def _is_identity(params):
        return identity_param is not None and params.get(identity_param)

    def _host_params(params):
        if identity_param is None:
            return params
        params = dict(params)
        params.pop(identity_param, None)
        return params

    def impl(*args, **params):
        if _is_identity(params):
            return args[0], args[n_data]
        analyzed = _analysis_intercept(
            p.name, args[:n_data], _host_params(params))
        if analyzed is not None:
            return analyzed, args[n_data]
        if _use_staged_eager():
            data, tok = args[:n_data], args[n_data]
            avals = [core.get_aval(a) for a in data]
            out_aval = out_aval_fn(*avals, **params)
            host_args = [
                _np(jax.device_get(a), av) for a, av in zip(data, avals)
            ]
            result = host_fn(*host_args, **_host_params(params))
            out = _contig(np.asarray(result, dtype=out_aval.dtype))
            return jax.device_put(out, _staged_result_device(data)), tok
        return _jax_dispatch.apply_primitive(p, *args, **params)

    p.def_impl(impl)

    def abstract_eval(*avals, **params):
        out = out_aval_fn(*avals[:n_data], **params)
        if _is_identity(params):
            return (out, _TOKEN_AVAL), set()
        return (out, _TOKEN_AVAL), {unordered_comm_effect}

    p.def_effectful_abstract_eval(abstract_eval)

    def lowering(ctx, *args, **params):
        if _is_identity(params):
            return list(args)
        from ..runtime import bridge

        host_params = _host_params(params)
        attrs = (_token_ffi_attrs(name, host_params)
                 if bridge.ffi_available() and _single_partition(ctx)
                 else None)
        if attrs is not None:
            return _emit_token_ffi(
                ctx, f"tpucomm_{name}_t", args, attrs, n_data,
                alias_data=name in ("allreduce", "reduce", "scan",
                                    "bcast", "recv"))
        _check_callback_support(ctx)
        data_avals = ctx.avals_in[:n_data]
        out_aval = ctx.avals_out[0]

        def _callback(*flat):
            data, tok = flat[:n_data], flat[n_data]
            result = host_fn(
                *[_np(a, av) for a, av in zip(data, data_avals)],
                **host_params
            )
            return (_contig(np.asarray(result, dtype=out_aval.dtype)),
                    np.asarray(tok, np.uint32))

        return _emit_unordered_callback(ctx, _callback, args)

    mlir.register_lowering(p, lowering)
    _token_variants[name] = p
    return p


def _bind_token_variant(name, x, token, **params):
    """(result, token') through the token-operand primitive.

    The wire token is the per-trace side chain's head when one exists
    (falling back to the caller's token): every world op in a trace —
    user-chained, tangent, or transposed — then sits on ONE token chain,
    so AD-introduced ops and later user ops can never be mutually
    unordered (the side chain only ever ADDS ordering edges: its head is
    always downstream of the user's chain).  The chain guard still sees
    the caller's ORIGINAL token for footgun detection."""
    p = _token_variants[name]
    wire_tok = _ad_chain_token(token)
    tok = jnp.asarray(wire_tok, jnp.uint32)
    args = (tok,) if x is None else (jnp.asarray(x), tok)
    out, tok2 = p.bind(*args, **params)
    _chain_guard.note_op(params.get("comm"), token, tok2)
    _ad_chain_set(tok2)
    return out, tok2


def token_variant_fn(name, **params):
    """A ``token_fn`` for :func:`.._dispatch.maybe_tokenized`: routes the
    op through its token-operand variant in explicit-token mode.
    Validation happens in the ops-layer entry before dispatch (both
    routes share it)."""

    def fn(x, token):
        return _bind_token_variant(name, x, token, **params)

    fn.comm = params.get("comm")  # for the unthreaded-op chain guard
    return fn


def custom_fold_token_fn(op, comm, root=None, prefix=False):
    """Token-chained composite for user-defined reduction operators:
    the wire carries no user code, so the data moves via the token-
    operand allgather/gather and the fold runs locally — the same
    composite as the value path, but with the token riding the
    communication op so explicit-token mode keeps its ordering."""

    def fn(x, token):
        x = jnp.asarray(x)
        if root is not None:  # noqa: E306
            rows, tok = _bind_token_variant("gather", x, token, comm=comm,
                                            root=root)
            if comm.rank() == root:
                return op.reduce(rows).astype(x.dtype), tok
            return rows, tok
        rows, tok = _bind_token_variant("allgather", x, token, comm=comm)
        if prefix:
            return op.reduce(rows[: comm.rank() + 1]).astype(x.dtype), tok
        return op.reduce(rows).astype(x.dtype), tok

    fn.comm = comm  # for the unthreaded-op chain guard
    return fn


def _same_aval(x_aval, **params):
    return core.ShapedArray(x_aval.shape, x_aval.dtype)


def _scalar_aval(*avals, **params):
    return core.ShapedArray((), np.dtype(np.int32))


def _elementwise_batching(p):
    def rule(batched_args, batch_dims, **params):
        (x,), (bd,) = batched_args, batch_dims
        return p.bind(x, **params), bd

    batching.primitive_batchers[p] = rule


# ---------------- host-side executors ----------------
#
# Every executor consults the schedule-plan runner (runtime/planrt.py)
# first: with MPI4JAX_TPU_PLAN off (the default) that is one module-
# global boolean; with a verified plan installed, sends/recvs may post
# as non-blocking tickets on the progress engine (deferred completions,
# pre-posted hoisted receives) and every other op is signature-checked
# against the plan before running its historic path — a diverging op
# stream disables the plan loudly and falls back.


_planrt = None


def _plan_runner(comm):
    # module reference cached after the first call: this sits on the
    # per-op dispatch path whose microseconds PR 5 fought for, and with
    # plans off the whole detour is one cached-attribute + one cached-
    # env check inside planrt.get
    global _planrt
    if _planrt is None:
        from ..runtime import planrt as _p

        _planrt = _p
    return _planrt.get(comm)


def _plan_sync(comm, kind, execute, **sig):
    """Run a non-accelerated op under the plan runner's cursor (or
    directly when no plan serves this comm)."""
    rt = _plan_runner(comm)
    if rt is None:
        return execute()
    return rt.run_sync(kind, execute, **sig)


def _coll_algo_detail(comm, opname, nbytes):
    """Algorithm name for a trace line; never let the observability
    probe take down the op itself."""
    try:
        return comm.coll_algo(opname, nbytes)
    except Exception:
        return "?"


def _reuse_ok() -> bool:
    """Output-buffer reuse (bridge ``reuse=True``) is safe only on the
    callback path, where jax copies the result into the XLA output
    buffer before the (ordered) callback returns.  Staged-eager
    dispatch device_puts the numpy result — potentially zero-copy — so
    it must keep fresh buffers."""
    return not _use_staged_eager()


def _host_allreduce(x, *, comm, op, algo=None):
    from ..runtime import bridge

    if algo is not None:
        from .. import tune as _tune

        algo_code = _tune.ALGO_CODES[algo]
        detail = f"op {op.name} algo {algo} (forced)"
    else:
        algo_code = None
        detail = None
    with tracing.CallTrace(
        comm.rank(), "Allreduce",
        (lambda: detail) if detail is not None else
        (lambda: f"op {op.name} algo "
                 f"{_coll_algo_detail(comm, 'allreduce', x.nbytes)}"),
        nbytes=x.nbytes,
    ):
        # the plan signature stays ("allreduce", reduce_op, nbytes):
        # a quantized call IS an allreduce to the verifier and the
        # schedule compiler — only the wire encoding differs
        return _plan_sync(
            comm, "allreduce",
            lambda: bridge.allreduce(comm.handle, x, _OP_CODE[op.name],
                                     algo=algo_code, reuse=_reuse_ok()),
            reduce_op=op.name, nbytes=x.nbytes,
        )


def _host_reduce(x, *, comm, op, root):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Reduce", f"op {op.name} root {root}",
                           peer=root, nbytes=x.nbytes):
        return _plan_sync(
            comm, "reduce",
            lambda: bridge.reduce(comm.handle, x, _OP_CODE[op.name], root,
                                  reuse=_reuse_ok()),
            reduce_op=op.name, root=root, nbytes=x.nbytes,
        )


def _host_scan(x, *, comm, op):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Scan", f"op {op.name}",
                           nbytes=x.nbytes):
        return _plan_sync(
            comm, "scan",
            lambda: bridge.scan(comm.handle, x, _OP_CODE[op.name],
                                reuse=_reuse_ok()),
            reduce_op=op.name, nbytes=x.nbytes,
        )


def _host_bcast(x, *, comm, root):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Bcast", f"root {root}",
                           peer=root, nbytes=x.nbytes):
        return _plan_sync(comm, "bcast",
                          lambda: bridge.bcast(comm.handle, x, root),
                          root=root, nbytes=x.nbytes)


def _host_allgather(x, *, comm):
    from ..runtime import bridge

    with tracing.CallTrace(
        comm.rank(), "Allgather",
        lambda: f"algo {_coll_algo_detail(comm, 'allgather', x.nbytes)}",
        nbytes=x.nbytes,
    ):
        return _plan_sync(
            comm, "allgather",
            lambda: bridge.allgather(comm.handle, x, comm.size(),
                                     reuse=_reuse_ok()),
            nbytes=x.nbytes,
        )


def _host_gather(x, *, comm, root):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Gather", f"root {root}",
                           peer=root, nbytes=x.nbytes):
        # root gets (size, *x.shape); non-root sends and gets x back
        # (exact reference contract, gather.py:86-96,213-226 there)
        return _plan_sync(
            comm, "gather",
            lambda: bridge.gather(comm.handle, x, comm.size(), root,
                                  comm.rank()),
            root=root, nbytes=x.nbytes,
        )


def _host_scatter(x, *, comm, root):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Scatter", f"root {root}",
                           peer=root, nbytes=x.nbytes):
        return _plan_sync(comm, "scatter",
                          lambda: bridge.scatter(comm.handle, x, root),
                          root=root, nbytes=x.nbytes)


def _host_alltoall(x, *, comm, algo=None):
    from ..runtime import bridge

    if algo is not None:
        from .. import tune as _tune

        algo_code = _tune.ALGO_CODES[algo]
        detail = f"algo {algo} (forced)"
    else:
        algo_code = None
        detail = ""
    with tracing.CallTrace(comm.rank(), "Alltoall", detail,
                           nbytes=x.nbytes):
        # the plan signature stays ("alltoall", nbytes): a quantized or
        # hierarchical exchange IS an alltoall to the verifier and the
        # schedule compiler — only the wire encoding/routing differs
        return _plan_sync(comm, "alltoall",
                          lambda: bridge.alltoall(comm.handle, x,
                                                  algo=algo_code),
                          nbytes=x.nbytes)


def _host_shift2(x, *, comm, lo, hi, tag):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Shift2", f"lo {lo} hi {hi}",
                           peer=hi, nbytes=x.nbytes, tag=tag):
        return _plan_sync(comm, "shift2",
                          lambda: bridge.shift2(comm.handle, x, lo, hi, tag),
                          lo=lo, hi=hi, tag=tag, nbytes=x.nbytes)


def _host_barrier(*, comm):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Barrier", ""):
        _plan_sync(comm, "barrier", lambda: bridge.barrier(comm.handle))
    return np.zeros((), np.int32)


def _host_send(x, *, comm, dest, tag):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Send", f"to {dest} tag {tag}",
                           peer=dest, nbytes=x.nbytes, tag=tag):
        rt = _plan_runner(comm)
        if rt is None or not rt.run_send(x, dest, tag):
            bridge.send(comm.handle, x, dest, tag)
    return np.zeros((), np.int32)


def _host_recv(x, *, comm, source, tag, status=None):
    from ..runtime import bridge

    with tracing.CallTrace(comm.rank(), "Recv", f"from {source} tag {tag}",
                           peer=source, nbytes=x.nbytes, tag=tag):
        rt = _plan_runner(comm)
        if status is None:
            if rt is not None:
                out = rt.run_recv(x.shape, x.dtype, source, tag,
                                  reuse=_reuse_ok())
                if out is not None:
                    return out
            # strict path: arrived size must equal the buffer exactly
            return bridge.recv(comm.handle, x.shape, x.dtype, source, tag,
                               reuse=_reuse_ok())
        def _ex():
            return bridge.recv_status(
                comm.handle, x.shape, x.dtype, source, tag
            )
        if rt is not None:
            out, src, tg, cnt = rt.run_sync("recv", _ex, source=source,
                                            tag=tag)
        else:
            out, src, tg, cnt = _ex()
    status.obj._fill(src, tg, cnt)
    return out


def _host_sendrecv(x, *, comm, source, dest, sendtag, recvtag, status=None):
    from ..runtime import bridge

    with tracing.CallTrace(
        comm.rank(), "Sendrecv", f"to {dest} from {source}",
        peer=dest, nbytes=2 * x.nbytes, tag=sendtag,
    ):
        if status is None and sendtag == recvtag:
            return _plan_sync(
                comm, "sendrecv",
                lambda: bridge.sendrecv(
                    comm.handle, x, x.shape, x.dtype, source, dest,
                    sendtag, reuse=_reuse_ok()),
                dest=dest, source=source, sendtag=sendtag,
                recvtag=recvtag,
            )
        out, src, tg, cnt = _plan_sync(
            comm, "sendrecv",
            lambda: bridge.sendrecv_status(
                comm.handle, x, x.shape, x.dtype, source, dest, sendtag,
                recvtag),
            dest=dest, source=source, sendtag=sendtag, recvtag=recvtag,
        )
    if status is None:
        # no status to report a short message through: keep the strict
        # exact-size fail-fast contract of the plain path
        if cnt != out.nbytes:
            import sys

            print(
                f"tpucomm_Sendrecv: size mismatch from rank {source}: "
                f"expected {out.nbytes} bytes, got {cnt}",
                file=sys.stderr, flush=True,
            )
            import os

            os._exit(1)
    else:
        status.obj._fill(src, tg, cnt)
    return out


# ---------------- primitives ----------------

# allreduce carries a `transpose` flag (reference allreduce.py:80-89,215-217):
# the transposed pass lowers to identity — cotangents of the replicated
# output pass through per rank — and transposing it again flips the flag
# back, so double-transpose ≡ allreduce.  Built by hand (not the factory)
# because the transposed pass carries no effect and no callback.
allreduce_p = core.Primitive("mpi4jax_tpu_allreduce")


def _host_allreduce_or_identity(x, *, comm, op, transpose=False,
                                algo=None):
    # the transposed pass is a communication-free identity (reference
    # allreduce.py:87-89 there)
    return x if transpose else _host_allreduce(x, comm=comm, op=op,
                                               algo=algo)


_allreduce_staged = _staged_eager_impl(
    allreduce_p,
    lambda x_aval, **params: core.ShapedArray(x_aval.shape, x_aval.dtype),
    _host_allreduce_or_identity,
)


def _allreduce_impl(x, *, comm, op, transpose=False, ordered=True,
                    algo=None):
    if transpose:
        return x  # identity: skip the staging D2H/H2D round trip too
    # (_allreduce_staged's eager_impl performs the analysis intercept)
    return _allreduce_staged(x, comm=comm, op=op, transpose=transpose,
                             ordered=ordered, algo=algo)


allreduce_p.def_impl(_allreduce_impl)


def _allreduce_abstract_eval(x_aval, *, comm, op, transpose=False,
                             ordered=True, algo=None):
    if transpose:
        effects = set()
    else:
        effects = {comm_effect if ordered else unordered_comm_effect}
    return core.ShapedArray(x_aval.shape, x_aval.dtype), effects


allreduce_p.def_effectful_abstract_eval(_allreduce_abstract_eval)


def _allreduce_lowering(ctx, x, *, comm, op, transpose=False, ordered=True,
                        algo=None):
    if transpose:
        return [x]  # identity pass, no communication
    _check_callback_support(ctx)

    out_aval = ctx.avals_out[0]

    def _callback(*flat):
        result = _host_allreduce(
            *[_np(a, av) for a, av in zip(flat, ctx.avals_in)],
            comm=comm, op=op, algo=algo,
        )
        return (_contig(np.asarray(result, dtype=out_aval.dtype)),)

    if not ordered:
        return _emit_unordered_callback(ctx, _callback, [x])
    token = ctx.tokens_in.get(comm_effect)
    results, token, _ = _jax_callback.emit_python_callback(
        ctx, _callback, token, [x], ctx.avals_in, ctx.avals_out,
        has_side_effect=True, returns_token=True,
    )
    ctx.set_tokens_out(mlir.TokenSet({comm_effect: token}))
    return results


mlir.register_lowering(allreduce_p, _allreduce_lowering)
allreduce_p._callback_lowering = _allreduce_lowering
_register_ffi_lowering(
    allreduce_p, "tpucomm_allreduce", identity_param="transpose",
    alias_in_out=True,
)
reduce_p = _make_primitive("reduce", _same_aval, _host_reduce)
scan_p = _make_primitive("scan", _same_aval, _host_scan)
bcast_p = _make_primitive("bcast", _same_aval, _host_bcast)
alltoall_p = _make_primitive("alltoall", _same_aval, _host_alltoall)
sendrecv_p = _make_primitive("sendrecv", _same_aval, _host_sendrecv)
recv_p = _make_primitive("recv", _same_aval, _host_recv)
send_p = _make_primitive("send", _scalar_aval, _host_send)
barrier_p = _make_primitive("barrier", _scalar_aval, _host_barrier)


def _stacked_aval(x_aval, *, comm, **params):
    return core.ShapedArray((comm.size(),) + x_aval.shape, x_aval.dtype)


def _gather_aval(x_aval, *, comm, root, **_):
    # rank-dependent output, possible because each world process traces
    # its own program: root (size, *in), others the input back (exact
    # reference contract, gather.py:86-96,213-226 there)
    if comm.rank() == root:
        return core.ShapedArray((comm.size(),) + x_aval.shape, x_aval.dtype)
    return core.ShapedArray(x_aval.shape, x_aval.dtype)


def _unstacked_aval(x_aval, *, comm, **params):
    return core.ShapedArray(x_aval.shape[1:], x_aval.dtype)


# one-op bidirectional neighbor exchange (MPI_Neighbor_alltoall on a
# 1-D ring segment): the halo-exchange hot path — a single blocking
# point per direction-dim instead of two sequential sendrecvs (each
# blocking wait costs a scheduler quantum when ranks share cores)
shift2_p = _make_primitive("shift2", _same_aval, _host_shift2)
allgather_p = _make_primitive("allgather", _stacked_aval, _host_allgather)
gather_p = _make_primitive("gather", _gather_aval, _host_gather)
scatter_p = _make_primitive("scatter", _unstacked_aval, _host_scatter)

for _p, _target, _alias in (
    (shift2_p, "tpucomm_shift2", False),  # send half reads while recv writes
    (reduce_p, "tpucomm_reduce", True),
    (scan_p, "tpucomm_scan", True),
    (bcast_p, "tpucomm_bcast", True),
    (alltoall_p, "tpucomm_alltoall", False),
    (send_p, "tpucomm_send", False),
    (barrier_p, "tpucomm_barrier", False),
    (allgather_p, "tpucomm_allgather", False),
    (gather_p, "tpucomm_gather", False),
    (scatter_p, "tpucomm_scatter", False),
):
    _register_ffi_lowering(_p, _target, alias_in_out=_alias)


# recv/sendrecv route around the FFI fast path when the call carries a
# Status (the fill is a host-side effect the Python callback performs) or
# split send/recv tags (the strict native sendrecv takes one tag).
def _recv_ffi_lowering(ctx, *args, **params):
    from ..runtime import bridge

    if (params.get("status") is not None
            or not params.get("ordered", True)
            or not bridge.ffi_available()):
        return recv_p._callback_lowering(ctx, *args, **params)
    params.pop("status", None)
    params.pop("ordered", None)
    # the operand is only a shape carrier — its buffer is dead, safe to
    # write the received bytes straight into it
    return _emit_ffi_call(ctx, "tpucomm_recv", args, _ffi_attrs(**params),
                          alias_in_out=True)


def _sendrecv_ffi_lowering(ctx, *args, **params):
    from ..runtime import bridge

    if (
        params.get("status") is not None
        or params["sendtag"] != params["recvtag"]
        or not params.get("ordered", True)
        or not bridge.ffi_available()
    ):
        return sendrecv_p._callback_lowering(ctx, *args, **params)
    params.pop("status", None)
    params.pop("ordered", None)
    tag = params.pop("sendtag")
    params.pop("recvtag")
    return _emit_ffi_call(
        ctx, "tpucomm_sendrecv", args, _ffi_attrs(tag=tag, **params)
    )


mlir.register_lowering(recv_p, _recv_ffi_lowering, platform="cpu")
mlir.register_lowering(sendrecv_p, _sendrecv_ffi_lowering, platform="cpu")

# token-operand variants for every op (explicit-token mode wire format)
_make_token_variant("shift2", _same_aval, _host_shift2)
_make_token_variant("allreduce", _same_aval, _host_allreduce,
                    identity_param="transpose")
_make_token_variant("reduce", _same_aval, _host_reduce)
_make_token_variant("scan", _same_aval, _host_scan)
_make_token_variant("bcast", _same_aval, _host_bcast)
_make_token_variant("alltoall", _same_aval, _host_alltoall)
_make_token_variant("sendrecv", _same_aval, _host_sendrecv)
_make_token_variant("recv", _same_aval, _host_recv)
_make_token_variant("send", _scalar_aval, _host_send)
_make_token_variant("barrier", _scalar_aval, _host_barrier, n_data=0)
_make_token_variant("allgather", _stacked_aval, _host_allgather)
_make_token_variant("gather", _gather_aval, _host_gather)
_make_token_variant("scatter", _unstacked_aval, _host_scatter)


# ---- AD for the token-operand variants (the composition mode) ----
#
# Token-threading conventions mirror the reference L1 exactly
# (allreduce.py:186-217, sendrecv.py:350-409 there): the tangent op
# chains off the PRIMAL's output token but the primal's token is what
# flows downstream (the tangent's is Zeroed, jax#6285); the transpose
# binds through the primal INPUT token.  Every rank traces the same
# doubled schedule, so the extra tangent op cannot skew cross-rank
# collective order.


def _token_or_fresh(token):
    # transpose rules receive primal inputs that can be UndefinedPrimal;
    # any uint32 works as the wire token (its only role is the data
    # edge), so a fresh zero keeps the op bindable
    if ad.is_undefined_primal(token):
        return jnp.zeros((), jnp.uint32)
    return token


# AD-introduced world ops (tangent binds, transposed binds) are not part
# of the USER's token chain, and with fake (uint32) tokens two of them
# with no chain between each other have undefined relative order — the
# exact hazard the chain guard flags for user code.  A per-trace SIDE
# CHAIN fixes it: the first AD-introduced op in a trace anchors to its
# forward op's token (part of the user chain), and every subsequent one
# chains off the previous AD op's output token, giving all
# AD-introduced world ops in one trace a total order that is identical
# on every rank (same transposition order for matching programs).
# Entries are capped and liveness-pruned; an evicted entry only costs
# the next AD op its chain link (it re-anchors to its hint), never
# correctness of values.
_ad_side_chain = {}  # id(trace) -> [weakref(trace), token]


def _ad_current_trace():
    trace = getattr(core.trace_ctx, "trace", None)
    if trace is None or type(trace).__name__ == "EvalTrace":
        return None  # eager: Python order IS execution order
    return trace


def _ad_chain_token(hint):
    trace = _ad_current_trace()
    if trace is None:
        return hint
    ent = _ad_side_chain.get(id(trace))
    # identity check, not liveness: a dict key is id(trace), which a
    # LATER trace can reuse after the first is collected — a stale
    # entry's token must never leak into a different trace
    if ent is not None and ent[0]() is trace:
        return ent[1]
    return hint


def _ad_chain_set(tok):
    import weakref

    trace = _ad_current_trace()
    if trace is None:
        return
    if len(_ad_side_chain) > 64:
        for k in [k for k, v in _ad_side_chain.items() if v[0]() is None]:
            del _ad_side_chain[k]
        while len(_ad_side_chain) > 64:  # all live: evict oldest
            del _ad_side_chain[next(iter(_ad_side_chain))]
    try:
        wr = weakref.ref(trace)
    except TypeError:
        wr = (lambda t: (lambda: t))(trace)
    _ad_side_chain[id(trace)] = [wr, tok]


def _allreduce_t_jvp(primals, tangents, *, comm, op, transpose=False,
                     algo=None):
    x, token = primals
    x_tan, _token_tan = tangents
    p = _token_variants["allreduce"]
    val, tok = p.bind(x, token, comm=comm, op=op, transpose=transpose,
                      algo=algo)
    if type(x_tan) is ad.Zero:
        # a symbolically-zero tangent differentiates nothing — legal for
        # any op (a non-SUM op behind stop_gradient must not raise)
        jvp = ad.Zero.from_primal_value(val)
    elif op.name != "SUM":
        raise NotImplementedError(
            f"world-tier allreduce is differentiable for SUM only, got "
            f"{op.name}"
        )
    else:
        jvp, tok_jvp = p.bind(x_tan, _ad_chain_token(tok), comm=comm,
                              op=op, transpose=transpose, algo=algo)
        _ad_chain_set(tok_jvp)
    return (val, tok), (jvp, ad.Zero.from_primal_value(tok))


def _allreduce_t_transpose(cts, x, token, *, comm, op, transpose=False,
                           algo=None):
    ct_out, ct_tok = cts
    if op.name != "SUM":
        raise NotImplementedError(
            "the linear transpose of allreduce is only defined for SUM"
        )
    p = _token_variants["allreduce"]
    # always bind (materializing a Zero cotangent): world programs are
    # per-rank, so a rank silently skipping a communicating transposed
    # op could deadlock peers that did not
    ct_out = ad.instantiate_zeros(ct_out)
    res, tok_out = p.bind(ct_out,
                          _ad_chain_token(_token_or_fresh(token)),
                          comm=comm, op=op, transpose=not transpose,
                          algo=algo)
    _ad_chain_set(tok_out)
    return res, ct_tok


_t_allreduce_p = _token_variants["allreduce"]
ad.primitive_jvps[_t_allreduce_p] = _allreduce_t_jvp
ad.primitive_transposes[_t_allreduce_p] = _allreduce_t_transpose


def _sendrecv_t_jvp(primals, tangents, *, comm, source, dest, sendtag,
                    recvtag, status=None):
    # same contract as the ordered-mode rule (a working JVP, superset of
    # the reference's fwd-mode raise): tangents ride the same edge,
    # chained off the primal's token; only the primal fills a Status
    x, token = primals
    x_tan, _token_tan = tangents
    p = _token_variants["sendrecv"]
    val, tok = p.bind(x, token, comm=comm, source=source, dest=dest,
                      sendtag=sendtag, recvtag=recvtag, status=status)
    if type(x_tan) is ad.Zero:
        jvp = ad.Zero.from_primal_value(val)
    else:
        jvp, tok_jvp = p.bind(x_tan, _ad_chain_token(tok), comm=comm,
                              source=source, dest=dest, sendtag=sendtag,
                              recvtag=recvtag, status=None)
        _ad_chain_set(tok_jvp)
    return (val, tok), (jvp, ad.Zero.from_primal_value(tok))


def _sendrecv_t_transpose(cts, x, token, *, comm, source, dest, sendtag,
                          recvtag, status=None):
    # cotangent flows backward along the message edge — swap source/dest
    # with the ordered rule's tag-swap semantics (see
    # _sendrecv_transpose below)
    from ..utils.status import ANY_TAG

    ct_out, ct_tok = cts
    if recvtag == ANY_TAG:
        t_send, t_recv = sendtag, ANY_TAG
    else:
        t_send, t_recv = recvtag, sendtag
    p = _token_variants["sendrecv"]
    ct_out = ad.instantiate_zeros(ct_out)
    res, tok_out = p.bind(ct_out,
                          _ad_chain_token(_token_or_fresh(token)),
                          comm=comm, source=dest, dest=source,
                          sendtag=t_send, recvtag=t_recv, status=None)
    _ad_chain_set(tok_out)
    return res, ct_tok


_t_sendrecv_p = _token_variants["sendrecv"]
ad.primitive_jvps[_t_sendrecv_p] = _sendrecv_t_jvp
ad.primitive_transposes[_t_sendrecv_p] = _sendrecv_t_transpose


# ---------------- AD rules (reference parity) ----------------


def _allreduce_jvp(primals, tangents, *, comm, op, transpose=False,
                   ordered=True, algo=None):
    # reference: JVP defined for SUM only (allreduce.py:192-195 there);
    # a symbolically-zero tangent short-circuits first, so a non-SUM op
    # behind stop_gradient is legal.  A forced (quantized) algorithm
    # rides along: the tangent sync compresses exactly like the primal
    # (the reference DP recipe quantizes gradients, not just values).
    (x,), (t,) = primals, tangents
    primal_out = allreduce_p.bind(x, comm=comm, op=op, transpose=transpose,
                                  ordered=ordered, algo=algo)
    if type(t) is ad.Zero:
        tangent_out = ad.Zero.from_primal_value(primal_out)
    elif op.name != "SUM":
        raise NotImplementedError(
            f"world-tier allreduce is differentiable for SUM only, got "
            f"{op.name}"
        )
    else:
        tangent_out = allreduce_p.bind(
            t, comm=comm, op=op, transpose=transpose, ordered=ordered,
            algo=algo
        )
    return primal_out, tangent_out


def _allreduce_transpose(ct, x, *, comm, op, transpose=False,
                         ordered=True, algo=None):
    # flip the flag: transpose(allreduce) is the identity pass, and
    # transpose of that is allreduce again (reference allreduce.py:206-218)
    return (
        allreduce_p.bind(ct, comm=comm, op=op, transpose=not transpose,
                         ordered=ordered, algo=algo),
    )


ad.primitive_jvps[allreduce_p] = _allreduce_jvp
ad.primitive_transposes[allreduce_p] = _allreduce_transpose


def _sendrecv_jvp(primals, tangents, *, comm, source, dest, sendtag,
                  recvtag, status=None, ordered=True):
    # improvement over the reference (which raises for fwd mode,
    # sendrecv.py:150-155): tangents ride the same message edge.  Only the
    # primal pass fills a Status — one receive, one record.
    (x,), (t,) = primals, tangents
    primal_out = sendrecv_p.bind(x, comm=comm, source=source, dest=dest,
                                 sendtag=sendtag, recvtag=recvtag,
                                 status=status, ordered=ordered)
    if type(t) is ad.Zero:
        tangent_out = ad.Zero.from_primal_value(primal_out)
    else:
        tangent_out = sendrecv_p.bind(
            t, comm=comm, source=source, dest=dest, sendtag=sendtag,
            recvtag=recvtag, status=None, ordered=ordered,
        )
    return primal_out, tangent_out


def _sendrecv_transpose(ct, x, *, comm, source, dest, sendtag, recvtag,
                        status=None, ordered=True):
    # the cotangent flows backward along the message edge: swap source/dest
    # (reference sendrecv.py:390-409).  Tags swap with the direction: the
    # forward edge matched because sendtag(sender) == recvtag(receiver),
    # so the reversed edge must send with the old recvtag and expect the
    # old sendtag.  A wildcard recvtag can't be sent on the wire — keep
    # the own sendtag and accept any, which is consistent on every edge
    # whose forward recv was also a wildcard.
    from ..utils.status import ANY_TAG

    if recvtag == ANY_TAG:
        t_send, t_recv = sendtag, ANY_TAG
    else:
        t_send, t_recv = recvtag, sendtag
    return (
        sendrecv_p.bind(ct, comm=comm, source=dest, dest=source,
                        sendtag=t_send, recvtag=t_recv, status=None,
                        ordered=ordered),
    )


ad.primitive_jvps[sendrecv_p] = _sendrecv_jvp
ad.primitive_transposes[sendrecv_p] = _sendrecv_transpose

# batching where the op is elementwise across the batch axis (reference
# scope: allreduce/barrier/sendrecv, allreduce.py:182-185, barrier.py:120-123,
# sendrecv.py:316-343; bcast/reduce/scan are elementwise too and included)
for _p in (allreduce_p, reduce_p, scan_p, bcast_p, sendrecv_p, recv_p):
    _elementwise_batching(_p)


# shape-changing ops batch too (the reference supports none of these —
# SURVEY.md §2.1 lists batching only for allreduce/barrier/sendrecv).  The
# batch axis rides inside the communicated payload, so one message still
# moves the whole batch:


def _stacking_batching(p):
    # out = (size, *in): the stacking axis is prepended, pushing the batch
    # axis one position right
    def rule(batched_args, batch_dims, **params):
        (x,), (bd,) = batched_args, batch_dims
        return p.bind(x, **params), bd + 1

    batching.primitive_batchers[p] = rule


def _leading_axis_batching(p, out_bd):
    # ops constrained to a (size, ...) leading axis: move the batch axis to
    # position 1 so the per-rank slicing on axis 0 is undisturbed
    def rule(batched_args, batch_dims, **params):
        (x,), (bd,) = batched_args, batch_dims
        x = jnp.moveaxis(x, bd, 1)
        return p.bind(x, **params), out_bd

    batching.primitive_batchers[p] = rule


_stacking_batching(allgather_p)


def _gather_batching(batched_args, batch_dims, *, comm, root, **params):
    # root output gains the stacking axis in front (batch axis shifts
    # right); non-root output is the input unchanged
    (x,), (bd,) = batched_args, batch_dims
    out = gather_p.bind(x, comm=comm, root=root, **params)
    return out, (bd + 1 if comm.rank() == root else bd)


batching.primitive_batchers[gather_p] = _gather_batching
_leading_axis_batching(alltoall_p, out_bd=1)  # out same shape as in
_leading_axis_batching(scatter_p, out_bd=0)   # out drops axis 0


def _send_batching(batched_args, batch_dims, **params):
    # the batch rides inside the one message; the scalar completion value
    # is unbatched
    (x,), (_,) = batched_args, batch_dims
    return send_p.bind(x, **params), batching.not_mapped


batching.primitive_batchers[send_p] = _send_batching


# ---------------- public entry points (called from op modules) -----------


def allreduce(x, op: ReduceOp, comm, algo=None):
    """``algo`` forces a collective algorithm name for this one call —
    the quantized-compression route passes "qring"/"qrd" here; None
    (the default) keeps engine selection.  Not meaningful for custom
    reduce ops (their fold rides allgather)."""
    x = jnp.asarray(x)  # dtype validated at the ops-layer entry
    if op.custom:
        # user-defined op: the wire protocol carries no user code, so
        # compose from allgather + a local jax fold (the analog of the
        # reference handing a user MPI_Op to libmpi, utils.py:133-152)
        rows = allgather_p.bind(x, comm=comm, ordered=_ordered_now())
        return op.reduce(rows).astype(x.dtype)
    return allreduce_p.bind(x, comm=comm, op=op, transpose=False,
                            ordered=_ordered_now(), algo=algo)


def reduce(x, op: ReduceOp, root, comm):
    x = jnp.asarray(x)  # dtype validated at the ops-layer entry
    if op.custom:
        # rank-dependent result (root reduces, others pass through) is
        # fine here: world programs are per-rank (reference
        # reduce.py:71-80 has the same contract)
        rows = gather_p.bind(x, comm=comm, root=root,
                             ordered=_ordered_now())
        if comm.rank() == root:
            return op.reduce(rows).astype(x.dtype)
        return rows
    return reduce_p.bind(x, comm=comm, op=op, root=root,
                         ordered=_ordered_now())


def scan(x, op: ReduceOp, comm):
    x = jnp.asarray(x)  # dtype validated at the ops-layer entry
    if op.custom:
        rows = allgather_p.bind(x, comm=comm, ordered=_ordered_now())
        return op.reduce(rows[: comm.rank() + 1]).astype(x.dtype)
    return scan_p.bind(x, comm=comm, op=op, ordered=_ordered_now())


def bcast(x, root, comm):
    return bcast_p.bind(jnp.asarray(x), comm=comm, root=root,
                        ordered=_ordered_now())


def allgather(x, comm):
    return allgather_p.bind(jnp.asarray(x), comm=comm,
                            ordered=_ordered_now())


def gather(x, root, comm):
    return gather_p.bind(jnp.asarray(x), comm=comm, root=root,
                         ordered=_ordered_now())


def scatter(x, root, comm):
    x = jnp.asarray(x)
    if x.ndim < 1 or x.shape[0] != comm.size():
        raise ValueError(
            f"scatter requires input shape (size, ...) = ({comm.size()}, "
            f"...), got {x.shape} [scatter, rank "
            f"{comm.rank()}/{comm.size()}, dtype {x.dtype}]"
        )
    return scatter_p.bind(x, comm=comm, root=root, ordered=_ordered_now())


def alltoall(x, comm, algo=None):
    """``algo`` forces an alltoall schedule name for this one call —
    the quantized-compression route passes "qalltoall" here; None (the
    default) keeps engine selection."""
    x = jnp.asarray(x)
    if x.ndim < 1 or x.shape[0] != comm.size():
        raise ValueError(
            f"alltoall requires leading axis == communicator size "
            f"({comm.size()}), got shape {x.shape} [alltoall, rank "
            f"{comm.rank()}/{comm.size()}, dtype {x.dtype}]"
        )
    return alltoall_p.bind(x, comm=comm, ordered=_ordered_now(),
                           algo=algo)


def _note_if_unthreaded(comm, token):
    """Direct-path ops (send/recv/sendrecv/neighbor/barrier) bypass
    maybe_tokenized; flag a tokenless bind in explicit mode here."""
    if token is None and not _ordered_now():
        _chain_guard.note_unthreaded(comm)


def neighbor_exchange(to_lo, to_hi, *, lo, hi, comm, tag=60, token=None):
    """(from_lo, from_hi) strips from the 1-D ring neighbors, one op.

    ``lo``/``hi`` are neighbor ranks or None for a wall (the returned
    strip on a wall side is the opposite input, passthrough — callers
    treating walls specially just ignore it).  Self-wrap (both
    neighbors == own rank) is a local rotation.  Deadlock-free for any
    chain/ring when every member calls at the same program position —
    the one-op replacement for the two-shift halo schedule.
    """
    _note_if_unthreaded(comm, token)
    lo_i = -1 if lo is None else int(lo)
    hi_i = -1 if hi is None else int(hi)
    x = jnp.stack([jnp.asarray(to_lo), jnp.asarray(to_hi)])
    if token is not None and not _ordered_now():
        out, tok = _bind_token_variant("shift2", x, token, comm=comm,
                                       lo=lo_i, hi=hi_i, tag=int(tag))
        return (out[0], out[1]), tok
    from . import _dispatch as _disp

    x = _disp.token_in(token, x)
    out = shift2_p.bind(x, comm=comm, lo=lo_i, hi=hi_i, tag=int(tag),
                        ordered=_ordered_now())
    if token is not None:
        return (out[0], out[1]), _disp.token_out(token, out)
    return out[0], out[1]


def barrier(comm, token):
    _note_if_unthreaded(comm, token)
    if token is not None and not _ordered_now():
        _, tok = _bind_token_variant("barrier", None, token, comm=comm)
        return tok
    del token  # ordering comes from the ordered effect
    return barrier_p.bind(comm=comm, ordered=_ordered_now())


def send(x, dest, tag, comm, token):
    _note_if_unthreaded(comm, token)
    from . import _dispatch

    if token is not None and not _ordered_now():
        _, tok = _bind_token_variant("send", x, token, comm=comm,
                                     dest=dest, tag=tag)
        return tok
    x = _dispatch.token_in(token, jnp.asarray(x))  # token ties the input
    done = send_p.bind(jnp.asarray(x), comm=comm, dest=dest, tag=tag,
                       ordered=_ordered_now())
    if token is not None:
        return _dispatch.token_out(token, done)
    return None


def recv(x, source, tag, comm, token, status=None):
    from ..utils.status import HashableStatus, Status

    _note_if_unthreaded(comm, token)

    if isinstance(status, Status):
        status = HashableStatus(status)
    from . import _dispatch as _disp

    if token is not None and not _ordered_now():
        return _bind_token_variant("recv", x, token, comm=comm,
                                   source=source, tag=tag, status=status)
    x = _disp.token_in(token, jnp.asarray(x))  # token ties the dummy input
    result = recv_p.bind(jnp.asarray(x), comm=comm, source=source, tag=tag,
                         status=status, ordered=_ordered_now())
    if token is not None:
        return result, _disp.token_out(token, result)
    return result


def sendrecv_dispatch(x, *, perm, shift, wrap, comm, token,
                      source=None, dest=None, sendtag=0, recvtag=None,
                      status=None):
    """World-tier sendrecv: per-rank explicit source/dest (reference style).

    Accepts explicit ``source``/``dest`` ints, or the mesh-tier
    ``perm``/``shift`` conveniences resolved against this process's rank.
    """
    _note_if_unthreaded(comm, token)
    from ..utils.status import ANY_TAG, HashableStatus, Status

    if recvtag is None:
        recvtag = ANY_TAG if status is not None else sendtag
    if isinstance(status, Status):
        status = HashableStatus(status)
    rank, size = comm.rank(), comm.size()
    if source is None or dest is None:
        if shift is not None:
            dest = (rank + shift) % size if wrap else rank + shift
            source = (rank - shift) % size if wrap else rank - shift
            if not (0 <= dest < size) or not (0 <= source < size):
                raise ValueError(
                    "shift moves past the edge with wrap=False; world-tier "
                    "sendrecv needs a valid partner on every rank — use "
                    "send/recv for edge ranks"
                )
        elif perm is not None:
            src_map = {d: s for s, d in perm}
            dst_map = {s: d for s, d in perm}
            if rank not in src_map or rank not in dst_map:
                raise ValueError(
                    f"perm must cover rank {rank} as both source and dest "
                    "on the world tier; use send/recv for one-sided edges"
                )
            source, dest = src_map[rank], dst_map[rank]
        else:
            raise ValueError("pass source/dest, perm=, or shift=")

    from . import _dispatch as _disp

    if token is not None and not _ordered_now():
        return _bind_token_variant(
            "sendrecv", x, token, comm=comm, source=source, dest=dest,
            sendtag=sendtag, recvtag=recvtag, status=status)
    x = _disp.token_in(token, jnp.asarray(x))
    result = sendrecv_p.bind(
        jnp.asarray(x), comm=comm, source=source, dest=dest,
        sendtag=sendtag, recvtag=recvtag, status=status,
        ordered=_ordered_now(),
    )
    if token is not None:
        return result, _disp.token_out(token, result)
    return result
