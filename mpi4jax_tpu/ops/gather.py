"""gather — collect every rank's array at the root.

Reference: /root/reference/mpi4jax/_src/collective_ops/gather.py (root gets
``(nproc, *in)``, others a dummy, :86-96,213-226).  SPMD divergence
(DESIGN.md): the mesh tier returns the full gathered array on *every* rank —
a superset of the reference contract with identical memory cost on TPU
(``lax.all_gather`` materializes the result wherever it runs).
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def gather(x, root=0, *, comm=None, token=None):
    """Gather ``x`` from all ranks; result ``(size, *x.shape)``.

    Mesh tier: result replicated on every rank (the root's view equals the
    reference's root result).  World tier: root receives the gathered array,
    other ranks get their input back (exact reference contract).
    """
    x = _validation.check_array("x", x)
    root = _validation.check_static_int("root", root)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.gather(v, root, comm.axis)
    else:
        from . import _world_impl

        _validation.check_in_range("root", root, comm.size(),
                                   op="gather", comm=comm)
        _validation.check_wire_dtype("gather", x, comm)
        body = lambda v: _world_impl.gather(v, root, comm)
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("gather", comm=comm,
                                                  root=root))
    return _dispatch.maybe_tokenized(body, x, token)
