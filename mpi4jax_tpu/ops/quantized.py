"""Quantized (int8) allreduce — trade precision for ICI bandwidth.

Technique pattern after EQuARX (PAPERS.md: "Efficient Quantized AllReduce
in XLA"): an allreduce decomposed into reduce-scatter + all-gather with
block-quantized int8 payloads and per-block scales, cutting wire bytes ~4x
for float32 (~2x for bfloat16) at ~1e-2 relative error.  Own
implementation, mesh tier only:

1. split the flattened array into ``size`` destination chunks;
2. per-chunk absmax scales; quantize to int8;
3. one ``all_to_all`` moves int8 chunks (+ tiny f32 scales);
4. dequantize, reduce the ``size`` partial chunks locally (f32 math);
5. re-quantize the reduced chunk, ``all_gather`` it back, dequantize.

Exposed via ``allreduce(..., compression="int8")`` and directly as
:func:`quantized_allreduce_sum`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import _mesh_impl


def _pad_to(x, n):
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def _quantize(x):
    """per-row int8 quantization: x (rows, k) → (q int8, scale f32 (rows,))."""
    absmax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[:, None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def quantized_allreduce_sum(x, axis):
    """SUM allreduce with int8-compressed transfers (mesh tier).

    Returns an approximation of ``psum(x, axis)`` with ~1e-2 relative
    error; payload on the wire is ~1/4 of the float32 collective.
    """
    size = lax.axis_size(axis)
    x = _mesh_impl.as_varying(x, axis)
    orig_dtype = x.dtype
    flat, pad = _pad_to(x, size)
    chunks = flat.reshape(size, -1)  # row j → rank j

    q, scale = _quantize(chunks)
    # one all_to_all for payloads, one for the (tiny) scales
    q_t = lax.all_to_all(q[:, None], axis, split_axis=0, concat_axis=0)
    s_t = lax.all_to_all(
        scale.reshape(size, 1), axis, split_axis=0, concat_axis=0
    )
    # rows: every rank's contribution to OUR chunk; reduce in f32
    partial = q_t[:, 0].astype(jnp.float32) * s_t  # (size, chunk)
    mine = jnp.sum(partial, axis=0)  # (chunk,)

    # re-quantize the reduced chunk and share it
    q2, s2 = _quantize(mine[None])
    q_all = lax.all_gather(q2[0], axis, axis=0, tiled=False)  # (size, chunk)
    s_all = lax.all_gather(s2, axis, axis=0, tiled=False)  # (size, 1)
    full = (q_all.astype(jnp.float32) * s_all).reshape(-1)
    if pad:
        full = full[:-pad]
    return full.reshape(x.shape).astype(orig_dtype)
