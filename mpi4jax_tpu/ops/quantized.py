"""Quantized (int8) allreduce — trade precision for wire bandwidth.

Technique pattern after EQuARX (PAPERS.md: "Efficient Quantized AllReduce
in XLA"): block-quantized int8 payloads with f32 absmax scales cut wire
bytes ~4x for float32 (~2x for bfloat16) at ~1e-2 relative error.

Two execution paths:

- **Native in-collective path (world tier, preferred):** the transport's
  algorithm engine carries ``qring`` / ``qrd`` allreduce schedules that
  quantize per chunk at the sender, ship int8 codes + per-256-element
  f32 absmax scales in ONE wire frame per chunk, and dequantize-and-
  reduce streaming in f32 at the receiver (``native/tpucomm.cc``).
  ``allreduce(..., compression="int8")`` routes here whenever the comm
  is world-tier, the native library carries the quantized engine, and
  ``MPI4JAX_TPU_COLL_QUANT`` is not ``deny``.  Results are
  rank-consistent: every rank reconstructs bit-identical output.

- **Python schedule (mesh tier, and the world-tier fallback):** the
  EQuARX decomposition expressed in jax ops —

  1. split the flattened array into ``size`` destination chunks;
  2. per-chunk absmax scales; quantize to int8;
  3. ONE ``all_to_all`` moves int8 chunks with their f32 scales packed
     into the same int8 payload (bitcast — no separate scale leg);
  4. dequantize, reduce the ``size`` partial chunks locally (f32 math);
  5. re-quantize the reduced chunk, ONE ``all_gather`` returns it
     (scales packed the same way), dequantize.

  On the mesh tier the transfers are XLA collectives over ICI; on the
  world tier they ride the native transport.

This module also hosts the **numpy reference** of the native wire codec
(`quant_pack_ref` / `quant_unpack_ref` / `quant_pack_wire_ref`,
bit-identical to ``tpucomm_quant_pack``/``unpack`` — test-enforced; the
in-kernel Pallas codec ``pallas_collectives.quant_pack_pallas`` and the
quantized ICI leg (``topo/_ici_leg.py``) are held to the same contract)
and per-rank
**schedule simulators** (:func:`simulate_qring_sum`,
:func:`simulate_qrd_sum`) that reproduce the native algorithms' exact
f32 arithmetic without any transport — the accuracy harness
(``benchmarks/quant_accuracy.py``) drives DP training steps through them
to bound the quality cost of quantized gradient synchronization.

Exposed via ``allreduce(..., compression="int8")`` and directly as
:func:`quantized_allreduce_sum` / :func:`quantized_allreduce_sum_world`.
"""

from __future__ import annotations

import numpy as np

#: elements per f32 absmax scale in the native wire codec — keep in sync
#: with ``kQuantBlock`` in native/tpucomm.cc (test-enforced via the
#: packed-bytes probe)
QUANT_BLOCK = 256


def _pad_to(x, n):
    import jax.numpy as jnp

    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def _quantize(x):
    """per-row int8 quantization: x (rows, k) → (q int8, scale f32 (rows,))."""
    import jax.numpy as jnp

    absmax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[:, None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def check_quantizable(x, comm=None):
    """int8 compression is defined for real floating inputs only: the
    quantize/dequantize round-trip runs in f32 (complex would silently
    drop the imaginary part; integers would lose exactness the normal
    path guarantees)."""
    import jax.numpy as jnp

    from ..utils import validation as _validation

    if not jnp.issubdtype(np.dtype(x.dtype), jnp.floating):
        _validation.fail(
            f"compression='int8' requires a real floating dtype, got "
            f"{np.dtype(x.dtype).name}; use the uncompressed allreduce",
            op="allreduce(compression='int8')", comm=comm, x=x,
            exc=TypeError)


def native_quant_algo(comm, x):
    """The native in-collective algorithm name ("qring"/"qrd") that
    should carry a world-tier ``compression="int8"`` allreduce, or None
    when the Python schedule must serve it: the loaded native library
    predates the quantized engine, or ``MPI4JAX_TPU_COLL_QUANT=deny``
    vetoes int8 wire formats process-wide.

    The pick mirrors the tune table's exact-algorithm decision for the
    payload size (ring-family sizes compress as qring, latency-bound
    sizes as qrd), so a tuned deployment keeps its shape.  Inside an
    analysis virtual world the native library is never probed — the
    verified schedule pins the native path's (identical) signature.
    """
    from ..utils import config

    if config.quant_mode() == "deny":
        return None
    from . import _world_impl

    ex = _world_impl._analysis_executor
    if ex is None or not ex.owns(comm):
        if type(comm).__name__ == "AbstractComm":
            # abstract-eval analysis (analysis.check): no live transport
            # exists and none may be built — route as if the native
            # engine were present so the verified schedule matches the
            # production path's (identical allreduce) signature
            pass
        else:
            from ..runtime import bridge

            if not bridge.quant_available():
                return None
    from .. import tune

    nbytes = int(x.size) * np.dtype(x.dtype).itemsize
    return tune.quantized_algorithm(nbytes)


def native_quant_alltoall(comm):
    """The algorithm name ("qalltoall") carrying a world-tier
    ``compression="int8"`` alltoall, or None to run the exact exchange:
    unlike allreduce there is no Python fallback schedule — a
    pre-quant native library or ``MPI4JAX_TPU_COLL_QUANT=deny``
    degrades to the exact twin, consistently on every rank (both
    signals are process-wide and identical across the job)."""
    from ..utils import config

    if config.quant_mode() == "deny":
        return None
    from . import _world_impl

    ex = _world_impl._analysis_executor
    if ex is None or not ex.owns(comm):
        if type(comm).__name__ == "AbstractComm":
            # abstract-eval analysis: route as if the native engine were
            # present — the schedule signature is plain "alltoall"
            # either way
            pass
        else:
            from ..runtime import bridge

            if not bridge.quant_available():
                return None
    return "qalltoall"


def _pack_scales(q, scale):
    """Append each row's f32 scale to its int8 payload (bitcast, no
    widening): (rows, k) int8 + (rows,) f32 -> (rows, k+4) int8.  One
    collective leg then moves codes AND scales — half the round count
    of the historic separate-scale schedule, bit-identical results
    (the bitcast preserves the exact scale bits)."""
    import jax.numpy as jnp
    from jax import lax

    sbytes = lax.bitcast_convert_type(
        scale.astype(jnp.float32), jnp.int8)  # (rows, 4)
    return jnp.concatenate([q, sbytes], axis=-1)


def _unpack_scales(packed):
    """Inverse of :func:`_pack_scales`: (rows, k+4) -> ((rows, k) int8,
    (rows,) f32)."""
    import jax.numpy as jnp
    from jax import lax

    q = packed[..., :-4]
    scale = lax.bitcast_convert_type(packed[..., -4:], jnp.float32)
    return q, scale


def _quantized_schedule(x, size, alltoall, allgather):
    """The one copy of the EQuARX-style schedule; the two tiers inject
    their transport legs (``alltoall(rows)``/``allgather(row)`` both
    follow the (size, ...) leading-axis contract).  Scales ride inside
    the int8 payload (``_pack_scales``), so each phase is ONE leg."""
    import jax.numpy as jnp

    orig_dtype = x.dtype
    flat, pad = _pad_to(x, size)
    chunks = flat.reshape(size, -1)  # row j -> rank j

    q, scale = _quantize(chunks)
    packed = alltoall(_pack_scales(q, scale))   # (size, chunk+4) int8
    q_t, s_t = _unpack_scales(packed)
    # rows: every rank's contribution to OUR chunk; reduce in f32
    partial = q_t.astype(jnp.float32) * s_t[:, None]
    mine = jnp.sum(partial, axis=0)             # (chunk,)

    # re-quantize the reduced chunk and share it (scales packed along)
    q2, s2 = _quantize(mine[None])
    packed2 = allgather(_pack_scales(q2, s2)[0])  # (size, chunk+4)
    q_all, s_all = _unpack_scales(packed2)
    full = (q_all.astype(jnp.float32) * s_all[:, None]).reshape(-1)
    if pad:
        full = full[:-pad]
    return full.reshape(x.shape).astype(orig_dtype)


def quantized_allreduce_sum(x, axis):
    """SUM allreduce with int8-compressed transfers (mesh tier).

    Returns an approximation of ``psum(x, axis)`` with ~1e-2 relative
    error; payload on the wire is ~1/4 of the float32 collective.
    """
    from jax import lax

    from . import _mesh_impl

    check_quantizable(x)
    size = lax.axis_size(axis)
    x = _mesh_impl.as_varying(x, axis)
    return _quantized_schedule(
        x, size,
        lambda rows: lax.all_to_all(rows, axis, split_axis=0,
                                    concat_axis=0),
        lambda row: lax.all_gather(row, axis, axis=0, tiled=False),
    )


def quantized_allreduce_sum_world(x, comm):
    """SUM allreduce with int8-compressed transfers over the world-tier
    native transport — the Python fallback schedule (identical to the
    mesh version, legs carried by the TCP transport).  The preferred
    world-tier route is the native in-collective ``qring``/``qrd`` path
    (see :func:`native_quant_algo`); ``allreduce(compression="int8")``
    only lands here when that path is unavailable or denied."""
    from . import _world_impl

    check_quantizable(x, comm)
    return _quantized_schedule(
        x, comm.size(),
        lambda rows: _world_impl.alltoall(rows, comm),
        lambda row: _world_impl.allgather(row, comm),
    )


# ---------------- numpy reference of the native wire codec ----------------
#
# Bit-identical to native/tpucomm.cc's quant_pack_f32/quant_unpack_f32
# (test-enforced against the real library): per-256-element blocks,
# scale = absmax/127 (1.0 for an all-zero block), codes =
# round-to-nearest-even of value * (1/scale) clipped to ±127, all in
# f32.  The schedule simulators below compose these exactly like the
# native algorithms, so the accuracy harness measures the REAL
# quantization error, not an approximation of it.


def quant_pack_ref(x):
    """(scales f32 (nblocks,), codes int8 (n,)) for a 1-D f32 array."""
    x = np.ascontiguousarray(x, np.float32).reshape(-1)
    n = x.size
    nb = max((n + QUANT_BLOCK - 1) // QUANT_BLOCK, 0)
    padded = np.zeros(nb * QUANT_BLOCK, np.float32)
    padded[:n] = x
    blocks = padded.reshape(nb, QUANT_BLOCK)
    amax = np.max(np.abs(blocks), axis=1)
    scale = np.where(amax > 0, amax / np.float32(127.0),
                     np.float32(1.0)).astype(np.float32)
    inv = (np.float32(1.0) / scale).astype(np.float32)
    v = (blocks * inv[:, None]).astype(np.float32)
    v = np.clip(v, np.float32(-127.0), np.float32(127.0))
    codes = np.rint(v).astype(np.int8).reshape(-1)[:n]
    return scale, codes


def quant_pack_wire_ref(x):
    """The full native wire frame of a 1-D f32 array — ``ceil(n/256)``
    f32 scales viewed as their little-endian int8 bytes, then ``n``
    int8 codes (``bridge.quant_packed_bytes(n)`` bytes total): the
    layout ``tpucomm_quant_pack`` emits and the in-kernel Pallas codec
    (``pallas_collectives.quant_pack_pallas``) must match bit-for-bit
    (test-enforced).  The quantized ICI leg's numpy backend ships
    exactly these bytes to the leader leg."""
    scales, codes = quant_pack_ref(x)
    return np.concatenate([scales.view(np.int8), codes])


def quant_unpack_ref(scales, codes):
    """f32 values from (scales, codes) — exact: scale * code."""
    codes = np.asarray(codes, np.int8)
    n = codes.size
    nb = scales.size
    padded = np.zeros(nb * QUANT_BLOCK, np.float32)
    padded[:n] = codes.astype(np.float32)
    out = (padded.reshape(nb, QUANT_BLOCK)
           * scales.astype(np.float32)[:, None]).astype(np.float32)
    return out.reshape(-1)[:n]


def _qdq_ref(x):
    """quantize-dequantize round trip (the owner-requantize step)."""
    scales, codes = quant_pack_ref(x)
    return quant_unpack_ref(scales, codes)


def _chunk_lo(count, size, i):
    per = (count + size - 1) // size
    return min(per * i, count)


def simulate_qring_sum(parts):
    """The native ``qring`` allreduce's exact arithmetic over per-rank
    f32 arrays, no transport: a direct quantized reduce-scatter (each
    rank's inputs quantized once; contributions folded in fixed rank
    order) followed by the once-quantized allgather.  Returns the ONE
    result every rank reconstructs (the native algorithm is
    rank-consistent by construction)."""
    parts = [np.ascontiguousarray(p, np.float32).reshape(-1) for p in parts]
    size = len(parts)
    count = parts[0].size
    if size == 1:
        return parts[0].copy()
    out = np.empty(count, np.float32)
    for c in range(size):
        lo, hi = _chunk_lo(count, size, c), _chunk_lo(count, size, c + 1)
        acc = parts[c][lo:hi].astype(np.float32)  # owner's own data, exact
        # arrival order rank-1, rank-2, ... (the fixed fold order)
        for round_ in range(1, size):
            src = (c - round_) % size
            acc = (acc + _qdq_ref(parts[src][lo:hi])).astype(np.float32)
        out[lo:hi] = _qdq_ref(acc)  # once-quantized allgather
    return out


def simulate_qrd_sum(parts):
    """The native ``qrd`` allreduce's exact arithmetic (quantized
    recursive doubling with the non-power-of-two fold and the final
    requantize that keeps every rank bit-identical)."""
    parts = [np.ascontiguousarray(p, np.float32).reshape(-1) for p in parts]
    size = len(parts)
    if size == 1:
        return parts[0].copy()
    accs = [p.astype(np.float32) for p in parts]
    pof2 = 1
    while pof2 * 2 <= size:
        pof2 *= 2
    rem = size - pof2
    group = {}  # newrank -> acc
    for rank in range(size):
        if rank < 2 * rem:
            if rank % 2 == 1:
                group[rank // 2] = (_qdq_ref(accs[rank])
                                    + _qdq_ref(accs[rank - 1])
                                    ).astype(np.float32)
        else:
            group[rank - rem] = accs[rank]
    for shift in range(pof2.bit_length() - 1):
        mask = 1 << shift
        nxt = {}
        for nr, acc in group.items():
            peer = nr ^ mask
            nxt[nr] = (_qdq_ref(acc) + _qdq_ref(group[peer])
                       ).astype(np.float32)
        group = nxt
    # all butterfly participants are bit-identical now
    result = group[0]
    if rem > 0:
        result = _qdq_ref(result)  # the quantized return frame
    return result
