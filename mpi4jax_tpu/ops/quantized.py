"""Quantized (int8) allreduce — trade precision for wire bandwidth.

Technique pattern after EQuARX (PAPERS.md: "Efficient Quantized AllReduce
in XLA"): an allreduce decomposed into reduce-scatter + all-gather with
block-quantized int8 payloads and per-block scales, cutting wire bytes ~4x
for float32 (~2x for bfloat16) at ~1e-2 relative error.  Own
implementation, both tiers:

1. split the flattened array into ``size`` destination chunks;
2. per-chunk absmax scales; quantize to int8;
3. one ``all_to_all`` moves int8 chunks (+ tiny f32 scales);
4. dequantize, reduce the ``size`` partial chunks locally (f32 math);
5. re-quantize the reduced chunk, ``all_gather`` it back, dequantize.

On the mesh tier the transfers are XLA collectives over ICI; on the
world tier they are the same alltoall/allgather schedule over the native
TCP transport (DCN analog), where the 4x byte saving matters even more.

Exposed via ``allreduce(..., compression="int8")`` and directly as
:func:`quantized_allreduce_sum` / :func:`quantized_allreduce_sum_world`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import _mesh_impl


def _pad_to(x, n):
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def _quantize(x):
    """per-row int8 quantization: x (rows, k) → (q int8, scale f32 (rows,))."""
    absmax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[:, None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def check_quantizable(x, comm=None):
    """int8 compression is defined for real floating inputs only: the
    quantize/dequantize round-trip runs in f32 (complex would silently
    drop the imaginary part; integers would lose exactness the normal
    path guarantees)."""
    import numpy as np

    from ..utils import validation as _validation

    if not jnp.issubdtype(np.dtype(x.dtype), jnp.floating):
        _validation.fail(
            f"compression='int8' requires a real floating dtype, got "
            f"{np.dtype(x.dtype).name}; use the uncompressed allreduce",
            op="allreduce(compression='int8')", comm=comm, x=x,
            exc=TypeError)


def _quantized_schedule(x, size, alltoall, allgather):
    """The one copy of the EQuARX-style schedule; the two tiers inject
    their transport legs (``alltoall(rows)``/``allgather(row)`` both
    follow the (size, ...) leading-axis contract)."""
    orig_dtype = x.dtype
    flat, pad = _pad_to(x, size)
    chunks = flat.reshape(size, -1)  # row j -> rank j

    q, scale = _quantize(chunks)
    # one alltoall for payloads, one for the (tiny) scales
    q_t = alltoall(q)                          # (size, chunk) int8
    s_t = alltoall(scale.reshape(size, 1))     # (size, 1) f32
    # rows: every rank's contribution to OUR chunk; reduce in f32
    partial = q_t.astype(jnp.float32) * s_t
    mine = jnp.sum(partial, axis=0)            # (chunk,)

    # re-quantize the reduced chunk and share it
    q2, s2 = _quantize(mine[None])
    q_all = allgather(q2[0])                   # (size, chunk)
    s_all = allgather(s2[0])                   # (size,)
    full = (q_all.astype(jnp.float32) * s_all[:, None]).reshape(-1)
    if pad:
        full = full[:-pad]
    return full.reshape(x.shape).astype(orig_dtype)


def quantized_allreduce_sum(x, axis):
    """SUM allreduce with int8-compressed transfers (mesh tier).

    Returns an approximation of ``psum(x, axis)`` with ~1e-2 relative
    error; payload on the wire is ~1/4 of the float32 collective.
    """
    check_quantizable(x)
    size = lax.axis_size(axis)
    x = _mesh_impl.as_varying(x, axis)
    return _quantized_schedule(
        x, size,
        lambda rows: lax.all_to_all(rows, axis, split_axis=0,
                                    concat_axis=0),
        lambda row: lax.all_gather(row, axis, axis=0, tiled=False),
    )


def quantized_allreduce_sum_world(x, comm):
    """SUM allreduce with int8-compressed transfers over the world-tier
    native transport — identical schedule to the mesh version, with the
    alltoall/allgather legs carried by the TCP transport (the DCN path,
    where the ~4x byte saving is the point)."""
    from . import _world_impl

    check_quantizable(x, comm)
    return _quantized_schedule(
        x, comm.size(),
        lambda rows: _world_impl.alltoall(rows, comm),
        lambda row: _world_impl.allgather(row, comm),
    )
