"""recv — point-to-point receive.

Reference: /root/reference/mpi4jax/_src/collective_ops/recv.py (output takes
the shape/dtype of the dummy input ``x``, :197-201).

Like :mod:`.send`, a lone ``recv`` requires per-rank programs — world tier
only; the mesh tier points to :func:`mpi4jax_tpu.sendrecv`.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch


def recv(x, source, tag=0, *, comm=None, token=None):
    """Receive into the shape/dtype of ``x`` from rank ``source``.

    World tier only (one process per rank); see module docstring.
    """
    x = _validation.check_array("x", x)
    source = _validation.check_static_int("source", source)
    tag = _validation.check_static_int("tag", tag)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        raise NotImplementedError(
            "recv() has no meaning inside a single SPMD program: every rank "
            "executes the same code, so there is no separate sender. Use "
            "sendrecv(x, perm=...) (compiled to lax.ppermute over ICI), or "
            "run one process per rank via `python -m "
            "mpi4jax_tpu.runtime.launch` for MPMD send/recv."
        )

    from . import _world_impl

    _validation.check_in_range("source", source, comm.size())
    return _world_impl.recv(x, source, tag, comm, token)
