"""recv — point-to-point receive.

Reference: /root/reference/mpi4jax/_src/collective_ops/recv.py (output takes
the shape/dtype of the dummy input ``x``, :197-201).

Like :mod:`.send`, a lone ``recv`` requires per-rank programs — world tier
only; the mesh tier points to :func:`mpi4jax_tpu.sendrecv`.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch


def recv(x, source, tag=None, *, comm=None, token=None, status=None):
    """Receive into the shape/dtype of ``x`` from rank ``source``.

    ``tag=None`` accepts any tag (the reference's ``MPI.ANY_TAG`` default,
    recv.py:43-50 there); pass an int to require it (a mismatch is a
    fail-fast transport abort).  ``source`` may be
    :data:`mpi4jax_tpu.ANY_SOURCE` — the reference's *default*
    (recv.py:45 there; libmpi matches the wildcard natively): the
    transport polls every peer socket and takes the first complete
    frame, per-socket order still strict.  ``status``: a
    :class:`mpi4jax_tpu.Status` filled with the actual
    (source, tag, byte count) when the receive executes — eagerly or
    under ``jit`` (reference recv.py:120-123).

    World tier only (one process per rank); see module docstring.
    """
    from ..utils.status import ANY_SOURCE, ANY_TAG, Status

    x = _validation.check_array("x", x)
    source = _validation.check_static_int("source", source)
    if tag is None:
        tag = ANY_TAG
    tag = _validation.check_static_int("tag", tag)
    if status is not None and not isinstance(status, Status):
        raise TypeError(
            f"status must be an mpi4jax_tpu.Status, got {type(status)}"
        )
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        raise NotImplementedError(
            "recv() has no meaning inside a single SPMD program: every rank "
            "executes the same code, so there is no separate sender. Use "
            "sendrecv(x, perm=...) (compiled to lax.ppermute over ICI), or "
            "run one process per rank via `python -m "
            "mpi4jax_tpu.runtime.launch` for MPMD send/recv."
        )

    from . import _world_impl

    if source != ANY_SOURCE:
        _validation.check_in_range("source", source, comm.size(),
                                   op="recv", comm=comm)
    _validation.check_wire_dtype("recv", x, comm)
    return _world_impl.recv(x, source, tag, comm, token, status)
