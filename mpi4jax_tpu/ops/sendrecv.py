"""sendrecv — paired exchange; the halo-exchange / ring building block.

Reference: /root/reference/mpi4jax/_src/collective_ops/sendrecv.py (per-rank
``source``/``dest`` ints :46-125; transpose swaps source and dest :390-409 —
the cotangent flows backward along the message edge).

Mesh tier: ``lax.ppermute`` over a *static permutation* — the SPMD spelling
of per-rank source/dest.  Conveniences:

- ``perm=[(src, dst), ...]`` explicit pairs;
- ``shift=k, wrap=...`` the ring pattern (dest = rank+k), which is the whole
  of the reference's in-repo usage (halo exchange, shallow_water.py there).

Autodiff: ``ppermute``'s transpose is the inverse permutation — exactly the
reference's source/dest swap — and (an improvement over the reference, which
raises for forward mode :150-155) JVP works too.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def _resolve_perm(comm, perm, shift, wrap):
    if (perm is None) == (shift is None):
        raise ValueError("pass exactly one of perm= or shift=")
    if perm is not None:
        return [
            (
                _validation.check_static_int("source", s),
                _validation.check_static_int("dest", d),
            )
            for s, d in perm
        ]
    shift = _validation.check_static_int("shift", shift)
    return _mesh_impl.ring_perm(comm.size(), shift, wrap)


def sendrecv(x, *, perm=None, shift=None, wrap=True, source=None, dest=None,
             tag=None, sendtag=0, recvtag=None, status=None, comm=None,
             token=None):
    """Exchange ``x`` along a static rank permutation.

    Each pair ``(s, d)`` in the permutation delivers rank ``s``'s ``x`` to
    rank ``d``; ranks that are not a destination receive zeros.  With
    ``shift=k``, data moves to ``rank + k`` (a ring when ``wrap=True``).

    On the world tier (one process per rank) the reference's per-rank
    ``source=``/``dest=`` integers are also accepted
    (/root/reference/mpi4jax/_src/collective_ops/sendrecv.py:46-125), as
    are split ``sendtag``/``recvtag`` (sendrecv.py:52-53 there;
    ``recvtag=None`` matches the send tag, or any tag when ``status`` is
    given) and ``status`` introspection (filled with the received
    source/tag/byte-count at execution; tested by
    tests/collective_ops/test_sendrecv.py:29-61 there).  ``tag=k`` is
    shorthand for ``sendtag=k, recvtag=k``.  On the mesh tier a single
    SPMD program cannot take per-rank arguments — express the pattern as
    ``perm``/``shift`` instead.
    """
    x = _validation.check_array("x", x)
    comm = _dispatch.resolve_comm(comm)
    if tag is not None:
        sendtag = recvtag = _validation.check_static_int("tag", tag)
    sendtag = _validation.check_static_int("sendtag", sendtag)
    if recvtag is not None:
        recvtag = _validation.check_static_int("recvtag", recvtag)

    if _dispatch.is_mesh(comm):
        if source is not None or dest is not None:
            raise ValueError(
                "mesh-tier sendrecv takes the global pattern (perm=[(src, "
                "dst), ...] or shift=k), not per-rank source/dest ints — "
                "all ranks execute one SPMD program. Use the world tier "
                "(launcher) for per-rank MPMD arguments."
            )
        if status is not None:
            raise ValueError(
                "status introspection is world-tier only: mesh-tier "
                "sendrecv compiles to lax.ppermute over ICI, which has no "
                "per-message envelope"
            )
        # reject non-default tags loudly (a silently dropped tag would
        # change matching semantics for ported world code); tag=0 /
        # matching tags are the no-op spelling and stay accepted
        if sendtag != 0 or (recvtag is not None and recvtag != sendtag):
            raise ValueError(
                "message tags are world-tier only: mesh-tier sendrecv "
                "compiles to lax.ppermute over ICI, which has no tag "
                "matching"
            )
        pairs = _resolve_perm(comm, perm, shift, wrap)
        body = lambda v: _mesh_impl.sendrecv(v, pairs, comm.axis)
        return _dispatch.maybe_tokenized(body, x, token)

    from . import _world_impl

    _validation.check_wire_dtype("sendrecv", x, comm)
    return _world_impl.sendrecv_dispatch(
        x, perm=perm, shift=shift, wrap=wrap, comm=comm, token=token,
        source=source, dest=dest, sendtag=sendtag, recvtag=recvtag,
        status=status,
    )


def permute(x, perm, *, comm=None, token=None):
    """Alias for :func:`sendrecv` with an explicit permutation."""
    return sendrecv(x, perm=perm, comm=comm, token=token)
