"""scatter — distribute rows of the root's array, one per rank.

Reference: /root/reference/mpi4jax/_src/collective_ops/scatter.py (root
passes ``(nproc, *out)``, non-root input is a passthrough dummy,
:86-90,205-217).  SPMD contract here: every rank passes a ``(size, ...)``
buffer (only the root's values are read) and receives its row.  Mesh tier:
one ``lax.all_to_all`` + static root-row pick — O(|x|) traffic per rank,
cheaper than broadcast-then-slice.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def scatter(x, root=0, *, comm=None, token=None):
    """Rank ``j`` receives ``x[j]`` of the root's ``x`` of shape (size, ...)."""
    x = _validation.check_array("x", x)
    root = _validation.check_static_int("root", root)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.scatter(v, root, comm.axis)
    else:
        from . import _world_impl

        _validation.check_in_range("root", root, comm.size(),
                                   op="scatter", comm=comm)
        _validation.check_wire_dtype("scatter", x, comm)
        body = lambda v: _world_impl.scatter(v, root, comm)
        if x.ndim < 1 or x.shape[0] != comm.size():
            _validation.fail(
                f"scatter requires input shape (size, ...) = "
                f"({comm.size()}, ...)",
                op="scatter", comm=comm, x=x, exc=ValueError)
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("scatter", comm=comm,
                                                  root=root))
    return _dispatch.maybe_tokenized(body, x, token)
